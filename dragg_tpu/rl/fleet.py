"""Fleet-scale vectorized RL training — the paper's headline workload at
fleet scale (ROADMAP item 1, docs/architecture.md §17).

The reference trains ONE ``RLAgent`` against ONE community through Redis
round-trips (dragg/agent.py, dragg/aggregator.py:876-911).  Here the
round-12 fleet engine already folds ``fleet.communities = C`` independent
communities into one batched tensor program, so the JAX-native RL
environment is a ready-made *vectorized fleet of parallel rollouts*: this
module gives the per-community env carry a leading community axis, maps
it onto the engine's per-community aggregate folds
(``Engine.community_fold_arrays``), and trains the reward-price policy
across all C rollout streams inside ONE fused jitted step — no
per-community recompile, no host round-trips inside a chunk.

Two policy layouts (``[rl.fleet] policy``):

* ``"shared"`` (default) — IMPALA-style actor/learner split after the
  Volt-VAR RLlib-IMPALA paper (PAPERS.md, arxiv 2402.15932): C parallel
  actors (per-community RNG streams derived from the fleet seed stride,
  so exploration is deterministic and composition-invariant) feed one
  SHARED replay buffer, and a single batched learner update per step
  trains one actor-critic.  Both cores are supported: the reference's
  linear basis actor-critic (:mod:`dragg_tpu.rl.core`) and the Flax DDPG
  twin-Q core (:mod:`dragg_tpu.rl.neural`).  The shared policy's
  observation is EXTENDED with per-community scenario event-timeline
  features (round 13: tariff shock / DR cap / outage / comfort-relax
  intensity over the upcoming window), so one policy learns across
  heterogeneous event schedules.
* ``"per_community"`` — C independent agents: the unmodified reference
  cores, ``vmap``-ped over the community axis (a control for shared-vs-
  independent learning A/Bs; 4-scalar observations, no event features).

Optionally (``[rl.fleet] gradient = "mpc"``) the actor update gains a
DETERMINISTIC first-order term through the community response — the
CA-AC-MPC angle (PAPERS.md, arxiv 2605.29155): d(agg_load)/d(rp) is
computed by forward-mode ``jax.jvp`` through the engine's relaxed solve.
The reluqp family's iteration is branch-free by construction (fixed
dense-matmul sequence + clamp — ops/reluqp.py), and ``lax.while_loop``
supports exactly the forward-mode differentiation this needs; one jvp
with the per-community price-window tangent yields every community's own
d(agg)/d(a) in a single pass (communities are decoupled through rp).
"""

from __future__ import annotations

import json
import os
import time
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dragg_tpu.rl.agent import RLAgent, new_rl_data
from dragg_tpu.rl.basis import (
    STATE_ACTION_DIM,
    STATE_DIM,
    state_action_basis,
    state_basis,
)
from dragg_tpu.rl.core import (
    MEMORY_CAP,
    AgentParams,
    RLObservation,
    StepRecord,
    obs_to_state,
    params_from_config,
)
from dragg_tpu.rl.env import (
    EnvCarry,
    init_fleet_env_carry,
    init_tracker,
    observe,
    simplified_response,
    tracker_step,
)

# Per-community scenario event features appended to the shared policy's
# state vector (round-13 timeline families, in this order):
# [price-shock intensity, DR-cap activity fraction, outage fraction,
#  comfort-relax intensity].  Event-free runs see exact zeros.
N_EVENT_FEATURES = 4
FLEET_STATE_SCALARS = 4 + N_EVENT_FEATURES        # replay state width
FLEET_STATE_DIM = STATE_DIM + N_EVENT_FEATURES    # φ(s) width
FLEET_SA_DIM = STATE_ACTION_DIM + N_EVENT_FEATURES  # φ(s, a) width

# PRNG stream constants: decouple the RL exploration / learner streams
# from the population-synthesis and forecast-noise uses of the same
# community seeds (engine._prepare keys on the raw PRNGKey(seed)).
_NOISE_STREAM = 0x52F7
_LEARNER_STREAM = 0x1EA5


class FleetParams(NamedTuple):
    """Static ``[rl.fleet]`` knobs (docs/config.md)."""

    policy: str          # "shared" | "per_community"
    learner_batch: int   # shared learner minibatch (resolved, > 0)
    gradient: str        # "score" | "mpc"
    mpc_weight: float
    event_features: bool
    n_communities: int


def fleet_params_from_config(config: dict, n_communities: int) -> FleetParams:
    """Resolve + validate the ``[rl.fleet]`` table."""
    f = config.get("rl", {}).get("fleet", {}) or {}
    policy = str(f.get("policy", "shared"))
    if policy not in ("shared", "per_community"):
        raise ValueError(
            f"rl.fleet.policy must be 'shared' or 'per_community', "
            f"got {policy!r}")
    gradient = str(f.get("gradient", "score"))
    if gradient not in ("score", "mpc"):
        raise ValueError(
            f"rl.fleet.gradient must be 'score' or 'mpc', got {gradient!r}")
    if gradient == "mpc" and policy != "shared":
        raise ValueError(
            "rl.fleet.gradient = 'mpc' requires rl.fleet.policy = 'shared' "
            "(the deterministic actor term updates the one shared policy)")
    lb = int(f.get("learner_batch", 0) or 0)
    if lb <= 0:
        lb = int(config["rl"]["parameters"]["batch_size"])
    return FleetParams(
        policy=policy,
        learner_batch=lb,
        gradient=gradient,
        mpc_weight=float(f.get("mpc_weight", 0.25)),
        event_features=bool(f.get("event_features", True)),
        n_communities=int(n_communities),
    )


class FleetObservation(NamedTuple):
    """One fleet timestep's observation: the reference 4-scalar
    observation batched over communities, the per-community event
    features, and (mpc gradient mode) d(reward)/d(action) for the action
    whose reward ``obs.reward`` reports."""

    obs: RLObservation        # (C,) leaves
    events: jnp.ndarray       # (C, N_EVENT_FEATURES)
    drda: jnp.ndarray         # (C,)


# --------------------------------------------------------------------------
# Per-community PRNG streams (satellite: fleet seed stride determinism)
# --------------------------------------------------------------------------

def community_seeds(config: dict, n_communities: int) -> np.ndarray:
    """Per-community seeds from the SAME derivation as the fleet
    population (homes.fleet_config / FleetSpec.seeds:
    ``random_seed + c * seed_stride``) — community c of a C-fleet and
    community 0 of the corresponding standalone run share one seed by
    construction (regression-pinned in tests/test_rl_fleet.py)."""
    from dragg_tpu.homes import fleet_config

    _c, stride, _off = fleet_config(config)
    base = int(config["simulation"]["random_seed"])
    return base + stride * np.arange(n_communities)


def community_noise_keys(config: dict, n_communities: int) -> jnp.ndarray:
    """(C, 2) uint32 per-community exploration-noise keys: the community
    seed's PRNGKey folded with the RL noise stream constant (decoupled
    from the engine's forecast-noise use of the same seed)."""
    seeds = community_seeds(config, n_communities)
    return jnp.stack([
        jax.random.fold_in(jax.random.PRNGKey(int(s)), _NOISE_STREAM)
        for s in seeds
    ])


def _learner_key(config: dict) -> jnp.ndarray:
    base = int(config["simulation"]["random_seed"])
    return jax.random.fold_in(jax.random.PRNGKey(base), _LEARNER_STREAM)


# --------------------------------------------------------------------------
# Extended feature maps (event features ride the basis tail)
# --------------------------------------------------------------------------

def _phi_s_fleet(sv):
    """φ(s) for the (4 + F)-scalar fleet state: the reference 23-dim
    basis over the 4 reference scalars, with the raw event features
    appended as linear terms."""
    return jnp.concatenate([state_basis(sv[0], sv[1], sv[2]), sv[4:]])


def _phi_sa_fleet(sv, a):
    return jnp.concatenate(
        [state_action_basis(sv[0], sv[1], sv[2], sv[3], a), sv[4:]])


def _fleet_state(fobs: FleetObservation) -> jnp.ndarray:
    """(C, 4 + F) stacked state scalars + event features."""
    return jnp.concatenate(
        [obs_to_state(fobs.obs), fobs.events.astype(jnp.float32)], axis=-1)


# --------------------------------------------------------------------------
# Shared linear core (IMPALA-style: C actors, one learner, one policy)
# --------------------------------------------------------------------------

class FleetLinearCarry(NamedTuple):
    """Shared-policy linear actor-critic state: ONE θ pair, C rollout
    streams, one shared replay holding C transitions per fleet step."""

    theta_mu: jnp.ndarray     # (FLEET_STATE_DIM,)
    theta_q: jnp.ndarray      # (FLEET_SA_DIM, n_q)
    z_theta_mu: jnp.ndarray   # (C, FLEET_STATE_DIM) per-community traces
    state: jnp.ndarray        # (C, 4 + F)
    next_action: jnp.ndarray  # (C,)
    avg_reward: jnp.ndarray   # ()
    cum_reward: jnp.ndarray   # ()
    i: jnp.ndarray            # () int32 twin-Q index
    t: jnp.ndarray            # () int32 fleet steps taken
    mem_s: jnp.ndarray        # (CAP, 4 + F) shared replay
    mem_a: jnp.ndarray        # (CAP,)
    mem_r: jnp.ndarray        # (CAP,)
    mem_s1: jnp.ndarray       # (CAP, 4 + F)
    comm_keys: jnp.ndarray    # (C, 2) per-community noise streams
    key: jnp.ndarray          # (2,) learner stream (minibatch sampling)


def init_fleet_linear(params: AgentParams, fparams: FleetParams,
                      config: dict) -> FleetLinearCarry:
    C = fparams.n_communities
    f32 = jnp.float32
    key = _learner_key(config)
    key, kq = jax.random.split(key)
    return FleetLinearCarry(
        theta_mu=jnp.zeros((FLEET_STATE_DIM,), f32),
        theta_q=0.3 * jax.random.normal(kq, (FLEET_SA_DIM, params.n_q), f32),
        z_theta_mu=jnp.zeros((C, FLEET_STATE_DIM), f32),
        state=jnp.zeros((C, FLEET_STATE_SCALARS), f32),
        next_action=jnp.zeros((C,), f32),
        avg_reward=jnp.zeros((), f32),
        cum_reward=jnp.zeros((), f32),
        i=jnp.zeros((), jnp.int32),
        t=jnp.zeros((), jnp.int32),
        mem_s=jnp.zeros((MEMORY_CAP, FLEET_STATE_SCALARS), f32),
        mem_a=jnp.zeros((MEMORY_CAP,), f32),
        mem_r=jnp.zeros((MEMORY_CAP,), f32),
        mem_s1=jnp.zeros((MEMORY_CAP, FLEET_STATE_SCALARS), f32),
        comm_keys=community_noise_keys(config, C),
        key=key,
    )


def fleet_linear_step(carry: FleetLinearCarry, fobs: FleetObservation,
                      params: AgentParams, fparams: FleetParams):
    """One fused fleet step of the shared linear core.

    Actor side is the reference's per-community math (core.train_step)
    vectorized over C; learner side is ONE batched ridge refit per step
    from the SHARED replay (the IMPALA split: rollouts feed experience,
    the learner consumes it batched); policy side averages the
    per-community eligibility-trace gradients into the one shared θ_μ.
    """
    C = carry.state.shape[0]
    f32 = jnp.float32
    next_state = _fleet_state(fobs)                     # (C, D)
    first = carry.t == 0
    state = jnp.where(first, next_state, carry.state)
    action = carry.next_action                          # (C,)
    r = fobs.obs.reward.astype(f32)                     # (C,)

    # Per-community exploration streams + the learner's own stream.
    splits = jax.vmap(jax.random.split)(carry.comm_keys)  # (C, 2, 2)
    comm_keys, k_next = splits[:, 0], splits[:, 1]
    key, k_idx, k_act = jax.random.split(carry.key, 3)

    # Memorize C transitions per fleet step (same slot discipline as the
    # single-community core: the degenerate t=0 self-loops are dropped,
    # so the valid prefix of the shared buffer stays dense — fleet step k
    # owns slots (k-1)·C .. k·C-1 mod CAP).
    base = jnp.maximum(carry.t - 1, 0) * C
    slots = jnp.mod(base + jnp.arange(C), MEMORY_CAP)
    keep = lambda old, new: jnp.where(first, old, new)
    mem_s = carry.mem_s.at[slots].set(keep(carry.mem_s[slots], state))
    mem_a = carry.mem_a.at[slots].set(keep(carry.mem_a[slots], action))
    mem_r = carry.mem_r.at[slots].set(keep(carry.mem_r[slots], r))
    mem_s1 = carry.mem_s1.at[slots].set(keep(carry.mem_s1[slots], next_state))
    valid = jnp.minimum(carry.t * C, MEMORY_CAP)

    # Twin-Q index flip BEFORE the TD pair (core.train_step parity).
    i = jnp.mod(carry.i + 1, params.n_q)
    phi_k = jax.vmap(_phi_sa_fleet)(state, action)       # (C, SA)
    mu_next = jax.vmap(lambda sv: carry.theta_mu @ _phi_s_fleet(sv))(
        next_state)                                      # (C,)
    noise = jax.vmap(lambda k: jax.random.normal(k, (), f32))(k_next)
    next_action = mu_next + params.sigma * noise
    phi_k1 = jax.vmap(_phi_sa_fleet)(next_state, next_action)
    q_pred = phi_k @ carry.theta_q[:, i]                 # (C,)
    q_obs = r + params.beta * (phi_k1 @ carry.theta_q[:, i])

    # ----- Batched learner update (shared replay → one ridge refit).
    B = fparams.learner_batch
    idx = jax.random.randint(k_idx, (B,), 0, jnp.maximum(valid, 1))
    s_b, a_b = mem_s[idx], mem_a[idx]
    r_b, s1_b = mem_r[idx], mem_s1[idx]
    a1_keys = jax.random.split(k_act, B)
    mu1 = jax.vmap(lambda sv: carry.theta_mu @ _phi_s_fleet(sv))(s1_b)
    a1 = mu1 + params.sigma * jax.vmap(
        lambda k: jax.random.normal(k, (), f32))(a1_keys)
    phi1 = jax.vmap(_phi_sa_fleet)(s1_b, a1)             # (B, SA)
    q1 = jnp.min(phi1 @ carry.theta_q, axis=1)
    y = r_b + params.beta * q1
    phi = jax.vmap(_phi_sa_fleet)(s_b, a_b)
    phi_c = phi - jnp.mean(phi, axis=0)
    y_c = y - jnp.mean(y)
    gram = phi_c.T @ phi_c + params.ridge_alpha * jnp.eye(
        FLEET_SA_DIM, dtype=phi.dtype)
    theta_r = jnp.linalg.solve(gram, phi_c.T @ y_c)
    blended = (params.alpha_q * theta_r
               + (1.0 - params.alpha_q) * carry.theta_q[:, i])
    do = valid > B
    theta_q = carry.theta_q.at[:, i].set(
        jnp.where(do, blended, carry.theta_q[:, i]))

    # ----- Shared policy update: per-community traces, averaged gradient
    # (the standardized-score discipline of core.train_step, batched).
    x_k = jax.vmap(_phi_s_fleet)(state)                  # (C, SD)
    delta = jnp.clip(q_obs - q_pred, -1.0, 1.0)          # (C,)
    avg_reward = carry.avg_reward + params.alpha_r * jnp.mean(delta)
    cum_reward = carry.cum_reward + jnp.mean(r)
    mu = jnp.clip(x_k @ carry.theta_mu,
                  params.action_low, params.action_high)  # (C,)
    grad_pi_mu = (action - mu)[:, None] / params.sigma * x_k
    z = params.lam_theta * carry.z_theta_mu + grad_pi_mu
    g = jnp.mean(delta[:, None] * z, axis=0)
    if fparams.gradient == "mpc":
        # Deterministic actor term through the relaxed MPC response
        # (CA-AC-MPC): dR/dθ ≈ E_c[ dr/da · φ(s) ], clipped like the TD
        # error for the same stability reason.
        drda = jnp.clip(fobs.drda.astype(f32), -1.0, 1.0)
        g = g + fparams.mpc_weight * jnp.mean(drda[:, None] * x_k, axis=0)
    theta_mu = carry.theta_mu + params.alpha_mu * g

    new_carry = FleetLinearCarry(
        theta_mu=theta_mu, theta_q=theta_q, z_theta_mu=z,
        state=next_state, next_action=next_action,
        avg_reward=avg_reward, cum_reward=cum_reward,
        i=i, t=carry.t + 1,
        mem_s=mem_s, mem_a=mem_a, mem_r=mem_r, mem_s1=mem_s1,
        comm_keys=comm_keys, key=key,
    )
    record = StepRecord(
        theta_q=theta_q[:, i], theta_mu=theta_mu,
        q_obs=q_obs, q_pred=q_pred, action=action,
        average_reward=avg_reward, cumulative_reward=cum_reward,
        reward=r, mu=mu,
    )
    return new_carry, record


# --------------------------------------------------------------------------
# Shared DDPG core (Flax twin-Q, one policy, C rollout streams)
# --------------------------------------------------------------------------

class FleetDDPGCarry(NamedTuple):
    """Shared-policy DDPG state — :class:`dragg_tpu.rl.neural.DDPGCarry`
    with the rollout-side leaves batched over C and one shared replay.
    The networks take the (4 + F)-scalar fleet state (Flax Dense infers
    input width at init, so the neural module's MLPs are reused as-is)."""

    actor: dict
    critic1: dict
    critic2: dict
    t_actor: dict
    t_critic1: dict
    t_critic2: dict
    opt_actor: "object"
    opt_critic1: "object"
    opt_critic2: "object"
    state: jnp.ndarray        # (C, 4 + F)
    next_action: jnp.ndarray  # (C,)
    avg_reward: jnp.ndarray
    cum_reward: jnp.ndarray
    t: jnp.ndarray
    mem_s: jnp.ndarray        # (CAP, 4 + F)
    mem_a: jnp.ndarray
    mem_r: jnp.ndarray
    mem_s1: jnp.ndarray
    comm_keys: jnp.ndarray    # (C, 2)
    key: jnp.ndarray          # (2,)


def init_fleet_ddpg(params, fparams: FleetParams,
                    config: dict) -> FleetDDPGCarry:
    from dragg_tpu.rl import neural

    C = fparams.n_communities
    D = FLEET_STATE_SCALARS
    f32 = jnp.float32
    key = _learner_key(config)
    key, ka, k1, k2 = jax.random.split(key, 4)
    a_net, c_net = neural._nets(params.hidden)
    actor = a_net.init(ka, jnp.zeros((D,), f32))
    critic1 = c_net.init(k1, jnp.zeros((D + neural.ACTION_DIM,), f32))
    critic2 = c_net.init(k2, jnp.zeros((D + neural.ACTION_DIM,), f32))
    return FleetDDPGCarry(
        actor=actor, critic1=critic1, critic2=critic2,
        t_actor=jax.tree.map(jnp.array, actor),
        t_critic1=jax.tree.map(jnp.array, critic1),
        t_critic2=jax.tree.map(jnp.array, critic2),
        opt_actor=neural._adam_init(actor),
        opt_critic1=neural._adam_init(critic1),
        opt_critic2=neural._adam_init(critic2),
        state=jnp.zeros((C, D), f32),
        next_action=jnp.zeros((C,), f32),
        avg_reward=jnp.zeros((), f32),
        cum_reward=jnp.zeros((), f32),
        t=jnp.zeros((), jnp.int32),
        mem_s=jnp.zeros((MEMORY_CAP, D), f32),
        mem_a=jnp.zeros((MEMORY_CAP,), f32),
        mem_r=jnp.zeros((MEMORY_CAP,), f32),
        mem_s1=jnp.zeros((MEMORY_CAP, D), f32),
        comm_keys=community_noise_keys(config, C),
        key=key,
    )


def fleet_ddpg_step(carry: FleetDDPGCarry, fobs: FleetObservation,
                    params, fparams: FleetParams):
    """Shared-policy DDPG fleet step: C rollouts feed the shared replay;
    critic/actor/target updates follow neural.train_step exactly, gated
    and delayed on the FLEET step counter."""
    from dragg_tpu.rl import neural

    C = carry.state.shape[0]
    f32 = jnp.float32
    next_state = _fleet_state(fobs)
    first = carry.t == 0
    state = jnp.where(first, next_state, carry.state)
    action = carry.next_action
    r = fobs.obs.reward.astype(f32)

    splits = jax.vmap(jax.random.split)(carry.comm_keys)
    comm_keys, k_next = splits[:, 0], splits[:, 1]
    key, k_idx = jax.random.split(carry.key)

    base = jnp.maximum(carry.t - 1, 0) * C
    slots = jnp.mod(base + jnp.arange(C), MEMORY_CAP)
    keep = lambda old, new: jnp.where(first, old, new)
    mem_s = carry.mem_s.at[slots].set(keep(carry.mem_s[slots], state))
    mem_a = carry.mem_a.at[slots].set(keep(carry.mem_a[slots], action))
    mem_r = carry.mem_r.at[slots].set(keep(carry.mem_r[slots], r))
    mem_s1 = carry.mem_s1.at[slots].set(keep(carry.mem_s1[slots], next_state))
    valid = jnp.minimum(carry.t * C, MEMORY_CAP)

    B = fparams.learner_batch
    idx = jax.random.randint(k_idx, (B,), 0, jnp.maximum(valid, 1))
    bs, ba, br, bs1 = mem_s[idx], mem_a[idx], mem_r[idx], mem_s1[idx]

    a1 = neural._mu(carry.t_actor, bs1, params)
    q1t = neural._q(carry.t_critic1, bs1, a1, params)
    q2t = neural._q(carry.t_critic2, bs1, a1, params)
    y = br + params.beta * jnp.minimum(q1t, q2t)

    def critic_loss(cp):
        return jnp.mean((neural._q(cp, bs, ba, params) - y) ** 2)

    gated = neural.gated_adam
    do_update = (valid >= B).astype(f32)
    g1 = jax.grad(critic_loss)(carry.critic1)
    g2 = jax.grad(critic_loss)(carry.critic2)
    critic1, opt_c1 = gated(
        do_update,
        neural._adam_update(g1, carry.opt_critic1, carry.critic1,
                            params.critic_lr),
        carry.critic1, carry.opt_critic1)
    critic2, opt_c2 = gated(
        do_update,
        neural._adam_update(g2, carry.opt_critic2, carry.critic2,
                            params.critic_lr),
        carry.critic2, carry.opt_critic2)

    drda = lax.stop_gradient(jnp.clip(fobs.drda.astype(f32), -1.0, 1.0))

    def actor_loss(ap):
        loss = -jnp.mean(neural._q(critic1, bs, neural._mu(ap, bs, params),
                                   params))
        if fparams.gradient == "mpc":
            # Deterministic env-gradient term on the CURRENT rollout
            # states (CA-AC-MPC): ascend dr/da · μ(s).
            loss = loss - fparams.mpc_weight * jnp.mean(
                drda * neural._mu(ap, state, params))
        return loss

    delay = max(1, params.policy_delay)
    do_actor = do_update * (jnp.mod(carry.t, delay) == 0).astype(f32)
    ga = jax.grad(actor_loss)(carry.actor)
    actor, opt_a = gated(
        do_actor,
        neural._adam_update(ga, carry.opt_actor, carry.actor,
                            params.actor_lr),
        carry.actor, carry.opt_actor)

    tau = params.tau * do_actor
    t_actor = neural._polyak(carry.t_actor, actor, tau)
    t_critic1 = neural._polyak(carry.t_critic1, critic1, tau)
    t_critic2 = neural._polyak(carry.t_critic2, critic2, tau)

    mu_next = neural._mu(actor, next_state, params)      # (C,)
    noise = params.sigma * jax.vmap(
        lambda k: jax.random.normal(k, (), f32))(k_next)
    next_action = jnp.clip(mu_next + noise,
                           params.action_low, params.action_high)

    q_pred = neural._q(carry.critic1, state, action, params)  # (C,)
    q_obs = r + params.beta * q_pred
    cum_reward = carry.cum_reward + jnp.mean(r)
    avg_reward = carry.avg_reward + (jnp.mean(r) - carry.avg_reward) / (
        carry.t.astype(f32) + 1.0)

    new_carry = FleetDDPGCarry(
        actor=actor, critic1=critic1, critic2=critic2,
        t_actor=t_actor, t_critic1=t_critic1, t_critic2=t_critic2,
        opt_actor=opt_a, opt_critic1=opt_c1, opt_critic2=opt_c2,
        state=next_state, next_action=next_action,
        avg_reward=avg_reward, cum_reward=cum_reward,
        t=carry.t + 1,
        mem_s=mem_s, mem_a=mem_a, mem_r=mem_r, mem_s1=mem_s1,
        comm_keys=comm_keys, key=key,
    )
    pnorm = lambda p: jnp.sqrt(sum(
        jnp.sum(x * x) for x in jax.tree.leaves(p)))
    record = StepRecord(
        theta_q=pnorm(critic1), theta_mu=pnorm(actor),
        q_obs=q_obs, q_pred=q_pred, action=action,
        average_reward=avg_reward, cumulative_reward=cum_reward,
        reward=r, mu=mu_next,
    )
    return new_carry, record


# --------------------------------------------------------------------------
# Per-community mode: the reference cores, vmapped over C
# --------------------------------------------------------------------------

def init_fleet_per_community(kind: str, params, config: dict,
                             n_communities: int):
    """C independent agent carries stacked along a leading community
    axis, each seeded by ITS community's fleet seed (the same derivation
    as the population — community_seeds)."""
    from dragg_tpu.rl import core, neural

    init = core.init_carry if kind == "linear" else neural.init_carry
    carries = [init(params, int(s))
               for s in community_seeds(config, n_communities)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *carries)


# --------------------------------------------------------------------------
# Scenario event features
# --------------------------------------------------------------------------

def traced_event_features(evt: dict, start, C: int, window: int,
                          max_rp: float) -> jnp.ndarray:
    """(C, N_EVENT_FEATURES) per-community event intensities over the
    upcoming ``window`` steps, computed from the engine's device-resident
    timeline series (engine._evt — only ACTIVE families are present;
    absent families contribute exact zeros).  ``start`` is the
    environment-series index of the current step (engine._prepare's
    anchor: ``start_index + t``)."""
    f32 = jnp.float32
    z = jnp.zeros((C,), f32)

    def win(name):
        s = evt[name]                                   # (C, T)
        return lax.dynamic_slice(s, (0, start), (s.shape[0], window))

    if "price" in evt:
        price = jnp.clip(jnp.mean(win("price"), axis=1)
                         / jnp.float32(max(max_rp, 1e-6)), -3.0, 3.0)
    else:
        price = z
    if "cap" in evt:
        cw = win("cap")
        cap_active = jnp.mean(
            (jnp.isfinite(cw) & (cw > 0)).astype(f32), axis=1)
        outage = jnp.mean((cw == 0).astype(f32), axis=1)
    else:
        cap_active, outage = z, z
    relax = (jnp.clip(jnp.mean(win("relax"), axis=1) / 2.0, 0.0, 3.0)
             if "relax" in evt else z)
    return jnp.stack([price, cap_active, outage, relax], axis=1)


def event_feature_table(timeline, start_index: int, num_timesteps: int,
                        window: int, max_rp: float) -> np.ndarray:
    """Host-precomputed (T, C, F) feature table for the engine-less
    simplified fleet case — same feature definitions as
    :func:`traced_event_features`, windowed per step."""
    C = timeline.n_communities
    feats = np.zeros((num_timesteps, C, N_EVENT_FEATURES), np.float32)
    price = np.asarray(timeline.price)
    cap = np.asarray(timeline.cap)
    relax = np.asarray(timeline.relax)
    T_env = price.shape[1]
    for t in range(num_timesteps):
        a = min(start_index + t, T_env - 1)
        b = min(a + window, T_env)
        pw, cw, rw = price[:, a:b], cap[:, a:b], relax[:, a:b]
        feats[t, :, 0] = np.clip(pw.mean(axis=1) / max(max_rp, 1e-6), -3, 3)
        feats[t, :, 1] = (np.isfinite(cw) & (cw > 0)).mean(axis=1)
        feats[t, :, 2] = (cw == 0).mean(axis=1)
        feats[t, :, 3] = np.clip(rw.mean(axis=1) / 2.0, 0, 3)
    return feats


# --------------------------------------------------------------------------
# Host-facing fleet agent
# --------------------------------------------------------------------------

class FleetAgent(RLAgent):
    """Host bookkeeping for the vectorized fleet policy.

    Reuses :class:`RLAgent`'s telemetry writer / schema; the numeric
    state is one of the four (core × policy-layout) carries above.  The
    rl_data scalar series hold the FLEET MEAN per step (comparable
    across C); per-community actions ride the extra
    ``action_by_community`` key.
    """

    name = "utility"

    def __init__(self, config: dict, n_communities: int):
        self.config = config
        self.kind = str(config["rl"]["parameters"].get("agent", "linear"))
        self.fparams = fleet_params_from_config(config, n_communities)
        if self.kind == "ddpg":
            from dragg_tpu.rl import neural

            self.params = neural.params_from_config(config)
        elif self.kind == "linear":
            self.params = params_from_config(config)
        else:
            raise ValueError(
                f"Unknown rl.parameters.agent {self.kind!r} (linear | ddpg)")
        if self.fparams.policy == "shared":
            if self.kind == "linear":
                self.carry = init_fleet_linear(self.params, self.fparams,
                                               config)
                self._core = fleet_linear_step
            else:
                self.carry = init_fleet_ddpg(self.params, self.fparams,
                                             config)
                self._core = fleet_ddpg_step
        else:
            from dragg_tpu.rl import core, neural

            self.carry = init_fleet_per_community(
                self.kind, self.params, config, n_communities)
            base = core.train_step if self.kind == "linear" \
                else neural.train_step
            params = self.params

            def per_comm(carry, fobs, _p, _f, _step=base, _params=params):
                return jax.vmap(lambda c, o: _step(c, o, _params))(
                    carry, fobs.obs)

            self._core = per_comm
        self.rl_data = new_rl_data(
            self.params.beta, self.params.batch_size, self.params.sigma,
            {"agent": self.kind,
             "fleet": {"communities": n_communities,
                       "policy": self.fparams.policy,
                       "learner_batch": self.fparams.learner_batch,
                       "gradient": self.fparams.gradient,
                       "event_features": self.fparams.event_features}})
        self.rl_data["action_by_community"] = []

    def scan_step(self, carry, fobs: FleetObservation):
        return self._core(carry, fobs, self.params, self.fparams)

    # ------------------------------------------------------------ telemetry
    def record_chunk(self, recs: StepRecord) -> None:
        """Fold a stacked chunk of fleet StepRecords into the rl_data
        schema: scalar keys take the fleet mean per step; θ rows are the
        shared vectors (shared policy) or the community mean
        (per-community mode); per-community actions are kept whole."""
        actions = np.asarray(recs.action)
        T = actions.shape[0]
        acts = actions.reshape(T, -1)
        self.rl_data["action_by_community"].extend(
            [[float(v) for v in row] for row in acts])

        shared = self.fparams.policy == "shared"

        def theta_rows(a):
            a = np.asarray(a)
            if not shared:
                a = a.mean(axis=1)     # fold the community axis
            if a.ndim == 1:            # DDPG parameter norms
                return [[float(v)] for v in a]
            return [list(map(float, row)) for row in a]

        self.rl_data["theta_q"].extend(theta_rows(recs.theta_q))
        self.rl_data["theta_mu"].extend(theta_rows(recs.theta_mu))
        for name, field in (
            ("q_obs", recs.q_obs), ("q_pred", recs.q_pred),
            ("action", recs.action),
            ("average_reward", recs.average_reward),
            ("cumulative_reward", recs.cumulative_reward),
            ("reward", recs.reward), ("mu", recs.mu),
        ):
            a = np.asarray(field).reshape(T, -1).mean(axis=1)
            self.rl_data[name].extend(float(v) for v in a)


# --------------------------------------------------------------------------
# Fleet env carry + fused rl_agg step
# --------------------------------------------------------------------------

class FleetEnvCarry(NamedTuple):
    """(C,)-batched environment carry plus the mpc-gradient channel."""

    env: EnvCarry             # every leaf (C, ...)
    drda: jnp.ndarray         # (C,) d r_{t}/d a_{t-1} (zeros in score mode)


def _rp_matrix(rp_c, H: int, rp_len: int, dt: int):
    """(C, H) per-community price windows + the jvp tangent d rp/d a
    (the window indicator) — the fleet generalization of the runner's
    scalar announcement (rl/runner._fused_step window semantics)."""
    C = rp_c.shape[0]
    if rp_len <= dt or rp_len >= H:
        rp_mat = jnp.broadcast_to(rp_c[:, None], (C, H)).astype(jnp.float32)
        tangent = jnp.ones((C, H), jnp.float32)
    else:
        win = (jnp.arange(H) < rp_len).astype(jnp.float32)[None, :]
        rp_mat = (rp_c[:, None] * win).astype(jnp.float32)
        tangent = jnp.broadcast_to(win, (C, H)).astype(jnp.float32)
    return rp_mat, tangent


def _fleet_fused_step(engine, agent: FleetAgent, dt, norms, max_rp, rp_len,
                      fold, carry, t, t0):
    """One fused fleet RL + community-MPC timestep: C agents observe →
    the shared (or per-community) policy acts → the ENGINE solves every
    community under one compiled pattern set with per-community reward
    prices → per-community aggregates fold back into the batched env
    carry.  Ordering parity with rl/runner._fused_step throughout."""
    comm, mask = fold
    C = agent.fparams.n_communities
    (cstate, acarry, fenv), factor = carry
    env = fenv.env
    obs = jax.vmap(observe, in_axes=(0, None, None, 0))(env, t, dt, norms)
    H = engine.params.horizon
    if agent.fparams.event_features and engine._evt:
        ev = traced_event_features(
            engine._evt, engine.params.start_index + t, C, H, max_rp)
    else:
        ev = jnp.zeros((C, N_EVENT_FEATURES), jnp.float32)
    fobs = FleetObservation(obs=obs, events=ev, drda=fenv.drda)
    acarry, rec = agent.scan_step(acarry, fobs)
    aparams = agent.params
    action = jnp.clip(acarry.next_action,
                      aparams.action_low, aparams.action_high)   # (C,)
    rp_c = jnp.clip(action, -max_rp, max_rp)
    rp_mat, tangent = _rp_matrix(rp_c, H, rp_len, dt)

    K = max(1, engine.params.admm_refactor_every)
    refresh = (t == t0) | ((t % K) == 0)

    def env_step(rp):
        cs, fc, outs = engine._step(cstate, t, rp, refresh, factor)
        # The differentiated head is the RELAXED response: the plan's
        # continuous step-1 grid power.  The applied step-0 aggregate is
        # integerized under the default semantics (tpu.integer_first_
        # action pins ROUNDED duty counts — engine._integerize_first_
        # action), whose tangent is zero almost everywhere; the relaxed
        # plan is exactly what the branch-free solve differentiates
        # (CA-AC-MPC's relaxed-solve gradient).
        fore = jax.ops.segment_sum(outs.forecast_p_grid * mask, comm,
                                   num_segments=C)
        return fore, (cs, fc, outs)

    if agent.fparams.gradient == "mpc":
        # ONE forward-mode pass through the branch-free relaxed solve
        # yields every community's d(relaxed load)/d(action): communities
        # couple only through their own rp rows, so the full-window
        # tangent's cross terms are structurally zero.
        fore_c, dagg, (cstate, factor, outs) = jax.jvp(
            env_step, (rp_mat,), (tangent,), has_aux=True)
    else:
        fore_c, (cstate, factor, outs) = env_step(rp_mat)
        dagg = jnp.zeros((C,), jnp.float32)

    agg_c = jax.ops.segment_sum(outs.p_grid * mask, comm, num_segments=C)
    tracker, sp = jax.vmap(tracker_step, in_axes=(0, 0, None))(
        env.tracker, agg_c, t + 1)
    new_env = EnvCarry(
        agg_load=agg_c,
        forecast_load=fore_c,
        prev_forecast_load=env.forecast_load,
        setpoint=sp,
        prev_action=env.action,
        action=rp_c,
        tracker=tracker,
    )
    # dr_{t+1}/da_t for the NEXT step's actor term: r = -((agg-sp)/norm)²
    # with the setpoint's own (1/prev_n) dependence on agg dropped — a
    # first-order surrogate, clipped at use.
    if agent.fparams.gradient == "mpc":
        err = (agg_c - sp) / norms
        drda = -2.0 * err * dagg / norms
    else:
        drda = jnp.zeros((C,), jnp.float32)
    return (((cstate, acarry, FleetEnvCarry(new_env, drda)), factor),
            (outs, rec, rp_c, env.setpoint))


# --------------------------------------------------------------------------
# Run modes
# --------------------------------------------------------------------------

def _replicate_on_mesh(engine, *trees):
    """Replicate small host carries on the engine's mesh (the same
    discipline as rl/runner.run_rl_agg: a sharded community state cannot
    mix with uncommitted single-device leaves in one jitted carry)."""
    mesh = getattr(engine, "mesh", None)
    if mesh is None:
        return trees if len(trees) > 1 else trees[0]
    from jax.sharding import NamedSharding, PartitionSpec

    rep = NamedSharding(mesh, PartitionSpec())
    put = lambda a: jax.device_put(jnp.asarray(a), rep)
    out = tuple(jax.tree_util.tree_map(put, tr) for tr in trees)
    return out if len(out) > 1 else out[0]


def run_rl_agg_fleet(agg) -> None:
    """RL price-signal aggregator over a C-community MPC fleet: the
    fleet analog of rl/runner.run_rl_agg (same chunk/checkpoint loop,
    batched carries, per-community reward prices)."""
    config = agg.config
    agg.case = "rl_agg"
    C = agg.n_communities
    if agg.all_homes is None:
        agg.get_homes()
    if agg.engine is None:
        agg._build_engine()
    agg.reset_collected_data()
    agg.all_rps = np.zeros(agg.num_timesteps)
    agg.all_sps = np.zeros(agg.num_timesteps)
    agg.fleet_rps = np.zeros((agg.num_timesteps, C))
    agg.fleet_sps = np.zeros((agg.num_timesteps, C))

    from dragg_tpu.rl.runner import _rl_settings

    settings = _rl_settings(config)
    norms_np = agg._max_possible_load_per_community()
    agent = FleetAgent(config, C)
    B = len(agg.all_homes) // C
    env0 = FleetEnvCarry(
        env=init_fleet_env_carry(B, settings["prev_n"], norms_np),
        drda=jnp.zeros((C,), jnp.float32),
    )
    cstate = agg.engine.init_state()
    fold = agg.engine.community_fold_arrays()
    acarry, env0, norms, fold = _replicate_on_mesh(
        agg.engine, agent.carry, env0, jnp.asarray(norms_np, jnp.float32),
        (jnp.asarray(fold[0]), jnp.asarray(fold[1])))

    step = partial(
        _fleet_fused_step, agg.engine, agent, agg.engine.params.dt, norms,
        settings["max_rp"],
        settings["action_horizon"] * agg.engine.params.dt, fold)

    @jax.jit
    def chunk(consts, carry, ts):  # dragg: disable=DT013, fleet RL carry is checkpoint-snapshotted and re-dispatched across chunks; donation pending a measured A/B (round-12 CPU caveat: donated dispatch runs synchronously)
        with agg.engine._bound(consts):
            (carry, _), stacked = lax.scan(
                lambda c, t: step(c, t, ts[0]),
                (carry, agg.engine.init_factor()), ts)
        return carry, stacked

    agg.checkpoint_interval = agg._checkpoint_steps()
    if agg.run_dir is None:
        agg.set_run_dir()
    agg.log.logger.info(
        f"Performing FLEET RL AGG run: {C} communities × {B} homes, "
        f"policy={agent.fparams.policy}/{agent.kind}, "
        f"gradient={agent.fparams.gradient}")
    agg.start_time = time.time()  # dragg: disable=DT014, wall-clock elapsed accounting for progress telemetry
    case_dir = os.path.join(agg.run_dir, agg.case)
    carry, t = agg.try_resume((cstate, acarry, env0))
    if agg.resumed_from is not None:
        rl_file = os.path.join(agg.resumed_from, "rl_data.json")
        if os.path.isfile(rl_file):
            with open(rl_file) as f:
                agent.rl_data = json.load(f)
        fleet_file = os.path.join(agg.resumed_from, "fleet_rl.json")
        if os.path.isfile(fleet_file):
            with open(fleet_file) as f:
                fr = json.load(f)
            agg.fleet_rps = np.asarray(fr["rps"], dtype=np.float64)
            agg.fleet_sps = np.asarray(fr["sps"], dtype=np.float64)
    chunks = 0
    while t < agg.num_timesteps:
        n_steps = min(agg.checkpoint_interval, agg.num_timesteps - t)
        carry, (outs, recs, rps, sps) = chunk(agg.engine._consts(), carry,
                                              jnp.arange(t, t + n_steps))
        agg._collect_chunk(outs, track_setpoints=False)
        agent.record_chunk(recs)
        rps = np.asarray(rps)                      # (n_steps, C)
        sps = np.asarray(sps)
        agg.fleet_rps[t:t + n_steps] = rps
        agg.fleet_sps[t:t + n_steps] = sps
        agg.all_rps[t:t + n_steps] = rps.mean(axis=1)
        agg.all_sps[t:t + n_steps] = sps.mean(axis=1)
        t += n_steps
        chunks += 1
        if t < agg.num_timesteps:
            _set_fleet_summary(agg, agent)
            agg.write_outputs()
            agg.save_checkpoint(carry, extra_json={
                "rl_data.json": agent.rl_data,
                "fleet_rl.json": {"rps": agg.fleet_rps.tolist(),
                                  "sps": agg.fleet_sps.tolist()}})
            if agg.stop_after_chunks is not None \
                    and chunks >= agg.stop_after_chunks:
                agg.log.logger.info(f"Stopping early after {chunks} chunks.")
                agg._state, agent.carry, _ = carry
                agg.agent = agent
                return
    agg._state, agent.carry, _ = carry
    agg.check_baseline_vals()
    _set_fleet_summary(agg, agent)
    agg.write_outputs()
    agent.write_rl_data(case_dir)
    agg.clear_checkpoint()
    agg.agent = agent


def _set_fleet_summary(agg, agent: FleetAgent) -> None:
    """Per-community RL extras for the Summary block.  The full (T, C)
    reward-price matrix is included up to a size cap (beyond it, only
    the per-community mean |rp| — summary JSON is not a bulk store)."""
    block = {
        "communities": agent.fparams.n_communities,
        "policy": agent.fparams.policy,
        "agent": agent.kind,
        "learner_batch": agent.fparams.learner_batch,
        "gradient": agent.fparams.gradient,
        "event_features": agent.fparams.event_features,
        "mean_abs_rp_by_community":
            [round(float(v), 6)
             for v in np.abs(agg.fleet_rps).mean(axis=0)],
    }
    if agg.fleet_rps.size <= 200_000:
        block["RP_by_community"] = agg.fleet_rps.T.tolist()
        block["setpoint_by_community"] = agg.fleet_sps.T.tolist()
    agg.extra_summary["fleet_rl"] = block


def run_rl_simplified_fleet(agg) -> None:
    """RL agents vs C simplified linear communities — the whole fleet
    loop (C rollouts + shared learner + linear response) is ONE device
    scan.  Scenario event timelines (if configured) ride the observation
    as a host-precomputed feature table; in mpc-gradient mode the
    response derivative is EXACT (the model is linear)."""
    config = agg.config
    agg.case = "simplified"
    C = agg.n_communities
    from dragg_tpu.rl.runner import _rl_settings

    settings = _rl_settings(config)
    simp = config["agg"].get("simplified", {})
    c_rate = float(simp.get("response_rate", 0.3))
    n_homes = int(config["community"]["total_number_homes"])
    house_p_avg = float(config["community"].get("house_p_avg", 1.2))
    norm = max(1.0, house_p_avg * n_homes * 2.5)
    dt = agg.dt
    max_rp = settings["max_rp"]

    agent = FleetAgent(config, C)
    aparams = agent.params

    tr = init_tracker(settings["prev_n"], house_p_avg * n_homes * 2.5)
    sp0 = float(np.mean(np.asarray(tr.tracked)))
    f32 = jnp.float32
    rep = lambda v: jnp.full((C,), v, f32)
    env0 = FleetEnvCarry(
        env=EnvCarry(
            agg_load=rep(1.1 * sp0), forecast_load=rep(1.1 * sp0),
            prev_forecast_load=rep(1.1 * sp0), setpoint=rep(sp0),
            prev_action=jnp.zeros((C,), f32), action=jnp.zeros((C,), f32),
            tracker=jax.tree.map(
                lambda a: jnp.broadcast_to(a, (C,) + a.shape), tr),
        ),
        drda=jnp.zeros((C,), f32),
    )

    # Event features: resolved timeline → host (T, C, F) table (window =
    # one hour, the announcement granularity of the simplified case).
    feats = jnp.zeros((agg.num_timesteps, C, N_EVENT_FEATURES), f32)
    if agent.fparams.event_features:
        from dragg_tpu.scenarios import timeline_for

        tl = timeline_for(config, C, agg.start_index + agg.num_timesteps
                          + dt, dt, agg.start_index)
        if tl is not None:
            feats = jnp.asarray(event_feature_table(
                tl, agg.start_index, agg.num_timesteps, dt, max_rp))

    use_mpc = agent.fparams.gradient == "mpc"

    def step(carry, t):
        acarry, fenv = carry
        env = fenv.env
        obs = jax.vmap(observe, in_axes=(0, None, None, None))(
            env, t, dt, norm)
        fobs = FleetObservation(obs=obs, events=feats[t], drda=fenv.drda)
        acarry, rec = agent.scan_step(acarry, fobs)
        action = jnp.clip(acarry.next_action,
                          aparams.action_low, aparams.action_high)
        rp = jnp.clip(action, -max_rp, max_rp)          # (C,)
        load, cost = simplified_response(env.agg_load, rp, env.setpoint,
                                         c_rate)
        tracker, sp = jax.vmap(tracker_step, in_axes=(0, 0, None))(
            env.tracker, load, t + 1)
        new_env = EnvCarry(
            agg_load=load, forecast_load=load,
            prev_forecast_load=env.agg_load,
            setpoint=sp, prev_action=env.action, action=rp, tracker=tracker)
        if use_mpc:
            # Exact response derivative: d load/d rp = -c·(sp − load).
            dload = -c_rate * (env.setpoint - env.agg_load)
            err = (load - sp) / norm
            drda = -2.0 * err * dload / norm
        else:
            drda = jnp.zeros_like(load)
        return ((acarry, FleetEnvCarry(new_env, drda)),
                (rec, load, cost, rp, env.setpoint))

    @jax.jit
    def run(carry, ts):  # dragg: disable=DT013, fleet simplified-response carry is tiny (agent params + env scalars) and re-read for logging; donation buys nothing here
        return lax.scan(step, carry, ts)

    agg.log.logger.info(
        f"Performing FLEET RL simplified run: {C} communities, "
        f"policy={agent.fparams.policy}/{agent.kind}")
    agg.start_time = time.time()  # dragg: disable=DT014, wall-clock elapsed accounting for progress telemetry
    (acarry, _env), (recs, loads, costs, rps, sps) = run(
        (agent.carry, env0), jnp.arange(agg.num_timesteps))
    agent.carry = acarry
    agent.record_chunk(recs)

    loads = np.asarray(loads)                      # (T, C)
    costs = np.asarray(costs)
    rps = np.asarray(rps)
    sps = np.asarray(sps)
    agg._solve_iters = []
    # Fleet aggregate = sum over communities (the baseline fleet
    # engine's agg_load convention); per-community series ride the
    # fleet_rl Summary block.
    agg.baseline_agg_load_list = loads.sum(axis=1).tolist()
    agg.all_rps = rps.mean(axis=1).astype(np.float64)
    agg.all_sps = sps.mean(axis=1).astype(np.float64)
    agg.fleet_rps = rps.astype(np.float64)
    agg.fleet_sps = sps.astype(np.float64)
    agg.extra_summary = {"agg_cost": costs.sum(axis=1).tolist()}
    _set_fleet_summary(agg, agent)
    agg.summary_only_case = True
    if agg.run_dir is None:
        agg.set_run_dir()
    agg.write_outputs()
    agg.extra_summary = {}
    agg.summary_only_case = False
    case_dir = os.path.join(agg.run_dir, agg.case)
    agent.write_rl_data(case_dir)
    agg.agent = agent
