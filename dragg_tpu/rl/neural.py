"""Flax DDPG agent with twin-Q critics — the neural upgrade of the linear
actor-critic core (BASELINE.md row 4: "DDPG + twin-Q (Flax)").

Capability mapping to the reference agent (dragg/agent.py:42-232): same
4-scalar observation, same replay-buffer + batch critic fit + policy-step
structure — but the function approximators are MLPs trained by Adam instead
of hand-built polynomial/Fourier bases fit by Ridge, and the critic targets
use TD3-style tricks (twin critics with min-target, target networks with
Polyak averaging) that the reference's twin-Q flag gestures at
(dragg/agent.py:189-201).

Everything is fixed-shape and jittable: ``DDPGCarry`` is a pytree threaded
through ``lax.scan`` exactly like the linear ``AgentCarry``, so the fused
rl_agg / rl_simplified device scans (dragg_tpu/rl/runner.py) work unchanged
with either core — select with ``[rl.parameters] agent = "ddpg"``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from dragg_tpu.rl.core import RLObservation, StepRecord, obs_to_state

MEMORY_CAP = 2048  # replay capacity — matches the linear core's circular buffer

STATE_DIM = 4
ACTION_DIM = 1


class DDPGParams(NamedTuple):
    """Static hyperparameters (lr/tau/hidden are tpu-config extras; the rest
    map to the reference's [rl.parameters], dragg/agent.py:78-86)."""

    sigma: float        # exploration noise std (reference's epsilon)
    beta: float         # discount
    batch_size: int
    actor_lr: float
    critic_lr: float
    tau: float          # Polyak target-update rate
    policy_delay: int   # actor/target update cadence in steps (TD3)
    action_low: float
    action_high: float
    hidden: int         # MLP width


class MLP(nn.Module):
    """Two-hidden-layer MLP; tanh head for the actor, linear for critics."""

    hidden: int
    out: int
    tanh_out: bool = False

    @nn.compact
    def __call__(self, x):
        x = nn.tanh(nn.Dense(self.hidden)(x))
        x = nn.tanh(nn.Dense(self.hidden)(x))
        x = nn.Dense(self.out)(x)
        return nn.tanh(x) if self.tanh_out else x


class AdamState(NamedTuple):
    """Minimal Adam moments (avoids carrying optax state pytrees whose
    structure is opaque to checkpoint templates)."""

    mu: dict
    nu: dict
    count: jnp.ndarray


def _adam_init(params) -> AdamState:
    z = jax.tree.map(jnp.zeros_like, params)
    return AdamState(mu=z, nu=jax.tree.map(jnp.zeros_like, params),
                     count=jnp.zeros((), jnp.int32))


def _adam_update(grads, st: AdamState, params, lr: float,
                 b1=0.9, b2=0.999, eps=1e-8):
    count = st.count + 1
    mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, st.mu, grads)
    nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * g * g, st.nu, grads)
    c = count.astype(jnp.float32)
    mhat = jax.tree.map(lambda m: m / (1 - b1 ** c), mu)
    vhat = jax.tree.map(lambda v: v / (1 - b2 ** c), nu)
    new_params = jax.tree.map(
        lambda p, m, v: p - lr * m / (jnp.sqrt(v) + eps), params, mhat, vhat
    )
    return new_params, AdamState(mu=mu, nu=nu, count=count)


class DDPGCarry(NamedTuple):
    """Agent state threaded through ``lax.scan``."""

    actor: dict
    critic1: dict
    critic2: dict
    t_actor: dict       # target networks
    t_critic1: dict
    t_critic2: dict
    opt_actor: AdamState
    opt_critic1: AdamState
    opt_critic2: AdamState
    state: jnp.ndarray        # (4,)
    next_action: jnp.ndarray  # ()
    avg_reward: jnp.ndarray
    cum_reward: jnp.ndarray
    t: jnp.ndarray            # () int32
    mem_s: jnp.ndarray        # (CAP, 4)
    mem_a: jnp.ndarray        # (CAP,)
    mem_r: jnp.ndarray        # (CAP,)
    mem_s1: jnp.ndarray       # (CAP, 4)
    key: jnp.ndarray


_actor_net: MLP | None = None
_critic_net: MLP | None = None


def _nets(hidden: int):
    global _actor_net, _critic_net
    if _actor_net is None or _actor_net.hidden != hidden:
        _actor_net = MLP(hidden=hidden, out=ACTION_DIM, tanh_out=True)
        _critic_net = MLP(hidden=hidden, out=1)
    return _actor_net, _critic_net


def _scale_action(raw, params: DDPGParams):
    """tanh output in [-1, 1] → action space."""
    lo, hi = params.action_low, params.action_high
    return lo + (raw + 1.0) * 0.5 * (hi - lo)


def _mu(actor_params, s, params: DDPGParams):
    a_net, _ = _nets(params.hidden)
    return _scale_action(a_net.apply(actor_params, s)[..., 0], params)


def _q(critic_params, s, a, params: DDPGParams):
    _, c_net = _nets(params.hidden)
    sa = jnp.concatenate([s, a[..., None]], axis=-1)
    return c_net.apply(critic_params, sa)[..., 0]


def init_carry(params: DDPGParams, seed: int) -> DDPGCarry:
    key = jax.random.PRNGKey(seed ^ 0xDD96)
    key, ka, k1, k2 = jax.random.split(key, 4)
    a_net, c_net = _nets(params.hidden)
    s0 = jnp.zeros((STATE_DIM,), jnp.float32)
    sa0 = jnp.zeros((STATE_DIM + ACTION_DIM,), jnp.float32)
    actor = a_net.init(ka, s0)
    critic1 = c_net.init(k1, sa0)
    critic2 = c_net.init(k2, sa0)
    f32 = jnp.float32
    return DDPGCarry(
        actor=actor, critic1=critic1, critic2=critic2,
        t_actor=jax.tree.map(jnp.array, actor),
        t_critic1=jax.tree.map(jnp.array, critic1),
        t_critic2=jax.tree.map(jnp.array, critic2),
        opt_actor=_adam_init(actor),
        opt_critic1=_adam_init(critic1),
        opt_critic2=_adam_init(critic2),
        state=jnp.zeros((STATE_DIM,), f32),
        next_action=jnp.zeros((), f32),
        avg_reward=jnp.zeros((), f32),
        cum_reward=jnp.zeros((), f32),
        t=jnp.zeros((), jnp.int32),
        mem_s=jnp.zeros((MEMORY_CAP, STATE_DIM), f32),
        mem_a=jnp.zeros((MEMORY_CAP,), f32),
        mem_r=jnp.zeros((MEMORY_CAP,), f32),
        mem_s1=jnp.zeros((MEMORY_CAP, STATE_DIM), f32),
        key=key,
    )


def _polyak(target, online, tau):
    return jax.tree.map(lambda t, o: (1 - tau) * t + tau * o, target, online)


def gated_adam(gate, new_pair, old_params, old_opt):
    """Select (params, opt) updated-vs-unchanged.  Zeroing gradients is
    NOT enough to freeze Adam — momentum keeps moving the parameters and
    count skews bias correction — so the whole update is switched.  The
    ONE implementation shared by :func:`train_step` and the fleet DDPG
    core (dragg_tpu/rl/fleet), so the freeze semantics cannot drift."""
    new_params, new_opt = new_pair
    pick = lambda a, b: jax.tree.map(
        lambda x, y: jnp.where(gate > 0, x, y), a, b)
    return pick(new_params, old_params), AdamState(
        mu=pick(new_opt.mu, old_opt.mu),
        nu=pick(new_opt.nu, old_opt.nu),
        count=jnp.where(gate > 0, new_opt.count, old_opt.count),
    )


def train_step(carry: DDPGCarry, obs: RLObservation, params: DDPGParams):
    """One DDPG step with the same contract as the linear core's
    ``train_step``: observe → memorize → (critic, actor, target) updates →
    sample the next exploratory action.  Returns (carry, StepRecord) — the
    record's ``theta_q``/``theta_mu`` slots carry network parameter norms
    (scalars) so the telemetry schema stays write-compatible."""
    f32 = jnp.float32
    next_state = obs_to_state(obs)
    first = carry.t == 0
    state = jnp.where(first, next_state, carry.state)
    action = carry.next_action
    r = obs.reward.astype(f32)

    key, k_next, k_idx = jax.random.split(carry.key, 3)

    # Memorize (same slot discipline as the linear core: drop the t=0
    # degenerate transition; slot k-1 holds step k's experience).
    slot = jnp.mod(jnp.maximum(carry.t - 1, 0), MEMORY_CAP)
    keep = lambda old, new: jnp.where(first, old, new)
    mem_s = carry.mem_s.at[slot].set(keep(carry.mem_s[slot], state))
    mem_a = carry.mem_a.at[slot].set(keep(carry.mem_a[slot], action))
    mem_r = carry.mem_r.at[slot].set(keep(carry.mem_r[slot], r))
    mem_s1 = carry.mem_s1.at[slot].set(keep(carry.mem_s1[slot], next_state))
    valid = jnp.minimum(carry.t, MEMORY_CAP)

    # --- Batch sample.
    B = params.batch_size
    idx = jax.random.randint(k_idx, (B,), 0, jnp.maximum(valid, 1))
    bs, ba, br, bs1 = mem_s[idx], mem_a[idx], mem_r[idx], mem_s1[idx]

    # --- Critic update: y = r + beta * min_i Q_ti(s', mu_t(s')).
    a1 = _mu(carry.t_actor, bs1, params)
    q1t = _q(carry.t_critic1, bs1, a1, params)
    q2t = _q(carry.t_critic2, bs1, a1, params)
    y = br + params.beta * jnp.minimum(q1t, q2t)

    def critic_loss(cp):
        return jnp.mean((_q(cp, bs, ba, params) - y) ** 2)

    gated = gated_adam
    do_update = (carry.t >= B).astype(f32)  # len(memory) > batch gate
    g1 = jax.grad(critic_loss)(carry.critic1)
    g2 = jax.grad(critic_loss)(carry.critic2)
    critic1, opt_c1 = gated(
        do_update,
        _adam_update(g1, carry.opt_critic1, carry.critic1, params.critic_lr),
        carry.critic1, carry.opt_critic1)
    critic2, opt_c2 = gated(
        do_update,
        _adam_update(g2, carry.opt_critic2, carry.critic2, params.critic_lr),
        carry.critic2, carry.opt_critic2)

    # --- Delayed actor update: maximize Q1(s, mu(s)).
    def actor_loss(ap):
        return -jnp.mean(_q(critic1, bs, _mu(ap, bs, params), params))

    delay = max(1, params.policy_delay)
    do_actor = do_update * (jnp.mod(carry.t, delay) == 0).astype(f32)
    ga = jax.grad(actor_loss)(carry.actor)
    actor, opt_a = gated(
        do_actor,
        _adam_update(ga, carry.opt_actor, carry.actor, params.actor_lr),
        carry.actor, carry.opt_actor)

    # --- Polyak target updates (gated with the actor cadence).
    tau = params.tau * do_actor
    t_actor = _polyak(carry.t_actor, actor, tau)
    t_critic1 = _polyak(carry.t_critic1, critic1, tau)
    t_critic2 = _polyak(carry.t_critic2, critic2, tau)

    # --- Next exploratory action.
    mu_next = _mu(actor, next_state, params)
    noise = params.sigma * jax.random.normal(k_next, (), f32)
    next_action = jnp.clip(mu_next + noise, params.action_low, params.action_high)

    q_pred = _q(carry.critic1, state[None, :], action[None], params)[0]
    q_obs = r + params.beta * q_pred  # 1-step TD pair for telemetry
    cum_reward = carry.cum_reward + r
    avg_reward = carry.avg_reward + (r - carry.avg_reward) / (
        carry.t.astype(f32) + 1.0
    )

    new_carry = DDPGCarry(
        actor=actor, critic1=critic1, critic2=critic2,
        t_actor=t_actor, t_critic1=t_critic1, t_critic2=t_critic2,
        opt_actor=opt_a, opt_critic1=opt_c1, opt_critic2=opt_c2,
        state=next_state, next_action=next_action,
        avg_reward=avg_reward, cum_reward=cum_reward,
        t=carry.t + 1,
        mem_s=mem_s, mem_a=mem_a, mem_r=mem_r, mem_s1=mem_s1,
        key=key,
    )
    pnorm = lambda p: jnp.sqrt(sum(
        jnp.sum(x * x) for x in jax.tree.leaves(p)
    ))
    record = StepRecord(
        theta_q=pnorm(critic1),
        theta_mu=pnorm(actor),
        q_obs=q_obs,
        q_pred=q_pred,
        action=action,
        average_reward=avg_reward,
        cumulative_reward=cum_reward,
        reward=r,
        mu=mu_next,
    )
    return new_carry, record


def params_from_config(config: dict) -> DDPGParams:
    """[rl.parameters] (+ optional [tpu] neural knobs) → DDPGParams."""
    p = config["rl"]["parameters"]
    space = config["rl"]["utility"]["action_space"]
    tpu = config.get("tpu", {})
    return DDPGParams(
        sigma=float(p["epsilon"]),
        beta=float(p["beta"]),
        batch_size=int(p["batch_size"]),
        actor_lr=float(tpu.get("ddpg_actor_lr", 1e-3)),
        critic_lr=float(tpu.get("ddpg_critic_lr", 1e-3)),
        tau=float(tpu.get("ddpg_tau", 0.01)),
        policy_delay=int(tpu.get("ddpg_policy_delay", 2)),
        action_low=float(space[0]),
        action_high=float(space[1]),
        hidden=int(tpu.get("ddpg_hidden", 64)),
    )
