"""Functional RL agent core — the reference ``RLAgent`` re-expressed as one
jittable step over explicit state.

Capability parity with dragg/agent.py:42-232:

* Gaussian policy with linearly-parameterized mean μ = θ_μ·φ(s), fixed σ
  (dragg/agent.py:151-165);
* twin-Q linear critic with alternating update index (dragg/agent.py:189-201);
* replay buffer + batch Ridge regression targets
  y = r + β·min_i θ_qᵢ·φ(s', a'~π) (dragg/agent.py:167-213) — the sklearn
  ``Ridge(α).fit`` becomes the closed-form device solve
  (ΦᵀΦ + αI)⁻¹Φᵀy (SURVEY.md §2.2);
* eligibility-trace policy update with TD-error clipped to ±1
  (dragg/agent.py:215-232).

Deviation (documented): the reference's twin-Q ridge blend uses
``theta_q.flatten()`` (dragg/agent.py:213), which is shape-inconsistent when
two critics exist; we blend against the updated column ``theta_q[:, i]``.

Everything is fixed-shape: the replay buffer is a circular device array and
the batch update is gated by masking rather than Python control flow, so the
step composes into ``lax.scan`` alongside the community engine.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from dragg_tpu.rl.basis import (
    STATE_ACTION_DIM,
    STATE_DIM,
    state_action_basis,
    state_basis,
)

MEMORY_CAP = 2048  # circular replay capacity (reference list is unbounded)


class AgentParams(NamedTuple):
    """Hyperparameters (dragg/agent.py:78-86; config [rl.parameters])."""

    alpha_q: float
    alpha_mu: float
    alpha_r: float
    beta: float
    sigma: float
    batch_size: int
    n_q: int           # 2 if twin_q else 1
    lam_theta: float   # eligibility-trace decay (dragg/agent.py:61)
    ridge_alpha: float  # Ridge regularization (dragg/agent.py:210)
    action_low: float
    action_high: float


class RLObservation(NamedTuple):
    """One environment observation s_{t+1} plus the reward r_t.

    The four state scalars are the reference's state dict keys
    (dragg/agent.py:89-107): normalized forecast error, forecast trend,
    fractional time-of-day, and the change in action.
    """

    fcst_error: jnp.ndarray
    forecast_trend: jnp.ndarray
    time_of_day: jnp.ndarray
    delta_action: jnp.ndarray
    reward: jnp.ndarray


class AgentCarry(NamedTuple):
    """Explicit agent state threaded through ``lax.scan``."""

    theta_mu: jnp.ndarray     # (STATE_DIM,)
    theta_q: jnp.ndarray      # (STATE_ACTION_DIM, n_q)
    z_theta_mu: jnp.ndarray   # (STATE_DIM,) eligibility trace
    state: jnp.ndarray        # (4,) current state scalars
    next_action: jnp.ndarray  # () action chosen for the upcoming step
    avg_reward: jnp.ndarray   # ()
    cum_reward: jnp.ndarray   # ()
    i: jnp.ndarray            # () int32 twin-Q index
    t: jnp.ndarray            # () int32 steps taken
    mem_s: jnp.ndarray        # (CAP, 4) replay: state
    mem_a: jnp.ndarray        # (CAP,)   replay: action
    mem_r: jnp.ndarray        # (CAP,)   replay: reward
    mem_s1: jnp.ndarray       # (CAP, 4) replay: next state
    key: jnp.ndarray          # PRNG key


class StepRecord(NamedTuple):
    """Per-step telemetry — the reference's rl_data fields
    (dragg/agent.py:247-256)."""

    theta_q: jnp.ndarray
    theta_mu: jnp.ndarray
    q_obs: jnp.ndarray
    q_pred: jnp.ndarray
    action: jnp.ndarray
    average_reward: jnp.ndarray
    cumulative_reward: jnp.ndarray
    reward: jnp.ndarray
    mu: jnp.ndarray


def init_carry(params: AgentParams, seed: int) -> AgentCarry:
    """Fresh agent state.  θ_q ~ N(0, 0.3) matches the reference's lazy critic
    init (dragg/agent.py:199); θ_μ starts at zero (dragg/agent.py:161)."""
    key = jax.random.PRNGKey(seed)
    key, kq = jax.random.split(key)
    f32 = jnp.float32
    return AgentCarry(
        theta_mu=jnp.zeros((STATE_DIM,), f32),
        theta_q=0.3 * jax.random.normal(kq, (STATE_ACTION_DIM, params.n_q), f32),
        z_theta_mu=jnp.zeros((STATE_DIM,), f32),
        state=jnp.zeros((4,), f32),
        next_action=jnp.zeros((), f32),
        avg_reward=jnp.zeros((), f32),
        cum_reward=jnp.zeros((), f32),
        i=jnp.zeros((), jnp.int32),
        t=jnp.zeros((), jnp.int32),
        mem_s=jnp.zeros((MEMORY_CAP, 4), f32),
        mem_a=jnp.zeros((MEMORY_CAP,), f32),
        mem_r=jnp.zeros((MEMORY_CAP,), f32),
        mem_s1=jnp.zeros((MEMORY_CAP, 4), f32),
        key=key,
    )


def obs_to_state(obs: RLObservation) -> jnp.ndarray:
    """Stack the four observation scalars into the ``(..., 4)`` state
    vector — the ONE definition of the state layout, shared by the
    single-community cores here / in :mod:`dragg_tpu.rl.neural` and the
    fleet cores (:mod:`dragg_tpu.rl.fleet`, where the leaves carry a
    leading community axis), so the two cannot drift."""
    f32 = jnp.float32
    return jnp.stack([
        obs.fcst_error.astype(f32),
        obs.forecast_trend.astype(f32),
        obs.time_of_day.astype(f32),
        obs.delta_action.astype(f32),
    ], axis=-1)


def _phi_s(s):
    return state_basis(s[0], s[1], s[2])


def _phi_sa(s, a):
    return state_action_basis(s[0], s[1], s[2], s[3], a)


def _policy_action(theta_mu, s, sigma, key):
    """a ~ N(θ_μ·φ(s), σ) (dragg/agent.py:151-165)."""
    mu = theta_mu @ _phi_s(s)
    return mu + sigma * jax.random.normal(key, (), jnp.float32), mu


def _ridge_update(carry: AgentCarry, params: AgentParams, key):
    """Batch critic refit (dragg/agent.py:203-213) as a closed-form solve.

    Samples ``batch_size`` experiences from the valid prefix of the circular
    buffer, recomputes stochastic next actions under the current policy,
    builds TD targets with the min over critics, and ridge-fits θ.
    """
    B = params.batch_size
    # carry.t here is the post-increment step count; t=0 stored nothing, so
    # the dense valid prefix holds t−1 experiences.
    valid = jnp.minimum(carry.t - 1, MEMORY_CAP)
    kidx, kact = jax.random.split(key)
    idx = jax.random.randint(kidx, (B,), 0, jnp.maximum(valid, 1))
    s = carry.mem_s[idx]          # (B, 4)
    a = carry.mem_a[idx]          # (B,)
    r = carry.mem_r[idx]          # (B,)
    s1 = carry.mem_s1[idx]        # (B, 4)
    a1_keys = jax.random.split(kact, B)
    a1, _ = jax.vmap(lambda sv, k: _policy_action(carry.theta_mu, sv, params.sigma, k))(s1, a1_keys)
    phi1 = jax.vmap(_phi_sa)(s1, a1)          # (B, DIM)
    q1 = jnp.min(phi1 @ carry.theta_q, axis=1)  # min over critics (dragg/agent.py:174)
    y = r + params.beta * q1
    phi = jax.vmap(_phi_sa)(s, a)             # (B, DIM)
    # sklearn Ridge(fit_intercept=True) centers features and targets; mirror
    # that so the coefficient vector matches Ridge.coef_ semantics.
    phi_c = phi - jnp.mean(phi, axis=0)
    y_c = y - jnp.mean(y)
    gram = phi_c.T @ phi_c + params.ridge_alpha * jnp.eye(STATE_ACTION_DIM, dtype=phi.dtype)
    theta_r = jnp.linalg.solve(gram, phi_c.T @ y_c)
    i = carry.i
    blended = params.alpha_q * theta_r + (1.0 - params.alpha_q) * carry.theta_q[:, i]
    do = (carry.t - 1) > B  # len(memory) > BATCH_SIZE (dragg/agent.py:203)
    new_col = jnp.where(do, blended, carry.theta_q[:, i])
    return carry.theta_q.at[:, i].set(new_col)


def train_step(carry: AgentCarry, obs: RLObservation, params: AgentParams):
    """One agent step — the reference's ``train(env)`` (dragg/agent.py:130-149)
    with the env observation passed in explicitly.

    Returns ``(new_carry, record)``; ``new_carry.next_action`` is the action
    to apply next timestep (the reward-price scalar before clipping).
    """
    f32 = jnp.float32
    next_state = obs_to_state(obs)
    # Timestep 0: state ← next_state, action stays 0 (dragg/agent.py:132-136).
    first = carry.t == 0
    state = jnp.where(first, next_state, carry.state)
    action = carry.next_action
    r = obs.reward.astype(f32)

    key, k_next, k_ridge = jax.random.split(carry.key, 3)
    xu_k = _phi_sa(state, action)
    next_action, _ = _policy_action(carry.theta_mu, next_state, params.sigma, k_next)
    xu_k1 = _phi_sa(next_state, next_action)

    # memorize (dragg/agent.py:125-128).  The reference skips t=0 (its
    # falsy-action guard); we likewise drop the degenerate t=0 self-loop
    # (s1, 0, r0, s1) so the buffer holds only real transitions — slot k-1
    # stores step k's experience, keeping the valid prefix dense.
    slot = jnp.mod(jnp.maximum(carry.t - 1, 0), MEMORY_CAP)
    keep = lambda old, new: jnp.where(first, old, new)
    mem_s = carry.mem_s.at[slot].set(keep(carry.mem_s[slot], state))
    mem_a = carry.mem_a.at[slot].set(keep(carry.mem_a[slot], action))
    mem_r = carry.mem_r.at[slot].set(keep(carry.mem_r[slot], r))
    mem_s1 = carry.mem_s1.at[slot].set(keep(carry.mem_s1[slot], next_state))

    # Twin-Q index flip BEFORE the TD pair (dragg/agent.py:190-201).
    i = jnp.mod(carry.i + 1, params.n_q)
    q_pred = carry.theta_q[:, i] @ xu_k
    q_obs = r + params.beta * (carry.theta_q[:, i] @ xu_k1)

    mid = carry._replace(
        mem_s=mem_s, mem_a=mem_a, mem_r=mem_r, mem_s1=mem_s1,
        i=i, t=carry.t + 1, state=state,
    )
    theta_q = _ridge_update(mid, params, k_ridge)

    # Policy update (dragg/agent.py:215-232).  Three documented deviations
    # from the reference, which as written cannot improve its policy:
    # * TD error: standard target-minus-prediction (q_obs − q_pred); the
    #   reference computes the negation (dragg/agent.py:222), which performs
    #   gradient DESCENT on return;
    # * Gaussian score: ∇_μ log π = (a−μ)/σ²·φ(s); the reference multiplies
    #   by σ² (dragg/agent.py:229), mis-scaling updates by σ⁴ (≈1.6e5× too
    #   small at the default σ=0.05);
    # * the score is STANDARDIZED to (a−μ)/σ·φ(s) — the 1/σ² true-gradient
    #   scale folded into the step size — so ``alpha`` stays a dimensionless
    #   learning rate: with the raw score, any σ ≲ 0.05 needs α rescaled by
    #   σ² or θ_μ diverges (measured: NaN within 3k steps at σ=0.02,
    #   α=0.0625; stable and learning with the standardized form).
    x_k = _phi_s(state)
    delta = jnp.clip(q_obs - q_pred, -1.0, 1.0)
    avg_reward = carry.avg_reward + params.alpha_r * delta
    cum_reward = carry.cum_reward + r
    mu = jnp.clip(carry.theta_mu @ x_k, params.action_low, params.action_high)
    grad_pi_mu = (action - mu) / params.sigma * x_k
    z = params.lam_theta * carry.z_theta_mu + grad_pi_mu
    theta_mu = carry.theta_mu + params.alpha_mu * delta * z

    new_carry = AgentCarry(
        theta_mu=theta_mu,
        theta_q=theta_q,
        z_theta_mu=z,
        state=next_state,
        next_action=next_action,
        avg_reward=avg_reward,
        cum_reward=cum_reward,
        i=i,
        t=carry.t + 1,
        mem_s=mem_s,
        mem_a=mem_a,
        mem_r=mem_r,
        mem_s1=mem_s1,
        key=key,
    )
    record = StepRecord(
        theta_q=theta_q[:, i],
        theta_mu=theta_mu,
        q_obs=q_obs,
        q_pred=q_pred,
        action=action,
        average_reward=avg_reward,
        cumulative_reward=cum_reward,
        reward=r,
        mu=mu,
    )
    return new_carry, record


def params_from_config(config: dict) -> AgentParams:
    """Build AgentParams from the [rl] config tables (dragg/agent.py:71-86)."""
    p = config["rl"]["parameters"]
    space = config["rl"]["utility"]["action_space"]
    alpha = float(p["alpha"])
    return AgentParams(
        alpha_q=alpha,
        alpha_mu=alpha,
        alpha_r=alpha * 4.0,   # ALPHA_r = alpha·2² (dragg/agent.py:82)
        beta=float(p["beta"]),
        sigma=float(p["epsilon"]),
        batch_size=int(p["batch_size"]),
        n_q=2 if p.get("twin_q", True) else 1,
        lam_theta=0.01,
        ridge_alpha=0.01,
        action_low=float(space[0]),
        action_high=float(space[1]),
    )
