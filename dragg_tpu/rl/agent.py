"""Host-facing RL agent classes with the reference's API surface.

``RLAgent`` mirrors the reference's abstract class (dragg/agent.py:42-123):
``train(env)``, ``get_policy_action(state)``, ``memorize``, rl_data
recording/writing, and reload from a previous run — but every numeric update
is delegated to the jitted functional core (:mod:`dragg_tpu.rl.core`), so the
host class is just bookkeeping around one device call per step.

``UtilityAgent`` is the concrete price-signal designer: the reference leaves
``calc_state``/``reward`` abstract (dragg/agent.py:67-69,113-123) and ships no
subclass; the concrete state (forecast error/trend, time-of-day, action delta
— exactly the keys its bases consume, dragg/agent.py:89-107) and the
negative-quadratic tracking reward ("encourages the agent to move towards a
state with curr_error = 0 … negative reward values", dragg/agent.py:114-118)
are therefore our minimal faithful concretization.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from dragg_tpu.rl.core import (
    AgentCarry,
    AgentParams,
    RLObservation,
    StepRecord,
    init_carry,
    params_from_config,
    train_step,
)

RL_DATA_KEYS = (
    "theta_q", "theta_mu", "q_obs", "q_pred", "action",
    "average_reward", "cumulative_reward", "reward", "mu",
)


def new_rl_data(beta: float, batch_size: int, sigma: float,
                extra_params: dict) -> dict:
    """Fresh rl_data telemetry dict (dragg/agent.py:247-256 schema) — the
    ONE constructor shared by the single-community agents here and the
    fleet agent (dragg_tpu/rl/fleet), so the JSON schema cannot fork."""
    data: dict = {k: [] for k in RL_DATA_KEYS}
    data["parameters"] = {
        "beta": beta, "batch_size": batch_size, "sigma": sigma,
        **extra_params,
    }
    return data


class RLAgent:
    """Linear actor-critic price-signal agent (dragg/agent.py:42).

    Subclasses provide ``calc_state(env) -> RLObservation-fields dict`` and
    ``reward(env) -> float``; ``train(env)`` runs one jitted core step.
    """

    name = "agent"

    def __init__(self, config: dict, seed: int | None = None):
        self.config = config
        if seed is None:
            seed = int(config["simulation"]["random_seed"])
        # Core selection: the reference's linear-basis actor-critic (default,
        # dragg/agent.py:42-232) or the Flax DDPG twin-Q upgrade
        # (BASELINE.md row 4) — same step contract, swappable per config.
        self.kind = str(config["rl"]["parameters"].get("agent", "linear"))
        if self.kind == "ddpg":
            from dragg_tpu.rl import neural

            self.params = neural.params_from_config(config)
            self.carry = neural.init_carry(self.params, seed)
            self.step_core = neural.train_step
            extra_params = {"agent": "ddpg", "tau": self.params.tau,
                            "actor_lr": self.params.actor_lr,
                            "critic_lr": self.params.critic_lr}
        elif self.kind == "linear":
            self.params: AgentParams = params_from_config(config)
            self.carry: AgentCarry = init_carry(self.params, seed)
            self.step_core = train_step
            extra_params = {
                "agent": "linear",
                "alpha_q": self.params.alpha_q,
                "alpha_mu": self.params.alpha_mu,
                "alpha_r": self.params.alpha_r,
                "twin_q": self.params.n_q == 2,
            }
        else:
            raise ValueError(
                f"Unknown rl.parameters.agent {self.kind!r} (linear | ddpg)"
            )
        self._step = jax.jit(lambda c, o: self.step_core(c, o, self.params))
        self.rl_data: dict = new_rl_data(
            self.params.beta, self.params.batch_size, self.params.sigma,
            extra_params)

    def scan_step(self, carry, obs):
        """The jittable (carry, obs) → (carry, record) hook the fused device
        scans trace (dragg_tpu/rl/runner.py)."""
        return self.step_core(carry, obs, self.params)

    # -- abstract surface (dragg/agent.py:67-69,113-123) --------------------
    def calc_state(self, env) -> dict:
        raise NotImplementedError

    def reward(self, env) -> float:
        raise NotImplementedError

    # ----------------------------------------------------------------- train
    def train(self, env) -> float:
        """One RL step (dragg/agent.py:130-149). Returns the next action."""
        s = self.calc_state(env)
        obs = RLObservation(
            fcst_error=jnp.float32(s["fcst_error"]),
            forecast_trend=jnp.float32(s["forecast_trend"]),
            time_of_day=jnp.float32(s["time_of_day"]),
            delta_action=jnp.float32(s["delta_action"]),
            reward=jnp.float32(self.reward(env)),
        )
        self.carry, rec = self._step(self.carry, obs)
        self.record_rl_data(rec)
        return float(self.carry.next_action)

    def get_policy_action(self, state: dict) -> float:
        """Sample a ~ N(μ(s), σ) without updating (dragg/agent.py:151-165)."""
        key, sub = jax.random.split(self.carry.key)
        self.carry = self.carry._replace(key=key)
        sv = jnp.asarray(
            [state["fcst_error"], state["forecast_trend"], state["time_of_day"], state["delta_action"]],
            dtype=jnp.float32,
        )
        if self.kind == "ddpg":
            from dragg_tpu.rl.neural import _mu

            mu = _mu(self.carry.actor, sv, self.params)
            a = mu + self.params.sigma * jax.random.normal(sub, (), jnp.float32)
        else:
            from dragg_tpu.rl.core import _policy_action

            a, _ = _policy_action(self.carry.theta_mu, sv, self.params.sigma, sub)
        return float(a)

    # ------------------------------------------------------------- telemetry
    def record_rl_data(self, rec: StepRecord) -> None:
        """Append one step of telemetry (dragg/agent.py:247-256)."""
        self.rl_data["theta_q"].append(np.asarray(rec.theta_q).tolist())
        self.rl_data["theta_mu"].append(np.asarray(rec.theta_mu).tolist())
        self.rl_data["q_obs"].append(float(rec.q_obs))
        self.rl_data["q_pred"].append(float(rec.q_pred))
        self.rl_data["action"].append(float(rec.action))
        self.rl_data["average_reward"].append(float(rec.average_reward))
        self.rl_data["cumulative_reward"].append(float(rec.cumulative_reward))
        self.rl_data["reward"].append(float(rec.reward))
        self.rl_data["mu"].append(float(rec.mu))

    def record_chunk(self, recs: StepRecord) -> None:
        """Append a stacked chunk of StepRecords (device-scan output)."""
        n = np.asarray(recs.q_obs).shape[0]
        tq = np.asarray(recs.theta_q)
        tm = np.asarray(recs.theta_mu)
        for k in range(n):
            self.rl_data["theta_q"].append(tq[k].tolist())
            self.rl_data["theta_mu"].append(tm[k].tolist())
            for name, field in (
                ("q_obs", recs.q_obs), ("q_pred", recs.q_pred), ("action", recs.action),
                ("average_reward", recs.average_reward),
                ("cumulative_reward", recs.cumulative_reward),
                ("reward", recs.reward), ("mu", recs.mu),
            ):
                self.rl_data[name].append(float(np.asarray(field)[k]))

    def write_rl_data(self, output_dir: str) -> None:
        """<output_dir>/<name>_agent-results.json (dragg/agent.py:270-273).
        Multi-host: rank-0 only, like every other output writer — the run
        directory tree is never created on non-zero processes."""
        if jax.process_index() != 0:
            return
        path = os.path.join(output_dir, f"{self.name}_agent-results.json")
        with open(path, "w") as f:
            json.dump(self.rl_data, f, indent=4)

    def load_from_previous(self, file: str) -> None:
        """Warm-start θ from a previous agent-results file
        (dragg/agent.py:275-282).  Linear core only: the DDPG telemetry
        stores parameter norms, not weights — neural runs resume through
        the checkpoint system instead (aggregator.save_checkpoint)."""
        if self.kind == "ddpg":
            raise ValueError(
                "load_from_previous applies to the linear agent; resume a "
                "DDPG run from its checkpoint directory instead"
            )
        with open(file) as f:
            data = json.load(f)
        if data.get("theta_mu"):
            theta_mu = jnp.asarray(data["theta_mu"][-1], dtype=jnp.float32)
            self.carry = self.carry._replace(theta_mu=theta_mu)
        if data.get("theta_q"):
            col = jnp.asarray(data["theta_q"][-1], dtype=jnp.float32)
            tq = jnp.stack([col] * self.params.n_q, axis=1)
            self.carry = self.carry._replace(theta_q=tq)


class UtilityAgent(RLAgent):
    """Concrete community price-signal designer (see module docstring).

    ``env`` duck-type: ``agg_load``, ``forecast_load``, ``prev_forecast_load``,
    ``agg_setpoint``, ``timestep``, ``dt``, ``norm`` (max possible community
    load, for scale-free features), ``prev_action``, ``action``.

    State and reward are the single shared definition in
    :func:`dragg_tpu.rl.env.observe` — the same function the fused device
    scans trace — so the host API and the on-device RL loop cannot diverge.
    """

    name = "utility"

    def _observe(self, env):
        from dragg_tpu.rl.env import EnvCarry, observe

        ec = EnvCarry(
            agg_load=jnp.float32(env.agg_load),
            forecast_load=jnp.float32(env.forecast_load),
            prev_forecast_load=jnp.float32(env.prev_forecast_load),
            setpoint=jnp.float32(env.agg_setpoint),
            prev_action=jnp.float32(env.prev_action),
            action=jnp.float32(env.action),
            tracker=None,  # not consumed by observe()
        )
        return observe(ec, jnp.int32(env.timestep), env.dt, env.norm)

    def calc_state(self, env) -> dict:
        o = self._observe(env)
        return {
            "fcst_error": float(o.fcst_error),
            "forecast_trend": float(o.forecast_trend),
            "time_of_day": float(o.time_of_day),
            "delta_action": float(o.delta_action),
        }

    def reward(self, env) -> float:
        return float(self._observe(env).reward)
