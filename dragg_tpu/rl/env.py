"""Environment-side device functions for the RL loop.

* the utility setpoint tracker — ``gen_setpoint``'s trailing-average load
  (dragg/aggregator.py:677-696) as a pure scan-able update;
* the simplified linear community response — ``test_response``'s
  ``load ← load − c·rp·(setpoint − load)`` model (dragg/aggregator.py:898-911),
  the reference's cheap stand-in for the whole MPC fleet and our RL-loop test
  fixture (SURVEY.md §4);
* the observation builder shared by the host agent and the fused device scans.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from dragg_tpu.rl.core import RLObservation


class SetpointTracker(NamedTuple):
    """Device state of ``gen_setpoint`` (dragg/aggregator.py:677-696).

    Only the trailing-load window matters: the setpoint is its average.  The
    reference also tracks ``max_load``/``min_load`` instance attributes, but
    nothing ever consumes them — the host-side ``gen_setpoint`` keeps that
    bookkeeping for API parity; the device carry does not.
    """

    tracked: jnp.ndarray   # (prev_n,) trailing loads


def init_tracker(prev_n: int, max_poss_load: float) -> SetpointTracker:
    """timestep<2 initialization: tracked ← 0.5·max_possible_load
    (dragg/aggregator.py:683-686)."""
    return SetpointTracker(
        tracked=jnp.full((prev_n,), 0.5 * max_poss_load, dtype=jnp.float32),
    )


def tracker_step(tr: SetpointTracker, agg_load, timestep) -> tuple[SetpointTracker, jnp.ndarray]:
    """Update the trailing window with the latest community load and return
    (new_tracker, setpoint = avg(tracked)) (dragg/aggregator.py:687-696)."""
    fresh = timestep < 2
    rolled = jnp.concatenate([tr.tracked[1:], jnp.reshape(agg_load, (1,))])
    tracked = jnp.where(fresh, tr.tracked, rolled)
    sp = jnp.mean(tracked)
    return SetpointTracker(tracked), sp


class EnvCarry(NamedTuple):
    """Community-level measurements threaded through the RL scan — the
    aggregator attributes the agent's state reads (setup_rl_agg_run,
    dragg/aggregator.py:876-896)."""

    agg_load: jnp.ndarray
    forecast_load: jnp.ndarray
    prev_forecast_load: jnp.ndarray
    setpoint: jnp.ndarray
    prev_action: jnp.ndarray  # action applied two steps ago
    action: jnp.ndarray       # action applied last step
    tracker: SetpointTracker


def init_env_carry(n_homes: int, prev_n: int, max_poss_load: float) -> EnvCarry:
    """setup_rl_agg_run initial guesses: forecast = agg = 3 kW/home
    (dragg/aggregator.py:889-893)."""
    f32 = jnp.float32
    fl = jnp.asarray(3.0 * n_homes, f32)
    tr = init_tracker(prev_n, max_poss_load)
    sp = jnp.mean(tr.tracked)
    return EnvCarry(
        agg_load=fl, forecast_load=fl, prev_forecast_load=fl,
        setpoint=sp, prev_action=jnp.zeros((), f32), action=jnp.zeros((), f32),
        tracker=tr,
    )


def init_fleet_env_carry(n_homes: int, prev_n: int, max_poss_load) -> EnvCarry:
    """(C,)-batched :func:`init_env_carry` for the vectorized fleet RL
    loop (dragg_tpu/rl/fleet): every EnvCarry leaf gains a leading
    community axis.  ``n_homes`` is PER COMMUNITY; ``max_poss_load`` is
    the (C,) per-community max-possible-load vector (communities are
    distinct populations — fleet seeds — so their normalizers differ)."""
    import jax

    mpl = jnp.asarray(max_poss_load, jnp.float32)
    return jax.vmap(lambda m: init_env_carry(n_homes, prev_n, m))(mpl)


def observe(env: EnvCarry, t, dt: int, norm: float) -> RLObservation:
    """Build the agent observation + reward from community measurements
    (concretization of the abstract calc_state/reward — see
    dragg_tpu/rl/agent.py docstring)."""
    day = 24 * dt
    err = (env.agg_load - env.setpoint) / norm
    return RLObservation(
        fcst_error=(env.forecast_load - env.setpoint) / norm,
        forecast_trend=(env.forecast_load - env.prev_forecast_load) / norm,
        time_of_day=jnp.mod(t, day).astype(jnp.float32) / day,
        delta_action=env.action - env.prev_action,
        reward=-(err * err),
    )


def simplified_response(agg_load, rp, setpoint, response_rate):
    """One step of the linear community model (dragg/aggregator.py:903-909):
    ``load ← load − c·rp·(setpoint − load)``; cost = load·rp."""
    load = agg_load - response_rate * rp * (setpoint - agg_load)
    return load, load * rp
