"""Reinforcement-learning price-signal aggregator (reference L3, dragg/agent.py).

TPU-native re-design: the reference's linear actor-critic (polynomial/Fourier
state bases, Gaussian policy, twin-Q critic fit by batch Ridge regression over
a replay buffer, dragg/agent.py:42-232) becomes a pure-functional JAX core —
one jittable ``train_step`` whose replay buffer, ridge solve and policy update
all live on device — so the whole RL loop composes with the community engine
inside a single ``lax.scan``.  A Flax DDPG twin-Q core with the same step
contract lives in :mod:`dragg_tpu.rl.neural` (``[rl.parameters] agent =
"ddpg"``), and the fleet-scale vectorized trainer (C communities, shared
IMPALA-style policy — ROADMAP item 1, architecture.md §17) in
:mod:`dragg_tpu.rl.fleet` (imported lazily by the runner dispatch; not
re-exported here so baseline runs never pay the Flax import).
"""

from dragg_tpu.rl.agent import RLAgent, UtilityAgent
from dragg_tpu.rl.core import AgentParams, AgentCarry, RLObservation, init_carry, train_step

__all__ = [
    "RLAgent",
    "UtilityAgent",
    "AgentParams",
    "AgentCarry",
    "RLObservation",
    "init_carry",
    "train_step",
]
