"""RL run modes — ``run_rl_agg`` and ``run_rl_simplified``.

The reference documents three cases (README.md:54-56): the RBO-MPC baseline,
the RL price-signal aggregator driving the MPC community, and the RL agent
against the simplified linear community model; its snapshot wires only the
baseline (dragg/aggregator.py:960-970) while shipping the scaffolding for the
other two (setup_rl_agg_run :876-896, test_response :898-911, RL branches in
redis_set_current_values :671-675).  Here both RL cases are first-class — and
TPU-native: each timestep of {setpoint tracking → agent observation → policy
sample → critic/actor update → community response} is one fused jitted step,
scanned on device per checkpoint chunk.  The reference's per-step flow
(redis push reward_price → pool fan-out → Redis collect → gen_setpoint)
becomes a pure carry with zero host↔device round-trips inside a chunk.
"""

from __future__ import annotations

import json
import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dragg_tpu.rl.agent import UtilityAgent
from dragg_tpu.rl.env import (
    EnvCarry,
    init_env_carry,
    init_tracker,
    observe,
    simplified_response,
    tracker_step,
)


def _rl_settings(config: dict):
    rl_cfg = config["agg"].get("rl", {})
    return {
        "prev_n": int(rl_cfg.get("prev_timesteps", 12)),
        "max_rp": float(rl_cfg.get("max_rp", 0.02)),
        "action_horizon": int(rl_cfg.get("action_horizon", 1)),
    }


# --------------------------------------------------------------------------
# RL aggregator driving the MPC community (case "rl_agg")
# --------------------------------------------------------------------------

def _fused_step(engine, agent, dt, norm, max_rp, rp_len, carry, t, t0):
    """One fused RL + community-MPC timestep.

    Ordering parity with the reference's per-step flow: the agent trains on
    the measurements of the previous step (train → next_action,
    dragg/agent.py:130-149), the new reward price is announced to the fleet
    (redis_set_current_values, dragg/aggregator.py:664-675), the community
    solves, and the setpoint tracker advances (collect_data → gen_setpoint,
    dragg/aggregator.py:726-755).

    ``rp_len = action_horizon·dt`` is the announced-price window.  A
    single-hour announcement (action_horizon ≤ 1, i.e. rp_len ≤ dt)
    broadcasts across the whole MPC horizon — parity with the reference's
    length-1 Redis list broadcasting at dragg/mpc_calc.py:353.  Multi-hour
    windows price only the first ``rp_len`` horizon steps (zero beyond) — a
    well-defined generalization of a case the reference mis-shapes on.
    """
    (cstate, acarry, env), factor = carry
    obs = observe(env, t, dt, norm)
    acarry, rec = agent.scan_step(acarry, obs)
    aparams = agent.params
    action = jnp.clip(acarry.next_action, aparams.action_low, aparams.action_high)
    rp_scalar = jnp.clip(action, -max_rp, max_rp)
    H = engine.params.horizon
    if rp_len <= dt or rp_len >= H:
        rp_vec = jnp.full((H,), rp_scalar, dtype=jnp.float32)
    else:
        rp_vec = jnp.where(jnp.arange(H) < rp_len, rp_scalar, 0.0).astype(jnp.float32)
    # Factor-cache refresh on the chunk's first step and on the periodic
    # cadence — same policy as Engine._chunk.  The cache is chunk-local
    # (outside the checkpointed carry), like Engine._chunk's.
    K = max(1, engine.params.admm_refactor_every)
    refresh = (t == t0) | ((t % K) == 0)
    cstate, factor, outs = engine._step(cstate, t, rp_vec, refresh, factor)
    tracker, sp = tracker_step(env.tracker, outs.agg_load, t + 1)
    new_env = EnvCarry(
        agg_load=outs.agg_load,
        forecast_load=outs.forecast_load,
        prev_forecast_load=env.forecast_load,
        setpoint=sp,
        prev_action=env.action,
        action=rp_scalar,
        tracker=tracker,
    )
    return ((cstate, acarry, new_env), factor), (outs, rec, rp_scalar, env.setpoint)


def run_rl_agg(agg) -> None:
    """RL price-signal aggregator over the full MPC community.

    Fleet dispatch (ROADMAP item 1): ``fleet.communities > 1`` routes to
    the vectorized fleet trainer (dragg_tpu/rl/fleet) — C parallel
    rollouts under one compiled pattern set.  C = 1 keeps THIS
    single-community path byte-for-byte (the fleet-RL C=1 equivalence
    pin in tests/test_rl_fleet.py depends on it)."""
    if getattr(agg, "n_communities", 1) > 1:
        from dragg_tpu.rl.fleet import run_rl_agg_fleet

        return run_rl_agg_fleet(agg)
    config = agg.config
    agg.case = "rl_agg"
    if agg.all_homes is None:
        agg.get_homes()
    if agg.engine is None:
        agg._build_engine()
    agg.reset_collected_data()
    agg.all_rps = np.zeros(agg.num_timesteps)
    agg.all_sps = np.zeros(agg.num_timesteps)

    settings = _rl_settings(config)
    norm = agg._max_possible_load()
    agent = UtilityAgent(config)
    acarry = agent.carry
    env = init_env_carry(len(agg.all_homes), settings["prev_n"], norm)
    cstate = agg.engine.init_state()
    mesh = getattr(agg.engine, "mesh", None)
    if mesh is not None:
        # Sharded engine: the community state is sharded over "homes";
        # the agent/env carries (scalars and small windows) must be
        # explicitly REPLICATED on the same mesh, or jit rejects the
        # mixed single-device/mesh carry.
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        put_rep = lambda a: jax.device_put(jnp.asarray(a), rep)
        acarry = jax.tree_util.tree_map(put_rep, acarry)
        env = jax.tree_util.tree_map(put_rep, env)

    step = partial(
        _fused_step, agg.engine, agent, agg.engine.params.dt, norm,
        settings["max_rp"], settings["action_horizon"] * agg.engine.params.dt,
    )

    @jax.jit
    def chunk(consts, carry, ts):  # dragg: disable=DT013, carry is host-snapshotted for the checkpoint AFTER dispatch and re-used by try_resume templates; donation pending a measured A/B (round-12 CPU caveat: donated dispatch runs synchronously)
        # The factor cache enters/leaves here so the checkpointed carry
        # (and try_resume's template) never includes it.  Engine constants
        # arrive as arguments via the same _bound mechanism as
        # Engine._chunk_entry (multi-host: no closing over global arrays).
        with agg.engine._bound(consts):
            (carry, _), stacked = lax.scan(
                lambda c, t: step(c, t, ts[0]), (carry, agg.engine.init_factor()), ts
            )
        return carry, stacked

    agg.checkpoint_interval = agg._checkpoint_steps()
    if agg.run_dir is None:
        agg.set_run_dir()
    agg.log.logger.info(
        f"Performing RL AGG run for horizon: {config['home']['hems']['prediction_horizon']}"
    )
    agg.start_time = time.time()  # dragg: disable=DT014, wall-clock elapsed accounting for progress telemetry
    case_dir = os.path.join(agg.run_dir, agg.case)
    carry, t = agg.try_resume((cstate, acarry, env))
    if agg.resumed_from is not None:
        # Restore the agent's telemetry saved inside the same atomic
        # checkpoint directory.
        rl_file = os.path.join(agg.resumed_from, "rl_data.json")
        if os.path.isfile(rl_file):
            with open(rl_file) as f:
                agent.rl_data = json.load(f)
    chunks = 0
    while t < agg.num_timesteps:
        n_steps = min(agg.checkpoint_interval, agg.num_timesteps - t)
        carry, (outs, recs, rps, sps) = chunk(agg.engine._consts(), carry,
                                              jnp.arange(t, t + n_steps))
        agg._collect_chunk(outs, track_setpoints=False)
        agent.record_chunk(recs)
        agg.all_rps[t:t + n_steps] = np.asarray(rps)
        agg.all_sps[t:t + n_steps] = np.asarray(sps)
        t += n_steps
        chunks += 1
        if t < agg.num_timesteps:
            agg.write_outputs()
            agg.save_checkpoint(carry, extra_json={"rl_data.json": agent.rl_data})
            if agg.stop_after_chunks is not None and chunks >= agg.stop_after_chunks:
                agg.log.logger.info(f"Stopping early after {chunks} chunks.")
                agg._state, agent.carry, _ = carry
                agg.agent = agent
                return
    agg._state, agent.carry, _ = carry
    agg.check_baseline_vals()
    agg.write_outputs()
    agent.write_rl_data(case_dir)
    agg.clear_checkpoint()
    agg.agent = agent


# --------------------------------------------------------------------------
# RL agent vs the simplified linear community model (case "simplified")
# --------------------------------------------------------------------------

def run_rl_simplified(agg) -> None:
    """RL agent against ``test_response``'s linear model — the whole loop
    (agent + environment) is one device scan; no MPC fleet is built.
    ``fleet.communities > 1`` routes to the vectorized fleet trainer
    (dragg_tpu/rl/fleet), same dispatch contract as :func:`run_rl_agg`."""
    if getattr(agg, "n_communities", 1) > 1:
        from dragg_tpu.rl.fleet import run_rl_simplified_fleet

        return run_rl_simplified_fleet(agg)
    config = agg.config
    agg.case = "simplified"
    settings = _rl_settings(config)
    simp = config["agg"].get("simplified", {})
    c_rate = float(simp.get("response_rate", 0.3))
    n_homes = int(config["community"]["total_number_homes"])
    house_p_avg = float(config["community"].get("house_p_avg", 1.2))
    # No MPC fleet: normalize by the community's average-power proxy
    # (set_dummy_rl_parameters, dragg/aggregator.py:872-874).
    norm = max(1.0, house_p_avg * n_homes * 2.5)
    dt = agg.dt

    agent = UtilityAgent(config)
    aparams = agent.params
    max_rp = settings["max_rp"]

    tr = init_tracker(settings["prev_n"], house_p_avg * n_homes * 2.5)
    sp0 = float(np.mean(np.asarray(tr.tracked)))
    # t=0 community load: setpoint + 10% (test_response, dragg/aggregator.py:904-905).
    f32 = jnp.float32
    env0 = EnvCarry(
        agg_load=jnp.asarray(1.1 * sp0, f32),
        forecast_load=jnp.asarray(1.1 * sp0, f32),
        prev_forecast_load=jnp.asarray(1.1 * sp0, f32),
        setpoint=jnp.asarray(sp0, f32),
        prev_action=jnp.zeros((), f32),
        action=jnp.zeros((), f32),
        tracker=tr,
    )

    def step(carry, t):
        acarry, env = carry
        obs = observe(env, t, dt, norm)
        acarry, rec = agent.scan_step(acarry, obs)
        action = jnp.clip(acarry.next_action, aparams.action_low, aparams.action_high)
        rp = jnp.clip(action, -max_rp, max_rp)
        load, cost = simplified_response(env.agg_load, rp, env.setpoint, c_rate)
        tracker, sp = tracker_step(env.tracker, load, t + 1)
        new_env = EnvCarry(
            agg_load=load, forecast_load=load, prev_forecast_load=env.agg_load,
            setpoint=sp, prev_action=env.action, action=rp, tracker=tracker,
        )
        return (acarry, new_env), (rec, load, cost, rp, env.setpoint)

    @jax.jit
    def run(carry, ts):  # dragg: disable=DT013, simplified-response carry is tiny (agent params + env scalars) and re-read for logging; donation buys nothing here
        return lax.scan(step, carry, ts)

    agg.log.logger.info("Performing RL simplified-response run")
    agg.start_time = time.time()  # dragg: disable=DT014, wall-clock elapsed accounting for progress telemetry
    (acarry, env), (recs, loads, costs, rps, sps) = run(
        (agent.carry, env0), jnp.arange(agg.num_timesteps)
    )
    agent.carry = acarry
    agent.record_chunk(recs)

    # Reuse the aggregator's Summary builder + results writer
    # (summarize_baseline/write_outputs, aggregator.py) — no per-home blocks
    # exist in this case, only the Summary.
    agg._solve_iters = []
    agg.baseline_agg_load_list = np.asarray(loads).tolist()
    agg.all_rps = np.asarray(rps, dtype=np.float64)
    agg.all_sps = np.asarray(sps, dtype=np.float64)
    agg.extra_summary = {"agg_cost": np.asarray(costs).tolist()}
    agg.summary_only_case = True
    if agg.run_dir is None:
        agg.set_run_dir()
    agg.write_outputs()
    agg.extra_summary = {}
    agg.summary_only_case = False
    case_dir = os.path.join(agg.run_dir, agg.case)
    agent.write_rl_data(case_dir)
    agg.agent = agent
