"""Weather / price timeseries ingestion.

Capability parity with the reference's data layer:

* NSRDB weather csv ingestion with subhourly resampling
  (dragg/aggregator.py:129-165) — same file format, same int cast of GHI/OAT,
  same repeat-rows-to-dt-grid scheme.
* TOU price construction (dragg/aggregator.py:206-216).  The reference
  assigns the peak price and then *overwrites* it with the shoulder
  assignment, so the peak price never takes effect; we reproduce that
  effective behavior by default and fix it behind ``fix_tou_peak=True``.
* Synthetic data generators so the framework runs standalone without the
  NREL/NEEA data files (the reference ships them; we do not copy data).

All series are produced at the aggregator's ``dt`` steps-per-hour resolution
covering the full weather span, ready to be placed on device once and sliced
per-timestep with ``lax.dynamic_slice`` inside the jitted step.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from datetime import datetime

import numpy as np
import pandas as pd

log = logging.getLogger("dragg_tpu.data")


def parse_dt(s: str) -> datetime:
    """Parse the reference's '%Y-%m-%d %H' datetime format (dragg/aggregator.py:118)."""
    return datetime.strptime(s, "%Y-%m-%d %H")


@dataclass
class EnvironmentData:
    """Full-span environmental series at dt steps/hour resolution.

    Attributes
    ----------
    oat, ghi, tou : np.ndarray  (n_steps,)
        Outdoor air temp (degC), global horizontal irradiance (W/m2), and
        time-of-use price ($/kWh) over the whole data span.
    data_start : datetime
        Timestamp of index 0.
    dt : int
        Steps per hour.
    """

    oat: np.ndarray
    ghi: np.ndarray
    tou: np.ndarray
    data_start: datetime
    dt: int

    @property
    def n_steps(self) -> int:
        return len(self.oat)

    def start_index(self, start_dt: datetime) -> int:
        """Step index of ``start_dt`` in the series.

        The reference computed this in *hours* (dragg/aggregator.py:630-638)
        and used it as a list index at dt resolution — correct only for
        ``dt == 1``.  We index in steps, which coincides at dt=1.
        """
        hours = (start_dt - self.data_start).total_seconds() / 3600
        return int(round(hours * self.dt))

    def check_coverage(self, start_dt: datetime, end_dt: datetime, horizon_hours: int) -> None:
        """Simulation window + prediction horizon must lie inside the data
        (parity with dragg/aggregator.py:617-628)."""
        s = self.start_index(start_dt)
        if s < 0:
            raise ValueError("The start datetime must exist in the data provided.")
        e = self.start_index(end_dt) + horizon_hours * self.dt
        if e + 1 > self.n_steps:
            raise ValueError("The end datetime + the prediction horizon must exist in the data provided.")


def load_nsrdb(path: str, dt: int) -> tuple[np.ndarray, np.ndarray, datetime]:
    """Ingest an NSRDB csv (two metadata rows, then Year/Month/Day/Hour/Minute/
    GHI/Temperature columns) and resample to ``dt`` steps/hour.

    Mirrors dragg/aggregator.py:129-157: each source row (at 60/k-minute
    cadence, typically half-hourly) is repeated ceil(dt/2) times if Minute==0
    else floor(dt/2), yielding exactly dt rows per hour, and GHI/OAT are cast
    to int.
    """
    df = pd.read_csv(path, skiprows=2)
    reps = [int(np.ceil(dt / 2)) if v == 0 else int(np.floor(dt / 2)) for v in df.Minute]
    df = df.loc[np.repeat(df.index.values, reps)]
    df = df.rename(columns={"Temperature": "OAT"})
    oat = df["OAT"].to_numpy().astype(int).astype(np.float64)
    ghi = df["GHI"].to_numpy().astype(int).astype(np.float64)
    first = df.iloc[0]
    data_start = datetime(int(first.Year), int(first.Month), int(first.Day), int(first.Hour), 0)
    return oat, ghi, data_start


def build_tou(
    n_steps: int,
    data_start: datetime,
    dt: int,
    base_price: float,
    tou_enabled: bool = True,
    shoulder_times: tuple[int, int] = (9, 21),
    shoulder_price: float = 0.09,
    peak_times: tuple[int, int] = (14, 18),
    peak_price: float = 0.13,
    fix_tou_peak: bool = False,
) -> np.ndarray:
    """Construct the TOU price series over the full span.

    Reference behavior (dragg/aggregator.py:206-216): price = shoulder_price
    for hours in [shoulder_times), else base_price — the peak assignment is
    dead code because the subsequent shoulder assignment overwrites the whole
    column.  Set ``fix_tou_peak=True`` for the presumably-intended tiering
    (peak within shoulder window).
    """
    hours = (np.arange(n_steps) // dt + data_start.hour) % 24
    tou = np.full(n_steps, float(base_price))
    if tou_enabled:
        if fix_tou_peak:
            sh = (hours >= shoulder_times[0]) & (hours < shoulder_times[1])
            pk = (hours >= peak_times[0]) & (hours < peak_times[1])
            tou[sh] = float(shoulder_price)
            tou[pk] = float(peak_price)
        else:
            sh = (hours >= shoulder_times[0]) & (hours < shoulder_times[1])
            tou[sh] = float(shoulder_price)
    return tou


def load_spp(path: str, load_zone: str, dt: int) -> tuple[np.ndarray, datetime]:
    """Ingest ERCOT DAM Settlement Point Prices (dragg/aggregator.py:167-204,
    whose implementation is dead code on modern pandas — SURVEY.md §5.6; this
    is the working equivalent).

    Accepts the ERCOT workbook layout as .xlsx (all sheets concatenated —
    needs an Excel engine like openpyxl) or a .csv with the same columns:
    Delivery Date / Hour Ending / Settlement Point / Settlement Point Price.
    Filters to ``load_zone``, converts $/MWh → $/kWh, shifts Hour Ending to
    hour-beginning, and repeats hourly prices onto the dt-step grid.

    Returns (prices at dt steps/hour, timestamp of index 0).
    """
    if path.endswith(".csv"):
        df = pd.read_csv(path)
    else:
        try:
            sheets = pd.read_excel(path, sheet_name=None)
        except ImportError as e:
            raise RuntimeError(
                "Reading ERCOT .xlsx needs an Excel engine (openpyxl); "
                "convert the workbook to .csv with the same columns instead"
            ) from e
        df = pd.concat(sheets.values(), ignore_index=True)
    df = df[df["Settlement Point"] == load_zone].copy()
    if df.empty:
        raise ValueError(f"No SPP rows for load zone {load_zone!r} in {path}")
    # "Hour Ending" is 1..24 (or "01:00".."24:00"); shift to hour-beginning
    # 0..23 (dragg/aggregator.py:194-196).
    he = df["Hour Ending"].astype(str).str.replace(":00", "", regex=False)
    hour = pd.to_numeric(he) - 1
    ts = pd.to_datetime(df["Delivery Date"]) + pd.to_timedelta(hour, unit="h")
    spp = df["Settlement Point Price"].astype(float) / 1000.0  # $/MWh → $/kWh
    out = pd.Series(spp.to_numpy(), index=ts).sort_index()
    out = out[~out.index.duplicated(keep="first")]  # repeated-hour (DST) rows
    # Fill interior gaps forward onto a contiguous hourly grid.
    full = pd.date_range(out.index[0], out.index[-1], freq="h")
    out = out.reindex(full).ffill()
    prices = np.repeat(out.to_numpy(), dt)
    return prices, out.index[0].to_pydatetime()


def synth_spp(start: datetime, days: int, dt: int, seed: int = 0) -> np.ndarray:
    """Synthetic day-ahead price series ($/kWh) with a morning/evening
    double peak, for standalone runs without ERCOT data."""
    rng = np.random.RandomState(seed ^ 0x599)
    n = days * 24 * dt
    hod = (np.arange(n) / dt + start.hour) % 24.0
    base = 0.03 + 0.02 * np.exp(-0.5 * ((hod - 8) / 2.0) ** 2) \
        + 0.035 * np.exp(-0.5 * ((hod - 18) / 2.5) ** 2)
    noise = np.abs(rng.randn(n)) * 0.004
    return base + noise


def _align_price_series(prices: np.ndarray, price_start: datetime,
                        data_start: datetime, n_steps: int, dt: int,
                        base_price: float) -> np.ndarray:
    """Align an independently-indexed price series onto the weather grid
    (the reference's outer-merge + ffill, dragg/aggregator.py:219-230), with
    out-of-span steps falling back to edge values / base price."""
    if len(prices) == 0:
        return np.full(n_steps, float(base_price))
    offset = int(round((data_start - price_start).total_seconds() / 3600 * dt))
    idx = np.clip(np.arange(n_steps) + offset, 0, len(prices) - 1)
    return np.asarray(prices, dtype=np.float64)[idx]


def bundled_data_dir() -> str | None:
    """The repo's first-party `data/` directory (round 5 — the reference
    ships data files, dragg/data/, so its DEFAULT run reads files; ours
    now does too).  Returns None when the bundled weather file is absent
    (e.g. an installed package without the repo checkout), in which case
    callers fall back to the synthetic generators as before.

    Assets are generated — never copied — by tools/make_data_assets.py.
    """
    d = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "data")
    if os.path.exists(os.path.join(d, "nsrdb.csv")):
        return d
    return None


def load_environment(config: dict, data_dir: str | None = None) -> EnvironmentData:
    """Build the EnvironmentData from config: NSRDB file if present, else
    synthetic weather covering the simulation year.  With ``spp_enabled``
    the price series comes from ERCOT SPP data (or its synthesizer) instead
    of the TOU schedule (dragg/aggregator.py:219-224).

    ``data_dir=None`` resolves to the repo's bundled `data/` assets when
    present (reference-default behavior: out-of-box runs ingest files,
    dragg/aggregator.py:129-165); synthetic series remain the explicit
    fallback (``data_dir=""`` forces them)."""
    dt = int(config["agg"]["subhourly_steps"])
    seed = int(config["simulation"]["random_seed"])
    if data_dir is None:
        data_dir = bundled_data_dir()
    elif data_dir == "":
        data_dir = None
    ts_file = None
    if data_dir is not None:
        ts_file = os.path.join(data_dir, os.environ.get("SOLAR_TEMPERATURE_DATA_FILE", "nsrdb.csv"))
    if ts_file is not None and os.path.exists(ts_file):
        oat, ghi, data_start = load_nsrdb(ts_file, dt)
    else:
        if ts_file is not None:
            # A data dir was configured but the weather file is absent: a
            # mistyped DATA_DIR would otherwise produce a plausible-looking
            # simulation of synthetic weather with no clue but the absence
            # of an error (round-1 verdict, weak #7) — say so loudly.
            log.warning(
                "Weather file %s not found — substituting SYNTHETIC weather. "
                'Set data_dir="" to silence this (explicit synthetic), or '
                "point DATA_DIR at the directory holding nsrdb.csv.", ts_file,
            )
        start = parse_dt(config["simulation"]["start_datetime"])
        year_start = datetime(start.year, 1, 1)
        oat, ghi, data_start = synth_weather(year_start, days=366, dt=dt, seed=seed)

    if bool(config["agg"].get("spp_enabled", False)):
        spp_file = None
        if data_dir is not None:
            spp_file = os.path.join(data_dir, os.environ.get("SPP_DATA_FILE", "spp_data.csv"))
        if spp_file is not None and os.path.exists(spp_file):
            prices, price_start = load_spp(
                spp_file, config["simulation"].get("load_zone", "LZ_HOUSTON"), dt
            )
        else:
            if spp_file is not None:
                log.warning(
                    "SPP price file %s not found — substituting SYNTHETIC "
                    "day-ahead prices.", spp_file,
                )
            prices = synth_spp(data_start, days=len(oat) // (24 * dt) + 1, dt=dt, seed=seed)
            price_start = data_start
        tou = _align_price_series(
            prices, price_start, data_start, len(oat), dt,
            base_price=float(config["agg"]["base_price"]),
        )
    else:
        tou_cfg = config["agg"].get("tou", {})
        tou = build_tou(
            len(oat),
            data_start,
            dt,
            base_price=config["agg"]["base_price"],
            tou_enabled=bool(config["agg"].get("tou_enabled", False)),
            shoulder_times=tuple(tou_cfg.get("shoulder_times", (9, 21))),
            shoulder_price=float(tou_cfg.get("shoulder_price", 0.09)),
            peak_times=tuple(tou_cfg.get("peak_times", (14, 18))),
            peak_price=float(tou_cfg.get("peak_price", 0.13)),
            fix_tou_peak=bool(config.get("tpu", {}).get("fix_tou_peak", False)),
        )
    return EnvironmentData(oat=oat, ghi=ghi, tou=tou, data_start=data_start, dt=dt)


def synth_weather(
    start: datetime, days: int, dt: int, seed: int = 0
) -> tuple[np.ndarray, np.ndarray, datetime]:
    """Generate synthetic weather at dt steps/hour: seasonal + diurnal OAT and
    a clear-sky-like GHI, with the same int quantization the NSRDB ingest
    applies.  Deterministic given ``seed``."""
    rng = np.random.RandomState(seed ^ 0x5EED)
    n = days * 24 * dt
    t_hours = np.arange(n) / dt
    doy = (t_hours / 24.0 + (start.timetuple().tm_yday - 1)) % 365.25
    hod = (t_hours + start.hour) % 24.0
    seasonal = 15.0 - 12.0 * np.cos(2 * np.pi * (doy - 15) / 365.25)
    diurnal = 6.0 * np.sin(2 * np.pi * (hod - 9) / 24.0)
    noise = rng.randn(n) * 1.5
    # Smooth the noise so consecutive steps are correlated like real weather.
    kernel = np.exp(-0.5 * (np.arange(-12, 13) / 4.0) ** 2)
    kernel /= kernel.sum()
    noise = np.convolve(noise, kernel, mode="same")
    oat = np.round(seasonal + diurnal + noise).astype(int).astype(np.float64)
    solar_elev = np.sin(np.pi * np.clip((hod - 6.0) / 12.0, 0.0, 1.0))
    season_scale = 0.65 + 0.35 * np.sin(2 * np.pi * (doy - 80) / 365.25)
    cloud = 1.0 - 0.3 * np.abs(np.sin(0.37 * t_hours + rng.rand() * 6.28))
    ghi = np.round(950.0 * solar_elev * season_scale * cloud).astype(int)
    ghi = np.clip(ghi, 0, None).astype(np.float64)
    return oat, ghi, start


def synth_waterdraw_profiles(
    n_profiles: int = 10, days: int = 7, seed: int = 0
) -> pd.DataFrame:
    """Generate minutely water-draw flow profiles in the reference file's
    layout (datetime index, one column per profile; see
    waterdraw_profiles.csv ingestion at dragg/aggregator.py:365-377).

    Draw events cluster at morning and evening hours, ~150-250 L/day total.
    """
    rng = np.random.RandomState(seed ^ 0xD3A3)
    n_min = days * 24 * 60
    idx = pd.date_range("2020-01-01", periods=n_min, freq="min")
    cols = {}
    minute_of_day = np.arange(n_min) % (24 * 60)
    density = (
        0.2
        + 1.2 * np.exp(-0.5 * ((minute_of_day - 7 * 60) / 60.0) ** 2)
        + 1.0 * np.exp(-0.5 * ((minute_of_day - 19 * 60) / 90.0) ** 2)
    )
    density /= density.sum() / (24 * 60)
    for p in range(n_profiles):
        flows = np.zeros(n_min)
        n_events = rng.poisson(8 * days)
        starts = rng.choice(n_min, size=n_events, p=density / density.sum())
        for s in starts:
            dur = rng.randint(1, 12)
            rate = rng.uniform(2.0, 8.0)
            flows[s : s + dur] += rate
        cols[f"Flow_{p:05d}"] = flows
    return pd.DataFrame(cols, index=idx)


def waterdraw_path(config: dict, data_dir: str | None) -> str | None:
    """Resolve the water-draw csv path from a data dir + the documented
    ``home.wh.waterdraw_file`` config key (reference semantics,
    dragg/data/config.toml) — THE one resolution, shared by the
    Aggregator, bench.py, and tools/validate_scale.py so a custom
    filename cannot be silently ignored by one of them (advisor
    finding, round 4).  ``data_dir=None`` resolves to the bundled
    assets like :func:`load_environment`; None return (→ synthetic
    draws) only when those are absent too (or ``data_dir=""``)."""
    if data_dir is None:
        data_dir = bundled_data_dir()
    elif data_dir == "":
        data_dir = None
    if data_dir is None:
        return None
    fname = config["home"]["wh"].get("waterdraw_file", "waterdraw_profiles.csv")
    return os.path.join(data_dir, fname)


def load_waterdraw_profiles(path: str | None, seed: int = 0) -> pd.DataFrame:
    """Load the minutely water-draw profile csv, or synthesize one."""
    if path is not None and os.path.exists(path):
        df = pd.read_csv(path, index_col=0)
        df.index = pd.to_datetime(df.index, format="%Y-%m-%d %H:%M:%S")
        return df
    if path is not None:
        log.warning(
            "Water-draw profile file %s not found — substituting SYNTHETIC "
            "draw profiles.", path,
        )
    return synth_waterdraw_profiles(seed=seed)
