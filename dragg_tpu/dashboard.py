"""Interactive results dashboard over finished runs.

The reference ships ``dragg/plotter.py`` — a Dash/plotly scaffold for the
README's "make into a Dash/plotly webapp" TODO (reference README.md:109) whose
body is an unrelated gapminder demo.  This module is the working equivalent:
a zero-dependency web dashboard (stdlib ``http.server`` + the matplotlib
figures :class:`dragg_tpu.reformat.Reformat` already builds) that discovers
runs the same way the analysis layer does and serves every comparison figure
as on-demand SVG, plus per-home drill-down like the reference's
``plot_single_home`` (dragg/reformat.py:257-296).

Routes:
  ``/``                     index: discovered runs, stats table, figure links
  ``/fig/<name>.svg``       any figure from :data:`FIGURES`
  ``/fig/single_home.svg?home=<name>``  per-home drill-down
  ``/live``                 tail of an IN-PROGRESS run's telemetry stream
                            (``events.jsonl`` — dragg_tpu/telemetry);
                            ``?run=<idx>`` selects among discovered streams
  ``/metrics.json``         the selected run's metrics snapshot: the final
                            ``metrics.json`` when the run finished, else a
                            partial snapshot folded live from the events

The figure routes only see FINISHED runs (they need results.json); the
live routes discover any run directory with an ``events.jsonl``, so an
in-progress simulation is observable the moment its first chunk lands.

Usage: ``python -m dragg_tpu dashboard [--port 8050]`` (the reference stub's
default Dash port), or :func:`serve` / :class:`Dashboard` programmatically.
"""

from __future__ import annotations

import glob
import html
import io
import json
import os
import threading
import urllib.parse
from datetime import datetime, timedelta
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from dragg_tpu import telemetry
from dragg_tpu.logger import Logger
from dragg_tpu.reformat import Reformat, daily_stats, stats_table

# name -> Reformat method building the figure (all take ax=None and return a
# matplotlib Figure or None when the needed series are absent).
FIGURES = (
    "baseline", "typ_day", "parametric", "rl2baseline",
    "max_and_12hravg", "all_rps", "single_home",
)


class Dashboard:
    """Render-side of the dashboard: HTML index + named SVG figures.

    Split from the HTTP handler so tests (and notebooks) can render without
    binding a socket.
    """

    def __init__(self, config=None, outputs_dir: str | None = None):
        self.log = Logger("dashboard")
        # pyplot's figure-manager state is process-global and not
        # thread-safe; ThreadingHTTPServer renders concurrently (a browser
        # fires one request per <img>), so figure building is serialized.
        self.render_lock = threading.Lock()
        self.ref = Reformat(config=config, outputs_dir=outputs_dir)
        if not self.ref.files:
            # Reformat's discovery permutes the CONFIG's parameter space into
            # directory names (reference parity, dragg/reformat.py:101-171) —
            # right for scripted comparisons, wrong for "show me whatever is
            # here".  Fall back to globbing the outputs tree.
            self.ref.files = self._glob_runs()

    def _glob_runs(self) -> list[dict]:
        files = []
        pattern = os.path.join(self.ref.outputs_dir, "**", "results.json")
        for path in sorted(glob.glob(pattern, recursive=True)):
            vdir = os.path.dirname(os.path.dirname(path))
            case = os.path.basename(os.path.dirname(path))
            run = os.path.basename(os.path.dirname(os.path.dirname(vdir)))
            try:
                parent = self._parent(path, vdir)
            except Exception as e:  # in-progress / corrupt run: skip, don't die
                self.log.logger.warning(f"skipping unreadable run {path}: {e!r}")
                continue
            entry = {
                "results": path,
                "name": f"{case}, {run}",
                "case": case,
                # Figures read path/agg_dt/ts/x_lims off the parent
                # (set_mpc_folders layout); reconstruct them from Summary.
                "parent": parent,
            }
            agent = os.path.join(os.path.dirname(path), "utility_agent-results.json")
            if os.path.isfile(agent):
                entry["q_results"] = agent
            files.append(entry)
            self.log.logger.info(f"glob fallback: adding {path}")
        return files

    def _parent(self, results_path: str, vdir: str) -> dict:
        s = self.ref._load(results_path)["Summary"]
        start = datetime.strptime(s["start_datetime"], "%Y-%m-%d %H")
        end = datetime.strptime(s["end_datetime"], "%Y-%m-%d %H")
        hours = (end - start).total_seconds() / 3600
        n = len(s.get("p_grid_aggregate", []))
        agg_dt = max(1, round(n / hours)) if hours else 1
        x_lims = [start + timedelta(minutes=(60 // agg_dt) * i) for i in range(n)]
        return {"path": vdir, "agg_dt": agg_dt, "ts": n, "x_lims": x_lims}

    # ------------------------------------------------------------- figures
    def render_figure(self, name: str, home: str | None = None) -> bytes | None:
        """SVG bytes for one named figure, or None for an unknown name /
        a figure with nothing to draw."""
        if name not in FIGURES:
            return None
        if name == "single_home":
            fig = self.ref.plot_single_home(name=home)
        elif name in ("rl2baseline", "all_rps"):
            fig = getattr(self.ref, name)()
        else:
            fig = getattr(self.ref, f"plot_{name}")()
        if fig is None:
            return None
        buf = io.BytesIO()
        fig.savefig(buf, format="svg", bbox_inches="tight")
        import matplotlib.pyplot as plt

        plt.close(fig)
        return buf.getvalue()

    def render_figure_locked(self, name: str, home: str | None = None) -> bytes | None:
        with self.render_lock:
            return self.render_figure(name, home=home)

    # ------------------------------------------------------------ live runs
    def live_runs(self) -> list[dict]:
        """Every run directory under the outputs tree with a telemetry
        stream (``events.jsonl``), newest first — in-progress runs
        included (they have no results.json yet, so figure discovery
        can't see them)."""
        runs = []
        pattern = os.path.join(self.ref.outputs_dir, "**",
                               telemetry.EVENTS_FILE)
        for path in glob.glob(pattern, recursive=True):
            # Per-shard sub-streams (shard<k>/events.jsonl) are merged
            # into their coordinator run's tail, not listed as runs of
            # their own.
            parent = os.path.basename(os.path.dirname(path))
            if parent.startswith("shard") and parent[len("shard"):].isdigit():
                continue
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            runs.append({
                "events": path,
                "dir": os.path.dirname(path),
                "mtime": mtime,
                "final": os.path.isfile(os.path.join(
                    os.path.dirname(path), telemetry.METRICS_FILE)),
            })
        runs.sort(key=lambda r: r["mtime"], reverse=True)
        return runs

    def _select_run(self, runs: list[dict], query: str) -> dict | None:
        """The ``?run=<idx>`` selection (index into :meth:`live_runs`'s
        newest-first order — never a raw client-supplied path)."""
        if not runs:
            return None
        try:
            idx = int(urllib.parse.parse_qs(query).get("run", ["0"])[0])
        except ValueError:
            return None
        return runs[idx] if 0 <= idx < len(runs) else None

    @staticmethod
    def tail_events(events_path: str, limit: int = 50,
                    tail_bytes: int = 262_144) -> list[dict]:
        """Last ``limit`` parseable event records of an events.jsonl —
        delegates to the shared bounded tailer (telemetry.tail_events),
        which the serving daemon's ``/events.jsonl`` endpoint uses too;
        serve run directories therefore show up in ``/live`` like any
        other stream.  A shard coordinator run (per-shard sub-streams
        under ``shard<k>/`` — dragg_tpu/shard/slots.py) is tailed
        MERGED: the shared multi-stream tailer interleaves every
        sub-stream by wall time and stamps each record's ``_stream``
        source."""
        if len(telemetry.stream_paths(events_path)) > 1:
            return telemetry.tail_events_dir(events_path, limit=limit,
                                             tail_bytes=tail_bytes)
        return telemetry.tail_events(events_path, limit=limit,
                                     tail_bytes=tail_bytes)

    def metrics_snapshot(self, run: dict) -> dict:
        """The run's metrics: the final ``metrics.json`` when the run
        wrote one, else a partial snapshot folded from the event stream
        (event counts + the latest record per type), so ``/metrics.json``
        answers for a run that is still mid-simulation."""
        if run["final"]:
            try:
                with open(os.path.join(run["dir"],
                                       telemetry.METRICS_FILE)) as f:
                    snap = json.load(f)
                return {"final": True, "run_dir": run["dir"], **snap}
            except (OSError, ValueError):
                pass  # fall through to the event fold
        events = self.tail_events(run["events"], limit=500)
        by_event: dict[str, int] = {}
        last: dict[str, dict] = {}
        for rec in events:
            name = rec.get("event", "?")
            by_event[name] = by_event.get(name, 0) + 1
            last[name] = rec
        return {"final": False, "run_dir": run["dir"],
                "tailed_events": len(events), "by_event": by_event,
                "last": last}

    # ------------------------------------------------- observatory panels
    @staticmethod
    def convergence_panel_html(events: list[dict]) -> str:
        """Convergence panel (round-9 observatory): the latest
        ``solver.convergence`` record per bucket rendered as a text-bar
        residual histogram, plus the latest worst-homes capture.
        Empty string when the stream carries no observatory events."""
        latest: dict[str, dict] = {}
        worst = None
        for rec in events:
            if rec.get("event") == "solver.convergence":
                latest[str(rec.get("bucket"))] = rec
            elif rec.get("event") == "solver.worst":
                worst = rec
        if not latest and worst is None:
            return ""
        parts = ["<h3>Solver convergence (latest chunk)</h3>"]
        for bucket, rec in latest.items():
            hist = rec.get("rprim_hist") or []
            peak = max(hist) if hist else 0
            bars = "".join(
                "▁▂▃▄▅▆▇█"[min(7, int(8 * v / peak))] if peak else "▁"
                for v in hist)
            parts.append(
                f"<div><code>{html.escape(bucket)}</code> "
                f"t={rec.get('t0')}..{rec.get('t1')} "
                f"r_prim <code>{html.escape(bars)}</code> "
                f"(bins 10⁻⁷…10¹ + diverged) "
                f"mean_iters={rec.get('mean_iters')} "
                f"diverged={rec.get('diverged')}</div>")
        if worst is not None and worst.get("homes"):
            rows = "".join(
                f"<tr><td>{h.get('home')}</td>"
                f"<td>{html.escape(str(h.get('bucket')))}</td>"
                f"<td>{h.get('t')}</td><td>{h.get('r_prim'):.3g}</td>"
                f"<td>{h.get('r_dual'):.3g}</td><td>{h.get('iters')}</td>"
                f"</tr>"
                for h in worst["homes"])
            parts.append(
                "<h4>Worst homes</h4><table border=1 cellpadding=3 "
                "style='border-collapse:collapse'><tr><th>home</th>"
                "<th>bucket</th><th>t</th><th>r_prim</th><th>r_dual</th>"
                f"<th>iters</th></tr>{rows}</table>")
        return "\n".join(parts)

    @staticmethod
    def compile_timeline_html(events: list[dict]) -> str:
        """Compile timeline: every ``compile.stage`` / ``compile.done``
        in the tail as one chronological table (stage, seconds, pattern
        shapes, cache verdict)."""
        rows = []
        for rec in events:
            if rec.get("event") == "compile.stage":
                rows.append((rec.get("mono"), rec.get("label"),
                             rec.get("stage"), rec.get("s"),
                             str(rec.get("buckets", ""))[:80], ""))
            elif rec.get("event") == "compile.done":
                rows.append((rec.get("mono"), rec.get("label"), "done",
                             rec.get("total_s"), "",
                             f"cache={rec.get('cache')}"))
        if not rows:
            return ""
        body = "".join(
            f"<tr><td>{m}</td><td>{html.escape(str(l))}</td>"
            f"<td>{html.escape(str(st))}</td><td>{s}</td>"
            f"<td><code>{html.escape(b)}</code></td>"
            f"<td>{html.escape(note)}</td></tr>"
            for m, l, st, s, b, note in rows)
        return ("<h3>Compile timeline</h3><table border=1 cellpadding=3 "
                "style='border-collapse:collapse'><tr><th>mono</th>"
                "<th>label</th><th>stage</th><th>s</th><th>pattern</th>"
                f"<th></th></tr>{body}</table>")

    def live_html(self, query: str = "") -> str:
        runs = self.live_runs()
        run = self._select_run(runs, query)
        run_list = "\n".join(
            f'<li><a href="/live?run={i}">{html.escape(r["dir"])}</a>'
            f'{" (finished)" if r["final"] else " (in progress)"}</li>'
            for i, r in enumerate(runs)
        )
        if run is None:
            body = "<p>(no telemetry streams found)</p>"
        else:
            snap = self.metrics_snapshot(run)
            # One tail read serves both: the observatory panels need a
            # deeper window (solver.convergence / compile.stage records
            # are sparser than chunk noise), the event table the last 50.
            panel_events = self.tail_events(run["events"], limit=400)
            events = panel_events[-50:]
            panels = (self.convergence_panel_html(panel_events)
                      + self.compile_timeline_html(panel_events))
            rows = "\n".join(
                "<tr><td>{}</td><td>{}</td><td><code>{}</code></td></tr>"
                .format(
                    html.escape(str(rec.get("mono", ""))),
                    html.escape(str(rec.get("event", ""))),
                    html.escape(json.dumps(
                        {k: v for k, v in rec.items()
                         if k not in ("event", "t", "mono", "pid", "seq")},
                        default=str)[:400]),
                )
                for rec in events
            )
            body = (
                f"<h2>{html.escape(run['dir'])}"
                f"{' — finished' if run['final'] else ' — in progress'}</h2>"
                f"<h3>Metrics</h3><pre>"
                f"{html.escape(json.dumps(snap, indent=1, default=str)[:8000])}"
                f"</pre>"
                f"{panels}"
                f"<h3>Last {len(events)} events</h3>"
                f"<table border=1 cellpadding=4 style='border-collapse:"
                f"collapse'><tr><th>mono</th><th>event</th><th>fields</th>"
                f"</tr>{rows}</table>"
            )
        return f"""<!doctype html><html><head><title>dragg_tpu live</title>
<meta http-equiv="refresh" content="5">
<style>body{{font-family:sans-serif;margin:2em;max-width:1100px}}
pre{{background:#f6f6f6;padding:1em;overflow-x:auto}}</style></head><body>
<h1>live telemetry</h1><p><a href="/">back to results</a> —
auto-refreshes every 5 s</p>
<h2>Streams</h2><ul>{run_list or "<li>(none)</li>"}</ul>
{body}
</body></html>"""

    # --------------------------------------------------------------- index
    def _home_names(self) -> list[str]:
        names: set[str] = set()
        for file in self.ref.files:
            data = self.ref._load(file["results"])
            names |= {n for n, h in data.items()
                      if n != "Summary" and isinstance(h, dict) and "type" in h}
        return sorted(names)

    def index_html(self) -> str:
        rows = []
        for file in self.ref.files:
            summary = self.ref._load(file["results"])["Summary"]
            loads = np.asarray(summary.get("p_grid_aggregate", []), dtype=float)
            steps_per_day = 24 * file["parent"].get("agg_dt", 1)
            if loads.size:
                rows.append((file["name"], daily_stats(loads, steps_per_day)))
        stats = stats_table(rows) if rows else "(no finished runs found)"

        figs = "\n".join(
            f'<h3>{name}</h3><img src="/fig/{name}.svg" style="max-width:100%">'
            for name in FIGURES if name != "single_home"
        )
        homes = "\n".join(
            f'<li><a href="/fig/single_home.svg?home={urllib.parse.quote(n)}">{html.escape(n)}</a></li>'
            for n in self._home_names()
        )
        run_list = "\n".join(
            f"<li><code>{html.escape(f['results'])}</code></li>" for f in self.ref.files
        )
        return f"""<!doctype html><html><head><title>dragg_tpu dashboard</title>
<style>body{{font-family:sans-serif;margin:2em;max-width:1100px}}
pre{{background:#f6f6f6;padding:1em;overflow-x:auto}}</style></head><body>
<h1>dragg_tpu dashboard</h1>
<p><a href="/live">live telemetry</a> (in-progress runs)</p>
<h2>Discovered runs</h2><ul>{run_list or "<li>(none)</li>"}</ul>
<h2>Daily statistics</h2><pre>{html.escape(stats)}</pre>
<h2>Figures</h2>{figs}
<h2>Per-home drill-down</h2><ul>{homes or "<li>(no per-home data)</li>"}</ul>
</body></html>"""


def make_handler(dash: Dashboard):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route to the framework logger
            dash.log.logger.info("http: " + fmt % args)

        def _send(self, code: int, ctype: str, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
            parsed = urllib.parse.urlparse(self.path)
            if parsed.path in ("", "/"):
                try:
                    body = dash.index_html().encode()
                except Exception as e:  # a bad run file must not kill the server
                    self._send(500, "text/plain", f"index failed: {e!r}".encode())
                    return
                self._send(200, "text/html; charset=utf-8", body)
                return
            if parsed.path == "/live":
                try:
                    body = dash.live_html(parsed.query).encode()
                except Exception as e:  # a torn stream must not kill the server
                    self._send(500, "text/plain", f"live failed: {e!r}".encode())
                    return
                self._send(200, "text/html; charset=utf-8", body)
                return
            if parsed.path == "/metrics.json":
                try:
                    runs = dash.live_runs()
                    run = dash._select_run(runs, parsed.query)
                    if run is None:
                        self._send(404, "application/json",
                                   b'{"error": "no telemetry stream"}')
                        return
                    body = json.dumps(dash.metrics_snapshot(run),
                                      default=str).encode()
                except Exception as e:
                    self._send(500, "text/plain",
                               f"metrics failed: {e!r}".encode())
                    return
                self._send(200, "application/json", body)
                return
            if parsed.path in ("/rollup.json", "/metrics"):
                # Fleet rollup (ISSUE 20 — telemetry/rollup.py): folded
                # per-stream metrics + per-shard health scoreboard, as
                # JSON or Prometheus text exposition.
                try:
                    runs = dash.live_runs()
                    run = dash._select_run(runs, parsed.query)
                    if run is None:
                        self._send(404, "application/json",
                                   b'{"error": "no telemetry stream"}')
                        return
                    roll = telemetry.rollup.fold_rollup(run["dir"])
                    if parsed.path == "/rollup.json":
                        body = json.dumps(roll, default=str).encode()
                        ctype = "application/json"
                    else:
                        body = telemetry.rollup.prometheus_text(
                            roll).encode()
                        ctype = "text/plain; version=0.0.4"
                except Exception as e:
                    self._send(500, "text/plain",
                               f"rollup failed: {e!r}".encode())
                    return
                self._send(200, ctype, body)
                return
            if parsed.path.startswith("/fig/") and parsed.path.endswith(".svg"):
                name = parsed.path[len("/fig/"):-len(".svg")]
                home = urllib.parse.parse_qs(parsed.query).get("home", [None])[0]
                try:
                    svg = dash.render_figure_locked(name, home=home)
                except Exception as e:
                    self._send(500, "text/plain", f"figure failed: {e!r}".encode())
                    return
                if svg is None:
                    self._send(404, "text/plain", b"no such figure")
                    return
                self._send(200, "image/svg+xml", svg)
                return
            self._send(404, "text/plain", b"not found")

    return Handler


def serve(config=None, outputs_dir: str | None = None, port: int = 8050,
          host: str = "127.0.0.1") -> None:
    """Blocking server loop (port default = the Dash default the reference
    stub would have used)."""
    dash = Dashboard(config=config, outputs_dir=outputs_dir)
    httpd = ThreadingHTTPServer((host, port), make_handler(dash))
    dash.log.logger.info(
        f"dashboard on http://{host}:{httpd.server_address[1]} "
        f"({len(dash.ref.files)} runs)"
    )
    try:
        # Explicit poll_interval keeps Ctrl-C/shutdown responsive on a
        # quiet socket (the serving daemon's DT006 discipline, applied
        # repo-wide now the lint scope covers the dashboard too).
        httpd.serve_forever(poll_interval=0.5)
    finally:
        httpd.server_close()
