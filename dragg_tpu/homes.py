"""Seeded home-population synthesis.

Capability parity with the reference's ``create_homes``
(dragg/aggregator.py:273-587): given parameter distributions and per-type
counts, produce the community as (a) a list of JSON-serializable home dicts
with the reference's exact schema (so cached ``all_homes-<N>-config.json``
files interoperate) and (b) a :class:`HomeBatch` struct-of-arrays padded to a
single superset shape so the whole community solves as one batched tensor
program (base homes get zero-width battery/PV blocks; SURVEY.md §7 step 2).

Seeding: the numpy parameter streams are drawn in the reference's exact order
(dragg/aggregator.py:281-359 then the per-type loops :393-578), so home
parameters are reproducible home-by-home for a given seed.  Home *names* use
an embedded name pool instead of the third-party ``names`` package, and the
water-draw profile sampling uses the same global-numpy-RNG calls in a
documented order (pandas' internal ``DataFrame.sample`` RNG consumption is
version-dependent and not reproducible bit-for-bit).
"""

from __future__ import annotations

import random
import string
from typing import Any, NamedTuple

import numpy as np
import pandas as pd

from dragg_tpu.config import configured_solver
from dragg_tpu.names_data import FIRST_NAMES

# Home types.  The first four are the reference's (dragg/aggregator.py
# per-type loops); "ev" and "heat_pump" are scenario types (ROADMAP item 4,
# docs/architecture.md §15 — no reference analog), APPENDED so the legacy
# type codes (and every artifact/checkpoint keyed on them) are unchanged.
# Materialization order in create_homes is pv_battery, pv_only,
# battery_only, ev, heat_pump, base — new-type parameter draws happen
# inside their own loops, so a zero-count config consumes no RNG and
# reproduces the pre-scenario population byte-for-byte.
HOME_TYPES = ("pv_battery", "pv_only", "battery_only", "base", "ev",
              "heat_pump")
TYPE_CODES = {t: i for i, t in enumerate(HOME_TYPES)}

# Scenario-type parameter distributions, used when a config predates the
# [home.ev] / [home.heat_pump] tables (an unmodified reference TOML must
# keep loading — config.REQUIRED_KEYS is NOT extended).
EV_PARAM_DEFAULTS: dict[str, list] = {
    "capacity": [40.0, 80.0],       # kWh usable pack
    "max_rate": [3.3, 9.6],         # kW home charger
    "charge_eff": [0.88, 0.95],
    "target_soc": [0.7, 0.9],       # fraction of capacity due at departure
    "init_soc": [0.3, 0.6],
    "away_start": [7.0, 9.0],       # hour of day the vehicle departs
    "away_duration": [7.0, 10.0],   # hours away (deadline window length)
    "trip_kwh": [6.0, 14.0],        # SOC consumed by the daily trip
}
HP_PARAM_DEFAULTS: dict[str, list] = {
    "cop_base": [2.4, 3.2],         # heating COP at 0 degC OAT
    "cop_slope": [0.04, 0.08],      # COP change per degC (ops/qp.hp_cops)
}


def _uniform(rng_cfg, n):
    return np.random.uniform(rng_cfg[0], rng_cfg[1], n)


def _make_name() -> str:
    first = random.choice(FIRST_NAMES)
    suffix = "".join(random.choices(string.ascii_uppercase + string.digits, k=5))
    return f"{first}-{suffix}"


def _battery_params(cfg: dict) -> dict:
    b = cfg["home"]["battery"]
    return {
        "max_rate": np.random.uniform(b["max_rate"][0], b["max_rate"][1]),
        "capacity": np.random.uniform(b["capacity"][0], b["capacity"][1]),
        "capacity_lower": np.random.uniform(b["lower_bound"][0], b["lower_bound"][1]),
        "capacity_upper": np.random.uniform(b["upper_bound"][0], b["upper_bound"][1]),
        "ch_eff": np.random.uniform(b["charge_eff"][0], b["charge_eff"][1]),
        "disch_eff": np.random.uniform(b["discharge_eff"][0], b["discharge_eff"][1]),
        "e_batt_init": np.random.uniform(b["lower_bound"][1], b["upper_bound"][0]),
    }


def _pv_params(cfg: dict) -> dict:
    p = cfg["home"]["pv"]
    return {
        "area": np.random.uniform(p["area"][0], p["area"][1]),
        "eff": np.random.uniform(p["efficiency"][0], p["efficiency"][1]),
    }


def _scenario_dist(tbl: dict, key: str, defaults: dict) -> float:
    lo, hi = tbl.get(key, defaults[key])
    return float(np.random.uniform(lo, hi))


def _ev_params(cfg: dict) -> dict:
    e = cfg["home"].get("ev", {})
    d = lambda k: _scenario_dist(e, k, EV_PARAM_DEFAULTS)
    cap = d("capacity")
    start = d("away_start")
    return {
        "capacity": cap,
        "max_rate": d("max_rate"),
        "charge_eff": d("charge_eff"),
        "target_soc": d("target_soc"),
        "init_soc": d("init_soc"),
        "away_start": start,
        "away_end": start + d("away_duration"),
        "trip_kwh": d("trip_kwh"),
    }


def _hp_params(cfg: dict) -> dict:
    h = cfg["home"].get("heat_pump", {})
    d = lambda k: _scenario_dist(h, k, HP_PARAM_DEFAULTS)
    return {"cop_base": d("cop_base"), "cop_slope": d("cop_slope")}


def create_homes(
    config: dict,
    num_timesteps: int,
    dt: int,
    waterdraw_df: pd.DataFrame,
) -> list[dict[str, Any]]:
    """Synthesize the home population.  Returns the reference-schema list of
    home dicts (order: pv_battery, pv_only, battery_only, base — parity with
    dragg/aggregator.py:393-578)."""
    seed = int(config["simulation"]["random_seed"])
    np.random.seed(seed)
    random.seed(seed)
    n = int(config["community"]["total_number_homes"])
    hvac = config["home"]["hvac"]
    wh = config["home"]["wh"]

    # HVAC parameter streams (order parity: dragg/aggregator.py:285-322).
    home_r = _uniform(hvac["r_dist"], n)
    home_c = _uniform(hvac["c_dist"], n)
    p_cool = _uniform(hvac["p_cool_dist"], n)
    p_heat = _uniform(hvac["p_heat_dist"], n)
    t_sp = _uniform(hvac["temp_sp_dist"], n)
    t_db = _uniform(hvac["temp_deadband_dist"], n)
    t_init_pos = np.random.uniform(0.25, 0.75, n)
    t_min = t_sp - 0.5 * t_db
    t_max = t_sp + 0.5 * t_db
    t_init = t_min + t_init_pos * t_db

    # Water-heater parameter streams (order parity: dragg/aggregator.py:325-359).
    wh_r = _uniform(wh["r_dist"], n)
    wh_p = _uniform(wh["p_dist"], n)
    wh_sp = _uniform(wh["sp_dist"], n)
    wh_db = _uniform(wh["deadband_dist"], n)
    wh_init_pos = np.random.uniform(0.25, 0.75, n)
    wh_min = wh_sp - 0.5 * wh_db
    wh_max = wh_sp + 0.5 * wh_db
    wh_init = wh_min + wh_init_pos * wh_db
    wh_size = _uniform(wh["size_dist"], n)

    # Water-draw events (dragg/aggregator.py:361-377): per-cell lognormal-ish
    # noise, hourly resample, then per home pick a random profile column and
    # ndays random days, clipped to tank size.
    ndays = num_timesteps // (24 * dt) + 1
    noisy = waterdraw_df.to_numpy() * (1 + 0.2 * np.random.randn(waterdraw_df.shape[1], waterdraw_df.shape[0]).T)
    hourly = (
        pd.DataFrame(noisy, index=waterdraw_df.index, columns=waterdraw_df.columns)
        .resample("h")
        .sum()
        .to_numpy()
    )
    n_hours_data, n_cols = hourly.shape
    n_days_data = n_hours_data // 24
    draw_sizes_all = []
    for j in range(n):
        col = int(np.random.choice(n_cols))
        this_house = hourly[: n_days_data * 24, col].reshape(-1, 24)
        days = np.random.choice(this_house.shape[0], ndays)
        this_house = this_house[days].flatten()
        draw_sizes_all.append(np.clip(this_house, 0, wh_size[j]).tolist())

    hems = {
        "horizon": config["home"]["hems"]["prediction_horizon"],
        "hourly_agg_steps": dt,
        "sub_subhourly_steps": config["home"]["hems"]["sub_subhourly_steps"],
        "solver": configured_solver(config),
        "discount_factor": config["home"]["hems"]["discount_factor"],
    }

    def _common(i):
        return {
            "hvac": {
                "r": home_r[i], "c": home_c[i], "p_c": p_cool[i], "p_h": p_heat[i],
                "temp_in_min": t_min[i], "temp_in_max": t_max[i],
                "temp_in_sp": t_sp[i], "temp_in_init": t_init[i],
            },
            "wh": {
                "r": wh_r[i], "p": wh_p[i],
                "temp_wh_min": wh_min[i], "temp_wh_max": wh_max[i],
                "temp_wh_sp": wh_sp[i], "temp_wh_init": wh_init[i],
                "tank_size": wh_size[i], "draw_sizes": draw_sizes_all[i],
            },
            "hems": hems,
        }

    comm = config["community"]
    n_pvb = int(comm.get("homes_pv_battery", 0))
    n_pv = int(comm.get("homes_pv", 0))
    n_b = int(comm.get("homes_battery", 0))
    n_ev = int(comm.get("homes_ev", 0))
    n_hp = int(comm.get("homes_heat_pump", 0))
    n_base = n - n_pvb - n_pv - n_b - n_ev - n_hp
    if n_base < 0:
        raise ValueError("Per-type home counts exceed total_number_homes")

    all_homes: list[dict[str, Any]] = []
    i = 0
    for _ in range(n_pvb):
        name = _make_name()
        battery = _battery_params(config)
        pv = _pv_params(config)
        all_homes.append({"name": name, "type": "pv_battery", **_common(i), "battery": battery, "pv": pv})
        i += 1
    for _ in range(n_pv):
        name = _make_name()
        pv = _pv_params(config)
        all_homes.append({"name": name, "type": "pv_only", **_common(i), "pv": pv})
        i += 1
    for _ in range(n_b):
        name = _make_name()
        battery = _battery_params(config)
        all_homes.append({"name": name, "type": "battery_only", **_common(i), "battery": battery})
        i += 1
    # Scenario types (ROADMAP item 4) draw their parameters inside their
    # own loops — zero counts consume no RNG, keeping legacy populations
    # byte-identical — and sit BEFORE base so the list stays grouped by
    # type (the bucketed engine's slicing invariant).
    for _ in range(n_ev):
        name = _make_name()
        ev = _ev_params(config)
        all_homes.append({"name": name, "type": "ev", **_common(i), "ev": ev})
        i += 1
    for _ in range(n_hp):
        name = _make_name()
        hp = _hp_params(config)
        all_homes.append({"name": name, "type": "heat_pump", **_common(i), "heat_pump": hp})
        i += 1
    for _ in range(n_base):
        name = _make_name()
        all_homes.append({"name": name, "type": "base", **_common(i)})
        i += 1
    return all_homes


def check_home_configs(all_homes: list[dict], config: dict) -> None:
    """Population check — counts of each home type must match config
    (parity with dragg/aggregator.py:232-253)."""
    counts = {t: sum(1 for h in all_homes if h["type"] == t) for t in HOME_TYPES}
    comm = config["community"]
    expect = {
        "pv_battery": int(comm.get("homes_pv_battery", 0)),
        "pv_only": int(comm.get("homes_pv", 0)),
        "battery_only": int(comm.get("homes_battery", 0)),
        "ev": int(comm.get("homes_ev", 0)),
        "heat_pump": int(comm.get("homes_heat_pump", 0)),
    }
    expect["base"] = int(comm["total_number_homes"]) - sum(expect.values())
    for t, c in expect.items():
        if counts[t] != c:
            raise ValueError(f"Incorrect number of {t} homes: {counts[t]} != {c}")


class FleetSpec(NamedTuple):
    """Static description of a multi-community fleet folded into one home
    batch (ROADMAP item 3 / architecture.md §14).

    The fleet batch is TYPE-MAJOR: all communities' homes of one type are
    contiguous, so the type-bucketed engine solves ``C·B_type`` homes per
    bucket under the SAME compiled pattern set as a single community
    (compile cost flat in C by construction).  The arrays below are per
    fleet-batch row (type-major order) and map each row back to its
    community identity:

    * ``community[i]``  — which community row ``i`` belongs to;
    * ``global_idx[i]`` — the row's COMMUNITY-MAJOR fleet index
      (``c * B + local``) — the index into the aggregator's flat
      ``all_homes`` list, and the order ``Engine.real_home_cols`` maps
      merged outputs back to;
    * ``local_idx[i]``  — the row's index within its own community's
      standalone batch.  The forecast-noise stream is keyed on
      ``(community seed, local_idx)`` so every home draws EXACTLY the
      noise it would draw in a standalone run of its community — fleet
      batching must not perturb per-community trajectories (parity:
      tests/test_fleet.py);
    * ``env_offset[i]`` — per-home offset (in sim steps) into the
      environment series, so communities can see time-shifted weather
      (``fleet.weather_offset_hours``); all-zero keeps the engine on the
      scalar shared-window path.
    """

    n_communities: int
    homes_per_community: int
    seeds: tuple               # per-community population seed
    community: np.ndarray      # (N,) int32
    global_idx: np.ndarray     # (N,) int32 community-major fleet index
    local_idx: np.ndarray      # (N,) int32 within-community index
    env_offset: np.ndarray     # (N,) int32 env-series offset (sim steps)


def fleet_config(config: dict) -> tuple[int, int, int]:
    """The resolved ``[fleet]`` knobs: (communities, seed_stride,
    weather_offset_hours).  ``communities = 1`` (the default) is the
    single-community engine unchanged."""
    f = config.get("fleet", {})
    c = int(f.get("communities", 1))
    if c < 1:
        raise ValueError(f"fleet.communities must be >= 1, got {c}")
    off = int(f.get("weather_offset_hours", 0))
    if off < 0:
        # A negative offset would UNDERSHOOT the startup coverage check
        # (horizon + (C-1)*off shrinks) while the traced gather clamps
        # its negative indices to 0 — silently wrong weather instead of
        # a loud error.
        raise ValueError(
            f"fleet.weather_offset_hours must be >= 0, got {off}")
    return (c, int(f.get("seed_stride", 1)), off)


def fleet_community_base(config: dict) -> int:
    """``fleet.community_base`` — the GLOBAL index of this engine's first
    community (cross-process sharding, architecture.md §19): a shard
    worker running communities ``[base, base + C)`` of a larger fleet
    sets it so every community keeps its global identity — population
    seed ``random_seed + (base + c) * seed_stride``, name prefix
    ``c<base+c>-``, weather offset ``(base + c) * weather_offset_hours``
    — and the shard's per-community outputs are bit-identical to the
    same communities inside the in-process fleet.  Default 0 (the whole
    fleet in one engine; every legacy path unchanged)."""
    base = int(config.get("fleet", {}).get("community_base", 0))
    if base < 0:
        raise ValueError(f"fleet.community_base must be >= 0, got {base}")
    return base


def create_fleet_homes(config: dict, num_timesteps: int, dt: int,
                       waterdraw_df: pd.DataFrame) -> list[dict[str, Any]]:
    """Synthesize C independent communities (``fleet.communities``), each
    drawn with its OWN seed (``random_seed + c * seed_stride``) so the
    fleet is C distinct populations, not C copies.  Returns the flat
    COMMUNITY-MAJOR list (community 0's homes, then community 1's, …);
    names are prefixed ``c<k>-`` so a 100k-home fleet cannot collide in
    the results.json / home_logs namespaces."""
    n_comm, stride, _off = fleet_config(config)
    base = fleet_community_base(config)
    if n_comm == 1 and base == 0:
        return create_homes(config, num_timesteps, dt, waterdraw_df)
    import copy as _copy

    base_seed = int(config["simulation"]["random_seed"])
    all_homes: list[dict[str, Any]] = []
    for c in range(n_comm):
        cfg_c = _copy.deepcopy(config)
        cfg_c["simulation"]["random_seed"] = base_seed + (base + c) * stride
        homes_c = create_homes(cfg_c, num_timesteps, dt, waterdraw_df)
        for h in homes_c:
            h["name"] = f"c{base + c}-{h['name']}"
        all_homes.extend(homes_c)
    return all_homes


def fleet_spec_for(all_homes: list[dict], config: dict) -> FleetSpec | None:
    """Derive the :class:`FleetSpec` from a community-major ``all_homes``
    list + config (``None`` when ``fleet.communities == 1``).  Works on
    freshly synthesized AND cache-reloaded home lists — everything is
    recomputed from the list structure, so a reloaded
    ``all_homes-<N>-config.json`` reconstructs the identical fleet.

    Raises when the list is not C equal blocks each grouped by type —
    the slicing the type-bucketed fleet engine depends on."""
    n_comm, stride, off_hours = fleet_config(config)
    base = fleet_community_base(config)
    if n_comm == 1 and base == 0:
        return None
    n_total = len(all_homes)
    if n_total % n_comm:
        raise ValueError(
            f"fleet of {n_comm} communities needs len(all_homes) divisible "
            f"by it, got {n_total}")
    B = n_total // n_comm
    dt = int(config["agg"]["subhourly_steps"])
    base_seed = int(config["simulation"]["random_seed"])
    codes = np.asarray([TYPE_CODES[h["type"]] for h in all_homes])
    # Per-community type runs must be identical across blocks (same config
    # synthesizes the same counts) and grouped (create_homes order).
    ranges0 = type_bucket_ranges(codes[:B])
    if ranges0 is None:
        raise ValueError("fleet communities must be grouped by home type "
                         "(the create_homes materialization order)")
    for c in range(1, n_comm):
        if type_bucket_ranges(codes[c * B:(c + 1) * B]) != ranges0:
            raise ValueError(
                f"fleet community {c} has a different type partition than "
                f"community 0 — all communities must share one config")
    # Type-major fleet order: for each type run, every community's slice.
    order = np.concatenate([
        np.arange(c * B + a, c * B + b)
        for (_t, a, b) in ranges0 for c in range(n_comm)])
    community = order // B
    local_idx = order % B
    # ``community`` stays SHARD-LOCAL (0-based — the index the engine's
    # fold/segment arrays use); the global identity rides the seeds, the
    # env offsets, and the c<global>- name prefixes.
    return FleetSpec(
        n_communities=n_comm,
        homes_per_community=B,
        seeds=tuple(base_seed + (base + c) * stride for c in range(n_comm)),
        community=community.astype(np.int32),
        global_idx=order.astype(np.int32),
        local_idx=local_idx.astype(np.int32),
        env_offset=((base + community) * off_hours * dt).astype(np.int32),
    )


def build_fleet_batch(all_homes: list[dict], config: dict, horizon: int,
                      dt: int, sub_steps: int):
    """(HomeBatch, FleetSpec | None) for a community-major ``all_homes``
    list: the batch rows are the TYPE-MAJOR fleet order (``spec.global_idx``
    maps them back), so ``type_bucket_ranges`` sees C·B_type contiguous
    homes per type and the bucketed engine compiles ONE pattern per type
    regardless of C.  With ``fleet.communities == 1`` this is exactly
    :func:`build_home_batch`."""
    spec = fleet_spec_for(all_homes, config)
    if spec is None:
        return build_home_batch(all_homes, horizon, dt, sub_steps), None
    ordered = [all_homes[i] for i in spec.global_idx]
    return build_home_batch(ordered, horizon, dt, sub_steps), spec


class HomeBatch(NamedTuple):
    """Struct-of-arrays community, padded to the superset (pv_battery) shape.

    All arrays have leading dim n_homes.  Physical parameters keep the
    reference's units and meanings (dragg/mpc_calc.py:157-191,233-262):
    ``hvac_c`` already includes the ×1000 scale, ``hvac_p_c``/``p_h``/``wh_p``
    are per-sub-subhourly-step powers (total / s), ``wh_r`` includes ×1000,
    ``wh_c = tank_size * 4.2`` kJ/degC.
    """

    type_code: np.ndarray      # int, index into HOME_TYPES
    has_pv: np.ndarray         # float 0/1
    has_batt: np.ndarray       # float 0/1
    hvac_r: np.ndarray
    hvac_c: np.ndarray         # c * 1000
    hvac_p_c: np.ndarray       # p_c / s
    hvac_p_h: np.ndarray       # p_h / s
    temp_in_min: np.ndarray
    temp_in_max: np.ndarray
    temp_in_init: np.ndarray
    wh_r: np.ndarray           # r * 1000
    wh_c: np.ndarray           # tank_size * 4.2
    wh_p: np.ndarray           # p / s
    temp_wh_min: np.ndarray
    temp_wh_max: np.ndarray
    temp_wh_init: np.ndarray
    tank_size: np.ndarray
    draws_hourly: np.ndarray   # (n_homes, pad + n_hours) with (H//dt + 1) leading zeros
    batt_max_rate: np.ndarray
    batt_cap_min: np.ndarray   # capacity_lower * capacity
    batt_cap_max: np.ndarray   # capacity_upper * capacity
    batt_ch_eff: np.ndarray
    batt_disch_eff: np.ndarray
    e_batt_init_frac: np.ndarray  # fraction of capacity (t=0 init; dragg/mpc_calc.py:274)
    batt_capacity: np.ndarray
    pv_area: np.ndarray
    pv_eff: np.ndarray
    # Scenario types (ROADMAP item 4; zeros / identities for absent types
    # so the legacy batch math is untouched).
    is_ev: np.ndarray          # float 0/1
    ev_cap: np.ndarray         # kWh
    ev_rate: np.ndarray        # kW charger rate
    ev_ch_eff: np.ndarray      # charge efficiency (1.0 default)
    ev_init_frac: np.ndarray   # t=0 SOC fraction of ev_cap
    ev_target_kwh: np.ndarray  # departure-deadline energy, kWh
    ev_away_start: np.ndarray  # hour of day [0, 24)
    ev_away_end: np.ndarray    # hour of day (may exceed 24 → clipped window)
    ev_trip_kwh: np.ndarray    # SOC drained when the vehicle returns
    is_hp: np.ndarray          # float 0/1
    hp_cop_base: np.ndarray    # heating COP at 0 degC (1.0 default = resistive)
    hp_cop_slope: np.ndarray   # COP per degC (0.0 default)

    @property
    def n_homes(self) -> int:
        return int(self.type_code.shape[0])


def type_bucket_ranges(type_code) -> list[tuple[str, int, int]] | None:
    """Contiguous per-type runs of the batch, in community order:
    ``[(type_name, start, stop), ...]``.

    The population is materialized in type order (``create_homes``:
    pv_battery, pv_only, battery_only, base), so each home type occupies
    one contiguous slice and the type-bucketed engine can treat buckets
    as slices plus a static column map — no scatter.  Returns ``None``
    when some type appears in more than one run (a hand-built,
    interleaved batch): such a community is not bucketable by slicing.
    Empty types simply produce no range (never a zero-width bucket).
    """
    codes = np.asarray(type_code)
    if codes.size == 0:
        return None
    ranges: list[tuple[str, int, int]] = []
    seen: set[int] = set()
    start = 0
    for i in range(1, codes.size + 1):
        if i == codes.size or codes[i] != codes[start]:
            code = int(codes[start])
            if code in seen:
                return None  # type split across non-adjacent runs
            seen.add(code)
            ranges.append((HOME_TYPES[code], start, i))
            start = i
    return ranges


def slice_batch(batch: "HomeBatch", start: int, stop: int) -> "HomeBatch":
    """A HomeBatch view of homes ``[start:stop)`` (every per-home array
    sliced along the leading axis)."""
    return type(batch)(*[np.asarray(f)[start:stop] for f in batch])


def pad_batch(batch: "HomeBatch", multiple: int):
    """Pad every per-home array to a multiple of the shard count.

    Padding replicates the last home (edge padding) so the dummy problems
    remain well-posed (no zero tank sizes / RC constants); the returned
    mask is 0 for padded homes so aggregate reductions are unchanged.
    (Shared by the sharded engine's whole-batch padding and the
    type-bucketed engine's per-bucket padding.)
    """
    n = batch.n_homes
    n_pad = (-n) % multiple
    if n_pad == 0:
        return batch, np.ones(n)
    padded = type(batch)(*[
        np.pad(np.asarray(f), [(0, n_pad)] + [(0, 0)] * (np.asarray(f).ndim - 1),
               mode="edge")
        for f in batch
    ])
    mask = np.concatenate([np.ones(n), np.zeros(n_pad)])
    return padded, mask


def build_home_batch(all_homes: list[dict], horizon: int, dt: int, sub_steps: int) -> HomeBatch:
    """Pack home dicts into the padded superset batch.

    ``draws_hourly`` is prepended with ``horizon//dt + 1`` zero hours exactly
    as the reference's ``water_draws`` does (dragg/mpc_calc.py:194), so a
    window slice at hour ``t//dt`` of length ``horizon//dt + 1`` reproduces
    the reference draw schedule.
    """
    n = len(all_homes)
    s = float(max(1, sub_steps))
    pad = horizon // dt + 1

    def g(fn):
        return np.array([fn(h) for h in all_homes], dtype=np.float64)

    type_code = np.array([TYPE_CODES[h["type"]] for h in all_homes], dtype=np.int32)
    has_pv = np.array(["pv" in h["type"] for h in all_homes], dtype=np.float64)
    has_batt = np.array(["battery" in h["type"] for h in all_homes], dtype=np.float64)

    draw_len = max(len(h["wh"]["draw_sizes"]) for h in all_homes)
    draws = np.zeros((n, pad + draw_len), dtype=np.float64)
    for i, h in enumerate(all_homes):
        d = np.asarray(h["wh"]["draw_sizes"], dtype=np.float64)
        draws[i, pad : pad + len(d)] = d

    def batt(key, default=0.0):
        return np.array(
            [float(h["battery"][key]) if "battery" in h else default for h in all_homes],
            dtype=np.float64,
        )

    def ev(key, default=0.0):
        return np.array(
            [float(h["ev"][key]) if "ev" in h else default for h in all_homes],
            dtype=np.float64,
        )

    def hp(key, default=0.0):
        return np.array(
            [float(h["heat_pump"][key]) if "heat_pump" in h else default
             for h in all_homes],
            dtype=np.float64,
        )

    capacity = batt("capacity")
    return HomeBatch(
        type_code=type_code,
        has_pv=has_pv,
        has_batt=has_batt,
        hvac_r=g(lambda h: float(h["hvac"]["r"])),
        hvac_c=g(lambda h: float(h["hvac"]["c"]) * 1000.0),
        hvac_p_c=g(lambda h: float(h["hvac"]["p_c"]) / s),
        hvac_p_h=g(lambda h: float(h["hvac"]["p_h"]) / s),
        temp_in_min=g(lambda h: float(h["hvac"]["temp_in_min"])),
        temp_in_max=g(lambda h: float(h["hvac"]["temp_in_max"])),
        temp_in_init=g(lambda h: float(h["hvac"]["temp_in_init"])),
        wh_r=g(lambda h: float(h["wh"]["r"]) * 1000.0),
        wh_c=g(lambda h: float(h["wh"]["tank_size"]) * 4.2),
        wh_p=g(lambda h: float(h["wh"]["p"]) / s),
        temp_wh_min=g(lambda h: float(h["wh"]["temp_wh_min"])),
        temp_wh_max=g(lambda h: float(h["wh"]["temp_wh_max"])),
        temp_wh_init=g(lambda h: float(h["wh"]["temp_wh_init"])),
        tank_size=g(lambda h: float(h["wh"]["tank_size"])),
        draws_hourly=draws,
        batt_max_rate=batt("max_rate"),
        batt_cap_min=batt("capacity_lower") * capacity,
        batt_cap_max=batt("capacity_upper") * capacity,
        batt_ch_eff=batt("ch_eff", 1.0),
        batt_disch_eff=batt("disch_eff", 1.0),
        e_batt_init_frac=batt("e_batt_init"),
        batt_capacity=capacity,
        pv_area=np.array([float(h["pv"]["area"]) if "pv" in h else 0.0 for h in all_homes]),
        pv_eff=np.array([float(h["pv"]["eff"]) if "pv" in h else 0.0 for h in all_homes]),
        is_ev=np.array([1.0 if "ev" in h else 0.0 for h in all_homes]),
        ev_cap=ev("capacity"),
        ev_rate=ev("max_rate"),
        ev_ch_eff=ev("charge_eff", 1.0),
        ev_init_frac=ev("init_soc"),
        ev_target_kwh=ev("target_soc") * ev("capacity"),
        ev_away_start=ev("away_start"),
        ev_away_end=ev("away_end"),
        ev_trip_kwh=ev("trip_kwh"),
        is_hp=np.array([1.0 if "heat_pump" in h else 0.0 for h in all_homes]),
        hp_cop_base=hp("cop_base", 1.0),
        hp_cop_slope=hp("cop_slope", 0.0),
    )
