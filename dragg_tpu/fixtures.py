"""Shared single-step QP assembly for parity tests and measurement tools.

One canonical recipe for "the community's t=0 QP exactly as the engine
would assemble it" (seeded population, weather window, draw smoothing,
water-mixed initial WH temperature, season gate), shared by
tests/test_qp_parity.py and tools/milp_gap.py so the parity-tested
matrices and the MILP-gap-measured matrices can never drift apart
(advisor finding, round 4).

The draw smoothing and initial-condition arithmetic mirror the engine's
step preparation (dragg_tpu/engine.py) and the reference semantics at
dragg/mpc_calc.py:193-204 (water draws) and :270-289 (WH mixing).
"""

from __future__ import annotations

import numpy as np


def assemble_community_qp(horizon_hours: int = 4, n_homes: int = 6,
                          homes_pv: int = 1, homes_battery: int = 1,
                          homes_pv_battery: int = 1,
                          homes_ev: int = 0, homes_heat_pump: int = 0,
                          season: str = "heat",
                          return_inputs: bool = False):
    """Assemble the t=0 community QP for a seeded mixed community.

    ``season``: "heat" pins the reference test fixture's heat-only gate;
    "auto" applies the NOMINAL community-wide form of the season rule
    (max window OAT <= 30 C -> heat-only, else cool-only — the threshold
    of dragg/mpc_calc.py:302-309).  NOTE this is a simplification of the
    engine's live gate, which is per-home and includes sampled forecast
    noise (dragg_tpu/engine.py:421-424) — with the default deep-winter
    t=0 window the two agree for every home (max OAT is far below 30 C),
    but near-threshold windows could diverge; measurement tools relying
    on "auto" should stick to windows away from the threshold.

    Returns ``(qp, pattern, layout, s)`` where ``s`` is
    ``sub_subhourly_steps`` (the duty-count cap).
    """
    import jax.numpy as jnp

    from dragg_tpu.config import default_config
    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes
    from dragg_tpu.ops.qp import TAP_TEMP, assemble_qp_step

    cfg = default_config()
    cfg["community"]["total_number_homes"] = n_homes
    cfg["community"]["homes_pv"] = homes_pv
    cfg["community"]["homes_battery"] = homes_battery
    cfg["community"]["homes_pv_battery"] = homes_pv_battery
    cfg["community"]["homes_ev"] = homes_ev
    cfg["community"]["homes_heat_pump"] = homes_heat_pump
    cfg["home"]["hems"]["prediction_horizon"] = horizon_hours
    # This fixture extracts ONE superset-shaped QP via the engine's
    # whole-batch attributes (_draws/_tank/_oat/...), which a bucketed
    # engine keeps per bucket instead — pin the superset path (round-8
    # `auto` would otherwise bucket large mixed fixtures).
    cfg["tpu"]["bucketed"] = "false"
    seed = int(cfg["simulation"]["random_seed"])
    env = load_environment(cfg)
    dt = env.dt
    waterdraw = load_waterdraw_profiles(None, seed=seed)
    homes = create_homes(cfg, 24 * dt, dt, waterdraw)
    hems = cfg["home"]["hems"]
    batch = build_home_batch(homes, horizon_hours * dt, dt,
                             int(hems["sub_subhourly_steps"]))
    eng = make_engine(batch, env, cfg, env.start_index(env.data_start))
    p, lay, b = eng.params, eng.layout, eng.batch
    H, s, n = p.horizon, p.s, eng.n_homes

    draws = np.asarray(eng._draws)[:, : H // dt + 1]
    raw = np.repeat(draws, dt, axis=-1) / dt
    draw_size = np.zeros((n, H + 1))
    for i in range(H + 1):
        if i < dt:
            draw_size[:, i] = raw[:, i]
        else:
            draw_size[:, i] = raw[:, max(i - 1, 0): min(i + 2, raw.shape[1])].mean(axis=1)
    tank = np.asarray(eng._tank)
    twh0 = np.asarray(b.temp_wh_init)
    twh_init = (twh0 * (tank - draw_size[:, 0]) + TAP_TEMP * draw_size[:, 0]) / tank

    oat_w = np.asarray(eng._oat)[: H + 1]
    ghi_w = np.asarray(eng._ghi)[: H + 1]
    tou_w = np.asarray(eng._tou)[:H]
    price = np.broadcast_to(tou_w[None], (n, H)).copy()
    if season == "auto":
        heat_season = float(np.max(oat_w)) <= 30.0
    else:
        heat_season = season == "heat"
    heat_cap = np.full(n, float(s) if heat_season else 0.0)
    cool_cap = np.full(n, 0.0 if heat_season else float(s))

    # EV availability / deadline bounds at t=0 — the SAME helper the
    # engine's traced step uses (ops/qp.ev_charge_bounds), so the
    # parity-tested EV matrices are the engine's matrices.
    if lay.has_ev:
        from dragg_tpu.engine import env_hour0
        from dragg_tpu.ops.qp import ev_charge_bounds

        hour0 = env_hour0(env)
        t0 = p.start_index
        hod_c = ((t0 + np.arange(p.horizon)) // dt + hour0) % 24
        hod_s = ((t0 + 1 + np.arange(p.horizon)) // dt + hour0) % 24
        e_ev0 = np.asarray(b.is_ev) * np.asarray(b.ev_init_frac) \
            * np.asarray(b.ev_cap)
        ev_avail, ev_floor = ev_charge_bounds(hod_c, hod_s, b, e_ev0, dt)
        e_ev_init = jnp.asarray(e_ev0, dtype=jnp.float32)
    else:
        ev_avail = ev_floor = e_ev_init = None

    qp = assemble_qp_step(
        eng.static, lay, b,
        oat_window=oat_w, ghi_window=ghi_w, price_total=jnp.asarray(price),
        draw_frac=jnp.asarray(draw_size / tank[:, None]),
        temp_in_init=jnp.asarray(b.temp_in_init, dtype=jnp.float32),
        temp_wh_init=jnp.asarray(twh_init, dtype=jnp.float32),
        e_batt_init=jnp.asarray(b.e_batt_init_frac * b.batt_capacity,
                                dtype=jnp.float32),
        cool_cap=jnp.asarray(cool_cap, dtype=jnp.float32),
        heat_cap=jnp.asarray(heat_cap, dtype=jnp.float32),
        wh_cap=s, discount=p.discount,
        e_ev_init=e_ev_init, ev_avail=ev_avail, ev_floor=ev_floor,
    )
    if return_inputs:
        # Raw model inputs for INDEPENDENT re-derivations of the program
        # (tests/test_model_parity.py transcribes the reference's cvxpy
        # constraints directly from these — bypassing ops/qp.py — to
        # check the canonicalized matrices encode the same model).
        inputs = dict(
            batch=b, dt=dt, s=int(s), discount=float(p.discount),
            oat_window=np.asarray(oat_w), ghi_window=np.asarray(ghi_w),
            price=price, draw_size=draw_size, tank=tank,
            temp_in_init=np.asarray(b.temp_in_init, dtype=np.float64),
            temp_wh_init=np.asarray(twh_init, dtype=np.float64),
            e_batt_init=np.asarray(b.e_batt_init_frac * b.batt_capacity,
                                   dtype=np.float64),
            cool_cap=cool_cap, heat_cap=heat_cap,
        )
        return qp, eng.static.pattern, lay, int(s), inputs
    return qp, eng.static.pattern, lay, int(s)
