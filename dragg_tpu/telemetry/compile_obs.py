"""Staged compile observability (round 9 — the observatory tentpole).

The round-4 10k engine-compile hang was never bisected: the bench child
logged "constructing engine" and then nothing for 900 s, so the
supervisor could only classify COMPILE_HANG — not WHICH stage (trace?
XLA compile? first device execution?) or which bucket pattern was in
flight, and the abandoned compile wedged the tunnel for every later
process (CLAUDE.md).  :func:`staged_compile` splits the jit boundary
into explicit AOT stages —

    lower          trace the chunk program (host-side, shape-dependent)
    compile        XLA compilation of the lowered module
    first_execute  the compiled program's first device run

— with, per stage: a heartbeat beat BEFORE the stage starts (so a
supervised child that hangs inside it leaves the stage name + per-bucket
pattern shapes as its last progress payload, and the supervisor's
stall-kill verdict names the stage instead of just COMPILE_HANG), a
``fault_hook("compile_<stage>")`` site (chaos tests inject hangs
deterministically), a ``compile.stage`` event + ``compile.stage_s``
metric, and persistent-cache hit/miss detection on the compile stage
(entry-count delta in the enabled cache dir — a "hit" names the warm
path, so a 58.9 s cold bucketed compile is distinguishable from a 2 s
cache load in the artifacts).

The compiled executable is returned as a ``runner`` with
``engine.run_chunk``'s signature: callers that keep using it (bench's
timed chunks) never pay a second jit trace/compile of the same shape.
"""

from __future__ import annotations

import os
import time

from dragg_tpu import telemetry

STAGES = ("lower", "compile", "first_execute")


def _cache_entries() -> int | None:
    """Entry count of the enabled persistent compile cache (None = cache
    off / unreadable).  Counting files is the honest observable: JAX does
    not expose hit/miss, but a compile that wrote no new entry on an
    enabled cache was served from it."""
    from dragg_tpu.utils.compile_cache import enabled_cache_dir

    d = enabled_cache_dir()
    if not d:
        return None
    try:
        return len(os.listdir(d))
    except OSError:
        return None


def staged_compile(engine, state, t0: int, rps, label: str = "chunk"):
    """Lower → compile → first-execute ``engine``'s chunk program with
    per-stage telemetry/heartbeat/fault instrumentation (module
    docstring).  Returns ``(runner, state_out, outs, report)`` where
    ``runner(state, t0, rps)`` re-runs the SAME compiled executable
    (chunk shape fixed) and ``report`` = {label, stages: {name: s},
    cache: hit|miss|unknown, total_s, buckets}."""
    import jax
    import jax.numpy as jnp

    from dragg_tpu.resilience.faults import fault_hook
    from dragg_tpu.resilience.heartbeat import beat

    buckets = [dict(name=b["name"], n_slots=b["n_slots"], m_eq=b["m_eq"],
                    n_var=b["n_var"]) for b in engine.bucket_info()]
    bdesc = ",".join(f"{b['name']}[{b['n_slots']}x{b['m_eq']}]"
                     for b in buckets)
    consts = engine._consts()
    args = (consts, state, jnp.asarray(t0),
            jnp.asarray(rps, dtype=jnp.float32))
    stages: dict[str, float] = {}

    def begin(stage: str) -> float:
        # Beat BEFORE the stage: if it hangs, this is the child's last
        # progress payload — the supervisor surfaces it on the
        # failure.COMPILE_HANG event (stage + pattern attribution).
        beat({"stage": f"compile:{stage}", "label": label, "buckets": bdesc})
        fault_hook(f"compile_{stage}")
        return time.perf_counter()

    def end(stage: str, t_begin: float) -> None:
        s = time.perf_counter() - t_begin
        stages[stage] = round(s, 3)
        telemetry.observe("compile.stage_s", s)
        telemetry.emit("compile.stage", label=label, stage=stage,
                       s=round(s, 3), buckets=bdesc)

    tb = begin("lower")
    lowered = engine._chunk_fn.lower(*args)
    end("lower", tb)

    n_before = _cache_entries()
    tb = begin("compile")
    compiled = lowered.compile()
    end("compile", tb)
    n_after = _cache_entries()
    if n_before is None or n_after is None:
        cache = "unknown"
    elif n_after > n_before:
        cache = "miss"
    else:
        # No new entry: a true hit — unless the compile finished under
        # the persistence floor (jax_persistent_cache_min_compile_time_secs,
        # 0.1 s per utils/compile_cache), where XLA writes nothing either
        # way and hit vs sub-floor-cold is indistinguishable.
        try:
            import jax

            floor = float(jax.config.jax_persistent_cache_min_compile_time_secs)
        except Exception:
            floor = 0.1
        cache = "hit" if stages["compile"] >= floor else "unknown"

    tb = begin("first_execute")
    state_out, outs = compiled(*args)
    jax.block_until_ready(outs.agg_load)
    end("first_execute", tb)
    beat({"stage": "compile:done", "label": label})

    total = sum(stages.values())
    telemetry.emit("compile.done", label=label, total_s=round(total, 3),
                   cache=cache, stages=dict(stages), buckets=buckets)

    def runner(state, t0, rps):
        return compiled(consts, state, jnp.asarray(t0),
                        jnp.asarray(rps, dtype=jnp.float32))

    report = dict(label=label, stages=dict(stages), cache=cache,
                  total_s=round(total, 3), buckets=buckets)
    return runner, state_out, outs, report


def selftest(n_homes: int = 4, horizon: int = 2, steps: int = 2) -> dict:
    """Tiny end-to-end staged compile (doctor ``--compile-check`` runs
    this in a hard-timeouted subprocess): builds a minimal community
    engine, stages its chunk compile, and returns the report with an
    ``ok`` verdict.  Synthetic data, any backend."""
    import numpy as np

    from dragg_tpu.config import default_config
    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    cfg = default_config()
    cfg["community"]["total_number_homes"] = n_homes
    cfg["community"]["homes_pv"] = 0
    cfg["home"]["hems"]["prediction_horizon"] = horizon
    env = load_environment(cfg, data_dir=None)
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24, 1, wd)
    batch = build_home_batch(
        homes, max(1, horizon), 1,
        int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    engine = make_engine(batch, env, cfg, 0)
    rps = np.zeros((steps, engine.params.horizon), np.float32)
    _runner, _state, outs, report = staged_compile(
        engine, engine.init_state(), 0, rps, label="selftest")
    report["ok"] = (all(s in report["stages"] for s in STAGES)
                    and bool(np.isfinite(float(np.asarray(outs.agg_load)[0]))))
    return report
