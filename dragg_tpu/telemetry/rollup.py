"""Live fleet metrics rollup (ISSUE 20 tentpole): fold the main
process's and every shard sub-stream's in-progress metrics snapshots
plus stream tails into ONE fleet view, with a per-shard health
scoreboard — served as ``/rollup.json`` and as Prometheus text
exposition at ``/metrics`` by the serve daemon, the shard chunk-ingest
server, and the dashboard.

The feed is the periodic snapshot flush (``telemetry.init_run``'s
``flush_s`` / ``$DRAGG_TELEMETRY_FLUSH_S``, plus the shard worker's
per-chunk flush): each process rewrites its own ``metrics.json``
atomically mid-run, so a kill -9 loses at most one flush interval of
metric deltas and the coordinator's post-mortem still sees the victim's
last interval.  Stdlib only, jax-free.
"""

from __future__ import annotations

import json
import os
import time

from dragg_tpu.telemetry import bus


def _load_metrics(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def fold_rollup(run_dir: str, now: float | None = None,
                tail_limit: int = 200) -> dict:
    """The fleet rollup for one run directory: per-stream metrics
    snapshots (main + every ``shard<k>``), fleet-summed counters, and
    the per-shard health scoreboard (last-beat age, chunk-frontier lag,
    degradation state, wire retry/dedup counters)."""
    now = time.time() if now is None else now
    events_path = os.path.join(run_dir, bus.EVENTS_FILE)
    streams: dict = {}
    for path in bus.stream_paths(events_path):
        label = os.path.basename(os.path.dirname(path))
        if path == events_path:
            label = "main"
        snap = _load_metrics(os.path.join(os.path.dirname(path),
                                          bus.METRICS_FILE))
        streams[label] = {"metrics": snap, "path": path}
    # One bounded merged tail feeds every stream's liveness fields.
    tail = bus.tail_events_dir(events_path, limit=tail_limit)
    last_t: dict = {}
    frontier: dict = {}
    platform: dict = {}
    wire_counts: dict = {}
    for rec in tail:
        label = rec.get("_stream", "main")
        t = rec.get("t")
        if t is not None:
            last_t[label] = max(last_t.get(label, 0.0), t)
        ev = rec.get("event")
        if ev == "chunk.done" and rec.get("t1") is not None:
            frontier[label] = max(frontier.get(label, 0),
                                  int(rec["t1"]))
        elif ev == "shard.chunk" and rec.get("t1") is not None:
            # The coordinator's merge record names the shard — the
            # frontier survives even when a shard stream is lost.
            lab = f"shard{rec.get('shard')}"
            frontier[lab] = max(frontier.get(lab, 0), int(rec["t1"]))
        elif ev in ("shard.transition", "degrade.transition"):
            lab = (f"shard{rec['shard']}" if rec.get("shard") is not None
                   else label)
            platform[lab] = rec.get("to_platform")
        elif ev == "shard.launch":
            platform.setdefault(f"shard{rec.get('shard')}",
                                rec.get("platform"))
    fleet_counters: dict = {}
    for label, entry in streams.items():
        snap = entry["metrics"]
        counters = (snap or {}).get("counters") or {}
        for name, v in counters.items():
            fleet_counters[name] = fleet_counters.get(name, 0.0) + v
        if label.startswith("shard"):
            wire_counts[label] = {
                "retries": counters.get("wire.retries", 0),
                "dedup": counters.get("wire.dedup", 0)}
        entry["written_at"] = (snap or {}).get("written_at")
        entry.pop("path", None)
    # Server-side dedup lands on the MAIN stream's counters; surface it
    # on the scoreboard too (the client-side view can undercount when a
    # lost ack hid the dup from the worker).
    main_counters = ((streams.get("main") or {}).get("metrics")
                     or {}).get("counters") or {}
    shards = sorted(lab for lab in set(streams) | set(frontier)
                    if lab.startswith("shard"))
    target = max(frontier.values(), default=0)
    scoreboard = []
    for lab in shards:
        beat_t = last_t.get(lab)
        snap = (streams.get(lab) or {}).get("metrics")
        scoreboard.append({
            "shard": lab,
            "last_event_age_s": (round(now - beat_t, 3)
                                 if beat_t else None),
            "frontier_t": frontier.get(lab),
            "frontier_lag": (target - frontier[lab]
                             if lab in frontier else None),
            "platform": platform.get(lab),
            "wire_retries": (wire_counts.get(lab) or {}).get("retries", 0),
            "wire_dedup_client": (wire_counts.get(lab)
                                  or {}).get("dedup", 0),
            "metrics_written_at": (snap or {}).get("written_at"),
        })
    return {
        "schema": 1,
        "run_dir": run_dir,
        "folded_at": round(now, 3),
        "streams": streams,
        "fleet_counters": fleet_counters,
        "wire_dedup_server": main_counters.get("wire.dedup", 0),
        "frontier_t": target or None,
        "shards": scoreboard,
    }


def _prom_name(name: str) -> str:
    return "dragg_" + "".join(c if c.isalnum() else "_" for c in name)


def prometheus_text(rollup: dict) -> str:
    """Prometheus text exposition (version 0.0.4) of a rollup: every
    stream's counters/gauges labelled by stream, histograms as
    ``_count``/``_sum`` pairs, plus the per-shard health scoreboard."""
    lines: list[str] = []
    typed: set = set()

    def sample(name: str, kind: str, labels: dict, value) -> None:
        if value is None:
            return
        pname = _prom_name(name)
        base = pname.removesuffix("_count").removesuffix("_sum")
        if base not in typed and kind in ("counter", "gauge"):
            typed.add(base)
            lines.append(f"# TYPE {base} {kind}")
        lab = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        lines.append(f"{pname}{{{lab}}} {float(value)}")

    for label, entry in sorted((rollup.get("streams") or {}).items()):
        snap = entry.get("metrics") or {}
        for name, v in sorted((snap.get("counters") or {}).items()):
            sample(name, "counter", {"stream": label}, v)
        for name, v in sorted((snap.get("gauges") or {}).items()):
            sample(name, "gauge", {"stream": label}, v)
        for name, h in sorted((snap.get("histograms") or {}).items()):
            sample(f"{name}_count", "histogram", {"stream": label},
                   h.get("count"))
            sample(f"{name}_sum", "histogram", {"stream": label},
                   h.get("sum"))
    for row in rollup.get("shards") or []:
        labels = {"shard": row["shard"]}
        sample("shard.last_event_age_s", "gauge", labels,
               row.get("last_event_age_s"))
        sample("shard.frontier_t", "gauge", labels, row.get("frontier_t"))
        sample("shard.frontier_lag", "gauge", labels,
               row.get("frontier_lag"))
        sample("shard.wire_retries", "gauge", labels,
               row.get("wire_retries"))
        sample("shard.wire_dedup", "gauge", labels,
               row.get("wire_dedup_client"))
    if rollup.get("frontier_t") is not None:
        sample("fleet.frontier_t", "gauge", {"run": "current"},
               rollup["frontier_t"])
    return "\n".join(lines) + "\n"
