"""Run-scoped event bus + metrics registry + span API — stdlib only.

One process-wide bus, explicitly opened by entry points
(:func:`init_run`) or joined automatically from ``$DRAGG_TELEMETRY_DIR``
(how supervised children land their events in the same stream as the
jax-free parent that launched them).  While a bus is open:

* :func:`emit` appends one typed JSON record per call to
  ``<run_dir>/events.jsonl`` (append-only; each record carries wall
  time, a monotonic offset, pid, and a per-process sequence number, so
  merged multi-process streams stay ordered and attributable);
* :func:`inc` / :func:`set_gauge` / :func:`observe` update the in-memory
  metrics registry; :func:`snapshot` reads it and
  :func:`write_snapshot` persists it as ``<run_dir>/metrics.json``;
* :func:`span` times a block into a histogram metric (and emits a
  ``span`` event), wrapping ``jax.profiler.TraceAnnotation`` when jax is
  ALREADY imported in this process — telemetry itself never imports jax,
  because the resilience parents that emit through it must stay jax-free
  (a wedged tunnel hangs any backend init; see resilience.supervisor).

Disabled mode (no bus open, env unset) is the default and near-free:
every entry point is a registry membership check plus one module-global
load — measured ≪1 µs/call (tests/test_telemetry.py pins the A/B).
Name discipline is enforced even when disabled: an unregistered name
raises ValueError so a typo cannot hide until a run is instrumented.
IO failures, by contrast, are swallowed — telemetry must never kill the
workload it observes.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from dragg_tpu.telemetry import registry, trace

ENV_DIR = "DRAGG_TELEMETRY_DIR"
ENV_FLUSH = "DRAGG_TELEMETRY_FLUSH_S"
EVENTS_FILE = "events.jsonl"
METRICS_FILE = "metrics.json"
SCHEMA_VERSION = 1
_SAMPLE_CAP = 256  # bounded per-histogram sample tail kept in snapshots


def _jsonable(o):
    """Fallback serializer: numpy scalars -> float, everything else str."""
    try:
        return float(o)
    except (TypeError, ValueError):
        return str(o)


class _Hist:
    __slots__ = ("count", "total", "vmin", "vmax", "last", "samples")

    def __init__(self):
        import collections

        self.count = 0
        self.total = 0.0
        self.vmin = self.vmax = self.last = None
        # A true bounded TAIL (the newest _SAMPLE_CAP observations), not
        # a prefix: consumers like bench's chunk_rates want steady-state
        # samples, and a prefix would silently drop the warmed-up end of
        # a long series.
        self.samples: "collections.deque[float]" = collections.deque(
            maxlen=_SAMPLE_CAP)

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.last = v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)
        self.samples.append(v)

    def summary(self) -> dict:
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin,
            "max": self.vmax,
            "mean": self.total / self.count if self.count else None,
            "last": self.last,
            "samples": list(self.samples),
        }


class _Bus:
    def __init__(self, run_dir: str | None, jsonl: bool = True,
                 flush_s: float | None = None):
        self.run_dir = run_dir
        self.lock = threading.RLock()
        self.seq = 0
        self.mono0 = time.monotonic()
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.hists: dict[str, _Hist] = {}
        self.path = None
        self._fh = None
        if flush_s is None:
            try:
                flush_s = float(os.environ.get(ENV_FLUSH) or 0.0)
            except ValueError:
                flush_s = 0.0
        self.flush_s = max(0.0, flush_s)
        self._next_flush = self.mono0 + self.flush_s
        if run_dir and jsonl:
            os.makedirs(run_dir, exist_ok=True)
            self.path = os.path.join(run_dir, EVENTS_FILE)
            self._fh = open(self.path, "a", encoding="utf-8")

    def emit(self, event: str, fields: dict) -> None:
        with self.lock:
            self.seq += 1
            rec = {"event": event, "t": round(time.time(), 3),
                   "mono": round(time.monotonic() - self.mono0, 6),
                   "pid": os.getpid(), "seq": self.seq}
            rec.update(fields)
            # Causal trace context (ISSUE 20): when tracing is on, every
            # record carries trace/span/parent.  setdefault lets an
            # emitter's finer span win; with tracing off NOTHING is
            # added, keeping the off-mode stream byte-identical.
            ctx = trace.current()
            if ctx is not None:
                rec.setdefault("trace", ctx["trace"])
                rec.setdefault("span", ctx["span"])
                if "parent" not in rec and ctx["parent"] is not None:
                    rec["parent"] = ctx["parent"]
            if self._fh is not None:
                try:
                    # One full line per write: POSIX O_APPEND keeps lines
                    # from different processes whole in a shared file.
                    self._fh.write(json.dumps(rec, default=_jsonable) + "\n")
                    self._fh.flush()
                except (OSError, ValueError):
                    pass  # telemetry never kills the workload
            # Periodic in-progress metrics flush (the live-rollup feed):
            # a kill -9 between flushes loses at most flush_s of metric
            # deltas instead of the whole metrics.json.  Off (0.0) by
            # default — round-19 runs write metrics.json only at close.
            if self.flush_s and self.run_dir:
                now = time.monotonic()
                if now >= self._next_flush:
                    self._next_flush = now + self.flush_s
                    _write_snapshot_locked(self)

    def snapshot(self) -> dict:
        with self.lock:
            return {
                "schema": SCHEMA_VERSION,
                "written_at": round(time.time(), 3),
                "run_dir": self.run_dir,
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "histograms": {k: h.summary() for k, h in self.hists.items()},
            }

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


_bus: _Bus | None = None
_env_checked = False
_state_lock = threading.Lock()


def _current() -> _Bus | None:
    """The active bus, joining ``$DRAGG_TELEMETRY_DIR`` lazily on first
    use (re-checked after every :func:`close_run`)."""
    global _bus, _env_checked
    bus = _bus
    if bus is not None or _env_checked:
        return bus
    with _state_lock:
        if _bus is None and not _env_checked:
            _env_checked = True
            d = os.environ.get(ENV_DIR)
            if d:
                try:
                    _bus = _Bus(d)
                except OSError:
                    _bus = None
        return _bus


def init_run(run_dir: str | None = None, jsonl: bool = True,
             flush_s: float | None = None) -> str | None:
    """Open the process bus.  ``run_dir=None`` gives a memory-only bus
    (metrics + spans work, no events file — what bench's measured child
    uses unless the supervisor exported a telemetry dir).  Returns the
    events.jsonl path, or None when memory-only.  ``flush_s`` > 0 turns
    on the periodic in-progress metrics flush (default: read
    ``$DRAGG_TELEMETRY_FLUSH_S``, else off)."""
    global _bus, _env_checked
    with _state_lock:
        if _bus is not None:
            _bus.close()
        _bus = _Bus(run_dir, jsonl=jsonl, flush_s=flush_s)
        _env_checked = True
        return _bus.path


def close_run(write_metrics: bool = False) -> None:
    """Close the bus (optionally persisting a final metrics snapshot
    first) and re-arm the ``$DRAGG_TELEMETRY_DIR`` auto-join."""
    global _bus, _env_checked
    with _state_lock:
        if _bus is not None:
            if write_metrics and _bus.run_dir:
                _write_snapshot_locked(_bus)
            _bus.close()
        _bus = None
        _env_checked = False


def active() -> bool:
    return _current() is not None


def events_path() -> str | None:
    bus = _current()
    return bus.path if bus else None


def run_dir() -> str | None:
    bus = _current()
    return bus.run_dir if bus else None


# ------------------------------------------------------------------ emits
def emit(event: str, **fields) -> None:
    """Append one typed event record to the run stream (no-op when no
    bus is open; unregistered names raise regardless)."""
    registry.check_event(event)
    bus = _current()
    if bus is not None:
        bus.emit(event, fields)


def inc(name: str, value: float = 1.0) -> None:
    registry.check_metric(name, "counter")
    bus = _current()
    if bus is not None:
        with bus.lock:
            bus.counters[name] = bus.counters.get(name, 0.0) + float(value)


def set_gauge(name: str, value: float) -> None:
    registry.check_metric(name, "gauge")
    bus = _current()
    if bus is not None:
        with bus.lock:
            bus.gauges[name] = float(value)


def observe(name: str, value: float) -> None:
    registry.check_metric(name, "histogram")
    bus = _current()
    if bus is not None:
        with bus.lock:
            bus.hists.setdefault(name, _Hist()).observe(float(value))


class span:
    """``with telemetry.span("bench.chunk_s") as sp: ...`` — times the
    block into the named histogram metric, emits a ``span`` event, and
    leaves the duration on ``sp.s``.  Wraps the block in a
    ``jax.profiler.TraceAnnotation`` when jax is already imported (so
    spans show up in profiler traces) — never imports jax itself."""

    __slots__ = ("name", "s", "_t0", "_ann")

    def __init__(self, name: str):
        registry.check_metric(name, "histogram")
        self.name = name
        self.s = None
        self._ann = None

    def __enter__(self):
        if "jax" in sys.modules and _current() is not None:
            try:
                from jax.profiler import TraceAnnotation

                self._ann = TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.s = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        bus = _current()
        if bus is not None:
            with bus.lock:
                bus.hists.setdefault(self.name, _Hist()).observe(self.s)
            bus.emit("span", {"name": self.name, "s": round(self.s, 6)})
        return False


def tail_events(events_path: str, limit: int = 50,
                tail_bytes: int = 262_144) -> list[dict]:
    """Last ``limit`` parseable event records of an events.jsonl — reads
    a bounded byte tail, so tailing a huge in-progress stream stays
    O(limit) not O(run).  Torn/mid-write lines are skipped.  Shared by
    the dashboard's ``/live`` surface and the serving daemon's
    ``/events.jsonl`` endpoint (one tailer, one dialect)."""
    try:
        with open(events_path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - tail_bytes))
            lines = f.read().decode("utf-8", "replace").splitlines()
    except OSError:
        return []
    out: list[dict] = []
    for line in reversed(lines):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue  # torn first line of the tail window / mid-write
        if len(out) >= limit:
            break
    return list(reversed(out))


def stream_paths(events_path: str) -> list[str]:
    """The events.jsonl streams of one run: the main file plus any
    per-shard sub-streams (``shard<k>/events.jsonl`` — the shard slots
    export one per worker child so concurrent shards never interleave
    into one bus file; dragg_tpu/shard/slots.py).  Ordered main-first,
    then shards by index."""
    paths = [events_path]
    run_dir = os.path.dirname(events_path)
    try:
        names = os.listdir(run_dir)
    except OSError:
        return paths
    shards = []
    for name in names:
        if name.startswith("shard"):
            try:
                idx = int(name[len("shard"):])
            except ValueError:
                continue
            p = os.path.join(run_dir, name, EVENTS_FILE)
            if os.path.isfile(p):
                shards.append((idx, p))
    paths.extend(p for _i, p in sorted(shards))
    return paths


def skew_offsets(records) -> dict:
    """Per-emitter wall-clock corrections from ``trace.skew`` records:
    ``{(_stream, pid): offset_s}`` (last record wins).  The offsets come
    from the shard wire's clock handshake (shard/transport.py) — on a
    single host they are ~0, on a real multi-host fleet they are the
    honesty correction merged ordering needs."""
    offsets: dict = {}
    for rec in records:
        if rec.get("event") == "trace.skew":
            try:
                offsets[(rec.get("_stream", "main"), rec.get("pid"))] = \
                    float(rec.get("offset_s") or 0.0)
            except (TypeError, ValueError):
                continue
    return offsets


def tail_events_dir(events_path: str, limit: int = 50,
                    tail_bytes: int = 262_144) -> list[dict]:
    """Merged tail across one run's streams (:func:`stream_paths`):
    the newest ``limit`` records across the main stream AND every shard
    sub-stream, ordered by ``(t, pid, seq)`` — wall time first, then
    pid and per-process seq so cross-process ties interleave
    DETERMINISTICALLY (tests/test_trace.py pins the order).  When a
    stream carries ``trace.skew`` records (the wire clock handshake),
    each emitter's ``t`` is skew-corrected before ordering; without
    them, wall clocks are trusted as-is — the documented caveat for
    multi-host runs without the tcp transport.  Each record carries a
    ``_stream`` key naming its source (``"main"`` or ``"shard<k>"``)
    so a merged view stays attributable.  A run with no sub-streams
    reduces to :func:`tail_events` plus the ``_stream`` annotation."""
    labelled: list[dict] = []
    for path in stream_paths(events_path):
        label = os.path.basename(os.path.dirname(path))
        if path == events_path:
            label = "main"
        for rec in tail_events(path, limit=limit, tail_bytes=tail_bytes):
            labelled.append({**rec, "_stream": label})
    offsets = skew_offsets(labelled)
    merged = []
    for rec in labelled:
        off = offsets.get((rec["_stream"], rec.get("pid")), 0.0)
        merged.append((rec.get("t", 0.0) + off, rec.get("pid") or 0,
                       rec.get("seq", 0), rec))
    merged.sort(key=lambda r: (r[0], r[1], r[2]))
    return [rec for _t, _p, _s, rec in merged[-limit:]]


class EventFollower:
    """Incremental reader of one events.jsonl stream — the counterpart
    of :func:`tail_events` for consumers that poll repeatedly (the
    serving daemon's ``/result?stream=1`` transport, the load harness
    watching ``serve.done`` for daemon-side completion times): each
    ``poll()`` costs O(new bytes), never a re-read of the tail."""

    def __init__(self, path: str, *, tail_bytes: int | None = None):
        """``tail_bytes`` bounds the FIRST read to the file's last N
        bytes (opening a follower on a long-lived events file reads a
        bounded backlog, then goes incremental); a torn first line is
        dropped by the JSON parse."""
        self.path = path
        self._pos = 0
        self._buf = b""
        self._tail_bytes = tail_bytes
        self._primed = tail_bytes is None

    def poll(self, *, contains: bytes | None = None) -> list[dict]:
        """Records appended since the last poll (torn tails wait for the
        next poll).  ``contains`` pre-filters raw lines by substring
        BEFORE the JSON parse — a consumer watching one event kind on a
        busy stream (e.g. ``b'"serve.chunk"'``) skips the parse cost of
        everything else."""
        try:
            with open(self.path, "rb") as f:
                if not self._primed:
                    f.seek(0, os.SEEK_END)
                    self._pos = max(0, f.tell() - int(self._tail_bytes))
                    self._primed = True
                f.seek(self._pos)
                data = f.read()
                self._pos = f.tell()
        except OSError:
            return []
        if not data:
            return []
        self._buf += data
        out = []
        while b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            if contains is not None and contains not in line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out


# -------------------------------------------------------------- snapshots
def snapshot() -> dict:
    """The current metrics registry as one JSON-able dict
    (``{"active": False}`` when no bus is open)."""
    bus = _current()
    if bus is None:
        return {"active": False}
    return bus.snapshot()


def _write_snapshot_locked(bus: _Bus, name: str | None = None) -> str | None:
    if not bus.run_dir:
        return None
    path = os.path.join(bus.run_dir, name or METRICS_FILE)
    try:
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(bus.snapshot(), f, indent=1, default=_jsonable)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def write_snapshot(name: str | None = None) -> str | None:
    """Persist the metrics registry as ``<run_dir>/metrics.json``
    (atomic tmp+rename).  Returns the path, or None when memory-only /
    no bus / write failure.  ``name`` overrides the file name — pass a
    distinct one when several processes share a stream dir and each
    wants its own snapshot (bench children on a supervised pass), since
    the default is last-writer-wins."""
    bus = _current()
    if bus is None:
        return None
    return _write_snapshot_locked(bus, name)


def selftest() -> dict:
    """Doctor's plumbing check: a throwaway bus in a temp dir, one emit,
    one metric, parse the line back.  Never touches the process bus."""
    import tempfile

    with tempfile.TemporaryDirectory(prefix="dragg_tel_") as d:
        bus = _Bus(d)
        try:
            bus.emit("telemetry.selftest", {"ok": True})
            with bus.lock:
                bus.hists.setdefault("probe.elapsed_s", _Hist()).observe(0.0)
            with open(bus.path) as f:
                rec = json.loads(f.read().strip().splitlines()[-1])
            ok = rec["event"] == "telemetry.selftest" and rec["seq"] == 1
            return {"ok": ok, "events": len(registry.EVENTS),
                    "metrics": len(registry.METRICS)}
        finally:
            bus.close()
