"""The central telemetry name registry — every event and metric name, in
one literal table.

Rationale (round 7): the repo accumulated *pockets* of observability —
bench phase timers, resilience heartbeats, the probe --watch transcript,
per-home failure logs — each with its own ad-hoc format, so nothing
could be correlated across a run.  The registry is the contract that
keeps the unified stream analyzable: an emit with an unregistered name
raises at runtime, ``tools/lint.py`` rejects free-string names
statically, and ``docs/telemetry.md`` must document every entry (a test
enforces the doc coverage both ways).

Both tables are PURE LITERALS on purpose: the lint rule reads them via
``ast`` without importing this package, so a computed key would be
invisible to it.  ``tests/test_telemetry.py`` asserts the ``failure.*``
entries stay in sync with :data:`dragg_tpu.resilience.taxonomy.FAILURE_KINDS`.
"""

from __future__ import annotations

# Event name -> one-line semantics.  Field names in parentheses are the
# payload keys the emitter attaches beyond the envelope (t/mono/pid/seq).
EVENTS: dict[str, str] = {
    "run.start": "simulation run began (case, homes, horizon, solver, "
                 "run_dir)",
    "run.end": "simulation run finished (timestep, num_timesteps, "
               "elapsed_s, completed)",
    "chunk.done": "one device scan chunk finished (t0, t1, n_steps, "
                  "device_s, steps_per_s, solve_rate, solver_iters, "
                  "r_prim_max, r_dual_max, repair_failed)",
    "span": "a telemetry.span() block closed (name = the histogram "
            "metric it observed, s = seconds)",
    "bench.result": "one benchmark headline artifact mirrored onto the "
                    "stream (result = the bench.py JSON-line dict)",
    "probe.verdict": "classified tunnel liveness verdict (alive, kind, "
                     "detail, backend, proxy, compile_helper, elapsed_s)",
    "heartbeat.beat": "child progress beat under supervision (progress "
                      "payload, if any)",
    "supervisor.launch": "supervised child launched (label, pid, "
                         "deadline_s, stall_s)",
    "supervisor.exit": "supervised child exited (label, rc, ok, failure, "
                       "timed_out, stalled, elapsed_s, progress = the "
                       "child's last heartbeat payload — names the stage "
                       "a stall-killed child was in)",
    "degrade.transition": "degradation policy moved platforms "
                          "(from_platform, to_platform, "
                          "resumed_from_timestep, failure)",
    "telemetry.selftest": "doctor plumbing check event (written to a "
                          "throwaway dir only)",
    # Observatory layer (round 9): per-home solver attribution folded on
    # device (engine._per_home_obs) and emitted per chunk by the
    # aggregator, plus the staged-compile spans (telemetry/compile_obs).
    "solver.convergence": "one bucket's per-chunk convergence attribution "
                          "(t0, t1, bucket, n_homes, rprim_hist, "
                          "iters_hist, mean_iters, diverged — histogram "
                          "bin edges in docs/telemetry.md)",
    "solver.worst": "the chunk's worst-k homes by final primal residual "
                    "(t0, t1, homes = [{home, bucket, t, r_prim, r_dual, "
                    "iters}])",
    "solver.diverged": "a chunk contained certified-diverged homes (t0, "
                       "t1, total, by_bucket)",
    "compile.stage": "one staged-compile stage closed (label, stage = "
                     "lower|compile|first_execute, s, buckets = pattern "
                     "shape keys)",
    "compile.done": "a staged compile finished (label, total_s, cache = "
                    "hit|miss|unknown, stages = {name: s}, buckets)",
    # Serving daemon (dragg_tpu/serve — ISSUE 7).  The request lifecycle
    # mirrors the journal states (serve/journal.py), so the event stream
    # and the fsync'd journal tell one story.
    "serve.request": "serving daemon accepted + journaled one request "
                     "(id, timestep, home)",
    "serve.assign": "one coalesced batch dispatched to a worker slot "
                    "(batch, slot, gen, n, groups, occupancy, timestep, "
                    "steps, pattern, window_wait_s)",
    "serve.chunk": "one incremental per-step result of a multi-chunk "
                   "request, emitted by the worker and served over "
                   "/result?stream=1 (id, step, steps, timestep, + the "
                   "response fields)",
    "serve.pattern": "a pattern lane came up — configured at boot, "
                     "compile-on-demand spill, or journal replay (name, "
                     "signature, source = config|spill|replay, workers, "
                     "fleet_slots)",
    "serve.stream": "a streaming /result?stream=1 connection closed "
                    "(id, chunks, terminal, elapsed_s)",
    "serve.done": "one request answered and journaled terminal (id, "
                  "batch, platform, degraded)",
    "serve.failed": "one request failed terminally (id, reason, retries)",
    "serve.reject": "admission pushed back — 429 backpressure (id, "
                    "reason = queue_full|probe_down|pattern_capacity|"
                    "stream_capacity, retry_after_s)",
    "serve.replay": "journal replay at daemon start (requeued, terminal, "
                    "dropped_lines)",
    "serve.worker.launch": "worker slot launched a generation (slot, gen, "
                           "pid, platform, stub)",
    "serve.worker.ready": "a worker generation finished warmup (slot, "
                          "gen, platform, warmup_s, cache = the staged-"
                          "compile persistent-cache verdict)",
    "serve.worker.exit": "a worker generation died (slot, gen, rc, "
                         "failure = taxonomy kind, ready)",
    "serve.drain": "graceful drain began (queue = outstanding requests)",
    "serve.error": "serving dispatch loop survived an internal error "
                   "(error)",
    # Cross-process fleet sharding (dragg_tpu/shard — architecture.md
    # §19).  The coordinator's lifecycle mirrors the shard journal
    # states (shard/journal.py), so the event stream and the fsync'd
    # journal tell one story; worker-side engine events land on
    # per-shard sub-streams (shard<k>/events.jsonl — slots.py).
    "shard.plan": "shard run planned/resumed (communities, workers, "
                  "ranges, steps, chunk_steps, target_t, resumed)",
    "shard.launch": "shard worker generation launched (shard, gen, pid, "
                    "platform)",
    "shard.chunk": "one shard chunk merged + journal-acked (shard, seq, "
                   "t0, t1, solve_rate, device_s)",
    "shard.exit": "a shard worker generation died (shard, gen, rc, "
                  "failure = taxonomy kind)",
    "shard.transition": "one shard degraded platforms independently "
                        "(shard, from_platform, to_platform)",
    "shard.done": "a shard reached the target frontier (shard, chunks)",
    "shard.merge": "the merged fleet result assembled (communities, "
                   "workers, steps, solve_rate, restarts, elapsed_s)",
    # Networked shard transport (shard/transport.py — architecture.md
    # §20).  Client-side events land on the worker's per-shard
    # sub-stream; server-side events land on the coordinator's stream.
    "wire.push": "wire client delivered one chunk frame (shard, seq, "
                 "dup = server already had it, attempts)",
    "wire.ingest": "chunk-ingest server accepted one frame (shard, seq, "
                   "dup, bytes) — journal-acked BEFORE the 200",
    "wire.fence": "chunk-ingest server refused a stale-epoch push "
                  "(shard, seq, got, want)",
    "wire.reject": "chunk-ingest server discarded a torn/invalid frame "
                   "whole (reason, bytes)",
    "wire.degrade": "wire client fell back (sticky) to the shared spool "
                    "after the retry budget (shard, after_s, attempts)",
    # Fleet trace plane (ISSUE 20).  The trace/span/parent fields ride
    # EVERY event's envelope when tracing is on (telemetry/trace.py);
    # trace.skew is the wire clock handshake's per-process correction.
    "trace.skew": "wire client measured its wall-clock offset against "
                  "the coordinator's /clock (shard, offset_s, rtt_s) — "
                  "merged ordering and the trace assembler apply it",
    # The resilience failure taxonomy as event types (one per kind in
    # taxonomy.FAILURE_KINDS; ``source`` says which layer classified it:
    # "probe" or "supervisor", ``detail``/``label`` locate it).
    "failure.TUNNEL_DOWN": "classified failure: tunnel unreachable "
                           "(taxonomy TUNNEL_DOWN)",
    "failure.WEDGED": "classified failure: round-4 wedge signature "
                      "(taxonomy WEDGED)",
    "failure.COMPILE_HANG": "classified failure: heartbeat went stale, "
                            "child killed early (taxonomy COMPILE_HANG)",
    "failure.VMEM_OOM": "classified failure: scoped-VMEM OOM signature "
                        "(taxonomy VMEM_OOM)",
    "failure.CHILD_CRASH": "classified failure: abnormal child death "
                           "(taxonomy CHILD_CRASH)",
    "failure.DEADLINE": "classified failure: still beating at the hard "
                        "deadline (taxonomy DEADLINE)",
}

# Metric name -> (kind, one-line semantics).  Kinds: "counter" (monotone
# sum), "gauge" (last value wins), "histogram" (count/sum/min/max/mean +
# a bounded sample tail; span() observes into histograms).
METRICS: dict[str, tuple[str, str]] = {
    "engine.chunk_device_s": ("histogram",
                              "device wall seconds per scan chunk"),
    "engine.chunk_steps_per_s": ("histogram",
                                 "achieved sim-timesteps/s per chunk"),
    "engine.collect_s": ("histogram",
                         "host collect seconds per chunk"),
    "engine.overlap_hidden_s": ("histogram",
                                "host collect/checkpoint seconds per "
                                "chunk PROVABLY hidden behind the next "
                                "chunk's device execution (pipeline "
                                "lower bound — aggregator.run_baseline)"),
    "engine.solve_iters": ("histogram",
                           "mean solver iterations per step (one sample "
                           "per chunk)"),
    "engine.solve_rate": ("gauge", "latest chunk mean solve rate"),
    "engine.r_prim_max": ("gauge",
                          "latest chunk max primal residual (f32-max "
                          "sentinel = a home diverged non-finite)"),
    "engine.r_dual_max": ("gauge", "latest chunk max dual residual"),
    "engine.repair_failed": ("counter",
                             "cumulative homes whose integer-pin repair "
                             "failed (kept the relaxed action)"),
    "sim.timestep": ("gauge", "latest completed sim timestep"),
    "bench.warmup_s": ("histogram",
                       "bench warmup (compile) chunk seconds"),
    "bench.chunk_s": ("histogram", "bench timed chunk seconds"),
    "bench.phase.assemble_s": ("histogram",
                               "bench assemble-phase seconds per step"),
    "bench.phase.solve_s": ("histogram",
                            "bench solve-phase seconds per step (ipm — "
                            "no factor cache, one honest key)"),
    "bench.phase.solve_refresh_s": ("histogram",
                                    "bench solve-phase seconds per step, "
                                    "exact refactorization (admm)"),
    "bench.phase.solve_cached_s": ("histogram",
                                   "bench solve-phase seconds per step, "
                                   "cached factor (admm)"),
    "bench.phase.merge_collect_s": ("histogram",
                                    "bench merge/collect-phase seconds "
                                    "per step"),
    # Type-bucketed engine (tpu.bucketed): per-bucket solve-phase seconds
    # per step, one literal per home type (separately-jitted bucket solve
    # — engine.bucket_solve_fns; absent buckets simply never observe).
    "bench.phase.solve_pv_battery_s": ("histogram",
                                       "bench pv_battery-bucket solve "
                                       "seconds per step (bucketed)"),
    "bench.phase.solve_pv_only_s": ("histogram",
                                    "bench pv_only-bucket solve seconds "
                                    "per step (bucketed)"),
    "bench.phase.solve_battery_only_s": ("histogram",
                                         "bench battery_only-bucket solve "
                                         "seconds per step (bucketed)"),
    "bench.phase.solve_base_s": ("histogram",
                                 "bench base-bucket solve seconds per "
                                 "step (bucketed)"),
    "bench.phase.solve_ev_s": ("histogram",
                               "bench ev-bucket solve seconds per step "
                               "(bucketed; scenario type)"),
    "bench.phase.solve_heat_pump_s": ("histogram",
                                      "bench heat_pump-bucket solve "
                                      "seconds per step (bucketed; "
                                      "scenario type)"),
    "bench.rate_ts_per_s": ("gauge", "headline sim-timesteps/s"),
    "bench.flops_per_step": ("gauge",
                             "analytic FLOPs per sim step — the MFU "
                             "back-fill basis when the platform peak is "
                             "unknown"),
    "probe.elapsed_s": ("histogram", "liveness probe wall seconds"),
    "supervisor.child_s": ("histogram", "supervised child wall seconds"),
    # Observatory layer (round 9): one per-bucket literal per home type
    # (the bench.phase.solve_<type>_s precedent) — mean per-home
    # convergence iterations per chunk, from the device-side fold.
    "solver.conv_iters_pv_battery": ("histogram",
                                     "mean per-home convergence iterations "
                                     "per chunk, pv_battery bucket"),
    "solver.conv_iters_pv_only": ("histogram",
                                  "mean per-home convergence iterations "
                                  "per chunk, pv_only bucket"),
    "solver.conv_iters_battery_only": ("histogram",
                                       "mean per-home convergence "
                                       "iterations per chunk, battery_only "
                                       "bucket"),
    "solver.conv_iters_base": ("histogram",
                               "mean per-home convergence iterations per "
                               "chunk, base bucket"),
    "solver.conv_iters_ev": ("histogram",
                             "mean per-home convergence iterations per "
                             "chunk, ev bucket (scenario type)"),
    "solver.conv_iters_heat_pump": ("histogram",
                                    "mean per-home convergence iterations "
                                    "per chunk, heat_pump bucket "
                                    "(scenario type)"),
    "solver.conv_iters_superset": ("histogram",
                                   "mean per-home convergence iterations "
                                   "per chunk, unbucketed superset batch"),
    "solver.diverged_homes": ("counter",
                              "cumulative certified-diverged home-steps "
                              "(per-home divergence flag from the solver)"),
    "solver.worst_rprim": ("gauge",
                           "worst home's final primal residual in the "
                           "latest chunk"),
    "compile.stage_s": ("histogram",
                        "staged-compile stage wall seconds (stage name on "
                        "the paired compile.stage event)"),
    # Serving daemon (dragg_tpu/serve — ISSUE 7).
    "serve.queue_depth": ("gauge",
                          "pending + assigned requests in the daemon"),
    "serve.request_latency_s": ("histogram",
                                "accept→answer wall seconds per request"),
    "serve.batch_s": ("histogram",
                      "worker-reported solve seconds per dispatched batch"),
    "serve.requests_done": ("counter", "requests answered terminally"),
    "serve.requests_failed": ("counter",
                              "requests failed terminally (deadline / "
                              "retries exhausted)"),
    "serve.requests_rejected": ("counter",
                                "admissions pushed back with 429"),
    "serve.request_retries": ("counter",
                              "request re-dispatches after worker deaths"),
    "serve.worker_restarts": ("counter",
                              "worker relaunches beyond each slot's first "
                              "generation"),
    # Fleet-backed coalescing serving (ISSUE 13).
    "serve.batch_occupancy": ("histogram",
                              "filled community slots / fleet_slots per "
                              "dispatched batch (1.0 = every slot of the "
                              "warm fleet solve carried a request group)"),
    "serve.coalesced_requests": ("histogram",
                                 "requests folded into one dispatched "
                                 "fleet batch (coalescing efficiency = "
                                 "mean of this / solve)"),
    "serve.batch_window_wait_s": ("histogram",
                                  "oldest request's wait inside the "
                                  "coalescing window at dispatch "
                                  "(serve.batch_window_ms latency cost, "
                                  "measured)"),
    "serve.first_chunk_latency_s": ("histogram",
                                    "accept -> first streamed chunk wall "
                                    "seconds for /result?stream=1 "
                                    "consumers"),
    "serve.streams": ("counter",
                      "streaming /result?stream=1 connections served"),
    "serve.streams_rejected": ("counter",
                               "streaming connections answered 429 past "
                               "the serve.max_streams cap"),
    "serve.spill_lanes": ("counter",
                          "compile-on-demand pattern lanes created for "
                          "unseen bucket-pattern signatures"),
    "serve.patterns_active": ("gauge",
                              "pattern lanes currently holding worker "
                              "slots (default + configured + spill)"),
    # Cross-process fleet sharding (dragg_tpu/shard — architecture.md
    # §19).
    "shard.restarts": ("counter",
                       "shard worker relaunches beyond each shard's "
                       "first generation"),
    "shard.chunk_s": ("histogram",
                      "worker-reported device seconds per merged shard "
                      "chunk"),
    "wire.push_s": ("histogram",
                    "wall seconds per chunk push, first attempt to "
                    "durable ack (retries included)"),
    "wire.retries": ("counter",
                     "failed chunk-push attempts retried by the wire "
                     "client (at-least-once delivery)"),
    "wire.dedup": ("counter",
                   "duplicate chunk frames acked without re-merge by the "
                   "chunk-ingest server (at-least-once deliveries caught "
                   "by the (epoch, shard, chunk) token)"),
}


def check_event(name: str) -> None:
    if name not in EVENTS:
        raise ValueError(
            f"unregistered telemetry event {name!r} — register it in "
            f"dragg_tpu/telemetry/registry.py (and docs/telemetry.md)")


def check_metric(name: str, kind: str) -> None:
    got = METRICS.get(name)
    if got is None:
        raise ValueError(
            f"unregistered telemetry metric {name!r} — register it in "
            f"dragg_tpu/telemetry/registry.py (and docs/telemetry.md)")
    if got[0] != kind:
        raise ValueError(
            f"telemetry metric {name!r} is registered as a {got[0]}, "
            f"used as a {kind}")
