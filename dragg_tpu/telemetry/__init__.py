"""Unified run telemetry: event bus + metrics registry + span API.

Round-7 tentpole.  One substrate for every observability pocket the repo
grew separately — bench phase timers, resilience heartbeats and
classified probe verdicts, the ``--watch`` outage transcript, per-chunk
solver telemetry — so a run leaves ONE correlated forensic record:
``<run_dir>/events.jsonl`` (append-only typed events) plus a final
``metrics.json`` snapshot, both under the central name registry
(:mod:`~dragg_tpu.telemetry.registry`; ``docs/telemetry.md`` documents
every name, ``tools/lint.py`` rejects free-string names).

Usage::

    from dragg_tpu import telemetry

    telemetry.init_run(run_dir)            # or $DRAGG_TELEMETRY_DIR joins lazily
    telemetry.emit("chunk.done", t0=0, t1=24, solve_rate=1.0)
    with telemetry.span("engine.chunk_device_s"):
        ...device work...
    telemetry.write_snapshot()             # <run_dir>/metrics.json
    telemetry.close_run()

Stdlib-only by contract: the jax-free resilience parents emit through
this module, so importing it must never initialize a jax backend.
"""

from dragg_tpu.telemetry import rollup, trace, traces
from dragg_tpu.telemetry.bus import (
    ENV_DIR,
    ENV_FLUSH,
    EVENTS_FILE,
    METRICS_FILE,
    EventFollower,
    active,
    close_run,
    emit,
    events_path,
    inc,
    init_run,
    observe,
    run_dir,
    selftest,
    set_gauge,
    skew_offsets,
    snapshot,
    span,
    stream_paths,
    tail_events,
    tail_events_dir,
    write_snapshot,
)
from dragg_tpu.telemetry.registry import EVENTS, METRICS

__all__ = [
    "ENV_DIR", "ENV_FLUSH", "EVENTS_FILE", "METRICS_FILE", "EVENTS",
    "METRICS", "EventFollower",
    "active", "close_run", "emit", "events_path", "inc", "init_run",
    "observe", "rollup", "run_dir", "selftest", "set_gauge",
    "skew_offsets", "snapshot", "span", "stream_paths", "tail_events",
    "tail_events_dir", "trace", "traces", "write_snapshot",
]
