"""Causal trace context — run-scoped ``trace_id`` plus per-process
``span_id``/``parent_id`` riding the existing event envelope (ISSUE 20
tentpole).  Stdlib only, jax-free, and ZERO-COST when tracing is off:
with no context enabled and ``$DRAGG_TRACE_CTX`` unset, every entry
point is one module-global load, and the bus adds NO fields to emitted
records — off-mode ``events.jsonl`` streams stay byte-identical to the
round-19 envelope (tests/test_trace.py pins it).

The context is process-wide (one root span per process, like the bus
itself) and crosses process boundaries three ways, mirroring how the
telemetry dir already travels:

* **env** — a parent exports ``$DRAGG_TRACE_CTX = "<trace>:<span>"``
  (``env_value()``); the child joins LAZILY on its first emit
  (``current()``), minting its own process span with the exported span
  as parent.  The resilience supervisor and the shard/serve slot
  launchers do this export.
* **HTTP** — the serve daemon answers ``X-Dragg-Trace`` /
  ``X-Dragg-Span`` response headers and records a client-supplied
  ``X-Dragg-Parent`` on the request's ``serve.request`` record as
  ``client_parent`` (informational — the in-stream tree stays rooted
  at the daemon even when the client's span never appears in it).
* **wire** — the trace fields ride the DRGW frame's JSON doc body
  (no codec change), so a chunk pushed over TCP carries its span to
  the coordinator's merge.

Emitters open FINER spans explicitly by splatting
``**trace.child_fields()`` into an emit — the bus's envelope injection
uses ``setdefault``, so explicit span/parent fields always win over the
process root context.
"""

from __future__ import annotations

import os
import threading
import uuid

ENV_CTX = "DRAGG_TRACE_CTX"  # "<trace_id>:<parent_span_id>"

_ctx: dict | None = None
_env_checked = False
_lock = threading.Lock()


def _new_id(n: int) -> str:
    return uuid.uuid4().hex[:n]


def new_span() -> str:
    """A fresh span id (callers link it to a parent explicitly)."""
    return _new_id(12)


def enable(trace_id: str | None = None,
           parent: str | None = None) -> dict:
    """Open this process's trace context: adopt (or mint) the run-scoped
    trace id and mint the process root span.  Returns a copy of the
    context ``{"trace", "span", "parent"}``."""
    global _ctx, _env_checked
    with _lock:
        _ctx = {"trace": trace_id or _new_id(16),
                "span": _new_id(12),
                "parent": parent}
        _env_checked = True
        return dict(_ctx)


def disable() -> None:
    """Drop the context and re-arm the ``$DRAGG_TRACE_CTX`` auto-join
    (the :func:`telemetry.close_run` counterpart for tests)."""
    global _ctx, _env_checked
    with _lock:
        _ctx = None
        _env_checked = False


def current() -> dict | None:
    """The active context, joining ``$DRAGG_TRACE_CTX`` lazily on first
    use — how supervised children (which never call :func:`enable`)
    land inside the parent's trace.  None = tracing off."""
    global _ctx, _env_checked
    ctx = _ctx
    if ctx is not None or _env_checked:
        return ctx
    with _lock:
        if _ctx is None and not _env_checked:
            _env_checked = True
            raw = os.environ.get(ENV_CTX) or ""
            if ":" in raw:
                tid, _, parent = raw.partition(":")
                if tid:
                    _ctx = {"trace": tid, "span": _new_id(12),
                            "parent": parent or None}
        return _ctx


def enabled() -> bool:
    return current() is not None


def env_value(span: str | None = None) -> str | None:
    """The ``$DRAGG_TRACE_CTX`` export for a child whose root span
    should parent on ``span`` (default: this process's root span).
    None when tracing is off — callers then export nothing."""
    ctx = current()
    if ctx is None:
        return None
    return f"{ctx['trace']}:{span or ctx['span']}"


def child_fields(parent: str | None = None) -> dict:
    """Fields for an emit that opens a NEW child span: a fresh span id
    parented on ``parent`` (default: this process's root span).  Empty
    when tracing is off, so ``emit(..., **trace.child_fields())`` adds
    no keys to an untraced stream."""
    ctx = current()
    if ctx is None:
        return {}
    return {"span": _new_id(12), "parent": parent or ctx["span"]}


def span_fields(span: str, parent: str | None = None) -> dict:
    """Fields for an emit inside an EXISTING span (e.g. several events
    of one chunk span).  Empty when tracing is off."""
    ctx = current()
    if ctx is None:
        return {}
    out = {"span": span}
    if parent is not None:
        out["parent"] = parent
    return out
