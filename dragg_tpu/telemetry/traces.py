"""Trace assembler — causal trees + critical-path attribution from a
run's merged event streams (ISSUE 20 tentpole; CLI in
tools/trace_view.py).

A *span* is declared implicitly: any event record carrying a ``span``
field extends that span's node; the first ``parent`` seen for a span id
fixes its tree edge.  The assembler reads the FULL main stream plus
every ``shard<k>`` sub-stream (not the bounded tails the dashboard
uses), applies the wire clock-skew correction (``trace.skew`` records),
groups spans by ``trace`` id, and reports:

* per-trace causal trees (roots = spans with no parent, orphans =
  spans whose parent id never appears — a complete trace has >= 1 root
  and ZERO orphans, the acceptance invariant);
* critical-path attribution — seconds bucketed into queue / compile /
  device / collect / wire / merge / other from the duration fields the
  instrumented layers already emit;
* an ASCII timeline (one bar per span, indented by tree depth).

Everything here is stdlib-only and jax-free: post-mortems run in the
same un-wedgeable parents as the rest of the resilience layer.
"""

from __future__ import annotations

import json
import os

from dragg_tpu.telemetry import bus

# Duration fields -> attribution bucket.  Each entry names (event,
# field) pairs whose values are seconds spent in that phase; the
# emitting layers are cited so the mapping stays auditable.
ATTRIBUTION = {
    # oldest request's wait inside the coalescing window (serve daemon)
    "queue": (("serve.assign", "window_wait_s"),),
    # staged-compile stage seconds (telemetry/compile_obs)
    "compile": (("compile.stage", "s"),),
    # device wall seconds per engine chunk (aggregator / shard worker)
    "device": (("chunk.done", "device_s"),),
    # host collect seconds per chunk (span event over engine.collect_s)
    "collect": (("span:engine.collect_s", "s"),),
    # wire client push wall seconds, retries included (shard/transport)
    "wire": (("wire.push", "s"),),
    # coordinator merge seconds per shard chunk (shard/coordinator)
    "merge": (("shard.chunk", "s"),),
}


def read_records(run_dir: str) -> list[dict]:
    """Every parseable record of a run's streams (main + shard
    sub-streams), each labelled ``_stream``, ordered by the same
    skew-corrected ``(t, pid, seq)`` key as
    :func:`telemetry.tail_events_dir` — but over the FULL files."""
    events_path = os.path.join(run_dir, bus.EVENTS_FILE)
    labelled: list[dict] = []
    for path in bus.stream_paths(events_path):
        label = os.path.basename(os.path.dirname(path))
        if path == events_path:
            label = "main"
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn mid-write tail
            if isinstance(rec, dict):
                labelled.append({**rec, "_stream": label})
    offsets = bus.skew_offsets(labelled)
    labelled.sort(key=lambda r: (
        r.get("t", 0.0) + offsets.get((r["_stream"], r.get("pid")), 0.0),
        r.get("pid") or 0, r.get("seq", 0)))
    return labelled


def _event_key(rec: dict) -> str:
    """The ATTRIBUTION lookup key: span events are keyed by the metric
    they observed (``span:<name>``), everything else by event name."""
    if rec.get("event") == "span":
        return f"span:{rec.get('name')}"
    return str(rec.get("event"))


def _bucket_seconds(rec: dict) -> tuple[str, float] | None:
    key = _event_key(rec)
    for bucket, pairs in ATTRIBUTION.items():
        for ev, field in pairs:
            if key == ev and rec.get(field) is not None:
                try:
                    return bucket, float(rec[field])
                except (TypeError, ValueError):
                    return None
    return None


def assemble(records: list[dict]) -> dict:
    """Causal trees from labelled records.  Returns::

        {"traces": {trace_id: {"spans": {span_id: node},
                               "roots": [span_id...],
                               "orphans": [span_id...]}},
         "untraced": <records with no trace field>}

    where each node is ``{"span", "parent", "t0", "t1", "events":
    [event names], "streams": [...], "first": <first record>,
    "seconds": {bucket: s}}``."""
    traces: dict = {}
    untraced = 0
    for rec in records:
        tid = rec.get("trace")
        if tid is None:
            untraced += 1
            continue
        sid = rec.get("span")
        if sid is None:
            continue
        tr = traces.setdefault(tid, {"spans": {}, "roots": [],
                                     "orphans": []})
        node = tr["spans"].get(sid)
        if node is None:
            node = tr["spans"][sid] = {
                "span": sid, "parent": None, "t0": None, "t1": None,
                "events": [], "streams": [], "first": rec,
                "seconds": {}}
        if node["parent"] is None and rec.get("parent") is not None:
            node["parent"] = rec["parent"]
        t = rec.get("t")
        if t is not None:
            node["t0"] = t if node["t0"] is None else min(node["t0"], t)
            node["t1"] = t if node["t1"] is None else max(node["t1"], t)
        node["events"].append(str(rec.get("event")))
        if rec["_stream"] not in node["streams"]:
            node["streams"].append(rec["_stream"])
        hit = _bucket_seconds(rec)
        if hit is not None:
            b, s = hit
            node["seconds"][b] = node["seconds"].get(b, 0.0) + s
    for tr in traces.values():
        spans = tr["spans"]
        for sid, node in spans.items():
            if node["parent"] is None:
                tr["roots"].append(sid)
            elif node["parent"] not in spans:
                tr["orphans"].append(sid)
    return {"traces": traces, "untraced": untraced}


def _children(tr: dict) -> dict:
    kids: dict = {}
    for sid, node in tr["spans"].items():
        if node["parent"] in tr["spans"]:
            kids.setdefault(node["parent"], []).append(sid)
    for v in kids.values():
        v.sort(key=lambda s: (tr["spans"][s]["t0"] or 0.0, s))
    return kids


def critical_path(tr: dict) -> dict:
    """The root-to-leaf chain with the largest attributed seconds, plus
    the whole trace's per-bucket attribution.  Chains are weighted by
    the sum of their nodes' bucketed seconds (falling back to span wall
    extent for unattributed spans), so the answer names WHERE the time
    went, not just which subtree had the most events."""
    kids = _children(tr)

    def node_w(node: dict) -> float:
        s = sum(node["seconds"].values())
        if s:
            return s
        if node["t0"] is not None and node["t1"] is not None:
            return node["t1"] - node["t0"]
        return 0.0

    best_chain: list[str] = []
    best_w = -1.0

    def walk(sid: str, chain: list[str], w: float) -> None:
        nonlocal best_chain, best_w
        chain = chain + [sid]
        w += node_w(tr["spans"][sid])
        if sid not in kids:
            if w > best_w:
                best_w, best_chain = w, chain
            return
        for kid in kids[sid]:
            walk(kid, chain, w)

    for root in tr["roots"]:
        walk(root, [], 0.0)
    total: dict = {}
    for node in tr["spans"].values():
        for b, s in node["seconds"].items():
            total[b] = total.get(b, 0.0) + s
    path_secs: dict = {}
    for sid in best_chain:
        for b, s in tr["spans"][sid]["seconds"].items():
            path_secs[b] = path_secs.get(b, 0.0) + s
    return {"path": best_chain,
            "path_seconds": {b: round(s, 6) for b, s in path_secs.items()},
            "path_total_s": round(max(best_w, 0.0), 6),
            "trace_seconds": {b: round(s, 6) for b, s in total.items()}}


def render_ascii(tr: dict, width: int = 60) -> str:
    """One bar per span, indented by depth, scaled to the trace extent."""
    spans = tr["spans"]
    if not spans:
        return "(empty trace)"
    t0s = [n["t0"] for n in spans.values() if n["t0"] is not None]
    t1s = [n["t1"] for n in spans.values() if n["t1"] is not None]
    lo, hi = (min(t0s), max(t1s)) if t0s else (0.0, 1.0)
    extent = max(hi - lo, 1e-9)
    kids = _children(tr)
    lines = []

    def bar(node: dict) -> str:
        if node["t0"] is None:
            return " " * width
        a = int((node["t0"] - lo) / extent * (width - 1))
        b = int((node["t1"] - lo) / extent * (width - 1))
        return " " * a + "#" * max(1, b - a + 1) + " " * (width - 1 - b)

    def walk(sid: str, depth: int) -> None:
        node = spans[sid]
        label = f"{'  ' * depth}{sid} [{node['events'][0]}"
        if len(node["events"]) > 1:
            label += f" +{len(node['events']) - 1}"
        label += "]"
        secs = " ".join(f"{b}={s:.3f}s"
                        for b, s in sorted(node["seconds"].items()))
        lines.append(f"{label:<44.44} |{bar(node)}| {secs}")
        for kid in kids.get(sid, []):
            walk(kid, depth + 1)

    for root in sorted(tr["roots"],
                       key=lambda s: (spans[s]["t0"] or 0.0, s)):
        walk(root, 0)
    for orphan in tr["orphans"]:
        node = spans[orphan]
        lines.append(f"ORPHAN {orphan} (parent {node['parent']}) "
                     f"[{node['events'][0]}]")
    return "\n".join(lines)


def trace_report(run_dir: str, records: list[dict] | None = None) -> dict:
    """The JSON artifact: every trace's tree summary, critical path,
    and completeness verdict for one run directory.  Pass ``records``
    (from :func:`read_records`) to avoid a second full-stream read."""
    if records is None:
        records = read_records(run_dir)
    asm = assemble(records)
    out = {"run_dir": run_dir, "records": len(records),
           "untraced_records": asm["untraced"], "traces": {}}
    for tid, tr in asm["traces"].items():
        out["traces"][tid] = {
            "spans": len(tr["spans"]),
            "roots": tr["roots"],
            "orphans": tr["orphans"],
            "complete": bool(tr["roots"]) and not tr["orphans"],
            "critical_path": critical_path(tr),
        }
    out["complete"] = bool(out["traces"]) and all(
        t["complete"] for t in out["traces"].values())
    return out


def completeness_problems(report: dict) -> list[str]:
    """Human-readable reasons a report fails the zero-orphan invariant
    (empty list = complete)."""
    problems = []
    if not report["traces"]:
        problems.append("no traced records found (tracing off?)")
    for tid, tr in report["traces"].items():
        if not tr["roots"]:
            problems.append(f"trace {tid}: no root span")
        if tr["orphans"]:
            problems.append(
                f"trace {tid}: {len(tr['orphans'])} orphan span(s): "
                f"{tr['orphans'][:5]}")
    return problems


def phase_breakdown(records: list[dict], ids) -> dict:
    """Per-request phase decomposition for the serving tools: for each
    request id, seconds spent in queue (accept -> batch dispatch,
    including the coalescing window), solve (dispatch -> terminal
    answer), stream (streamed-connection lifetime), and compile
    (staged-compile stages overlapping the request's solve window —
    spill-lane compiles land here).  Built from the daemon's own
    records, so SLO gating can name the guilty phase server-side."""
    ids = set(ids)
    accept_t: dict = {}
    done: dict = {}        # id -> (t, batch)
    assigns: dict = {}     # batch -> (t, window_wait_s)
    stream_s: dict = {}
    compiles: list = []    # (t, s)
    for rec in records:
        ev = rec.get("event")
        if ev == "serve.request" and rec.get("id") in ids:
            accept_t[rec["id"]] = rec.get("t")
        elif ev == "serve.assign":
            assigns[rec.get("batch")] = (rec.get("t"),
                                         float(rec.get("window_wait_s")
                                               or 0.0))
        elif ev == "serve.done" and rec.get("id") in ids:
            done[rec["id"]] = (rec.get("t"), rec.get("batch"))
        elif ev == "serve.stream" and rec.get("id") in ids:
            stream_s[rec["id"]] = float(rec.get("elapsed_s") or 0.0)
        elif ev == "compile.stage" and rec.get("s") is not None:
            compiles.append((rec.get("t"), float(rec["s"])))
    out = {}
    for rid, (t_done, batch) in done.items():
        t_acc = accept_t.get(rid)
        t_asn, _wait = assigns.get(batch, (None, 0.0))
        phases = {"queue_s": None, "solve_s": None,
                  "stream_s": stream_s.get(rid), "compile_s": 0.0}
        if t_acc is not None and t_asn is not None:
            phases["queue_s"] = max(0.0, t_asn - t_acc)
        if t_asn is not None and t_done is not None:
            phases["solve_s"] = max(0.0, t_done - t_asn)
            phases["compile_s"] = round(sum(
                s for tc, s in compiles
                if tc is not None and t_asn <= tc <= t_done), 6)
        out[rid] = phases
    return out
