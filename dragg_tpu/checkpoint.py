"""Checkpoint / resume of device state.

The reference only checkpoints its *outputs*: collected_data is re-serialized
to results.json every checkpoint interval (dragg/aggregator.py:776-778,
831-844) but a killed run must restart from t=0.  Here the carried device
state (the ``CommunityState`` scan carry — thermal/SoC state, fallback plans,
ADMM warm starts, PRNG key — and, for RL runs, the agent/environment carries)
is persisted alongside results.json, so a run resumes mid-simulation
bit-exactly: the same chunked ``lax.scan`` continues from the saved carry.

Format: one ``.npz`` with leaves in ``jax.tree_util.tree_flatten`` order.
Loading requires a template pytree with the same structure (engines and
agents can always rebuild their initial carries), which avoids serializing
tree structure and keeps the format dumb and portable.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np


def to_host(a) -> np.ndarray:
    """Fetch an array to host numpy, handling leaves sharded across
    processes: a multi-host global array is all-gathered (a collective —
    EVERY process must call this) before the local read."""
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(a, tiled=True))
    return np.asarray(a)


def save_pytree(path: str, tree) -> None:
    """Write a pytree of arrays as an npz (leaves in flatten order).
    Multi-host note: the gather runs on all processes; callers gate the
    actual file write with ``jax.process_index() == 0``."""
    leaves = jax.tree_util.tree_leaves(tree)
    arrays = {f"leaf_{i:04d}": to_host(l) for i, l in enumerate(leaves)}
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **arrays)
    os.replace(tmp, path)


def load_pytree(path: str, template):
    """Load an npz produced by :func:`save_pytree` into ``template``'s
    structure.  Shapes must match the template's leaves."""
    data = np.load(path)
    leaves, treedef = jax.tree_util.tree_flatten(template)
    # Sort numerically: lexicographic sort would interleave leaf_10000
    # between leaf_1000 and leaf_1001, silently permuting same-shaped leaves.
    keys = sorted(data.files, key=lambda k: int(k.rsplit("_", 1)[1]))
    if len(keys) != len(leaves):
        raise ValueError(
            f"Checkpoint {path} has {len(keys)} leaves; template has {len(leaves)}"
        )
    new_leaves = []
    for key, tmpl in zip(keys, leaves):
        arr = data[key]
        tshape = np.shape(tmpl)
        if tuple(arr.shape) != tuple(tshape):
            raise ValueError(
                f"Checkpoint leaf {key} shape {arr.shape} != template {tshape}"
            )
        if isinstance(tmpl, jax.Array):
            # Restore the template's placement in ONE transfer: a sharded
            # engine's state must come back with the SAME NamedSharding, or
            # the resumed chunk compiles a differently-partitioned program
            # whose fp reassociation breaks bit-exact resume.
            leaf = jax.device_put(arr.astype(tmpl.dtype), tmpl.sharding)
        else:
            leaf = jax.numpy.asarray(arr, dtype=np.asarray(tmpl).dtype)
        new_leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def save_progress(path: str, progress: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(progress, f)
    os.replace(tmp, path)


def load_progress(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
