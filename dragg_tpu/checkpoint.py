"""Checkpoint / resume of device state.

The reference only checkpoints its *outputs*: collected_data is re-serialized
to results.json every checkpoint interval (dragg/aggregator.py:776-778,
831-844) but a killed run must restart from t=0.  Here the carried device
state (the ``CommunityState`` scan carry — thermal/SoC state, fallback plans,
ADMM warm starts, PRNG key — and, for RL runs, the agent/environment carries)
is persisted alongside results.json, so a run resumes mid-simulation
bit-exactly: the same chunked ``lax.scan`` continues from the saved carry.

Format: one ``.npz`` with leaves in ``jax.tree_util.tree_flatten`` order.
Loading requires a template pytree with the same structure (engines and
agents can always rebuild their initial carries), which avoids serializing
tree structure and keeps the format dumb and portable.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np


def to_host(a, copy: bool = False) -> np.ndarray:
    """Fetch an array to host numpy, handling leaves sharded across
    processes: a multi-host global array is all-gathered (a collective —
    EVERY process must call this) before the local read.

    ``copy=True`` forces an owning deep copy.  ``np.asarray`` of a jax
    CPU array can be a zero-copy VIEW of the device buffer — fine for
    write-once reads, but a checkpoint snapshot taken under the
    aggregator's double-buffered pipeline must outlive the donated carry
    it was taken from (the next chunk's execution reuses those buffers;
    see :func:`host_snapshot`)."""
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(a, tiled=True))
    out = np.asarray(a)
    return np.array(out, copy=True) if copy else out


def host_snapshot(tree):
    """Deep host copy of a state pytree, safe to keep across a DONATED
    re-dispatch of the same carry (aggregator.run_baseline's pipeline:
    the snapshot is taken, then the carry's buffers are donated to chunk
    N+1, then the snapshot is checkpointed while N+1 runs).  Blocks until
    the leaves are computed — i.e. until the producing chunk finished."""
    return jax.tree_util.tree_map(lambda a: to_host(a, copy=True), tree)


def save_pytree(path: str, tree) -> None:
    """Write a pytree of arrays as an npz (leaves in flatten order).
    Multi-host note: the gather runs on all processes; callers gate the
    actual file write with ``jax.process_index() == 0``."""
    leaves = jax.tree_util.tree_leaves(tree)
    arrays = {f"leaf_{i:04d}": to_host(l) for i, l in enumerate(leaves)}
    tmp = path + ".tmp.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_pytree(path: str, template):
    """Load an npz produced by :func:`save_pytree` into ``template``'s
    structure.  Shapes must match the template's leaves."""
    with np.load(path) as data:
        leaves, treedef = jax.tree_util.tree_flatten(template)
        # Sort numerically: lexicographic sort would interleave leaf_10000
        # between leaf_1000 and leaf_1001, silently permuting same-shaped
        # leaves.
        keys = sorted(data.files, key=lambda k: int(k.rsplit("_", 1)[1]))
        if len(keys) != len(leaves):
            raise ValueError(
                f"Checkpoint {path} has {len(keys)} leaves; template has "
                f"{len(leaves)}"
            )
        new_leaves = []
        for key, tmpl in zip(keys, leaves):
            arr = data[key]
            tshape = np.shape(tmpl)
            if tuple(arr.shape) != tuple(tshape):
                raise ValueError(
                    f"Checkpoint leaf {key} shape {arr.shape} != template "
                    f"{tshape}"
                )
            if isinstance(tmpl, jax.Array):
                # Restore the template's placement in ONE transfer: a sharded
                # engine's state must come back with the SAME NamedSharding,
                # or the resumed chunk compiles a differently-partitioned
                # program whose fp reassociation breaks bit-exact resume.
                leaf = jax.device_put(arr.astype(tmpl.dtype), tmpl.sharding)
            else:
                leaf = jax.numpy.asarray(arr, dtype=np.asarray(tmpl).dtype)
            new_leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


# --------------------------------------------------- multi-host shard files
#
# Rank-0-only checkpoints (save_pytree above) require the whole global state
# gathered to one process and a shared filesystem at resume.  A TPU pod's
# workers usually have *separate* local disks, so instead each process dumps
# exactly its OWN addressable block of every sharded leaf — no collective at
# save time, and resume reassembles the global arrays from per-process files
# via jax.make_array_from_process_local_data.  File layout per checkpoint
# dir: ``state.proc00000-of-00004.npz`` etc.; replicated / host leaves are
# written in full into every process's file so a non-shared FS restores
# without any cross-process reads.


def shard_file_name(process_index: int, process_count: int) -> str:
    return f"state.proc{process_index:05d}-of-{process_count:05d}.npz"


def _local_block(a) -> np.ndarray:
    """This process's addressable block of ``a`` as one contiguous numpy
    array.  Cross-process leaves here are sharded along exactly one axis in
    contiguous per-process blocks (the home axis under NamedSharding); a
    non-contiguous layout is a config error and raises loudly."""
    if not isinstance(a, jax.Array) or a.is_fully_addressable:
        return np.asarray(a)
    blocks = {}
    for s in a.addressable_shards:
        key = tuple((sl.start or 0, sl.stop if sl.stop is not None else dim)
                    for sl, dim in zip(s.index, a.shape))
        blocks.setdefault(key, s.data)
    if len(blocks) == 1:
        return np.asarray(next(iter(blocks.values())))
    # Distinct blocks must tile a contiguous range along one axis.
    keys = sorted(blocks)
    varying = [ax for ax in range(len(keys[0]))
               if len({k[ax] for k in keys}) > 1]
    if len(varying) != 1:
        raise ValueError(
            f"checkpoint shard layout not contiguous-1D: blocks {keys}")
    ax = varying[0]
    keys.sort(key=lambda k: k[ax][0])
    for prev, nxt in zip(keys, keys[1:]):
        if prev[ax][1] != nxt[ax][0]:
            raise ValueError(
                f"checkpoint shard blocks not contiguous along axis {ax}: {keys}")
    return np.concatenate([np.asarray(blocks[k]) for k in keys], axis=ax)


def save_pytree_local(path: str, tree, timestep: int) -> None:
    """Write THIS process's blocks of ``tree`` (no collectives — safe to
    call on every process concurrently).  ``timestep`` is stored inside the
    file so resume can detect a torn multi-process checkpoint (some workers
    crashed between writing shards and publishing LATEST)."""
    leaves = jax.tree_util.tree_leaves(tree)
    arrays = {f"leaf_{i:04d}": _local_block(l) for i, l in enumerate(leaves)}
    arrays["__timestep__"] = np.asarray(timestep, np.int64)
    tmp = f"{path}.tmp{jax.process_index()}.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_pytree_local(path: str, template, expect_timestep: int | None = None):
    """Load this process's shard file into ``template``'s structure.  Leaves
    whose template is a cross-process jax.Array are rebuilt from the local
    block via ``jax.make_array_from_process_local_data`` (a collective-free
    constructor — but every process must call it for its own shard);
    fully-addressable leaves restore exactly like :func:`load_pytree`."""
    # Context manager: NpzFile holds the file descriptor open until closed
    # (ADVICE round 3 — the resume probe used one, the loader leaked it).
    with np.load(path) as data:
        if expect_timestep is not None and "__timestep__" in data.files:
            got = int(data["__timestep__"])
            if got != expect_timestep:
                raise ValueError(
                    f"shard file {path} holds timestep {got}, expected "
                    f"{expect_timestep} (torn multi-process checkpoint)")
        leaves, treedef = jax.tree_util.tree_flatten(template)
        keys = sorted((k for k in data.files if k.startswith("leaf_")),
                      key=lambda k: int(k.rsplit("_", 1)[1]))
        if len(keys) != len(leaves):
            raise ValueError(
                f"Checkpoint {path} has {len(keys)} leaves; template has "
                f"{len(leaves)}")
        new_leaves = []
        for key, tmpl in zip(keys, leaves):
            arr = data[key]
            if (isinstance(tmpl, jax.Array) and tmpl.size == 0
                    and arr.size == 0):
                # Zero-size leaves (e.g. the zero-width warm-start carry)
                # are content-free, and their SHARDING is not stable across
                # save/load: XLA canonicalizes empty outputs to replicated,
                # so the saved block can be the (n, 0) global while the
                # fresh template expects an (n/p, 0) local block.  Rebuild
                # from the template alone — but still require the saved
                # shape to be the template's global or local-block shape:
                # accepting ANY zero-size array would mask torn/mismatched-
                # layout checkpoints that every other leaf path rejects
                # loudly (ADVICE round 4).
                ok_shapes = {tuple(tmpl.shape)}
                if not tmpl.is_fully_addressable:
                    ok_shapes.add(tuple(_local_block(tmpl).shape))
                if tuple(arr.shape) not in ok_shapes:
                    raise ValueError(
                        f"Checkpoint zero-size leaf {key} shape {arr.shape} "
                        f"matches neither the template's global nor "
                        f"local-block shape ({sorted(ok_shapes)})")
                if tmpl.is_fully_addressable:
                    leaf = jax.device_put(
                        np.zeros(tmpl.shape, tmpl.dtype), tmpl.sharding)
                else:
                    leaf = jax.make_array_from_process_local_data(
                        tmpl.sharding,
                        np.zeros(_local_block(tmpl).shape, tmpl.dtype),
                        tmpl.shape)
                new_leaves.append(leaf)
                continue
            if isinstance(tmpl, jax.Array) and not tmpl.is_fully_addressable:
                want = _local_block(tmpl).shape
                if tuple(arr.shape) != tuple(want):
                    raise ValueError(
                        f"Checkpoint leaf {key} local block {arr.shape} != "
                        f"template's local block {want}")
                leaf = jax.make_array_from_process_local_data(
                    tmpl.sharding, arr.astype(tmpl.dtype), tmpl.shape)
            else:
                if tuple(arr.shape) != tuple(np.shape(tmpl)):
                    raise ValueError(
                        f"Checkpoint leaf {key} shape {arr.shape} != template "
                        f"{np.shape(tmpl)}")
                if isinstance(tmpl, jax.Array):
                    leaf = jax.device_put(arr.astype(tmpl.dtype), tmpl.sharding)
                else:
                    leaf = jax.numpy.asarray(arr, dtype=np.asarray(tmpl).dtype)
            new_leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


# ------------------------------------------------- versioned checkpoint dirs
#
# The aggregator's proven atomic-checkpoint shape (save_checkpoint),
# factored for other chunk-checkpointing hosts (the shard workers,
# dragg_tpu/shard/worker.py; the reshard tool rewrites these trees):
# each checkpoint is a self-contained ``ckpt_t<t>`` directory
# (state.npz + progress.json) staged under a ``.tmp`` name and renamed
# into place, after which the ``LATEST`` pointer is atomically replaced.
# A kill at any instant leaves either the previous complete checkpoint
# or the new complete one — never a torn mix.


def save_checkpoint_dir(root: str, timestep: int, tree,
                        progress: dict) -> str:
    """Write one versioned checkpoint directory and publish it via
    ``LATEST``.  ``progress`` must carry every host-side field resume
    needs (the caller's run-shape guard included); ``timestep`` is added
    to it and names the directory.  Superseded checkpoints are pruned.
    Returns the published directory path."""
    import shutil

    os.makedirs(root, exist_ok=True)
    name = f"ckpt_t{timestep:08d}"
    tmp = os.path.join(root, name + ".tmp")
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    save_pytree(os.path.join(tmp, "state.npz"), tree)
    save_progress(os.path.join(tmp, "progress.json"),
                  {**progress, "timestep": int(timestep)})
    final = os.path.join(root, name)
    # A previous run killed between this rename and the LATEST replace
    # leaves a complete dir at `final` while LATEST points at the older
    # checkpoint; the resumed run reaches this timestep again and
    # os.rename onto a non-empty dir raises.  Clear it first.
    shutil.rmtree(final, ignore_errors=True)
    os.rename(tmp, final)
    latest_tmp = os.path.join(root, f"LATEST.tmp{os.getpid()}")
    with open(latest_tmp, "w") as f:
        f.write(name)
        f.flush()
        os.fsync(f.fileno())
    os.replace(latest_tmp, os.path.join(root, "LATEST"))
    for entry in os.listdir(root):
        if entry.startswith("ckpt_") and entry != name:
            shutil.rmtree(os.path.join(root, entry), ignore_errors=True)
    return final


def latest_checkpoint_dir(root: str) -> str | None:
    """The directory ``LATEST`` points at, or None when absent/torn."""
    pointer = os.path.join(root, "LATEST")
    try:
        with open(pointer) as f:
            name = f.read().strip()
    except OSError:
        return None
    d = os.path.join(root, name)
    return d if os.path.isdir(d) else None


def save_progress(path: str, progress: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(progress, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_progress(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
