"""Checkpoint / resume of device state.

The reference only checkpoints its *outputs*: collected_data is re-serialized
to results.json every checkpoint interval (dragg/aggregator.py:776-778,
831-844) but a killed run must restart from t=0.  Here the carried device
state (the ``CommunityState`` scan carry — thermal/SoC state, fallback plans,
ADMM warm starts, PRNG key — and, for RL runs, the agent/environment carries)
is persisted alongside results.json, so a run resumes mid-simulation
bit-exactly: the same chunked ``lax.scan`` continues from the saved carry.

Format: one ``.npz`` with leaves in ``jax.tree_util.tree_flatten`` order.
Loading requires a template pytree with the same structure (engines and
agents can always rebuild their initial carries), which avoids serializing
tree structure and keeps the format dumb and portable.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np


def save_pytree(path: str, tree) -> None:
    """Write a pytree of arrays as an npz (leaves in flatten order)."""
    leaves = jax.tree_util.tree_leaves(tree)
    arrays = {f"leaf_{i:04d}": np.asarray(l) for i, l in enumerate(leaves)}
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **arrays)
    os.replace(tmp, path)


def load_pytree(path: str, template):
    """Load an npz produced by :func:`save_pytree` into ``template``'s
    structure.  Shapes must match the template's leaves."""
    data = np.load(path)
    leaves, treedef = jax.tree_util.tree_flatten(template)
    # Sort numerically: lexicographic sort would interleave leaf_10000
    # between leaf_1000 and leaf_1001, silently permuting same-shaped leaves.
    keys = sorted(data.files, key=lambda k: int(k.rsplit("_", 1)[1]))
    if len(keys) != len(leaves):
        raise ValueError(
            f"Checkpoint {path} has {len(keys)} leaves; template has {len(leaves)}"
        )
    new_leaves = []
    for key, tmpl in zip(keys, leaves):
        arr = data[key]
        tshape = np.shape(tmpl)
        if tuple(arr.shape) != tuple(tshape):
            raise ValueError(
                f"Checkpoint leaf {key} shape {arr.shape} != template {tshape}"
            )
        new_leaves.append(jax.numpy.asarray(arr, dtype=np.asarray(tmpl).dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def save_progress(path: str, progress: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(progress, f)
    os.replace(tmp, path)


def load_progress(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
