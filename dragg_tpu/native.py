"""ctypes bindings for the native host runtime (native/*.cpp).

Two components, both with transparent pure-Python fallbacks so the framework
runs on machines without a C++ toolchain:

* :class:`StateBus` — the in-process replacement for the reference's Redis
  server (dragg/redis_client.py:13-25): same verbs, same semantics, C++
  shared-memory store instead of a C server over TCP.
* :class:`SeriesCollector` — native per-home series accumulation and the
  streaming results.json writer (replaces the reference's per-timestep
  Redis reads + whole-dict json.dump, dragg/aggregator.py:728-755,831-844).

The shared library is built once on demand with ``g++ -O2 -shared -fPIC``
into a cache dir next to the package (pybind11 is unavailable in this image;
a plain C ABI + ctypes needs no build-time Python dependency at all).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_LIB_TRIED = False
_LOCK = threading.Lock()

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SOURCES = ["statebus.cpp", "collector.cpp"]


def _build_lib() -> str | None:
    """Compile the native library if needed; returns its path or None."""
    cache = os.path.join(_SRC_DIR, "_build")
    lib_path = os.path.join(cache, "libdragghost.so")
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    if not all(os.path.isfile(s) for s in srcs):
        return None
    if os.path.isfile(lib_path) and all(
        os.path.getmtime(lib_path) >= os.path.getmtime(s) for s in srcs
    ):
        return lib_path
    os.makedirs(cache, exist_ok=True)
    # Compile to a per-process temp name and atomically publish, so
    # concurrent builders never dlopen a half-written library.
    tmp_path = f"{lib_path}.{os.getpid()}.tmp"
    cmd = ["g++", "-std=c++17", "-O2", "-shared", "-fPIC", "-o", tmp_path, *srcs]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp_path, lib_path)
    except (OSError, subprocess.SubprocessError):
        if os.path.exists(tmp_path):
            try:
                os.remove(tmp_path)
            except OSError:
                pass
        return None
    return lib_path


def load_library():
    """Load (building if necessary) the native library; None if unavailable."""
    global _LIB, _LIB_TRIED
    with _LOCK:
        if _LIB_TRIED:
            return _LIB
        _LIB_TRIED = True
        path = _build_lib()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        c = ctypes.c_char_p
        i64 = ctypes.c_int64
        dp = ctypes.POINTER(ctypes.c_double)
        lib.sb_free.argtypes = [ctypes.c_void_p]
        lib.sb_get.restype = ctypes.c_void_p
        lib.sb_get.argtypes = [c]
        lib.sb_set.argtypes = [c, c]
        lib.sb_del.argtypes = [c]
        lib.sb_exists.argtypes = [c]
        lib.sb_hset.argtypes = [c, c, c]
        lib.sb_hget.restype = ctypes.c_void_p
        lib.sb_hget.argtypes = [c, c]
        lib.sb_hgetall.restype = ctypes.c_void_p
        lib.sb_hgetall.argtypes = [c]
        lib.sb_rpush.argtypes = [c, c]
        lib.sb_rpush_n.argtypes = [c, ctypes.POINTER(c), i64]
        lib.sb_llen.restype = i64
        lib.sb_llen.argtypes = [c]
        lib.sb_lrange.restype = ctypes.c_void_p
        lib.sb_lrange.argtypes = [c, i64, i64]
        lib.col_new.restype = i64
        lib.col_new.argtypes = [i64]
        lib.col_free.argtypes = [i64]
        lib.col_add_chunk.argtypes = [i64, c, dp, i64, i64]
        lib.col_import_series.argtypes = [i64, c, i64, dp, i64]
        lib.col_series_len.restype = i64
        lib.col_series_len.argtypes = [i64, c, i64]
        lib.col_get_series.restype = i64
        lib.col_get_series.argtypes = [i64, c, i64, dp, i64]
        lib.col_write_json.restype = ctypes.c_int
        lib.col_write_json.argtypes = [i64, c, ctypes.c_char_p, i64]
        _LIB = lib
        return _LIB


def _take_cstr(lib, ptr) -> bytes | None:
    """Copy + free a heap C string returned by the library."""
    if not ptr:
        return None
    try:
        return ctypes.string_at(ptr)
    finally:
        lib.sb_free(ptr)


def _parse_frames(raw: bytes, pairs: bool):
    """Decode the length-prefixed framing produced by statebus.cpp."""
    pos = raw.index(b"\n")
    n = int(raw[:pos])
    pos += 1
    out = []
    for _ in range(n * (2 if pairs else 1)):
        sp = raw.index(b" ", pos)
        ln = int(raw[pos:sp])
        start = sp + 1
        out.append(raw[start:start + ln])
        pos = start + ln + 1  # skip trailing newline
    if pairs:
        return {out[i].decode(): out[i + 1].decode() for i in range(0, len(out), 2)}
    return [b.decode() for b in out]


# --------------------------------------------------------------------------
# StateBus
# --------------------------------------------------------------------------

_FALLBACK_DATA: dict = {}
_FALLBACK_MU = threading.Lock()


def _check_text(v) -> str:
    """Keys/values cross the C-string ABI; embedded NULs would silently
    truncate there, so both backends reject them up front (the store's
    payloads are numeric strings — full binary safety is out of scope)."""
    s = str(v)
    if "\x00" in s:
        raise ValueError("StateBus keys/values must not contain NUL bytes")
    return s


class StateBus:
    """Redis-verb store. Native-backed when the library builds; otherwise a
    threadsafe in-process dict with identical semantics.  Both backends are
    process-global (like a Redis server): every StateBus instance sees the
    same data."""

    def __init__(self):
        self._lib = load_library()
        if self._lib is None:
            self._data = _FALLBACK_DATA
            self._mu = _FALLBACK_MU

    @property
    def native(self) -> bool:
        return self._lib is not None

    def flushall(self):
        if self._lib:
            self._lib.sb_flushall()
        else:
            with self._mu:
                self._data.clear()

    def delete(self, key: str):
        if self._lib:
            self._lib.sb_del(key.encode())
        else:
            with self._mu:
                self._data.pop(key, None)

    def set(self, key: str, val) -> None:
        if self._lib:
            self._lib.sb_set(_check_text(key).encode(), _check_text(val).encode())
        else:
            with self._mu:
                self._data[_check_text(key)] = _check_text(val)

    def get(self, key: str) -> str | None:
        if self._lib:
            raw = _take_cstr(self._lib, self._lib.sb_get(key.encode()))
            return None if raw is None else raw.decode()
        with self._mu:
            v = self._data.get(key)
            return v if isinstance(v, str) else None

    def hset(self, key: str, field: str, val) -> None:
        if self._lib:
            self._lib.sb_hset(_check_text(key).encode(), _check_text(field).encode(), _check_text(val).encode())
        else:
            with self._mu:
                d = self._data.setdefault(key, {})
                if not isinstance(d, dict):
                    d = self._data[key] = {}
                d[_check_text(field)] = _check_text(val)

    def hget(self, key: str, field: str) -> str | None:
        if self._lib:
            raw = _take_cstr(self._lib, self._lib.sb_hget(key.encode(), field.encode()))
            return None if raw is None else raw.decode()
        with self._mu:
            d = self._data.get(key)
            return d.get(field) if isinstance(d, dict) else None

    def hgetall(self, key: str) -> dict:
        if self._lib:
            raw = _take_cstr(self._lib, self._lib.sb_hgetall(key.encode()))
            return {} if raw is None else _parse_frames(raw, pairs=True)
        with self._mu:
            d = self._data.get(key)
            return dict(d) if isinstance(d, dict) else {}

    def rpush(self, key: str, *vals) -> None:
        if self._lib:
            # One batched native call → one lock acquisition, so a
            # multi-value push is atomic like Redis RPUSH (a concurrent
            # lrange/llen can't observe it half-applied).
            enc = [_check_text(v).encode() for v in vals]
            arr = (ctypes.c_char_p * len(enc))(*enc)
            self._lib.sb_rpush_n(key.encode(), arr, len(enc))
        else:
            with self._mu:
                lst = self._data.setdefault(key, [])
                if not isinstance(lst, list):
                    lst = self._data[key] = []
                lst.extend(_check_text(v) for v in vals)

    def llen(self, key: str) -> int:
        if self._lib:
            return int(self._lib.sb_llen(key.encode()))
        with self._mu:
            lst = self._data.get(key)
            return len(lst) if isinstance(lst, list) else 0

    def lrange(self, key: str, start: int, stop: int) -> list[str]:
        if self._lib:
            raw = _take_cstr(self._lib, self._lib.sb_lrange(key.encode(), start, stop))
            return [] if raw is None else _parse_frames(raw, pairs=False)
        with self._mu:
            lst = self._data.get(key)
            if not isinstance(lst, list):
                return []
            n = len(lst)
            if start < 0:
                start += n
            if stop < 0:
                stop += n
            # Redis semantics: indices still negative after conversion clamp
            # to the list edges (stop < 0 → empty range).
            if stop < 0:
                return []
            return lst[max(start, 0):min(stop, n - 1) + 1]


# --------------------------------------------------------------------------
# SeriesCollector
# --------------------------------------------------------------------------

class SeriesCollector:
    """Per-home series store with a streaming JSON writer.

    Falls back to Python lists when the native library is unavailable; the
    API (add_chunk / get / length / import_series / write_json) is identical.
    """

    def __init__(self, n_homes: int):
        import numpy as np

        self._np = np
        self.n_homes = int(n_homes)
        self._lib = load_library()
        if self._lib is not None:
            self._h = self._lib.col_new(self.n_homes)
        else:
            self._h = None
            self._series: dict[str, list[list[float]]] = {}

    @property
    def native(self) -> bool:
        return self._h is not None

    def close(self):
        if self._h is not None:
            self._lib.col_free(self._h)
            self._h = None

    def add_chunk(self, key: str, data) -> None:
        """Append a (n_steps, n_homes) array to series ``key``."""
        np = self._np
        arr = np.ascontiguousarray(np.asarray(data, dtype=np.float64))
        if arr.ndim != 2 or arr.shape[1] != self.n_homes:
            raise ValueError(f"chunk shape {arr.shape} != (*, {self.n_homes})")
        if self._h is not None:
            ptr = arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
            rc = self._lib.col_add_chunk(self._h, key.encode(), ptr,
                                         arr.shape[0], arr.shape[1])
            if rc != 0:
                raise RuntimeError(f"col_add_chunk failed: {rc}")
        else:
            cols = self._series.setdefault(key, [[] for _ in range(self.n_homes)])
            for i in range(self.n_homes):
                cols[i].extend(float(v) for v in arr[:, i])

    def import_series(self, key: str, home_idx: int, values) -> None:
        np = self._np
        arr = np.ascontiguousarray(np.asarray(values, dtype=np.float64))
        if self._h is not None:
            ptr = arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
            rc = self._lib.col_import_series(self._h, key.encode(), home_idx, ptr, arr.size)
            if rc != 0:
                raise RuntimeError(f"col_import_series failed: {rc}")
        else:
            cols = self._series.setdefault(key, [[] for _ in range(self.n_homes)])
            cols[home_idx] = [float(v) for v in arr]

    def length(self, key: str, home_idx: int = 0) -> int:
        if self._h is not None:
            return int(self._lib.col_series_len(self._h, key.encode(), home_idx))
        cols = self._series.get(key)
        return len(cols[home_idx]) if cols else 0

    def get(self, key: str, home_idx: int) -> list[float]:
        if self._h is not None:
            n = self.length(key, home_idx)
            buf = (ctypes.c_double * n)()
            got = self._lib.col_get_series(self._h, key.encode(), home_idx, buf, n)
            return list(buf[: max(got, 0)])
        cols = self._series.get(key)
        return list(cols[home_idx]) if cols else []

    def keys(self) -> list[str]:
        if self._h is not None:
            raise NotImplementedError("track keys on the Python side")
        return list(self._series)

    def write_json(self, path: str, plan: list[tuple]) -> None:
        """Execute a write plan.

        ``plan`` is a list of ('raw', str) and ('series', key, home_idx)
        records; raw fragments carry all JSON structure, series records
        expand to JSON arrays of the stored doubles.
        """
        if self._h is not None:
            parts = []
            for rec in plan:
                if rec[0] == "raw":
                    b = rec[1].encode()
                    parts.append(b"R %d\n%s" % (len(b), b))
                else:
                    k = rec[1].encode()
                    parts.append(b"S %d %d\n%s" % (len(k), rec[2], k))
            blob = b"".join(parts)
            rc = self._lib.col_write_json(self._h, path.encode(), blob, len(blob))
            if rc != 0:
                raise RuntimeError(f"col_write_json failed: {rc}")
        else:
            import json as _json

            out = []
            for rec in plan:
                if rec[0] == "raw":
                    out.append(rec[1])
                else:
                    out.append(_json.dumps(self.get(rec[1], rec[2])))
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write("".join(out))
            os.replace(tmp, path)
