"""Reference-compatible state-bus client (L4 parity).

The reference's ``RedisClient`` is a singleton wrapping a redis-py
connection pool to an external C Redis server (dragg/redis_client.py:4-25).
Here the same API fronts the in-process C++ state bus
(:mod:`dragg_tpu.native`) — no server, no TCP, no serialization across a
socket — so orchestration code written against the reference's client
(``RedisClient().conn.hset/hgetall/rpush/lrange/...``) runs unchanged.

The TPU engine itself never touches this bus (community state is device
arrays; SURVEY.md §2.2 "Redis server → eliminated on-device"); it exists
for reference-parity tooling and host-side CPU-reference mode.
"""

from __future__ import annotations

from dragg_tpu.native import StateBus


class Singleton(type):
    """Same singleton metaclass shape as the reference
    (dragg/redis_client.py:4-11)."""

    _instances: dict = {}

    def __call__(cls, *args, **kwargs):
        if cls not in cls._instances:
            cls._instances[cls] = super().__call__(*args, **kwargs)
        return cls._instances[cls]


class RedisClient(metaclass=Singleton):
    """Singleton exposing ``.conn`` with the Redis verbs the reference uses
    (dragg/redis_client.py:13-25)."""

    def __init__(self):
        self.conn = StateBus()
