"""dragg_tpu — a TPU-native community energy-simulation framework.

Re-implements the capabilities of the reference DRAGG simulator
(corymosiman12/dragg: per-home MPC over HVAC/water-heater RC thermal dynamics,
optional battery + PV, community aggregator, RL price-signal agent) as a
batched tensor program: every home's MPC is a fixed-shape QP solved by a JAX
ADMM kernel ``vmap``'d over the whole community and sharded over a TPU mesh,
instead of one CVXPY MILP per home fanned out over a Redis-coordinated process
pool (reference: dragg/aggregator.py:711-726, dragg/mpc_calc.py:434-454).

Public API mirrors the reference's entry points:

    from dragg_tpu import Aggregator
    Aggregator().run()
"""

__version__ = "0.1.0"

from dragg_tpu.config import load_config, default_config  # noqa: F401


def __getattr__(name):
    # Lazy import: keeps `import dragg_tpu` light and avoids import cycles.
    if name == "Aggregator":
        try:
            from dragg_tpu.aggregator import Aggregator
        except ImportError as e:  # PEP 562: unresolvable names must raise AttributeError
            raise AttributeError(f"module 'dragg_tpu' has no attribute {name!r}") from e
        return Aggregator
    raise AttributeError(f"module 'dragg_tpu' has no attribute {name!r}")
