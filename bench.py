"""Benchmark harness — one JSON line on stdout.

Headline metric (BASELINE.json): MPC sim-timesteps/sec on the single-chip
batched community — 10k homes, 24 h prediction horizon, mixed home types.
``vs_baseline`` is measured against the north-star target rate of
50 sim-timesteps/s (BASELINE.md: 100k homes over a 4-chip v4-8 slice
→ 25k homes/chip; we report the per-chip rate at 10k homes, so ≥1.0 means
the single-chip engine is on pace for the pod-slice target).

Usage: python bench.py [--homes N] [--horizon-hours H] [--steps K]
"""

from __future__ import annotations

import argparse
import json
import time

TARGET_TS_PER_S = 50.0  # BASELINE.md north star


def build(n_homes: int, horizon_hours: int, admm_iters: int):
    import numpy as np

    from dragg_tpu.config import default_config
    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    cfg = default_config()
    cfg["community"]["total_number_homes"] = n_homes
    # Mixed population, reference default ratio-ish: 40% PV, 10% battery,
    # 10% pv_battery.
    cfg["community"]["homes_pv"] = int(0.4 * n_homes)
    cfg["community"]["homes_battery"] = int(0.1 * n_homes)
    cfg["community"]["homes_pv_battery"] = int(0.1 * n_homes)
    cfg["simulation"]["start_datetime"] = "2015-01-01 00"
    cfg["simulation"]["end_datetime"] = "2015-01-08 00"
    cfg["home"]["hems"]["prediction_horizon"] = horizon_hours
    cfg["tpu"]["admm_iters"] = admm_iters

    env = load_environment(cfg, data_dir=None)
    dt = int(cfg["agg"]["subhourly_steps"])
    waterdraw = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24 * 7 * dt, dt, waterdraw)
    hems = cfg["home"]["hems"]
    batch = build_home_batch(
        homes, max(1, int(hems["prediction_horizon"]) * dt), dt,
        int(hems["sub_subhourly_steps"]),
    )
    engine = make_engine(batch, env, cfg, 0)
    return engine, np


def main() -> None:
    ap = argparse.ArgumentParser()
    # Default sized to what the tunneled single-chip test rig executes
    # reliably today; the BASELINE target config is --homes 10000.
    ap.add_argument("--homes", type=int, default=1_000)
    ap.add_argument("--horizon-hours", type=int, default=24)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--admm-iters", type=int, default=1000)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU run (50 homes, 4h horizon) for verification")
    args = ap.parse_args()

    import jax

    if args.smoke:
        jax.config.update("jax_platforms", "cpu")
        args.homes, args.horizon_hours, args.steps = 50, 4, 4

    engine, np = build(args.homes, args.horizon_hours, args.admm_iters)
    H = engine.params.horizon
    state = engine.init_state()
    rps = np.zeros((args.steps, H), dtype=np.float32)

    # Warmup with the SAME chunk shape as the timed run — the scan length is
    # baked into the compiled program, so a different shape would put a full
    # recompile inside the timed window.
    state, outs = engine.run_chunk(state, 0, rps)
    jax.block_until_ready(outs.agg_load)

    t0 = time.perf_counter()
    state, outs = engine.run_chunk(state, args.steps, rps)
    jax.block_until_ready(outs.agg_load)
    elapsed = time.perf_counter() - t0

    rate = args.steps / elapsed
    print(json.dumps({
        "metric": f"sim_timesteps_per_s_{args.homes}homes_{args.horizon_hours}h_horizon",
        "value": round(rate, 3),
        "unit": "timesteps/s",
        "vs_baseline": round(rate / TARGET_TS_PER_S, 3),
    }))


if __name__ == "__main__":
    main()
