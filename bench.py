"""Benchmark harness — always prints exactly ONE JSON line on stdout, rc 0.

Headline metric (BASELINE.json): MPC sim-timesteps/sec on the single-chip
batched community at the BASELINE target config — 10k homes, 24 h prediction
horizon, mixed home types.  ``vs_baseline`` is measured against the
north-star rate of 50 sim-timesteps/s (BASELINE.md: 100k homes over a 4-chip
v4-8 slice → 25k homes/chip; we report the per-chip rate, so ≥1.0 means the
single-chip engine is on pace for the pod-slice target).

Robustness (the round-1 run died in TPU backend init with a bare traceback;
since round 6 the survival logic lives in dragg_tpu/resilience):

* the measured run executes in a SUPERVISED child process
  (resilience.supervisor): hard deadline, heartbeat-stall detection on
  TPU attempts (a child that stops logging progress is killed before its
  abandoned compile can wedge the tunnel — $BENCH_STALL_TIMEOUT, default
  900 s, 0 disables; CPU attempts run deadline-only, since a big CPU
  chunk legitimately computes longer than any beat cadence), and
  classified failures (taxonomy kinds in ``attempts``);
* every TPU attempt is gated on a hard-timeout jax-level tunnel probe
  (resilience.liveness; a wedged tunnel hangs backend init; the proxy
  accepting TCP is not liveness — CLAUDE.md), with each verdict appended
  to $DRAGG_PROBE_LOG; retries use probe-gated backoff;
* platform ladder: probe → TPU attempt → backoff+probe → TPU retry
  (shorter chunks) → CPU fallback at the FULL requested config (clearly
  labelled ``fallback: true`` — so outage-round artifacts still carry a
  BASELINE-scale number; budget via $BENCH_CPU_TIMEOUT, default 1800 s);
* any failure path still emits the one-line JSON (value 0.0 + error info)
  instead of a traceback.

Every line carries a ``data`` field naming the environment it measured
("bundled" = the shipped first-party assets, "synthetic" = the rounds-2..4
generators); ``--dual-report`` emits BOTH lines in one invocation so
round artifacts always cover the shipped default AND the cross-round
comparison environment (VERDICT r5 weak #3).

Besides the headline rate the JSON carries per-phase timers
(assemble / solve / merge+collect), the solver iteration count, XLA's FLOP
estimate for the compiled chunk, and an MFU estimate whose denominator is
resolved by :func:`peak_flops_for` (device_kind-keyed TPU spec table,
``--peak-tflops`` override, or the clearly-labelled CPU estimate) and
named in ``mfu_basis`` — the key is never silently dropped (ISSUE 11).
``precision`` (the hot-loop matmul policy) rides the JSON as a HARD
bench_trend series key.

The benchmarked config defaults to the SHIPPED bundled-data environment
(VERDICT r5 weak #3); ``--synthetic`` pins the rounds-2..4 generators for
cross-round comparability.  Since round 7 every timer flows through the
unified telemetry registry (dragg_tpu/telemetry): warmup/chunk/phase
timings are spans, the JSON derives from metrics snapshots, and
``flops_per_step`` is always populated (analytic model) so MFU can be
back-filled from telemetry the moment a chip is reachable.

Usage: python bench.py [--homes N] [--horizon-hours H] [--steps K]
                       [--chunks C] [--platform auto|tpu|cpu] [--smoke]
                       [--synthetic] [--dual-report]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

TARGET_TS_PER_S = 50.0  # BASELINE.md north star

# Peak dense bf16 FLOPs/s per chip, keyed by device_kind substring
# (public spec numbers; MFU vs bf16 peak is the conservative convention).
PEAK_FLOPS = [
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12), ("v5e", 394e12), ("v5 lite", 394e12), ("v5", 459e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]

# CPU fallback peak: an ORDER-OF-MAGNITUDE estimate, clearly labelled as
# such in ``mfu_basis`` (ISSUE 11 satellite — ``peak`` was silently None
# off-TPU, which dropped MFU from every committed artifact since all
# five are CPU fallbacks).  Model: ~32 host cores × ~64 GFLOP/s f32 FMA
# (AVX-512-class) ≈ 2 TFLOP/s.  CPU-MFU values are for ROOFLINE-DISTANCE
# reading only, never cross-platform comparison — the basis field keys
# that.
CPU_PEAK_FLOPS_EST = 2.0e12


def peak_flops_for(device_kind: str, platform: str,
                   override_tflops: float | None = None
                   ) -> tuple[float | None, str | None]:
    """(peak FLOPs/s, mfu_basis label) for the measured device.

    Resolution: an explicit ``--peak-tflops`` override wins (basis
    ``"override"``; argparse rejects non-positive values, so the
    override can never silently void the denominator), then the
    device_kind-keyed TPU spec table (basis ``"tpu_spec:<key>"``), then
    the labelled CPU estimate (basis ``"cpu_estimate"``).  An unmatched
    accelerator returns (None, None) → the JSON carries ``mfu: null``
    WITH the null basis instead of silently dropping the key."""
    if override_tflops is not None:
        return float(override_tflops) * 1e12, "override"
    for key, val in PEAK_FLOPS:
        if key in str(device_kind).lower():
            return val, f"tpu_spec:{key}"
    if platform == "cpu":
        return CPU_PEAK_FLOPS_EST, "cpu_estimate"
    return None, None

# Peak HBM bandwidth per chip (bytes/s, public spec numbers).  The IPM's
# band kernels have negligible matmul FLOPs — the meaningful utilization
# metric for them is achieved HBM bandwidth, not MFU.
PEAK_HBM_BW = [
    ("v6", 1640e9), ("trillium", 1640e9),
    ("v5p", 2765e9), ("v5e", 819e9), ("v5 lite", 819e9), ("v5", 2765e9),
    ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
]


def _log(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)
    # Every log line is a progress beat: under supervision
    # ($DRAGG_HEARTBEAT_FILE exported by resilience.supervisor) the stall
    # detector reads the beat age; unsupervised it is a no-op.
    from dragg_tpu.resilience.heartbeat import beat

    beat({"stage": msg[:120]})


LEGACY_MIX = {"pv_only": 0.4, "battery_only": 0.1, "pv_battery": 0.1}


def parse_mix(text: str | None) -> dict[str, float] | None:
    """``--mix`` parser: comma-separated ``type=fraction`` pairs over the
    full six-type vocabulary (homes.HOME_TYPES minus base, which takes the
    remainder) — e.g. ``pv_only=0.3,ev=0.1,heat_pump=0.1``.  None = the
    legacy bench mix (0.4/0.1/0.1), so historical invocations and
    artifacts are unchanged."""
    if text is None:
        return None
    from dragg_tpu.scenarios import MIX_KEYS

    mix: dict[str, float] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        t, sep, frac = part.partition("=")
        if t not in MIX_KEYS:
            raise SystemExit(
                f"--mix: unknown home type {t!r} (known: "
                f"{','.join(sorted(MIX_KEYS))})")
        try:
            val = float(frac)
        except ValueError:
            val = -1.0
        if not sep or not 0.0 <= val <= 1.0:
            raise SystemExit(
                f"--mix: {part!r} must be <type>=<fraction in [0, 1]>")
        mix[t] = val
    if sum(mix.values()) > 1.0 + 1e-9:
        raise SystemExit(f"--mix fractions sum to {sum(mix.values()):.3f} > 1")
    return mix


def mix_label(mix: dict[str, float] | None, pack: str | None) -> str:
    """Canonical composition label — tools/bench_trend.py keys the trend
    series on it (a mix or pack change is a different workload, never a
    perf signal), so it must be deterministic across invocations."""
    base = ("legacy" if mix is None
            else ",".join(f"{t}={mix[t]:g}" for t in sorted(mix)))
    return f"{base}+pack:{pack}" if pack else base


def bench_config(n_homes: int, horizon_hours: int, admm_iters: int,
                 solver: str = "admm", band_kernel: str | None = None,
                 data_dir: str | None = None, semantics: str = "default",
                 bucketed: str = "auto", per_home_obs: str = "true",
                 communities: int = 1, mix: dict[str, float] | None = None,
                 pack: str | None = None, precision: str = "f32",
                 iter_kernel: str | None = None) -> dict:
    """THE benchmark community config as a pure dict — shared by the
    measured child's engine build below AND the jax-free ``--shards``
    parent (which ships it to shard workers over the spool, so the
    sharded measurement runs exactly the population the in-process bench
    does).  Imports only config + scenarios; never initializes jax."""
    from dragg_tpu.config import default_config
    from dragg_tpu.scenarios import MIX_KEYS, apply_scenarios

    cfg = default_config()
    cfg["community"]["total_number_homes"] = n_homes
    cfg["fleet"]["communities"] = communities
    # Mixed population — default is the legacy bench mix (40% PV, 10%
    # battery, 10% pv_battery); --mix swaps in any six-type composition
    # and --pack layers a scenario pack (whose [mix] fractions override
    # these counts — apply_scenarios below).
    for t, key in MIX_KEYS.items():
        frac = (mix if mix is not None else LEGACY_MIX).get(t, 0.0)
        cfg["community"][key] = int(frac * n_homes)
    if pack:
        cfg["scenarios"]["pack"] = pack
    cfg = apply_scenarios(cfg, data_dir or None)
    cfg["simulation"]["start_datetime"] = "2015-01-01 00"
    cfg["simulation"]["end_datetime"] = "2015-01-08 00"
    cfg["home"]["hems"]["prediction_horizon"] = horizon_hours
    cfg["tpu"]["admm_iters"] = admm_iters
    cfg["home"]["hems"]["solver"] = solver
    cfg["tpu"]["bucketed"] = bucketed
    # Observatory A/B knob (round 9): "false" compiles the per-home
    # attribution fold out of the device program so the overhead A/B in
    # docs/perf_notes.md compares identical semantics.
    cfg["telemetry"]["per_home"] = per_home_obs == "true"
    if band_kernel is not None:
        cfg["tpu"]["band_kernel"] = band_kernel
    # Hot-loop matmul policy + fused iteration kernel (ISSUE 11): the
    # precision is a HARD bench_trend series key, so it must land in the
    # engine exactly as the artifact will record it.
    cfg["tpu"]["precision"] = precision
    if iter_kernel is not None:
        cfg["tpu"]["iter_kernel"] = iter_kernel
    if semantics != "default":
        # "integer"/"relaxation" override the shipped default so on-chip
        # A/Bs and cross-round comparisons (rounds <=4 measured the
        # relaxation) can pin either side.
        cfg["tpu"]["integer_first_action"] = semantics == "integer"
    return cfg


def build(n_homes: int, horizon_hours: int, admm_iters: int,
          solver: str = "admm", band_kernel: str | None = None,
          data_dir: str | None = None, semantics: str = "default",
          bucketed: str = "auto", per_home_obs: str = "true",
          communities: int = 1, mix: dict[str, float] | None = None,
          pack: str | None = None, precision: str = "f32",
          iter_kernel: str | None = None):
    """Build THE benchmark community engine (population mix, sim window,
    solver config).  This is the one definition of the measured community —
    tools/bench_engine_kernels.py reuses it so kernel A/B verdicts are
    measured on the same population as the headline bench.  ``data_dir``
    points at real nsrdb.csv/waterdraw_profiles.csv assets (default:
    synthetic — real January weather measures ~1.1 % more fallback steps
    and ~26 % more wall, docs/perf_notes.md round 4).  ``communities > 1``
    folds C independent communities of ``n_homes`` EACH into one fleet
    batch (round 12 — same compiled pattern set, C·B_type homes per type
    bucket)."""
    import numpy as np

    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_fleet_batch, create_fleet_homes

    cfg = bench_config(n_homes, horizon_hours, admm_iters, solver=solver,
                       band_kernel=band_kernel, data_dir=data_dir,
                       semantics=semantics, bucketed=bucketed,
                       per_home_obs=per_home_obs, communities=communities,
                       mix=mix, pack=pack, precision=precision,
                       iter_kernel=iter_kernel)

    # Stage logs: the round-4 live window showed a 10k-home TPU attempt
    # hanging somewhere between "building engine" and the first step with
    # no further output for 900 s — these narrow the next such hang to a
    # stage (host synthesis / pallas self-test+device commit / jit wrap).
    from dragg_tpu.data import waterdraw_path

    env = load_environment(cfg, data_dir=data_dir)
    dt = int(cfg["agg"]["subhourly_steps"])
    waterdraw = load_waterdraw_profiles(waterdraw_path(cfg, data_dir), seed=12)
    homes = create_fleet_homes(cfg, 24 * 7 * dt, dt, waterdraw)
    hems = cfg["home"]["hems"]
    batch, fleet = build_fleet_batch(
        homes, cfg, max(1, int(hems["prediction_horizon"]) * dt), dt,
        int(hems["sub_subhourly_steps"]),
    )
    _log(f"home batch built ({batch.n_homes} homes"
         + (f" = {communities} communities × {n_homes})" if fleet is not None
            else ")"))
    # Run the pallas compile self-test BEFORE the engine constructor so a
    # hang between here and "engine ready" is attributable: self-test
    # (first TPU compile in this process) vs device commit of the batch
    # constants vs jit wrapping.
    from dragg_tpu.ops import pallas_band

    _log("pallas self-test (first TPU kernel compile)...")
    _log(f"pallas self-test: {pallas_band.available()}")
    _log("constructing engine (device commit + jit wrap)...")
    engine = make_engine(batch, env, cfg, 0, fleet=fleet,
                         data_dir=data_dir or None)
    _log(f"engine ready: band_kernel={engine.band_kernel} "
         f"bw={engine.band_bw} bucketed={engine.bucketed}")
    if engine.bucketed:
        _log("buckets: " + ", ".join(
            f"{b['name']}×{b['n_real']} (m={b['m_eq']}, n={b['n_var']})"
            for b in engine.bucket_info()))
    return engine, np


def run_measured(args) -> dict:
    """The actual measurement (runs inside the supervised child).

    Every timer lands in the unified telemetry registry
    (dragg_tpu/telemetry): warmup/chunk/phase timings are spans observed
    into histograms, and the JSON fields are DERIVED from metrics
    snapshots — no scattered perf_counter pairs deciding headline
    numbers (round 7).  When the supervising parent exported
    ``$DRAGG_TELEMETRY_DIR`` the events also stream to its
    events.jsonl; otherwise the bus is memory-only."""
    from dragg_tpu import telemetry
    from dragg_tpu.resilience.faults import fault_hook

    telemetry.init_run(os.environ.get(telemetry.ENV_DIR))
    fault_hook("bench_build")
    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from dragg_tpu.utils.compile_cache import enable_compile_cache
    from dragg_tpu.utils.stderr_filter import install_aot_mismatch_filter

    # Warm persistent-cache loads on XLA:CPU log a spurious per-entry
    # feature-mismatch ERROR (tuning prefs only — see stderr_filter.py);
    # drop exactly that signature, keep real ISA mismatches loud.
    install_aot_mismatch_filter()
    # Solver-scoped persistent cache (utils/compile_cache round 10): an
    # explicit solver keys the cache dir by family so sweeps across
    # families never LRU-evict each other; "auto" races two families in
    # one process and stays in the shared scope.
    scope_cfg = (None if args.solver == "auto"
                 else {"home": {"hems": {"solver": args.solver}}})
    cache_dir = enable_compile_cache(scope_cfg)
    _log(f"compile cache: {cache_dir}")
    _log(f"initializing backend (platform={args.platform})...")
    dev = jax.devices()[0]  # dragg: disable=DT004, supervised child
    platform = dev.platform
    device_kind = getattr(dev, "device_kind", platform)
    _log(f"backend up: {platform} / {device_kind}")
    if args.platform == "tpu" and platform == "cpu":
        raise RuntimeError("requested TPU but backend resolved to CPU")

    _log(f"building engine: {args.homes} homes, {args.horizon_hours}h horizon")
    mix = parse_mix(args.mix)
    engine, np = build(args.homes, args.horizon_hours, args.admm_iters,
                       solver="admm" if args.solver == "auto" else args.solver,
                       data_dir=args.data_dir, semantics=args.semantics,
                       bucketed=args.bucketed,
                       per_home_obs=args.per_home_obs,
                       communities=args.communities,
                       mix=mix, pack=args.pack, precision=args.precision)
    solver_used = engine.params.solver
    if args.solver == "auto":
        # Race the two solver families over SEVERAL sequential steps and
        # keep the winner (the ADMM/IPM balance flips with batch size and
        # hardware — docs/perf_notes.md).  A one-step race is misleading:
        # it samples the ADMM's best case (first warm-started step) while
        # its steady-state iteration count keeps growing — at 1000 homes
        # the one-step race picked an ADMM that then ran 683 iters/step in
        # the timed chunks, 4x slower than the IPM it beat in the race.
        try:
            engine_ipm, _ = build(args.homes, args.horizon_hours,
                                  args.admm_iters, solver="ipm",
                                  data_dir=args.data_dir,
                                  semantics=args.semantics,
                                  bucketed=args.bucketed,
                                  per_home_obs=args.per_home_obs,
                                  communities=args.communities,
                                  mix=mix, pack=args.pack,
                                  precision=args.precision)

            def steps_time(eng, k=6, budget_s=60.0):
                """Mean warm-step time over up to k steps, stopping early
                once ``budget_s`` is spent — at 10k homes a warm step can
                run ~20 s and the race must not eat the attempt timeout."""
                st = eng.init_state()
                rp0 = np.zeros(eng.params.horizon, dtype=np.float32)
                st, out = eng.step(st, 0, rp0)       # compile + cold step
                jax.block_until_ready(out.agg_load)
                t0 = time.perf_counter()
                done = 0
                for i in range(1, k + 1):
                    st, out = eng.step(st, i, rp0)
                    jax.block_until_ready(out.agg_load)
                    done = i
                    if time.perf_counter() - t0 > budget_s:
                        break
                return (time.perf_counter() - t0) / done, done

            t_admm, k_a = steps_time(engine)
            t_ipm, k_i = steps_time(engine_ipm)
            _log(f"solver race: admm {t_admm:.2f}s/step over {k_a} warm "
                 f"steps vs ipm {t_ipm:.2f}s/step over {k_i}")
            if t_ipm < t_admm:
                engine, solver_used = engine_ipm, "ipm"
        except Exception as e:  # the race must never sink the benchmark
            _log(f"solver race failed ({e!r}); staying on admm")
    H = engine.params.horizon
    state = engine.init_state()

    # Size the scan chunk so one device execution stays under ~25 s: the
    # axon-tunneled runtime faults on single executions in the ~60 s range
    # (round-2 finding — the r1/r2 10k-home failures were exactly this), and
    # a smaller chunk costs only scan-overhead amortization.  The estimate
    # uses the single-step path (its own jit; compiles first).
    steps = args.steps
    if platform != "cpu" and args.steps > 2:
        _log("estimating per-step time (single-step compile)...")
        st2, out2 = engine.step(state, 0, np.zeros(H, dtype=np.float32))
        jax.block_until_ready(out2.agg_load)
        t0 = time.perf_counter()
        st2, out2 = engine.step(state, 0, np.zeros(H, dtype=np.float32))
        jax.block_until_ready(out2.agg_load)
        t_step = time.perf_counter() - t0
        steps = int(max(2, min(args.steps, 25.0 / max(t_step, 1e-3))))
        _log(f"~{t_step:.2f}s/step (refresh path) → {steps} steps/chunk")
    rps = np.zeros((steps, H), dtype=np.float32)

    # Warmup with the SAME chunk shape as the timed run — the scan length is
    # baked into the compiled program, so a different shape would put a full
    # recompile inside the timed window.  The warmup runs as a STAGED
    # compile (telemetry/compile_obs): lower → compile → first-execute
    # each get a heartbeat beat + compile.stage event with the per-bucket
    # pattern shapes, so a supervised child that hangs here is killed
    # with the STAGE named in its last progress payload (the round-4 10k
    # hang was never bisected past "between build and first step"), and
    # the persistent-cache hit/miss is recorded.  The timed chunks reuse
    # the returned compiled executable — no second compile.
    _log("warmup chunk (staged compile: lower -> compile -> execute)...")
    creport = None
    with telemetry.span("bench.warmup_s"):
        try:
            from dragg_tpu.telemetry.compile_obs import staged_compile

            run_chunk, state, outs, creport = staged_compile(
                engine, state, 0, rps,
                label=f"bench_{args.homes}x{args.horizon_hours}h")
        except Exception as e:  # AOT quirk must never sink the benchmark
            _log(f"staged compile failed ({e!r}); plain jit warmup")
            run_chunk = engine.run_chunk
            state, outs = run_chunk(state, 0, rps)
            jax.block_until_ready(outs.agg_load)
    if creport is not None:
        _log(f"staged compile: {creport['stages']} cache={creport['cache']}")
    _log(f"warmup done; timing {args.chunks} chunks of {steps} steps")

    iters_per_step = []
    solve_rates = []
    fallback_home_steps = []  # reluqp: homes that needed the rho bank's
                              # exact-refactorization tail (0 elsewhere)
    t_cursor = steps
    for c in range(args.chunks):
        fault_hook("bench_chunk")
        with telemetry.span("bench.chunk_s") as sp:
            state, outs = run_chunk(state, t_cursor, rps)
            jax.block_until_ready(outs.agg_load)
        t_cursor += steps
        iters_per_step.append(float(np.mean(np.asarray(outs.admm_iters))))
        solve_rates.append(float(np.mean(np.asarray(outs.correct_solve))))
        fallback_home_steps.append(
            float(np.sum(np.asarray(outs.bank_fallback_count))))
        _log(f"chunk {c}: {steps / sp.s:.3f} ts/s, "
             f"mean solver iters {iters_per_step[-1]:.0f}, "
             f"solve rate {solve_rates[-1]:.4f}")
    # The headline rate and the compile time come OUT OF the metrics
    # snapshot the spans populated — one source of truth for timers.
    hists = telemetry.snapshot()["histograms"]
    compile_s = hists["bench.warmup_s"]["last"]
    chunk_rates = [steps / s for s in hists["bench.chunk_s"]["samples"]]
    # Best chunk as the headline (cross-round comparability).  Chunks do
    # NOT differ only by noise: later chunks cover later sim windows whose
    # problems are measurably harder (BENCH_r05's [0.15, 0.112, 0.11] decay
    # reproduced at 512 homes — mean IPM iters 10.2 → 15.8 with solve rate
    # 0.96 → 0.81 as t advances, while re-running a FIXED (t, state) chunk
    # holds rate constant, ruling out host-side accumulation —
    # docs/perf_notes.md round 8).  chunk_rates carries the full profile.
    rate = max(chunk_rates)
    telemetry.set_gauge("bench.rate_ts_per_s", rate)

    # Per-bucket telemetry (type-bucketed engine): solve rate per bucket
    # from the last timed chunk's per-home mask; the per-bucket solve-phase
    # timers join below, inside the phase-profiling block.
    binfo = engine.bucket_info()
    bucket_stats = None
    if engine.bucketed:
        cs = np.asarray(outs.correct_solve)
        bucket_stats = {
            b["name"]: {
                "n_homes": b["n_real"], "m_eq": b["m_eq"],
                "n_var": b["n_var"],
                "solve_rate": round(float(
                    cs[:, b["start_slot"]:b["start_slot"] + b["n_real"]]
                    .mean()), 4),
            }
            for b in binfo
        }

    # --- Phase breakdown (separately jitted; attribution, not headline).
    phases = None
    try:
        _log("phase profiling...")
        prep, solve, fin = engine.phase_fns()
        jt = jax.numpy.asarray(t_cursor)
        jrp = jax.numpy.zeros((H,), dtype=jax.numpy.float32)
        refresh = jax.numpy.asarray(True)  # measure the worst-case step
        factor0 = engine.init_factor()
        qp, aux = jax.block_until_ready(prep(state, jt, jrp))
        sol, fcarry, warm_sol, _rf = jax.block_until_ready(
            solve(state, qp, factor0, refresh))
        jax.block_until_ready(fin(state, jt, sol, aux, warm_sol))
        no_refresh = jax.numpy.asarray(False)  # steady-state: cached factor
        jax.block_until_ready(solve(state, qp, fcarry, no_refresh))
        reps = max(2, min(8, args.steps))

        def timeit(metric, fn, *a):
            """Per-step phase time, observed into the named histogram —
            the phases dict below is read back from the snapshot.  The
            reps stay UNBLOCKED between dispatches (pipelining parity
            with the scan), one block at the end."""
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn(*a)
            jax.block_until_ready(out)
            telemetry.observe(metric, (time.perf_counter() - t0) / reps)  # dragg: disable=DT007, every caller below passes a bench.phase.* registry literal

        timeit("bench.phase.assemble_s", prep, state, jt, jrp)
        if solver_used == "ipm":
            # The IPM has NO cross-step factor cache (engine._solve: the
            # refresh flag and factor carry pass through untouched), so
            # "refresh" and "cached" would time the SAME program and any
            # delta is noise — exactly what BENCH_r05's 8.79 vs 9.00 was
            # (VERDICT r5 weak #4; measured ±3% run-to-run,
            # docs/perf_notes.md round 6).  One honest key instead.
            timeit("bench.phase.solve_s", solve, state, qp, factor0, refresh)
        else:
            timeit("bench.phase.solve_refresh_s",
                   solve, state, qp, factor0, refresh)
            timeit("bench.phase.solve_cached_s",
                   solve, state, qp, fcarry, no_refresh)
        timeit("bench.phase.merge_collect_s",
               fin, state, jt, sol, aux, warm_sol)
        # Per-bucket solve attribution (type-bucketed engine): one
        # separately-jitted assemble+solve per bucket (engine.bucket_
        # solve_fns), observed into the per-type registry literals so the
        # A/B artifacts can show WHERE the bucketed win comes from.
        _BUCKET_SOLVE_METRICS = {
            "pv_battery": "bench.phase.solve_pv_battery_s",
            "pv_only": "bench.phase.solve_pv_only_s",
            "battery_only": "bench.phase.solve_battery_only_s",
            "base": "bench.phase.solve_base_s",
            "ev": "bench.phase.solve_ev_s",
            "heat_pump": "bench.phase.solve_heat_pump_s",
        }
        for bname, bfn in engine.bucket_solve_fns():
            jax.block_until_ready(bfn(state, jt, jrp, refresh, factor0))
            timeit(_BUCKET_SOLVE_METRICS[bname],
                   bfn, state, jt, jrp, refresh, factor0)
        pfx = "bench.phase."
        phases = {
            name[len(pfx):-len("_s")]: h["mean"]
            for name, h in telemetry.snapshot()["histograms"].items()
            if name.startswith(pfx)
        }
        if bucket_stats is not None:
            for bname in list(bucket_stats):
                key = f"solve_{bname}"
                if key in phases:
                    bucket_stats[bname]["solve_s_per_step"] = round(
                        phases[key], 4)
        _log(f"phases (s/step): {phases}")
    except Exception as e:  # profiling must never sink the benchmark
        phases = None
        _log(f"phase profiling failed: {e!r}")

    # --- FLOPs + MFU, per family: the ADMM and IPM get analytic models of
    # their dominant ops (the IPM's band scans have no dense matmuls — its
    # hbm_util is the binding metric); reluqp gets the EXACT dense-matmul
    # iteration count (ops.reluqp.iteration_flops — its whole inner loop
    # IS dense matmul, so flops_per_step/MFU is finally a real measurement
    # rather than a floor).
    # XLA's cost_analysis counts the ADMM while_loop body ONCE, not per
    # iteration, so it can't drive MFU; use an analytic model of the
    # dominant dense ops instead (documented in docs/perf_notes.md):
    #   per iteration:      s_solve = 3 batched (m,m)@(m,) matmuls → 6Bm²
    #   per factorization:  Cholesky ≈ Bm³/3, Linv (triangular solve) ≈ Bm³,
    #                       Sinv ≈ Bm³ (S itself is formed from the sparse
    #                       triple lists — negligible FLOPs)
    # charged once per admm_refactor_every steps, matching the factor-cache
    # cadence (in-loop adaptive-rho refactors add more; warm-started steady
    # state rarely triggers them).
    # Shapes come from bucket_info so the same sums cover both engines:
    # unbucketed = one superset entry (B, m); bucketed = per-type entries
    # summed (iters is the binding bucket's count — a slight overestimate
    # for buckets that freeze earlier).
    K = max(1, engine.params.admm_refactor_every)
    mean_iters = float(np.mean(iters_per_step))
    mfu = None
    peak, mfu_basis = peak_flops_for(device_kind, platform,
                                     args.peak_tflops)
    hbm_util = bytes_per_step = None
    if solver_used == "admm":
        flops_iter = sum(6.0 * b["n_slots"] * b["m_eq"] ** 2 for b in binfo)
        flops_factor = sum((1 / 3 + 1 + 1) * b["n_slots"] * b["m_eq"] ** 3
                           for b in binfo)
        flops_per_step = mean_iters * flops_iter + flops_factor / K
        if peak:
            mfu = (flops_per_step * rate) / peak
    elif solver_used == "reluqp":
        # EXACT dense-matmul count, not an analytic floor: every inner
        # iteration is the three batched einsums of the x-update —
        # ops.reluqp.iteration_flops, pinned against a hand count in
        # tests/test_reluqp.py — times the MEASURED iteration count, plus
        # the rho-bank rebuild amortized over the refresh cadence (the
        # same (1/3+1+1)·m³ per-factor model as the ADMM, times the bank
        # size).  This is the first family whose flops_per_step/MFU is
        # real MXU work rather than an analytic floor (ISSUE 6).
        from dragg_tpu.ops.reluqp import bank_factor_flops, iteration_flops

        R = engine.params.reluqp_bank
        flops_iter = sum(b["n_slots"] * iteration_flops(b["m_eq"], b["n_var"])
                         for b in binfo)
        flops_factor = sum(b["n_slots"] * bank_factor_flops(b["m_eq"], R)
                           for b in binfo)
        flops_per_step = mean_iters * flops_iter + flops_factor / K
        if peak:
            mfu = (flops_per_step * rate) / peak
    else:
        # IPM FLOPs floor (VPU elementwise, per iteration per home): band
        # factor ≈ 2·m·(bw+1)², ~10 forward/backward solve passes at
        # 2·m·(bw+1) MACs each, and ~6 sparse A matvecs at 2·nnz.  The
        # resulting MFU is honestly TINY — the IPM has no dense matmuls
        # and is bandwidth-bound (hbm_util below is the binding metric) —
        # but a populated value lets artifacts show HOW far this solver
        # sits from the MXU roofline instead of reporting null
        # (VERDICT r4 next-2).
        def ipm_iter_flops(b):
            if b["band_bw"] is not None:
                bwp1 = b["band_bw"] + 1
                return b["n_slots"] * (2.0 * b["m_eq"] * bwp1 * bwp1
                                       + 10 * 2.0 * b["m_eq"] * bwp1
                                       + 6 * 2.0 * b["nnz"])
            # Band plan disabled → the factorization is a dense per-home
            # Cholesky: m³/3 plus ~10 triangular-solve passes at 2·m²
            # MACs and the same sparse matvecs.  flops_per_step is ALWAYS
            # populated (round 7): the analytic model is platform-free,
            # so MFU can be back-filled from telemetry the moment a chip
            # is reachable instead of staying null until a re-run.
            return b["n_slots"] * (b["m_eq"] ** 3 / 3.0
                                   + 10 * 2.0 * b["m_eq"] ** 2
                                   + 6 * 2.0 * b["nnz"])

        flops_per_step = mean_iters * sum(ipm_iter_flops(b) for b in binfo)
        if peak:
            mfu = (flops_per_step * rate) / peak
        # The IPM is bandwidth-bound: per iteration the fused band kernels
        # stream the (B, m, bw+1) factor ~9 times (scatter write, Cholesky
        # read+write, and 2 refined solves × [L fwd+bwd ×2 passes + band-S
        # matvec] ≈ 10 passes counting rhs/solution vectors), plus the
        # sparse A matvecs (~4 nnz/row over m rows, read ~6 times across
        # predictor/corrector/residuals).  Loose analytic floor — reported
        # as achieved-bandwidth fraction of the chip's HBM peak.  The band
        # width comes from the engine's actual RCM plan (bw=4 at the MPC
        # pattern today) rather than a hardcoded literal, so a pattern
        # change can't silently skew hbm_util (ADVICE r2).
        if any(b["band_bw"] is None for b in binfo):
            # Band plan disabled: the analytic model below is specific to
            # the banded path — substituting a literal bandwidth here would
            # silently skew hbm_util for that configuration (ADVICE r3);
            # emit null instead.
            bytes_per_step = hbm_util = None
        else:
            bytes_iter = sum(
                b["n_slots"] * b["m_eq"] * 4 * (9 * (b["band_bw"] + 1)
                                                + 6 * 4 + 8)
                for b in binfo)
            bytes_per_step = mean_iters * bytes_iter
            for key, val in PEAK_HBM_BW:
                if key in str(device_kind).lower():
                    hbm_util = (bytes_per_step * rate) / val
                    break

    # Optional profiler trace for manual inspection (BENCH_TRACE_DIR=...).
    trace_dir = os.environ.get("BENCH_TRACE_DIR")
    if trace_dir:
        try:
            with jax.profiler.trace(trace_dir):
                state, outs = run_chunk(state, 0, rps)
                jax.block_until_ready(outs.agg_load)
            _log(f"profiler trace written to {trace_dir}")
        except Exception as e:
            _log(f"profiler trace failed: {e!r}")

    # Which band factor/solve implementation ACTUALLY compiled into the
    # engine ("pallas" or "xla" — "auto" is resolved at build), plus the
    # Pallas compile self-test verdict (None = never attempted, e.g. CPU;
    # False = attempted and fell back).  Without these a silent self-test
    # fallback is indistinguishable from "pallas didn't help" (VERDICT r2).
    from dragg_tpu.ops import pallas_band

    # Which data environment this rate was measured on ("bundled" = the
    # shipped first-party assets; "synthetic" = the rounds-2..4
    # generators; a custom --data-dir reports its path).  Bundled vs
    # synthetic differ drastically in fallback work per step (solve
    # 1.0000 vs 0.9263 — docs/perf_notes.md round 5), so a rate without
    # this field is not comparable to anything (VERDICT r5 weak #3).
    from dragg_tpu.data import bundled_data_dir

    if args.data_dir == "":
        data_label = "synthetic"
    elif args.data_dir is not None:
        data_label = args.data_dir
    else:
        data_label = "bundled" if bundled_data_dir() else "synthetic"

    if flops_per_step is not None:
        telemetry.set_gauge("bench.flops_per_step", float(flops_per_step))
    result = {
        "metric": f"sim_timesteps_per_s_{args.homes}homes_{args.horizon_hours}h_horizon",
        "value": round(rate, 3),
        "unit": "timesteps/s",
        "vs_baseline": round(rate / TARGET_TS_PER_S, 3),
        "platform": platform,
        "device_kind": str(device_kind),
        "n_homes": args.homes,
        # Fleet fields (round 12): C independent communities of n_homes
        # each folded into one batch.  tools/bench_trend.py treats
        # ``communities`` as a HARD series key — fleet rows form their own
        # trend series and never gate against single-community history.
        "communities": args.communities,
        "homes_total": args.homes * args.communities,
        # Cross-process sharding (architecture.md §19): the in-process
        # bench is always one process; the --shards parent branch emits
        # its own record with shards = N.  tools/bench_trend.py treats
        # ``shards`` as a HARD series key (era default 1) — N-shard rows
        # form their own trend series and never gate against in-process
        # history.
        "shards": 1,
        # Chunk-exchange transport (architecture.md §20): meaningless
        # in-process, recorded for self-describing artifacts — a SOFT
        # bench_trend key (a flip annotates, never fragments or gates).
        "transport": "spool",
        # Population composition + scenario pack (ROADMAP item 4):
        # tools/bench_trend.py treats ``mix`` as a HARD series key — a
        # scenario-pack / mix row is a different workload and never gates
        # against the legacy 4-type history (era default: "legacy").
        "mix": mix_label(mix, args.pack),
        "pack": args.pack,
        # Compiled pattern count — flat in C by construction (the fleet
        # folds into the home axis; each type bucket holds C·B_type homes
        # under ONE pattern).  A value that grows with C is a fleet-axis
        # regression.
        "bucket_patterns": len(binfo),
        "solver": solver_used,
        # Which optimization semantics this rate was measured under:
        # "integer" = the shipped default (integer_first_action repair —
        # applied actions are integer duty counts like the reference's
        # GLPK_MI); "relaxation" = LP-relaxation only (VERDICT r4 weak #6:
        # every headline artifact must state which semantics ran).
        "semantics": ("integer" if engine.params.integer_first_action
                      else "relaxation"),
        # Hot-loop matmul policy (ISSUE 11): tools/bench_trend.py treats
        # ``precision`` as a HARD series key (era default "f32") — a
        # bf16x3 rate is a different numerical contract and never gates
        # against the f32 history.  The EFFECTIVE policy is recorded:
        # the ipm has no dense matmuls and ignores the key (its math is
        # bit-identical to f32), so labelling such a run "bf16x3" would
        # fork its trend series with numerically identical rows and
        # silently ungate real regressions.  ``iter_kernel`` is the
        # RESOLVED fused-window implementation (reluqp only).
        "precision": (engine.params.precision
                      if solver_used in ("admm", "reluqp") else "f32"),
        # RL series key (ROADMAP item 1): bench.py measures the MPC
        # baseline — always "none" here.  RL training rows come from
        # tools/bench_rl_fleet.py with rl="<policy>_<agent>";
        # tools/bench_trend.py treats ``rl`` as a HARD series key, so
        # those rows never gate against this baseline history.
        "rl": "none",
        "iter_kernel": (engine.iter_kernel
                        if solver_used == "reluqp" else None),
        "data": data_label,
        "band_kernel": (engine.admm_band_kernel if solver_used == "admm"
                        else engine.band_kernel),
        "pallas_selftest": pallas_band._SELFTEST,
        # Whether the type-bucketed engine ran (tpu.bucketed resolution)
        # and, when it did, each bucket's shape + solve rate (+ per-bucket
        # solve s/step when phase profiling succeeded).
        "bucketed": engine.bucketed,
        "buckets": bucket_stats,
        "horizon_steps": H,
        "chunk_rates": [round(r, 3) for r in chunk_rates],
        "compile_s": round(compile_s, 1),
        # Staged-compile attribution (telemetry/compile_obs): per-stage
        # seconds + persistent-cache verdict (None when the AOT staging
        # fell back to plain jit warmup).
        "compile_stages": creport["stages"] if creport else None,
        "compile_cache": creport["cache"] if creport else None,
        "admm_iters_per_step": round(float(np.mean(iters_per_step)), 1),
        "solve_rate": round(float(np.mean(solve_rates)), 4),
        "phase_s_per_step": {k: round(v, 4) for k, v in phases.items()} if phases else None,
        "flops_per_step_est": flops_per_step,
        "mfu": round(mfu, 4) if mfu is not None else None,
        # What ``mfu`` was computed AGAINST (ISSUE 11 satellite):
        # "tpu_spec:<key>" = the device_kind-keyed public spec table,
        # "cpu_estimate" = the clearly-labelled order-of-magnitude CPU
        # peak (roofline-distance reading only, never cross-platform),
        # "override" = --peak-tflops; null with mfu null = unmatched
        # accelerator (the key is never silently dropped).
        "mfu_basis": mfu_basis if mfu is not None else None,
        "hbm_bytes_per_step_est": bytes_per_step,
        "hbm_util": round(hbm_util, 4) if hbm_util is not None else None,
        # reluqp only: whether the pre-factorized path sufficed, or some
        # home-steps entered the rho bank's fallback exact-refactorization
        # tail (ops/reluqp.py; summed over the timed chunks — the per-step
        # counts ride StepOutputs.bank_fallback_count).
        "reluqp_bank_fallback_home_steps": (
            int(sum(fallback_home_steps)) if solver_used == "reluqp"
            else None),
        "reluqp_bank_fallback": (
            bool(sum(fallback_home_steps) > 0) if solver_used == "reluqp"
            else None),
    }
    # Mirror the headline artifact onto the unified stream and persist
    # the metrics snapshot (no-op on the memory-only bus) so a run dir
    # carries the same numbers the JSON line reports.  The snapshot is
    # per-child-pid: several bench children can share one supervised
    # stream dir (--dual-report, retries) and must not clobber each
    # other's metrics.
    telemetry.emit("bench.result", result=result)
    telemetry.write_snapshot(name=f"metrics.bench_{os.getpid()}.json")
    return result


def child_argv(args, platform: str, attempt: int,
               data_dir: str | None) -> list[str]:
    """Child command line for one measured attempt.  TPU retries
    (attempt > 0) shrink the chunk length: long single device executions
    are the known axon-runtime failure mode (round 2)."""
    steps, chunks = args.steps, args.chunks
    if platform == "tpu" and attempt > 0:
        steps, chunks = max(2, args.steps // 4), args.chunks * 2
    cmd = [
        sys.executable, os.path.abspath(__file__), "--_child",
        "--platform", platform, "--homes", str(args.homes),
        "--horizon-hours", str(args.horizon_hours), "--steps", str(steps),
        "--chunks", str(chunks), "--admm-iters", str(args.admm_iters),
        "--solver", args.solver,
        "--semantics", args.semantics,
        "--bucketed", args.bucketed,
        "--per-home-obs", args.per_home_obs,
        "--communities", str(args.communities),
        "--precision", args.precision,
    ]
    if args.peak_tflops is not None:
        cmd += ["--peak-tflops", str(args.peak_tflops)]
    if args.mix is not None:
        cmd += ["--mix", args.mix]
    if args.pack is not None:
        cmd += ["--pack", args.pack]
    if data_dir is not None:
        # "" is meaningful — it forces the synthetic generators (the
        # rounds-2..4 environment); dropping it would silently run the
        # child on the bundled assets (round-5 review finding).
        cmd += ["--data-dir", data_dir]
    return cmd


def run_sharded_bench(args) -> dict:
    """The ``--shards N`` measurement: the SAME bench population
    (bench_config), run by the shard coordinator across N supervised
    worker processes, each chunk ``--steps`` long × ``--chunks`` chunks.
    This parent stays jax-free (the workers own the backends).

    The headline ``value`` is the steady-state rate — per-chunk device
    seconds EXCLUDING each worker generation's first chunk (its
    compile), mirroring the in-process bench's warmup exclusion;
    ``wall_ts_per_s`` keeps the compile-inclusive number honest.
    ``shards`` is a HARD bench_trend series key (era default 1)."""
    import tempfile

    from dragg_tpu.resilience.supervisor import assert_parent_has_no_jax
    from dragg_tpu.shard.coordinator import run_sharded

    assert_parent_has_no_jax()
    mix = parse_mix(args.mix)
    data_dir = args.data_dir
    cfg = bench_config(args.homes, args.horizon_hours, args.admm_iters,
                       solver=args.solver if args.solver != "auto"
                       else "ipm",
                       data_dir=data_dir, semantics=args.semantics,
                       bucketed=args.bucketed,
                       per_home_obs=args.per_home_obs,
                       communities=args.communities, mix=mix,
                       pack=args.pack, precision=args.precision)
    steps = args.steps * args.chunks
    cfg.setdefault("shard", {})["transport"] = args.transport
    run_dir = os.environ.get("DRAGG_SHARD_RUN_DIR") or tempfile.mkdtemp(
        prefix="bench_shards_")
    t0 = time.perf_counter()
    res = run_sharded(cfg, run_dir=run_dir, steps=steps,
                      workers=args.shards, chunk_steps=args.steps,
                      platform=args.platform, data_dir=data_dir, log=_log)
    elapsed = time.perf_counter() - t0
    homes_total = args.homes * args.communities
    steady = res.get("steady_home_steps_per_s")
    wall_rate = steps / max(elapsed, 1e-9)
    value = (steady / homes_total) if steady else wall_rate
    from dragg_tpu.data import bundled_data_dir

    if data_dir == "":
        data_label = "synthetic"
    elif data_dir is not None:
        data_label = data_dir
    else:
        data_label = "bundled" if bundled_data_dir() else "synthetic"
    return {
        "metric": f"sim_timesteps_per_s_{args.homes}homes_"
                  f"{args.horizon_hours}h_horizon",
        "value": round(value, 3),
        "unit": "timesteps/s",
        "vs_baseline": round(value / TARGET_TS_PER_S, 3),
        "rate_basis": ("steady_device" if steady else "wall"),
        "wall_ts_per_s": round(wall_rate, 3),
        "platform": "+".join(res["platforms"]) or "?",
        "n_homes": args.homes,
        "communities": args.communities,
        "homes_total": homes_total,
        "shards": args.shards,
        "transport": args.transport,
        "shard_ranges": res["ranges"],
        "home_steps_per_s": res["home_steps_per_s"],
        "steady_home_steps_per_s": steady,
        "restarts": res["restarts"],
        "mix": mix_label(mix, args.pack),
        "pack": args.pack,
        "solver": args.solver if args.solver != "auto" else "ipm",
        "semantics": ("integer" if cfg["tpu"].get("integer_first_action",
                                                  True) else "relaxation"),
        "precision": args.precision,
        "rl": "none",
        "data": data_label,
        "solve_rate": res["solve_rate"],
        "compile_s": None,
        "run_dir": run_dir,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    # Defaults = the BASELINE target config (BASELINE.md row "10k-home
    # batched MPC, 24 h horizon").
    ap.add_argument("--homes", type=int, default=10_000,
                    help="homes PER COMMUNITY (fleet total = homes × "
                         "--communities)")
    ap.add_argument("--shards", type=int, default=1,
                    help="shard worker processes N (architecture.md §19): "
                         "N > 1 runs the measurement through the jax-free "
                         "shard coordinator — fleet.communities split into "
                         "N contiguous ranges, one supervised worker "
                         "process (own mesh/backend) each; JSON gains "
                         "shards as a HARD bench_trend series key")
    ap.add_argument("--transport", choices=["spool", "tcp"],
                    default="spool",
                    help="shard chunk exchange (--shards > 1): 'spool' = "
                         "shared-disk outbox files (round 18), 'tcp' = "
                         "workers push checksummed frames to the "
                         "coordinator's chunk-ingest server "
                         "(architecture.md §20); SOFT bench_trend key — "
                         "a flip annotates, never gates")
    ap.add_argument("--communities", type=int, default=1,
                    help="fleet size C (round 12): fold C independent "
                         "communities of --homes each into one batched "
                         "fleet engine; JSON gains communities/"
                         "homes_total fields and bench_trend keys the "
                         "series on C")
    ap.add_argument("--mix", default=None,
                    help="population composition as comma type=fraction "
                         "pairs over pv_only/battery_only/pv_battery/ev/"
                         "heat_pump (base takes the remainder), e.g. "
                         "'pv_only=0.3,ev=0.1,heat_pump=0.1'; default = "
                         "the legacy 0.4/0.1/0.1 bench mix.  The JSON "
                         "gains a canonical 'mix' field that bench_trend "
                         "treats as a HARD series key")
    ap.add_argument("--pack", default=None,
                    help="scenario pack name (data/packs/<name>.toml — "
                         "docs/scenarios.md): its [mix] overrides the "
                         "community counts and its [[events]] compile a "
                         "DR/tariff-shock/outage timeline into the step")
    ap.add_argument("--horizon-hours", type=int, default=24)
    ap.add_argument("--steps", type=int, default=16, help="timesteps per timed chunk")
    ap.add_argument("--chunks", type=int, default=3, help="number of timed chunks")
    ap.add_argument("--admm-iters", type=int, default=1000)
    ap.add_argument("--solver", choices=["auto", "admm", "ipm", "reluqp"],
                    default="ipm",
                    help="ipm (default): the measured-fastest family in "
                         "every recorded regime (docs/perf_notes.md "
                         "'Solver default decision') — skipping the race "
                         "saves half a constrained TPU window; reluqp: the "
                         "pre-factorized dense-matmul family (MXU work by "
                         "construction — ops/reluqp.py); auto: race "
                         "admm/ipm over several warm steps and keep the "
                         "winner")
    ap.add_argument("--platform", choices=["auto", "tpu", "cpu"], default="auto")
    ap.add_argument("--bucketed", choices=["auto", "true", "false"],
                    default="auto",
                    help="type-bucketed shape specialization (tpu.bucketed): "
                         "auto (default) buckets the bench mix; false pins "
                         "the one-batch superset path for A/Bs")
    ap.add_argument("--per-home-obs", choices=["true", "false"],
                    default="true", dest="per_home_obs",
                    help="telemetry.per_home: the round-9 per-home solver "
                         "attribution fold (histograms + worst-k on the "
                         "StepOutputs transfer); false compiles it out — "
                         "for the observatory overhead A/B")
    ap.add_argument("--precision", choices=["f32", "bf16x3"], default="f32",
                    help="tpu.precision hot-loop matmul policy (ISSUE 11): "
                         "bf16x3 = 3-pass bf16 compute with f32 "
                         "accumulation in the dense solver iterations "
                         "(reluqp/admm), f32 residual path; a HARD "
                         "bench_trend series key — bf16x3 rows never "
                         "gate against f32 history")
    def _positive_tflops(text):
        v = float(text)
        if v <= 0:
            raise argparse.ArgumentTypeError(
                f"--peak-tflops must be > 0, got {v}")
        return v

    ap.add_argument("--peak-tflops", type=_positive_tflops, default=None,
                    help="override the per-platform peak-FLOPs table "
                         "(TFLOP/s) for the MFU denominator; the JSON's "
                         "mfu_basis then reads 'override'")
    ap.add_argument("--semantics", choices=["default", "integer", "relaxation"],
                    default="default",
                    help="integer = integer_first_action repair (the shipped "
                         "default since round 5); relaxation = LP-only, for "
                         "cross-round perf A/Bs (rounds <=4 measured this)")
    ap.add_argument("--data-dir", default=None,
                    help="directory with nsrdb.csv + waterdraw_profiles.csv "
                         "(default: the shipped bundled assets — the "
                         "environment headline artifacts measure)")
    ap.add_argument("--synthetic", action="store_true",
                    help="measure the rounds-2..4 synthetic environment "
                         "(alias for --data-dir ''; kept for cross-round "
                         "comparability — the 'data' field labels either "
                         "way)")
    ap.add_argument("--dual-report", action="store_true",
                    help="emit TWO JSON lines: the bundled-data shipped "
                         "default AND the rounds-2..4 synthetic environment "
                         "(each labelled by its 'data' field)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny inline CPU run (50 homes, 4h horizon) for verification")
    ap.add_argument("--_child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.synthetic:
        if args.data_dir not in (None, ""):
            ap.error("--synthetic conflicts with an explicit --data-dir")
        args.data_dir = ""  # "" forces the synthetic generators

    if args.smoke:
        args.platform = "cpu"
        args.homes, args.horizon_hours = 50, 4
        args.steps, args.chunks, args.admm_iters = 4, 1, 1000

    # Child mode (or inline smoke): do the measurement, print JSON.
    if args._child or args.smoke:
        result = run_measured(args)
        print(json.dumps(result))
        return

    if args.shards > 1:
        # Sharded measurement (architecture.md §19): THIS jax-free parent
        # runs the shard coordinator directly — the workers are its
        # supervised children, so no extra supervision wrapper applies.
        print(json.dumps(run_sharded_bench(args)))
        return

    # Parent mode: the supervised ladder (dragg_tpu/resilience) — this
    # process NEVER initializes a jax backend, so a wedged tunnel cannot
    # hang the harness.  Every TPU attempt is probe-gated with the
    # classified liveness check (a hung first attempt is known to WEDGE
    # the tunnel — round 4, docs/onchip_r4/bench_10k_24h.json), retries
    # back off exponentially behind fresh probes, and the CPU fallback
    # runs the FULL requested config so outage-round artifacts still
    # carry a BASELINE-scale number.  Probe verdicts append to
    # $DRAGG_PROBE_LOG (default docs/probe_log.txt); each attempt's
    # classified failure (taxonomy kind) lands in ``attempts``.
    from dragg_tpu.resilience.runner import run_device_job
    from dragg_tpu.resilience.supervisor import assert_parent_has_no_jax

    assert_parent_has_no_jax()
    t_tpu = float(os.environ.get("BENCH_TPU_TIMEOUT", 900))
    t_cpu = float(os.environ.get("BENCH_CPU_TIMEOUT", 1800))
    stall = float(os.environ.get("BENCH_STALL_TIMEOUT", 900)) or None
    probe_log = os.environ.get("DRAGG_PROBE_LOG", "docs/probe_log.txt")

    if args.dual_report:
        # (data label override, --data-dir value) per emitted line.  An
        # explicit --data-dir narrows the dual report to that one env.
        reports = ([(args.data_dir,)] if args.data_dir is not None
                   else [(None,), ("",)])
    else:
        reports = [(args.data_dir,)]

    for (data_dir,) in reports:
        try:
            result, attempts = run_device_job(
                lambda platform, attempt: child_argv(args, platform, attempt,
                                                     data_dir),
                platform=args.platform,
                tpu_deadline_s=t_tpu, cpu_deadline_s=t_cpu,
                retries=1,
                backoff_s=float(os.environ.get("BENCH_RETRY_BACKOFF", 10)),
                probe_log=probe_log, stall_s=stall, log=_log,
            )
        except Exception as e:  # pragma: no cover — harness belt-and-braces
            # The contract is one JSON line per report, rc 0, whatever
            # breaks (round-1 regression: a bare traceback and no number).
            result, attempts = None, [{"error": repr(e)}]
        if result is not None:
            if result.get("platform") == "cpu" and args.platform == "auto":
                result["fallback"] = True
                # Degradation provenance (ISSUE 7 satellite): when the
                # ladder fell back mid-flight — a TPU attempt actually
                # ran (or was probe-skipped) and the same config re-ran
                # on CPU — name the classified failure and where the TPU
                # attempt died (its last heartbeat progress payload), so
                # the artifact says WHY this is a CPU number.
                # tools/bench_trend.py treats the field as a soft key: a
                # degraded run annotates its platform series instead of
                # poisoning it.
                tpu_fail = next(
                    (a for a in reversed(attempts)
                     if a.get("platform") == "tpu" and a.get("failure")),
                    None)
                if tpu_fail is not None:
                    degraded = {"from": "tpu", "to": "cpu",
                                "failure": tpu_fail["failure"]}
                    progress = tpu_fail.get("progress") or {}
                    if progress.get("timestep") is not None:
                        degraded["transition_step"] = progress["timestep"]
                    if progress.get("stage") is not None:
                        degraded["transition_stage"] = progress["stage"]
                    result["degraded"] = degraded
            result["attempts"] = attempts
            print(json.dumps(result))
        else:
            print(json.dumps({
                "metric": f"sim_timesteps_per_s_{args.homes}homes_"
                          f"{args.horizon_hours}h_horizon",
                "value": 0.0,
                "unit": "timesteps/s",
                "vs_baseline": 0.0,
                # Error-path label is best-effort: the jax-free parent
                # can't check whether bundled assets exist.
                "data": ("synthetic" if data_dir == "" else
                         data_dir if data_dir else "default"),
                "error": "all benchmark attempts failed",
                "attempts": attempts,
            }))


if __name__ == "__main__":
    main()
