"""On-chip measurement runbook — the supervised-API successor to the
bash stages of tools/onchip_runbook.sh (which is now a thin wrapper
around this; VERDICT r5 weak #1 context).

Every stage runs under dragg_tpu/resilience supervision: hard deadline,
heartbeat-stall detection, process-group kill, classified failure —
and the runbook is probe-gated BETWEEN stages with the classified
liveness check, so a wedge aborts the pass (naming WEDGED) instead of
burning the remaining timeouts against a dead tunnel.  This parent
process never initializes a jax backend and therefore cannot be wedged.

Round-5 stage plan (unchanged semantics, see the per-stage comments):
hang bisection first, scoped-VMEM auto-policy validation (with the
expected-OOM control), staged engine benches 1k → 10k → 25k, the
engine-level kernel A/B, and scale validation.

    python tools/runbook.py [--out docs/onchip_r6]
    python tools/runbook.py --watch 180 [--out docs/onchip_r6]
        probe at that cadence and fire a full pass into a FRESH
        suffix dir on every DOWN→LIVE edge (the watcher formerly in
        tools/watch_and_run.sh)
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from dragg_tpu.resilience.liveness import check_liveness  # noqa: E402
from dragg_tpu.resilience.supervisor import (assert_parent_has_no_jax,  # noqa: E402
                                             run_supervised)

PY = sys.executable


def stages(out: str) -> list[dict]:
    """The stage table.  ``expect_fail`` marks bounded hypothesis checks
    (the LANE_BLOCK=512 control is EXPECTED to scoped-VMEM OOM);
    ``gate_on`` makes a stage conditional on a predicate over earlier
    results (the 2.5k/5k bracket runs only when the 10k diagnose fails)."""
    diag = [PY, "tools/diagnose_tpu_hang.py"]
    bench = [PY, "bench.py"]

    def diag10k_failed(results):
        r = results.get("diagnose_10k", {})
        return not (r.get("json") or {}).get("all_ok", False)

    return [
        # 0. Staged-compile canary (round-9 observatory): the SMALLEST
        #    possible engine compile run through the staged path (doctor
        #    --compile-check → telemetry/compile_obs), so the very first
        #    on-chip artifact of a pass carries lower/compile/execute
        #    stage timings + the persistent-cache verdict — and a hang
        #    here is stage-attributed before any big compile is risked.
        dict(name="doctor_compile_check", timeout=900,
             argv=[PY, "-m", "dragg_tpu", "doctor", "--compile-check"]),
        # 1. HANG BISECTION FIRST (VERDICT r4 next-1): the 10k engine
        #    compile has never completed on the axon backend and the
        #    abandoned attempt wedges the tunnel; a completed 10k
        #    diagnose also warms the compile cache for the later bench.
        dict(name="diagnose_1k", timeout=1200,
             argv=diag + ["--homes", "1000", "--horizon", "24",
                          "--timeout", "180"]),
        dict(name="diagnose_10k", timeout=3600,
             argv=diag + ["--homes", "10000", "--horizon", "24",
                          "--timeout", "420"]),
        #    Bracket the failing scale while the tunnel still answers.
        dict(name="diagnose_2k5", timeout=1800, gate_on=diag10k_failed,
             argv=diag + ["--homes", "2500", "--horizon", "24",
                          "--timeout", "300"]),
        dict(name="diagnose_5k", timeout=2400, gate_on=diag10k_failed,
             argv=diag + ["--homes", "5000", "--horizon", "24",
                          "--timeout", "420"]),
        # 2. Band-kernel microbench.  The 48h (m=149) run uses NO env
        #    overrides — validates the round-5 scoped-VMEM auto policy.
        dict(name="band_kernel_24h", timeout=600,
             argv=[PY, "tools/bench_band_kernel.py", "--homes", "10000",
                   "--horizon", "24"]),
        dict(name="band_kernel_48h_auto", timeout=600,
             argv=[PY, "tools/bench_band_kernel.py", "--homes", "25000",
                   "--horizon", "48"]),
        #    Hypothesis check (bounded, EXPECTED to scoped-VMEM OOM at
        #    m=149).  BCHUNK=0 pins chunking OFF — the round-4 OOM
        #    config; with it unset the auto policy would B-chunk and the
        #    control could pass for the wrong reason.
        dict(name="band_kernel_48h_lb512_expect_oom", timeout=300,
             expect_fail=True,
             env={"DRAGG_LANE_BLOCK": "512", "DRAGG_PALLAS_BCHUNK": "0"},
             argv=[PY, "tools/bench_band_kernel.py", "--homes", "25000",
                   "--horizon", "48"]),
        # 3. STAGED engine benches, 1k first.  bench.py itself is a
        #    supervised probe-gated ladder; its internal budget (probe 60
        #    + BENCH_TPU_TIMEOUT + probe + retry/2 + CPU fallback) must
        #    FIT the outer timeout.
        dict(name="bench_1k_24h", timeout=900,
             env={"BENCH_TPU_TIMEOUT": "300", "BENCH_CPU_TIMEOUT": "300"},
             argv=bench + ["--homes", "1000", "--horizon-hours", "24",
                           "--solver", "ipm"]),
        # 4. Engine-level band-kernel A/B at 1k (cheap): end-to-end
        #    verdict for the auto kernel policy.
        dict(name="band_ab_1k", timeout=900,
             argv=[PY, "tools/bench_engine_kernels.py", "--homes", "1000",
                   "--horizon-hours", "24"]),
        # 4b. Engine-level SOLVER A/B (round 10): reluqp vs ipm vs admm at
        #     the 512-home bench mix — the on-chip counterpart of the CPU
        #     A/B in docs/perf_notes.md "Round 10", behind the same probe
        #     gates as every stage.  The JSON carries solver_s_per_step +
        #     whether the reluqp rho bank's fallback refactorization ran.
        dict(name="solver_ab_512_reluqp", timeout=1200,
             argv=[PY, "tools/bench_engine_kernels.py", "--homes", "512",
                   "--horizon-hours", "24",
                   "--solvers", "ipm,admm,reluqp"]),
        #     Headline-style reluqp bench at 1k: the first artifact whose
        #     flops_per_step/MFU is the EXACT dense-iteration count
        #     (bench.py reluqp branch) rather than an analytic floor.
        dict(name="bench_1k_24h_reluqp", timeout=900,
             env={"BENCH_TPU_TIMEOUT": "300", "BENCH_CPU_TIMEOUT": "300"},
             argv=bench + ["--homes", "1000", "--horizon-hours", "24",
                           "--solver", "reluqp"]),
        # 4c. Mixed-precision + fused-iteration A/Bs (ISSUE 11), probe-
        #     gated like every stage.  The precision A/B decides whether
        #     bf16x3 (3-pass bf16 MXU compute, f32 residuals —
        #     ops/precision.py) earns the dense families a default on
        #     chip; the iter-kernel A/B settles tpu.iter_kernel's auto
        #     policy (ops/pallas_iter.py — currently lax, no recorded
        #     on-chip number).  CPU control for both is recorded in
        #     docs/perf_notes.md round 14 (expected ~neutral-to-negative
        #     off-chip).
        dict(name="precision_ab_512_reluqp", timeout=1200,
             argv=[PY, "tools/bench_engine_kernels.py", "--homes", "512",
                   "--horizon-hours", "24",
                   "--solvers", "reluqp,admm",
                   "--precision", "f32,bf16x3"]),
        dict(name="iter_kernel_ab_512_reluqp", timeout=1200,
             argv=[PY, "tools/bench_engine_kernels.py", "--homes", "512",
                   "--horizon-hours", "24",
                   "--iter-kernels", "lax,pallas"]),
        #     Headline-style bf16x3 bench at 1k: its own bench_trend
        #     series (precision is a hard key), with MFU now real —
        #     mfu_basis names the spec-table entry it was computed
        #     against.  Budget: probe 60 + attempt 300 + backoff 10 +
        #     probe 60 + retry 150 + CPU 300 = 880 < 900.
        dict(name="bench_1k_24h_reluqp_bf16x3", timeout=900,
             env={"BENCH_TPU_TIMEOUT": "300", "BENCH_CPU_TIMEOUT": "300"},
             argv=bench + ["--homes", "1000", "--horizon-hours", "24",
                           "--solver", "reluqp",
                           "--precision", "bf16x3"]),
        # 5. Headline bench, BASELINE row-3 config (10k x 24h), SHIPPED
        #    semantics, DUAL-REPORT: one line on the bundled shipped
        #    default, one on the rounds-2..4 synthetic environment
        #    (VERDICT r5 weak #3).  Internal budget per line: probe 60 +
        #    attempt 600 + backoff 10 + probe 60 + retry 300 (half
        #    deadline) + CPU 600 = 1630; x2 lines = 3260 < 3600.
        dict(name="bench_10k_24h", timeout=3600,
             env={"BENCH_TPU_TIMEOUT": "600", "BENCH_CPU_TIMEOUT": "600"},
             argv=bench + ["--homes", "10000", "--horizon-hours", "24",
                           "--solver", "ipm", "--dual-report"]),
        #    Relaxation A/B — the semantics rounds 2-4 measured, on the
        #    synthetic weather those rounds ran (both knobs pinned for
        #    comparability).
        dict(name="bench_10k_24h_relaxation", timeout=1800,
             env={"BENCH_TPU_TIMEOUT": "600", "BENCH_CPU_TIMEOUT": "600"},
             argv=bench + ["--homes", "10000", "--horizon-hours", "24",
                           "--solver", "ipm", "--semantics", "relaxation",
                           "--data-dir", ""]),
        # 6. The row-5 per-chip slice: 25k homes x 48h, auto VMEM policy.
        #    Internal: 60 + 600 + 10 + 60 + 300 + 1200 = 2230 < 2400.
        dict(name="bench_25k_48h", timeout=2400,
             env={"BENCH_TPU_TIMEOUT": "600", "BENCH_CPU_TIMEOUT": "1200"},
             argv=bench + ["--homes", "25000", "--horizon-hours", "48",
                           "--steps", "8", "--solver", "ipm"]),
        # 7. Scale validation at 10k x 48h x 2 days (solve rate + comfort;
        #    validate_scale supervises its own measurement child).
        dict(name="validate_10k_48h", timeout=2400,
             argv=[PY, "tools/validate_scale.py", "--homes", "10000",
                   "--horizon-hours", "48", "--days", "2",
                   "--solver", "ipm"]),
        # 8. Fleet RL training smoke (ROADMAP item 1): C=8 communities
        #    of 64 homes, shared IMPALA-style policy, one fused jitted
        #    step — first on-chip home-steps/s + learner-steps/s for the
        #    RL workload (its own bench_trend series: rl is a hard key).
        #    bench_rl_fleet supervises its own measurement child
        #    (deadline + stall beat), probe-gated here like every stage.
        dict(name="rl_fleet_smoke_8x64", timeout=1200,
             argv=[PY, "tools/bench_rl_fleet.py", "--homes", "64",
                   "--communities", "8", "--hours", "24",
                   "--horizon-hours", "6", "--deadline", "900",
                   "--stall", "300"]),
    ]


def run_pass(out: str, probe_timeout: float = 60.0) -> int:
    """One full runbook pass into ``out``.  Returns 0 when every stage
    either succeeded or failed as expected; 1 on abort (tunnel down or
    wedged between stages) or unexpected stage failure."""
    assert_parent_has_no_jax()
    os.makedirs(out, exist_ok=True)
    probe_log = os.path.join(out, "probe_log.txt")
    transcript = os.path.join(out, "runbook.log")
    # One telemetry stream per pass (<out>/events.jsonl): probe verdicts,
    # supervisor lifecycle, and the stage children's own events (the
    # supervisor exports $DRAGG_TELEMETRY_DIR) — one forensic file per
    # on-chip window (docs/telemetry.md).
    from dragg_tpu import telemetry

    telemetry.init_run(out)

    def log(msg: str) -> None:
        line = f"[{time.strftime('%H:%M:%S')}] {msg}"
        print(line, flush=True)
        with open(transcript, "a") as f:
            f.write(line + "\n")

    def gate(label: str) -> bool:
        report = check_liveness(probe_timeout, log_path=probe_log)
        log(f"probe({label}): "
            f"{'LIVE' if report.alive else report.kind} {report.detail}")
        return report.alive

    if not gate("start"):
        log("TPU unreachable; aborting")
        return 1
    env_base = dict(os.environ, DRAGG_PROBE_LOG=probe_log)
    results: dict[str, object] = {}
    rc = 0
    for stage in stages(out):
        if stage.get("gate_on") and not stage["gate_on"](results):
            continue
        name = stage["name"]
        env = dict(env_base, **stage.get("env", {}))
        res = run_supervised(
            stage["argv"], stage["timeout"], label=name, env=env, cwd=ROOT,
            stdout_path=os.path.join(out, f"{name}.json"),
            stderr_path=os.path.join(out, f"{name}.log"),
            log=log)
        results[name] = {"ok": res.ok, "failure": res.failure,
                         "json": res.json}
        if res.json is not None:
            log(f"{name}: {json.dumps(res.json)[:2000]}")
        if not res.ok and stage.get("expect_fail"):
            log(f"{name}: failed AS EXPECTED ({res.failure}) — hypothesis "
                "control")
        elif not res.ok:
            rc = 1
        # Probe BETWEEN stages: a wedge aborts the pass instead of
        # burning the remaining stage timeouts (round-5 runbook rule).
        if not gate(f"after_{name}"):
            log(f"tunnel lost after {name}; aborting pass")
            return 1
    log("runbook pass complete — record results in docs/perf_notes.md")
    return rc


def watch(out: str, cadence_s: float) -> int:
    """Fire a full pass into a FRESH suffix dir on every DOWN→LIVE edge
    (live windows are the scarce resource — rounds 2-5 had one in four
    rounds).  A pass that fails does NOT latch 'live': the edge stays
    armed so a transient flap cannot suppress a real window."""
    n = 0
    prev_live = False
    while True:
        report = check_liveness(60.0,
                                log_path=os.path.join(out, "probe_log.txt"))
        if report.alive and not prev_live:
            n += 1
            # Always a fresh suffix dir: the base OUT holds committed
            # artifacts from earlier passes and per-stage writes would
            # truncate them.
            rc = run_pass(f"{out}_w{n}")
            print(f"[{time.strftime('%H:%M:%S')}] runbook pass {n} rc={rc}",
                  flush=True)
            prev_live = rc == 0
        else:
            prev_live = report.alive
        time.sleep(cadence_s)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/onchip_r6")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="probe cadence seconds; 0 = single pass now")
    args = ap.parse_args()
    if args.watch:
        return watch(args.out, args.watch)
    return run_pass(args.out)


if __name__ == "__main__":
    sys.exit(main())
