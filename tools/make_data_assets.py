"""Generate the repo's first-party bundled data assets (round-5, VERDICT
r4 missing #1).

The reference ships real NREL NSRDB weather and NEEA water-draw profiles
(`dragg/data/nsrdb.csv`, `dragg/data/waterdraw_profiles.csv`, ingested at
dragg/aggregator.py:129-165,361-377) so its DEFAULT run exercises the
file-ingestion path.  We do not copy data files; instead this tool
synthesizes physically-plausible series with the framework's own
generators (dragg_tpu/data.py) and writes them in the REFERENCE'S EXACT
FILE LAYOUT, so:

* `data/nsrdb.csv` — two metadata rows, then
  Year/Month/Day/Hour/Minute/GHI/Relative Humidity/Temperature/Pressure
  at half-hourly cadence covering 2015 + a 7-day horizon margin (the
  reference file is half-hourly 2015; the loader keeps Minute==0 rows at
  dt=1 and casts GHI/OAT to int — dragg/aggregator.py:139-152).
* `data/waterdraw_profiles.csv` — minutely flow profiles, datetime
  index, one `Flow_*` column per profile (reference: 10 profiles x 7
  days starting 2020-01-01).

Deterministic: re-running reproduces the checked-in files byte-for-byte.

Usage: python tools/make_data_assets.py [--out data]
"""

import argparse
import os
import sys
from datetime import datetime

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd

from dragg_tpu.data import synth_waterdraw_profiles, synth_weather

SEED = 12  # the shipped config default (simulation.random_seed)


def write_nsrdb(path: str) -> None:
    # Half-hourly = dt=2 steps/hour from the synthesizer.
    days = 366 + 7  # 2015 is not a leap year but keep horizon margin
    oat, ghi, start = synth_weather(datetime(2015, 1, 1), days=days, dt=2,
                                    seed=SEED)
    n = len(oat)
    ts = pd.date_range("2015-01-01", periods=n, freq="30min")
    # Plausible co-variates for layout parity (unused by the loader).
    hod = ts.hour + ts.minute / 60.0
    rh = np.clip(70 - 0.8 * (oat - 10) + 10 * np.cos(2 * np.pi * hod / 24),
                 5, 100)
    pressure = np.full(n, 1013.0)
    df = pd.DataFrame({
        "Year": ts.year, "Month": ts.month, "Day": ts.day,
        "Hour": ts.hour, "Minute": ts.minute,
        "GHI": ghi.astype(int),
        "Relative Humidity": np.round(rh, 2),
        "Temperature": oat.astype(int),
        "Pressure": pressure,
    })
    meta1 = ("Source,Location ID,City,State,Country,Latitude,Longitude,"
             "Time Zone,Elevation,Local Time Zone,GHI Units,Temperature "
             "Units,Version")
    meta2 = ("dragg-tpu-synth,0,-,-,-,29.69,-95.34,-6,12,-6,w/m2,c,"
             "round5-seed12")
    with open(path, "w") as f:
        f.write(meta1 + "\n" + meta2 + "\n")
        df.to_csv(f, index=False)


def write_waterdraws(path: str) -> None:
    df = synth_waterdraw_profiles(n_profiles=10, days=7, seed=SEED)
    df.index.name = None
    df.round(3).to_csv(path, date_format="%Y-%m-%d %H:%M:%S")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "data"))
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)
    nsrdb = os.path.join(args.out, "nsrdb.csv")
    wd = os.path.join(args.out, "waterdraw_profiles.csv")
    write_nsrdb(nsrdb)
    write_waterdraws(wd)
    for p in (nsrdb, wd):
        print(f"wrote {p} ({os.path.getsize(p)} bytes)")


if __name__ == "__main__":
    main()
