"""Engine-level band-kernel A/B: time warm engine steps for each
``tpu.band_kernel`` family on whatever backend is up.

The round-4 microbench (docs/onchip_r4/band_kernel_24h.json) showed the
pallas refined solve 0.73x vs the XLA scan on real Mosaic while the
factor is 1.41x the other way — so the engine-level winner is not
decidable from kernel timings alone.  This tool gives the end-to-end
verdict that sets the ``auto`` policy.

Prints one JSON line: {kernel: s/step} + the winner.

Usage: python tools/bench_engine_kernels.py [--homes 1000]
       [--horizon-hours 24] [--steps 6] [--kernels pallas,xla,cr]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--homes", type=int, default=1000)
    ap.add_argument("--horizon-hours", type=int, default=24)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--kernels", default="pallas,xla,cr")
    ap.add_argument("--bucketed", choices=["auto", "true", "false"],
                    default="false",
                    help="tpu.bucketed for the timed engine.  Default "
                         "false: the kernel verdicts that set the 'auto' "
                         "band policy must stay comparable to the "
                         "superset-shaped docs/onchip_r4 artifacts — a "
                         "bucketed engine changes every factored shape, "
                         "which would skew the A/B for non-kernel reasons "
                         "(CLAUDE.md: cross-round perf A/Bs pin "
                         "--bucketed false)")
    args = ap.parse_args()

    import jax

    import bench as bench_mod
    from dragg_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    dev = jax.devices()[0]  # device-call-ok: runs under the runbook supervisor deadline
    res = {
        "tool": "bench_engine_kernels",
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "homes": args.homes, "horizon_h": args.horizon_hours,
        "steps": args.steps, "bucketed": args.bucketed,
    }

    timings = {}
    for kern in args.kernels.split(","):
        kern = kern.strip()
        try:
            # THE benchmark community (bench.build — same population mix
            # and sim window as the headline bench, one definition).
            eng, _np = bench_mod.build(args.homes, args.horizon_hours,
                                       1000, solver="ipm",
                                       band_kernel=kern,
                                       bucketed=args.bucketed)
            eng = eng if eng.band_kernel == kern else None
            if eng is None:
                timings[kern] = None
                res[f"{kern}_err"] = "kernel did not resolve as requested"
                continue
            st = eng.init_state()
            rp0 = np.zeros(eng.params.horizon, dtype=np.float32)
            t_c0 = time.perf_counter()
            st, out = eng.step(st, 0, rp0)          # compile + cold step
            jax.block_until_ready(out.agg_load)
            res[f"{kern}_compile_s"] = round(time.perf_counter() - t_c0, 1)
            t0 = time.perf_counter()
            done = 0
            for i in range(1, args.steps + 1):
                st, out = eng.step(st, i, rp0)
                jax.block_until_ready(out.agg_load)
                done = i
                if time.perf_counter() - t0 > 120:
                    break
            timings[kern] = round((time.perf_counter() - t0) / done, 4)
        except Exception as e:
            timings[kern] = None
            res[f"{kern}_err"] = repr(e)[:300]

    res["s_per_step"] = timings
    alive = {k: v for k, v in timings.items() if v}
    if alive:
        res["winner"] = min(alive, key=alive.get)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
