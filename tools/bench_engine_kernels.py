"""Engine-level A/B: time warm engine steps for each ``tpu.band_kernel``
family — or, with ``--solvers``, for each SOLVER family — on whatever
backend is up.

The round-4 microbench (docs/onchip_r4/band_kernel_24h.json) showed the
pallas refined solve 0.73x vs the XLA scan on real Mosaic while the
factor is 1.41x the other way — so the engine-level winner is not
decidable from kernel timings alone.  This tool gives the end-to-end
verdict that sets the ``auto`` policy.

``--solvers ipm,admm,reluqp`` switches the swept axis from band kernels
to solver families (round 10: the reluqp engine-level A/B the runbook
runs on chip) — same build recipe, same warm-step timing loop, one
engine per family, ``solver_s_per_step`` in the JSON.

Prints one JSON line: {kernel-or-solver: s/step} + the winner.

Usage: python tools/bench_engine_kernels.py [--homes 1000]
       [--horizon-hours 24] [--steps 6] [--kernels pallas,xla,cr]
       [--solvers ipm,admm,reluqp] [--bucketed auto|true|false]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--homes", type=int, default=1000)
    ap.add_argument("--horizon-hours", type=int, default=24)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--kernels", default="pallas,xla,cr")
    ap.add_argument("--solvers", default="",
                    help="comma list of solver families (ipm,admm,reluqp): "
                         "sweep SOLVERS at a fixed auto band kernel "
                         "instead of band kernels at the fixed ipm solver")
    ap.add_argument("--bucketed", choices=["auto", "true", "false"],
                    default="false",
                    help="tpu.bucketed for the timed engine.  Default "
                         "false: the kernel verdicts that set the 'auto' "
                         "band policy must stay comparable to the "
                         "superset-shaped docs/onchip_r4 artifacts — a "
                         "bucketed engine changes every factored shape, "
                         "which would skew the A/B for non-kernel reasons "
                         "(CLAUDE.md: cross-round perf A/Bs pin "
                         "--bucketed false)")
    args = ap.parse_args()

    import jax

    import bench as bench_mod
    from dragg_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    dev = jax.devices()[0]  # device-call-ok: runs under the runbook supervisor deadline
    res = {
        "tool": "bench_engine_kernels",
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "homes": args.homes, "horizon_h": args.horizon_hours,
        "steps": args.steps, "bucketed": args.bucketed,
    }

    solver_mode = bool(args.solvers.strip())
    sweep = (args.solvers if solver_mode else args.kernels).split(",")

    def build_variant(label):
        """One engine per sweep point: solver families at the auto band
        kernel (--solvers), or band kernels at the fixed ipm solver —
        always THE benchmark community (bench.build: same population mix
        and sim window as the headline bench, one definition)."""
        if solver_mode:
            eng, _ = bench_mod.build(args.homes, args.horizon_hours, 1000,
                                     solver=label, bucketed=args.bucketed)
            return eng if eng.params.solver == label else None
        eng, _ = bench_mod.build(args.homes, args.horizon_hours, 1000,
                                 solver="ipm", band_kernel=label,
                                 bucketed=args.bucketed)
        return eng if eng.band_kernel == label else None

    timings = {}
    for label in sweep:
        label = label.strip()
        try:
            eng = build_variant(label)
            if eng is None:
                timings[label] = None
                res[f"{label}_err"] = "variant did not resolve as requested"
                continue
            st = eng.init_state()
            rp0 = np.zeros(eng.params.horizon, dtype=np.float32)
            t_c0 = time.perf_counter()
            st, out = eng.step(st, 0, rp0)          # compile + cold step
            jax.block_until_ready(out.agg_load)
            res[f"{label}_compile_s"] = round(time.perf_counter() - t_c0, 1)
            t0 = time.perf_counter()
            done = 0
            fb_total = 0.0
            for i in range(1, args.steps + 1):
                st, out = eng.step(st, i, rp0)
                jax.block_until_ready(out.agg_load)
                fb_total += float(np.asarray(out.bank_fallback_count))
                done = i
                if time.perf_counter() - t0 > 120:
                    break
            timings[label] = round((time.perf_counter() - t0) / done, 4)
            if solver_mode and label == "reluqp":
                # Whether the pre-factorized path sufficed on the timed
                # steps, or the rho bank's fallback refactorization ran.
                res["reluqp_bank_fallback_home_steps"] = int(fb_total)
        except Exception as e:
            timings[label] = None
            res[f"{label}_err"] = repr(e)[:300]

    res["solver_s_per_step" if solver_mode else "s_per_step"] = timings
    alive = {k: v for k, v in timings.items() if v}
    if alive:
        res["winner"] = min(alive, key=alive.get)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
