"""Engine-level A/B: time warm engine steps for each ``tpu.band_kernel``
family — or, with ``--solvers``, for each SOLVER family — on whatever
backend is up.

The round-4 microbench (docs/onchip_r4/band_kernel_24h.json) showed the
pallas refined solve 0.73x vs the XLA scan on real Mosaic while the
factor is 1.41x the other way — so the engine-level winner is not
decidable from kernel timings alone.  This tool gives the end-to-end
verdict that sets the ``auto`` policy.

``--solvers ipm,admm,reluqp`` switches the swept axis from band kernels
to solver families (round 10: the reluqp engine-level A/B the runbook
runs on chip) — same build recipe, same warm-step timing loop, one
engine per family, ``solver_s_per_step`` in the JSON.

``--precision f32,bf16x3`` (ISSUE 11) crosses whatever axis is swept
with the hot-loop matmul policy (labels become ``<label>@<precision>``
when more than one precision is listed) — the engine-level A/B that
decides whether bf16x3 earns a default on chip.  ``--iter-kernels
lax,pallas`` sweeps the fused reluqp check-window kernel
(ops/pallas_iter.py) at the fixed reluqp solver — the A/B that settles
``tpu.iter_kernel``'s ``auto`` policy (currently lax: no recorded
on-chip number).

Prints one JSON line: {kernel-or-solver: s/step} + the winner.

Usage: python tools/bench_engine_kernels.py [--homes 1000]
       [--horizon-hours 24] [--steps 6] [--kernels pallas,xla,cr]
       [--solvers ipm,admm,reluqp] [--iter-kernels lax,pallas]
       [--precision f32,bf16x3] [--bucketed auto|true|false]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--homes", type=int, default=1000)
    ap.add_argument("--horizon-hours", type=int, default=24)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--kernels", default="pallas,xla,cr")
    ap.add_argument("--solvers", default="",
                    help="comma list of solver families (ipm,admm,reluqp): "
                         "sweep SOLVERS at a fixed auto band kernel "
                         "instead of band kernels at the fixed ipm solver")
    ap.add_argument("--iter-kernels", default="", dest="iter_kernels",
                    help="comma list of reluqp check-window kernels "
                         "(lax,pallas — ops/pallas_iter.py): sweep the "
                         "fused-iteration implementation at the fixed "
                         "reluqp solver; decides tpu.iter_kernel's auto "
                         "policy (ISSUE 11)")
    ap.add_argument("--precision", default="f32",
                    help="comma list of hot-loop matmul policies "
                         "(f32,bf16x3 — ops/precision.py) crossed with "
                         "the swept axis; >1 entry labels timings "
                         "<label>@<precision>")
    ap.add_argument("--bucketed", choices=["auto", "true", "false"],
                    default="false",
                    help="tpu.bucketed for the timed engine.  Default "
                         "false: the kernel verdicts that set the 'auto' "
                         "band policy must stay comparable to the "
                         "superset-shaped docs/onchip_r4 artifacts — a "
                         "bucketed engine changes every factored shape, "
                         "which would skew the A/B for non-kernel reasons "
                         "(CLAUDE.md: cross-round perf A/Bs pin "
                         "--bucketed false)")
    args = ap.parse_args()

    import jax

    import bench as bench_mod
    from dragg_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    dev = jax.devices()[0]  # dragg: disable=DT004, runs under the runbook supervisor deadline
    res = {
        "tool": "bench_engine_kernels",
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "homes": args.homes, "horizon_h": args.horizon_hours,
        "steps": args.steps, "bucketed": args.bucketed,
    }

    solver_mode = bool(args.solvers.strip())
    iter_mode = bool(args.iter_kernels.strip())
    if solver_mode and iter_mode:
        raise SystemExit("--solvers and --iter-kernels are exclusive axes")
    sweep = (args.iter_kernels if iter_mode
             else args.solvers if solver_mode
             else args.kernels).split(",")
    precisions = [p.strip() for p in args.precision.split(",") if p.strip()]
    res["precision"] = ",".join(precisions)

    def build_variant(label, precision):
        """One engine per sweep point: solver families at the auto band
        kernel (--solvers), reluqp iteration kernels (--iter-kernels),
        or band kernels at the fixed ipm solver — always THE benchmark
        community (bench.build: same population mix and sim window as
        the headline bench, one definition), crossed with the hot-loop
        precision policy."""
        if iter_mode:
            eng, _ = bench_mod.build(args.homes, args.horizon_hours, 1000,
                                     solver="reluqp", bucketed=args.bucketed,
                                     precision=precision, iter_kernel=label)
            return eng if eng.iter_kernel == label else None
        if solver_mode:
            eng, _ = bench_mod.build(args.homes, args.horizon_hours, 1000,
                                     solver=label, bucketed=args.bucketed,
                                     precision=precision)
            return eng if eng.params.solver == label else None
        eng, _ = bench_mod.build(args.homes, args.horizon_hours, 1000,
                                 solver="ipm", band_kernel=label,
                                 bucketed=args.bucketed,
                                 precision=precision)
        return eng if eng.band_kernel == label else None

    def consumes_precision(label):
        """Only the dense families consume the policy: the band-kernel
        sweep runs the fixed ipm solver (no dense matmuls — bit-identical
        under any policy), so crossing it with --precision would time
        identical engines twice and emit noise rows a reader could take
        as a precision verdict."""
        if iter_mode:
            return True   # fixed reluqp solver
        if solver_mode:
            return label in ("admm", "reluqp")
        return False

    timings = {}
    points = []
    for lbl in sweep:
        lbl = lbl.strip()
        if consumes_precision(lbl):
            points += [(lbl, prec, len(precisions) > 1)
                       for prec in (precisions or ["f32"])]
        else:
            points.append((lbl, "f32", False))
    for label, precision, tag in points:
        if tag:
            label = f"{label}@{precision}"
        try:
            eng = build_variant(label.split("@")[0], precision)
            if eng is None:
                timings[label] = None
                res[f"{label}_err"] = "variant did not resolve as requested"
                continue
            st = eng.init_state()
            rp0 = np.zeros(eng.params.horizon, dtype=np.float32)
            t_c0 = time.perf_counter()
            st, out = eng.step(st, 0, rp0)          # compile + cold step
            jax.block_until_ready(out.agg_load)
            res[f"{label}_compile_s"] = round(time.perf_counter() - t_c0, 1)
            t0 = time.perf_counter()
            done = 0
            fb_total = 0.0
            for i in range(1, args.steps + 1):
                st, out = eng.step(st, i, rp0)
                jax.block_until_ready(out.agg_load)
                fb_total += float(np.asarray(out.bank_fallback_count))
                done = i
                if time.perf_counter() - t0 > 120:
                    break
            timings[label] = round((time.perf_counter() - t0) / done, 4)
            if (solver_mode or iter_mode) \
                    and label.split("@")[0] in ("reluqp", "lax", "pallas"):
                # Whether the pre-factorized path sufficed on the timed
                # steps, or the rho bank's fallback refactorization ran
                # (per sweep point — a precision/kernel flip can change
                # who needs the tail).
                res[f"{label}_bank_fallback_home_steps"] = int(fb_total)
        except Exception as e:
            timings[label] = None
            res[f"{label}_err"] = repr(e)[:300]

    res["iter_kernel_s_per_step" if iter_mode
        else "solver_s_per_step" if solver_mode
        else "s_per_step"] = timings
    alive = {k: v for k, v in timings.items() if v}
    if alive:
        res["winner"] = min(alive, key=alive.get)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
