"""Staged TPU-hang localizer — run FIRST in a live window if the engine
bench ever hangs again.

Round-4 evidence (docs/onchip_r4/bench_10k_24h.json): the 10k-home bench
hung for 900 s somewhere between "building engine" and the first step,
while microbench-scale kernels compiled fine the same minute — and the
abandoned compile then WEDGED the tunnel for every later backend init.
This tool bisects that interval: each stage runs in its OWN subprocess
under its own hard timeout (a hung stage cannot wedge the parent, and
the tunnel state is re-probed between stages), printing one JSON line
with per-stage verdicts.

Stages:
  probe          jax.devices() (backend init)
  selftest       pallas compile self-test (first Mosaic kernel compile)
  device_put     commit 10k-home-sized constants to HBM + tiny jnp op
  jit_big        compile one big fused elementwise jit (engine-glue scale)
  engine_small   build + 1 step at 256 homes
  engine_build   build ONLY at --homes (no step)
  engine_step    build + 1 step at --homes

Usage: python tools/diagnose_tpu_hang.py [--homes 10000] [--horizon 24]
       [--timeout 240]
"""

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

STAGES = {
    "probe": """
import jax
d = jax.devices()[0]
print("STAGE_OK", d.platform, d.device_kind)
""",
    "selftest": """
from dragg_tpu.ops import pallas_band
print("STAGE_OK", pallas_band.available())
""",
    "device_put": """
import numpy as np, jax, jax.numpy as jnp
arrs = [jax.device_put(np.random.default_rng(0).standard_normal(
    ({homes}, 64)).astype(np.float32)) for _ in range(8)]
s = jnp.asarray(0.0)
for a in arrs:
    s = s + jnp.sum(a)
print("STAGE_OK", float(s) == float(s))
""",
    "jit_big": """
import numpy as np, jax, jax.numpy as jnp
n_var = 9 * {horizon} + 5
x = jax.device_put(np.ones(({homes}, n_var), np.float32))
@jax.jit
def f(x):
    for _ in range(20):
        x = jnp.tanh(x) * 1.01 + 0.1
    return x.sum()
print("STAGE_OK", float(f(x)) != 0.0)
""",
    "engine_small": """
import numpy as np
import bench
eng, np_ = bench.build(256, {horizon}, 1000, solver="ipm")
st = eng.init_state()
st, out = eng.step(st, 0, np_.zeros(eng.params.horizon, np_.float32))
import jax; jax.block_until_ready(out.agg_load)
print("STAGE_OK", float(out.agg_load) == float(out.agg_load))
""",
    "engine_build": """
import bench
eng, np_ = bench.build({homes}, {horizon}, 1000, solver="ipm")
print("STAGE_OK", eng.band_kernel)
""",
    "engine_step": """
import numpy as np
import bench
eng, np_ = bench.build({homes}, {horizon}, 1000, solver="ipm")
st = eng.init_state()
st, out = eng.step(st, 0, np_.zeros(eng.params.horizon, np_.float32))
import jax; jax.block_until_ready(out.agg_load)
print("STAGE_OK", float(out.agg_load) == float(out.agg_load))
""",
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--homes", type=int, default=10_000)
    ap.add_argument("--horizon", type=int, default=24)
    ap.add_argument("--timeout", type=float, default=240.0,
                    help="per-stage hard timeout, seconds")
    ap.add_argument("--stages", default=",".join(STAGES),
                    help="comma list to run (default: all, in order)")
    args = ap.parse_args()

    from dragg_tpu.utils.probe import probe_tpu

    results = {"tool": "diagnose_tpu_hang", "homes": args.homes,
               "horizon": args.horizon, "stages": {}}
    for name in args.stages.split(","):
        name = name.strip()
        if not name:
            continue
        code = STAGES[name].format(homes=args.homes, horizon=args.horizon)
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], cwd=ROOT,
                capture_output=True, text=True, timeout=args.timeout)
            dt = round(time.monotonic() - t0, 1)
            ok = proc.returncode == 0 and "STAGE_OK" in (proc.stdout or "")
            results["stages"][name] = {
                "ok": ok, "s": dt,
                **({} if ok else
                   {"err": ((proc.stderr or "")[-400:]).replace("\n", " ")}),
            }
        except subprocess.TimeoutExpired:
            results["stages"][name] = {
                "ok": False, "s": round(time.monotonic() - t0, 1),
                "err": f"HUNG >{args.timeout:.0f}s"}
        print(f"[{name}] {results['stages'][name]}", file=sys.stderr,
              flush=True)
        if not results["stages"][name]["ok"]:
            # A hung stage very likely wedged the tunnel — verify and stop
            # rather than stacking more hung compiles onto it.
            alive, detail = probe_tpu(60.0)
            results["post_failure_probe"] = {"alive": alive, "detail": detail}
            if not alive:
                results["verdict"] = (
                    f"stage '{name}' failed AND the tunnel is now wedged — "
                    "the failure is the wedge trigger; restart the tunnel "
                    "before retrying")
                break
    # ≥1 stage required: all() over an empty dict is vacuously True, and
    # the runbook greps '"all_ok": true' — a no-stage artifact must not
    # read as a clean pass (ADVICE r5 #4).
    results["all_ok"] = bool(results["stages"]) and \
        all(s["ok"] for s in results["stages"].values())
    print(json.dumps(results))


if __name__ == "__main__":
    main()
