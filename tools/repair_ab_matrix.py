"""Pin the integer-repair A/B matrix as a committed JSON artifact
(VERDICT r5 weak #5: the 512-home matrix existed only as a perf_notes
narrative).

Runs the full combination matrix — solver {admm, ipm} × repair
{off, project, resolve} — on the SAME 512-home mixed community over one
simulated day, recording per-combo: solve rate, max comfort-band
violation on solved steps, community cost, and mean solver iterations.
The committed artifact (docs/repair_ab_512_r6.json) is what the
closed-loop MILP test's claims cite.

Usage: python tools/repair_ab_matrix.py [--homes 512] [--horizon-hours 6]
           [--steps 24] [--out docs/repair_ab_512_r6.json]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_combo(n, horizon_h, steps, solver, repair):
    import jax
    import numpy as np

    from dragg_tpu.config import default_config
    from dragg_tpu.data import (load_environment, load_waterdraw_profiles,
                                waterdraw_path)
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes
    from dragg_tpu.resilience.heartbeat import beat

    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = int(0.4 * n)
    cfg["community"]["homes_battery"] = int(0.1 * n)
    cfg["community"]["homes_pv_battery"] = int(0.1 * n)
    cfg["home"]["hems"]["prediction_horizon"] = horizon_h
    cfg["home"]["hems"]["solver"] = solver
    cfg["tpu"]["integer_first_action"] = repair != "off"
    if repair != "off":
        cfg["tpu"]["integer_repair"] = repair

    env = load_environment(cfg)
    dt = int(cfg["agg"]["subhourly_steps"])
    wd = load_waterdraw_profiles(waterdraw_path(cfg, None), seed=12)
    homes = create_homes(cfg, steps, dt, wd)
    batch = build_home_batch(homes, horizon_h * dt, dt,
                             int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    eng = make_engine(batch, env, cfg, 0)
    state = eng.init_state()
    rps = np.zeros((steps, eng.params.horizon), dtype=np.float32)
    t0 = time.perf_counter()
    state, outs = eng.run_chunk(state, 0, rps)
    jax.block_until_ready(outs.agg_load)
    wall = time.perf_counter() - t0

    solved = np.asarray(outs.correct_solve)
    tin = np.asarray(outs.temp_in)
    twh = np.asarray(outs.temp_wh)
    vi = np.where(solved > 0,
                  np.maximum(np.asarray(batch.temp_in_min)[None] - tin,
                             tin - np.asarray(batch.temp_in_max)[None]), -1.0)
    vw = np.where(solved > 0,
                  np.maximum(np.asarray(batch.temp_wh_min)[None] - twh,
                             twh - np.asarray(batch.temp_wh_max)[None]), -1.0)
    beat({"combo": f"{solver}/{repair}"})
    return {
        "solver": solver,
        "repair": repair,
        "solve_rate": round(float(solved.mean()), 4),
        "comfort_violation_max": round(max(float(vi.max()), float(vw.max())), 5),
        "community_cost": round(float(np.asarray(outs.cost).sum()), 4),
        "mean_solver_iters": round(float(np.mean(np.asarray(outs.admm_iters))), 1),
        "repair_failed_total": int(np.asarray(outs.repair_failed).sum()),
        "wall_s": round(wall, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--homes", type=int, default=512)
    ap.add_argument("--horizon-hours", type=int, default=6)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--out", default=None,
                    help="also write the artifact JSON here")
    args = ap.parse_args()

    result = {
        "tool": "repair_ab_matrix",
        "homes": args.homes,
        "horizon_hours": args.horizon_hours,
        "steps": args.steps,
        "combos": [],
    }
    for solver in ("admm", "ipm"):
        for repair in ("off", "project", "resolve"):
            row = run_combo(args.homes, args.horizon_hours, args.steps,
                            solver, repair)
            print(f"[{solver}/{repair}] {row}", file=sys.stderr, flush=True)
            result["combos"].append(row)
    line = json.dumps(result)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)


if __name__ == "__main__":
    main()
