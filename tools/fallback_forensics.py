"""Decompose the fallback population at headline scale (VERDICT r4 #6).

BENCH_r04 measured solve_rate 0.882 at 10k homes x 24 h — ~1,180
home-steps/day riding the bang-bang fallback controller — but the
infeasibility forensics that blamed the WH comfort band were done at 512
homes.  This tool grounds the story AT SCALE: it steps the real engine
eagerly, and for every home-step the solver gave up on it re-solves that
home's exact matrices with HiGHS (the trusted oracle) and classifies:

* ``infeasible``       — HiGHS agrees no feasible point exists (the
                         reference's GLPK would fail identically and ride
                         its own fallback, dragg/mpc_calc.py:527-596);
* ``under_converged``  — HiGHS finds a feasible optimum our solver
                         missed: a REAL behavioral delta from the
                         reference, the fraction worth tuning away.

Also cross-checks the converse at a sample: homes we SOLVED where HiGHS
agrees feasible (sanity against false positives).

Emits one JSON line; paste the table into docs/perf_notes.md.

Usage: python tools/fallback_forensics.py [--homes 10000] [--steps 24]
         [--horizon-hours 24] [--solver ipm]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--homes", type=int, default=10000)
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--horizon-hours", type=int, default=24)
    ap.add_argument("--solver", default="ipm")
    ap.add_argument("--sample-solved", type=int, default=64,
                    help="solved homes per step to cross-check vs HiGHS")
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--data-dir", default=None,
                    help='weather assets dir; "" forces synthetic (the '
                         "rounds-2..4 bench environment)")
    args = ap.parse_args()

    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    from scipy.optimize import linprog

    import bench
    from dragg_tpu.ops.qp import densify_A

    # Superset-pinned: this tool densifies the ONE shared pattern and
    # cross-checks every home against HiGHS on it; the bucketed engine
    # has per-type patterns instead.
    engine, _np = bench.build(args.homes, args.horizon_hours,
                              admm_iters=1500, solver=args.solver,
                              data_dir=args.data_dir, bucketed="false")
    pat = engine.static.pattern
    H = engine.params.horizon
    state = engine.init_state()
    rng = np.random.RandomState(7)

    counts = {"infeasible": 0, "under_converged": 0}
    per_step = []
    solved_checked = solved_mismatch = 0
    t0 = time.time()
    for t in range(args.steps):
        import jax.numpy as jnp

        qp, _aux = engine._prepare(engine._ctx0, state, jnp.asarray(t),
                                   jnp.zeros((H,), jnp.float32))
        state, out = engine.step(state, t, np.zeros((H,), np.float32))
        cs = np.asarray(out.correct_solve)
        fail_idx = np.where(cs == 0.0)[0]
        vals = np.asarray(qp.vals)
        beq = np.asarray(qp.b_eq, np.float64)
        l = np.asarray(qp.l_box, np.float64)
        u = np.asarray(qp.u_box, np.float64)
        q = np.asarray(qp.q, np.float64)

        def classify(i) -> bool:
            """True = HiGHS feasible."""
            A = np.asarray(densify_A(pat, vals[i:i + 1]), np.float64)[0]
            bounds = [(lo if np.isfinite(lo) else None,
                       hi if np.isfinite(hi) else None)
                      for lo, hi in zip(l[i], u[i])]
            res = linprog(q[i], A_eq=A, b_eq=beq[i], bounds=bounds,
                          method="highs")
            return bool(res.success)

        step_inf = step_uc = 0
        for i in fail_idx:
            if classify(int(i)):
                counts["under_converged"] += 1
                step_uc += 1
            else:
                counts["infeasible"] += 1
                step_inf += 1
        ok_idx = np.where(cs == 1.0)[0]
        if len(ok_idx) and args.sample_solved:
            for i in rng.choice(ok_idx,
                                size=min(args.sample_solved, len(ok_idx)),
                                replace=False):
                solved_checked += 1
                if not classify(int(i)):
                    solved_mismatch += 1
        per_step.append({"t": t, "failed": int(len(fail_idx)),
                         "infeasible": step_inf, "under_converged": step_uc})
        print(f"[forensics] t={t}: failed={len(fail_idx)} "
              f"(infeasible={step_inf}, under-converged={step_uc})",
              file=sys.stderr, flush=True)

    total_failed = counts["infeasible"] + counts["under_converged"]
    result = {
        "data": "synthetic" if args.data_dir == "" else "bundled",
        "homes": args.homes, "steps": args.steps,
        "horizon_hours": args.horizon_hours, "solver": args.solver,
        "total_home_steps": args.homes * args.steps,
        "failed_home_steps": total_failed,
        "solve_rate": round(1 - total_failed / (args.homes * args.steps), 4),
        **counts,
        "under_converged_frac_of_failures": round(
            counts["under_converged"] / max(total_failed, 1), 4),
        "solved_cross_checked": solved_checked,
        "solved_but_highs_infeasible": solved_mismatch,
        "per_step": per_step,
        "wall_s": round(time.time() - t0, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
