"""Quantify the MILP-relaxation gap (SURVEY.md §7 hard part a).

The reference solves a per-home MIXED-INTEGER program: the duty-cycle
variables are integer counts in [0, sub_subhourly_steps]
(dragg/mpc_calc.py:171-173, constrained at :344-349) and GLPK_MI's integer
optimum is what `cleanup_and_finish` reports (after dividing the counts by
sub_subhourly_steps, dragg/mpc_calc.py:497-499).  This framework ships the
LP relaxation (dragg_tpu/ops/qp.py:10-15) whose cost LOWER-bounds the MILP
— but the gap between the two had never been measured (round-3 verdict,
weak #7).

This tool builds the exact shipped QP matrices for the BASELINE 20-home
community and solves each home twice with the same trusted CPU solver
family (HiGHS): once as the shipped LP relaxation, once with integrality
restored on the cool/heat/wh duty-count columns (scipy.optimize.milp →
HiGHS-MILP).  It prints one JSON line with per-home and aggregate gaps.

Usage: python tools/milp_gap.py [--homes 20] [--horizon 8] [--mixed]
"""

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def assemble_step(horizon_hours: int, n_homes: int, mixed: bool):
    """Assemble the t=0 community QP via the SHARED recipe
    (dragg_tpu/fixtures.py — same one tests/test_qp_parity.py pins), with
    the engine's season gate.  Default mix is BASELINE semantics: all
    base-type homes (HVAC+WH only — BASELINE.md's 20-home row);
    ``--mixed`` adds PV/battery/PV+battery homes for the broader
    community shape (reference shipped config has 4 PV of 10 homes)."""
    from dragg_tpu.fixtures import assemble_community_qp

    return assemble_community_qp(
        horizon_hours=horizon_hours, n_homes=n_homes,
        homes_pv=min(4, n_homes // 5) if mixed else 0,
        homes_battery=min(2, n_homes // 10) if mixed else 0,
        homes_pv_battery=min(2, n_homes // 10) if mixed else 0,
        season="auto")


def to_bounds(l: np.ndarray, u: np.ndarray) -> list:
    """(l, u) arrays → linprog bounds list with infinities mapped to None.
    One helper for every solve site so the handling cannot drift."""
    return [(lo if np.isfinite(lo) else None, hi if np.isfinite(hi) else None)
            for lo, hi in zip(l, u)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--homes", type=int, default=20)
    ap.add_argument("--horizon", type=int, default=8)
    ap.add_argument("--mixed", action="store_true",
                    help="PV/battery mix instead of the all-base BASELINE")
    args = ap.parse_args()

    from scipy.optimize import Bounds, LinearConstraint, linprog, milp

    from dragg_tpu.ops.qp import densify_A

    qp, pat, lay, s = assemble_step(args.horizon, args.homes, args.mixed)
    A = np.asarray(densify_A(pat, qp.vals), dtype=np.float64)
    beq = np.asarray(qp.b_eq, dtype=np.float64)
    l = np.asarray(qp.l_box, dtype=np.float64)
    u = np.asarray(qp.u_box, dtype=np.float64)
    q = np.asarray(qp.q, dtype=np.float64)
    H = lay.H

    # Integer columns: the duty-cycle counts (cool, heat, wh) —
    # dragg/mpc_calc.py:171-173 declares them integer in [0, s].
    integrality = np.zeros(pat.n)
    integrality[lay.i_cool: lay.i_cool + H] = 1
    integrality[lay.i_heat: lay.i_heat + H] = 1
    integrality[lay.i_wh: lay.i_wh + H] = 1

    int_cols = integrality > 0

    gaps, rep_gaps, lp_objs, milp_objs, rep_objs = [], [], [], [], []
    first_gaps, first_objs = [], []
    n_inf_lp = n_inf_milp = n_inf_repair = n_inf_first = 0
    for i in range(A.shape[0]):
        lp = linprog(q[i], A_eq=A[i], b_eq=beq[i], bounds=to_bounds(l[i], u[i]),
                     method="highs")
        if not lp.success:
            n_inf_lp += 1
            continue
        mi = milp(c=q[i],
                  constraints=LinearConstraint(A[i], beq[i], beq[i]),
                  bounds=Bounds(np.where(np.isfinite(l[i]), l[i], -np.inf),
                                np.where(np.isfinite(u[i]), u[i], np.inf)),
                  integrality=integrality)
        if not mi.success:
            # LP-feasible but integer-infeasible: the reference would route
            # this home to its fallback controller; the relaxation solving
            # it is a capability superset, but count it.
            n_inf_milp += 1
            continue
        scale = max(abs(mi.fun), 1e-3)
        gaps.append((mi.fun - lp.fun) / scale)
        lp_objs.append(lp.fun)
        milp_objs.append(mi.fun)

        # Candidate TPU-native repair: round the LP duty counts to the
        # nearest integer, PIN them (l = u = rounded), re-solve the LP for
        # the continuous variables.  On TPU this is a second batched IPM
        # solve with tightened boxes — no branch & bound.  Measures (a) how
        # often naive rounding is comfort-infeasible, (b) the cost gap of
        # the repaired integer solution vs the true MILP optimum.
        xr = np.round(lp.x[int_cols])
        lr, ur = l[i].copy(), u[i].copy()
        lr[int_cols] = xr
        ur[int_cols] = xr
        rep = linprog(q[i], A_eq=A[i], b_eq=beq[i], bounds=to_bounds(lr, ur),
                      method="highs")
        if not rep.success:
            n_inf_repair += 1
        else:
            rep_gaps.append((rep.fun - mi.fun) / scale)
            rep_objs.append(rep.fun)

        # Receding-horizon repair: only the FIRST-step duty counts are ever
        # APPLIED to the plant (the rest re-plan next step), so integerizing
        # k=0 alone reproduces the reference's implementable discretization
        # with minimal restriction.  Try nearest; on infeasibility retry
        # with the other rounding of each first-step count (2^3 worst case).
        # NOTE on the reported number: the re-solved objective is a PARTIAL
        # relaxation (k>0 duty columns stay continuous), so it sits BETWEEN
        # the LP bound and the full-integer optimum — "first_plan_cost_
        # vs_milp" below is typically negative and is NOT a suboptimality
        # bound on the repair; the headline results here are the
        # feasibility count and that the applied action is implementable.
        # Closed-loop realized-cost comparison needs a full sim A/B.
        first_cols = np.array([lay.i_cool, lay.i_heat, lay.i_wh])
        x0 = lp.x[first_cols]
        found = None
        cands = sorted(itertools.product(*[
            sorted({np.floor(v), np.ceil(v), np.round(v)}) for v in x0
        ]), key=lambda c: np.sum(np.abs(np.asarray(c) - x0)))
        for cand in cands:
            lr, ur = l[i].copy(), u[i].copy()
            cv = np.clip(np.asarray(cand), l[i][first_cols], u[i][first_cols])
            lr[first_cols] = cv
            ur[first_cols] = cv
            r0 = linprog(q[i], A_eq=A[i], b_eq=beq[i],
                         bounds=to_bounds(lr, ur), method="highs")
            if r0.success:
                found = r0
                break
        if found is None:
            n_inf_first += 1
        else:
            first_gaps.append((found.fun - mi.fun) / scale)
            first_objs.append(found.fun)

    out = {
        "tool": "milp_gap",
        "homes": args.homes,
        "horizon_h": args.horizon,
        "sub_steps": s,
        "n_compared": len(gaps),
        "n_lp_infeasible": n_inf_lp,
        "n_milp_only_infeasible": n_inf_milp,
        "gap_mean": float(np.mean(gaps)) if gaps else None,
        "gap_max": float(np.max(gaps)) if gaps else None,
        "gap_median": float(np.median(gaps)) if gaps else None,
        "lp_cost_total": float(np.sum(lp_objs)) if lp_objs else None,
        "milp_cost_total": float(np.sum(milp_objs)) if milp_objs else None,
        "aggregate_gap": (float((np.sum(milp_objs) - np.sum(lp_objs))
                                / max(abs(np.sum(milp_objs)), 1e-3))
                          if milp_objs else None),
        # Rounding-repair candidate (see loop body): cost of the repaired
        # integer-feasible solution vs the true MILP optimum.
        "n_repair_infeasible": n_inf_repair,
        "repair_gap_mean": float(np.mean(rep_gaps)) if rep_gaps else None,
        "repair_gap_max": float(np.max(rep_gaps)) if rep_gaps else None,
        "repair_cost_total": float(np.sum(rep_objs)) if rep_objs else None,
        # First-action-only integerization (receding-horizon repair).  The
        # cost-vs-MILP numbers are from a PARTIAL relaxation (see loop
        # comment) — feasibility count is the headline result.
        "n_first_infeasible": n_inf_first,
        "first_plan_cost_vs_milp_mean": (float(np.mean(first_gaps))
                                         if first_gaps else None),
        "first_plan_cost_vs_milp_max": (float(np.max(first_gaps))
                                        if first_gaps else None),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
