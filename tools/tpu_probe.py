"""TPU-tunnel liveness CLI over the shared subprocess probe
(dragg_tpu/utils/probe.py) with a committed transcript.

Every call appends one timestamped line to the legacy text log AND one
``probe.verdict`` record to a telemetry event stream (events.jsonl —
dragg_tpu/telemetry), so the watcher, the resilience supervisor, bench's
ladder, and the runbook all share ONE forensic format (round 7; the
round-3 verdict's missing outage record was the text log's origin).

Usage:
  python tools/tpu_probe.py [--log docs/onchip_r4/probe_log.txt]
      one probe; exit 0 = live, 1 = down
  python tools/tpu_probe.py --classify
      additionally print the classified verdict JSON (resilience
      taxonomy: alive / TUNNEL_DOWN / WEDGED + wedge-signature fields)
  python tools/tpu_probe.py --watch 180
      probe forever at that cadence (for a background watcher); the
      outage/uptime transcript accumulates in the event stream
  python tools/tpu_probe.py --events-dir docs/onchip_r7
      route the event stream (default: the --log file's directory;
      pass '' to disable and keep only the text log)
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragg_tpu import telemetry  # noqa: E402
from dragg_tpu.resilience.liveness import check_liveness  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--log", default="docs/onchip_r4/probe_log.txt")
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--classify", action="store_true",
                    help="print the classified verdict as a JSON line")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="probe forever at this cadence in seconds")
    ap.add_argument("--events-dir", default=None,
                    help="directory for the telemetry event stream "
                         "(events.jsonl; default: alongside --log, "
                         "'' disables)")
    args = ap.parse_args()

    events_dir = (args.events_dir if args.events_dir is not None
                  else os.path.dirname(args.log) or ".")
    if events_dir:
        # One stream per watcher: check_liveness emits probe.verdict
        # (and failure.<kind>) onto it for every probe below.
        telemetry.init_run(events_dir)

    while True:
        report = check_liveness(args.timeout, log_path=args.log)
        if args.classify:
            print(json.dumps(report._asdict()), flush=True)
        else:
            print(f"{'LIVE' if report.alive else 'DOWN'} {report.detail}",
                  flush=True)
        if not args.watch:
            sys.exit(0 if report.alive else 1)
        time.sleep(args.watch)


if __name__ == "__main__":
    main()
