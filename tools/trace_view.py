#!/usr/bin/env python
"""Assemble and render causal traces from a run's telemetry streams.

Reads every events.jsonl under a run directory (coordinator bus plus
``shard<k>/`` sub-streams — telemetry.traces.read_records applies the
``trace.skew`` offsets and the ``(t, pid, seq)`` merge order), assembles
the span trees, and prints one ASCII timeline per trace with the
critical-path attribution (queue / compile / device / collect / wire /
merge — docs/telemetry.md "Tracing").

    python tools/trace_view.py <run_dir>                  # ASCII timelines
    python tools/trace_view.py <run_dir> --json           # report JSON
    python tools/trace_view.py <run_dir> --out report.json
    python tools/trace_view.py <run_dir> --assert-complete  # CI gate:
        exit 1 naming every orphan span / rootless trace

Stdlib + telemetry only; never imports jax (safe on a wedged tunnel).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragg_tpu.telemetry import traces  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", help="run directory holding events.jsonl "
                                    "(sub-streams merged automatically)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the report JSON instead of ASCII timelines")
    ap.add_argument("--out", default=None,
                    help="also write the report JSON to this path")
    ap.add_argument("--width", type=int, default=60,
                    help="ASCII timeline width in columns (default 60)")
    ap.add_argument("--assert-complete", action="store_true",
                    help="exit 1 unless every trace is a single rooted "
                         "tree with zero orphan spans (CI trace-smoke)")
    args = ap.parse_args()

    records = traces.read_records(args.run_dir)
    report = traces.trace_report(args.run_dir, records=records)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"wrote {args.out}", file=sys.stderr)

    if args.as_json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        assembled = traces.assemble(records)
        if not assembled["traces"]:
            print(f"no traced records under {args.run_dir} "
                  f"(telemetry.trace off?)")
        for tid, tr in sorted(assembled["traces"].items()):
            meta = report["traces"][tid]
            print(f"trace {tid}: {meta['spans']} spans, "
                  f"{len(meta['roots'])} root(s), "
                  f"{'complete' if meta['complete'] else 'INCOMPLETE'}")
            print(traces.render_ascii(tr, width=args.width))
            cp = traces.critical_path(tr)
            buckets = ", ".join(f"{k}={v:.3f}s" for k, v in
                                sorted(cp["path_seconds"].items()) if v)
            print(f"  critical path: {' -> '.join(cp['path'])}"
                  + (f"  [{buckets}]" if buckets else ""))
            print()

    if args.assert_complete:
        problems = traces.completeness_problems(report)
        if problems:
            for p in problems:
                print(f"INCOMPLETE: {p}", file=sys.stderr)
            return 1
        n = len(report["traces"])
        print(f"complete: {n} trace{'s' if n != 1 else ''}, zero orphans",
              file=sys.stderr)
        if n == 0:
            print("INCOMPLETE: no traces assembled (was the run traced?)",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
