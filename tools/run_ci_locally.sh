#!/bin/bash
# Execute .github/workflows/ci.yml's jobs on the local host, mirroring the
# workflow steps one-to-one, and record a timestamped transcript.  This is
# the offline stand-in for a hosted runner: this environment has no GitHub
# remote, no docker daemon, and no `act`, so the docker job is SKIPPED and
# recorded as such (the round-3 verdict asked for executed-workflow
# evidence — this transcript is the closest achievable here, and the
# committed log distinguishes "ran green locally" from "never ran").
#
#   bash tools/run_ci_locally.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-docs/ci_local.log}
stamp() { date "+%Y-%m-%d %H:%M:%S"; }
say() { echo "[$(stamp)] $*" | tee -a "$LOG"; }
: > "$LOG"
RC=0
step() { # step <job.name> <cmd...>
  local name=$1; shift
  say ">>> $name: $*"
  local t0=$SECONDS
  if "$@" >>"$LOG" 2>&1; then
    say "<<< $name OK ($((SECONDS - t0))s)"
  else
    local rc=$?
    say "<<< $name FAILED rc=$rc ($((SECONDS - t0))s)"
    RC=1
  fi
}

say "ci.yml local execution on $(uname -sr), python $(python -V 2>&1)"

# --- job: lint (mirrors ci.yml lint steps; flake8 args pinned to the
#     workflow's list so drift against tools/lint.py is exercised here)
# dragglint (ISSUE 14): the full analyzer with a JSON findings artifact
# — rule catalog in docs/analysis.md; tools/lint.py is a shim over the
# same engine, exercised separately so the shim path cannot rot.
step "lint/dragglint" python -m dragg_tpu.analysis --json /tmp/dragglint_findings.json
step "lint/shim" python tools/lint.py
if python -c "import flake8" 2>/dev/null; then
  step "lint/flake8" python -m flake8 --max-line-length=100 \
    --extend-ignore=E203,E501,W503,E731,E741 \
    dragg_tpu tools tests bench.py __graft_entry__.py
else
  # The workflow pip-installs flake8; this zero-egress host cannot.
  say "lint/flake8 SKIPPED: flake8 not installed (tools/lint.py covers the offline subset)"
fi

# --- job: test (JAX_PLATFORMS=cpu like the workflow env; the axon var is
#     additionally stripped per CLAUDE.md — hosted runners never have it)
step "test/pytest" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python -m pytest tests/ -q -m "not slow"
step "test/smoke-bench" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  bash -c 'python bench.py --smoke | tee /tmp/bench_smoke.json &&
           python -c "import json; r=json.load(open(\"/tmp/bench_smoke.json\")); assert r[\"value\"]>0"'

# --- job: mixed-precision smoke (ISSUE 11): the bf16x3 hot-loop policy
#     must run end-to-end on the dense reluqp family and the artifact
#     must carry the precision + MFU-basis fields bench_trend keys on
step "test/smoke-bench-bf16x3" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  bash -c 'python bench.py --smoke --solver reluqp --precision bf16x3 | tee /tmp/bench_smoke_bf16.json &&
           python -c "import json; r=json.load(open(\"/tmp/bench_smoke_bf16.json\")); assert r[\"value\"]>0 and r[\"precision\"]==\"bf16x3\" and r[\"mfu_basis\"]==\"cpu_estimate\", r"'

# --- job: serve-soak smoke (ISSUE 7): the serving daemon's chaos soak on
#     the CPU mesh — all six taxonomy fault kinds plus kill -9 mid-batch;
#     asserts zero lost / zero double-answered requests, degradation
#     provenance, and warm-restart compile-cache reuse
step "test/serve-soak-smoke" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  bash -c 'python tools/serve_soak.py --smoke | tee /tmp/serve_soak_smoke.json &&
           python -c "import json; r=json.load(open(\"/tmp/serve_soak_smoke.json\")); assert r[\"ok\"], r[\"violations\"]"'

# --- job: serve-load smoke (ISSUE 13): the fleet-backed serving pool's
#     SLO-gated load harness — a small C=4 fleet worker (real engine),
#     ~20 requests at one rate; asserts the level passed its SLO
#     (p99 < deadline) and zero journal anomalies (no lost, no
#     double-answered)
step "test/serve-load-smoke" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  bash -c 'python tools/serve_load.py --smoke | tee /tmp/serve_load_smoke.json &&
           python -c "import json; r=json.load(open(\"/tmp/serve_load_smoke.json\")); assert r[\"ok\"] and not r[\"violations\"], r; lv=r[\"levels\"][0]; assert lv[\"p99_s\"] is not None and lv[\"p99_s\"] < r[\"metrics\"][\"slo_p99_s\"], lv"'

# --- job: fleet smoke (ISSUE 8): 4 communities × 64 homes folded into one
#     batched fleet engine (type buckets hold C·B_type homes under one
#     compiled pattern set); asserts solve rate, comfort bands, finiteness,
#     and the community-major output mapping at a CI-sized shape
step "test/fleet-smoke" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  bash -c 'python tools/validate_scale.py --communities 4 --homes 64 \
             --horizon-hours 4 --days 1 --chunk 12 --solver ipm \
             --min-solve-rate 0.8 \
             | tee /tmp/fleet_smoke.json &&
           python -c "import json; r=json.load(open(\"/tmp/fleet_smoke.json\")); assert r[\"ok\"] and r[\"communities\"]==4 and r[\"homes_total\"]==256, r"'

# --- job: scenario smoke (ISSUE 10): EV + heat-pump home types plus a
#     DR + tariff-shock + outage pack on the CPU mesh — asserts the six-
#     type mix solves in its own bucket patterns, event windows clamp the
#     grid, and the output mapping survives (solve-rate floor is loose:
#     outage islanding routes all-electric homes to the fallback BY
#     DESIGN, docs/scenarios.md)
step "test/scenario-smoke" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  bash -c 'python tools/validate_scale.py --homes 64 --horizon-hours 4 \
             --days 1 --chunk 12 --solver ipm \
             --mix 0.3,0.1,0.1,0.1,0.1 --pack stress_dr_outage \
             --min-solve-rate 0.5 \
             | tee /tmp/scenario_smoke.json &&
           python -c "import json; r=json.load(open(\"/tmp/scenario_smoke.json\")); assert r[\"ok\"] and r[\"events\"][\"events\"] and r[\"bucket_patterns\"]>=5, r"'

# --- job: shard smoke (ISSUE 15): cross-process fleet sharding — 4
#     communities split over 2 supervised worker processes through the
#     jax-free coordinator, merged per-community outputs asserted
#     AGAINST the in-process fleet (--shard-parity: exact solvedness +
#     fp-tolerance aggregates), plus the doctor's shard-journal
#     crash-safety selftest (torn-tail sweep + duplicate-epoch refusal)
step "test/shard-smoke" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  bash -c 'python tools/validate_scale.py --communities 4 --homes 16 \
             --horizon-hours 2 --days 1 --chunk 6 --steps 12 --solver ipm \
             --shards 2 --shard-parity --min-solve-rate 0.8 \
             | tee /tmp/shard_smoke.json &&
           python -c "import json; r=json.load(open(\"/tmp/shard_smoke.json\")); assert r[\"ok\"] and r[\"shards\"]==2 and r[\"shard_parity\"][\"ok\"], r" &&
           python -m dragg_tpu doctor --shard-check --backend-timeout 60 | grep "shard_journal *\[ok" >/dev/null'

# --- job: wire smoke (ISSUE 16): networked shard transport — the same
#     2-shard split pushing chunks over TCP to the coordinator's
#     chunk-ingest server (at-least-once, epoch-fenced, journal-before-
#     ack), merged outputs asserted against the IN-PROCESS fleet
#     (--shard-parity's reference leg always runs spool, so this is a
#     cross-transport A/B), plus the doctor's loopback wire selftest
#     (torn-frame sweep + dedup-across-restart + fence naming)
step "test/wire-smoke" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  bash -c 'python tools/validate_scale.py --communities 4 --homes 16 \
             --horizon-hours 2 --days 1 --chunk 6 --steps 12 --solver ipm \
             --shards 2 --transport tcp --shard-parity --min-solve-rate 0.8 \
             | tee /tmp/wire_smoke.json &&
           python -c "import json; r=json.load(open(\"/tmp/wire_smoke.json\")); assert r[\"ok\"] and r[\"shards\"]==2 and r[\"transport\"]==\"tcp\" and r[\"shard_parity\"][\"ok\"], r" &&
           python -m dragg_tpu doctor --shard-check --backend-timeout 60 | grep "shard_wire *\[ok" >/dev/null'

# --- job: trace smoke (ISSUE 20): the fleet trace plane — a traced
#     2-shard tcp run (trace ctx over env + wire frames, per-chunk
#     metric flushes) must assemble into complete causal trees
#     (every trace rooted, zero orphan spans — tools/trace_view.py
#     --assert-complete), plus the doctor's trace-plane selftest
#     (cross-process join + live flush + rollup fold)
step "test/trace-smoke" env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  bash -c 'python tools/validate_scale.py --communities 2 --homes 8 \
             --horizon-hours 2 --days 1 --chunk 6 --steps 12 --solver ipm \
             --shards 2 --transport tcp --trace --min-solve-rate 0.8 \
             | tee /tmp/trace_smoke.json &&
           python -c "import json; r=json.load(open(\"/tmp/trace_smoke.json\")); assert r[\"ok\"] and r[\"shards\"]==2, r" &&
           python tools/trace_view.py "$(python -c "import json; print(json.load(open(\"/tmp/trace_smoke.json\"))[\"run_dir\"])")" --assert-complete >/dev/null &&
           python -m dragg_tpu doctor --telemetry --backend-timeout 60 | grep "trace_plane *\[ok" >/dev/null'

# --- job: bench-trend gate (round 9): the committed BENCH_r*.json series
#     must show no like-for-like regression (comparability rules per
#     CLAUDE.md; tools/bench_trend.py docstring)
step "test/bench-trend-gate" python tools/bench_trend.py --gate

# --- job: docker (not executable here — no daemon; recorded, not faked)
if command -v docker >/dev/null 2>&1 && docker info >/dev/null 2>&1; then
  step "docker/build" docker build -t dragg-tpu:ci .
  step "docker/smoke" docker run --rm -e JAX_PLATFORMS=cpu dragg-tpu:ci \
    python bench.py --smoke
else
  say "docker job SKIPPED: no docker daemon in this environment"
fi

say "ci.yml local execution finished rc=$RC"
exit $RC
