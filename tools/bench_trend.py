"""Cross-round bench trend table + regression gate.

Folds the committed ``BENCH_r*.json`` artifacts (and, optionally,
telemetry ``metrics.json`` snapshots) into per-config trend rows with
threshold-based verdicts, so the growing artifact series detects
regressions structurally instead of by eyeball (ROADMAP: "as fast as the
hardware allows" needs round-over-round evidence, not one-off A/Bs).

Comparability rules (CLAUDE.md "Round-5 semantic defaults"):

* entries are compared ONLY within an identical hard key
  ``(metric, platform, solver, semantics, data, communities, mix,
  precision, rl, serve, shards)`` — a semantics flip
  (relaxation vs integer) or environment flip (synthetic vs bundled)
  changes the measured workload, so rate deltas across them are not
  perf signals;
* artifacts that predate a field get the era's documented default:
  missing ``semantics`` → "relaxation", missing ``data`` → "synthetic"
  (rounds ≤ 4 measured the relaxation on synthetic weather);
* ``bucketed`` is a SOFT key: ``tpu.bucketed`` is an engine default that
  legitimately changed round 8 (−39.7 % solve phase at the 512-home
  mix, docs/perf_notes.md), so a flip does not break comparability —
  the verdict row is annotated with the flip instead, and readers
  wanting a solver-only A/B pin ``--bucketed false`` at measurement
  time (CLAUDE.md);
* ``transport`` is a SOFT key too (round 19): spool vs tcp only moves
  chunk payloads between the same device work — a flip annotates the
  row (era default "spool"), never fragments or gates the series.

Verdicts: per consecutive comparable pair, the headline rate (higher is
better) and the steady-state solve phase (lower is better) each read
``improvement`` / ``regression`` / ``stable`` against ``--threshold``
(default 10 % — BENCH chunk rates drift across sim windows by problem
hardness, perf_notes round 8, so sub-threshold deltas are noise).

Usage:
    python tools/bench_trend.py [artifacts...] [--threshold 0.1] [--gate]

Default artifacts: ``BENCH_r*.json`` at the repo root, in round order.
``--gate`` exits 1 when any comparable pair regresses — wired into
tools/run_ci_locally.sh so a committed artifact that regresses a
like-for-like config fails local CI.  Prints a human table, then
exactly one machine-readable JSON line (repo bench convention).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HARD_KEY = ("metric", "platform", "solver", "semantics", "data",
            "communities", "mix", "precision", "rl", "serve", "shards")


def _round_ordinal(path: str, fallback: int) -> int:
    m = re.search(r"r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else fallback


def _iter_result_dicts(obj):
    """Bench result dicts inside one parsed JSON object (wrapper ``tail``
    strings included) or raw text."""
    if isinstance(obj, dict):
        if "metric" in obj and "value" in obj:
            yield obj
        elif "tail" in obj:  # the committed BENCH_r* wrapper format
            yield from _iter_text_results(str(obj.get("tail", "")))
        elif "histograms" in obj or "gauges" in obj:
            yield {"_snapshot": obj}
    elif isinstance(obj, list):
        for item in obj:
            yield from _iter_result_dicts(item)


def _iter_text_results(text: str):
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith("{") and '"metric"' in line):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            yield rec


def load_artifact(path: str, ordinal: int) -> list[dict]:
    """Every normalized bench entry found in one artifact file."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        return [dict(source=path, ordinal=ordinal, skipped=f"unreadable: {e}")]
    found = []
    try:
        parsed = json.loads(text)
    except ValueError:
        parsed = None
    for rec in (_iter_result_dicts(parsed) if parsed is not None
                else _iter_text_results(text)):
        found.append(_normalize(rec, path, ordinal))
    if not found:
        return [dict(source=path, ordinal=ordinal,
                     skipped="no bench result line (failed round?)")]
    return found


def _normalize(rec: dict, source: str, ordinal: int) -> dict:
    if "_snapshot" in rec:  # telemetry metrics.json snapshot
        snap = rec["_snapshot"]
        gauges = snap.get("gauges", {})
        hists = snap.get("histograms", {})
        pfx = "bench.phase."
        phases = {k[len(pfx):-len("_s")]: (v or {}).get("mean")
                  for k, v in hists.items() if k.startswith(pfx)}
        return dict(source=source, ordinal=ordinal,
                    metric="metrics_snapshot", platform="?", solver="?",
                    semantics="?", data="?", communities=1, mix="?",
                    precision="?", rl="none", serve="none", shards=1,
                    transport="spool", bucketed=False,
                    fallback=False, degraded=None,
                    value=float(gauges.get("bench.rate_ts_per_s", 0.0)),
                    solve_rate=gauges.get("engine.solve_rate"),
                    compile_s=None, phases=phases)
    phases = rec.get("phase_s_per_step") or {}
    return dict(
        source=source, ordinal=ordinal,
        metric=rec.get("metric"),
        platform=rec.get("platform", "?"),
        solver=rec.get("solver", "?"),
        # Era defaults for pre-field artifacts (module docstring).
        semantics=rec.get("semantics", "relaxation"),
        data=rec.get("data", "synthetic"),
        # Fleet size is a HARD key (round 12): a C-community rate is a
        # different workload than a single community at the same
        # per-community shape, so fleet rows form their own series and
        # never gate against single-community history.  Era default:
        # pre-fleet artifacts measured one community.
        communities=int(rec.get("communities", 1)),
        # Population composition + scenario pack is a HARD key (ISSUE 10):
        # a scenario-pack row (EV/heat-pump mixes, DR/outage timelines) is
        # a different workload than the legacy 4-type bench at the same
        # shape, so it forms its own series and never gates against the
        # pre-scenario history.  Era default: pre-field artifacts all
        # measured the legacy 0.4/0.1/0.1 mix.
        mix=str(rec.get("mix", "legacy")),
        # Hot-loop matmul policy is a HARD key (ISSUE 11): a bf16x3 rate
        # runs a different numerical contract (3-pass bf16 compute in the
        # dense solver iterations) than the f32 history at the same
        # shape, so bf16x3 rows form their own series and never gate
        # against f32 artifacts.  Era default: every pre-field artifact
        # ran full f32.
        precision=str(rec.get("precision", "f32")),
        # RL training rows are a HARD key (ROADMAP item 1): an RL fleet
        # training rate (tools/bench_rl_fleet.py — fused agent update +
        # MPC solve per step) is a different workload than the MPC
        # baseline at the same shape, so "rl" rows form their own series
        # and never gate against MPC-baseline history.  Era default:
        # every pre-field artifact measured the baseline ("none").
        rl=str(rec.get("rl", "none")),
        # Serving rows are a HARD key (ISSUE 13): a serve_load saturation
        # rate (tools/serve_load.py — warm fleet-backed pool, SLO-gated
        # latency curve) is a different workload than any engine
        # throughput at the same shape, so "serve" rows form their own
        # series — keyed by pool geometry (fleet slots × workers) — and
        # never gate against engine-throughput history.  Era default:
        # every pre-field artifact measured engines, not the pool.
        serve=str(rec.get("serve", "none")),
        # Cross-process shard count is a HARD key (round 18): an N-shard
        # coordinator rate (bench.py --shards — wall includes process
        # supervision + spool exchange; per-shard engines compile at
        # C/N·B_type shapes) is a different workload than the in-process
        # fleet at the same total, so N-shard rows form their own series
        # and never gate against in-process history.  Era default: every
        # pre-field artifact measured one process.
        shards=int(rec.get("shards", 1)),
        # Shard transport is a SOFT key (round 19, the `degraded`
        # pattern): a tcp-transport row measures the same device work as
        # a spool row at the same shard geometry — the wire only moves
        # chunk payloads — so a flip ANNOTATES the series instead of
        # fragmenting it.  Era default: every pre-field artifact
        # exchanged chunks over the shared-disk spool.
        transport=str(rec.get("transport", "spool")),
        bucketed=bool(rec.get("bucketed", False)),
        fallback=bool(rec.get("fallback", False)),
        degraded=rec.get("degraded"),
        value=float(rec.get("value") or 0.0),
        solve_rate=rec.get("solve_rate"),
        compile_s=rec.get("compile_s"),
        error=rec.get("error"),
        phases=phases,
    )


def solve_phase_s(entry: dict) -> float | None:
    """One steady-state solve-phase scalar per entry: the honest ipm key
    when present, else the cached (steady-state) factor path, else the
    refresh path (the only key very old artifacts carry)."""
    ph = entry.get("phases") or {}
    for key in ("solve", "solve_cached", "solve_refresh"):
        if ph.get(key) is not None:
            return float(ph[key])
    return None


def _verdict(delta: float | None, threshold: float,
             higher_is_better: bool) -> str | None:
    if delta is None:
        return None
    signed = delta if higher_is_better else -delta
    if signed > threshold:
        return "improvement"
    if signed < -threshold:
        return "regression"
    return "stable"


def build_trend(entries: list[dict], threshold: float) -> dict:
    """Group by hard key, order by round, verdict every consecutive
    pair."""
    groups: dict[tuple, list[dict]] = {}
    for e in entries:
        if e.get("skipped") or e.get("error") or e["value"] <= 0:
            continue
        groups.setdefault(tuple(e[k] for k in HARD_KEY), []).append(e)
    rows = []
    for key, group in sorted(groups.items(), key=lambda kv: str(kv[0])):
        group.sort(key=lambda e: e["ordinal"])
        for prev, cur in zip(group, group[1:]):
            d_rate = ((cur["value"] - prev["value"]) / prev["value"]
                      if prev["value"] else None)
            sp, sc = solve_phase_s(prev), solve_phase_s(cur)
            d_solve = (sc - sp) / sp if (sp and sc is not None) else None
            notes = []
            # Soft flag like `bucketed`: the platform hard key already
            # reflects the executed backend, but a degraded-ladder
            # artifact deserves lower trust than a requested-platform one.
            dg = [lbl for lbl, e in (("from", prev), ("to", cur))
                  if e["fallback"]]
            if dg:
                notes.append(
                    f"fallback artifact ({','.join(dg)}): the TPU→CPU "
                    f"ladder degraded — this side measured the fallback "
                    f"platform, not the requested one")
            # `degraded` is a SOFT key like `bucketed`: a supervised run
            # that fell back TPU→CPU mid-flight annotates its series
            # (failure kind + where the TPU attempt died) instead of
            # breaking comparability — the hard key already carries the
            # executed platform.
            for lbl, e in (("from", prev), ("to", cur)):
                d = e.get("degraded")
                if d:
                    where = (f" at step {d['transition_step']}"
                             if d.get("transition_step") is not None else
                             f" in {d['transition_stage']}"
                             if d.get("transition_stage") else "")
                    notes.append(
                        f"degraded artifact ({lbl}): mid-flight "
                        f"{d.get('from', 'tpu')}→{d.get('to', 'cpu')} on "
                        f"{d.get('failure')}{where} — annotating, not "
                        f"gating")
            if prev["bucketed"] != cur["bucketed"]:
                notes.append(
                    f"tpu.bucketed resolution changed "
                    f"{prev['bucketed']}→{cur['bucketed']} (engine default "
                    f"— round-8 shape specialization; pin --bucketed false "
                    f"for a solver-only A/B)")
            if prev.get("transport", "spool") != cur.get("transport",
                                                         "spool"):
                notes.append(
                    f"shard transport changed "
                    f"{prev.get('transport', 'spool')}→"
                    f"{cur.get('transport', 'spool')} (round-19 wire vs "
                    f"shared-disk chunk exchange — annotating, not "
                    f"gating; same device work either way)")
            rows.append(dict(
                key={k: prev[k] for k in HARD_KEY},
                from_source=os.path.basename(prev["source"]),
                to_source=os.path.basename(cur["source"]),
                rate=[prev["value"], cur["value"]],
                rate_delta=round(d_rate, 4) if d_rate is not None else None,
                rate_verdict=_verdict(d_rate, threshold, True),
                solve_s=[sp, sc],
                solve_delta=(round(d_solve, 4) if d_solve is not None
                             else None),
                solve_verdict=_verdict(d_solve, threshold, False),
                notes=notes,
            ))
    skipped = [dict(source=os.path.basename(e["source"]),
                    reason=e.get("skipped") or e.get("error")
                    or "zero value")
               for e in entries
               if e.get("skipped") or e.get("error")
               or (e.get("value", 0) or 0) <= 0]
    regressions = [r for r in rows
                   if "regression" in (r["rate_verdict"],
                                       r["solve_verdict"])]
    return dict(threshold=threshold, rows=rows, skipped=skipped,
                n_regressions=len(regressions))


def _fmt_pct(d: float | None) -> str:
    return f"{d * 100:+.1f}%" if d is not None else "—"


def print_table(trend: dict, out=sys.stderr) -> None:
    print(f"bench trend (threshold ±{trend['threshold']*100:.0f}%)",
          file=out)
    for r in trend["rows"]:
        k = r["key"]
        fleet = (f"/{k['communities']}comm" if k.get("communities", 1) != 1
                 else "")
        mix = (f"/{k['mix']}" if k.get("mix", "legacy") != "legacy" else "")
        prec = (f"/{k['precision']}"
                if k.get("precision", "f32") != "f32" else "")
        rl = (f"/rl:{k['rl']}" if k.get("rl", "none") != "none" else "")
        srv = (f"/serve:{k['serve']}"
               if k.get("serve", "none") != "none" else "")
        print(f"  {k['metric']} [{k['platform']}/{k['solver']}/"
              f"{k['semantics']}/{k['data']}{fleet}{mix}{prec}{rl}{srv}] "
              f"{r['from_source']} → {r['to_source']}", file=out)
        print(f"    rate  {r['rate'][0]:.3f} → {r['rate'][1]:.3f} "
              f"({_fmt_pct(r['rate_delta'])}) {r['rate_verdict']}",
              file=out)
        if r["solve_verdict"] is not None:
            print(f"    solve {r['solve_s'][0]:.4f} → {r['solve_s'][1]:.4f}"
                  f" s/step ({_fmt_pct(r['solve_delta'])}) "
                  f"{r['solve_verdict']}", file=out)
        for n in r["notes"]:
            print(f"    note: {n}", file=out)
    for s in trend["skipped"]:
        print(f"  {s['source']}: skipped ({s['reason']})", file=out)
    if not trend["rows"]:
        print("  (no comparable pairs)", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("artifacts", nargs="*",
                    help="bench artifacts / metrics snapshots (default: "
                         "the committed BENCH_r*.json series)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative delta below which a change is 'stable'")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 when any comparable pair regresses")
    args = ap.parse_args(argv)

    paths = args.artifacts or sorted(
        glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    entries = []
    for i, p in enumerate(paths):
        entries.extend(load_artifact(p, _round_ordinal(p, i)))
    trend = build_trend(entries, args.threshold)
    print_table(trend)
    print(json.dumps({"tool": "bench_trend", **trend}))
    return 1 if (args.gate and trend["n_regressions"]) else 0


if __name__ == "__main__":
    sys.exit(main())
