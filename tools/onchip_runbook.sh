#!/bin/bash
# On-chip measurement runbook — run the moment the TPU tunnel is alive.
# Round-5 revision (VERDICT r4 next-1/2/3):
#   * HANG BISECTION FIRST: the 10k engine compile has never completed on
#     the axon backend and the abandoned attempt wedges the tunnel —
#     bisect it per-stage (trace/lower/compile/execute, own subprocesses,
#     own timeouts) at 1k then 10k BEFORE anything else; a completed 10k
#     diagnose also warms the compile cache for the later bench;
#   * auto VMEM policy validation: the 48h (m=149) microbench runs with
#     NO env overrides — the round-5 _auto_blocks policy must pick a
#     fitting block — plus one explicit LANE_BLOCK=512 run that is
#     EXPECTED to scoped-VMEM OOM (confirms the hypothesis, bounded);
#   * semantics A/B at 10k: default (integer repair, the shipped story)
#     AND relaxation (comparable with rounds 2-4 numbers);
#   * probe BETWEEN steps (a wedge aborts instead of burning timeouts);
#     staged sizes; per-step outer timeouts sized to fit internal ladders.
# Output: docs/onchip_r*/ *.json|log.
#
#   bash tools/onchip_runbook.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-docs/onchip_r5}
mkdir -p "$OUT"
export DRAGG_PROBE_LOG="$OUT/probe_log.txt"
stamp() { date +%H:%M:%S; }
probe() { # probe <label> — returns 1 (and logs) when the tunnel is down
  python tools/tpu_probe.py --log "$DRAGG_PROBE_LOG" >/dev/null 2>&1
  local rc=$?
  echo "[$(stamp)] probe($1) rc=$rc" | tee -a "$OUT/runbook.log"
  return $rc
}
run() { # run <name> <timeout_s> <cmd...>
  local name=$1 t=$2; shift 2
  echo "[$(stamp)] >>> $name ($*)" | tee -a "$OUT/runbook.log"
  timeout "$t" "$@" >"$OUT/$name.json" 2>"$OUT/$name.log"
  local rc=$?
  echo "[$(stamp)] <<< $name rc=$rc" | tee -a "$OUT/runbook.log"
  tail -c 2000 "$OUT/$name.json" >> "$OUT/runbook.log" || true
  return $rc
}

# 0. Is the chip actually reachable? (hard timeout; a wedged tunnel hangs)
probe start || { echo "TPU unreachable; aborting" | tee -a "$OUT/runbook.log"; exit 1; }

# 1. THE HANG BISECTION — first, while the window is freshest (VERDICT
#    r4 next-1).  1k localizes scale-dependence cheaply; 10k is the
#    shape that has never compiled.  420 s/stage: a legitimately-slow
#    remote AOT compile must not be misdiagnosed as hung.  Each stage is
#    its own subprocess, so a hang here cannot wedge THIS process — and
#    the per-stage verdict JSON is the committed artifact either way.
run diagnose_1k 1200 python tools/diagnose_tpu_hang.py \
  --homes 1000 --horizon 24 --timeout 180
probe after_diag1k || exit 1
run diagnose_10k 3600 python tools/diagnose_tpu_hang.py \
  --homes 10000 --horizon 24 --timeout 420
probe after_diag10k || {
  echo "[$(stamp)] tunnel wedged by 10k diagnose — bracketing at 2.5k/5k next window" \
    | tee -a "$OUT/runbook.log"; exit 1; }
if ! grep -q '"all_ok": true' "$OUT/diagnose_10k.json" 2>/dev/null; then
  # Bracket the failing scale while the tunnel still answers.
  run diagnose_2k5 1800 python tools/diagnose_tpu_hang.py \
    --homes 2500 --horizon 24 --timeout 300
  probe after_diag2k5 || exit 1
  run diagnose_5k 2400 python tools/diagnose_tpu_hang.py \
    --homes 5000 --horizon 24 --timeout 420
  probe after_diag5k || exit 1
fi

# 2. Band-kernel microbench (failure-isolated per timing).  The 48h
#    (m=149) run uses NO env overrides — validates the round-5 scoped-
#    VMEM auto policy end-to-end (auto should pick lane 256 + B-chunks).
run band_kernel_24h 600 python tools/bench_band_kernel.py --homes 10000 --horizon 24
probe after_micro24 || exit 1
run band_kernel_48h_auto 600 python tools/bench_band_kernel.py --homes 25000 --horizon 48
probe after_micro48 || exit 1
#    Hypothesis check (bounded, EXPECTED to scoped-VMEM OOM at m=149).
#    BCHUNK=0 pins chunking OFF — the round-4 OOM config; with it unset
#    the auto policy would B-chunk and the control could pass for the
#    wrong reason (round-5 review finding).
run band_kernel_48h_lb512_expect_oom 300 env DRAGG_LANE_BLOCK=512 DRAGG_PALLAS_BCHUNK=0 \
  python tools/bench_band_kernel.py --homes 25000 --horizon 48
probe after_micro48b || exit 1

# 3. STAGED engine benches: 1k first.  bench.py probe-gates its TPU
#    attempts and falls back to a full-size CPU run; internal ladder
#    budget (probe 60 + BENCH_TPU_TIMEOUT + probe + retry/2 + CPU
#    fallback) must FIT the outer timeout.
run bench_1k_24h 900 env BENCH_TPU_TIMEOUT=300 BENCH_CPU_TIMEOUT=300 \
  python bench.py --homes 1000 --horizon-hours 24 --solver ipm
probe after_1k || exit 1

# 4. Engine-level band-kernel A/B at 1k (cheap): decides the auto kernel
#    policy with an end-to-end verdict (microbench said pallas-chol but
#    xla-solve, round 4).
run band_ab_1k 900 python tools/bench_engine_kernels.py --homes 1000 --horizon-hours 24
probe after_ab || exit 1

# 5. Headline bench, BASELINE row-3 config (10k x 24h), SHIPPED semantics
#    (integer repair — the artifact the driver records).
#    Internal budget: 60 + 600 + 60 + 300 + 600 = 1620 < 1800.
run bench_10k_24h 1800 env BENCH_TPU_TIMEOUT=600 BENCH_CPU_TIMEOUT=600 \
  python bench.py --homes 10000 --horizon-hours 24 --solver ipm
probe after_10k || exit 1
#    Relaxation A/B — the semantics rounds 2-4 measured (6.29 ts/s r2).
#    --data-dir "" pins the SYNTHETIC weather those rounds ran (bundled
#    vs synthetic differ drastically in fallback work per step — solve
#    1.0000 vs 0.9263, perf notes round 5 — so comparability needs both
#    knobs pinned):
run bench_10k_24h_relaxation 1800 env BENCH_TPU_TIMEOUT=600 BENCH_CPU_TIMEOUT=600 \
  python bench.py --homes 10000 --horizon-hours 24 --solver ipm \
  --semantics relaxation --data-dir ""
probe after_10k_rel || exit 1

# 6. The row-5 per-chip slice: 25k homes x 48h, auto VMEM policy (no env
#    overrides).  Internal: 60+600+60+300+1200 = 2220.
run bench_25k_48h 2400 env BENCH_TPU_TIMEOUT=600 BENCH_CPU_TIMEOUT=1200 \
  python bench.py --homes 25000 --horizon-hours 48 --steps 8 --solver ipm
probe after_25k || exit 1

# 7. Scale validation at 10k x 48h x 2 days (solve rate + comfort).
run validate_10k_48h 2400 python tools/validate_scale.py \
  --homes 10000 --horizon-hours 48 --days 2 --solver ipm

echo "[$(stamp)] runbook complete — record results in docs/perf_notes.md" | tee -a "$OUT/runbook.log"
