#!/bin/bash
# On-chip measurement runbook — run the moment the TPU tunnel is alive.
#
# Round 6: the stage logic moved from bash into the supervised Python
# API (tools/runbook.py over dragg_tpu/resilience): per-stage hard
# deadlines + heartbeat-stall detection + process-group kill, classified
# probe gates between stages (a wedge aborts the pass and NAMES itself),
# and a jax-free parent that cannot be wedged.  This wrapper only
# preserves the historical entry point.
#
# Round 15 adds the fleet-RL training smoke (rl_fleet_smoke_8x64 —
# tools/bench_rl_fleet.py): the first on-chip home-steps/s +
# learner-steps/s for the vectorized RL workload, probe-gated like every
# other stage.
#
#   bash tools/onchip_runbook.sh [outdir]
set -u
cd "$(dirname "$0")/.."
exec python tools/runbook.py --out "${1:-docs/onchip_r6}"
