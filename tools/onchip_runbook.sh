#!/bin/bash
# On-chip measurement runbook — run the moment the TPU tunnel is alive.
# Captures every round-3 measurement in priority order (CLAUDE.md "First
# actions"), each under its own timeout so a mid-run tunnel flap still
# leaves the earlier results on disk.  Output: docs/onchip_r3/*.json|log.
#
#   bash tools/onchip_runbook.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-docs/onchip_r3}
mkdir -p "$OUT"
stamp() { date +%H:%M:%S; }
run() { # run <name> <timeout_s> <cmd...>
  local name=$1 t=$2; shift 2
  echo "[$(stamp)] >>> $name ($*)" | tee -a "$OUT/runbook.log"
  timeout "$t" "$@" >"$OUT/$name.json" 2>"$OUT/$name.log"
  local rc=$?
  echo "[$(stamp)] <<< $name rc=$rc" | tee -a "$OUT/runbook.log"
  tail -c 2000 "$OUT/$name.json" >> "$OUT/runbook.log" || true
  return $rc
}

# 0. Is the chip actually reachable? (hard timeout; a wedged tunnel hangs)
timeout 60 python -c "import jax; d=jax.devices()[0]; print(d.platform, d.device_kind)" \
  > "$OUT/probe.txt" 2>&1 || { echo "TPU unreachable; aborting" | tee -a "$OUT/runbook.log"; exit 1; }
cat "$OUT/probe.txt" | tee -a "$OUT/runbook.log"

# 1. Band-kernel microbench: first-ever Mosaic timing of the pallas kernels,
#    the fused factor+solve variant, and the LANE_BLOCK sweep.
run band_kernel_24h 600 python tools/bench_band_kernel.py --homes 10000 --horizon 24
run band_kernel_48h 600 python tools/bench_band_kernel.py --homes 25000 --horizon 48

# 2. Headline bench at the BASELINE row-3 config (24h) — phase timers,
#    hbm_util, band_kernel field.  --solver ipm skips the ADMM race: the
#    default is settled (docs/perf_notes.md "Solver default decision") and
#    racing would burn ~half the live-tunnel window recompiling ADMM.
run bench_10k_24h 1800 python bench.py --homes 10000 --horizon-hours 24 --solver ipm

# 3. The row-5 per-chip slice: 25k homes x 48h.
run bench_25k_48h 2400 python bench.py --homes 25000 --horizon-hours 48 --steps 8 --solver ipm

# 4. Scale validation at 10k x 48h x 2 days (solve rate + comfort).
run validate_10k_48h 2400 python tools/validate_scale.py \
  --homes 10000 --horizon-hours 48 --days 2 --solver ipm

echo "[$(stamp)] runbook complete — record results in docs/perf_notes.md" | tee -a "$OUT/runbook.log"
