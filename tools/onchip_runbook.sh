#!/bin/bash
# On-chip measurement runbook — run the moment the TPU tunnel is alive.
# Round-4 revision, incorporating the first live window's lessons
# (docs/onchip_r4/, docs/perf_notes.md round 4):
#   * probe BETWEEN steps (a hung compile WEDGES the tunnel for every
#     later backend init — bail instead of burning timeouts);
#   * staged engine sizes (1k before 10k: the 10k attempt hung between
#     engine build and first compile; 1k localizes scale-dependence);
#   * engine-level band-kernel A/B (the microbench says pallas solve is
#     0.73x vs the XLA scan on real Mosaic — the engine default needs an
#     end-to-end verdict);
#   * DRAGG_LANE_BLOCK=256 fallback at m=149 (512 scoped-VMEM OOMs).
# Each step runs under its own timeout so a mid-run flap still leaves
# earlier results on disk.  Output: docs/onchip_r*/ *.json|log.
#
#   bash tools/onchip_runbook.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-docs/onchip_r4}
mkdir -p "$OUT"
export DRAGG_PROBE_LOG="$OUT/probe_log.txt"
stamp() { date +%H:%M:%S; }
probe() { # probe <label> — returns 1 (and logs) when the tunnel is down
  python tools/tpu_probe.py --log "$DRAGG_PROBE_LOG" >/dev/null 2>&1
  local rc=$?
  echo "[$(stamp)] probe($1) rc=$rc" | tee -a "$OUT/runbook.log"
  return $rc
}
run() { # run <name> <timeout_s> <cmd...>
  local name=$1 t=$2; shift 2
  echo "[$(stamp)] >>> $name ($*)" | tee -a "$OUT/runbook.log"
  timeout "$t" "$@" >"$OUT/$name.json" 2>"$OUT/$name.log"
  local rc=$?
  echo "[$(stamp)] <<< $name rc=$rc" | tee -a "$OUT/runbook.log"
  tail -c 2000 "$OUT/$name.json" >> "$OUT/runbook.log" || true
  return $rc
}

# 0. Is the chip actually reachable? (hard timeout; a wedged tunnel hangs)
probe start || { echo "TPU unreachable; aborting" | tee -a "$OUT/runbook.log"; exit 1; }

# 1. Band-kernel microbench (failure-isolated per timing).  48h runs at
#    LANE_BLOCK=256 first — 512 scoped-VMEM OOMs at m=149 — plus the
#    default for the A/B once the OOM is understood.
run band_kernel_24h 600 python tools/bench_band_kernel.py --homes 10000 --horizon 24
probe after_micro24 || exit 1
run band_kernel_48h_lb256 600 env DRAGG_LANE_BLOCK=256 \
  python tools/bench_band_kernel.py --homes 25000 --horizon 48
probe after_micro48 || exit 1
#    ...and the B-chunked fallback: if the OOM'd allocation really is the
#    FULL (m, B) output, lane block can't fix it but bounding B per
#    pallas_call can (bitwise-identical, tests/test_pallas_band.py).
run band_kernel_48h_bchunk 600 env DRAGG_PALLAS_BCHUNK=8192 \
  python tools/bench_band_kernel.py --homes 25000 --horizon 48
probe after_micro48b || exit 1

# 2. STAGED engine benches: 1k first (localizes the 10k hang), then the
#    BASELINE row-3 config.  bench.py itself probe-gates its TPU attempts
#    and falls back to a full-size CPU run, so a wedge mid-step still
#    yields a usable artifact.  IMPORTANT: bench.py's internal ladder
#    budget (probe 60 + BENCH_TPU_TIMEOUT + probe + retry/2 + CPU
#    fallback) must FIT inside the outer `run` timeout, or the outer
#    kill eats the fallback JSON — size both explicitly per step.
run bench_1k_24h 900 env BENCH_TPU_TIMEOUT=300 BENCH_CPU_TIMEOUT=300 \
  python bench.py --homes 1000 --horizon-hours 24 --solver ipm
if ! grep -q '"platform": "tpu"' "$OUT/bench_1k_24h.json" 2>/dev/null; then
  # No TPU-platform result — fell back to CPU, OR the bench hung and the
  # outer timeout killed it before any JSON (empty file): either way,
  # bisect the hang while the window is (possibly) still open.
  # 420 s/stage: if the "hang" is actually a legitimately-slow remote AOT
  # compile of the big engine program, a 240 s stage budget would
  # misdiagnose it as hung — give the engine stages headroom.  Outer
  # budget sized for the worst case (7 stages x 420 + probe): the
  # per-stage verdicts are the whole point, so the outer kill must never
  # eat the final JSON.
  run diagnose 3600 python tools/diagnose_tpu_hang.py \
    --homes 10000 --horizon 24 --timeout 420
fi
probe after_1k || exit 1

# 3. Engine-level band-kernel A/B at 1k (cheap): auto resolves to pallas;
#    xla and cr need explicit config — use the sweep tool.
run band_ab_1k 900 python tools/bench_engine_kernels.py --homes 1000 --horizon-hours 24
probe after_ab || exit 1

# 4. Headline bench at the BASELINE row-3 config (24h).
#    Internal budget: 60 + 600 + 60 + 300 + 600 = 1620 < 1800.
run bench_10k_24h 1800 env BENCH_TPU_TIMEOUT=600 BENCH_CPU_TIMEOUT=600 \
  python bench.py --homes 10000 --horizon-hours 24 --solver ipm
probe after_10k || exit 1

# 5. The row-5 per-chip slice: 25k homes x 48h (lane block 256 until the
#    m=149 VMEM OOM is resolved).  Internal: 60+600+60+300+1200 = 2220.
run bench_25k_48h 2400 env DRAGG_LANE_BLOCK=256 \
  BENCH_TPU_TIMEOUT=600 BENCH_CPU_TIMEOUT=1200 \
  python bench.py --homes 25000 --horizon-hours 48 --steps 8 --solver ipm
probe after_25k || exit 1

# 6. Scale validation at 10k x 48h x 2 days (solve rate + comfort).
run validate_10k_48h 2400 python tools/validate_scale.py \
  --homes 10000 --horizon-hours 48 --days 2 --solver ipm

echo "[$(stamp)] runbook complete — record results in docs/perf_notes.md" | tee -a "$OUT/runbook.log"
