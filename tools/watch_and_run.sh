#!/bin/bash
# Tunnel watcher that AUTO-RUNS the on-chip runbook on every DOWN→LIVE
# edge — live windows are the scarce resource (rounds 2-5: one window in
# four rounds) and must not be wasted waiting for a human to notice.
#
# Round 6: the watch loop moved into the supervised Python API
# (tools/runbook.py --watch over dragg_tpu/resilience); each pass runs
# into a fresh suffix dir, and a failed pass does not latch the edge.
# This wrapper only preserves the historical entry point.
#
#   nohup bash tools/watch_and_run.sh docs/onchip_r6 180 > /tmp/watch.out 2>&1 &
set -u
cd "$(dirname "$0")/.."
exec python tools/runbook.py --out "${1:-docs/onchip_r6}" --watch "${2:-180}"
