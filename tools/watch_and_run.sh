#!/bin/bash
# Tunnel watcher that AUTO-RUNS the on-chip runbook the moment a probe
# comes back LIVE — live windows are the scarce resource (rounds 2-4:
# one window in three rounds) and must not be wasted waiting for a human
# or an agent to notice.  Probes every CADENCE seconds, appends to the
# probe transcript, and on the first LIVE verdict executes
# tools/onchip_runbook.sh once, then keeps watching (a later flap +
# revival triggers a fresh runbook into a new suffix dir).
#
#   nohup bash tools/watch_and_run.sh docs/onchip_r4 180 > /tmp/watch.out 2>&1 &
set -u
cd "$(dirname "$0")/.."
OUT=${1:-docs/onchip_r4}
CADENCE=${2:-180}
n=0
prev=down
while true; do
  if python tools/tpu_probe.py --log "$OUT/probe_log.txt" >/dev/null 2>&1; then
    # Fire only on the DOWN→LIVE edge: a tunnel that stays up must not
    # re-run the multi-hour runbook every probe — the duplicate 10k/25k
    # compiles are themselves the documented wedge trigger (CLAUDE.md).
    if [ "$prev" = down ]; then
      n=$((n + 1))
      # Always a FRESH suffix dir: the base OUT holds committed artifacts
      # from earlier passes/rounds, and the runbook's > redirections would
      # silently truncate them (advisor finding, r4).
      dir="${OUT}_w$n"
      echo "[$(date +%H:%M:%S)] tunnel LIVE — running runbook into $dir"
      bash tools/onchip_runbook.sh "$dir"
      rc=$?
      echo "[$(date +%H:%M:%S)] runbook pass $n finished rc=$rc"
      if [ $rc -eq 0 ]; then
        prev=live
      else
        # A failed runbook (e.g. its own start probe lost a transient
        # flap) must NOT latch prev=live — that would suppress the edge
        # for the rest of a real window.  Treat as still-down and retry
        # on the next probe.
        prev=down
      fi
    else
      prev=live
    fi
  else
    prev=down
  fi
  sleep "$CADENCE"
done
