"""Component-level timing of the ADMM solve on the current backend.

Times, at a given (B, H): the factor (Cholesky+inverse), sparse S formation,
one 25-iteration window without rho refactors, the residual check, and the
full solve — to attribute the per-step solve time seen in bench.py.

Usage: python tools/profile_solver.py [B] [H]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import jax
import jax.numpy as jnp
from jax import lax


def timeit(fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    H = int(sys.argv[2]) if len(sys.argv) > 2 else 24

    from dragg_tpu.config import default_config
    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes
    from dragg_tpu.ops import admm as A

    cfg = default_config()
    cfg["community"]["total_number_homes"] = B
    cfg["community"]["homes_pv"] = int(0.4 * B)
    cfg["community"]["homes_battery"] = int(0.1 * B)
    cfg["community"]["homes_pv_battery"] = int(0.1 * B)
    cfg["home"]["hems"]["prediction_horizon"] = H
    # This tool times the SUPERSET-shaped ADMM components (factor, S
    # formation, iteration window) — pin the one-batch path so the
    # shapes printed match the matrices timed.
    cfg["tpu"]["bucketed"] = "false"
    env = load_environment(cfg, data_dir=None)
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24 * 7, 1, wd)
    hems = cfg["home"]["hems"]
    batch = build_home_batch(homes, H, 1, int(hems["sub_subhourly_steps"]))
    eng = make_engine(batch, env, cfg, 0)
    state = eng.init_state()
    from functools import partial

    qp, aux = jax.jit(partial(eng._prepare, eng._ctx0))(
        state, jnp.asarray(0), jnp.zeros((H,), jnp.float32))
    jax.block_until_ready(qp.vals)
    pat = eng.static.pattern
    m, n = pat.m, pat.n
    print(f"B={B} H={H} m_eq={m} n={n} nnz={pat.nnz}", flush=True)

    dev = jax.devices()[0]  # dragg: disable=DT004, runs under the runbook supervisor deadline
    print("device:", dev.device_kind, flush=True)

    rows = jnp.asarray(pat.rows); cols = jnp.asarray(pat.cols)
    d, e_eq, e_box, c = jax.jit(
        lambda v, q: A.ruiz_equilibrate_sparse(pat, v, q, iters=10),
        static_argnames=()
    )(qp.vals, qp.q)
    jax.block_until_ready(d)
    vals_s = e_eq[:, rows] * qp.vals * d[:, cols]
    schur = A._schur_structure_for(pat)
    print("schur: n_s =", schur.n_s, "P =", schur.P, flush=True)

    Dinv = jnp.ones((B, n), jnp.float32) * 0.5

    form_S = jax.jit(lambda v, Di: A.form_schur_sparse(schur, m, v, Di))
    S = form_S(vals_s, Dinv)
    t_formS = timeit(form_S, vals_s, Dinv)

    def chol_inv(S):
        L = jnp.linalg.cholesky(S)
        eye = jnp.broadcast_to(jnp.eye(m, dtype=S.dtype), S.shape)
        Linv = lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
        return jnp.einsum("bkm,bkn->bmn", Linv, Linv,
                          precision=lax.Precision.HIGHEST)
    chol_inv_j = jax.jit(chol_inv)
    Sinv = chol_inv_j(S)
    t_factor = timeit(chol_inv_j, S)

    def chol_only(S):
        return jnp.linalg.cholesky(S)
    t_chol = timeit(jax.jit(chol_only), S)

    r = jnp.ones((B, m), jnp.float32)

    def matvec(Sinv, r):
        return jnp.einsum("bmn,bn->bm", Sinv, r, precision=lax.Precision.HIGHEST)
    t_mv = timeit(jax.jit(matvec), Sinv, r)

    def s_solve_refine(Sinv, S, r):
        v = matvec(Sinv, r)
        resid = r - matvec(S, v)
        return v + matvec(Sinv, resid)
    t_refine = timeit(jax.jit(s_solve_refine), Sinv, S, r)

    x = jnp.ones((B, n), jnp.float32)
    row_cols = jnp.asarray(pat.row_cols); row_src = jnp.asarray(pat.row_src)
    col_rows = jnp.asarray(pat.col_rows); col_src = jnp.asarray(pat.col_src)
    vp_r = A._pad_gather(vals_s, row_src)
    vp_c = A._pad_gather(vals_s, col_src)

    def mv(x):
        return jnp.sum(vp_r * x[:, row_cols], axis=2)

    def mvt(y):
        return jnp.sum(vp_c * y[:, col_rows], axis=2)
    t_mv_sparse = timeit(jax.jit(mv), x)
    t_mvt_sparse = timeit(jax.jit(mvt), r)

    # One full solve (cold) with iteration counter.
    solve = jax.jit(lambda v, b, l, u, q: A.admm_solve_qp(
        pat, v, b, l, u, q, iters=1000, reg=1e-3))
    sol = solve(qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q)
    jax.block_until_ready(sol.x)
    t_solve = timeit(solve, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q, reps=2)
    iters = int(sol.iters)

    print(f"form_S            {t_formS * 1e3:9.2f} ms")
    print(f"cholesky          {t_chol * 1e3:9.2f} ms")
    print(f"factor (chol+inv) {t_factor * 1e3:9.2f} ms")
    print(f"Sinv matvec       {t_mv * 1e3:9.2f} ms")
    print(f"s_solve refine=1  {t_refine * 1e3:9.2f} ms")
    print(f"sparse mv         {t_mv_sparse * 1e3:9.2f} ms")
    print(f"sparse mvt        {t_mvt_sparse * 1e3:9.2f} ms")
    print(f"full solve        {t_solve * 1e3:9.2f} ms   ({iters} iters, "
          f"{t_solve / max(iters, 1) * 1e3:.3f} ms/iter)")
    print(f"solved: {int(jnp.sum(sol.solved))}/{B}")


if __name__ == "__main__":
    main()
