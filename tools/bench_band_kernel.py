"""Microbenchmark: Pallas band kernels vs the XLA scan path.

Times the two band operations that dominate an IPM iteration (Cholesky
factor and the refined solve) at MPC-realistic shapes on whatever backend
is up, printing one JSON line.  Engine-step comparisons come from
bench.py's phase timers (and its --solver auto race).  This is the measurement behind the
band_kernel='auto' policy (docs/perf_notes.md).

Usage: python tools/bench_band_kernel.py [--homes 10000] [--horizon 24]
       [--iters 30]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--homes", type=int, default=10_000)
    ap.add_argument("--horizon", type=int, default=24)
    ap.add_argument("--iters", type=int, default=30, help="timing repetitions")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from dragg_tpu.ops import banded as bd
    from dragg_tpu.ops import pallas_band as pb

    dev = jax.devices()[0]  # dragg: disable=DT004, runs under the runbook supervisor deadline
    B, bw = args.homes, 4
    m = 3 * args.horizon + 5  # MPC Schur size at H decision steps
    rng = np.random.default_rng(0)
    Sb = np.zeros((B, m, bw + 1), np.float32)
    Sb[:, :, 0] = 10.0 + rng.random((B, m))
    for k in range(1, bw + 1):
        Sb[:, k:, k] = rng.standard_normal((B, m - k)).astype(np.float32) * 0.5
    Sb = jax.device_put(jnp.asarray(Sb))
    Sb_t = jnp.transpose(Sb, (1, 2, 0))
    r = jax.device_put(jnp.asarray(rng.standard_normal((B, m)).astype(np.float32)))

    chol_x = jax.jit(lambda s: bd.banded_cholesky(s, bw))
    chol_p = jax.jit(lambda s: pb.banded_cholesky_t(s, bw))

    def solve_x(L, S, rr):
        v = bd.banded_solve(L, rr, bw)
        resid = rr - bd.band_matvec(S, v, bw)
        return v + bd.banded_solve(L, resid, bw)

    solve_x = jax.jit(solve_x)
    solve_p = jax.jit(lambda L, S, rr: pb.refined_banded_solve_t(L, S, rr, bw, refine=1))

    def timeit(fn, *a):
        out = jax.block_until_ready(fn(*a))  # compile
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(*a)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.iters

    res = {
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "homes": B, "m": m, "bw": bw,
        "lane_block": pb.LANE_BLOCK,
    }

    def timed(name, fn, *a):
        """One failure (e.g. a VMEM OOM at a large m × lane_block point —
        observed on-chip round 4 at m=149, LANE_BLOCK=512) must not sink
        the remaining measurements: record null + the error and continue."""
        try:
            res[name] = timeit(fn, *a)
        except Exception as e:
            res[name] = None
            res[name + "_err"] = repr(e)[:300]

    def ratio(num, den):
        return (round(res[num] / res[den], 2)
                if res.get(num) and res.get(den) else None)

    r_t = jnp.swapaxes(r, 0, 1)
    timed("chol_xla_s", chol_x, Sb)
    timed("chol_pallas_s", chol_p, Sb_t)
    # Warm-ups are guarded too: an OOM here must not sink the whole file
    # (the failure-isolation goal of this harness).
    try:
        Lx = jax.block_until_ready(chol_x(Sb))
        timed("solve_xla_s", solve_x, Lx, Sb, r)
    except Exception as e:
        res["solve_xla_s"] = None
        res["solve_xla_s_err"] = repr(e)[:300]
    try:
        Lp = jax.block_until_ready(chol_p(Sb_t))
        timed("solve_pallas_s", solve_p, Lp, Sb_t, r_t)
    except Exception as e:
        res["solve_pallas_s"] = None
        res["solve_pallas_s_err"] = repr(e)[:300]
    res["chol_speedup"] = ratio("chol_xla_s", "chol_pallas_s")
    res["solve_speedup"] = ratio("solve_xla_s", "solve_pallas_s")

    # Fused factor+solve (one kernel) vs the split chol → solve pair — the
    # predictor-step shape the IPM actually runs (refine=0).
    fused = jax.jit(lambda S, rr: pb.factor_refined_solve_t(S, rr, bw, refine=0))
    split = jax.jit(lambda S, rr: pb.refined_banded_solve_t(
        pb.banded_cholesky_t(S, bw), S, rr, bw, refine=0))
    timed("pred_split_s", split, Sb_t, r_t)
    timed("pred_fused_s", fused, Sb_t, r_t)
    res["fused_speedup"] = ratio("pred_split_s", "pred_fused_s")

    # XLA factor+solve pair at the same predictor shape — the band_kernel
    # A/B the engine actually chooses between.
    xla_fs = jax.jit(lambda S, rr: bd.banded_solve(bd.banded_cholesky(S, bw),
                                                   rr, bw))
    timed("pred_xla_s", xla_fs, Sb, r)

    # Block cyclic reduction (ops/block_cr.py): serial depth log2(m/bw)
    # instead of m.  CPU-measured 2.9x SLOWER than the scans (it doubles
    # FLOPs and CPUs aren't latency-bound — docs/perf_notes.md); this
    # timing decides whether the latency hypothesis holds on real TPU.
    from dragg_tpu.ops import block_cr as cr

    cr_fs = jax.jit(lambda S, rr: cr.cr_solve(cr.cr_factor(S, bw), rr))
    timed("pred_cr_s", cr_fs, Sb, r)
    res["cr_vs_pallas"] = ratio("pred_fused_s", "pred_cr_s")

    # LANE_BLOCK sweep over the fused kernel (the env knob DRAGG_LANE_BLOCK
    # applies the winner process-wide).  Skipped in interpret mode — block
    # size only matters on real Mosaic.
    if dev.platform == "tpu":
        sweep = {}
        for lbs in (128, 256, 512, 1024):
            f = jax.jit(lambda S, rr, _lb=lbs: pb.factor_refined_solve_t(
                S, rr, bw, refine=0, lane_block=_lb))
            try:
                sweep[str(lbs)] = round(timeit(f, Sb_t, r_t), 6)
            except Exception as e:
                sweep[str(lbs)] = repr(e)[:120]
        res["lane_block_sweep_s"] = sweep

    print(json.dumps({k: (round(v, 6) if isinstance(v, float) else v)
                      for k, v in res.items()}))


if __name__ == "__main__":
    main()
