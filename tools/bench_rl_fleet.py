"""Fleet RL training throughput — the ROADMAP item 1 measurement tool.

Runs one vectorized fleet RL case (dragg_tpu/rl/fleet) end-to-end and
prints ONE JSON line: home-steps/s and learner-steps/s at the configured
(C communities × B homes) scale, plus the ``rl`` series key bench_trend
gates on (RL rows never compare against MPC-baseline history — the same
hard-key convention as solver/semantics/communities/mix).

Two timed passes: the first pays the trace+compile (reported as
``cold_s``), the second rides the persistent XLA compile cache and
reports the warm training rate (the headline).

Supervised (round 6): the measurement runs in a CHILD process under the
resilience supervisor — hard deadline, optional heartbeat stall — so a
hung device chunk kills the child instead of wedging this process.

Usage: python tools/bench_rl_fleet.py [--homes 64] [--communities 8]
                                      [--hours 24] [--case rl_agg]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--homes", type=int, default=64,
                    help="homes PER COMMUNITY (fleet total = homes × "
                         "--communities)")
    ap.add_argument("--communities", type=int, default=8,
                    help="fleet size C — parallel RL rollout streams "
                         "under one compiled pattern set")
    ap.add_argument("--hours", type=int, default=24,
                    help="simulated hours (= learner steps at dt=1)")
    ap.add_argument("--horizon-hours", type=int, default=6)
    ap.add_argument("--case", choices=["rl_agg", "simplified"],
                    default="rl_agg")
    ap.add_argument("--agent", choices=["linear", "ddpg"], default="linear")
    ap.add_argument("--policy", choices=["shared", "per_community"],
                    default="shared")
    ap.add_argument("--gradient", choices=["score", "mpc"], default="score")
    ap.add_argument("--solver", choices=["admm", "ipm", "reluqp"],
                    default="ipm")
    ap.add_argument("--deadline", type=float, default=1800.0,
                    help="hard wall-clock limit for the supervised "
                         "measurement child")
    ap.add_argument("--stall", type=float, default=0.0,
                    help="heartbeat-stall kill (0 = disabled; set ~900 "
                         "on-chip where a stall means a wedge-risk hang)")
    ap.add_argument("--_child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if not args._child:
        # Supervised parent: jax-free, un-wedgeable (validate_scale.py
        # pattern).  The child is this same script; its one JSON line is
        # forwarded verbatim.
        from dragg_tpu.resilience.supervisor import (assert_parent_has_no_jax,
                                                     run_supervised)

        assert_parent_has_no_jax()
        res = run_supervised(
            [sys.executable, os.path.abspath(__file__), "--_child",
             *sys.argv[1:]],
            args.deadline, label="bench_rl_fleet",
            stall_s=args.stall or None,
            log=lambda m: print(f"[supervise] {m}", file=sys.stderr,
                                flush=True))
        sys.stderr.write(res.stderr_tail)
        if res.json is not None:
            print(json.dumps(res.json))
        elif not res.ok:
            print(json.dumps({"ok": False, "failure": res.failure,
                              "rc": res.rc,
                              "elapsed_s": round(res.elapsed_s, 1)}))
        sys.exit(res.rc if res.rc is not None and res.rc >= 0 else 1)

    import tempfile

    import jax
    import numpy as np

    from dragg_tpu.aggregator import Aggregator
    from dragg_tpu.config import default_config
    from dragg_tpu.resilience.heartbeat import beat

    def build_cfg():
        cfg = default_config()
        n = args.homes
        cfg["community"]["total_number_homes"] = n
        cfg["community"]["homes_pv"] = int(0.4 * n)
        cfg["community"]["homes_battery"] = int(0.1 * n)
        cfg["community"]["homes_pv_battery"] = int(0.1 * n)
        cfg["fleet"]["communities"] = args.communities
        cfg["home"]["hems"]["prediction_horizon"] = args.horizon_hours
        cfg["home"]["hems"]["solver"] = args.solver
        cfg["simulation"]["start_datetime"] = "2015-01-01 00"
        end_day = 1 + args.hours // 24
        end_h = args.hours % 24
        cfg["simulation"]["end_datetime"] = \
            f"2015-01-{end_day:02d} {end_h:02d}"
        cfg["simulation"]["run_rbo_mpc"] = False
        cfg["simulation"][f"run_{args.case}" if args.case == "rl_agg"
                          else "run_rl_simplified"] = True
        cfg["rl"]["parameters"]["agent"] = args.agent
        cfg["rl"]["fleet"]["policy"] = args.policy
        cfg["rl"]["fleet"]["gradient"] = args.gradient
        cfg["telemetry"]["enabled"] = False
        return cfg

    case_dir = "rl_agg" if args.case == "rl_agg" else "simplified"
    times = []
    agg = None
    for attempt in range(2):
        beat({"stage": f"pass{attempt}", "case": args.case})
        with tempfile.TemporaryDirectory() as td:
            agg = Aggregator(build_cfg(), data_dir="", outputs_dir=td)
            t0 = time.perf_counter()
            agg.run()
            times.append(time.perf_counter() - t0)
        beat({"stage": f"pass{attempt}_done",
              "elapsed_s": round(times[-1], 1)})

    T = agg.num_timesteps
    C = args.communities
    n_total = args.homes * C
    warm_s = times[-1]
    rl_label = f"{args.policy}_{args.agent}" + (
        "" if args.gradient == "score" else f"_{args.gradient}")
    result = {
        # ``rl`` is a HARD bench_trend series key (tools/bench_trend.py):
        # RL training rows form their own comparison series and never
        # gate against the MPC-baseline ("none") history.
        "rl": rl_label,
        "case": case_dir,
        "homes": args.homes,
        "communities": C,
        "homes_total": n_total,
        "steps": T,
        "agent": args.agent,
        "policy": args.policy,
        "gradient": args.gradient,
        "solver": args.solver if args.case == "rl_agg" else "none",
        "semantics": "integer" if args.case == "rl_agg" else "n/a",
        "mix": "legacy",
        "precision": "f32",
        "platform": jax.devices()[0].platform,  # dragg: disable=DT004, supervised child
        "n_devices": len(jax.devices()),  # dragg: disable=DT004, supervised child
        "cold_s": round(times[0], 2),
        "warm_s": round(warm_s, 2),
        # Home-steps/s: fleet total homes × sim steps per warm second —
        # comparable with the MPC engine's scale metric.
        "home_steps_per_s": round(n_total * T / warm_s, 1),
        # Learner-steps/s: fused policy updates per warm second (shared
        # mode runs ONE batched learner update per fleet step).
        "learner_steps_per_s": round(T / warm_s, 2),
        # Agent-env interactions per second across the fleet (C rollout
        # streams advance per learner step).
        "agent_steps_per_s": round(C * T / warm_s, 1),
        # rl_agg advances agg.timestep chunk by chunk; the simplified
        # case is summary-only (timestep stays 0) — its completion
        # signal is the full aggregate series.
        "ok": bool(np.isfinite(warm_s)
                   and (agg.timestep == T if args.case == "rl_agg"
                        else len(agg.baseline_agg_load_list) == T)),
    }
    print(json.dumps(result))
    sys.exit(0 if result["ok"] else 1)


if __name__ == "__main__":
    main()
