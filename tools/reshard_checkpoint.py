"""Elastic checkpoint resharding: rewrite a sharded fleet run's
community partition so a 10×10k run resumes as 20×5k (or back).

    python tools/reshard_checkpoint.py --run-dir OLD --out-dir NEW \
        --workers M

Reads a QUIESCED shard run directory (every shard checkpointed at the
SAME chunk boundary — the coordinator's ``stop_t`` barrier produces
exactly that; unequal frontiers are refused loudly), regroups the
per-community state rows and the merged chunk history into ``M`` new
contiguous community ranges, and writes a fresh run directory the
coordinator resumes from unchanged (``python -m dragg_tpu.shard
--run-dir NEW ...``).

What moves where:

* **carry state** — each community's per-home rows are extracted from
  the old shard engines' type-major order and re-laid into the new
  shard engines' order (bucket layouts may legitimately differ between
  partitions: ``tpu.bucketed=auto`` thresholds see different per-shard
  totals; the mapping is per GLOBAL home, so any old→new layout pair
  round-trips).  Values are copied bit-for-bit, never recomputed;
* **chunk history** — the already-merged per-community aggregate series
  are regrouped by community columns into the new shards' outbox files,
  and a fresh journal plans the new partition with every historical
  chunk acked, so the resumed coordinator's merge covers ``[0, t)``
  without re-solving anything;
* **validation** — community-by-community: every community's carry rows
  are read BACK from the new checkpoint files on disk and compared
  bit-exact against the old (per-community verdicts in the JSON line).

Offline by construction: engines are built only as state TEMPLATES on
the pinned CPU backend — the tool never touches the TPU and never runs
a solve.  Mesh-sharded worker checkpoints (``tpu.sharded`` true/auto on
a multi-device worker) carry slot-padded leaves this tool's unsharded
templates refuse loudly (load_pytree leaf-shape check) — reshard those
on a single-device resolution, or quiesce and reshard with
``tpu.sharded = false`` workers.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_shard(spool_dir, k, cfg, spec, build):
    """(engine, state, t) for one existing shard checkpoint."""
    from dragg_tpu.checkpoint import (latest_checkpoint_dir, load_progress,
                                      load_pytree)
    from dragg_tpu.serve import spool as sp

    root = sp.shard_ckpt_root(spool_dir, k)
    d = latest_checkpoint_dir(root)
    if d is None:
        raise SystemExit(f"shard {k}: no checkpoint under {root} — run the "
                         f"coordinator to a stop_t barrier first")
    prog = load_progress(os.path.join(d, "progress.json"))
    eng = build(cfg, spec["c0"], spec["c1"])
    state = load_pytree(os.path.join(d, "state.npz"), eng.init_state())
    return eng, state, int(prog["timestep"])


def _bucket_states(state):
    """Normalize a carry to its per-bucket list.  Bucketed engines carry
    a PLAIN tuple of CommunityState, unbucketed a single CommunityState
    — itself a NamedTuple, so the discriminator is ``_fields``, not
    ``isinstance(..., tuple)``."""
    return [state] if hasattr(state, "_fields") else list(state)


def _row_maps(engine, c0, B):
    """Per-bucket arrays of GLOBAL community-major home indices for each
    state row (-1 = pad slot).  Global home ``g`` of local community-
    major index ``j`` is ``c0*B + j`` — contiguous ranges make the shard
    offset a plain stride."""
    import numpy as np

    fr = np.asarray(engine._fleet_rows["home_idx"])
    true_n = getattr(engine, "true_n_homes", None) or engine.n_homes
    if engine.bucketed:
        out = []
        for b in engine.bucket_info():
            rows = np.full(b["n_slots"], -1, np.int64)
            rows[:b["n_real"]] = (c0 * B
                                  + fr[b["comm_start"]:
                                       b["comm_start"] + b["n_real"]])
            out.append(rows)
        return out
    rows = np.full(engine.n_homes, -1, np.int64)
    rows[:true_n] = c0 * B + fr[:true_n]
    return [rows]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--run-dir", required=True,
                    help="existing (quiesced) shard run directory")
    ap.add_argument("--out-dir", required=True,
                    help="fresh run directory for the new partition "
                         "(refused if it already has a journal)")
    ap.add_argument("--workers", type=int, required=True,
                    help="new shard count M")
    args = ap.parse_args()

    # Offline rewrite: pin the CPU backend BEFORE any jax op (CLAUDE.md —
    # a wedged tunnel hangs backend init; this tool must never need one).
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from dragg_tpu.checkpoint import save_checkpoint_dir
    from dragg_tpu.data import load_environment, load_waterdraw_profiles, waterdraw_path
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_fleet_batch, create_fleet_homes
    from dragg_tpu.serve import spool as sp
    from dragg_tpu.shard import journal as sj
    from dragg_tpu.shard.coordinator import JOURNAL_FILE
    from dragg_tpu.shard.partition import (merge_shard_series, shard_config,
                                           shard_ranges)
    from dragg_tpu.shard.worker import _run_shape

    old_spool = os.path.join(args.run_dir, "spool")
    rep = sj.replay(os.path.join(args.run_dir, JOURNAL_FILE))
    if rep.plan is None:
        raise SystemExit(f"{args.run_dir}: no journaled plan — not a shard "
                         f"run directory")
    C = int(rep.plan["communities"])
    steps = int(rep.plan["steps"])
    k_chunk = int(rep.plan["chunk_steps"])
    old_ranges = [tuple(r) for r in rep.plan["ranges"]]
    new_ranges = shard_ranges(C, args.workers)
    if os.path.exists(os.path.join(args.out_dir, JOURNAL_FILE)):
        raise SystemExit(f"{args.out_dir} already holds a shard journal — "
                         f"refusing to overwrite a run in place")

    spec0 = sp.read_json(sp.shard_spec_path(old_spool, 0))
    if spec0 is None:
        raise SystemExit(f"{old_spool}/s0/spec.json missing/torn")
    cfg = spec0["config"]
    data_dir = spec0.get("data_dir")
    B = int(cfg["community"]["total_number_homes"])
    env_cache = {}

    def build(cfg_global, c0, c1):
        scfg = shard_config(cfg_global, c0, c1)
        if "env" not in env_cache:
            env_cache["env"] = load_environment(scfg, data_dir=data_dir)
        env = env_cache["env"]
        dt = int(scfg["agg"]["subhourly_steps"])
        wd = load_waterdraw_profiles(
            waterdraw_path(scfg, data_dir),
            seed=int(scfg["simulation"]["random_seed"]))
        homes = create_fleet_homes(scfg, steps, dt, wd)
        hems = scfg["home"]["hems"]
        horizon = max(1, int(hems["prediction_horizon"]) * dt)
        batch, fleet = build_fleet_batch(
            homes, scfg, horizon, dt, int(hems["sub_subhourly_steps"]))
        return make_engine(batch, env, scfg, int(spec0.get("start_index", 0)),
                           fleet=fleet, data_dir=data_dir)

    # ---------------------------------------------------- load old shards
    old = []
    for k, (c0, c1) in enumerate(old_ranges):
        spec = sp.read_json(sp.shard_spec_path(old_spool, k))
        eng, state, t = _load_shard(old_spool, k, cfg, spec, build)
        old.append(dict(k=k, c0=c0, c1=c1, eng=eng,
                        states=_bucket_states(state), t=t))
    ts = sorted({o["t"] for o in old})
    if len(ts) != 1:
        raise SystemExit(f"shard frontiers unequal ({ts}) — quiesce the run "
                         f"at a stop_t barrier before resharding")
    t_bar = ts[0]
    if t_bar % k_chunk and t_bar != steps:
        raise SystemExit(f"frontier t={t_bar} is not a chunk boundary")

    # global home -> (old shard, bucket, row); + field-named old leaves
    lookup = np.full((C * B, 3), -1, np.int64)
    for o in old:
        for bi, rows in enumerate(_row_maps(o["eng"], o["c0"], B)):
            for r, g in enumerate(rows):
                if g >= 0:
                    lookup[g] = (o["k"], bi, r)
    if np.any(lookup[:, 0] < 0):
        missing = int(np.sum(lookup[:, 0] < 0))
        raise SystemExit(f"{missing} homes unmapped in the old checkpoints "
                         f"— corrupt run dir?")

    # Old chunk payload history, merged to (T, C) per series then
    # regrouped per new shard below.
    n_hist = t_bar // k_chunk + (1 if t_bar % k_chunk else 0)
    payloads = {}   # seq -> per-old-shard payload dict
    for seq in range(n_hist):
        per = {}
        for o in old:
            p = sp.read_json(sp.chunk_path(old_spool, o["k"], seq))
            if p is None:
                raise SystemExit(f"old shard {o['k']} chunk {seq} "
                                 f"missing/torn in the spool")
            per[o["k"]] = p
        payloads[seq] = per

    # ----------------------------------------------------- write new run
    os.makedirs(args.out_dir, exist_ok=True)
    new_spool = os.path.join(args.out_dir, "spool")
    journal = sj.Journal(os.path.join(args.out_dir, JOURNAL_FILE))
    journal.plan(C, args.workers, new_ranges, steps, k_chunk)
    verdicts = {}
    key_field = "key"  # the one non-home-axis CommunityState leaf
    for j, (a, b) in enumerate(new_ranges):
        sp.ensure_shard_dirs(new_spool, j)
        spec_j = {"config": cfg, "data_dir": data_dir, "c0": a, "c1": b,
                  "steps": steps, "chunk_steps": k_chunk, "stop_t": None,
                  "start_index": int(spec0.get("start_index", 0))}
        sp.atomic_write_json(sp.shard_spec_path(new_spool, j), spec_j)
        eng_j = build(cfg, a, b)
        template = eng_j.init_state()
        tpl_states = _bucket_states(template)
        new_states = []
        for bi, (tpl, rows) in enumerate(zip(tpl_states,
                                             _row_maps(eng_j, a, B))):
            fields = {}
            for f in tpl._fields:
                leaf = np.array(np.asarray(getattr(tpl, f)))
                if f == key_field:
                    # The PRNG-key leaf is a legacy scalar carry, equal
                    # across shards by construction (params.seed is the
                    # shared base seed) — verified, then copied.
                    vals = [np.asarray(getattr(st, f))
                            for o in old for st in o["states"]]
                    for v in vals[1:]:
                        if not np.array_equal(vals[0], v):
                            raise SystemExit(
                                "PRNG-key carry differs across old shards "
                                "— refusing to guess")
                    leaf = vals[0]
                else:
                    for r, g in enumerate(rows):
                        if g < 0:
                            continue
                        ok_, ob, orow = lookup[g]
                        src = np.asarray(getattr(old[ok_]["states"][ob], f))
                        leaf[r] = src[orow]
                fields[f] = leaf
            new_states.append(type(tpl)(**fields))
        new_state = (new_states[0] if hasattr(template, "_fields")
                     else tuple(new_states))
        scfg_j = shard_config(cfg, a, b)
        save_checkpoint_dir(
            sp.shard_ckpt_root(new_spool, j), t_bar, new_state,
            {"run_shape": _run_shape(spec_j, scfg_j, eng_j),
             "resharded_from": os.path.abspath(args.run_dir)})
        # Regrouped chunk history: merged (T_chunk, C) slabs sliced to
        # this shard's community columns, acked in the fresh journal.
        for seq in range(n_hist):
            per = payloads[seq]
            merged = {}
            any_p = per[0]
            for name in any_p["series"]:
                slab = merge_shard_series(
                    {o["k"]: np.asarray(per[o["k"]]["series"][name],
                                        dtype=np.float64)
                     for o in old},
                    old_ranges)
                merged[name] = slab[:, a:b].tolist()
            n_steps = int(any_p["t1"]) - int(any_p["t0"])
            solved = np.asarray(merged["solved"], dtype=np.float64)
            sp.atomic_write_json(
                sp.chunk_path(new_spool, j, seq),
                {"shard": j, "gen": 0, "seq": seq,
                 "t0": any_p["t0"], "t1": any_p["t1"],
                 "platform": "reshard",
                 "series": merged,
                 "solve_rate": float(solved.sum()
                                     / max(n_steps * (b - a) * B, 1)),
                 "viol_max": max(float(per[o["k"]].get("viol_max", 0.0))
                                 for o in old),
                 "band_tol": max(float(per[o["k"]].get("band_tol", 0.05))
                                 for o in old),
                 "device_s": None})
            journal.chunk(j, seq, int(any_p["t0"]), int(any_p["t1"]))
        # ---------------- community-by-community read-back validation
        from dragg_tpu.checkpoint import (latest_checkpoint_dir,
                                          load_pytree)

        d = latest_checkpoint_dir(sp.shard_ckpt_root(new_spool, j))
        back = _bucket_states(
            load_pytree(os.path.join(d, "state.npz"), eng_j.init_state()))
        rows_j = _row_maps(eng_j, a, B)
        for c in range(a, b):
            ok = True
            for bi, rows in enumerate(rows_j):
                for r, g in enumerate(rows):
                    if g < 0 or not (c * B <= g < (c + 1) * B):
                        continue
                    ok_, ob, orow = lookup[g]
                    for f in back[bi]._fields:
                        if f == key_field:
                            continue
                        nv = np.asarray(getattr(back[bi], f))[r]
                        ov = np.asarray(
                            getattr(old[ok_]["states"][ob], f))[orow]
                        if not np.array_equal(nv, ov):
                            ok = False
            verdicts[c] = ok
    journal.close()
    result = {
        "ok": all(verdicts.values()),
        "communities": C,
        "t": t_bar,
        "steps": steps,
        "chunk_steps": k_chunk,
        "old_workers": len(old_ranges),
        "new_workers": args.workers,
        "old_ranges": [list(r) for r in old_ranges],
        "new_ranges": [list(r) for r in new_ranges],
        "chunks_carried": n_hist,
        "validated_per_community": {str(c): bool(v)
                                    for c, v in sorted(verdicts.items())},
        "out_dir": os.path.abspath(args.out_dir),
    }
    print(json.dumps(result))
    sys.exit(0 if result["ok"] else 1)


if __name__ == "__main__":
    main()
