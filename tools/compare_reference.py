"""Home-by-home diff of a REAL reference run vs a dragg_tpu run (VERDICT
r4 next-5).

The reference stack (cvxpy + GLPK_MI + a redis server) is not in this
build image and cannot be installed here; the repo's Docker image ships
it precisely for this harness (see docs/reference_comparison.md for the
recipe).  This tool therefore has two modes:

* diff mode (runs anywhere): given the two runs' results.json files —
  the reference's layout and ours are schema-identical by construction
  (dragg_tpu/aggregator.py results writer, parity cites therein) — align
  homes by name and report per-series divergence statistics as one JSON
  line.
* --run-reference: execute the reference's own main loop in-process
  (needs cvxpy/glpk/redis importable AND a redis server); refuses with a
  clear message when the stack is absent.

Series compared per home (the reference's result hash fields,
dragg/mpc_calc.py:482-524): temp_in_opt, temp_wh_opt, p_grid_opt, cost,
hvac_cool_on_opt, hvac_heat_on_opt, wh_heat_on_opt, correct_solve.

Usage:
  python tools/compare_reference.py REF_RESULTS.json OURS_RESULTS.json
  python tools/compare_reference.py --run-reference --config C --data-dir D
"""

import argparse
import json
import sys

import numpy as np

SERIES = ("temp_in_opt", "temp_wh_opt", "p_grid_opt", "cost_opt",
          "hvac_cool_on_opt", "hvac_heat_on_opt", "wh_heat_on_opt",
          "correct_solve")


def load_homes(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    return {k: v for k, v in data.items() if k != "Summary"}


def diff(ref_path: str, ours_path: str) -> dict:
    ref, ours = load_homes(ref_path), load_homes(ours_path)
    shared = sorted(set(ref) & set(ours))
    out = {
        "n_homes_ref": len(ref), "n_homes_ours": len(ours),
        "n_shared": len(shared), "series": {},
    }
    worst = 0.0
    for s in SERIES:
        maxabs, rmse, n = 0.0, 0.0, 0
        per_home_max = {}
        missing = 0
        for h in shared:
            a = np.asarray(ref[h].get(s, []), dtype=float)
            b = np.asarray(ours[h].get(s, []), dtype=float)
            m = min(len(a), len(b))
            if m == 0:
                # A series absent from every home must be visible in the
                # verdict, not silently reported as zero divergence.
                missing += 1
                continue
            d = np.abs(a[:m] - b[:m])
            per_home_max[h] = float(d.max())
            maxabs = max(maxabs, float(d.max()))
            rmse += float(np.sum((a[:m] - b[:m]) ** 2))
            n += m
        top = sorted(per_home_max.items(), key=lambda kv: -kv[1])[:3]
        out["series"][s] = {
            "max_abs": round(maxabs, 6),
            "rmse": round((rmse / max(n, 1)) ** 0.5, 6),
            "worst_homes": [h for h, _ in top],
            **({"missing_homes": missing} if missing else {}),
        }
        if s in ("temp_in_opt", "temp_wh_opt"):
            worst = max(worst, maxabs)
    out["bounded"] = bool(worst <= 1.0)  # ≤1 °C trajectory divergence
    return out


def run_reference(config: str, data_dir: str) -> None:
    missing = []
    for mod in ("cvxpy", "redis", "pathos"):
        try:
            __import__(mod)
        except ImportError:
            missing.append(mod)
    if missing:
        sys.exit(
            f"reference stack unavailable: {', '.join(missing)} not "
            f"importable.  Build and run the repo's Docker image "
            f"(docs/reference_comparison.md) — it installs cvxpy+glpk+"
            f"redis and starts redis-server — then rerun with "
            f"--run-reference inside it.")
    sys.path.insert(0, "/root/reference")
    import os

    os.environ.setdefault("CONFIG_FILE", config)
    os.environ.setdefault("DATA_DIR", data_dir)
    from dragg.aggregator import Aggregator  # the real reference

    Aggregator().run()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("paths", nargs="*", help="REF_RESULTS.json OURS_RESULTS.json")
    ap.add_argument("--run-reference", action="store_true")
    ap.add_argument("--config", default="config.toml")
    ap.add_argument("--data-dir", default="/root/reference/dragg/data")
    args = ap.parse_args()
    if args.run_reference:
        run_reference(args.config, args.data_dir)
        return
    if len(args.paths) != 2:
        ap.error("need REF_RESULTS.json and OURS_RESULTS.json (or --run-reference)")
    print(json.dumps(diff(*args.paths)))


if __name__ == "__main__":
    main()
