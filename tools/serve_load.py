#!/usr/bin/env python
"""Deterministic SLO-gated load harness for the serving daemon
(ISSUE 13 acceptance harness — ROADMAP item 3's "latency-curve claim").

Drives one in-process :class:`dragg_tpu.serve.ServeDaemon` (real engine
workers by default; ``--stub`` for protocol-only) with a seeded,
reproducible request stream at stepped request rates until the SLO
breaks, and emits the full p50/p99-vs-req/s curve plus the saturation
point as ONE JSON line (repo bench convention) in the shared
``serve_bench_v1`` envelope (dragg_tpu/serve/loadgen.py — the soak
emits the same schema).

Per level: ``n = rate × duration`` requests are submitted open-loop on a
deterministic schedule (reward prices cycle ``--rp-groups`` distinct
values — distinct rp values form distinct coalescing groups, which is
exactly what the fleet-backed pool folds into one warm C-slot solve);
completion times come from the daemon's own ``serve.done`` events
(events.jsonl tail — no poll traffic inflating the measurement).  A
level passes its SLO when p99 ≤ ``--slo-p99``, nothing failed, nothing
was lost, and rejects stayed under ``--max-reject-frac``.  The first
breaching level ends the ladder; saturation = the last passing level's
achieved req/s.

The JSON line carries ``metric=serve_sat_rps`` and a ``serve`` series
key, so ``tools/bench_trend.py`` folds it into its own hard-keyed
``serve`` series (±10 % gate) that never gates against
engine-throughput history.

Usage::

    python tools/serve_load.py --smoke          # CI stage (small fleet)
    python tools/serve_load.py --stub --rates 4,8,16,32
    python tools/serve_load.py --homes 6 --horizon-hours 2 \\
        --fleet-slots 8 --rates 2,4,8,16 --duration-s 10

Headline numbers go to ``docs/perf_notes.md`` per the repo convention.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragg_tpu import telemetry  # noqa: E402
from dragg_tpu.telemetry import traces  # noqa: E402
from dragg_tpu.config import default_config  # noqa: E402
from dragg_tpu.resilience.supervisor import assert_parent_has_no_jax  # noqa: E402
from dragg_tpu.serve import ServeDaemon  # noqa: E402
from dragg_tpu.serve import loadgen  # noqa: E402


_log = loadgen.make_log("serve_load")
_http = loadgen.http_call


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    """Nearest-rank percentile of an ascending list (None when empty)."""
    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _hist_delta(snap0: dict, snap1: dict, name: str) -> tuple[int, float]:
    """(count, sum) growth of one histogram between two snapshots."""
    h0 = (snap0.get("histograms") or {}).get(name) or {}
    h1 = (snap1.get("histograms") or {}).get(name) or {}
    return (int((h1.get("count") or 0) - (h0.get("count") or 0)),
            float((h1.get("sum") or 0.0) - (h0.get("sum") or 0.0)))


def wait_ready(base: str, budget_s: float) -> bool:
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        try:
            code, _ = _http("GET", base + "/readyz", timeout=5.0)
            if code == 200:
                return True
        except OSError:
            pass
        time.sleep(0.25)
    return False


def run_level(base: str, events_path: str, reqs: list[dict], rate: float,
              wait_budget_s: float) -> dict:
    """Submit one level's requests open-loop at ``rate`` req/s and
    measure accept→answer latency from the daemon's serve.done events."""
    # tail_bytes=0 primes at EOF — prior levels' history is discarded
    # WITHOUT reading it (an unbounded follower starts at byte 0 and
    # would re-parse every earlier level's events each ladder step).
    follower = loadgen.EventFollower(events_path, tail_bytes=0)
    follower.poll()  # prime at EOF now, BEFORE the first submission
    send_wall: dict[str, float] = {}
    rejected: list[str] = []
    errors: list[str] = []
    lock = threading.Lock()
    done_wall: dict[str, float] = {}
    failed: dict[str, str] = {}
    ids = {r["id"] for r in reqs}
    stop = threading.Event()

    def watch():
        # Completion times come from the serve.done event's wall-clock
        # `t` (the envelope's `mono` is bus-relative) — 1 ms resolution,
        # plenty against second-scale SLOs, and no /result poll traffic
        # inflating the measurement.
        while not stop.is_set():
            for rec in follower.poll():
                ev, rid = rec.get("event"), rec.get("id")
                if rid not in ids:
                    continue
                if ev == "serve.done":
                    done_wall[rid] = float(rec.get("t") or time.time())
                elif ev == "serve.failed":
                    failed[rid] = str(rec.get("reason"))
            time.sleep(0.02)

    watcher = threading.Thread(target=watch, daemon=True)
    watcher.start()
    t0 = time.monotonic()
    t0_wall = time.time()
    for i, req in enumerate(reqs):
        target = t0 + i / rate
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        wall = time.time()
        try:
            code, _body = _http("POST", base + "/solve", req)
        except OSError as e:
            with lock:
                errors.append(f"{req['id']}: {e!r}")
            continue
        if code in (200, 202):
            send_wall[req["id"]] = wall
        elif code == 429:
            rejected.append(req["id"])
        else:
            errors.append(f"{req['id']}: HTTP {code}")
    submit_span = time.monotonic() - t0
    # Wait for every accepted id to terminate.
    deadline = time.monotonic() + wait_budget_s
    while time.monotonic() < deadline:
        if all(rid in done_wall or rid in failed for rid in send_wall):
            break
        time.sleep(0.05)
    stop.set()
    watcher.join(timeout=2.0)
    for rec in follower.poll():  # final sweep
        if rec.get("id") in ids and rec.get("event") == "serve.done":
            done_wall.setdefault(rec["id"],
                                 float(rec.get("t") or time.time()))
    lost = [rid for rid in send_wall
            if rid not in done_wall and rid not in failed]
    lats = sorted(max(0.0, done_wall[rid] - send_wall[rid])
                  for rid in done_wall if rid in send_wall)
    span = (max(done_wall.values()) - t0_wall) if done_wall else submit_span
    return {
        "rate_rps": rate,
        "offered": len(reqs),
        "accepted": len(send_wall),
        "done": len(done_wall),
        "rejected": len(rejected),
        "failed": len(failed),
        "lost": len(lost),
        "errors": errors[:5],
        "achieved_rps": round(len(done_wall) / max(1e-3, span), 3),
        "p50_s": round(_percentile(lats, 0.50), 4) if lats else None,
        "p99_s": round(_percentile(lats, 0.99), 4) if lats else None,
        "max_s": round(lats[-1], 4) if lats else None,
    }


def _phase_percentiles(run_dir: str, ids: list[str]) -> dict:
    """Per-phase p50/p99 for one level from the daemon's own records
    (telemetry.traces.phase_breakdown): queue = accept -> batch dispatch
    (the coalescing window included), solve = dispatch -> terminal
    answer, stream = streamed-connection lifetime, compile = staged-
    compile seconds overlapping the solve window (spill-lane compiles).
    Decomposed server-side so an SLO breach names the guilty phase
    without trusting client clocks."""
    try:
        records = traces.read_records(run_dir)
        per_req = traces.phase_breakdown(records, ids)
    except OSError:
        return {}
    out = {}
    for phase in ("queue", "solve", "stream", "compile"):
        vals = sorted(v for v in
                      (p.get(f"{phase}_s") for p in per_req.values())
                      if v is not None)
        if vals:
            out[phase] = {"p50_s": round(_percentile(vals, 0.50), 4),
                          "p99_s": round(_percentile(vals, 0.99), 4)}
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run: small fleet worker, one low rate, "
                         "~20 requests (the acceptance gate)")
    ap.add_argument("--stub", action="store_true",
                    help="stub workers (protocol/coalescing only, no jax)")
    ap.add_argument("--homes", type=int, default=6)
    ap.add_argument("--horizon-hours", type=int, default=2)
    ap.add_argument("--fleet-slots", type=int, default=4,
                    help="community slots C per worker engine "
                         "(1 = the round-11 single-shape pool)")
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--batch-window-ms", type=float, default=25.0)
    ap.add_argument("--rates", default=None,
                    help="comma-separated req/s ladder (default: "
                         "2,4,8,16,32,64; smoke: one low rate)")
    ap.add_argument("--duration-s", type=float, default=5.0,
                    help="submission window per level")
    ap.add_argument("--rp-groups", type=int, default=4,
                    help="distinct reward-price values cycling through "
                         "the stream (distinct rp = distinct coalescing "
                         "groups)")
    ap.add_argument("--steps", type=int, default=1,
                    help="multi-chunk request length (streaming path)")
    ap.add_argument("--t-window", type=int, default=1,
                    help="distinct timesteps cycling through the stream "
                         "(requests coalesce only within one timestep)")
    ap.add_argument("--slo-p99", type=float, default=None,
                    help="p99 latency SLO in seconds (default: 5 stub / "
                         "30 engine)")
    ap.add_argument("--max-reject-frac", type=float, default=0.01)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ready-budget-s", type=float, default=900.0,
                    help="warmup budget before the first level (cold "
                         "engine compile)")
    ap.add_argument("--root", default=None,
                    help="working directory (default: a fresh "
                         "/tmp/dragg_serve_load_<pid>)")
    args = ap.parse_args(argv)

    assert_parent_has_no_jax()
    slo = args.slo_p99 if args.slo_p99 is not None \
        else (5.0 if args.stub else 30.0)
    if args.rates:
        rates = [float(r) for r in args.rates.split(",") if r.strip()]
    elif args.smoke:
        rates = [4.0]
    else:
        rates = [2.0, 4.0, 8.0, 16.0, 32.0, 64.0]
    duration = 5.0 if args.smoke and args.duration_s == 5.0 \
        else args.duration_s
    root = args.root or f"/tmp/dragg_serve_load_{os.getpid()}"
    os.makedirs(root, exist_ok=True)

    cfg = default_config()
    cfg["community"]["total_number_homes"] = args.homes
    cfg["community"]["homes_pv"] = max(1, args.homes // 6)
    cfg["community"]["homes_battery"] = max(1, args.homes // 6)
    cfg["community"]["homes_pv_battery"] = max(1, args.homes // 6)
    cfg["home"]["hems"]["prediction_horizon"] = args.horizon_hours
    cfg["tpu"]["compile_cache_dir"] = os.path.join(root, "compile_cache")
    # Trace plane on (ISSUE 20): request -> batch -> chunk spans land in
    # the daemon's stream, and the per-level phase decomposition below
    # names the guilty phase when an SLO breaches.
    cfg.setdefault("telemetry", {})["trace"] = True
    cfg["serve"].update({
        "fleet_slots": max(1, args.fleet_slots),
        "workers": max(1, args.workers),
        "batch_window_ms": float(args.batch_window_ms),
        "poll_s": 0.01,
        "queue_max": 4096,
        "request_deadline_s": max(600.0, 4 * slo),
        "batch_deadline_s": 300.0,
        "worker_stall_s": 300.0,
    })

    rp_values = tuple(round(0.01 * g, 4) for g in range(args.rp_groups))
    _log(f"root={root} homes={args.homes} h={args.horizon_hours} "
         f"C={args.fleet_slots} workers={args.workers} stub={args.stub} "
         f"rates={rates} rp_groups={args.rp_groups} slo_p99={slo}s")

    daemon = ServeDaemon(copy.deepcopy(cfg), root, platform="cpu",
                         port=0, stub=args.stub, log=_log)
    daemon.start()
    base = f"http://127.0.0.1:{daemon.port}"
    levels = []
    all_ids: list[str] = []
    violations: list[str] = []
    warmup_s = None
    try:
        t_warm = time.monotonic()
        if not wait_ready(base, args.ready_budget_s):
            violations.append("worker never became ready inside the "
                              "warmup budget")
        warmup_s = round(time.monotonic() - t_warm, 2)
        events_path = telemetry.events_path() or os.path.join(
            root, telemetry.EVENTS_FILE)
        for li, rate in enumerate(rates):
            if violations:
                break
            n = max(1, int(round(rate * duration)))
            if args.smoke:
                n = max(n, 20)
            reqs = loadgen.build_requests(
                n, args.homes, prefix=f"l{li}r", t_window=args.t_window,
                rp_values=rp_values, steps=args.steps,
                seed=args.seed + li)
            all_ids += [r["id"] for r in reqs]
            snap0 = telemetry.snapshot()
            level = run_level(base, events_path, reqs, rate,
                              wait_budget_s=max(60.0, 6 * slo))
            snap1 = telemetry.snapshot()
            occ_n, occ_sum = _hist_delta(snap0, snap1,
                                         "serve.batch_occupancy")
            co_n, co_sum = _hist_delta(snap0, snap1,
                                       "serve.coalesced_requests")
            level["batches"] = occ_n
            level["occupancy_mean"] = (round(occ_sum / occ_n, 4)
                                       if occ_n else None)
            level["coalesced_mean"] = (round(co_sum / co_n, 4)
                                       if co_n else None)
            level["phases"] = _phase_percentiles(
                root, [r["id"] for r in reqs])
            breach = []
            if level["p99_s"] is None or level["p99_s"] > slo:
                breach.append(f"p99 {level['p99_s']}s > SLO {slo}s")
            if level["failed"] or level["lost"]:
                breach.append(f"{level['failed']} failed, "
                              f"{level['lost']} lost")
            if level["rejected"] > args.max_reject_frac * level["offered"]:
                breach.append(f"{level['rejected']}/{level['offered']} "
                              f"rejected")
            level["slo_ok"] = not breach
            level["breach"] = breach
            levels.append(level)
            _log(f"level {rate} req/s: done={level['done']} "
                 f"p50={level['p50_s']}s p99={level['p99_s']}s "
                 f"occ={level['occupancy_mean']} "
                 f"coalesced={level['coalesced_mean']} "
                 f"{'OK' if level['slo_ok'] else 'BREACH ' + '; '.join(breach)}")
            if breach:
                break
    finally:
        daemon.stop(drain=True)
    violations += loadgen.journal_anomalies(
        os.path.join(root, "journal.jsonl"), all_ids)

    passing = [lv for lv in levels if lv["slo_ok"]]
    sat = passing[-1]["achieved_rps"] if passing else 0.0
    head = passing[-1] if passing else (levels[-1] if levels else {})
    result = loadgen.result_envelope(
        "serve_load",
        ok=not violations and bool(passing),
        homes=args.homes,
        requests=len(all_ids),
        metrics={
            "saturation_rps": sat,
            "p50_s": head.get("p50_s"),
            "p99_s": head.get("p99_s"),
            "occupancy_mean": head.get("occupancy_mean"),
            "coalesced_mean": head.get("coalesced_mean"),
            "warmup_s": warmup_s,
            "slo_p99_s": slo,
            "phases": head.get("phases"),
        },
        violations=violations,
        # bench_trend series fields: `serve` is the hard key that keeps
        # these rows off the engine-throughput history.
        metric="serve_sat_rps",
        value=sat,
        platform="stub" if args.stub else "cpu",
        solver=str(cfg["home"]["hems"]["solver"]),
        serve=f"pool-C{args.fleet_slots}x{args.workers}w"
              f"{'-stub' if args.stub else ''}",
        fleet_slots=args.fleet_slots,
        workers=args.workers,
        horizon_hours=args.horizon_hours,
        steps=args.steps,
        rp_groups=args.rp_groups,
        batch_window_ms=args.batch_window_ms,
        seed=args.seed,
        smoke=bool(args.smoke),
        stub=bool(args.stub),
        levels=levels,
    )
    print(json.dumps(result, default=str))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
