"""Integration soak: rl_agg + DDPG + IPM + checkpoint, killed mid-run and
resumed — the round-2 feature set running together for 3 simulated days.

Usage: python tools/soak.py [outputs-dir]
Asserts: resume engages, full-length finite outputs, live RL actions, and a
solve rate above the genuine-infeasibility floor for H=12 January weather
(~85%; unsolved steps route through the fallback controller by design).
"""
import sys, os, glob, json, shutil
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))
import numpy as np
from dragg_tpu.aggregator import Aggregator
from dragg_tpu.config import default_config

OUT = sys.argv[1] if len(sys.argv) > 1 else "/tmp/dragg_soak_out"
shutil.rmtree(OUT, ignore_errors=True)

def make_cfg():
    cfg = default_config()
    n = 128
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = int(0.4*n)
    cfg["community"]["homes_battery"] = int(0.1*n)
    cfg["community"]["homes_pv_battery"] = int(0.1*n)
    cfg["simulation"]["end_datetime"] = "2015-01-04 00"   # 3 days
    cfg["simulation"]["run_rbo_mpc"] = False
    cfg["simulation"]["run_rl_agg"] = True
    cfg["simulation"]["checkpoint_interval"] = "daily"
    cfg["simulation"]["resume"] = True
    cfg["home"]["hems"]["prediction_horizon"] = 12
    cfg["home"]["hems"]["solver"] = "ipm"
    cfg["rl"]["parameters"]["agent"] = "ddpg"
    return cfg

# Phase 1: run and stop after the first checkpointed chunk (simulated kill).
agg = Aggregator(make_cfg(), data_dir=None, outputs_dir=OUT)
agg.stop_after_chunks = 1
agg.run()
print("phase1 stopped at t =", agg.timestep, flush=True)
assert agg.timestep == 24

# Phase 2: fresh process-equivalent resume to completion.
agg2 = Aggregator(make_cfg(), data_dir=None, outputs_dir=OUT)
agg2.run()
print("phase2 resumed_from:", agg2.resumed_from, flush=True)
assert agg2.resumed_from is not None, "resume must pick up the checkpoint"

res = glob.glob(os.path.join(OUT, "**", "rl_agg", "results.json"), recursive=True)
d = json.load(open(res[0]))
s = d["Summary"]
assert len(s["p_grid_aggregate"]) == 72, len(s["p_grid_aggregate"])
assert all(np.isfinite(s["p_grid_aggregate"]))
assert len(s["RP"]) == 72 and any(abs(r) > 0 for r in s["RP"]), "RL actions must move"
homes = [k for k in d if k != "Summary"]
assert len(homes) == 128
cs = np.asarray([d[h]["correct_solve"] for h in homes])
print(f"solve rate over 3 days: {cs.mean():.4f}", flush=True)
assert cs.mean() > 0.8  # infeasibility floor, see docstring
agent_files = glob.glob(os.path.join(OUT, "**", "utility_agent-results.json"), recursive=True)
a = json.load(open(agent_files[0]))
assert len(a["action"]) == 72
assert a["parameters"]["agent"] == "ddpg"
print("SOAK OK", flush=True)
