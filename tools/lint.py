#!/usr/bin/env python
"""Dependency-free lint, runnable in the hermetic build image.

Mirrors the enforcement the reference gets from its pre-commit suite
(reference .pre-commit-config.yaml: flake8, autoflake, check-ast) with
what the stdlib can check:

* every Python file parses (`check-ast` parity);
* no unused imports (autoflake parity; `# noqa` opt-out honored);
* no tabs in indentation, no trailing whitespace, newline at EOF;
* device-call discipline in `tools/`, `bench.py`, `dragg_tpu/serve/`,
  and `dragg_tpu/aggregator.py` (round 6; serve added by ISSUE 7, the
  aggregator's entry paths by ISSUE 8 — its one sanctioned device
  enumeration is ``resilience.devices.device_count``): no bare
  ``jax.devices()``/``jax.default_backend()``/``jax.local_devices()`` —
  a wedged tunnel hangs backend init, so device calls in entry points
  must run inside a supervised/probed child (dragg_tpu/resilience);
  lines that legitimately run in a supervised child carry a
  ``# device-call-ok: <why>`` marker — and no un-deadlined
  ``subprocess.run/check_output/check_call/call`` (a child that can
  hang forever defeats the supervision; pass ``timeout=``);
* accept-loop discipline in `dragg_tpu/serve/` plus the serving tools
  `tools/serve_load.py` / `tools/serve_soak.py` (ISSUE 7; scope extended
  by ISSUE 13 — the load harness runs an in-process daemon, so the same
  deadline discipline applies): the serving daemon must stay
  interruptible — ``serve_forever()`` needs an explicit
  ``poll_interval=`` (the default blocks shutdown on a quiet socket
  longer than the drain budget expects) and raw ``socket.accept()``
  loops are disallowed unless the line carries
  ``# accept-timeout-ok: <why>`` (a timeout is set on the socket);
* telemetry-name discipline in `dragg_tpu/`, `tools/`, and `bench.py`
  (round 7): every ``telemetry.emit/span/observe/inc/set_gauge`` call
  must name an entry of the central registry
  (dragg_tpu/telemetry/registry.py) as a string LITERAL — free strings
  fragment the unified stream the registry exists to keep analyzable.
  Computed names carry a ``# telemetry-name-ok: <why>`` marker (e.g.
  the taxonomy-kind events, whose kinds are each registered literally);
* home-type co-registration (ISSUE 10): every ``homes.HOME_TYPES`` entry
  must carry an ``ops/qp.TYPE_SPECS`` block spec, appear (quoted) in a
  parity-bearing test file under ``tests/``, and be documented in
  ``docs/config.md`` — a new scenario home type cannot ship half-wired
  (solving in a bucket nobody parity-checked or documented);
* precision discipline in the dense solver files (ISSUE 11):
  ``dragg_tpu/ops/reluqp.py`` and ``dragg_tpu/ops/admm.py`` may not call
  ``jnp.einsum``/``jnp.dot``/``jnp.matmul``/``jnp.tensordot``/
  ``lax.dot_general`` directly — every dense contraction routes through
  ``dragg_tpu/ops/precision.py`` (``mxu_einsum``), which owns the
  f32/bf16x3 cast discipline (bf16 compute with f32 accumulation; f32
  residual path — the rounds-2/9 divergence mode was exactly a
  hand-rolled dtype).  Non-matmul einsums (e.g. a diagonal trace) carry
  a ``# precision-ok: <why>`` marker;
* KKT-inverse discipline in the same scope (round 10): no direct
  ``np.linalg.inv``/``jnp.linalg.inv`` outside ``dragg_tpu/ops/`` — the
  dense rho-bank operators of the reluqp family must be built through
  the equilibrated, condition-checked Cholesky route
  (``ops.reluqp.equilibrated_spd_inverse``); an unequilibrated generic
  LU inverse of a KKT-sized operand silently amplifies float32
  conditioning error into the hot loop.  Sites whose operand is
  provably not KKT-sized carry a ``# kkt-inv-ok: <why>`` marker.

The full flake8/autoflake hooks run via .pre-commit-config.yaml and CI
where those tools are installable; this script is the offline floor and
is itself wired into CI so the two can't drift silently.
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SKIP_DIRS = {".git", "__pycache__", ".cache", "outputs", "native/_build",
             ".pytest_cache", ".claude"}


def iter_py_files():
    for base, dirs, files in os.walk(ROOT):
        dirs[:] = [d for d in dirs
                   if d not in SKIP_DIRS and not d.startswith(".")]
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(base, f)


class ImportUsage(ast.NodeVisitor):
    def __init__(self):
        self.imported: dict[str, int] = {}   # bound name -> lineno
        self.used: set[str] = set()

    def visit_Import(self, node):
        for a in node.names:
            name = a.asname or a.name.split(".")[0]
            self.imported[name] = node.lineno

    def visit_ImportFrom(self, node):
        for a in node.names:
            if a.name == "*":
                continue
            self.imported[a.asname or a.name] = node.lineno

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


# Entry-point files where every device touch must be supervised or
# probed: tools/ CLIs, the bench harness, the serving daemon, and (round
# 12) the aggregator's engine-build / simulation entry paths — the
# aggregator runs inside supervised children on every shipped path, and
# its one legitimate device enumeration routes through the sanctioned
# helper (dragg_tpu.resilience.devices.device_count) so a future bare
# call can't sneak back in (CLAUDE.md gotcha — never bare
# jax.devices()).
_DEVICE_CALLS = {"devices", "local_devices", "default_backend"}
_SUBPROCESS_FNS = {"run", "check_output", "check_call", "call"}
_DEVICE_MARKER = "# device-call-ok:"


def _is_entry_point(path: str) -> bool:
    rel = os.path.relpath(path, ROOT)
    return (rel == "bench.py" or rel.startswith("tools" + os.sep)
            or rel == os.path.join("dragg_tpu", "aggregator.py")
            or _is_serve_scope(path))


# Accept-loop discipline (ISSUE 7; see the module docstring bullet).
_ACCEPT_MARKER = "# accept-timeout-ok:"


def _is_serve_scope(path: str) -> bool:
    rel = os.path.relpath(path, ROOT)
    return (rel.startswith(os.path.join("dragg_tpu", "serve") + os.sep)
            or rel in (os.path.join("tools", "serve_load.py"),
                       os.path.join("tools", "serve_soak.py")))


def check_accept_loop_discipline(tree, lines: list[str], rel: str) -> list[str]:
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if fn.attr == "serve_forever":
            if not any(kw.arg == "poll_interval" for kw in node.keywords) \
                    and _ACCEPT_MARKER not in line:
                problems.append(
                    f"{rel}:{node.lineno}: serve_forever() without "
                    f"poll_interval= in the serving daemon — a quiet "
                    f"socket must not outlive the drain budget; pass "
                    f"poll_interval= or mark the line "
                    f"'{_ACCEPT_MARKER} <why>'")
        elif fn.attr == "accept" and not node.args and not node.keywords:
            if _ACCEPT_MARKER not in line:
                problems.append(
                    f"{rel}:{node.lineno}: raw socket accept() in the "
                    f"serving daemon — an un-timeouted accept loop cannot "
                    f"drain; set a socket timeout and mark the line "
                    f"'{_ACCEPT_MARKER} <why>'")
    return problems


# Telemetry-name discipline (round 7): emits in framework + entry-point
# code must reference the central registry so the unified event stream
# stays analyzable (one schema, documented in docs/telemetry.md).
_TELEMETRY_FNS = {"emit": "EVENTS", "span": "METRICS", "observe": "METRICS",
                  "inc": "METRICS", "set_gauge": "METRICS"}
_TELEMETRY_MARKER = "# telemetry-name-ok:"
_REGISTRY_PATH = os.path.join(ROOT, "dragg_tpu", "telemetry", "registry.py")
_registry_names_cache: dict | None = None


def _telemetry_registry() -> dict | None:
    """{'EVENTS': set, 'METRICS': set} parsed from the registry module's
    literal tables via ast (no import — lint stays dependency-free)."""
    global _registry_names_cache
    if _registry_names_cache is not None:
        return _registry_names_cache
    try:
        with open(_REGISTRY_PATH, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    names: dict = {"EVENTS": set(), "METRICS": set()}
    for node in ast.walk(tree):
        if not (isinstance(node, (ast.Assign, ast.AnnAssign))):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id in names \
                    and isinstance(node.value, ast.Dict):
                names[t.id] |= {k.value for k in node.value.keys
                                if isinstance(k, ast.Constant)
                                and isinstance(k.value, str)}
    _registry_names_cache = names
    return names


def _is_telemetry_scope(path: str) -> bool:
    rel = os.path.relpath(path, ROOT)
    return (rel == "bench.py" or rel.startswith("tools" + os.sep)
            or rel.startswith("dragg_tpu" + os.sep))


def check_telemetry_names(tree, lines: list[str], rel: str) -> list[str]:
    reg = _telemetry_registry()
    if reg is None:
        return []
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
                and fn.value.id == "telemetry" and fn.attr in _TELEMETRY_FNS):
            continue
        table = _TELEMETRY_FNS[fn.attr]
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        arg = node.args[0] if node.args else None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in reg[table]:
                problems.append(
                    f"{rel}:{node.lineno}: telemetry.{fn.attr}"
                    f"({arg.value!r}) names nothing in registry.{table} — "
                    f"register it in dragg_tpu/telemetry/registry.py (and "
                    f"docs/telemetry.md)")
        elif _TELEMETRY_MARKER not in line:
            problems.append(
                f"{rel}:{node.lineno}: telemetry.{fn.attr}() with a "
                f"computed name — pass a registry literal, or mark the "
                f"line '{_TELEMETRY_MARKER} <why>' if every runtime value "
                f"is registered")
    return problems


# Precision discipline (ISSUE 11; see the module docstring bullet).
_PRECISION_MARKER = "# precision-ok:"
_PRECISION_FILES = (os.path.join("dragg_tpu", "ops", "reluqp.py"),
                    os.path.join("dragg_tpu", "ops", "admm.py"))
_DENSE_CONTRACTIONS = {"einsum", "dot", "matmul", "tensordot",
                       "dot_general"}


def _is_precision_scope(path: str) -> bool:
    return os.path.relpath(path, ROOT) in _PRECISION_FILES


def check_precision_discipline(tree, lines: list[str], rel: str) -> list[str]:
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        # Matches jnp.einsum / np.dot / lax.dot_general / lax.linalg...
        # — any attribute call named like a dense contraction.
        if not (isinstance(fn, ast.Attribute)
                and fn.attr in _DENSE_CONTRACTIONS):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if _PRECISION_MARKER not in line:
            problems.append(
                f"{rel}:{node.lineno}: bare dense contraction "
                f"({fn.attr}) in a precision-disciplined solver file — "
                f"route it through ops/precision.mxu_einsum (which owns "
                f"the f32/bf16x3 cast policy), or mark the line "
                f"'{_PRECISION_MARKER} <why>' if it is not a matmul")
    return problems


# KKT-inverse discipline (round 10; see the module docstring bullet).
_INV_MARKER = "# kkt-inv-ok:"


def _is_kkt_inv_scope(path: str) -> bool:
    rel = os.path.relpath(path, ROOT)
    return (_is_telemetry_scope(path)
            and not rel.startswith(os.path.join("dragg_tpu", "ops") + os.sep))


def check_kkt_inverse_discipline(tree, lines: list[str], rel: str) -> list[str]:
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        # Matches any `<base>.linalg.inv(...)` — np, jnp, scipy aliases.
        if not (isinstance(fn, ast.Attribute) and fn.attr == "inv"
                and isinstance(fn.value, ast.Attribute)
                and fn.value.attr == "linalg"):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if _INV_MARKER not in line:
            problems.append(
                f"{rel}:{node.lineno}: direct linalg.inv outside ops/ — "
                f"KKT-sized inverses must go through the equilibrated, "
                f"condition-checked helper "
                f"(dragg_tpu.ops.reluqp.equilibrated_spd_inverse); mark "
                f"the line '{_INV_MARKER} <why>' if the operand is "
                f"provably not KKT-sized")
    return problems


def check_device_discipline(tree, lines: list[str], rel: str) -> list[str]:
    problems = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if fn.value.id == "jax" and fn.attr in _DEVICE_CALLS:
            if _DEVICE_MARKER not in line:
                problems.append(
                    f"{rel}:{node.lineno}: bare jax.{fn.attr}() in an entry "
                    f"point — probe/supervise it (dragg_tpu/resilience), or "
                    f"mark the line '{_DEVICE_MARKER} <why>' if it runs in a "
                    f"supervised child")
        if fn.value.id == "subprocess" and fn.attr in _SUBPROCESS_FNS:
            if not any(kw.arg == "timeout" for kw in node.keywords):
                problems.append(
                    f"{rel}:{node.lineno}: subprocess.{fn.attr}() without "
                    f"timeout= in an entry point — an un-deadlined child can "
                    f"hang forever (use resilience.supervisor or pass a "
                    f"timeout)")
    return problems


# Home-type co-registration (ISSUE 10; see the module docstring bullet).
def _literal_names(path: str, var: str) -> list[str] | None:
    """String members of a top-level tuple/dict literal assigned to
    ``var`` in ``path`` (tuple → elements, dict → keys); None on parse
    failure so the rule degrades quietly rather than crashing lint."""
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        for t in targets:
            if not (isinstance(t, ast.Name) and t.id == var):
                continue
            v = node.value
            if isinstance(v, ast.Tuple):
                return [e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
            if isinstance(v, ast.Dict):
                return [k.value for k in v.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)]
    return None


def check_home_type_registry() -> list[str]:
    home_types = _literal_names(
        os.path.join(ROOT, "dragg_tpu", "homes.py"), "HOME_TYPES")
    specs = _literal_names(
        os.path.join(ROOT, "dragg_tpu", "ops", "qp.py"), "TYPE_SPECS")
    if home_types is None or specs is None:
        return []  # parse problems are reported per-file already
    try:
        with open(os.path.join(ROOT, "docs", "config.md"),
                  encoding="utf-8") as f:
            doc = f.read()
    except OSError:
        doc = ""
    # Parity evidence: the quoted type name appears in a test file whose
    # source mentions parity (the test_qp_parity / test_bucketed /
    # test_scenarios convention).
    parity_src = ""
    tests_dir = os.path.join(ROOT, "tests")
    try:
        test_files = sorted(os.listdir(tests_dir))
    except OSError:
        test_files = []
    for fn in test_files:
        if not fn.endswith(".py"):
            continue
        try:
            with open(os.path.join(tests_dir, fn), encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        if "parity" in src.lower():
            parity_src += src
    problems = []
    for t in home_types:
        if t not in specs:
            problems.append(
                f"dragg_tpu/homes.py: HOME_TYPES entry {t!r} has no "
                f"ops/qp.TYPE_SPECS block spec — the bucketed engine "
                f"cannot shape-specialize it")
        if f"`{t}`" not in doc and f"homes_{t}" not in doc:
            problems.append(
                f"docs/config.md: HOME_TYPES entry {t!r} undocumented — "
                f"mention `{t}` (or its homes_{t} count key)")
        if f'"{t}"' not in parity_src and f"'{t}'" not in parity_src:
            problems.append(
                f"tests/: HOME_TYPES entry {t!r} appears in no parity-"
                f"bearing test file — add objective-parity coverage "
                f"(tests/test_qp_parity.py pattern)")
    return problems


def check_file(path: str) -> list[str]:
    problems = []
    rel = os.path.relpath(path, ROOT)
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [f"{rel}:{e.lineno}: syntax error: {e.msg}"]

    lines = src.splitlines()
    for i, line in enumerate(lines, 1):
        if line != line.rstrip():
            problems.append(f"{rel}:{i}: trailing whitespace")
        if line[:len(line) - len(line.lstrip())].count("\t"):
            problems.append(f"{rel}:{i}: tab in indentation")
    if src and not src.endswith("\n"):
        problems.append(f"{rel}:{len(lines)}: no newline at end of file")

    uses = ImportUsage()
    uses.visit(tree)
    # Names referenced in __all__ or docstring-level re-export idioms count.
    for name, lineno in sorted(uses.imported.items(), key=lambda kv: kv[1]):
        if name in uses.used or name == "annotations":
            continue
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        if "noqa" in line:
            continue
        if f'"{name}"' in src or f"'{name}'" in src:  # __all__ / getattr use
            continue
        problems.append(f"{rel}:{lineno}: unused import '{name}'")
    if _is_entry_point(path):
        problems.extend(check_device_discipline(tree, lines, rel))
    if _is_serve_scope(path):
        problems.extend(check_accept_loop_discipline(tree, lines, rel))
    if _is_telemetry_scope(path):
        problems.extend(check_telemetry_names(tree, lines, rel))
    if _is_kkt_inv_scope(path):
        problems.extend(check_kkt_inverse_discipline(tree, lines, rel))
    if _is_precision_scope(path):
        problems.extend(check_precision_discipline(tree, lines, rel))
    return problems


def main() -> int:
    all_problems = []
    n = 0
    for path in sorted(iter_py_files()):
        n += 1
        all_problems.extend(check_file(path))
    all_problems.extend(check_home_type_registry())
    for p in all_problems:
        print(p)
    print(f"lint: {n} files, {len(all_problems)} problem(s)",
          file=sys.stderr)
    return 1 if all_problems else 0


if __name__ == "__main__":
    sys.exit(main())
