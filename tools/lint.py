#!/usr/bin/env python
"""Thin shim over dragglint (``python -m dragg_tpu.analysis``) — ISSUE 14.

This file used to BE the lint: 473 lines of seven ad-hoc checks with
one ast re-walk each, inconsistent suppression markers, and entry-point
whitelists.  Those checks are now rules DT001-DT011 of the dragglint
analyzer (plus the JAX-specific DT012-DT015 and the suppression
validator DT016 the old lint never had),
with a single-pass visitor dispatch, one suppression syntax
(``# dragg: disable=DT0xx, reason``), per-rule scope globs covering the
WHOLE package, and a committed baseline (``.dragglint-baseline.json``).
Rule catalog and workflow: docs/analysis.md.

The shim keeps every historical entry point working unchanged:

* ``python tools/lint.py`` in CI, run_ci_locally.sh, and muscle memory;
* the legacy markers (``# device-call-ok:`` / ``# accept-timeout-ok:``
  / ``# telemetry-name-ok:`` / ``# precision-ok:`` / ``# kkt-inv-ok:``
  and ``# noqa`` on imports) are grandfathered by the analyzer itself —
  still honored, warned once per run (except noqa, which keeps its
  permanent flake8 meaning) — so downstream docs/snippets don't break.

Arguments pass through: ``python tools/lint.py --changed`` etc.
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from dragg_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
