#!/usr/bin/env python
"""Deterministic chaos soak for the MPC serving daemon (ISSUE 7
acceptance harness).

Replays one deterministic request trace against a fresh in-process
:class:`dragg_tpu.serve.ServeDaemon` per scenario, driving
``$DRAGG_FAULT_INJECT`` through every failure kind of the resilience
taxonomy plus an external kill -9 mid-batch and a full daemon restart,
then asserts the serving invariants from the journal and the telemetry
stream:

* **no request lost** — every trace id reaches exactly one terminal
  journal state (all ``done`` here: retries are sized to outlast every
  injected fault);
* **no request answered twice** — at most one ``done`` record per id in
  the raw journal (the fsync'd journal is the delivery of record);
* **degradation provenance** — every response journaled after a
  platform transition carries the ``degraded`` record with the
  precipitating failure kind;
* **warm restart beats cold start** — after a CHILD_CRASH the
  replacement worker's staged compile must NOT be a cache miss
  (compile_obs hit/miss telemetry) and its warmup must undercut the
  soak's one genuinely cold warmup.

Scenario → taxonomy coverage: child_crash/kill9/midflight_degrade →
CHILD_CRASH, vmem_oom → VMEM_OOM, compile_hang → COMPILE_HANG,
deadline → DEADLINE, tunnel_down → TUNNEL_DOWN, wedge → WEDGED.

Usage::

    python tools/serve_soak.py --smoke            # CPU-mesh CI stage
    python tools/serve_soak.py --homes 32 --trace-len 64
    python tools/serve_soak.py --scenario kill9   # one scenario

Prints a human transcript on stderr and exactly one JSON line on stdout
(repo bench convention); exit 0 only when every invariant held.  The
measured headline numbers (cold-request→first-action latency, sustained
requests/s, restart-recovery seconds) go to ``docs/perf_notes.md`` per
the repo convention.
"""

from __future__ import annotations

import argparse
import copy
import functools
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragg_tpu import telemetry  # noqa: E402
from dragg_tpu.config import default_config  # noqa: E402
from dragg_tpu.resilience import faults  # noqa: E402
from dragg_tpu.resilience.supervisor import assert_parent_has_no_jax  # noqa: E402
from dragg_tpu.serve import ServeDaemon  # noqa: E402
from dragg_tpu.serve import journal as journal_mod  # noqa: E402
from dragg_tpu.serve import loadgen  # noqa: E402


_log = loadgen.make_log("serve_soak")
_http = functools.partial(loadgen.http_call, timeout=10.0)


def make_trace(n_requests: int, n_homes: int, path: str) -> list[dict]:
    """The deterministic replayed trace — the load harness's request
    builder with its defaults (ids r00.., timesteps cycling a small
    window, homes cycling the community, a few state overrides): soak
    and load replay the SAME distribution family by construction
    (loadgen.build_requests; schema test pins the sharing)."""
    trace = loadgen.build_requests(n_requests, n_homes)
    with open(path, "w") as f:
        for req in trace:
            f.write(json.dumps(req) + "\n")
    return trace


def load_trace(path: str) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ------------------------------------------------------------- journal QA
def journal_records(path: str) -> list[dict]:
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                recs.append(json.loads(line))
            except ValueError:
                pass
    return recs


def check_invariants(trace: list[dict], journal_path: str,
                     expect_degraded: str | None,
                     degraded_after_transition_only: bool) -> list[str]:
    """The soak invariants, read from the journal of record.  Returns a
    list of violation strings (empty = clean)."""
    violations = []
    recs = journal_records(journal_path)
    trace_ids = [r["id"] for r in trace]
    done_counts = {rid: 0 for rid in trace_ids}
    failed = []
    transition_seen = False
    for rec in recs:
        if rec.get("state") == journal_mod.DONE:
            rid = rec.get("id")
            if rid in done_counts:
                done_counts[rid] += 1
                deg = (rec.get("response") or {}).get("degraded")
                if transition_seen or not degraded_after_transition_only:
                    if expect_degraded and not deg:
                        violations.append(
                            f"{rid}: answered after degradation without "
                            f"provenance")
                    elif expect_degraded and deg.get("failure") != expect_degraded:
                        violations.append(
                            f"{rid}: degraded provenance names "
                            f"{deg.get('failure')!r}, expected "
                            f"{expect_degraded!r}")
                elif deg:
                    violations.append(
                        f"{rid}: carries degradation provenance before any "
                        f"transition")
        elif rec.get("state") == journal_mod.FAILED \
                and rec.get("id") in done_counts:
            failed.append(rec)
        elif rec.get("state") == journal_mod.TRANSITION:
            transition_seen = True
    for rid, n in done_counts.items():
        if n == 0:
            violations.append(f"{rid}: LOST — no terminal done record")
        elif n > 1:
            violations.append(f"{rid}: answered {n} times")
    for rec in failed:
        violations.append(f"{rec['id']}: failed terminally "
                          f"({rec.get('reason')})")
    if expect_degraded and not transition_seen:
        violations.append(f"expected a {expect_degraded} degradation "
                          f"transition; journal has none")
    return violations


def events_summary(serve_dir: str) -> dict:
    """Fold the scenario's telemetry stream: failure kinds observed,
    compile verdicts + worker lifecycle (the warm-restart evidence)."""
    path = os.path.join(serve_dir, telemetry.EVENTS_FILE)
    failures = []
    compiles = []
    ready = []
    exits = []
    for rec in telemetry.tail_events(path, limit=100000,
                                     tail_bytes=1 << 26):
        ev = rec.get("event", "")
        if ev.startswith("failure.") and rec.get("source") == "serve":
            failures.append(ev[len("failure."):])
        elif ev == "compile.done":
            compiles.append({"cache": rec.get("cache"),
                             "total_s": rec.get("total_s"),
                             "pid": rec.get("pid"),
                             "t": rec.get("t")})
        elif ev == "serve.worker.ready":
            ready.append({"gen": rec.get("gen"), "mono": rec.get("mono"),
                          "warmup_s": rec.get("warmup_s"),
                          "cache": rec.get("cache")})
        elif ev == "serve.worker.exit":
            exits.append({"gen": rec.get("gen"), "mono": rec.get("mono"),
                          "failure": rec.get("failure")})
    return {"failures": failures, "compiles": compiles, "ready": ready,
            "exits": exits}


# --------------------------------------------------------------- scenario
def run_scenario(name: str, *, root: str, base_cfg: dict, trace: list[dict],
                 platform: str = "cpu", fault_spec: str = "",
                 serve_overrides: dict | None = None,
                 expect_failure: str | None = None,
                 expect_degraded: str | None = None,
                 degraded_after_transition_only: bool = False,
                 kill9_on_inflight: bool = False,
                 restart_daemon: bool = False,
                 timeout_s: float = 420.0) -> dict:
    sdir = os.path.join(root, name)
    os.makedirs(sdir, exist_ok=True)
    state_dir = os.path.join(sdir, "fault_state")
    os.makedirs(state_dir, exist_ok=True)
    os.environ[faults.ENV] = fault_spec
    os.environ["DRAGG_FAULT_STATE"] = state_dir
    faults.reset_plan()
    cfg = copy.deepcopy(base_cfg)
    cfg["serve"].update(serve_overrides or {})
    _log(f"--- scenario {name}: platform={platform} "
         f"faults={fault_spec or '(none)'}")
    t0 = time.monotonic()
    daemon = ServeDaemon(cfg, sdir, platform=platform, port=0, log=_log)
    daemon.start()
    base = f"http://127.0.0.1:{daemon.port}"
    report: dict = {"name": name, "violations": []}
    try:
        t_submit = time.monotonic()
        for req in trace:
            code, body = _http("POST", base + "/solve", req)
            if code not in (200, 202):
                report["violations"].append(
                    f"{req['id']}: POST /solve answered {code}: {body}")
        if kill9_on_inflight:
            # The injected hang freezes the worker mid-batch; the
            # external SIGKILL is the literal kill -9 of the acceptance
            # criterion (abrupt device-loss analog, no Python involved).
            deadline = time.monotonic() + timeout_s
            pid = None
            while time.monotonic() < deadline:
                with daemon.lock:
                    if daemon.in_flight:
                        slot = daemon.slots[
                            next(iter(daemon.in_flight))]
                        pid = slot.proc.pid if slot.proc else None
                        break
                time.sleep(0.05)
            if pid is None:
                report["violations"].append("kill9: no batch ever went "
                                            "in-flight")
            else:
                time.sleep(0.5)  # let the worker reach the fault site
                _log(f"kill -9 worker pid={pid} mid-batch")
                os.kill(pid, signal.SIGKILL)
        if restart_daemon:
            # Abrupt stop with work outstanding: no drain, workers killed,
            # journal left as-is.  The NEW daemon must replay and finish.
            time.sleep(0.2)
            daemon.stop(drain=False)
            _log("daemon stopped abruptly with outstanding work; "
                 "restarting on the same journal")
            daemon = ServeDaemon(cfg, sdir, platform=platform, port=0,
                                 log=_log)
            daemon.start()
            base = f"http://127.0.0.1:{daemon.port}"
        # Wait for every trace id to reach a terminal state.
        deadline = time.monotonic() + timeout_s
        last_done_mono = None
        outstanding = {r["id"] for r in trace}
        while outstanding and time.monotonic() < deadline:
            for rid in list(outstanding):
                code, body = _http("GET", f"{base}/result?id={rid}")
                if code == 200 and body.get("status") in ("done", "failed"):
                    outstanding.discard(rid)
                    last_done_mono = time.monotonic()
            time.sleep(0.1)
        if outstanding:
            report["violations"].append(
                f"timed out with {len(outstanding)} requests unterminated: "
                f"{sorted(outstanding)[:5]}")
        if last_done_mono is not None:
            span = max(1e-6, last_done_mono - t_submit)
            report["sustained_rps"] = round(len(trace) / span, 3)
        code, body = _http("GET", base + "/healthz")
        report["health"] = body if code == 200 else {"error": code}
    finally:
        daemon.stop(drain=True)
        os.environ.pop(faults.ENV, None)
        faults.reset_plan()
    report["elapsed_s"] = round(time.monotonic() - t0, 1)
    report["violations"] += check_invariants(
        trace, os.path.join(sdir, "journal.jsonl"), expect_degraded,
        degraded_after_transition_only)
    ev = events_summary(sdir)
    report["events"] = {k: ev[k] for k in ("failures", "ready")}
    report["compiles"] = ev["compiles"]
    if expect_failure and expect_failure not in ev["failures"]:
        report["violations"].append(
            f"expected a classified {expect_failure} worker failure; "
            f"saw {ev['failures']}")
    # Warm-restart evidence for the crash scenarios: a replacement worker
    # must come up after the first death (a worker killed during warmup —
    # the compile-hang scenario — never reported ready BEFORE dying, so
    # key on exit/ready ordering, not on counting ready reports) and must
    # not recompile (cache != miss).
    if expect_failure:
        if not ev["exits"]:
            report["violations"].append(
                "crash scenario recorded no worker exit")
        else:
            first_exit = min(e["mono"] for e in ev["exits"])
            ready_after = [r for r in ev["ready"]
                           if r["mono"] > first_exit]
            if not ready_after:
                report["violations"].append(
                    "crash scenario never produced a replacement-worker "
                    "ready report")
            else:
                last = ready_after[-1]
                report["restart_cache"] = last.get("cache")
                report["restart_warmup_s"] = last.get("warmup_s")
                report["recovery_s"] = round(
                    ready_after[0]["mono"] - first_exit, 2)
                # "miss" is a violation only when a compile COMPLETED
                # before the death (then the cache must hold the entry);
                # a worker hung mid-compile persisted nothing, so its
                # replacement legitimately compiles cold.  compile.done
                # is emitted in the WORKER process (its mono is not
                # comparable to the daemon's), so order by count: ≥2
                # compiles means the dead generation finished one.
                compiled_before = len(ev["compiles"]) >= 2
                if last.get("cache") == "miss" and compiled_before:
                    report["violations"].append(
                        "replacement worker RECOMPILED (persistent-cache "
                        "miss after restart)")
    if ev["ready"]:
        report["cold_ready_s"] = ev["ready"][0].get("warmup_s")
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="CI-sized run: tiny community, short trace, all "
                         "scenarios (the acceptance gate)")
    ap.add_argument("--homes", type=int, default=None)
    ap.add_argument("--horizon-hours", type=int, default=None)
    ap.add_argument("--trace-len", type=int, default=None)
    ap.add_argument("--trace", default=None,
                    help="replay an existing JSONL request trace")
    ap.add_argument("--scenario", default=None,
                    help="run just one named scenario")
    ap.add_argument("--root", default=None,
                    help="soak working directory (default: a fresh "
                         "/tmp/dragg_serve_soak_<pid>)")
    ap.add_argument("--stub", action="store_true",
                    help="stub workers (protocol-only; no jax, no "
                         "compile-cache assertions)")
    args = ap.parse_args(argv)

    assert_parent_has_no_jax()
    homes = args.homes if args.homes is not None else (6 if args.smoke else 32)
    horizon = args.horizon_hours or (2 if args.smoke else 4)
    trace_len = args.trace_len or (12 if args.smoke else 48)
    root = args.root or f"/tmp/dragg_serve_soak_{os.getpid()}"
    os.makedirs(root, exist_ok=True)
    cache_dir = os.path.join(root, "compile_cache")

    cfg = default_config()
    cfg["community"]["total_number_homes"] = homes
    cfg["community"]["homes_pv"] = max(1, homes // 6)
    cfg["community"]["homes_battery"] = max(1, homes // 6)
    cfg["community"]["homes_pv_battery"] = max(1, homes // 6)
    cfg["home"]["hems"]["prediction_horizon"] = horizon
    cfg["tpu"]["compile_cache_dir"] = cache_dir
    cfg["serve"].update({
        "request_retries": 3, "backoff_s": 0.2, "poll_s": 0.02,
        "batch_deadline_s": 120.0, "worker_stall_s": 60.0,
        "request_deadline_s": 600.0, "drain_s": 20.0,
    })

    if args.trace:
        trace = load_trace(args.trace)
    else:
        trace = make_trace(trace_len, homes, os.path.join(root, "trace.jsonl"))
    _log(f"root={root} homes={homes} horizon={horizon}h "
         f"trace={len(trace)} requests")

    CC = "CHILD_CRASH"
    scenarios = [
        dict(name="baseline"),
        dict(name="child_crash", fault_spec="exit@serve_batch:2:once",
             expect_failure=CC),
        dict(name="kill9", fault_spec="hang@serve_batch:2:once",
             kill9_on_inflight=True, expect_failure=CC),
        dict(name="vmem_oom", fault_spec="vmem_oom@serve_batch:1:once",
             expect_failure="VMEM_OOM"),
        dict(name="compile_hang", fault_spec="hang@compile_compile:1:once",
             serve_overrides={"worker_stall_s": 20.0},
             expect_failure="COMPILE_HANG"),
        dict(name="deadline", fault_spec="hang@serve_batch:1:once",
             serve_overrides={"worker_stall_s": 0.0,
                              "batch_deadline_s": 5.0},
             expect_failure="DEADLINE"),
        dict(name="tunnel_down", platform="auto", fault_spec="probe_down:1",
             expect_degraded="TUNNEL_DOWN"),
        dict(name="wedge", platform="auto", fault_spec="probe_wedge:1",
             expect_degraded="WEDGED"),
        dict(name="midflight_degrade", platform="auto",
             fault_spec="probe_live:1,probe_down:1,exit@serve_batch:2:once",
             expect_failure=CC, expect_degraded=CC,
             degraded_after_transition_only=True),
        dict(name="daemon_restart", restart_daemon=True),
    ]
    if args.stub:
        # Stub workers have no staged-compile path — its chaos site never
        # fires; drop the scenario rather than time out waiting for it.
        scenarios = [s for s in scenarios
                     if "compile_" not in s.get("fault_spec", "")]
    if args.scenario:
        scenarios = [s for s in scenarios if s["name"] == args.scenario]
        if not scenarios:
            _log(f"unknown scenario {args.scenario!r}")
            return 2

    if args.stub:
        # Protocol-only mode: swap real workers for the stub responder.
        ServeDaemon_init = ServeDaemon.__init__

        def _stub_init(self, *a, **kw):
            kw["stub"] = True
            ServeDaemon_init(self, *a, **kw)
        ServeDaemon.__init__ = _stub_init  # type: ignore[method-assign]

    reports = {}
    violations = []
    cold_ready_s = None
    for spec in scenarios:
        spec = dict(spec)
        name = spec.pop("name")
        rep = run_scenario(name, root=root, base_cfg=cfg, trace=trace, **spec)
        reports[name] = rep
        violations += [f"{name}: {v}" for v in rep["violations"]]
        if name == "baseline":
            cold_ready_s = rep.get("cold_ready_s")
        _log(f"--- scenario {name}: "
             f"{'OK' if not rep['violations'] else 'VIOLATIONS'} "
             f"({rep['elapsed_s']}s, rps={rep.get('sustained_rps')})")

    # Cross-scenario invariant: restart recovery beats the cold start.
    crash = reports.get("child_crash", {})
    if cold_ready_s and crash.get("restart_warmup_s") is not None \
            and not args.stub:
        if crash["restart_warmup_s"] >= cold_ready_s:
            violations.append(
                f"warm restart ({crash['restart_warmup_s']}s) did not beat "
                f"the cold start ({cold_ready_s}s) — compile cache not "
                f"helping")

    result = loadgen.result_envelope(
        "serve_soak",
        ok=not violations,
        homes=homes,
        requests=len(trace),
        metrics={
            "cold_ready_s": cold_ready_s,
            "first_action_latency_proxy_s": cold_ready_s,
            "sustained_rps_baseline":
                reports.get("baseline", {}).get("sustained_rps"),
            "restart_recovery_s": crash.get("recovery_s"),
            "restart_warmup_s": crash.get("restart_warmup_s"),
            "restart_cache": crash.get("restart_cache"),
        },
        violations=violations,
        smoke=bool(args.smoke),
        horizon_hours=horizon,
        trace_len=len(trace),
        stub=bool(args.stub),
        scenarios=reports,
    )
    print(json.dumps(result, default=str))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
