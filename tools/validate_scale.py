"""Scale validation: a 10k-home x 48h-horizon multi-day run (round-1 verdict
item 4 / BASELINE.md row 5 regime on one chip).

Asserts, per chunk: solve rate >= threshold, comfort bands held on solved
steps (to fp32 band tolerance), all outputs finite.  Prints one JSON line.

Supervised (round 6): the measurement runs in a CHILD process under the
resilience supervisor — hard deadline (``--deadline``), heartbeat-stall
detection (``--stall``; each chunk beats), classified failure on the
parent's stderr — so a hung device chunk kills the child instead of
wedging this process (the parent never initializes a jax backend).

Usage: python tools/validate_scale.py [--homes 10000] [--horizon-hours 48]
                                      [--days 2] [--chunk 8]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_cfg(args):
    """(config, mix fractions) for the validated fleet — jax-free
    imports only, shared by the measured child and the ``--shards``
    coordinator parent so both validate EXACTLY the same population.
    Exits 2 with a JSON error line on malformed --mix (the established
    contract)."""
    from dragg_tpu.config import default_config

    cfg = default_config()
    n = args.homes
    cfg["community"]["total_number_homes"] = n
    cfg["fleet"]["communities"] = args.communities
    cfg["fleet"]["weather_offset_hours"] = args.weather_offset_hours
    try:
        fracs = ((0.4, 0.1, 0.1) if args.mix is None
                 else tuple(float(v) for v in args.mix.split(",")))
        if len(fracs) == 3:
            fracs = fracs + (0.0, 0.0)
        f_pv, f_bat, f_pvb, f_ev, f_hp = fracs
    except ValueError:
        print(json.dumps({"ok": False,
                          "error": f"--mix must be 3 or 5 comma fractions, "
                                   f"got {args.mix!r}"}))
        sys.exit(2)
    if any(f < 0 for f in fracs) or sum(fracs) > 1.0 + 1e-9:
        print(json.dumps({"ok": False,
                          "error": f"--mix fractions must be >= 0 and sum "
                                   f"<= 1, got {list(fracs)}"}))
        sys.exit(2)
    cfg["community"]["homes_pv"] = int(f_pv * n)
    cfg["community"]["homes_battery"] = int(f_bat * n)
    cfg["community"]["homes_pv_battery"] = int(f_pvb * n)
    cfg["community"]["homes_ev"] = int(f_ev * n)
    cfg["community"]["homes_heat_pump"] = int(f_hp * n)
    cfg["home"]["hems"]["prediction_horizon"] = args.horizon_hours
    cfg["home"]["hems"]["solver"] = args.solver
    cfg["tpu"]["bucketed"] = args.bucketed
    if args.pack:
        # Scenario pack: [mix] overrides the counts above, [[events]]
        # become the engine's event timeline (dragg_tpu/scenarios).
        from dragg_tpu.scenarios import apply_scenarios

        cfg["scenarios"]["pack"] = args.pack
        cfg = apply_scenarios(cfg, args.data_dir or None)
    return cfg, fracs


def run_shards(args):
    """The ``--shards N`` path: THIS jax-free parent runs the shard
    coordinator (tools are its supervised children — no extra wrapper),
    prints one JSON line in the validate_scale schema + shard fields,
    and with ``--shard-parity`` re-runs the SAME fleet as one in-process
    worker and asserts the merged per-community series match (exact
    solvedness; fp-tolerance aggregates across the differing bucket
    shapes — the tests/test_fleet.py tolerance class)."""
    import tempfile

    import numpy as np

    from dragg_tpu.resilience.supervisor import assert_parent_has_no_jax
    from dragg_tpu.shard.coordinator import run_sharded

    assert_parent_has_no_jax()
    cfg, fracs = build_cfg(args)
    if args.sharded:
        cfg["tpu"]["sharded"] = True
    if args.deadline:
        cfg.setdefault("shard", {})["deadline_s"] = args.deadline
    if args.stall:
        cfg.setdefault("shard", {})["stall_s"] = args.stall
    cfg.setdefault("shard", {})["transport"] = args.transport
    if args.trace:
        # Trace plane (ISSUE 20): the coordinator roots the trace and
        # every shard's records join it; assemble with
        # tools/trace_view.py <run_dir> after the run.
        cfg.setdefault("telemetry", {})["trace"] = True
    dt = int(cfg["agg"]["subhourly_steps"])
    num_ts = args.steps or args.days * 24 * dt
    run_dir = args.shard_run_dir or tempfile.mkdtemp(
        prefix="validate_shards_")
    t0 = time.perf_counter()
    res = run_sharded(
        cfg, run_dir=run_dir, steps=num_ts, workers=args.shards,
        chunk_steps=args.chunk, data_dir=args.data_dir,
        log=lambda m: print(f"[shard] {m}", file=sys.stderr, flush=True))
    total_s = time.perf_counter() - t0
    n_total = args.homes * args.communities
    parity = None
    if args.shard_parity:
        # The reference leg always runs the round-18 spool transport, so
        # --transport tcp --shard-parity is a CROSS-transport A/B: the
        # wire-delivered merge must be bit-identical to the shared-disk
        # one.
        ref_cfg = {**cfg, "shard": {**cfg.get("shard", {}),
                                    "transport": "spool"}}
        ref = run_sharded(
            ref_cfg, run_dir=os.path.join(run_dir, "parity_ref"),
            steps=num_ts,
            workers=1, chunk_steps=args.chunk, data_dir=args.data_dir,
            log=lambda m: print(f"[parity] {m}", file=sys.stderr,
                                flush=True))
        solved_eq = res["series"]["solved"] == ref["series"]["solved"]
        diffs = {}
        for name in ("agg_load", "agg_cost"):
            a = np.asarray(res["series"][name])
            b = np.asarray(ref["series"][name])
            diffs[name] = float(np.max(np.abs(a - b)
                                       / np.maximum(np.abs(b), 1e-6)))
        parity = {
            "solved_equal": bool(solved_eq),
            "max_rel_diff": diffs,
            "ok": bool(solved_eq and all(v <= 1e-3
                                         for v in diffs.values())),
        }
    result = {
        "homes": args.homes, "communities": args.communities,
        "homes_total": n_total, "shards": args.shards,
        "transport": args.transport,
        "shard_ranges": res["ranges"],
        # The workers' tpu.sharded resolution (each shards its OWN home
        # axis over its own visible devices — shard/worker.py).
        "sharded": cfg["tpu"].get("sharded", "auto"),
        "horizon_h": args.horizon_hours, "days": args.days,
        "steps": num_ts, "solver": args.solver,
        "platform": "+".join(res["platforms"]) or "?",
        "mix": list(fracs), "pack": args.pack,
        "solve_rate": res["solve_rate"],
        "comfort_violation_max": res["viol_max"],
        "timesteps_per_s": round(num_ts / max(total_s, 1e-9), 3),
        "home_steps_per_s": round(n_total * num_ts / max(total_s, 1e-9), 1),
        "steady_home_steps_per_s": res["steady_home_steps_per_s"],
        "restarts": res["restarts"],
        "total_s": round(total_s, 1),
        "shard_parity": parity,
        "run_dir": run_dir,
        "ok": bool(res["ok"]
                   and res["solve_rate"] >= args.min_solve_rate
                   and (parity is None or parity["ok"])),
    }
    print(json.dumps(result))
    sys.exit(0 if result["ok"] else 1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--homes", type=int, default=10_000,
                    help="homes PER COMMUNITY (fleet total = homes × "
                         "--communities)")
    ap.add_argument("--communities", type=int, default=1,
                    help="fleet size C (round 12): validate C independent "
                         "communities folded into one batched fleet "
                         "engine (per-community seeds; type buckets hold "
                         "C·B_type homes under one compiled pattern set)")
    ap.add_argument("--shards", type=int, default=1,
                    help="shard worker processes N (architecture.md §19): "
                         "N > 1 validates through the jax-free shard "
                         "coordinator — communities split into N "
                         "contiguous ranges, one supervised worker "
                         "process each, merged per-community outputs")
    ap.add_argument("--transport", choices=["spool", "tcp"],
                    default="spool",
                    help="with --shards: chunk exchange — 'spool' = "
                         "shared-disk outbox files (round 18), 'tcp' = "
                         "workers push checksummed frames to the "
                         "coordinator's chunk-ingest server over "
                         "shard.listen (architecture.md §20); the "
                         "--shard-parity reference leg ALWAYS runs spool, "
                         "making it a cross-transport A/B")
    ap.add_argument("--shard-parity", action="store_true",
                    help="with --shards: ALSO run the same fleet as one "
                         "in-process worker and assert the merged "
                         "per-community series match (exact solvedness, "
                         "fp-tolerance aggregates across the differing "
                         "bucket shapes — tests/test_fleet.py class)")
    ap.add_argument("--shard-run-dir", default=None,
                    help="with --shards: durable journal+spool directory "
                         "(default: a fresh temp dir; reuse to resume)")
    ap.add_argument("--trace", action="store_true",
                    help="with --shards: enable the causal trace plane "
                         "(telemetry.trace) — the run's events carry "
                         "trace/span ids across the coordinator, workers, "
                         "and the wire; render with tools/trace_view.py "
                         "<run_dir>")
    ap.add_argument("--weather-offset-hours", type=int, default=0,
                    help="fleet.weather_offset_hours: community c's "
                         "environment windows shift c× this many hours")
    ap.add_argument("--horizon-hours", type=int, default=48)
    ap.add_argument("--days", type=int, default=2)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--solver", choices=["admm", "ipm", "reluqp"],
                    default="admm")
    ap.add_argument("--mix", default=None,
                    help="comma fractions pv,battery,pv_battery[,ev,"
                         "heat_pump] of the population (default "
                         "0.4,0.1,0.1 — the bench mix; 3 fractions keep "
                         "the legacy form); e.g. --mix 0,0,0 for an "
                         "all-base bucket-heavy community or "
                         "--mix 0.3,0.1,0.1,0.1,0.1 for the full "
                         "six-type scenario mix")
    ap.add_argument("--pack", default=None,
                    help="scenario pack name (data/packs/<name>.toml — "
                         "docs/scenarios.md): [mix] fractions override "
                         "--mix and [[events]] compile a DR/tariff-"
                         "shock/outage timeline into the validated step")
    ap.add_argument("--bucketed", choices=["auto", "true", "false"],
                    default="auto",
                    help="tpu.bucketed override for the scale check "
                         "(docs/config.md)")
    ap.add_argument("--min-solve-rate", type=float, default=0.97)
    ap.add_argument("--sharded", action="store_true",
                    help="shard the home axis over every visible device "
                         "(BASELINE row-5 topology; on the CPU test host "
                         "pair with XLA_FLAGS=--xla_force_host_platform_"
                         "device_count=8)")
    ap.add_argument("--steps", type=int, default=0,
                    help="cap the simulated timesteps (0 = days*24*dt); "
                         "lets the 100k-home community run ONE chunk "
                         "without a multi-hour CPU sim")
    ap.add_argument("--data-dir", default=os.environ.get("DATA_DIR") or None,
                    help="directory with nsrdb.csv + waterdraw_profiles.csv "
                         "(e.g. the reference's real assets); default: "
                         "$DATA_DIR, else synthetic weather/draws")
    ap.add_argument("--deadline", type=float, default=7200.0,
                    help="hard wall-clock limit for the supervised "
                         "measurement child")
    ap.add_argument("--stall", type=float, default=0.0,
                    help="kill the child if no chunk completes for this "
                         "many seconds (0 = disabled, the default: a big "
                         "CPU chunk legitimately computes longer than any "
                         "beat cadence and the hard --deadline still "
                         "bounds it; set ~900 for on-chip runs where a "
                         "stall means a wedge-risk hang)")
    ap.add_argument("--_child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.shards > 1 and not args._child:
        # Sharded validation: the coordinator supervises its own worker
        # children, so this parent needs no extra supervision wrapper.
        run_shards(args)

    if not args._child:
        # Supervised parent: jax-free, un-wedgeable.  The child is this
        # same script; its one JSON line is forwarded verbatim.
        from dragg_tpu.resilience.supervisor import (assert_parent_has_no_jax,
                                                     run_supervised)

        assert_parent_has_no_jax()
        res = run_supervised(
            [sys.executable, os.path.abspath(__file__), "--_child",
             *sys.argv[1:]],
            args.deadline, label="validate_scale",
            stall_s=args.stall or None,
            log=lambda m: print(f"[supervise] {m}", file=sys.stderr,
                                flush=True))
        sys.stderr.write(res.stderr_tail)
        if res.json is not None:
            print(json.dumps(res.json))
        elif not res.ok:
            print(json.dumps({"ok": False, "failure": res.failure,
                              "rc": res.rc,
                              "elapsed_s": round(res.elapsed_s, 1)}))
        sys.exit(res.rc if res.rc is not None and res.rc >= 0 else 1)

    import jax
    import numpy as np

    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_fleet_batch, create_fleet_homes
    from dragg_tpu.parallel.mesh import make_sharded_engine
    from dragg_tpu.scenarios import describe_timeline

    # Population mix: default is the bench mix; --mix exercises
    # bucket-heavy (0,0,0 = all base), superset-only (0,0,1), and — with
    # 5 fractions — the scenario types (ev, heat_pump; ISSUE 10).
    cfg, fracs = build_cfg(args)
    n = args.homes
    n_total = n * args.communities

    from dragg_tpu.data import waterdraw_path

    env = load_environment(cfg, data_dir=args.data_dir)
    dt = int(cfg["agg"]["subhourly_steps"])
    wd = load_waterdraw_profiles(waterdraw_path(cfg, args.data_dir), seed=12)
    num_ts = args.days * 24 * dt
    homes = create_fleet_homes(cfg, num_ts, dt, wd)
    hems = cfg["home"]["hems"]
    batch, fleet = build_fleet_batch(homes, cfg, args.horizon_hours * dt, dt,
                                     int(hems["sub_subhourly_steps"]))
    if args.sharded:
        eng = make_sharded_engine(batch, env, cfg, 0, fleet=fleet)
    else:
        eng = make_engine(batch, env, cfg, 0, fleet=fleet)
    state = eng.init_state()
    if args.steps:
        num_ts = args.steps

    # Band bounds in COMMUNITY-MAJOR fleet order (the order real_home_cols
    # maps outputs back to); identical to batch order when C == 1.
    order = (np.argsort(np.asarray(fleet.global_idx)) if fleet is not None
             else np.arange(batch.n_homes))
    tin_min = np.asarray(batch.temp_in_min)[order]
    tin_max = np.asarray(batch.temp_in_max)[order]
    twh_min = np.asarray(batch.temp_wh_min)[order]
    twh_max = np.asarray(batch.temp_wh_max)[order]
    band_tol = 0.05  # fp32 dynamics-row tolerance on ~degC scales
    # Scenario event windows legitimately widen the indoor band by the
    # scheduled comfort relief (DR / outage relaxation — ops/qp.py), so
    # the static-band check must grant the same headroom.
    evts = getattr(eng, "_events", None)
    if evts is not None:
        band_tol += float(np.max(evts.relax))

    from dragg_tpu.resilience.faults import fault_hook
    from dragg_tpu.resilience.heartbeat import beat

    t = 0
    rates, chunk_times, viol_max = [], [], 0.0
    t_all = time.perf_counter()
    beat({"timestep": 0})
    while t < num_ts:
        fault_hook("scale_chunk")
        k = min(args.chunk, num_ts - t)
        rps = np.zeros((k, eng.params.horizon), dtype=np.float32)
        t0 = time.perf_counter()
        state, outs = eng.run_chunk(state, t, rps)
        jax.block_until_ready(outs.agg_load)
        chunk_times.append(time.perf_counter() - t0)
        # Padded engines carry replica homes (whole-batch padding, or
        # per-bucket padding when type-bucketed); validate only the real
        # homes, mapped back to community order.
        cols = eng.real_home_cols
        solved = np.asarray(outs.correct_solve)[:, cols]   # (k, n)
        rates.append(float(solved.mean()))
        for leaf, name in zip(outs, outs._fields):
            a = np.asarray(leaf)
            assert np.all(np.isfinite(a)), f"non-finite {name} at t={t}"
        tin = np.asarray(outs.temp_in)[:, cols]
        twh = np.asarray(outs.temp_wh)[:, cols]
        # Comfort bands on solved steps (unsolved steps run the bang-bang
        # fallback, which tolerates excursions by design).
        vi = np.where(solved > 0,
                      np.maximum(tin_min[None] - tin, tin - tin_max[None]), -1.0)
        vw = np.where(solved > 0,
                      np.maximum(twh_min[None] - twh, twh - twh_max[None]), -1.0)
        viol_max = max(viol_max, float(vi.max()), float(vw.max()))
        t += k
        beat({"timestep": t})
        print(f"[t={t}/{num_ts}] solve_rate={rates[-1]:.4f} "
              f"chunk_s={chunk_times[-1]:.1f} viol_max={viol_max:.4f}",
              file=sys.stderr, flush=True)

    solve_rate = float(np.mean(rates))
    import resource

    result = {
        "homes": n, "communities": args.communities, "homes_total": n_total,
        "shards": 1,
        "weather_offset_hours": args.weather_offset_hours,
        "horizon_h": args.horizon_hours, "days": args.days,
        "steps": num_ts,
        "solver": args.solver,
        "platform": jax.devices()[0].platform,  # dragg: disable=DT004, supervised child
        "device_kind": str(getattr(jax.devices()[0], "device_kind", "")),  # dragg: disable=DT004, supervised child
        "sharded": bool(args.sharded),
        "n_devices": len(jax.devices()) if args.sharded else 1,  # dragg: disable=DT004, supervised child
        "home_slots": eng.n_homes,
        "mix": list(fracs),
        "pack": args.pack,
        "events": describe_timeline(getattr(eng, "_events", None)),
        "bucket_patterns": len(eng.bucket_info()),
        "bucketed": eng.bucketed,
        "solve_rate": round(solve_rate, 4),
        "comfort_violation_max": round(viol_max, 5),
        "timesteps_per_s": round(num_ts / sum(chunk_times), 3),
        # Scale-comparability rate: home-steps/s (fleet total homes ×
        # ts/s) — the number that must stay flat as C grows (ISSUE 8).
        "home_steps_per_s": round(n_total * num_ts / sum(chunk_times), 1),
        "total_s": round(time.perf_counter() - t_all, 1),
        "peak_rss_gb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2),
        "ok": bool(solve_rate >= args.min_solve_rate and viol_max <= band_tol),
    }
    print(json.dumps(result))
    sys.exit(0 if result["ok"] else 1)


if __name__ == "__main__":
    main()
