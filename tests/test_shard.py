"""Cross-process fleet sharding (round 18 — ISSUE 15, architecture.md
§19).

Parity contract: N shard worker processes, each running a contiguous
community range of the fleet via ``fleet.community_base``, must merge to
per-community aggregate series BIT-identical to the in-process fleet —
both sides fold per-home outputs through the ONE shared implementation
(shard/partition.fold_outputs) in community-major (``real_home_pairs``)
order.  The pinned configs sit in the composition-invariant regime the
fleet parity suite established (tests/test_fleet.py: unbucketed,
``ipm_tail_frac = 0``); bucketed/cross-shape compositions get the
tolerance-class treatment in the validate_scale ``--shard-parity`` CI
smoke instead.

Robustness is the headline: kill -9 mid-chunk, coordinator kill +
journal-frontier resume, independent TPU→CPU degradation, and elastic
checkpoint resharding.  Round 19 adds the NETWORKED transport
(``shard.transport = "tcp"`` — shard/wire.py + shard/transport.py,
architecture.md §20): frame-codec torture, the loopback ingest server's
dedup/fence/restart legs, sticky degradation to the spool, and the
wire-chaos parity run (torn frame + lost ack + mid-frame partition,
outputs still bit-identical).  Heavy legs (multi-run reshard roundtrip,
external coordinator kill, tcp kill -9 resume) are slow-marked with
light siblings per the round-15 tier-1 budget pattern.
"""

import copy
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from dragg_tpu.config import default_config
from dragg_tpu.shard import journal as sj
from dragg_tpu.shard.partition import (
    fold_community_series,
    fold_outputs,
    shard_config,
    shard_ranges,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(C=2, n=6, steps_solver="ipm"):
    """The composition-invariant pinned config (test_fleet convention):
    unbucketed, no tail compaction, unsharded single-device engines —
    per-home trajectories provably independent of batch composition, so
    shard-vs-fleet comparisons are BIT-exact."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["home"]["hems"]["prediction_horizon"] = 2
    cfg["home"]["hems"]["solver"] = steps_solver
    cfg["fleet"]["communities"] = C
    cfg["fleet"]["seed_stride"] = 5
    cfg["tpu"]["bucketed"] = "false"
    cfg["tpu"]["ipm_tail_frac"] = 0.0
    cfg["tpu"]["sharded"] = False
    cfg["telemetry"]["enabled"] = False
    return cfg


def _inprocess_reference(cfg, steps, chunk):
    """The in-process fleet run, folded per community with the SAME
    chunk boundaries the workers use (chunking resets the solver factor
    cache, so boundary-identical runs are the bit-exact comparison)."""
    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_fleet_batch, create_fleet_homes

    env = load_environment(cfg, data_dir="")
    wd = load_waterdraw_profiles(None, seed=12)
    dt = int(cfg["agg"]["subhourly_steps"])
    homes = create_fleet_homes(cfg, steps, dt, wd)
    H = int(cfg["home"]["hems"]["prediction_horizon"]) * dt
    batch, fleet = build_fleet_batch(
        homes, cfg, H, dt, int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    eng = make_engine(batch, env, cfg, 0, fleet=fleet)
    pairs = np.asarray(eng.real_home_pairs)
    C = eng.n_communities
    state, t = eng.init_state(), 0
    series = None
    while t < steps:
        k = min(chunk, steps - t)
        rps = np.zeros((k, eng.params.horizon), np.float32)
        state, outs = eng.run_chunk(state, t, rps)
        folded = fold_outputs(outs, pairs, C)
        if series is None:
            series = {name: [v] for name, v in folded.items()}
        else:
            for name, v in folded.items():
                series[name].append(v)
        t += k
    return {name: np.concatenate(vs, axis=0).tolist()
            for name, vs in series.items()}


# ---------------------------------------------------------------- units
def test_shard_ranges():
    """Balanced contiguous partition; degenerate inputs refused."""
    assert shard_ranges(10, 3) == [(0, 4), (4, 7), (7, 10)]
    assert shard_ranges(4, 4) == [(0, 1), (1, 2), (2, 3), (3, 4)]
    assert shard_ranges(5, 1) == [(0, 5)]
    with pytest.raises(ValueError, match="at least one community"):
        shard_ranges(2, 3)
    with pytest.raises(ValueError, match="workers"):
        shard_ranges(2, 0)


def test_shard_config_remaps_events():
    """Shard configs carry the range as community_base + count, and
    scenario events naming global communities are re-indexed local (or
    dropped when every target lives on another shard)."""
    cfg = {"fleet": {"communities": 10, "community_base": 0},
           "scenarios": {"events": [
               {"kind": "dr", "communities": [2, 3, 7]},
               {"kind": "outage", "communities": [0]},
               {"kind": "tariff_shock"}]}}
    sc = shard_config(cfg, 2, 5)
    assert sc["fleet"]["communities"] == 3
    assert sc["fleet"]["community_base"] == 2
    evs = sc["scenarios"]["events"]
    assert evs[0]["communities"] == [0, 1]       # globals 2, 3 → local
    assert evs[1] == {"kind": "tariff_shock"}    # all-community passthrough
    assert len(evs) == 2                         # community-0 event dropped
    assert cfg["scenarios"]["events"][0]["communities"] == [2, 3, 7]  # orig


def test_fold_community_series_order():
    """The fold sums each community's homes as one contiguous float64
    block in community-major order — the reduction both sides of every
    parity comparison share."""
    vals = np.arange(12, dtype=np.float64).reshape(3, 4)
    pairs = np.array([[0, 1], [0, 0], [1, 3], [1, 2]])
    out = fold_community_series(vals, pairs, 2)
    np.testing.assert_array_equal(out, [[1, 5], [9, 13], [17, 21]])
    assert out.dtype == np.float64


def test_community_base_identities():
    """fleet.community_base keeps global seeds / name prefixes / weather
    offsets (the shard workers' bit-identity ground)."""
    from dragg_tpu.data import load_waterdraw_profiles
    from dragg_tpu.homes import create_fleet_homes, fleet_spec_for

    cfg = _cfg(C=3)
    cfg["fleet"]["weather_offset_hours"] = 2
    wd = load_waterdraw_profiles(None, seed=12)
    full = create_fleet_homes(cfg, 24, 1, wd)

    scfg = shard_config(cfg, 1, 3)
    part = create_fleet_homes(scfg, 24, 1, wd)
    assert [h["name"] for h in part] == [h["name"] for h in full[6:]]
    assert part[0]["name"].startswith("c1-")
    spec = fleet_spec_for(part, scfg)
    assert spec.seeds == (12 + 5, 12 + 10)       # global seeds kept
    # env offsets are (base + local community) * off * dt
    np.testing.assert_array_equal(spec.env_offset,
                                  (1 + spec.community) * 2)
    # A single-community shard with a base is still a (C=1) fleet spec —
    # the non-fleet fast path would lose the global identities.
    scfg1 = shard_config(cfg, 2, 3)
    part1 = create_fleet_homes(scfg1, 24, 1, wd)
    spec1 = fleet_spec_for(part1, scfg1)
    assert spec1 is not None and spec1.seeds == (12 + 10,)
    assert part1[0]["name"].startswith("c2-")
    with pytest.raises(ValueError, match="community_base"):
        fleet_spec_for(part, {**scfg, "fleet": {**scfg["fleet"],
                                                "community_base": -1}})


# -------------------------------------------------------------- journal
def test_journal_lifecycle_and_duplicate_epoch(tmp_path):
    path = str(tmp_path / "shard_journal.jsonl")
    j = sj.Journal(path)
    j.epoch("tok-1")
    j.plan(4, 2, [(0, 2), (2, 4)], steps=8, chunk_steps=2)
    j.launch(0, 1, "cpu", 0, 2)
    j.chunk(0, 0, 0, 2)
    j.chunk(1, 0, 0, 2)
    j.chunk(0, 1, 2, 4)
    j.transition(1, "inherit", "cpu", "CHILD_CRASH")
    j.done(0, 2)
    with pytest.raises(ValueError, match="already claimed"):
        j.epoch("tok-1")
    j.close()
    # The refusal survives a restart: claims replay from the file.
    j2 = sj.Journal(path)
    with pytest.raises(ValueError, match="already claimed"):
        j2.epoch("tok-1")
    j2.epoch("tok-2")
    j2.close()
    rep = sj.replay(path)
    assert rep.epochs == ["tok-1", "tok-2"]
    assert rep.plan["ranges"] == [[0, 2], [2, 4]]
    assert rep.frontier == {0: 2, 1: 1}
    assert rep.platforms == {0: "cpu", 1: "cpu"}  # launch + transition
    assert rep.gens == {0: 1}  # successors continue the numbering
    assert rep.done == {0}
    assert rep.dropped_lines == 0


def test_journal_torn_tail_every_byte(tmp_path):
    """Truncation at EVERY byte boundary: replay never raises, the
    frontier only walks backward toward the head, and a torn final line
    drops silently (the serve-journal property-test precedent)."""
    path = str(tmp_path / "shard_journal.jsonl")
    j = sj.Journal(path)
    j.epoch("tok")
    j.plan(2, 2, [(0, 1), (1, 2)], steps=4, chunk_steps=2)
    for seq in range(2):
        j.chunk(0, seq, seq * 2, seq * 2 + 2)
        j.chunk(1, seq, seq * 2, seq * 2 + 2)
    j.close()
    raw = open(path, "rb").read()
    prev = None
    for cut in range(len(raw), -1, -1):
        with open(path, "wb") as f:
            f.write(raw[:cut])
        rep = sj.replay(path)
        total = sum(rep.frontier.values())
        assert rep.dropped_lines <= 1, cut
        if prev is not None:
            assert total <= prev, cut
        prev = total


def test_doctor_shard_check():
    """The ``doctor --shard-check`` selftest is green (light sibling of
    the CLI smoke in run_ci_locally.sh)."""
    from dragg_tpu.doctor import _check_shard_journal

    res = _check_shard_journal()
    assert res["status"] == "ok", res


# ------------------------------------------------------ wire (round 19)
def test_wire_frame_roundtrip_and_torn_every_byte():
    """The frame codec round-trips one document and decodes EVERY
    defect an unreliable wire can produce — truncation at any byte
    boundary, a flipped bit anywhere, trailing garbage — to TornFrame,
    never to a partial document (shard/wire.py contract)."""
    from dragg_tpu.shard import wire

    doc = {"kind": "chunk", "epoch": "tok", "shard": 1, "seq": 2,
           "payload": {"seq": 2, "t0": 0, "t1": 2,
                       "series": {"agg_load": [[1.5], [2.5]]}}}
    frame = wire.encode_frame(doc)
    assert wire.decode_frame(frame) == doc
    assert wire.chunk_token("tok", 1, 2) == "tok/s1/c2"
    for cut in range(len(frame)):
        with pytest.raises(wire.TornFrame):
            wire.decode_frame(frame[:cut])
    # A flipped bit in the magic / version / length / crc / body.
    for pos in (0, 4, 5, 9, len(frame) - 1):
        bad = bytearray(frame)
        bad[pos] ^= 0x01
        with pytest.raises(wire.TornFrame):
            wire.decode_frame(bytes(bad))
    with pytest.raises(wire.TornFrame, match="torn body"):
        wire.decode_frame(frame + b"x")


def test_wire_server_dedup_fence_restart_params(tmp_path):
    """Loopback ingest-server unit legs (no engine): journal-before-ack,
    duplicate token acked without re-merge, dedup surviving a transport
    restart (seeded from journal + spool, not process memory), epoch
    fencing naming the stale token, and the params long-poll channel."""
    from dragg_tpu.serve import spool as sp
    from dragg_tpu.shard.transport import (ChunkIngestServer, EpochFenced,
                                           WireClient)

    spool_dir = str(tmp_path / "spool")
    jpath = str(tmp_path / "shard_journal.jsonl")
    journal = sj.Journal(jpath)
    journal.epoch("tok-1")
    sp.write_epoch(spool_dir, "tok-1")
    payload = {"seq": 0, "t0": 0, "t1": 2,
               "series": {"agg_load": [[1.0], [2.0]]}}
    srv = ChunkIngestServer(spool_dir, journal, "tok-1")
    srv.start()
    try:
        cli = WireClient(srv.endpoint, "tok-1", 0, spool_dir, retry_s=5.0)
        assert cli.push_chunk(0, payload) == "acked"
        # Journal-before-ack: by the time push_chunk returned, the ack
        # was fsync'd and the retained spool file matches the payload.
        assert sj.replay(jpath).acked == {0: [0]}
        assert sp.read_json(sp.chunk_path(spool_dir, 0, 0)) == payload
        # The lost-ack retry path: a duplicate is acked, never re-merged.
        assert cli.push_chunk(0, payload) == "dup"
        # Epoch fencing over the wire: the refusal names the stale token.
        orphan = WireClient(srv.endpoint, "dead-tok", 0, spool_dir,
                            retry_s=5.0)
        with pytest.raises(EpochFenced, match="dead-tok/s0/c1"):
            orphan.push_chunk(1, {"seq": 1, "t0": 2, "t1": 4})
        # Params long-poll: nothing published -> None; published -> seen.
        assert cli.poll_params(have=0) is None
        assert srv.publish_params(0, {"stop_t": 4}) == 1
        got = cli.poll_params(have=0, wait_s=2.0)
        assert got == (1, {"stop_t": 4})
    finally:
        srv.stop()
    # Transport restart on the same run: the at-least-once token
    # survives, and the re-push is NOT re-journaled.
    srv2 = ChunkIngestServer(spool_dir, journal, "tok-1")
    srv2.start()
    try:
        cli2 = WireClient(srv2.endpoint, "tok-1", 0, spool_dir,
                          retry_s=5.0)
        assert cli2.push_chunk(0, payload) == "dup"
    finally:
        srv2.stop()
        journal.close()
    acks = [r for r in (json.loads(ln) for ln in open(jpath))
            if r.get("state") == "chunk"]
    assert len(acks) == 1


def test_wire_client_degrades_to_spool_sticky(tmp_path):
    """A wire that stays down past ``shard.transport_retry_s`` degrades
    to the shared-disk spool (round-18 path) and STAYS degraded — later
    chunks skip the retry stall entirely."""
    from dragg_tpu.serve import spool as sp
    from dragg_tpu.shard.transport import WireClient

    spool_dir = str(tmp_path / "spool")
    sp.ensure_shard_dirs(spool_dir, 0)
    # Port 1 on loopback: nothing listens, every attempt is refused.
    cli = WireClient("127.0.0.1:1", "tok", 0, spool_dir, retry_s=0.3,
                     op_timeout_s=0.5)
    payload = {"seq": 0, "t0": 0, "t1": 2}
    assert cli.push_chunk(0, payload) == "spool"
    assert cli.degraded
    assert sp.read_json(sp.chunk_path(spool_dir, 0, 0)) == payload
    t1 = time.monotonic()
    assert cli.push_chunk(1, {"seq": 1, "t0": 2, "t1": 4}) == "spool"
    assert time.monotonic() - t1 < 0.3, "sticky degradation re-dialed"


def test_doctor_shard_wire_check():
    """The ``doctor --shard-check`` wire selftest is green — a live
    loopback server swept with a torn frame at every byte boundary,
    dedup across a transport restart, and a named fence refusal (light
    sibling of the wire-smoke CLI leg in run_ci_locally.sh)."""
    from dragg_tpu.doctor import _check_shard_wire

    res = _check_shard_wire()
    assert res["status"] == "ok", res
    assert "torn-frame sweep" in res["note"]


# ----------------------------------------------------- telemetry merge
def test_tail_events_dir_merges_shard_streams(tmp_path):
    """Per-shard sub-streams merge into one wall-time-ordered tail with
    ``_stream`` attribution; runs without sub-streams reduce to the
    plain tailer."""
    from dragg_tpu import telemetry

    main = tmp_path / "events.jsonl"
    recs = [
        (str(main), {"event": "shard.plan", "t": 1.0, "seq": 1}),
        (str(tmp_path / "shard0" / "events.jsonl"),
         {"event": "chunk.done", "t": 2.0, "seq": 1}),
        (str(tmp_path / "shard1" / "events.jsonl"),
         {"event": "chunk.done", "t": 1.5, "seq": 1}),
        (str(main), {"event": "shard.merge", "t": 3.0, "seq": 2}),
    ]
    for path, rec in recs:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    paths = telemetry.stream_paths(str(main))
    assert [os.path.basename(os.path.dirname(p)) for p in paths[1:]] == \
        ["shard0", "shard1"]
    merged = telemetry.tail_events_dir(str(main), limit=10)
    assert [r["event"] for r in merged] == \
        ["shard.plan", "chunk.done", "chunk.done", "shard.merge"]
    assert [r["_stream"] for r in merged] == \
        ["main", "shard1", "shard0", "main"]


def test_supervisor_telemetry_dir_override(tmp_path):
    """run_supervised(telemetry_dir=...) routes the child's bus to the
    given sub-stream instead of the parent's shared dir (the shard
    slots' per-worker export, satellite 1)."""
    from dragg_tpu.resilience.supervisor import run_supervised

    sub = str(tmp_path / "shard7")
    res = run_supervised(
        [sys.executable, "-c",
         "import os; print(os.environ.get('DRAGG_TELEMETRY_DIR', ''))"],
        deadline_s=60.0, telemetry_dir=sub)
    assert res.ok and res.stdout_tail.strip().endswith("shard7")


# ------------------------------------------------- coordinator (light)
def test_coordinator_n1_and_kill9_chaos_bit_identical(tmp_path,
                                                      monkeypatch):
    """The headline contract in one compile budget: the in-process fleet
    reference vs (a) a 1-worker coordinator run (N=1 merged outputs
    bit-identical) and (b) a 2-worker run with one shard kill -9'd
    mid-chunk (merged outputs STILL bit-identical, exactly one relaunch,
    journal frontier complete — re-work bounded at one chunk by the
    worker's outbox-then-checkpoint ordering)."""
    from dragg_tpu.shard.coordinator import run_sharded

    cfg = _cfg(C=2)
    ref = _inprocess_reference(copy.deepcopy(cfg), steps=4, chunk=2)

    res1 = run_sharded(copy.deepcopy(cfg), run_dir=str(tmp_path / "n1"),
                       steps=4, workers=1, chunk_steps=2, platform="cpu",
                       data_dir="")
    assert res1["series"] == ref
    assert res1["restarts"] == {}

    monkeypatch.setenv("DRAGG_FAULT_INJECT", "sigkill@shard_chunk:2:once")
    monkeypatch.setenv("DRAGG_FAULT_STATE", str(tmp_path / "faults"))
    os.makedirs(str(tmp_path / "faults"), exist_ok=True)
    res2 = run_sharded(copy.deepcopy(cfg), run_dir=str(tmp_path / "n2"),
                       steps=4, workers=2, chunk_steps=2, platform="cpu",
                       data_dir="")
    assert res2["series"] == ref, "kill -9 perturbed the merged outputs"
    assert sum(res2["restarts"].values()) == 1
    rep = sj.replay(str(tmp_path / "n2" / "shard_journal.jsonl"))
    assert rep.frontier == {0: 2, 1: 2}
    assert sum(rep.restarts.values()) == 1
    assert rep.plan["communities"] == 2
    # The fleet totals are the column sums of the same float64 series.
    np.testing.assert_array_equal(
        np.asarray(res2["totals"]["agg_load"]),
        np.asarray(ref["agg_load"]).sum(axis=1))


def test_coordinator_degrades_one_shard_independently(tmp_path,
                                                      monkeypatch):
    """A shard whose generation dies at build is relaunched DEGRADED
    (inherit → cpu) after ``shard.degrade_after`` consecutive failures,
    with the transition journaled; the other shard never transitions.
    (Light: the injected death is pre-compile.)"""
    from dragg_tpu.shard.coordinator import run_sharded

    cfg = _cfg(C=2)
    cfg["shard"] = {"degrade_after": 1, "restarts": 3}
    monkeypatch.setenv("DRAGG_FAULT_INJECT", "exit@shard_build:1:once")
    monkeypatch.setenv("DRAGG_FAULT_STATE", str(tmp_path / "faults"))
    os.makedirs(str(tmp_path / "faults"), exist_ok=True)
    ref = _inprocess_reference(copy.deepcopy(cfg), steps=4, chunk=2)
    res = run_sharded(copy.deepcopy(cfg), run_dir=str(tmp_path / "run"),
                      steps=4, workers=2, chunk_steps=2, platform="auto",
                      data_dir="")
    assert res["series"] == ref
    rep = sj.replay(str(tmp_path / "run" / "shard_journal.jsonl"))
    degraded = [k for k, p in rep.platforms.items() if p == "cpu"]
    assert len(degraded) == 1, rep.platforms
    assert sum(rep.restarts.values()) == 1


def test_coordinator_refuses_changed_plan(tmp_path):
    """A run dir journaled for one partition refuses a different plan
    loudly (reshard the checkpoints instead) — the light sibling of the
    slow coordinator-restart legs (no workers launched)."""
    from dragg_tpu.shard.coordinator import JOURNAL_FILE, run_sharded

    j = sj.Journal(str(tmp_path / JOURNAL_FILE))
    j.epoch("old-tok")
    j.plan(2, 2, [(0, 1), (1, 2)], steps=4, chunk_steps=2)
    j.close()
    with pytest.raises(ValueError, match="journaled for plan"):
        run_sharded(_cfg(C=2), run_dir=str(tmp_path), steps=8, workers=2,
                    chunk_steps=2, platform="cpu", data_dir="")


def test_tcp_transport_parity_under_wire_chaos(tmp_path, monkeypatch):
    """The round-19 headline in one compile budget: a 2-shard run over
    the tcp transport with ALL THREE wire chaos legs armed — every
    worker's first push attempt sends a torn frame, one ack is dropped
    AFTER merge+journal (lost-ack), and a later attempt is cut mid-frame
    (network partition mid-chunk) — still merges outputs BIT-identical
    to the in-process fleet, with zero worker restarts and every chunk
    journal-acked exactly ONCE (the at-least-once re-push dedups, never
    double-merges)."""
    from dragg_tpu.resilience import faults
    from dragg_tpu.shard.coordinator import run_sharded

    cfg = _cfg(C=2)
    cfg["shard"] = {"transport": "tcp", "transport_retry_s": 30.0}
    ref = _inprocess_reference(copy.deepcopy(cfg), steps=4, chunk=2)

    # wire_send / wire_partition fire in the WORKER processes (each
    # worker counts its own hits; a wire_send fault skips that attempt's
    # wire_partition hit — the counters are offset by design);
    # wire_ack fires in THIS process (the coordinator's ingest handler
    # thread), so the cached fault plan must be re-read here too.
    monkeypatch.setenv(
        "DRAGG_FAULT_INJECT",
        "torn@wire_send:1,cut@wire_partition:2,drop@wire_ack:1")
    faults.reset_plan()
    try:
        res = run_sharded(copy.deepcopy(cfg),
                          run_dir=str(tmp_path / "run"), steps=4,
                          workers=2, chunk_steps=2, platform="cpu",
                          data_dir="")
    finally:
        faults.reset_plan()
    assert res["series"] == ref, "wire chaos perturbed the merged outputs"
    assert res["restarts"] == {}
    jpath = str(tmp_path / "run" / "shard_journal.jsonl")
    rep = sj.replay(jpath)
    assert rep.frontier == {0: 2, 1: 2}
    acks = [(r["shard"], r["seq"]) for r in
            (json.loads(ln) for ln in open(jpath))
            if r.get("state") == "chunk"]
    assert sorted(acks) == [(0, 0), (0, 1), (1, 0), (1, 1)], \
        "a lost ack double-journaled its chunk"


# -------------------------------------------------- heavy (slow-marked)
@pytest.mark.slow  # 1 coordinator run + ref; light sibling: wire-chaos test
def test_tcp_transport_kill9_resume_bounded_rework(tmp_path, monkeypatch):
    """kill -9 one shard mid-chunk while pushing over tcp: the relaunch
    resumes from its chunk checkpoint (re-work ≤ 1 chunk — the pushed
    payload was durable on the coordinator BEFORE the worker
    checkpointed, so outbox-before-checkpoint holds over the wire too)
    and the merged outputs stay bit-identical."""
    from dragg_tpu.shard.coordinator import run_sharded

    cfg = _cfg(C=2)
    cfg["shard"] = {"transport": "tcp"}
    ref = _inprocess_reference(copy.deepcopy(cfg), steps=4, chunk=2)
    monkeypatch.setenv("DRAGG_FAULT_INJECT", "sigkill@shard_chunk:2:once")
    monkeypatch.setenv("DRAGG_FAULT_STATE", str(tmp_path / "faults"))
    os.makedirs(str(tmp_path / "faults"), exist_ok=True)
    res = run_sharded(copy.deepcopy(cfg), run_dir=str(tmp_path / "run"),
                      steps=4, workers=2, chunk_steps=2, platform="cpu",
                      data_dir="")
    assert res["series"] == ref
    assert sum(res["restarts"].values()) == 1
    rep = sj.replay(str(tmp_path / "run" / "shard_journal.jsonl"))
    assert rep.frontier == {0: 2, 1: 2}
@pytest.mark.slow  # 2 coordinator runs; light siblings: plan-refusal + N=1 test
def test_coordinator_kill9_restart_resumes_from_frontier(tmp_path):
    """Kill -9 the COORDINATOR mid-run; a successor on the same run dir
    replays the journal to the exact chunk frontier, fences the orphan
    workers via a fresh EPOCH token, and completes with merged outputs
    bit-identical to a clean run."""
    from dragg_tpu.shard.coordinator import run_sharded

    cfg = _cfg(C=2)
    ref = _inprocess_reference(copy.deepcopy(cfg), steps=8, chunk=2)

    run_dir = str(tmp_path / "run")
    cfg_path = tmp_path / "cfg.json"
    # python -m dragg_tpu.shard builds from TOML/defaults; drive the
    # coordinator via a tiny stub so the killed process runs EXACTLY the
    # pinned config.
    stub = tmp_path / "coord.py"
    stub.write_text(
        "import json, sys\n"
        f"sys.path.insert(0, {ROOT!r})\n"
        "from dragg_tpu.shard.coordinator import run_sharded\n"
        f"cfg = json.load(open({str(cfg_path)!r}))\n"
        f"run_sharded(cfg, run_dir={run_dir!r}, steps=8, workers=2,\n"
        "            chunk_steps=2, platform='cpu', data_dir='')\n")
    cfg_path.write_text(json.dumps(cfg))
    proc = subprocess.Popen([sys.executable, str(stub)],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    journal_path = os.path.join(run_dir, "shard_journal.jsonl")
    deadline = time.monotonic() + 300
    try:
        while time.monotonic() < deadline:
            rep = sj.replay(journal_path)
            if sum(rep.frontier.values()) >= 1:
                break
            if proc.poll() is not None:
                pytest.fail("coordinator exited before first chunk ack")
            time.sleep(0.02)
        else:
            pytest.fail("no chunk acked within the deadline")
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()
    # What was genuinely unfinished at kill time (the tiny chunks can
    # race to completion within one poll period after the first ack —
    # the frontier>0 resume path is ALSO pinned deterministically by the
    # stop_t and reshard tests).
    rep_kill = sj.replay(journal_path)
    incomplete = [k for k in (0, 1) if rep_kill.frontier.get(k, 0) < 4]
    # Successor on the same run dir: journal replay + orphan fencing.
    res = run_sharded(copy.deepcopy(cfg), run_dir=run_dir, steps=8,
                      workers=2, chunk_steps=2, platform="cpu",
                      data_dir="")
    assert res["series"] == ref
    rep = sj.replay(journal_path)
    assert len(rep.epochs) == 2  # predecessor + successor tokens
    assert rep.frontier == {0: 4, 1: 4}
    # Shards the successor had to relaunch CONTINUE the generation
    # numbering (gen 2), so per-gen logs/payload tags never collide
    # across restarts; already-complete shards are not relaunched.
    for k in incomplete:
        assert rep.gens.get(k) == 2, (rep.gens, incomplete)


@pytest.mark.slow  # 4 coordinator/tool runs; light sibling: plan-refusal test
def test_reshard_roundtrip_4x_to_2x(tmp_path):
    """Elastic resharding: a 4-worker run quiesced at the stop_t
    barrier, resharded to 2 workers (tools/reshard_checkpoint.py,
    community-by-community read-back validation), resumes to merged
    outputs bit-identical to a straight-through run."""
    from dragg_tpu.shard.coordinator import run_sharded

    cfg = _cfg(C=4)
    ref = _inprocess_reference(copy.deepcopy(cfg), steps=8, chunk=2)

    d_old = str(tmp_path / "old")
    part = run_sharded(copy.deepcopy(cfg), run_dir=d_old, steps=8,
                       workers=4, chunk_steps=2, platform="cpu",
                       data_dir="", stop_t=4)
    assert part["stopped_early"] and part["steps"] == 4

    d_new = str(tmp_path / "new")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools",
                                      "reshard_checkpoint.py"),
         "--run-dir", d_old, "--out-dir", d_new, "--workers", "2"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"]
    assert verdict["new_ranges"] == [[0, 2], [2, 4]]
    assert all(verdict["validated_per_community"].values())

    res = run_sharded(copy.deepcopy(cfg), run_dir=d_new, steps=8,
                      workers=2, chunk_steps=2, platform="cpu",
                      data_dir="")
    assert res["series"] == ref, "resharded resume diverged"
