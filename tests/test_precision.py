"""Mixed-precision MXU policy (ISSUE 11 — ops/precision.py).

Three contracts, in the order they can fail:

1. ``tpu.precision="f32"`` (the default) is BIT-IDENTICAL to the
   pre-policy engine: ``mxu_einsum(..., precision="f32")`` is literally
   the historical HIGHEST-precision einsum, pinned bitwise here, and the
   solver reproduces its default output exactly.
2. ``"bf16x3"`` passes HiGHS objective parity on ALL SIX home types at a
   documented looser budget (the round-10 first-order-family convention:
   objectives, never iterates) — while every residual/check tensor stays
   f32 (the rounds-2/9 divergence mode is a low-precision residual, and
   ``f32_guard`` fails the TRACE if one leaks in).
3. The plumbing cannot drift: the compile cache scopes bf16x3
   executables away from the f32 LRU domain, a junk policy fails at
   config validation, and tools/bench_trend.py treats ``precision`` as a
   hard series key with era default f32 (the round-12 ``communities`` /
   round-13 ``mix`` pattern).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
from scipy.optimize import linprog

import jax
import jax.numpy as jnp

from dragg_tpu.config import default_config
from dragg_tpu.fixtures import assemble_community_qp
from dragg_tpu.ops.precision import (PRECISIONS, _split_bf16, f32_guard,
                                     mxu_einsum, validate_precision)
from dragg_tpu.ops.qp import densify_A
from dragg_tpu.ops.reluqp import reluqp_solve_qp

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- the helper
def test_f32_policy_is_bitwise_the_historical_einsum():
    """precision="f32" must reproduce jnp.einsum(..., HIGHEST) EXACTLY —
    this is what makes the default engine pre-change bit-identical by
    construction."""
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(4, 9, 7).astype(np.float32))
    b = jnp.asarray(rng.randn(4, 7).astype(np.float32))
    ours = mxu_einsum("bmn,bn->bm", a, b, precision="f32")
    ref = jnp.einsum("bmn,bn->bm", a, b,
                     precision=jax.lax.Precision.HIGHEST)
    assert ours.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(ours), np.asarray(ref))


def test_bf16x3_split_is_exactly_recomposable_and_accurate():
    """The hi/lo split must recompose to ~f32 (16-ish mantissa bits kept)
    and the 3-product contraction must sit orders of magnitude closer to
    f32 than a plain single-pass bf16 matmul — the whole point of x3."""
    rng = np.random.RandomState(1)
    x = rng.randn(64, 48).astype(np.float32) * 37.0
    hi, lo = _split_bf16(jnp.asarray(x))
    assert hi.dtype == jnp.bfloat16 and lo.dtype == jnp.bfloat16
    recomposed = np.asarray(hi, np.float32) + np.asarray(lo, np.float32)
    rel = np.max(np.abs(recomposed - x) / np.maximum(np.abs(x), 1e-6))
    assert rel < 2e-5, rel  # two bf16 limbs ≈ 16 mantissa bits

    a = jnp.asarray(rng.randn(8, 33, 48).astype(np.float32))
    b = jnp.asarray(rng.randn(8, 48).astype(np.float32))
    exact = np.asarray(mxu_einsum("bmn,bn->bm", a, b, precision="f32"),
                       np.float64)
    x3 = np.asarray(mxu_einsum("bmn,bn->bm", a, b, precision="bf16x3"),
                    np.float64)
    plain = np.asarray(jnp.einsum(
        "bmn,bn->bm", a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32), np.float64)
    # Absolute error on O(1)-normed operands (a relative metric divides
    # by near-zero cancelling outputs and measures nothing): the 3-pass
    # product must land ~2⁻¹⁶-accurate — measured 9.4e-5 vs plain
    # bf16's 6.8e-2 on this fixture, a ~700x gap.
    err_x3 = np.max(np.abs(x3 - exact))
    err_plain = np.max(np.abs(plain - exact))
    assert err_x3 < 5e-4, err_x3
    assert err_x3 < err_plain / 50, (err_x3, err_plain)


def test_f32_guard_and_registry():
    x = jnp.zeros((3,), jnp.float32)
    assert f32_guard(x, "test tensor") is x
    with pytest.raises(TypeError, match="must be float32"):
        f32_guard(x.astype(jnp.bfloat16), "test tensor")
    assert validate_precision("f32") == "f32"
    with pytest.raises(ValueError, match="precision"):
        validate_precision("fp8")
    assert PRECISIONS == ("f32", "bf16x3")


# ------------------------------------------------- solver-level contracts
@pytest.fixture(scope="module")
def six_type_qp():
    """t=0 community QP covering ALL SIX home types (base, pv_only,
    battery_only, pv_battery, ev, heat_pump) — the scenario-round parity
    fixture shape (tests/test_scenarios.py).  Module-scoped: the engine
    build behind the assembly is the expensive part and every solver-
    level test below reads the same matrices."""
    return assemble_community_qp(
        horizon_hours=4, n_homes=8, homes_pv=1, homes_battery=1,
        homes_pv_battery=1, homes_ev=2, homes_heat_pump=2)


def test_f32_default_solver_output_is_bit_identical(six_type_qp):
    """The precision kwarg's default path must not perturb a single bit
    of the default solve (same compiled math, same numbers)."""
    qp, pat, _lay, _s = six_type_qp
    base = reluqp_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box,
                           qp.q, iters=3000)
    pinned = reluqp_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box,
                             qp.q, iters=3000, precision="f32")
    for a, b in zip(base, pinned):
        if a is None:
            assert b is None
            continue
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16x3_highs_parity_all_six_types(six_type_qp):
    """bf16x3 objective parity vs HiGHS, home by home, across the six
    types.  Budget 2% — DOCUMENTED looser than the f32 families' 1%
    (round-10 convention): the 3-pass product carries ~2⁻¹⁶ relative
    error per contraction, so the converged objective sits a little
    further from the LP optimum while the f32 residual check still
    certifies feasibility at the unchanged tolerance."""
    qp, pat, _lay, _s = six_type_qp
    sol = reluqp_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          iters=4000, precision="bf16x3")
    A = np.asarray(densify_A(pat, qp.vals), dtype=np.float64)
    beq = np.asarray(qp.b_eq, np.float64)
    l = np.asarray(qp.l_box, np.float64)
    u = np.asarray(qp.u_box, np.float64)
    q = np.asarray(qp.q, np.float64)
    x = np.asarray(sol.x, np.float64)
    solved = np.asarray(sol.solved)
    n_checked = 0
    for i in range(A.shape[0]):
        bounds = [(lo if np.isfinite(lo) else None,
                   hi if np.isfinite(hi) else None)
                  for lo, hi in zip(l[i], u[i])]
        ref = linprog(q[i], A_eq=A[i], b_eq=beq[i], bounds=bounds,
                      method="highs")
        if not ref.success:
            assert not solved[i], f"home {i}: HiGHS infeasible, we solved"
            continue
        assert solved[i], f"home {i}: HiGHS feasible but unsolved"
        gap = (float(q[i] @ x[i]) - float(ref.fun)) / max(abs(ref.fun), 1e-3)
        assert gap < 0.02, f"home {i}: bf16x3 cost gap {gap:.4%}"
        assert gap > -0.01, f"home {i}: beat the optimum — infeasible"
        viol = np.max(np.abs(A[i] @ x[i] - beq[i]))
        assert viol < 2e-2, f"home {i}: equality violation {viol}"
        n_checked += 1
    assert n_checked >= 6


def test_bf16x3_residual_and_warm_tensors_stay_f32(six_type_qp):
    """Regression for the cast discipline: EVERY solution leaf that feeds
    the residual/check/warm-start path must come back f32 under bf16x3
    — a bf16 leak would reproduce the rounds-2/9 divergence and, via the
    warm-start carry, poison the next step's trace."""
    qp, pat, _lay, _s = six_type_qp
    # Same iters cap as the parity test above so the jitted solve is a
    # cache hit, not a third compile (dtypes don't need a fresh trace).
    sol = reluqp_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          iters=4000, precision="bf16x3")
    for name in ("x", "y_eq", "y_box", "r_prim", "r_dual", "rho"):
        leaf = getattr(sol, name)
        assert leaf.dtype == jnp.float32, (name, leaf.dtype)
    assert np.asarray(sol.solved).dtype == bool


def test_bf16x3_admm_dense_inv_converges():
    """The ADMM's dense_inv apply path under bf16x3: same matrices, same
    tolerance, all homes still solve (the f32 refinement/residual path
    absorbs the 3-pass product error)."""
    from dragg_tpu.ops.admm import admm_solve_qp

    qp, pat, _lay, _s = assemble_community_qp(horizon_hours=4, n_homes=6)
    sol32 = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          iters=3000, banded_factor=False,
                          solve_backend="dense_inv")
    solx3 = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          iters=3000, banded_factor=False,
                          solve_backend="dense_inv", precision="bf16x3")
    assert np.asarray(sol32.solved).all()
    assert np.asarray(solx3.solved).all()
    q64 = np.asarray(qp.q, np.float64)
    o32 = (q64 * np.asarray(sol32.x, np.float64)).sum(1)
    ox3 = (q64 * np.asarray(solx3.x, np.float64)).sum(1)
    np.testing.assert_allclose(ox3, o32, rtol=2e-2, atol=1e-2)


@pytest.mark.slow  # tier-1 budget: three engine+chunk compiles; the solver-level bitwise pin above keeps the bit-identity axis in tier-1 (round-11 heavy-sibling convention)
def test_engine_f32_default_bit_identical_and_pattern_count(tiny_config):
    """Engine-level acceptance pin: the default engine and an explicit
    precision="f32" engine produce BIT-IDENTICAL step outputs, and a
    bf16x3 engine compiles the SAME bucket pattern set (the policy
    changes matmul lowering, never shapes/patterns)."""
    import copy

    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    cfg = copy.deepcopy(tiny_config)
    cfg["home"]["hems"]["solver"] = "reluqp"
    env = load_environment(cfg)
    waterdraw = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24 * env.dt, env.dt, waterdraw)
    batch = build_home_batch(homes, 4 * env.dt, env.dt, 6)

    def run(precision=None):
        c = copy.deepcopy(cfg)
        if precision is not None:
            c["tpu"]["precision"] = precision
        eng = make_engine(batch, env, c, 0)
        rps = np.zeros((2, eng.params.horizon), np.float32)
        _, out = eng.run_chunk(eng.init_state(), 0, rps)
        return eng, out

    eng_d, out_d = run()
    eng_f, out_f = run("f32")
    np.testing.assert_array_equal(np.asarray(out_d.agg_load),
                                  np.asarray(out_f.agg_load))
    np.testing.assert_array_equal(np.asarray(out_d.correct_solve),
                                  np.asarray(out_f.correct_solve))
    eng_x, out_x = run("bf16x3")
    assert len(eng_x.bucket_info()) == len(eng_d.bucket_info())
    assert np.isfinite(np.asarray(out_x.agg_load)).all()


# --------------------------------------------------- config/cache plumbing
def test_engine_params_validates_precision_and_iter_kernel():
    from dragg_tpu.engine import engine_params

    cfg = default_config()
    p = engine_params(cfg, 0)
    assert p.precision == "f32" and p.iter_kernel == "auto"
    cfg["tpu"]["precision"] = "bf16x3"
    assert engine_params(cfg, 0).precision == "bf16x3"
    cfg["tpu"]["precision"] = "fp8"
    with pytest.raises(ValueError, match="precision"):
        engine_params(cfg, 0)
    cfg["tpu"]["precision"] = "f32"
    cfg["tpu"]["iter_kernel"] = "mosaic"
    with pytest.raises(ValueError, match="iter_kernel"):
        engine_params(cfg, 0)
    # The fused window is f32-only: the combination fails at build.
    cfg["tpu"]["iter_kernel"] = "pallas"
    cfg["tpu"]["precision"] = "bf16x3"
    with pytest.raises(ValueError, match="pallas"):
        engine_params(cfg, 0)


def test_precision_scopes_the_compile_cache(tmp_path, monkeypatch):
    """bf16x3 executables get their own LRU domain for the dense
    families; the ipm (which ignores the policy) and the f32 default
    keep their historical directory names."""
    from dragg_tpu.utils import compile_cache as cc

    monkeypatch.setenv("DRAGG_COMPILE_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)

    def cfg(solver, **tpu):
        return {"home": {"hems": {"solver": solver}}, "tpu": tpu}

    assert os.path.basename(
        cc._resolve_cache_dir(cfg("reluqp"))[1]) == "reluqp-bank5"
    assert os.path.basename(
        cc._resolve_cache_dir(cfg("reluqp", precision="bf16x3"))[1]) \
        == "reluqp-bank5-bf16x3"
    assert os.path.basename(
        cc._resolve_cache_dir(cfg("admm", precision="bf16x3"))[1]) \
        == "admm-bf16x3"
    # ipm ignores the policy — scope unchanged either way.
    assert os.path.basename(
        cc._resolve_cache_dir(cfg("ipm", precision="bf16x3"))[1]) == "ipm"


def test_run_shape_keys_checkpoints_on_precision():
    """A checkpoint written under one precision must invalidate (not
    cross-seed) a resume under the other — the warm iterates sit at
    different fixed-point accuracies (aggregator._run_shape)."""
    import inspect

    from dragg_tpu import aggregator

    src = inspect.getsource(aggregator.Aggregator._run_shape)
    assert '"precision"' in src


# ------------------------------------------------------ bench_trend gate
def _trend(tmp_path, artifacts):
    paths = []
    for i, obj in enumerate(artifacts):
        p = tmp_path / f"BENCH_r{i + 1:02d}.json"
        p.write_text(json.dumps(obj))
        paths.append(str(p))
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "bench_trend.py"),
         *paths, "--gate"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    return proc.returncode, json.loads(proc.stdout.strip().splitlines()[-1])


def test_trend_gate_precision_is_a_hard_key(tmp_path):
    """Satellite: bf16x3 rows form their own trend series (round-12
    ``communities`` / round-13 ``mix`` pattern).  A bf16x3 artifact 5x
    slower than the f32 history must NOT gate; a regression WITHIN the
    bf16x3 series must; and era-default f32 still pairs with pre-field
    artifacts that lack the key entirely."""
    def line(value, solve, **kw):
        return dict(metric="m", platform="cpu", solver="reluqp",
                    value=value, semantics="integer", data="bundled",
                    phase_s_per_step={"solve": solve}, **kw)

    # f32 history + slower first bf16x3 row: different hard key → pass.
    rc, trend = _trend(tmp_path, [line(10.0, 0.1, precision="f32"),
                                  line(2.0, 0.5, precision="bf16x3")])
    assert rc == 0 and not trend["rows"], trend
    # Regression INSIDE the bf16x3 series still gates.
    rc, trend = _trend(tmp_path, [line(10.0, 0.1, precision="bf16x3"),
                                  line(2.0, 0.5, precision="bf16x3")])
    assert rc == 1 and trend["n_regressions"] == 1, trend
    # Era default: a pre-field artifact (no precision key) pairs with an
    # explicit f32 row — one comparable stable pair, no gate.
    rc, trend = _trend(tmp_path, [line(10.0, 0.1),
                                  line(10.2, 0.1, precision="f32")])
    assert rc == 0 and len(trend["rows"]) == 1, trend
