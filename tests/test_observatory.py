"""The round-9 observatory layer: per-home solver attribution (device-side
fixed-bin histograms + worst-k riding the StepOutputs transfer), staged
compile telemetry (telemetry/compile_obs), and the bench trend gate
(tools/bench_trend.py).

Parity follows the round-7/round-8 precedent: sharded-vs-single float
telemetry gets tolerance (per-compile fp wobble near bin edges can move
a home one half-decade bin), while structural invariants (counts,
totals, index validity) are exact.
"""

import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dragg_tpu import telemetry
from dragg_tpu.config import default_config
from dragg_tpu.data import load_environment, load_waterdraw_profiles
from dragg_tpu.engine import (
    OBS_ITER_BINS,
    OBS_RES_BINS,
    make_engine,
)
from dragg_tpu.homes import build_home_batch, create_homes

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mixed_setup(n=64, pv=26, bat=6, pvb=6, horizon=4):
    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = pv
    cfg["community"]["homes_battery"] = bat
    cfg["community"]["homes_pv_battery"] = pvb
    cfg["home"]["hems"]["prediction_horizon"] = horizon
    env = load_environment(cfg, data_dir=None)
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24, 1, wd)
    batch = build_home_batch(homes, horizon, 1,
                             int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    return cfg, env, batch


@pytest.fixture(scope="module")
def obs_runs():
    """Bucketed single-device vs 8-device-mesh chunk outputs on the same
    64-home mixed community, observatory enabled (module-scoped: two
    engine compiles shared by the parity/structure tests)."""
    from dragg_tpu.parallel import make_mesh, make_sharded_engine

    cfg, env, batch = _mixed_setup()
    eng = make_engine(batch, env, cfg, 0)  # auto → bucketed at 64 homes
    assert eng.bucketed and eng.obs_enabled
    sh = make_sharded_engine(batch, env, cfg, 0, mesh=make_mesh(8))
    rps = np.zeros((3, eng.params.horizon), np.float32)
    _, out = eng.run_chunk(eng.init_state(), 0, rps)
    _, out_sh = sh.run_chunk(sh.init_state(), 0, rps)
    return eng, sh, out, out_sh


def _per_bucket_worst(eng, out):
    """Worst-capture slots regrouped per bucket ordinal (k varies with
    bucket slot counts): {ordinal: (idx, rp, iters)} per step."""
    wb = np.asarray(out.worst_bucket)
    wi = np.asarray(out.worst_idx)
    wrp = np.asarray(out.worst_rp)
    wit = np.asarray(out.worst_iters)
    by_ord = {}
    for o in range(len(eng.bucket_info())):
        sel = wb[0] == o  # static per-step layout: same columns every step
        by_ord[o] = (wi[:, sel], wrp[:, sel], wit[:, sel])
    return by_ord


def test_obs_structure_single_device(obs_runs):
    """Structural invariants of the device-side fold: every real home is
    counted exactly once per (step, bucket) histogram, worst indices are
    valid community indices inside their bucket's range, and worst
    residuals are consistent with the histogram's observations."""
    eng, _sh, out, _out_sh = obs_runs
    binfo = eng.bucket_info()
    ch = np.asarray(out.conv_hist)        # (T, nb, RBINS)
    ih = np.asarray(out.iters_hist)       # (T, nb, IBINS)
    isum = np.asarray(out.iters_sum)
    dc = np.asarray(out.diverged_count)
    T, nb, _ = ch.shape
    assert nb == len(binfo) == 4
    assert ch.shape[2] == OBS_RES_BINS and ih.shape[2] == OBS_ITER_BINS
    for bi, b in enumerate(binfo):
        # Exactly n_real observations per step in BOTH histograms.
        np.testing.assert_array_equal(ch[:, bi].sum(axis=1),
                                      np.full(T, b["n_real"]))
        np.testing.assert_array_equal(ih[:, bi].sum(axis=1),
                                      np.full(T, b["n_real"]))
        assert np.all(dc[:, bi] <= b["n_real"])
        assert np.all(isum[:, bi] >= 0)
    by_ord = _per_bucket_worst(eng, out)
    for bi, b in enumerate(binfo):
        wi, wrp, _wit = by_ord[bi]
        assert wi.shape[1] == min(eng.params.obs_worst_k, b["n_slots"])
        filled = wi >= 0
        # Unsharded buckets carry no padding, so every slot is real.
        assert np.all(filled[:, :min(b["n_real"], wi.shape[1])])
        lo, hi = b["comm_start"], b["comm_start"] + b["n_real"]
        assert np.all((wi[filled] >= lo) & (wi[filled] < hi))
        for t in range(T):
            f = filled[t]
            # No home captured twice, residuals sorted descending.
            assert len(set(wi[t, f].tolist())) == int(f.sum())
            assert np.all(np.diff(wrp[t, f]) <= 1e-6)


def test_obs_sharded_matches_single(obs_runs):
    """Sharded-vs-single parity for the fold: counts are exact where the
    quantity is discrete and robust (totals, divergence), tolerant where
    per-compile fp wobble can move a home across a half-decade bin edge
    or add a solver iteration (round-7 residual-wobble precedent)."""
    eng, sh, out, out_sh = obs_runs
    ch, ch_sh = np.asarray(out.conv_hist), np.asarray(out_sh.conv_hist)
    assert ch.shape == ch_sh.shape
    binfo = eng.bucket_info()
    for bi, b in enumerate(binfo):
        # Shard padding must be invisible: totals still count n_real.
        np.testing.assert_array_equal(ch_sh[:, bi].sum(axis=1),
                                      ch[:, bi].sum(axis=1))
        np.testing.assert_array_equal(
            np.asarray(out_sh.diverged_count)[:, bi],
            np.asarray(out.diverged_count)[:, bi])
        # Residual histograms: earth-mover distance between the per-step
        # distributions stays within a few bin-edge crossings.
        for t in range(ch.shape[0]):
            emd = np.abs(np.cumsum(ch[t, bi]) - np.cumsum(ch_sh[t, bi])).sum()
            assert emd <= max(2, 0.05 * b["n_real"]), (b["name"], t, emd)
        # Mean iterations per home: within one iteration of each other.
        isum = np.asarray(out.iters_sum)[:, bi] / b["n_real"]
        isum_sh = np.asarray(out_sh.iters_sum)[:, bi] / b["n_real"]
        np.testing.assert_allclose(isum_sh, isum, atol=1.0)
    # The binding worst home per bucket agrees to residual tolerance
    # (identity can swap between near-tied homes; magnitude cannot).
    w, w_sh = _per_bucket_worst(eng, out), _per_bucket_worst(sh, out_sh)
    for bi in range(len(binfo)):
        top = np.where(w[bi][0][:, 0] >= 0, w[bi][1][:, 0], 0.0)
        top_sh = np.where(w_sh[bi][0][:, 0] >= 0, w_sh[bi][1][:, 0], 0.0)
        np.testing.assert_allclose(top_sh, top, rtol=1e-3, atol=1e-3)


def test_obs_disabled_compiles_out():
    """``telemetry.per_home = false`` removes the fold from the program:
    zero-width observatory leaves, simulation outputs unchanged."""
    cfg, env, batch = _mixed_setup(n=8, pv=2, bat=1, pvb=1)
    cfg["tpu"]["bucketed"] = "false"
    cfg_off = copy.deepcopy(cfg)
    cfg_off["telemetry"]["per_home"] = False
    eng_on = make_engine(batch, env, cfg, 0)
    eng_off = make_engine(batch, env, cfg_off, 0)
    assert eng_on.obs_enabled and not eng_off.obs_enabled
    rps = np.zeros((2, eng_on.params.horizon), np.float32)
    _, out_on = eng_on.run_chunk(eng_on.init_state(), 0, rps)
    _, out_off = eng_off.run_chunk(eng_off.init_state(), 0, rps)
    assert np.asarray(out_off.conv_hist).size == 0
    assert np.asarray(out_off.worst_idx).size == 0
    assert np.asarray(out_on.conv_hist).size > 0
    np.testing.assert_array_equal(np.asarray(out_off.agg_load),
                                  np.asarray(out_on.agg_load))
    np.testing.assert_array_equal(np.asarray(out_off.correct_solve),
                                  np.asarray(out_on.correct_solve))


def test_aggregator_emits_observatory_events(tmp_path):
    """A tiny run's events.jsonl carries the new event family with the
    documented shapes, and the opt-in forensic dump reconstructs the
    worst homes' identity + chunk-start state."""
    telemetry.close_run()
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 6
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["simulation"]["end_datetime"] = "2015-01-01 12"
    cfg["home"]["hems"]["prediction_horizon"] = 2
    cfg["telemetry"]["enabled"] = True
    cfg["telemetry"]["dir"] = str(tmp_path)
    cfg["telemetry"]["forensics"] = True
    from dragg_tpu.aggregator import Aggregator

    agg = Aggregator(cfg, data_dir=None, outputs_dir=str(tmp_path / "out"))
    try:
        agg.run()
    finally:
        telemetry.close_run()
    recs = [json.loads(l) for l in open(tmp_path / telemetry.EVENTS_FILE)]
    conv = [r for r in recs if r["event"] == "solver.convergence"]
    assert conv, "no solver.convergence events"
    n_steps = conv[0]["t1"] - conv[0]["t0"]
    assert len(conv[0]["rprim_hist"]) == OBS_RES_BINS
    assert len(conv[0]["iters_hist"]) == OBS_ITER_BINS
    assert sum(conv[0]["rprim_hist"]) == conv[0]["n_homes"] * n_steps
    worst = [r for r in recs if r["event"] == "solver.worst"]
    assert worst and worst[0]["homes"]
    for h in worst[0]["homes"]:
        assert 0 <= h["home"] < 6
        assert conv[0]["t0"] <= h["t"] < conv[0]["t1"]
        assert {"bucket", "r_prim", "r_dual", "iters"} <= set(h)
    fdir = os.path.join(agg.run_dir, "forensics")
    dumps = sorted(os.listdir(fdir))
    assert dumps
    dump = json.load(open(os.path.join(fdir, dumps[0])))
    assert dump["solver"] == cfg["home"]["hems"]["solver"]
    assert len(dump["reward_prices"]) == dump["t1"] - dump["t0"]
    for h in dump["homes"]:
        assert h["name"] and h["config"]["type"] == h["type"]
        assert set(h["state_at_chunk_start"]) == {
            "temp_in", "temp_wh", "e_batt", "counter"}


def test_staged_compile_selftest_and_events(tmp_path):
    """compile_obs.selftest: all three stages timed, a cache verdict, a
    finite first-execute output, and the compile.* events on the
    stream."""
    telemetry.close_run()
    telemetry.init_run(str(tmp_path))
    try:
        from dragg_tpu.telemetry.compile_obs import STAGES, selftest

        rep = selftest()
    finally:
        telemetry.close_run()
    assert rep["ok"], rep
    assert set(rep["stages"]) == set(STAGES)
    assert rep["cache"] in ("hit", "miss", "unknown")
    recs = [json.loads(l) for l in open(tmp_path / telemetry.EVENTS_FILE)]
    stages = [r for r in recs if r["event"] == "compile.stage"]
    assert [r["stage"] for r in stages] == list(STAGES)
    assert all("[" in r["buckets"] for r in stages)  # pattern shapes
    done = [r for r in recs if r["event"] == "compile.done"]
    assert len(done) == 1 and done[0]["cache"] == rep["cache"]


@pytest.mark.slow
def test_compile_stall_names_stage_and_pattern(tmp_path):
    """The acceptance chaos scenario: an injected hang inside the XLA
    compile stage is stall-killed by the supervisor and the resulting
    failure.COMPILE_HANG event names the stuck STAGE and the bucket
    pattern shapes — not just the taxonomy kind (the round-4 gap)."""
    from dragg_tpu.resilience.supervisor import run_supervised

    telemetry.close_run()
    telemetry.init_run(str(tmp_path))
    child = ("import sys; sys.path.insert(0, %r)\n"
             "from dragg_tpu.resilience.heartbeat import beat\n"
             "beat({'stage': 'setup'})\n"
             "import jax; jax.config.update('jax_platforms', 'cpu')\n"
             "from dragg_tpu.telemetry.compile_obs import selftest\n"
             "selftest()\n" % ROOT)
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["DRAGG_FAULT_INJECT"] = "hang@compile_compile"
    try:
        # stall_s must outlast the beat-free setup (import + tiny engine
        # build, ~10 s here; more under full-suite load) but be far
        # under the hang's duration.
        res = run_supervised([sys.executable, "-c", child],
                             deadline_s=600.0, stall_s=45.0,
                             label="obs-chaos", env=env)
    finally:
        telemetry.close_run()
    assert not res.ok and res.stalled
    recs = [json.loads(l) for l in open(tmp_path / telemetry.EVENTS_FILE)]
    fails = [r for r in recs if r["event"] == "failure.COMPILE_HANG"]
    assert fails, [r["event"] for r in recs]
    prog = fails[0]["progress"]
    assert prog["stage"] == "compile:compile"
    assert "[" in prog["buckets"]  # "<type>[<slots>x<m_eq>]" shapes


# ------------------------------------------------------------ bench trend
def _trend(tmp_path, artifacts, extra=()):
    """Run tools/bench_trend.py over explicit artifact files; returns
    (rc, parsed JSON line)."""
    paths = []
    for i, obj in enumerate(artifacts):
        p = tmp_path / f"BENCH_r{i + 1:02d}.json"
        p.write_text(json.dumps(obj))
        paths.append(str(p))
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "bench_trend.py"),
         *paths, *extra],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    line = proc.stdout.strip().splitlines()[-1]
    return proc.returncode, json.loads(line)


def _bench_line(value, solve, ordinal, **over):
    rec = dict(metric="m", platform="cpu", solver="ipm", value=value,
               phase_s_per_step={"solve": solve})
    rec.update(over)
    return {"tail": "junk\n" + json.dumps(rec) + "\n"}


def test_bench_trend_verdicts_and_gate(tmp_path):
    """Improvement/stable/regression against the threshold, and --gate
    exits 1 exactly when a comparable pair regresses."""
    arts = [_bench_line(2.0, 0.50, 1),
            _bench_line(2.1, 0.48, 2),              # within ±10 % → stable
            _bench_line(3.0, 0.30, 3),              # improvement
            _bench_line(2.0, 0.45, 4)]              # regression
    rc, trend = _trend(tmp_path, arts)
    assert rc == 0  # no gate
    verdicts = [(r["rate_verdict"], r["solve_verdict"])
                for r in trend["rows"]]
    assert verdicts == [("stable", "stable"),
                        ("improvement", "improvement"),
                        ("regression", "regression")]
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    assert rc == 1 and trend["n_regressions"] == 1
    rc, _ = _trend(tmp_path, arts[:3], extra=("--gate",))
    assert rc == 0


def test_bench_trend_comparability_rules(tmp_path):
    """Semantics/data flips split the hard key (no cross-comparison);
    a bucketed flip compares but is annotated (CLAUDE.md round-8 rule);
    era defaults fill missing fields on old artifacts."""
    arts = [
        _bench_line(2.0, 0.50, 1),                  # era default: relaxation
        _bench_line(1.0, 0.90, 2, semantics="integer"),  # workload change
        _bench_line(1.4, 0.54, 3, semantics="integer", bucketed=True),
    ]
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    # r1→r2 must NOT pair (semantics flip would read as a regression);
    # r2→r3 pairs with the bucketed-flip note.
    assert rc == 0, trend
    assert len(trend["rows"]) == 1
    row = trend["rows"][0]
    assert row["key"]["semantics"] == "integer"
    assert row["solve_verdict"] == "improvement"
    assert any("bucketed" in n for n in row["notes"])


def test_bench_trend_degraded_soft_key(tmp_path):
    """A `degraded` artifact (supervised run fell back TPU→CPU
    mid-flight — ISSUE 7 satellite) still pairs within its platform
    series, annotated with the failure kind instead of gated on."""
    arts = [
        _bench_line(2.0, 0.50, 1),
        _bench_line(1.9, 0.52, 2, fallback=True,
                    degraded={"from": "tpu", "to": "cpu",
                              "failure": "COMPILE_HANG",
                              "transition_step": 48}),
    ]
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    assert rc == 0, trend                       # annotates, never poisons
    assert len(trend["rows"]) == 1
    notes = trend["rows"][0]["notes"]
    assert any("degraded artifact" in n and "COMPILE_HANG" in n
               and "step 48" in n for n in notes), notes
    assert trend["rows"][0]["rate_verdict"] == "stable"


def test_bench_trend_transport_soft_key(tmp_path):
    """Networked shard transport (ISSUE 16): ``transport`` is a SOFT
    series key — spool vs tcp only moves chunk payloads between the
    SAME device work, so a flip pairs within its hard-key series with
    an annotation (the ``bucketed``/``degraded`` pattern), never
    fragments it, and a genuine regression under either transport
    still gates.  Era default: artifacts that predate the field read
    transport="spool"."""
    arts = [
        _bench_line(2.0, 0.50, 1, shards=2),                  # era → spool
        _bench_line(1.95, 0.51, 2, shards=2, transport="tcp"),
    ]
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    assert rc == 0, trend
    assert len(trend["rows"]) == 1                # paired, not fragmented
    row = trend["rows"][0]
    assert "transport" not in row["key"]          # soft: not in the key
    assert any("transport" in n for n in row["notes"]), row["notes"]
    assert row["rate_verdict"] == "stable"
    # A real tcp-era regression still gates (the flip never launders one).
    arts.append(_bench_line(1.0, 0.9, 3, shards=2, transport="tcp"))
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    assert rc == 1 and trend["n_regressions"] == 1
    # Same-transport pairs carry no flip note.
    assert not any("transport" in n for n in trend["rows"][1]["notes"])


def test_bench_trend_communities_hard_key(tmp_path):
    """Fleet rows (ISSUE 8): ``communities`` is a HARD series key — a
    C-community artifact never pairs with single-community history (a
    fleet's rate at the same per-community shape is a different
    workload), while same-C fleet rows pair and gate normally.  Era
    default: artifacts that predate the field read communities=1."""
    arts = [
        _bench_line(2.0, 0.50, 1),                      # pre-fleet era → C=1
        _bench_line(0.3, 0.50, 2, communities=10),      # fleet row: no pair,
                                                        # would read as an
                                                        # -85% "regression"
        _bench_line(0.29, 0.51, 3, communities=10),     # fleet vs fleet: pairs
    ]
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    assert rc == 0, trend
    assert len(trend["rows"]) == 1
    row = trend["rows"][0]
    assert row["key"]["communities"] == 10
    assert row["rate_verdict"] == "stable"
    # And a genuine fleet-series regression still gates.
    arts.append(_bench_line(0.15, 0.51, 4, communities=10))
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    assert rc == 1 and trend["n_regressions"] == 1


def test_bench_trend_shards_hard_key(tmp_path):
    """Cross-process shard rows (ISSUE 15): ``shards`` is a HARD series
    key — an N-shard coordinator artifact (bench.py --shards: wall
    includes process supervision + spool exchange, per-shard engines
    compile at C/N·B_type shapes) never pairs with in-process history at
    the same total, while same-N rows pair and gate normally.  Era
    default: artifacts that predate the field read shards=1."""
    arts = [
        _bench_line(2.0, 0.50, 1),                   # pre-shard era → N=1
        _bench_line(0.9, 0.50, 2, shards=4),         # shard row: no pair
        _bench_line(0.88, 0.51, 3, shards=4),        # shard vs shard: pairs
    ]
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    assert rc == 0, trend
    assert len(trend["rows"]) == 1
    row = trend["rows"][0]
    assert row["key"]["shards"] == 4
    assert row["rate_verdict"] == "stable"
    # A genuine shard-series regression still gates.
    arts.append(_bench_line(0.4, 0.51, 4, shards=4))
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    assert rc == 1 and trend["n_regressions"] == 1


def test_bench_trend_mix_hard_key(tmp_path):
    """Scenario-pack rows (ISSUE 10): ``mix`` is a HARD series key — a
    bench row measured on an EV/heat-pump mix (or under a scenario pack's
    event timeline) never pairs with the legacy 4-type history, while
    same-mix rows pair and gate normally.  Era default: artifacts that
    predate the field read mix="legacy"."""
    scenario_mix = "ev=0.1,heat_pump=0.1,pv_only=0.3+pack:stress_dr_outage"
    arts = [
        _bench_line(2.0, 0.50, 1),                      # pre-scenario era
        _bench_line(0.8, 0.50, 2, mix=scenario_mix),    # pack row: no pair
        _bench_line(0.78, 0.51, 3, mix=scenario_mix),   # pack vs pack: pairs
    ]
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    assert rc == 0, trend
    assert len(trend["rows"]) == 1
    row = trend["rows"][0]
    assert row["key"]["mix"] == scenario_mix
    assert row["rate_verdict"] == "stable"
    # A genuine scenario-series regression still gates.
    arts.append(_bench_line(0.4, 0.51, 4, mix=scenario_mix))
    rc, trend = _trend(tmp_path, arts, extra=("--gate",))
    assert rc == 1 and trend["n_regressions"] == 1


def test_bench_trend_committed_series():
    """The committed BENCH_r01–r05 artifacts reproduce the known
    trajectory: the r02→r03 1000-home window improved, the r04→r05
    semantics flip (relaxation → integer) is NOT treated as a perf
    signal, r01 (failed round) is skipped, and the gate passes."""
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "bench_trend.py"),
         "--gate"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    trend = json.loads(proc.stdout.strip().splitlines()[-1])
    assert trend["n_regressions"] == 0
    keys = {(r["key"]["metric"], r["from_source"], r["to_source"])
            for r in trend["rows"]}
    assert ("sim_timesteps_per_s_1000homes_24h_horizon",
            "BENCH_r02.json", "BENCH_r03.json") in keys
    # The 10k r04 (relaxation era) and r05 (integer) must not pair.
    assert not any("BENCH_r04.json" in (r["from_source"],)
                   and "BENCH_r05.json" == r["to_source"]
                   for r in trend["rows"])
    assert any(s["source"] == "BENCH_r01.json" for s in trend["skipped"])
