"""Real-data-file ingestion tests (round-1 verdict, missing #4).

Every fixture is written in the REFERENCE file format so the non-synthetic
branches of dragg_tpu/data.py are exercised against the layouts the reference
actually ships:

* NSRDB csv — two metadata rows, then Year/Month/Day/Hour/Minute/GHI/
  Temperature columns at half-hourly cadence (ingested at
  dragg/aggregator.py:129-157);
* minutely water-draw csv — datetime index column + one Flow_* column per
  profile (ingested at dragg/aggregator.py:365-377);
* ERCOT DAM SPP workbook — Delivery Date / Hour Ending / Settlement Point /
  Settlement Point Price (dragg/aggregator.py:167-204; xlsx needs openpyxl).
"""

import os
from datetime import datetime

import numpy as np
import pandas as pd
import pytest

from dragg_tpu.data import (
    load_environment,
    load_nsrdb,
    load_spp,
    load_waterdraw_profiles,
)

# --------------------------------------------------------------------------
# NSRDB csv
# --------------------------------------------------------------------------

_NSRDB_META = (
    "Source,Location ID,City,State,Country,Latitude,Longitude,Time Zone,Elevation\n"
    "NSRDB,734589,-,-,-,29.69,-95.34,-6,12\n"
)


def _write_nsrdb(path, hours=48, year=2015):
    """Reference-format half-hourly NSRDB csv: 2 metadata rows then data rows
    with Minute alternating 0/30 (dragg/data/nsrdb.csv:1-5)."""
    rows = ["Year,Month,Day,Hour,Minute,GHI,Relative Humidity,Temperature,Pressure"]
    start = datetime(year, 1, 1)
    for h in range(hours):
        ts = pd.Timestamp(start) + pd.Timedelta(hours=h)
        for minute in (0, 30):
            # Distinct fractional values so the int cast is observable.
            ghi = max(0.0, 800 * np.sin(np.pi * (ts.hour - 6) / 12)) + 0.7
            oat = 5.0 + 10 * np.sin(np.pi * ts.hour / 24) + 0.3
            rows.append(
                f"{ts.year},{ts.month},{ts.day},{ts.hour},{minute},"
                f"{ghi:.2f},93.69,{oat:.2f},1020.0"
            )
    with open(path, "w") as f:
        f.write(_NSRDB_META + "\n".join(rows) + "\n")


@pytest.mark.parametrize("dt", [1, 2, 4])
def test_load_nsrdb_resampling(tmp_path, dt):
    """Half-hourly rows fan out to exactly dt rows/hour with the reference's
    ceil/floor repeat split (dragg/aggregator.py:143-144)."""
    path = str(tmp_path / "nsrdb.csv")
    hours = 24
    _write_nsrdb(path, hours=hours)
    oat, ghi, data_start = load_nsrdb(path, dt)
    assert len(oat) == len(ghi) == hours * dt
    assert data_start == datetime(2015, 1, 1, 0, 0)
    # GHI/OAT are int-cast (dragg/aggregator.py:154): values carry no
    # fractional part even though the file does.
    assert np.all(oat == np.floor(oat))
    assert np.all(ghi == np.floor(ghi))


def test_load_nsrdb_matches_reference_repeat_scheme(tmp_path):
    """dt=4 against a hand-computed expansion: Minute==0 repeats ceil(4/2)=2,
    Minute==30 repeats floor(4/2)=2, preserving source order."""
    path = str(tmp_path / "nsrdb.csv")
    _write_nsrdb(path, hours=3)
    dt = 4
    oat, ghi, _ = load_nsrdb(path, dt)

    raw = pd.read_csv(path, skiprows=2)
    reps = [int(np.ceil(dt / 2)) if v == 0 else int(np.floor(dt / 2))
            for v in raw.Minute]
    expected_oat = np.repeat(raw.Temperature.to_numpy(), reps).astype(int)
    expected_ghi = np.repeat(raw.GHI.to_numpy(), reps).astype(int)
    np.testing.assert_array_equal(oat, expected_oat.astype(float))
    np.testing.assert_array_equal(ghi, expected_ghi.astype(float))


def test_load_nsrdb_odd_dt(tmp_path):
    """dt=3: Minute==0 → 2 reps, Minute==30 → 1 rep; still 3 rows/hour."""
    path = str(tmp_path / "nsrdb.csv")
    _write_nsrdb(path, hours=10)
    oat, _, _ = load_nsrdb(path, 3)
    assert len(oat) == 10 * 3


def test_load_environment_uses_real_nsrdb(tmp_path, caplog):
    """When nsrdb.csv exists under data_dir the real file is ingested (no
    synthetic substitution, no warning)."""
    _write_nsrdb(str(tmp_path / "nsrdb.csv"), hours=72)
    from dragg_tpu.config import default_config

    cfg = default_config()
    cfg["agg"]["subhourly_steps"] = 2
    with caplog.at_level("WARNING", logger="dragg_tpu.data"):
        env = load_environment(cfg, data_dir=str(tmp_path))
    assert env.n_steps == 72 * 2
    assert env.data_start == datetime(2015, 1, 1)
    assert not any("SYNTHETIC" in r.message for r in caplog.records)


def test_load_environment_warns_on_missing_file(tmp_path, caplog):
    """A configured-but-missing weather file must warn loudly (round-1
    verdict, weak #7)."""
    from dragg_tpu.config import default_config

    with caplog.at_level("WARNING", logger="dragg_tpu.data"):
        load_environment(default_config(), data_dir=str(tmp_path / "nope"))
    assert any("SYNTHETIC" in r.message for r in caplog.records)


# --------------------------------------------------------------------------
# Water-draw csv
# --------------------------------------------------------------------------

def _write_waterdraw(path, days=2, n_profiles=3):
    """Reference-format minutely flow csv: datetime index (unnamed) + Flow_*
    columns (dragg/data/waterdraw_profiles.csv:1-3)."""
    idx = pd.date_range("2020-01-01", periods=days * 24 * 60, freq="min")
    rng = np.random.RandomState(7)
    cols = {}
    for p in range(n_profiles):
        flows = np.zeros(len(idx))
        events = rng.choice(len(idx), size=16 * days, replace=False)
        flows[events] = rng.uniform(2, 8, size=events.size)
        cols[f"Flow_99{p:03d}-{100 + p}"] = flows
    pd.DataFrame(cols, index=idx).to_csv(path)


def test_load_waterdraw_profiles_real_file(tmp_path):
    path = str(tmp_path / "waterdraw_profiles.csv")
    _write_waterdraw(path)
    df = load_waterdraw_profiles(path)
    assert isinstance(df.index, pd.DatetimeIndex)
    assert df.shape == (2 * 24 * 60, 3)
    assert all(c.startswith("Flow_") for c in df.columns)
    # Hourly resample (what home synthesis applies) preserves total volume.
    hourly = df.resample("h").sum()
    np.testing.assert_allclose(hourly.sum().to_numpy(), df.sum().to_numpy())


def test_waterdraw_feeds_home_synthesis(tmp_path):
    """End-to-end: a real waterdraw csv drives create_homes and every home's
    draw schedule stays within its tank size (dragg/aggregator.py:372-377)."""
    from dragg_tpu.config import default_config
    from dragg_tpu.homes import create_homes

    path = str(tmp_path / "waterdraw_profiles.csv")
    _write_waterdraw(path)
    df = load_waterdraw_profiles(path)

    cfg = default_config()
    cfg["community"]["total_number_homes"] = 4
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 0
    homes = create_homes(cfg, num_timesteps=24, dt=1, waterdraw_df=df)
    assert len(homes) == 4
    for h in homes:
        draws = np.asarray(h["wh"]["draw_sizes"])
        assert draws.min() >= 0.0
        assert draws.max() <= h["wh"]["tank_size"] + 1e-9


# --------------------------------------------------------------------------
# ERCOT SPP workbook (.xlsx branch)
# --------------------------------------------------------------------------

_SPP_COLUMNS = ["Delivery Date", "Hour Ending", "Repeated Hour Flag",
                "Settlement Point", "Settlement Point Price"]


def _spp_frame(days=2, zone="LZ_HOUSTON"):
    rows = []
    for d in range(days):
        date = f"01/{d + 1:02d}/2015"
        for he in range(1, 25):
            rows.append([date, f"{he}:00", "N", zone, 20.0 + he])
            rows.append([date, f"{he}:00", "N", "LZ_SOUTH", 99.0])
    return pd.DataFrame(rows, columns=_SPP_COLUMNS)


def test_load_spp_xlsx_branch(tmp_path):
    """The .xlsx branch: multi-sheet workbook concatenation, zone filter,
    $/MWh → $/kWh, Hour-Ending shift (dragg/aggregator.py:182-202).
    Skips when no Excel engine is available (this image has none)."""
    openpyxl = pytest.importorskip("openpyxl")  # noqa: F841
    path = str(tmp_path / "spp.xlsx")
    df = _spp_frame(days=2)
    with pd.ExcelWriter(path) as xl:
        df.iloc[:48].to_excel(xl, sheet_name="Jan1", index=False)
        df.iloc[48:].to_excel(xl, sheet_name="Jan2", index=False)
    prices, start = load_spp(path, "LZ_HOUSTON", dt=1)
    assert start == datetime(2015, 1, 1, 0)
    assert len(prices) == 48
    # Hour Ending 1 → hour-beginning 0, price 21 $/MWh → 0.021 $/kWh.
    assert prices[0] == pytest.approx(0.021)
    assert prices[23] == pytest.approx(0.044)


def test_load_spp_xlsx_without_engine_raises_helpfully(tmp_path, monkeypatch):
    """Without openpyxl the xlsx path must fail with the documented
    remediation message, not a bare ImportError."""
    try:
        import openpyxl  # noqa: F401
        pytest.skip("openpyxl installed; the no-engine path is unreachable")
    except ImportError:
        pass
    import zipfile

    path = str(tmp_path / "spp.xlsx")
    # A real zip container so pandas' format sniffing classifies it as xlsx
    # and proceeds to engine selection (where the ImportError fires).
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("xl/workbook.xml", "<workbook/>")
    with pytest.raises(RuntimeError, match="openpyxl"):
        load_spp(path, "LZ_HOUSTON", dt=1)


def test_load_spp_csv_equivalent(tmp_path):
    """The csv variant of the same workbook columns (always runnable)."""
    path = str(tmp_path / "spp.csv")
    _spp_frame(days=2).to_csv(path, index=False)
    prices, start = load_spp(path, "LZ_HOUSTON", dt=2)
    assert start == datetime(2015, 1, 1, 0)
    assert len(prices) == 48 * 2
    assert prices[0] == prices[1] == pytest.approx(0.021)


# --------------------------------------------------------------------------
# Bundled first-party assets (round 5 — VERDICT r4 missing #1)
# --------------------------------------------------------------------------


def test_default_run_uses_bundled_assets():
    """With NO data_dir the environment must come from the repo's bundled
    `data/nsrdb.csv` (reference-default behavior: out-of-box runs ingest
    files, dragg/aggregator.py:129-165), not the synthetic generator —
    and `data_dir=""` must force the synthetic fallback."""
    from dragg_tpu.config import default_config
    from dragg_tpu.data import bundled_data_dir, waterdraw_path

    assert bundled_data_dir() is not None, "bundled data/nsrdb.csv missing"
    cfg = default_config()
    env_default = load_environment(cfg)
    env_bundled = load_environment(cfg, data_dir=bundled_data_dir())
    env_synth = load_environment(cfg, data_dir="")
    np.testing.assert_array_equal(env_default.oat, env_bundled.oat)
    np.testing.assert_array_equal(env_default.ghi, env_bundled.ghi)
    assert not np.array_equal(env_default.oat, env_synth.oat[: len(env_default.oat)])
    # Water draws resolve to the bundled minutely profiles too.
    p = waterdraw_path(cfg, None)
    assert p is not None and p.endswith("waterdraw_profiles.csv")
    df = load_waterdraw_profiles(p)
    assert df.shape[1] == 10  # reference profile count
    assert waterdraw_path(cfg, "") is None


def test_bundled_assets_are_regenerable():
    """tools/make_data_assets.py must reproduce the checked-in files
    byte-for-byte (determinism guard: the assets are generated, never
    copied)."""
    import filecmp
    import subprocess
    import sys
    import tempfile

    from dragg_tpu.data import bundled_data_dir

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as td:
        subprocess.run(
            [sys.executable, os.path.join(root, "tools", "make_data_assets.py"),
             "--out", td],
            check=True, timeout=300, capture_output=True)
        for name in ("nsrdb.csv", "waterdraw_profiles.csv"):
            assert filecmp.cmp(os.path.join(td, name),
                               os.path.join(bundled_data_dir(), name),
                               shallow=False), f"{name} not reproducible"
