"""Fused reluqp check-window kernel (ISSUE 11 — ops/pallas_iter.py).

Interpreter-mode parity on the CPU backend, the tests/test_pallas_band.py
convention: the kernel must reproduce its in-module lax reference
element-wise (window state AND the in-kernel residual-max reduction),
chunking must be bitwise-invariant, and the SOLVER must produce the
same verdicts/objectives whichever window implementation runs.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dragg_tpu.ops import pallas_iter


@pytest.fixture
def window_problem():
    """A CONSISTENT iteration fixture: S⁻¹ is the true inverse of the
    ADMM operator S = Â D⁻¹ Âᵀ at the given rho, so the window is the
    real (contractive) solver map — a random 'Sinv' diverges over a
    deep window and measures only noise amplification."""
    rng = np.random.RandomState(7)
    B, m, n = 6, 9, 21
    A = rng.randn(B, m, n).astype(np.float32) * 0.5
    reg, sigma, rho0 = 1e-3, 1e-6, 0.4
    w = (0.5 + rng.rand(B, n)).astype(np.float32)
    rho = np.full(B, rho0, np.float32)
    p_diag = np.full((B, n), reg, np.float32)
    Dinv = (1.0 / (p_diag + sigma + rho[:, None] * w * w)).astype(np.float32)
    S = np.einsum("bmn,bn,bkn->bmk", A, Dinv, A) + 1e-4 * np.eye(m)[None]
    Sinv = np.linalg.inv(S).astype(np.float32)
    qs = rng.randn(B, n).astype(np.float32)
    bs = rng.randn(B, m).astype(np.float32)
    ls = (-1.0 - rng.rand(B, n)).astype(np.float32)
    us = (1.0 + rng.rand(B, n)).astype(np.float32)
    state = (rng.randn(B, n).astype(np.float32) * 0.1,
             np.clip(rng.randn(B, n).astype(np.float32), ls, us),
             rng.randn(B, m).astype(np.float32) * 0.1,
             rng.randn(B, n).astype(np.float32) * 0.1)
    e_eq = (0.5 + rng.rand(B, m)).astype(np.float32)
    e_box = (0.5 + rng.rand(B, n)).astype(np.float32)
    cd = (0.5 + rng.rand(B, n)).astype(np.float32)
    args = tuple(jnp.asarray(v) for v in
                 (A, Sinv, Dinv, w, qs, bs, ls, us, rho, *state,
                  e_eq, e_box, cd, p_diag))
    return args, dict(sigma=float(sigma), alpha=1.6)


def test_fused_window_matches_lax_reference(window_problem):
    """Element-wise parity of the whole window (state + all four
    residual-max scalars) in interpreter mode, at the solver's real
    check cadence.  Tolerance 1e-3 relative: the kernel's row-loop
    reductions legitimately reorder the f32 sums an einsum does."""
    args, kw = window_problem
    st_f, res_f = pallas_iter.fused_window(*args, k=25, **kw)
    st_r, res_r = pallas_iter.reference_window(*args, k=25, **kw)
    for a, b, name in zip(st_f + res_f, st_r + res_r,
                          ("x", "z", "nu", "y",
                           "r_prim", "r_dual", "p_sc", "d_sc")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4, err_msg=name)


def test_fused_window_chunking_is_bitwise(window_problem):
    """Homes are independent → a forced b_chunk produces bit-identical
    outputs (the pallas_band chunking contract)."""
    args, kw = window_problem
    whole = pallas_iter.fused_window(*args, k=5, **kw)
    chunked = pallas_iter.fused_window(*args, k=5, b_chunk=128, **kw)
    for a, b in zip(whole[0] + whole[1], chunked[0] + chunked[1]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_window_lane_block_invariant(window_problem):
    """The lane block is a tiling choice, not semantics."""
    args, kw = window_problem
    a128 = pallas_iter.fused_window(*args, k=5, lane_block=128, **kw)
    a256 = pallas_iter.fused_window(*args, k=5, lane_block=256, **kw)
    for x, y in zip(a128[0] + a128[1], a256[0] + a256[1]):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-6)


def test_auto_blocks_respects_budget():
    """The scoped-VMEM model: small shapes keep the full 512 lane block;
    the H=24 superset shape (m=77, n=221) must shrink to the 128 floor
    and engage the output b_chunk guard rather than silently exceed the
    budget."""
    lb_small, ck_small = pallas_iter._auto_blocks(9, 21, 4, 256)
    assert lb_small == 512 and ck_small == 0
    lb_big, ck_big = pallas_iter._auto_blocks(77, 221, 4, 100_000)
    assert lb_big == 128
    assert ck_big > 0 and ck_big % lb_big == 0


def test_solver_level_pallas_matches_lax():
    """End-to-end: the reluqp family solves the real t=0 community QP to
    the same verdicts and objectives whichever window implementation
    runs (interpret mode on CPU), and the engine resolves/records the
    kernel honestly."""
    from dragg_tpu.fixtures import assemble_community_qp
    from dragg_tpu.ops.reluqp import reluqp_solve_qp

    qp, pat, _lay, _s = assemble_community_qp(horizon_hours=4, n_homes=6)
    lax_sol = reluqp_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box,
                              qp.q, iters=3000, iter_kernel="lax")
    pl_sol = reluqp_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box,
                             qp.q, iters=3000, iter_kernel="pallas")
    np.testing.assert_array_equal(np.asarray(lax_sol.solved),
                                  np.asarray(pl_sol.solved))
    q64 = np.asarray(qp.q, np.float64)
    o_lax = (q64 * np.asarray(lax_sol.x, np.float64)).sum(1)
    o_pl = (q64 * np.asarray(pl_sol.x, np.float64)).sum(1)
    np.testing.assert_allclose(o_pl, o_lax, rtol=1e-2, atol=5e-3)
    # The fused window is f32-only by contract.
    with pytest.raises(ValueError, match="precision"):
        reluqp_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                        iters=100, iter_kernel="pallas",
                        precision="bf16x3")


def test_engine_resolves_iter_kernel(tiny_config):
    """auto → lax (no on-chip verdict recorded yet); explicit pallas is
    honored and reported via engine.iter_kernel; bench JSON records the
    resolved value only for the reluqp family."""
    import copy

    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    cfg = copy.deepcopy(tiny_config)
    cfg["home"]["hems"]["solver"] = "reluqp"
    env = load_environment(cfg)
    waterdraw = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24 * env.dt, env.dt, waterdraw)
    batch = build_home_batch(homes, 4 * env.dt, env.dt, 6)
    eng = make_engine(batch, env, cfg, 0)
    assert eng.iter_kernel == "lax"  # auto, pending the on-chip A/B
    cfg["tpu"]["iter_kernel"] = "pallas"
    eng2 = make_engine(batch, env, cfg, 0)
    assert eng2.iter_kernel == "pallas"
