"""Interior-point solver (dragg_tpu/ops/ipm.py): HiGHS parity, infeasible
handling, and the engine's solver="ipm" path."""

import sys

import numpy as np

import jax.numpy as jnp

sys.path.insert(0, "tests")
from test_qp_parity import _assemble_real_step, _linprog_reference  # noqa: E402

from dragg_tpu.ops.ipm import ipm_solve_qp  # noqa: E402
from dragg_tpu.ops.qp import QPLayout, densify_A  # noqa: E402


def test_ipm_matches_highs():
    """≤1 % objective gap vs HiGHS on the real community QP in ≤25 Mehrotra
    iterations (the ADMM path needs ~275 cold — docs/perf_notes.md)."""
    qp, pat = _assemble_real_step(horizon_hours=24, n_homes=6)
    sol = ipm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                       iters=25)
    A = np.asarray(densify_A(pat, qp.vals), np.float64)
    n_checked = 0
    for i in range(6):
        ref = _linprog_reference(
            A[i], np.asarray(qp.b_eq, np.float64)[i],
            np.asarray(qp.l_box, np.float64)[i],
            np.asarray(qp.u_box, np.float64)[i],
            np.asarray(qp.q, np.float64)[i])
        if not ref.success:
            assert not bool(sol.solved[i])
            continue
        assert bool(sol.solved[i]), f"home {i} unsolved"
        gap = (float(np.asarray(qp.q)[i] @ np.asarray(sol.x)[i]) - ref.fun) / max(
            abs(ref.fun), 1e-3)
        assert abs(gap) < 0.01, f"home {i}: gap {gap:.4%}"
        viol = np.max(np.abs(A[i] @ np.asarray(sol.x, np.float64)[i]
                             - np.asarray(qp.b_eq, np.float64)[i]))
        assert viol < 1e-2
        n_checked += 1
    assert n_checked >= 4


def test_ipm_flags_infeasible_home():
    """A home whose WH comfort box sits above its pinned initial temperature
    is primal-infeasible; the IPM must not claim success on it."""
    qp, pat = _assemble_real_step(horizon_hours=8, n_homes=6)
    l = np.asarray(qp.l_box).copy()
    H = (pat.n - 5) // 9
    lay = QPLayout(H)
    b0 = float(np.asarray(qp.b_eq)[0, lay.r_twh0])
    l[0, lay.i_twh: lay.i_twh + H + 1] = b0 + 5.0
    sol = ipm_solve_qp(pat, qp.vals, qp.b_eq, jnp.asarray(l), qp.u_box, qp.q,
                       iters=25)
    assert not bool(sol.solved[0])
    # The other homes still solve despite the lockstep neighbor diverging.
    assert int(jnp.sum(sol.solved[1:])) >= 4
    # Divergence-freeze contract (round 3): the infeasible home must not
    # hold the batch at the iteration cap — once it trips the freeze
    # (stalled rp + exploding duals) and the rest converge, the all-frozen
    # early exit fires well before the cap.  Measured exit: 7 iterations;
    # the bound leaves slack for fp wiggle while still failing loudly if
    # the freeze regresses to cap-burning (docs/perf_notes.md, +20%
    # whole-day A/B).
    assert int(sol.iters) < 20, f"expected early exit, ran {int(sol.iters)}/25"


def test_ipm_handles_fixed_variables():
    """Winter gate: cool bounds are [0, 0] — fixed variables have no strict
    interior, so the IPM eliminates them; solutions must pin them exactly."""
    qp, pat = _assemble_real_step(horizon_hours=8, n_homes=6)
    sol = ipm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                       iters=25)
    l = np.asarray(qp.l_box)
    u = np.asarray(qp.u_box)
    fixed = np.isfinite(l) & np.isfinite(u) & (u - l <= 1e-9 * (1 + np.abs(l)))
    assert fixed.any()  # the winter gate fixes the cool block
    x = np.asarray(sol.x)
    np.testing.assert_array_equal(x[fixed], l[fixed])


def test_engine_ipm_solver(tiny_config):
    """End-to-end: hems.solver='ipm' runs the whole engine chunk."""
    import copy

    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    cfg = copy.deepcopy(tiny_config)
    cfg["home"]["hems"]["solver"] = "ipm"
    env = load_environment(cfg, data_dir=None)
    dt = int(cfg["agg"]["subhourly_steps"])
    wd = load_waterdraw_profiles(None, seed=int(cfg["simulation"]["random_seed"]))
    homes = create_homes(cfg, 24 * dt, dt, wd)
    hems = cfg["home"]["hems"]
    batch = build_home_batch(homes, int(hems["prediction_horizon"]) * dt, dt,
                             int(hems["sub_subhourly_steps"]))
    eng = make_engine(batch, env, cfg, 0)
    assert eng.params.solver == "ipm"
    state, outs = eng.run_chunk(eng.init_state(), 0,
                                np.zeros((6, eng.params.horizon), np.float32))
    assert float(np.asarray(outs.correct_solve).mean()) > 0.9
    assert np.isfinite(np.asarray(outs.agg_load)).all()


def test_engine_ipm_matches_admm_aggregate(tiny_config):
    """Same community, both solvers: daily aggregate loads agree to ~1%."""
    import copy

    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    outs = {}
    for solver in ("admm", "ipm"):
        cfg = copy.deepcopy(tiny_config)
        cfg["home"]["hems"]["solver"] = solver
        env = load_environment(cfg, data_dir=None)
        dt = int(cfg["agg"]["subhourly_steps"])
        wd = load_waterdraw_profiles(None, seed=int(cfg["simulation"]["random_seed"]))
        homes = create_homes(cfg, 24 * dt, dt, wd)
        hems = cfg["home"]["hems"]
        batch = build_home_batch(homes, int(hems["prediction_horizon"]) * dt,
                                 dt, int(hems["sub_subhourly_steps"]))
        eng = make_engine(batch, env, cfg, 0)
        _, o = eng.run_chunk(eng.init_state(), 0,
                             np.zeros((12, eng.params.horizon), np.float32))
        outs[solver] = np.asarray(o.agg_load)
    total_admm = outs["admm"].sum()
    total_ipm = outs["ipm"].sum()
    assert abs(total_ipm - total_admm) / max(abs(total_admm), 1e-6) < 0.02


def test_ipm_early_exit_and_warm_start():
    """The while-loop early exit stops within the cap and returns the same
    solutions; the interior-safeguarded warm start (x0=shifted plan) solves
    to the same answers as the cold start."""
    qp, pat = _assemble_real_step(horizon_hours=8, n_homes=6)
    cold = ipm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                        iters=40)
    # Strictly below the cap: the 8-hour problem converges in ~13-26
    # iterations, so hitting 40 would mean the early exit is broken.
    assert int(cold.iters) < 40
    warm = ipm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                        iters=40, x0=cold.x)
    both = np.asarray(cold.solved) & np.asarray(warm.solved)
    assert both.sum() >= 4
    # The LP is degenerate — iterates may differ along zero-cost directions —
    # so solutions are compared by objective, not elementwise.
    q = np.asarray(qp.q)
    fc = (q * np.asarray(cold.x)).sum(axis=1)
    fw = (q * np.asarray(warm.x)).sum(axis=1)
    np.testing.assert_allclose(fw[both], fc[both], rtol=1e-3, atol=1e-2)


def test_ipm_tail_compaction_matches_quality():
    """Tail compaction (short full-batch phase + straggler sub-batch) must
    reach at least the solve count of the plain full-budget run at ~55%
    of the unit-iteration cost (docs/perf_notes.md measurements)."""
    qp, pat = _assemble_real_step(horizon_hours=24, n_homes=64)
    base = ipm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                        iters=28)
    tail = ipm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                        iters=11, tail_frac=0.25, tail_iters=28)
    n_base = int(np.sum(np.asarray(base.solved)))
    n_tail = int(np.sum(np.asarray(tail.solved)))
    assert n_tail >= n_base
    # Solved homes agree on objective between the two schedules.
    both = np.asarray(base.solved) & np.asarray(tail.solved)
    q = np.asarray(qp.q)
    fb = (q * np.asarray(base.x)).sum(axis=1)
    ft = (q * np.asarray(tail.x)).sum(axis=1)
    np.testing.assert_allclose(ft[both], fb[both], rtol=2e-3, atol=1e-2)


def test_ipm_tail_compaction_under_mesh():
    """Per-shard tail compaction (round 3): under a device mesh the
    straggler phase runs shard-locally inside shard_map (8 homes/shard
    here) — no cross-shard gather, static shapes.  Shard-local ranking may
    pick a different straggler set than global ranking, so parity is
    judged like the solver parity tests: solve counts must not regress vs
    the no-tail sharded run, and commonly-solved homes agree on objective
    with the single-device tail run."""
    from dragg_tpu.parallel.mesh import make_mesh

    qp, pat = _assemble_real_step(horizon_hours=24, n_homes=64)
    args = (pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q)
    mesh = make_mesh(8)
    single_tail = ipm_solve_qp(*args, iters=11, tail_frac=0.25, tail_iters=28)
    sh_no_tail = ipm_solve_qp(*args, iters=28, mesh=mesh)
    sh_tail = ipm_solve_qp(*args, iters=11, tail_frac=0.25, tail_iters=28,
                           mesh=mesh)
    n_no_tail = int(np.sum(np.asarray(sh_no_tail.solved)))
    n_tail = int(np.sum(np.asarray(sh_tail.solved)))
    assert n_tail >= n_no_tail - 1  # straggler budget must not cost solves
    q = np.asarray(qp.q)
    both = np.asarray(single_tail.solved) & np.asarray(sh_tail.solved)
    assert both.sum() >= 48
    fs = (q * np.asarray(single_tail.x)).sum(axis=1)
    fm = (q * np.asarray(sh_tail.x)).sum(axis=1)
    np.testing.assert_allclose(fm[both], fs[both], rtol=2e-3, atol=1e-2)


def test_ipm_tail_under_mesh_pallas_interpret():
    """The shard-local tail phase builds PLAIN (unwrapped) pallas band ops
    inside the shard_map region — nesting the mesh-wrapped ops would be
    illegal.  Exercise that composition in interpret mode on a small
    batch."""
    from dragg_tpu.parallel.mesh import make_mesh

    qp, pat = _assemble_real_step(horizon_hours=4, n_homes=32)
    args = (pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q)
    mesh = make_mesh(4)
    xla = ipm_solve_qp(*args, iters=12, tail_frac=0.25, tail_iters=20,
                       mesh=mesh, band_kernel="xla")
    pl = ipm_solve_qp(*args, iters=12, tail_frac=0.25, tail_iters=20,
                      mesh=mesh, band_kernel="pallas")
    assert np.asarray(pl.solved).sum() >= np.asarray(xla.solved).sum() - 1
    q = np.asarray(qp.q)
    both = np.asarray(xla.solved) & np.asarray(pl.solved)
    fx = (q * np.asarray(xla.x)).sum(axis=1)
    fp = (q * np.asarray(pl.x)).sum(axis=1)
    np.testing.assert_allclose(fp[both], fx[both], rtol=2e-3, atol=1e-2)
