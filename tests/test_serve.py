"""Serving daemon (dragg_tpu/serve) — fast-tier tests.

Everything here runs with STUB workers (serve/worker.py --stub: the full
spool protocol with a deterministic jax-free responder), so the daemon's
parent-side machinery — journal durability, admission control,
backpressure, retry/requeue after worker death, degradation provenance,
drain, restart replay — is exercised in seconds.  The real-engine chaos
paths (compile-cache survival across CHILD_CRASH) live in
tests/test_serve_chaos.py (slow tier) and tools/serve_soak.py (the
acceptance harness).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from dragg_tpu.config import default_config
from dragg_tpu.resilience import faults
from dragg_tpu.serve.daemon import ServeDaemon, serve_config
from dragg_tpu.serve.journal import Journal, replay


# --------------------------------------------------------------- journal
def test_journal_roundtrip(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.accepted("a", {"id": "a", "t": 0, "home": 1})
    j.accepted("b", {"id": "b", "t": 1, "home": 2})
    j.assigned(["a", "b"], batch=1, slot=0, gen=1, platform="cpu")
    assert j.done("a", {"p_grid": 1.0})
    j.close()

    rep = replay(path)
    assert set(rep.pending) == {"b"}
    assert rep.pending["b"]["req"]["home"] == 2
    assert set(rep.terminal) == {"a"}
    assert rep.terminal["a"]["response"]["p_grid"] == 1.0
    assert rep.dropped_lines == 0


def test_journal_refuses_double_answer(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.accepted("a", {"id": "a"})
    assert j.done("a", {"v": 1})
    assert not j.done("a", {"v": 2})
    assert not j.failed("a", "late failure")
    j.close()
    rep = replay(path)
    assert rep.terminal["a"]["response"] == {"v": 1}

    # The refusal survives a restart: a NEW journal on the same file must
    # refuse too (terminal ids replayed into the dedup set).
    j2 = Journal(path)
    assert not j2.done("a", {"v": 3})
    j2.close()
    assert replay(path).terminal["a"]["response"] == {"v": 1}


def test_journal_transition_record(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.accepted("a", {"id": "a"})
    j.transition("tpu", "cpu", "WEDGED", batch=3)
    j.close()
    rep = replay(path)
    assert rep.transition["failure"] == "WEDGED"
    assert rep.transition["from"] == "tpu"


def test_journal_torn_write_property(tmp_path):
    """The crash-consistency property test (ISSUE 7 satellite): truncate
    the journal at EVERY byte boundary — replay must never raise, never
    lose a request whose accepted record survived whole, and never
    produce a duplicate id across pending/terminal."""
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.accepted("a", {"id": "a", "home": 1})
    j.accepted("b", {"id": "b", "home": 2})
    j.assigned(["a", "b"], batch=1, slot=0, gen=1, platform="cpu")
    j.done("a", {"p_grid": 1.5})
    j.transition("tpu", "cpu", "TUNNEL_DOWN", batch=1)
    j.accepted("c", {"id": "c", "home": 3})
    j.failed("b", "retries exhausted")
    j.close()
    with open(path, "rb") as f:
        blob = f.read()
    # Byte offsets at which each record's trailing newline lands — a
    # record is durable iff its newline is inside the truncated prefix.
    line_ends = [i + 1 for i, ch in enumerate(blob) if ch == ord("\n")]
    torn = str(tmp_path / "torn.jsonl")
    for cut in range(len(blob) + 1):
        with open(torn, "wb") as f:
            f.write(blob[:cut])
        rep = replay(torn)  # must not raise at any cut
        whole_records = sum(1 for e in line_ends if e <= cut)
        overlap = set(rep.pending) & set(rep.terminal)
        assert not overlap, f"cut={cut}: duplicate ids {overlap}"
        assert rep.dropped_lines <= 1, f"cut={cut}: >1 torn line"
        # Durability: every fully-written accepted id is still known.
        for n_whole, rid in ((1, "a"), (2, "b"), (6, "c")):
            if whole_records >= n_whole:
                assert rid in rep.pending or rid in rep.terminal, \
                    f"cut={cut}: {rid} lost"
        # Terminal-state monotonicity: once done/failed is durable the id
        # must never replay as pending.
        if whole_records >= 4:
            assert "a" in rep.terminal
        if whole_records >= 7:
            assert "b" in rep.terminal
        if whole_records >= 5:
            assert (rep.transition or {}).get("failure") == "TUNNEL_DOWN"


def test_journal_ignores_garbage_lines(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    with open(path, "w") as f:
        f.write('{"state":"accepted","id":"a","req":{}}\n')
        f.write("not json at all\n")
        f.write('{"state":"done","id":"a","response":{}}\n')
        f.write('{"half": "torn')
    rep = replay(path)
    assert set(rep.terminal) == {"a"}
    assert not rep.pending
    assert rep.dropped_lines == 2


# ------------------------------------------------------------ HTTP helpers
def _post(base: str, body) -> tuple[int, dict]:
    req = urllib.request.Request(base + "/solve",
                                 data=json.dumps(body).encode())
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base: str, path: str) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(base + path, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait_terminal(base: str, ids, timeout_s: float = 30.0) -> dict:
    outcomes = {}
    deadline = time.monotonic() + timeout_s
    remaining = set(ids)
    while remaining and time.monotonic() < deadline:
        for rid in list(remaining):
            _code, body = _get(base, f"/result?id={rid}")
            if body.get("status") in ("done", "failed"):
                outcomes[rid] = body
                remaining.discard(rid)
        time.sleep(0.05)
    assert not remaining, f"requests never terminated: {remaining}"
    return outcomes


def _serve_cfg(**overrides) -> dict:
    cfg = default_config()
    cfg["serve"].update({"port": 0, "poll_s": 0.02, "backoff_s": 0.1,
                         "request_retries": 3, "batch_deadline_s": 30.0,
                         "worker_stall_s": 30.0, "drain_s": 10.0,
                         **overrides})
    return cfg


@pytest.fixture
def stub_daemon_factory(tmp_path, monkeypatch):
    """Build stub-worker daemons in tmp dirs; stops them at teardown and
    keeps fault injection scoped to the test."""
    daemons = []
    monkeypatch.setenv("DRAGG_FAULT_STATE", str(tmp_path / "fault_state"))
    os.makedirs(tmp_path / "fault_state", exist_ok=True)

    def build(name="d", platform="cpu", faults_spec="", **cfg_overrides):
        if faults_spec:
            monkeypatch.setenv(faults.ENV, faults_spec)
        else:
            monkeypatch.delenv(faults.ENV, raising=False)
        faults.reset_plan()
        d = ServeDaemon(_serve_cfg(**cfg_overrides),
                        str(tmp_path / name), platform=platform, stub=True)
        d.start()
        daemons.append(d)
        return d, f"http://127.0.0.1:{d.port}"

    yield build
    for d in daemons:
        try:
            d.stop(drain=False)
        except Exception:
            pass
    faults.reset_plan()


# ------------------------------------------------------------ daemon paths
def test_serve_config_defaults_and_overrides():
    cfg = default_config()
    scfg = serve_config(cfg)
    assert scfg["workers"] == 1 and scfg["journal_fsync"] is True
    cfg["serve"] = {"queue_max": 7}
    assert serve_config(cfg)["queue_max"] == 7
    assert serve_config(cfg)["workers"] == 1  # defaults still applied


def test_end_to_end_accept_solve_result(stub_daemon_factory):
    _d, base = stub_daemon_factory("e2e")
    code, body = _post(base, {"id": "a", "t": 0, "home": 2})
    assert code == 202 and body["status"] == "accepted"
    code, body = _post(base, [{"id": "b", "t": 0, "home": 3},
                              {"id": "c", "t": 2, "home": 2}])
    assert code == 202
    outcomes = _wait_terminal(base, ["a", "b", "c"])
    assert all(o["status"] == "done" for o in outcomes.values())
    # Stub responses are deterministic in (t, home).
    assert outcomes["a"]["response"]["p_grid"] == 1.5
    assert outcomes["c"]["response"]["p_grid"] == 1.52
    # Idempotent duplicate: answered from the journal, not re-solved.
    code, body = _post(base, {"id": "a"})
    assert code == 200 and body["status"] == "done"
    assert body["response"]["p_grid"] == 1.5
    # Unknown id → 404; health/ready surface agree the service is up.
    assert _get(base, "/result?id=nope")[0] == 404
    assert _get(base, "/healthz")[0] == 200
    assert _get(base, "/readyz")[0] == 200
    code, metrics = _get(base, "/metrics.json")
    assert code == 200 and metrics["serve"]["results"] == 3
    assert metrics["counters"]["serve.requests_done"] == 3.0


def test_backpressure_queue_full_answers_429(stub_daemon_factory):
    # queue_max 2 and a worker that can't start (bad config would be
    # slower — just flood before the stub warms up).
    _d, base = stub_daemon_factory("bp", queue_max=2, retry_after_s=3.0)
    codes = [_post(base, {"id": f"q{i}", "t": 9, "home": i})[0]
             for i in range(6)]
    assert 429 in codes, codes
    # The 429 carried Retry-After.
    req = urllib.request.Request(base + "/solve",
                                 data=json.dumps({"id": "qq"}).encode())
    try:
        urllib.request.urlopen(req, timeout=10)
    except urllib.error.HTTPError as e:
        if e.code == 429:
            assert int(e.headers["Retry-After"]) >= 1


def test_invalid_home_rejected(stub_daemon_factory):
    _d, base = stub_daemon_factory("bad")
    code, body = _post(base, {"id": "x", "home": 10_000})
    assert code == 400 and "outside the serving community" in body["error"]


def test_malformed_fields_rejected_before_the_journal(stub_daemon_factory):
    """Validation must happen BEFORE the durability point: a malformed
    field answers 400 and leaves NO journal record (a poisoned accepted
    record would crash every later replay — one bad POST must never
    brick restarts) and never poisons the dispatch loop."""
    d, base = stub_daemon_factory("malformed")
    bad = [{"id": "b1", "home": 0, "deadline_s": "oops"},
           {"id": "b2", "home": 0, "t": "x"},
           {"id": "b3", "home": 0, "rp": []},
           {"id": "b4", "home": "not-an-int"},
           {"id": "b5", "home": 0, "state": "warm"},
           {"id": "b6", "home": 0, "state": {"temp_in": "cold"}}]
    for req in bad:
        code, body = _post(base, req)
        assert code == 400, (req, code, body)
    # Nothing journaled; healthy requests still flow; a restart on the
    # same dir starts clean.
    jpath = os.path.join(d.serve_dir, "journal.jsonl")
    assert not os.path.exists(jpath) or not open(jpath).read().strip()
    assert _post(base, {"id": "ok", "t": 0, "home": 0})[0] == 202
    assert _wait_terminal(base, ["ok"])["ok"]["status"] == "done"
    d.stop(drain=False)
    d2 = ServeDaemon(_serve_cfg(), d.serve_dir, platform="cpu", stub=True)
    assert set(d2.results) == {"ok"}
    d2.stop(drain=False)


def test_worker_crash_requeues_and_serves(stub_daemon_factory):
    """A worker that dies mid-stream (exit 17 at its 2nd batch) costs a
    retry, not a request: the daemon requeues the in-flight batch to the
    relaunched generation and every id still terminates done exactly
    once."""
    d, base = stub_daemon_factory(
        "crash", faults_spec="exit@serve_batch:2:once")
    ids = [f"c{i}" for i in range(6)]
    for i, rid in enumerate(ids):
        # Distinct t per pair forces several batches → batch 2 exists.
        assert _post(base, {"id": rid, "t": i // 2, "home": i})[0] == 202
    outcomes = _wait_terminal(base, ids)
    assert all(o["status"] == "done" for o in outcomes.values())
    assert d.slots[0].gen >= 2, "worker was never relaunched"
    recs = [json.loads(line) for line in
            open(os.path.join(d.serve_dir, "journal.jsonl"))]
    done_ids = [r["id"] for r in recs if r["state"] == "done"]
    assert sorted(done_ids) == sorted(ids)  # exactly once each
    retried = [r for r in recs if r["state"] == "done"
               and r["response"].get("retries", 0) > 0]
    assert retried, "no request recorded a retry after the crash"


def test_degraded_service_carries_provenance(stub_daemon_factory):
    """probe says the tunnel is down → the service degrades to CPU at
    startup and EVERY response carries the platform-transition record."""
    d, base = stub_daemon_factory("deg", platform="auto",
                                  faults_spec="probe_down:1")
    ids = ["g0", "g1"]
    for i, rid in enumerate(ids):
        assert _post(base, {"id": rid, "t": 0, "home": i})[0] == 202
    outcomes = _wait_terminal(base, ids)
    for rid, o in outcomes.items():
        deg = o["response"].get("degraded")
        assert deg, f"{rid} answered without degradation provenance"
        assert deg["failure"] == "TUNNEL_DOWN"
        assert (deg["from"], deg["to"]) == ("tpu", "cpu")
    assert d.transition is not None
    # The transition is journaled → a restarted daemon keeps reporting it.
    rep = replay(os.path.join(d.serve_dir, "journal.jsonl"))
    assert rep.transition["failure"] == "TUNNEL_DOWN"


def test_strict_tpu_answers_429_when_probe_says_no(stub_daemon_factory):
    _d, base = stub_daemon_factory(
        "strict", platform="tpu", faults_spec="probe_down,probe_down:50",
        degrade_to_cpu=False)
    time.sleep(0.3)  # let the dispatch loop resolve (and fail) the probe
    code, body = _post(base, {"id": "s0", "home": 0})
    assert code == 429 and body["retry_after_s"] >= 1
    assert _get(base, "/readyz")[0] == 503


def test_restart_replays_unfinished_requests(tmp_path):
    """Daemon killed with journaled-but-unserved requests: the next
    daemon on the same directory must serve them with no resubmission
    (zero lost requests by construction)."""
    sdir = str(tmp_path / "replay")
    cfg = _serve_cfg()
    d1 = ServeDaemon(cfg, sdir, platform="cpu", stub=True)
    # No start(): requests are journaled but the dispatch loop never runs
    # — the sharpest version of "accepted then died".
    for i in range(3):
        code, _body = d1.accept({"id": f"p{i}", "t": 0, "home": i})
        assert code == 202
    d1.stop(drain=False)

    d2 = ServeDaemon(cfg, sdir, platform="cpu", stub=True)
    d2.start()
    try:
        base = f"http://127.0.0.1:{d2.port}"
        outcomes = _wait_terminal(base, [f"p{i}" for i in range(3)])
        assert all(o["status"] == "done" for o in outcomes.values())
    finally:
        d2.stop(drain=False)


def test_restart_ignores_stale_spool_and_fences_orphans(tmp_path):
    """A successor daemon on the same serve dir must not trust the
    predecessor's spool leftovers: stale ready/outbox files are dropped
    at slot construction (a cold worker must not be reported warm, a
    stale batch-1 answer must not collide with the new numbering), and
    the EPOCH token flips so orphan workers stand down."""
    from dragg_tpu.serve import spool as spool_mod

    sdir = str(tmp_path / "restart")
    cfg = _serve_cfg()
    d1 = ServeDaemon(cfg, sdir, platform="cpu", stub=True)
    d1.start()
    base = f"http://127.0.0.1:{d1.port}"
    assert _post(base, {"id": "s1", "t": 0, "home": 0})[0] == 202
    _wait_terminal(base, ["s1"])
    epoch1 = spool_mod.read_epoch(d1.spool_dir)
    # Abrupt death: no drain, spool left with ready-1.json + a planted
    # stale outbox answer for the successor's first batch number.
    d1.stop(drain=False)
    spool_mod.atomic_write_json(
        os.path.join(spool_mod.outbox_dir(d1.spool_dir, 0),
                     spool_mod.batch_name(1)),
        {"batch": 1, "platform": "stub", "gen": 1,
         "responses": {"ghost": {"p_grid": 0.0}}})

    d2 = ServeDaemon(cfg, sdir, platform="cpu", stub=True)
    try:
        # Stale artifacts are gone before any worker runs, and the spool
        # has a fresh ownership token.
        assert d2.slots[0].ready() is None
        assert spool_mod.list_batches(d2.slots[0].outbox()) == []
        assert spool_mod.read_epoch(d2.spool_dir) != epoch1
        d2.start()
        base = f"http://127.0.0.1:{d2.port}"
        assert _post(base, {"id": "s2", "t": 0, "home": 1})[0] == 202
        outcomes = _wait_terminal(base, ["s2"])
        assert outcomes["s2"]["status"] == "done"
        assert "ghost" not in d2.results
    finally:
        d2.stop(drain=False)


def test_evicted_duplicate_refused_without_resolve(stub_daemon_factory):
    """An id answered long ago and evicted from the bounded results
    cache must be refused at ADMISSION from the journal's terminal set —
    an evicted marker, no re-solve, no second journal lifecycle."""
    d, base = stub_daemon_factory("evict")
    assert _post(base, {"id": "old", "t": 0, "home": 0})[0] == 202
    _wait_terminal(base, ["old"])
    with d.lock:
        d.results.pop("old")  # simulate cache eviction past results_cache
    code, body = _post(base, {"id": "old"})
    assert code == 200 and body["status"] == "done" and body["evicted"]
    code, body = _get(base, "/result?id=old")
    assert code == 200 and body.get("evicted")
    recs = [json.loads(line) for line in
            open(os.path.join(d.serve_dir, "journal.jsonl"))]
    assert [r["id"] for r in recs if r["state"] == "accepted"] == ["old"]
    assert [r["id"] for r in recs if r["state"] == "done"] == ["old"]


def test_drain_finishes_inflight_work(stub_daemon_factory):
    d, base = stub_daemon_factory("drain")
    ids = [f"dr{i}" for i in range(4)]
    for i, rid in enumerate(ids):
        assert _post(base, {"id": rid, "t": 0, "home": i})[0] == 202
    assert d.stop(drain=True) is True
    rep = replay(os.path.join(d.serve_dir, "journal.jsonl"))
    assert set(rep.terminal) == set(ids) and not rep.pending
    # Draining admission answers 503.
    code, _ = d.accept({"id": "late"})
    assert code == 503


def test_request_deadline_expires_unserved_work(stub_daemon_factory):
    """A request whose own deadline passes while queued fails terminally
    with a deadline reason (never silently dropped)."""
    d, base = stub_daemon_factory(
        "ddl", faults_spec="hang@serve_batch:1:once",
        worker_stall_s=0.0, batch_deadline_s=2.0)
    code, _ = _post(base, {"id": "slow", "t": 0, "home": 0,
                           "deadline_s": 900})
    assert code == 202
    # This one expires while the hung batch blocks the worker.
    code, _ = _post(base, {"id": "fast", "t": 1, "home": 1,
                           "deadline_s": 0.3})
    assert code == 202
    outcomes = _wait_terminal(base, ["slow", "fast"], timeout_s=40)
    assert outcomes["fast"]["status"] == "failed"
    assert "deadline" in outcomes["fast"]["reason"]
    assert outcomes["slow"]["status"] == "done"  # retried after the kill
    assert d.slots[0].gen >= 2


def test_worker_pool_two_slots_share_the_queue(stub_daemon_factory):
    d, base = stub_daemon_factory("pool2", workers=2)
    ids = [f"w{i}" for i in range(8)]
    for i, rid in enumerate(ids):
        # Four distinct timesteps → at least four batches to spread.
        assert _post(base, {"id": rid, "t": i % 4, "home": i})[0] == 202
    outcomes = _wait_terminal(base, ids)
    assert all(o["status"] == "done" for o in outcomes.values())
    slots_used = {o["response"]["slot"] for o in outcomes.values()}
    assert len(d.slots) == 2
    assert slots_used <= {0, 1}


def test_concurrent_submitters_all_terminate(stub_daemon_factory):
    """Thread-per-client admission against one daemon: every id lands
    exactly one terminal outcome (the lock discipline under the HTTP
    thread pool)."""
    _d, base = stub_daemon_factory("conc", queue_max=512)
    ids = [f"t{i}" for i in range(24)]

    def submit(chunk):
        for rid in chunk:
            _post(base, {"id": rid, "t": int(rid[1:]) % 3,
                         "home": int(rid[1:]) % 6})
    threads = [threading.Thread(target=submit, args=(ids[i::4],))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    outcomes = _wait_terminal(base, ids)
    assert all(o["status"] == "done" for o in outcomes.values())
