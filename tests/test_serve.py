"""Serving daemon (dragg_tpu/serve) — fast-tier tests.

Everything here runs with STUB workers (serve/worker.py --stub: the full
spool protocol with a deterministic jax-free responder), so the daemon's
parent-side machinery — journal durability, admission control,
backpressure, retry/requeue after worker death, degradation provenance,
drain, restart replay — is exercised in seconds.  The real-engine chaos
paths (compile-cache survival across CHILD_CRASH) live in
tests/test_serve_chaos.py (slow tier) and tools/serve_soak.py (the
acceptance harness).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from dragg_tpu.config import default_config
from dragg_tpu.resilience import faults
from dragg_tpu.serve import patterns as patterns_mod
from dragg_tpu.serve.daemon import ServeDaemon, serve_config
from dragg_tpu.serve.journal import Journal, replay


# --------------------------------------------------------------- journal
def test_journal_roundtrip(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.accepted("a", {"id": "a", "t": 0, "home": 1})
    j.accepted("b", {"id": "b", "t": 1, "home": 2})
    j.assigned(["a", "b"], batch=1, slot=0, gen=1, platform="cpu")
    assert j.done("a", {"p_grid": 1.0})
    j.close()

    rep = replay(path)
    assert set(rep.pending) == {"b"}
    assert rep.pending["b"]["req"]["home"] == 2
    assert set(rep.terminal) == {"a"}
    assert rep.terminal["a"]["response"]["p_grid"] == 1.0
    assert rep.dropped_lines == 0


def test_journal_refuses_double_answer(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.accepted("a", {"id": "a"})
    assert j.done("a", {"v": 1})
    assert not j.done("a", {"v": 2})
    assert not j.failed("a", "late failure")
    j.close()
    rep = replay(path)
    assert rep.terminal["a"]["response"] == {"v": 1}

    # The refusal survives a restart: a NEW journal on the same file must
    # refuse too (terminal ids replayed into the dedup set).
    j2 = Journal(path)
    assert not j2.done("a", {"v": 3})
    j2.close()
    assert replay(path).terminal["a"]["response"] == {"v": 1}


def test_journal_transition_record(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.accepted("a", {"id": "a"})
    j.transition("tpu", "cpu", "WEDGED", batch=3)
    j.close()
    rep = replay(path)
    assert rep.transition["failure"] == "WEDGED"
    assert rep.transition["from"] == "tpu"


def test_journal_torn_write_property(tmp_path):
    """The crash-consistency property test (ISSUE 7 satellite): truncate
    the journal at EVERY byte boundary — replay must never raise, never
    lose a request whose accepted record survived whole, and never
    produce a duplicate id across pending/terminal."""
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.accepted("a", {"id": "a", "home": 1})
    j.accepted("b", {"id": "b", "home": 2})
    j.assigned(["a", "b"], batch=1, slot=0, gen=1, platform="cpu")
    j.done("a", {"p_grid": 1.5})
    j.transition("tpu", "cpu", "TUNNEL_DOWN", batch=1)
    j.accepted("c", {"id": "c", "home": 3})
    j.failed("b", "retries exhausted")
    j.close()
    with open(path, "rb") as f:
        blob = f.read()
    # Byte offsets at which each record's trailing newline lands — a
    # record is durable iff its newline is inside the truncated prefix.
    line_ends = [i + 1 for i, ch in enumerate(blob) if ch == ord("\n")]
    torn = str(tmp_path / "torn.jsonl")
    for cut in range(len(blob) + 1):
        with open(torn, "wb") as f:
            f.write(blob[:cut])
        rep = replay(torn)  # must not raise at any cut
        whole_records = sum(1 for e in line_ends if e <= cut)
        overlap = set(rep.pending) & set(rep.terminal)
        assert not overlap, f"cut={cut}: duplicate ids {overlap}"
        assert rep.dropped_lines <= 1, f"cut={cut}: >1 torn line"
        # Durability: every fully-written accepted id is still known.
        for n_whole, rid in ((1, "a"), (2, "b"), (6, "c")):
            if whole_records >= n_whole:
                assert rid in rep.pending or rid in rep.terminal, \
                    f"cut={cut}: {rid} lost"
        # Terminal-state monotonicity: once done/failed is durable the id
        # must never replay as pending.
        if whole_records >= 4:
            assert "a" in rep.terminal
        if whole_records >= 7:
            assert "b" in rep.terminal
        if whole_records >= 5:
            assert (rep.transition or {}).get("failure") == "TUNNEL_DOWN"


def test_journal_pattern_record_replay(tmp_path):
    """Pattern-lane provenance records fold into ReplayState.patterns
    (newest wins) — the restart path that rebuilds spill lanes."""
    path = str(tmp_path / "journal.jsonl")
    j = Journal(path)
    j.pattern("short", "h1[base:10]xC1", {"horizon_hours": 1}, "spill")
    j.pattern("short", "h1[base:10]xC2",
              {"horizon_hours": 1, "fleet_slots": 2}, "spill")
    j.accepted("a", {"id": "a", "pattern": "short"})
    j.close()
    rep = replay(path)
    assert set(rep.patterns) == {"short"}
    assert rep.patterns["short"]["signature"] == "h1[base:10]xC2"
    assert set(rep.pending) == {"a"}


def test_journal_ignores_garbage_lines(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    with open(path, "w") as f:
        f.write('{"state":"accepted","id":"a","req":{}}\n')
        f.write("not json at all\n")
        f.write('{"state":"done","id":"a","response":{}}\n')
        f.write('{"half": "torn')
    rep = replay(path)
    assert set(rep.terminal) == {"a"}
    assert not rep.pending
    assert rep.dropped_lines == 2


# ------------------------------------------------------------ HTTP helpers
def _post(base: str, body) -> tuple[int, dict]:
    req = urllib.request.Request(base + "/solve",
                                 data=json.dumps(body).encode())
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base: str, path: str) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(base + path, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait_terminal(base: str, ids, timeout_s: float = 30.0) -> dict:
    outcomes = {}
    deadline = time.monotonic() + timeout_s
    remaining = set(ids)
    while remaining and time.monotonic() < deadline:
        for rid in list(remaining):
            _code, body = _get(base, f"/result?id={rid}")
            if body.get("status") in ("done", "failed"):
                outcomes[rid] = body
                remaining.discard(rid)
        time.sleep(0.05)
    assert not remaining, f"requests never terminated: {remaining}"
    return outcomes


def _serve_cfg(**overrides) -> dict:
    cfg = default_config()
    cfg["serve"].update({"port": 0, "poll_s": 0.02, "backoff_s": 0.1,
                         "request_retries": 3, "batch_deadline_s": 30.0,
                         "worker_stall_s": 30.0, "drain_s": 10.0,
                         **overrides})
    return cfg


@pytest.fixture
def stub_daemon_factory(tmp_path, monkeypatch):
    """Build stub-worker daemons in tmp dirs; stops them at teardown and
    keeps fault injection scoped to the test."""
    daemons = []
    monkeypatch.setenv("DRAGG_FAULT_STATE", str(tmp_path / "fault_state"))
    os.makedirs(tmp_path / "fault_state", exist_ok=True)

    def build(name="d", platform="cpu", faults_spec="", **cfg_overrides):
        if faults_spec:
            monkeypatch.setenv(faults.ENV, faults_spec)
        else:
            monkeypatch.delenv(faults.ENV, raising=False)
        faults.reset_plan()
        d = ServeDaemon(_serve_cfg(**cfg_overrides),
                        str(tmp_path / name), platform=platform, stub=True)
        d.start()
        daemons.append(d)
        return d, f"http://127.0.0.1:{d.port}"

    yield build
    for d in daemons:
        try:
            d.stop(drain=False)
        except Exception:
            pass
    faults.reset_plan()


# ------------------------------------------------------------ daemon paths
def test_serve_config_defaults_and_overrides():
    cfg = default_config()
    scfg = serve_config(cfg)
    assert scfg["workers"] == 1 and scfg["journal_fsync"] is True
    cfg["serve"] = {"queue_max": 7}
    assert serve_config(cfg)["queue_max"] == 7
    assert serve_config(cfg)["workers"] == 1  # defaults still applied


def test_end_to_end_accept_solve_result(stub_daemon_factory):
    _d, base = stub_daemon_factory("e2e")
    code, body = _post(base, {"id": "a", "t": 0, "home": 2})
    assert code == 202 and body["status"] == "accepted"
    code, body = _post(base, [{"id": "b", "t": 0, "home": 3},
                              {"id": "c", "t": 2, "home": 2}])
    assert code == 202
    outcomes = _wait_terminal(base, ["a", "b", "c"])
    assert all(o["status"] == "done" for o in outcomes.values())
    # Stub responses are deterministic in (t, home).
    assert outcomes["a"]["response"]["p_grid"] == 1.5
    assert outcomes["c"]["response"]["p_grid"] == 1.52
    # Idempotent duplicate: answered from the journal, not re-solved.
    code, body = _post(base, {"id": "a"})
    assert code == 200 and body["status"] == "done"
    assert body["response"]["p_grid"] == 1.5
    # Unknown id → 404; health/ready surface agree the service is up.
    assert _get(base, "/result?id=nope")[0] == 404
    assert _get(base, "/healthz")[0] == 200
    assert _get(base, "/readyz")[0] == 200
    code, metrics = _get(base, "/metrics.json")
    assert code == 200 and metrics["serve"]["results"] == 3
    assert metrics["counters"]["serve.requests_done"] == 3.0


def test_backpressure_queue_full_answers_429(stub_daemon_factory):
    # queue_max 2 and a worker that can't start (bad config would be
    # slower — just flood before the stub warms up).
    _d, base = stub_daemon_factory("bp", queue_max=2, retry_after_s=3.0)
    codes = [_post(base, {"id": f"q{i}", "t": 9, "home": i})[0]
             for i in range(6)]
    assert 429 in codes, codes
    # The 429 carried Retry-After.
    req = urllib.request.Request(base + "/solve",
                                 data=json.dumps({"id": "qq"}).encode())
    try:
        urllib.request.urlopen(req, timeout=10)
    except urllib.error.HTTPError as e:
        if e.code == 429:
            assert int(e.headers["Retry-After"]) >= 1


def test_invalid_home_rejected(stub_daemon_factory):
    _d, base = stub_daemon_factory("bad")
    code, body = _post(base, {"id": "x", "home": 10_000})
    assert code == 400 and "outside the serving community" in body["error"]


def test_malformed_fields_rejected_before_the_journal(stub_daemon_factory):
    """Validation must happen BEFORE the durability point: a malformed
    field answers 400 and leaves NO journal record (a poisoned accepted
    record would crash every later replay — one bad POST must never
    brick restarts) and never poisons the dispatch loop."""
    d, base = stub_daemon_factory("malformed")
    bad = [{"id": "b1", "home": 0, "deadline_s": "oops"},
           {"id": "b2", "home": 0, "t": "x"},
           {"id": "b3", "home": 0, "rp": []},
           {"id": "b4", "home": "not-an-int"},
           {"id": "b5", "home": 0, "state": "warm"},
           {"id": "b6", "home": 0, "state": {"temp_in": "cold"}}]
    for req in bad:
        code, body = _post(base, req)
        assert code == 400, (req, code, body)
    # Nothing journaled; healthy requests still flow; a restart on the
    # same dir starts clean.
    jpath = os.path.join(d.serve_dir, "journal.jsonl")
    assert not os.path.exists(jpath) or not open(jpath).read().strip()
    assert _post(base, {"id": "ok", "t": 0, "home": 0})[0] == 202
    assert _wait_terminal(base, ["ok"])["ok"]["status"] == "done"
    d.stop(drain=False)
    d2 = ServeDaemon(_serve_cfg(), d.serve_dir, platform="cpu", stub=True)
    assert set(d2.results) == {"ok"}
    d2.stop(drain=False)


def test_worker_crash_requeues_and_serves(stub_daemon_factory):
    """A worker that dies mid-stream (exit 17 at its 2nd batch) costs a
    retry, not a request: the daemon requeues the in-flight batch to the
    relaunched generation and every id still terminates done exactly
    once."""
    d, base = stub_daemon_factory(
        "crash", faults_spec="exit@serve_batch:2:once")
    ids = [f"c{i}" for i in range(6)]
    for i, rid in enumerate(ids):
        # Distinct t per pair forces several batches → batch 2 exists.
        assert _post(base, {"id": rid, "t": i // 2, "home": i})[0] == 202
    outcomes = _wait_terminal(base, ids)
    assert all(o["status"] == "done" for o in outcomes.values())
    assert d.slots[0].gen >= 2, "worker was never relaunched"
    recs = [json.loads(line) for line in
            open(os.path.join(d.serve_dir, "journal.jsonl"))]
    done_ids = [r["id"] for r in recs if r["state"] == "done"]
    assert sorted(done_ids) == sorted(ids)  # exactly once each
    retried = [r for r in recs if r["state"] == "done"
               and r["response"].get("retries", 0) > 0]
    assert retried, "no request recorded a retry after the crash"


def test_degraded_service_carries_provenance(stub_daemon_factory):
    """probe says the tunnel is down → the service degrades to CPU at
    startup and EVERY response carries the platform-transition record."""
    d, base = stub_daemon_factory("deg", platform="auto",
                                  faults_spec="probe_down:1")
    ids = ["g0", "g1"]
    for i, rid in enumerate(ids):
        assert _post(base, {"id": rid, "t": 0, "home": i})[0] == 202
    outcomes = _wait_terminal(base, ids)
    for rid, o in outcomes.items():
        deg = o["response"].get("degraded")
        assert deg, f"{rid} answered without degradation provenance"
        assert deg["failure"] == "TUNNEL_DOWN"
        assert (deg["from"], deg["to"]) == ("tpu", "cpu")
    assert d.transition is not None
    # The transition is journaled → a restarted daemon keeps reporting it.
    rep = replay(os.path.join(d.serve_dir, "journal.jsonl"))
    assert rep.transition["failure"] == "TUNNEL_DOWN"


def test_strict_tpu_answers_429_when_probe_says_no(stub_daemon_factory):
    _d, base = stub_daemon_factory(
        "strict", platform="tpu", faults_spec="probe_down,probe_down:50",
        degrade_to_cpu=False)
    time.sleep(0.3)  # let the dispatch loop resolve (and fail) the probe
    code, body = _post(base, {"id": "s0", "home": 0})
    assert code == 429 and body["retry_after_s"] >= 1
    assert _get(base, "/readyz")[0] == 503


def test_restart_replays_unfinished_requests(tmp_path):
    """Daemon killed with journaled-but-unserved requests: the next
    daemon on the same directory must serve them with no resubmission
    (zero lost requests by construction)."""
    sdir = str(tmp_path / "replay")
    cfg = _serve_cfg()
    d1 = ServeDaemon(cfg, sdir, platform="cpu", stub=True)
    # No start(): requests are journaled but the dispatch loop never runs
    # — the sharpest version of "accepted then died".
    for i in range(3):
        code, _body = d1.accept({"id": f"p{i}", "t": 0, "home": i})
        assert code == 202
    d1.stop(drain=False)

    d2 = ServeDaemon(cfg, sdir, platform="cpu", stub=True)
    d2.start()
    try:
        base = f"http://127.0.0.1:{d2.port}"
        outcomes = _wait_terminal(base, [f"p{i}" for i in range(3)])
        assert all(o["status"] == "done" for o in outcomes.values())
    finally:
        d2.stop(drain=False)


def test_restart_ignores_stale_spool_and_fences_orphans(tmp_path):
    """A successor daemon on the same serve dir must not trust the
    predecessor's spool leftovers: stale ready/outbox files are dropped
    at slot construction (a cold worker must not be reported warm, a
    stale batch-1 answer must not collide with the new numbering), and
    the EPOCH token flips so orphan workers stand down."""
    from dragg_tpu.serve import spool as spool_mod

    sdir = str(tmp_path / "restart")
    cfg = _serve_cfg()
    d1 = ServeDaemon(cfg, sdir, platform="cpu", stub=True)
    d1.start()
    base = f"http://127.0.0.1:{d1.port}"
    assert _post(base, {"id": "s1", "t": 0, "home": 0})[0] == 202
    _wait_terminal(base, ["s1"])
    epoch1 = spool_mod.read_epoch(d1.spool_dir)
    # Abrupt death: no drain, spool left with ready-1.json + a planted
    # stale outbox answer for the successor's first batch number.
    d1.stop(drain=False)
    spool_mod.atomic_write_json(
        os.path.join(spool_mod.outbox_dir(d1.spool_dir, 0),
                     spool_mod.batch_name(1)),
        {"batch": 1, "platform": "stub", "gen": 1,
         "responses": {"ghost": {"p_grid": 0.0}}})

    d2 = ServeDaemon(cfg, sdir, platform="cpu", stub=True)
    try:
        # Stale artifacts are gone before any worker runs, and the spool
        # has a fresh ownership token.
        assert d2.slots[0].ready() is None
        assert spool_mod.list_batches(d2.slots[0].outbox()) == []
        assert spool_mod.read_epoch(d2.spool_dir) != epoch1
        d2.start()
        base = f"http://127.0.0.1:{d2.port}"
        assert _post(base, {"id": "s2", "t": 0, "home": 1})[0] == 202
        outcomes = _wait_terminal(base, ["s2"])
        assert outcomes["s2"]["status"] == "done"
        assert "ghost" not in d2.results
    finally:
        d2.stop(drain=False)


def test_evicted_duplicate_refused_without_resolve(stub_daemon_factory):
    """An id answered long ago and evicted from the bounded results
    cache must be refused at ADMISSION from the journal's terminal set —
    an evicted marker, no re-solve, no second journal lifecycle."""
    d, base = stub_daemon_factory("evict")
    assert _post(base, {"id": "old", "t": 0, "home": 0})[0] == 202
    _wait_terminal(base, ["old"])
    with d.lock:
        d.results.pop("old")  # simulate cache eviction past results_cache
    code, body = _post(base, {"id": "old"})
    assert code == 200 and body["status"] == "done" and body["evicted"]
    code, body = _get(base, "/result?id=old")
    assert code == 200 and body.get("evicted")
    recs = [json.loads(line) for line in
            open(os.path.join(d.serve_dir, "journal.jsonl"))]
    assert [r["id"] for r in recs if r["state"] == "accepted"] == ["old"]
    assert [r["id"] for r in recs if r["state"] == "done"] == ["old"]


def test_drain_finishes_inflight_work(stub_daemon_factory):
    d, base = stub_daemon_factory("drain")
    ids = [f"dr{i}" for i in range(4)]
    for i, rid in enumerate(ids):
        assert _post(base, {"id": rid, "t": 0, "home": i})[0] == 202
    assert d.stop(drain=True) is True
    rep = replay(os.path.join(d.serve_dir, "journal.jsonl"))
    assert set(rep.terminal) == set(ids) and not rep.pending
    # Draining admission answers 503.
    code, _ = d.accept({"id": "late"})
    assert code == 503


def test_request_deadline_expires_unserved_work(stub_daemon_factory):
    """A request whose own deadline passes while queued fails terminally
    with a deadline reason (never silently dropped)."""
    d, base = stub_daemon_factory(
        "ddl", faults_spec="hang@serve_batch:1:once",
        worker_stall_s=0.0, batch_deadline_s=2.0)
    code, _ = _post(base, {"id": "slow", "t": 0, "home": 0,
                           "deadline_s": 900})
    assert code == 202
    # This one expires while the hung batch blocks the worker.
    code, _ = _post(base, {"id": "fast", "t": 1, "home": 1,
                           "deadline_s": 0.3})
    assert code == 202
    outcomes = _wait_terminal(base, ["slow", "fast"], timeout_s=40)
    assert outcomes["fast"]["status"] == "failed"
    assert "deadline" in outcomes["fast"]["reason"]
    assert outcomes["slow"]["status"] == "done"  # retried after the kill
    assert d.slots[0].gen >= 2


def test_retry_survives_service_past_request_deadline(stub_daemon_factory):
    """The request deadline governs QUEUED time only: when a worker dies
    mid-service past it (a steps=N batch legitimately runs
    batch_deadline_s·N), the requeued retry re-arms its queueing
    deadline instead of expiring on the next tick — request_retries
    stays reachable for exactly the long requests where a retry
    matters."""
    d, base = stub_daemon_factory(
        "rearm", faults_spec="hang@serve_batch:1:once",
        worker_stall_s=0.0, batch_deadline_s=2.0)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and _get(base, "/readyz")[0] != 200:
        time.sleep(0.1)  # post only once dispatch is immediate
    code, _ = _post(base, {"id": "long", "t": 0, "home": 0,
                           "deadline_s": 1.0})
    assert code == 202
    outcomes = _wait_terminal(base, ["long"], timeout_s=40)
    assert outcomes["long"]["status"] == "done"
    assert d.slots[0].gen >= 2  # the first attempt really died


def test_replayed_out_of_range_home_fails_terminally(tmp_path):
    """A journal replayed against a SHRUNK community fails the
    out-of-range request terminally at replay — it must never reach a
    worker, where the unroutable home would crash the engine child and
    burn its coalesced batch-mates' retries with it."""
    sdir = str(tmp_path / "shrunk")
    os.makedirs(sdir, exist_ok=True)
    j = Journal(os.path.join(sdir, "journal.jsonl"))
    j.accepted("big", {"id": "big", "t": 0, "home": 999})
    j.accepted("ok", {"id": "ok", "t": 0, "home": 0})
    j.close()
    d = ServeDaemon(_serve_cfg(), sdir, platform="cpu", stub=True)
    try:
        assert "big" not in d.pending and "ok" in d.pending
        d.start()
        base = f"http://127.0.0.1:{d.port}"
        outcomes = _wait_terminal(base, ["big", "ok"])
        assert outcomes["big"]["status"] == "failed"
        assert "outside lane" in outcomes["big"]["reason"]
        assert outcomes["ok"]["status"] == "done"
    finally:
        d.stop(drain=False)


def test_lane_config_pins_fleet_geometry():
    """A base config reused from fleet TRAINING (communities = 8,
    seed-strided DISTINCT communities) must not leak into a serving
    lane: lane_config always pins [fleet] to the lane's own geometry
    (identical copies, zero stride/offset)."""
    cfg = default_config()
    cfg["fleet"].update({"communities": 8, "seed_stride": 7,
                         "weather_offset_hours": 3})
    lane1 = patterns_mod.lane_config(cfg, {"fleet_slots": 1})
    assert lane1["fleet"]["communities"] == 1
    assert lane1["fleet"]["seed_stride"] == 0
    assert lane1["fleet"]["weather_offset_hours"] == 0
    lane4 = patterns_mod.lane_config(cfg, {"fleet_slots": 4})
    assert lane4["fleet"]["communities"] == 4
    assert lane4["fleet"]["seed_stride"] == 0


def test_worker_pool_two_slots_share_the_queue(stub_daemon_factory):
    d, base = stub_daemon_factory("pool2", workers=2)
    ids = [f"w{i}" for i in range(8)]
    for i, rid in enumerate(ids):
        # Four distinct timesteps → at least four batches to spread.
        assert _post(base, {"id": rid, "t": i % 4, "home": i})[0] == 202
    outcomes = _wait_terminal(base, ids)
    assert all(o["status"] == "done" for o in outcomes.values())
    slots_used = {o["response"]["slot"] for o in outcomes.values()}
    assert len(d.slots) == 2
    assert slots_used <= {0, 1}


# -------------------------------------------- fleet coalescing (ISSUE 13)
def test_fleet_coalesces_rp_groups_into_one_batch(stub_daemon_factory):
    """Three same-timestep requests with distinct reward prices fold
    into ONE dispatched fleet batch — one group per community slot —
    and a fourth request sharing a group's rp joins that group's slot.
    The window is generous: all four fsync'd POSTs must land inside it
    counted from the FIRST accept, or the daemon (correctly) dispatches
    two batches and the single-batch assertion turns timing-flaky."""
    d, base = stub_daemon_factory("coal", fleet_slots=4,
                                  batch_window_ms=2000.0)
    reqs = [{"id": "g0", "t": 5, "home": 0, "rp": 0.0},
            {"id": "g1", "t": 5, "home": 1, "rp": 0.01},
            {"id": "g2", "t": 5, "home": 0, "rp": 0.02},
            {"id": "g3", "t": 5, "home": 4, "rp": 0.0}]
    for r in reqs:
        assert _post(base, r)[0] == 202
    outcomes = _wait_terminal(base, [r["id"] for r in reqs])
    resp = {rid: o["response"] for rid, o in outcomes.items()}
    assert len({r["batch"] for r in resp.values()}) == 1, \
        "distinct-rp groups were not coalesced into one fleet batch"
    # One community slot per rp group; same-rp requests share a slot.
    assert resp["g0"]["cslot"] == resp["g3"]["cslot"]
    assert len({r["cslot"] for r in resp.values()}) == 3
    # The stub answer is (t, home)-deterministic regardless of slot.
    assert resp["g1"]["p_grid"] == 1.3
    # Dispatch telemetry recorded the occupancy of the coalesced batch.
    recs = [json.loads(line) for line in
            open(os.path.join(d.serve_dir, "journal.jsonl"))]
    assigned = [r for r in recs if r["state"] == "assigned"]
    assert len(assigned) == 1 and len(assigned[0]["ids"]) == 4


def test_fleet_slots_cap_groups_per_batch(stub_daemon_factory):
    """More distinct rp groups than community slots split across
    batches — a fleet solve never carries more groups than C."""
    _d, base = stub_daemon_factory("cap", fleet_slots=2,
                                   batch_window_ms=150.0)
    ids = [f"c{i}" for i in range(4)]
    for i, rid in enumerate(ids):
        assert _post(base, {"id": rid, "t": 7, "home": i,
                            "rp": 0.01 * i})[0] == 202
    outcomes = _wait_terminal(base, ids)
    batches = {o["response"]["batch"] for o in outcomes.values()}
    assert len(batches) == 2
    for o in outcomes.values():
        assert o["response"]["cslot"] in (0, 1)


def test_steps_validation(stub_daemon_factory):
    _d, base = stub_daemon_factory("steps")
    assert _post(base, {"id": "x1", "home": 0, "steps": 0})[0] == 400
    assert _post(base, {"id": "x2", "home": 0, "steps": 10_000})[0] == 400
    assert _post(base, {"id": "x3", "home": 0, "steps": "many"})[0] == 400
    assert _post(base, {"id": "x4", "home": 0, "pattern": 7})[0] == 400


# ------------------------------------------------- streaming (ISSUE 13)
def test_streaming_result_chunks(stub_daemon_factory):
    """/result?stream=1 answers NDJSON: one line per solved chunk (from
    the events.jsonl tail the workers emit into), then the terminal
    record; first-chunk delivery never waits for the full run."""
    _d, base = stub_daemon_factory("stream")
    assert _post(base, {"id": "s", "t": 0, "home": 2, "steps": 3})[0] == 202
    with urllib.request.urlopen(base + "/result?id=s&stream=1",
                                timeout=30) as r:
        assert r.headers["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(ln) for ln in r.read().decode().splitlines()]
    chunks = [ln for ln in lines if ln["kind"] == "chunk"]
    assert [c["step"] for c in chunks] == [0, 1, 2]
    assert chunks[0]["p_grid"] == 1.5   # stub (t=0, home=2)
    assert chunks[2]["p_grid"] == 1.52  # stub (t=2, home=2)
    final = lines[-1]
    assert final["kind"] == "result" and final["status"] == "done"
    assert final["response"]["steps"] == 3
    assert final["response"]["p_grid"] == 1.52  # last chunk's fields
    # The plain poll surface still answers, and unknown ids still 404.
    assert _get(base, "/result?id=s")[1]["status"] == "done"
    assert _get(base, "/result?id=nope&stream=1")[0] == 404


# ------------------------------------- multi-pattern admission (ISSUE 13)
def test_pattern_admission_spill_and_capacity(stub_daemon_factory):
    d, base = stub_daemon_factory("pat", spill_patterns=1)
    # Unknown lane NAME is a client error (names route, specs spill).
    code, body = _post(base, {"id": "p0", "home": 0, "pattern": "nope"})
    assert code == 400 and "unknown pattern lane" in body["error"]
    # An inline spec for an unseen signature spills to a new lane.
    code, _ = _post(base, {"id": "p1", "home": 0,
                           "pattern": {"name": "short",
                                       "horizon_hours": 1}})
    assert code == 202
    assert d.lanes["short"].source == "spill"
    # The same signature (spelled without the name) reuses the lane.
    assert _post(base, {"id": "p2", "home": 1,
                        "pattern": {"horizon_hours": 1}})[0] == 202
    assert sum(1 for ln in d.lanes.values() if ln.source == "spill") == 1
    # A second distinct signature exceeds serve.spill_patterns → 429.
    code, body = _post(base, {"id": "p3", "home": 0,
                              "pattern": {"horizon_hours": 3}})
    assert code == 429 and "pattern" in body["error"]
    outcomes = _wait_terminal(base, ["p1", "p2"])
    assert all(o["status"] == "done" for o in outcomes.values())
    # Generation provenance is journaled (spill lanes only — config
    # lanes are recoverable from config).
    recs = [json.loads(line) for line in
            open(os.path.join(d.serve_dir, "journal.jsonl"))]
    pats = [r for r in recs if r["state"] == "pattern"]
    assert [p["name"] for p in pats] == ["short"]
    assert pats[0]["source"] == "spill" and "h1[" in pats[0]["signature"]
    # Malformed specs are 400s and never journaled.
    assert _post(base, {"id": "p4", "home": 0,
                        "pattern": {"bogus_key": 1}})[0] == 400


def test_spill_admission_guards_budget_and_size(stub_daemon_factory):
    """Doomed inline specs never spend the bounded spill budget: an
    oversize spec (the _INLINE_MAX / _INLINE_HOMES_MAX ceilings on
    network-supplied values) and an out-of-range home are both 400s
    BEFORE lane creation — no compile, no journaled pattern record —
    and the budget stays available for the next valid spill."""
    d, base = stub_daemon_factory("patguard", spill_patterns=1)
    code, body = _post(base, {"id": "g0", "home": 0,
                              "pattern": {"homes": {"total": 1_000_000}}})
    assert code == 400 and "homes.total" in body["error"]
    code, body = _post(base, {"id": "g1", "home": 0,
                              "pattern": {"horizon_hours": 1,
                                          "workers": 99}})
    assert code == 400 and "workers" in body["error"]
    code, body = _post(base, {"id": "g2", "home": 999,
                              "pattern": {"horizon_hours": 1}})
    assert code == 400 and "outside the serving community" in body["error"]
    assert sum(1 for ln in d.lanes.values() if ln.source == "spill") == 0
    recs = [json.loads(line) for line in
            open(os.path.join(d.serve_dir, "journal.jsonl"))]
    assert not [r for r in recs if r["state"] == "pattern"]
    # The budget those rejections did NOT spend admits a valid spill.
    assert _post(base, {"id": "g3", "home": 0,
                        "pattern": {"horizon_hours": 1}})[0] == 202
    assert _wait_terminal(base, ["g3"])["g3"]["status"] == "done"


def test_spill_lane_rename_collision_never_overwrites(stub_daemon_factory):
    """A client-chosen lane name can collide with the rename target
    itself — the rename must search for a free suffix, never overwrite
    an existing lane (an overwrite would leave the old lane's worker
    slots dispatching batches shaped for the NEW lane's engine)."""
    d, base = stub_daemon_factory("patcol", spill_patterns=4)
    assert _post(base, {"id": "c0", "home": 0,
                        "pattern": {"name": "x-3",
                                    "horizon_hours": 1}})[0] == 202
    assert _post(base, {"id": "c1", "home": 0,
                        "pattern": {"name": "x",
                                    "horizon_hours": 2}})[0] == 202
    # A third signature also named 'x': the naive rename target
    # f"x-{len(lanes)}" == "x-3" is TAKEN; it must land on a fresh name.
    assert _post(base, {"id": "c2", "home": 0,
                        "pattern": {"name": "x",
                                    "horizon_hours": 3}})[0] == 202
    spills = {n for n, ln in d.lanes.items() if ln.source == "spill"}
    assert spills == {"x-3", "x", "x-4"}
    # Every routed signature still points at a live lane that carries it.
    for sig, name in d._sig_to_lane.items():
        assert d.lanes[name].signature == sig
    outcomes = _wait_terminal(base, ["c0", "c1", "c2"])
    assert all(o["status"] == "done" for o in outcomes.values())


def test_stream_capacity_answers_429(stub_daemon_factory):
    """/result?stream=1 is bounded by serve.max_streams — past the cap
    a stream answers 429 + Retry-After (each stream pins an HTTP thread
    and an events-tail follower for up to its whole budget)."""
    _d, base = stub_daemon_factory("nostream", max_streams=0,
                                   retry_after_s=0.5)
    assert _post(base, {"id": "s0", "home": 0})[0] == 202
    assert _wait_terminal(base, ["s0"])["s0"]["status"] == "done"
    code, body = _get(base, "/result?id=s0&stream=1")
    assert code == 429 and "max_streams" in body["error"]
    assert body["retry_after_s"] == 0.5
    code, metrics = _get(base, "/metrics.json")
    assert metrics["counters"]["serve.streams_rejected"] == 1.0
    # The poll surface still answers, and unknown ids still 404 first.
    assert _get(base, "/result?id=s0")[1]["status"] == "done"
    assert _get(base, "/result?id=nope&stream=1")[0] == 404


def test_spill_lane_rebuilt_on_restart(tmp_path):
    """A journaled spill request replays onto a rebuilt lane: the
    pattern record is the generation provenance of record."""
    sdir = str(tmp_path / "spillre")
    cfg = _serve_cfg()
    d1 = ServeDaemon(cfg, sdir, platform="cpu", stub=True)
    code, _ = d1.accept({"id": "sp", "home": 0,
                         "pattern": {"name": "lane9", "horizon_hours": 1}})
    assert code == 202
    d1.stop(drain=False)
    d2 = ServeDaemon(cfg, sdir, platform="cpu", stub=True)
    try:
        assert "lane9" in d2.lanes and d2.lanes["lane9"].source == "replay"
        assert d2.pending["sp"]["lane"] == "lane9"
        d2.start()
        base = f"http://127.0.0.1:{d2.port}"
        assert _wait_terminal(base, ["sp"])["sp"]["status"] == "done"
    finally:
        d2.stop(drain=False)


# ------------------------- burst dedup property test (ISSUE 13 satellite)
def test_burst_duplicate_posts_with_backpressure_property(stub_daemon_factory):
    """Concurrent duplicate POSTs under queue backpressure: journal
    replay stays correct — no request lost, none double-answered, every
    duplicate answered from the terminal map without a second accepted
    record (= without a re-solve)."""
    d, base = stub_daemon_factory("burst", queue_max=6, retry_after_s=0.02)
    ids = [f"u{i:02d}" for i in range(15)]
    saw_429 = threading.Event()
    errors: list[str] = []

    def client(offset: int):
        # Every client posts EVERY id, repeatedly — maximal duplication —
        # retrying 429 backpressure with the advertised pacing.
        for rep in range(2):
            for rid in ids[offset:] + ids[:offset]:
                body = {"id": rid, "t": int(rid[1:]) % 2,
                        "home": int(rid[1:]) % 6}
                for _attempt in range(80):
                    code, _r = _post(base, body)
                    if code in (200, 202):
                        break
                    if code == 429:
                        saw_429.set()
                        time.sleep(0.02)
                    else:
                        errors.append(f"{rid}: HTTP {code}")
                        break
                else:
                    errors.append(f"{rid}: never admitted")

    threads = [threading.Thread(target=client, args=(i * 3,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert saw_429.is_set(), "queue_max=6 never produced backpressure"
    outcomes = _wait_terminal(base, ids)
    assert all(o["status"] == "done" for o in outcomes.values())
    # Journal property: exactly one accepted and one done per id —
    # duplicates were answered from the terminal map, never re-journaled
    # and never re-solved.
    jpath = os.path.join(d.serve_dir, "journal.jsonl")
    recs = [json.loads(line) for line in open(jpath)]
    accepted = [r["id"] for r in recs if r["state"] == "accepted"]
    done = [r["id"] for r in recs if r["state"] == "done"]
    assert sorted(accepted) == sorted(ids), "lost or re-accepted ids"
    assert sorted(done) == sorted(ids), "lost or double-answered ids"
    rep = replay(jpath)
    assert not rep.pending and set(rep.terminal) == set(ids)
    # A late duplicate is idempotent: 200 with the recorded answer.
    code, body = _post(base, {"id": ids[0]})
    assert code == 200 and body["status"] == "done"


def test_concurrent_submitters_all_terminate(stub_daemon_factory):
    """Thread-per-client admission against one daemon: every id lands
    exactly one terminal outcome (the lock discipline under the HTTP
    thread pool)."""
    _d, base = stub_daemon_factory("conc", queue_max=512)
    ids = [f"t{i}" for i in range(24)]

    def submit(chunk):
        for rid in chunk:
            _post(base, {"id": rid, "t": int(rid[1:]) % 3,
                         "home": int(rid[1:]) % 6})
    threads = [threading.Thread(target=submit, args=(ids[i::4],))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    outcomes = _wait_terminal(base, ids)
    assert all(o["status"] == "done" for o in outcomes.values())
