"""Block cyclic-reduction band backend (ops/block_cr.py) — correctness
against the sequential band machinery and end-to-end through the IPM.

The CR elimination order differs from the sequential Cholesky, so block
values are compared at f32-rounding tolerances and solver results by the
objective convention (CLAUDE.md: compare objectives, not iterates)."""

import sys

import numpy as np
import jax.numpy as jnp

sys.path.insert(0, "tests")

from dragg_tpu.ops import banded as bd
from dragg_tpu.ops.block_cr import band_to_blocktri, cr_factor, cr_solve
from dragg_tpu.ops.ipm import ipm_solve_qp
# One SPD-band generator for every band-backend test family, so cr and
# pallas/xla are always compared on the same matrix distribution.
from test_pallas_band import _random_band_spd


def test_blocktri_reconstructs_dense():
    """(D, U) must tile exactly the dense symmetric matrix the band
    storage describes (identity padding beyond m)."""
    B, m, bw = 2, 19, 4
    Sb = _random_band_spd(B, m, bw, seed=3)
    D, U, N, mp = band_to_blocktri(Sb, bw)
    s = bw
    dense = np.zeros((B, mp, mp), np.float32)
    Sb_np = np.asarray(Sb)
    for i in range(m):
        for d in range(0, bw + 1):
            if i - d >= 0:
                dense[:, i, i - d] = Sb_np[:, i, d]
                dense[:, i - d, i] = Sb_np[:, i, d]
    for i in range(m, mp):
        dense[:, i, i] = 1.0
    for k in range(N):
        np.testing.assert_array_equal(
            np.asarray(D[:, k]), dense[:, k * s:(k + 1) * s, k * s:(k + 1) * s])
    for k in range(N - 1):
        np.testing.assert_array_equal(
            np.asarray(U[:, k]),
            dense[:, k * s:(k + 1) * s, (k + 1) * s:(k + 2) * s])


def test_cr_solve_matches_sequential():
    """CR solutions match the sequential band Cholesky solve to f32
    rounding across even/odd block counts and bandwidths."""
    for i, (B, m, bw) in enumerate(
            [(3, 29, 4), (2, 149, 4), (2, 16, 4), (1, 7, 4), (2, 23, 3)]):
        Sb = _random_band_spd(B, m, bw, seed=i)
        rng = np.random.default_rng(100 + i)
        r = jnp.asarray(rng.standard_normal((B, m)).astype(np.float32))
        x_ref = bd.banded_solve(bd.banded_cholesky(Sb, bw), r, bw)
        x_cr = cr_solve(cr_factor(Sb, bw), r)
        rel = float(jnp.max(jnp.abs(x_cr - x_ref))) / \
            float(jnp.max(jnp.abs(x_ref)))
        assert rel < 1e-4, (B, m, bw, rel)


def test_ipm_cr_backend_end_to_end():
    """band_kernel="cr" through the full Mehrotra solver on a real MPC
    batch: solve counts and objectives must match the xla scan backend."""
    from test_qp_parity import _assemble_real_step

    qp, pat = _assemble_real_step(horizon_hours=24, n_homes=16)
    args = (pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q)
    xla = ipm_solve_qp(*args, iters=30, band_kernel="xla")
    cr = ipm_solve_qp(*args, iters=30, band_kernel="cr")
    n_x, n_c = int(np.asarray(xla.solved).sum()), int(np.asarray(cr.solved).sum())
    assert n_c >= n_x - 1, (n_c, n_x)
    both = np.asarray(xla.solved) & np.asarray(cr.solved)
    assert both.sum() >= 12
    q = np.asarray(qp.q)
    fx = (q * np.asarray(xla.x)).sum(axis=1)
    fc = (q * np.asarray(cr.x)).sum(axis=1)
    np.testing.assert_allclose(fc[both], fx[both], rtol=2e-3, atol=1e-2)


def test_ipm_cr_with_tail_and_mesh():
    """cr + per-shard tail compaction under the device mesh: pure-jax ops
    shard by SPMD propagation with no shard_map wrapping needed."""
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.parallel.mesh import make_mesh

    qp, pat = _assemble_real_step(horizon_hours=8, n_homes=32)
    args = (pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q)
    base = ipm_solve_qp(*args, iters=12, tail_frac=0.25, tail_iters=20,
                        band_kernel="xla", mesh=make_mesh(4))
    cr = ipm_solve_qp(*args, iters=12, tail_frac=0.25, tail_iters=20,
                      band_kernel="cr", mesh=make_mesh(4))
    assert int(np.asarray(cr.solved).sum()) >= int(np.asarray(base.solved).sum()) - 1
    both = np.asarray(base.solved) & np.asarray(cr.solved)
    q = np.asarray(qp.q)
    fb = (q * np.asarray(base.x)).sum(axis=1)
    fc = (q * np.asarray(cr.x)).sum(axis=1)
    np.testing.assert_allclose(fc[both], fb[both], rtol=2e-3, atol=1e-2)


def test_engine_accepts_cr_band_kernel(tiny_config):
    """tpu.band_kernel = "cr" builds and steps the engine (IPM on cr, the
    ADMM factor cache transparently on the scan kernels)."""
    import copy

    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    cfg = copy.deepcopy(tiny_config)
    cfg["tpu"]["band_kernel"] = "cr"
    env = load_environment(cfg, data_dir=None)
    dt = int(cfg["agg"]["subhourly_steps"])
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24 * dt, dt, wd)
    hems = cfg["home"]["hems"]
    batch = build_home_batch(homes, int(hems["prediction_horizon"]) * dt, dt,
                             int(hems["sub_subhourly_steps"]))
    eng = make_engine(batch, env, cfg, 0)
    assert eng.band_kernel == "cr"
    state, outs = eng.run_chunk(eng.init_state(), 0,
                                np.zeros((3, eng.params.horizon), np.float32))
    assert np.isfinite(np.asarray(outs.agg_load)).all()
    assert float(np.asarray(outs.correct_solve).mean()) > 0.8
