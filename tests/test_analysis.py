"""dragglint self-tests (ISSUE 14): a positive AND a negative fixture
for every rule ID, the suppression/baseline machinery, the clean-at-HEAD
pin, and the single-pass perf guard.

The tests drive the analyzer through its two public entry points:
``check_source`` (per-file rules against synthetic sources — the rel
path chooses which scope globs apply) and ``run_rules`` (the thin
wrapper the repo-level assertions go through, ISSUE 14 satellite).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from dragg_tpu.analysis import (
    Finding,
    RULE_IDS,
    analyze,
    check_source,
    make_rules,
    run_rules,
)
from dragg_tpu.analysis.core import apply_baseline, parse_disable
from dragg_tpu.analysis.project import ConfigDocRule, literal_names

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_src(src: str, rel: str, rule: str | None = None,
            live_only: bool = True) -> list[Finding]:
    out = check_source(src, rel, make_rules())
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    if live_only:
        out = [f for f in out if f.live]
    return out


# ------------------------------------------------------------ rule fixtures
def test_dt001_parse_error():
    assert run_src("def f(:\n", "x.py", "DT001")
    assert not run_src("def f():\n    pass\n", "x.py", "DT001")


def test_dt002_unused_import():
    bad = "import os\nimport sys\nprint(sys.argv)\n"
    got = run_src(bad, "x.py", "DT002")
    assert len(got) == 1 and "os" in got[0].message and got[0].line == 1
    assert not run_src("import os\nprint(os.sep)\n", "x.py", "DT002")
    # noqa keeps its flake8 meaning (suppressed, NOT counted legacy).
    sup = run_src("import os  # noqa: F401\n", "x.py", "DT002",
                  live_only=False)
    assert sup and sup[0].suppressed == "noqa"
    # Quoted names (__all__ / getattr re-exports) count as used.
    assert not run_src('import os\n__all__ = ["os"]\n', "x.py", "DT002")


def test_dt003_whitespace():
    got = run_src("def f():\n\tpass \nx = 2", "x.py")
    msgs = [f.message for f in got if f.rule == "DT003"]
    assert any("trailing" in m for m in msgs)
    assert any("tab" in m for m in msgs)
    assert any("newline" in m for m in msgs)
    assert not run_src("x = 1\n", "x.py", "DT003")


def test_dt004_device_call_and_scope():
    src = "import jax\nd = jax.devices()\n"
    assert run_src(src, "tools/x.py", "DT004")
    assert run_src(src, "dragg_tpu/engine_x.py", "DT004")  # widened scope
    assert not run_src(src, "tests/x.py", "DT004")         # out of scope
    ok = ("import jax\n"
          "d = jax.devices()  # dragg: disable=DT004, supervised child\n")
    assert not run_src(ok, "tools/x.py", "DT004")


def test_dt005_subprocess_deadline():
    bad = "import subprocess\nsubprocess.run(['true'])\n"
    assert run_src(bad, "tools/x.py", "DT005")
    ok = "import subprocess\nsubprocess.run(['true'], timeout=5)\n"
    assert not run_src(ok, "tools/x.py", "DT005")


def test_dt005_socket_deadline():
    """Round 19: the same deadline discipline on raw sockets — a socket
    created without a timeout in scope is the wire analog of an
    un-deadlined subprocess."""
    # Bound socket.socket() with no settimeout in the same function.
    bad = ("import socket\n"
           "def dial(h, p):\n"
           "    s = socket.socket()\n"
           "    s.connect((h, p))\n"
           "    return s\n")
    got = run_src(bad, "dragg_tpu/x.py", "DT005")
    assert len(got) == 1 and got[0].line == 3 and "'s'" in got[0].message
    # settimeout on the bound name in the same function clears it.
    ok = ("import socket\n"
          "def dial(h, p):\n"
          "    s = socket.socket()\n"
          "    s.settimeout(5.0)\n"
          "    s.connect((h, p))\n"
          "    return s\n")
    assert not run_src(ok, "dragg_tpu/x.py", "DT005")
    # create_connection: the timeout argument IS the deadline (positional
    # or keyword); without one it is tracked like a bare socket.
    assert not run_src("import socket\n"
                       "s = socket.create_connection(('h', 1), 5.0)\n",
                       "dragg_tpu/x.py", "DT005")
    assert not run_src("import socket\n"
                       "s = socket.create_connection(('h', 1), timeout=5)\n",
                       "dragg_tpu/x.py", "DT005")
    assert run_src("import socket\n"
                   "s = socket.create_connection(('h', 1))\n",
                   "dragg_tpu/x.py", "DT005")
    # An unbound creation (passed straight to a helper) reports inline.
    got = run_src("import socket\nuse(socket.socket())\n",
                  "dragg_tpu/x.py", "DT005")
    assert len(got) == 1 and got[0].line == 2
    # with-statement binding participates like an Assign.
    with_ok = ("import socket\n"
               "with socket.create_connection(('h', 1)) as s:\n"
               "    s.settimeout(2.0)\n"
               "    s.sendall(b'x')\n")
    assert not run_src(with_ok, "dragg_tpu/x.py", "DT005")
    # Same name in ANOTHER function does not satisfy the deadline.
    cross = ("import socket\n"
             "def a():\n"
             "    s = socket.socket()\n"
             "    return s\n"
             "def b(s):\n"
             "    s.settimeout(1.0)\n")
    assert run_src(cross, "dragg_tpu/x.py", "DT005")
    # Out of scope (tests/) stays exempt, like the subprocess leg.
    assert not run_src(bad, "tests/x.py", "DT005")


def test_dt006_accept_loop():
    src = ("httpd.serve_forever()\n"
           "httpd.serve_forever(poll_interval=0.2)\n"
           "conn, addr = sock.accept()\n"
           "conn, addr = sock.accept()  "
           "# dragg: disable=DT006, settimeout(1.0) above\n")
    got = run_src(src, "dragg_tpu/serve/x.py", "DT006")
    assert len(got) == 2
    assert {f.line for f in got} == {1, 3}


def test_dt007_telemetry_names():
    src = ("from dragg_tpu import telemetry\n"
           "telemetry.emit('chunk.done', t0=0)\n"          # registered
           "telemetry.emit('made.up.event')\n"             # bad
           "telemetry.observe('engine.chunk_device_s', 1.0)\n"
           "telemetry.span('free.string.metric')\n"        # bad
           "kind = 'WEDGED'\n"
           "telemetry.emit('failure.' + kind)\n"           # bad: computed
           "telemetry.emit('failure.' + kind)  "
           "# dragg: disable=DT007, taxonomy kinds are registered\n")
    got = run_src(src, "dragg_tpu/x.py", "DT007")
    assert {f.line for f in got} == {3, 5, 7}, got


def test_dt008_precision():
    src = ("import jax.numpy as jnp\n"
           "from jax import lax\n"
           "from dragg_tpu.ops.precision import mxu_einsum\n"
           "a = jnp.einsum('bmn,bn->bm', A, x)\n"                    # bad
           "b = jnp.matmul(A, x)\n"                                  # bad
           "c = lax.dot_general(A, x, d)\n"                          # bad
           "d = jnp.einsum('bkk->b', M)  # dragg: disable=DT008, trace\n"
           "e = mxu_einsum('bmn,bn->bm', A, x)\n"
           "f = jnp.linalg.cholesky(S)\n")
    got = run_src(src, "dragg_tpu/ops/reluqp.py", "DT008")
    assert {f.line for f in got} == {4, 5, 6}
    # The policy module itself owns the bare einsum, and non-ops files
    # are out of scope.
    assert not run_src(src, "dragg_tpu/ops/precision.py", "DT008")
    assert not run_src(src, "dragg_tpu/engine_x.py", "DT008")


def test_dt009_kkt_inverse():
    src = ("import numpy as np\n"
           "import jax.numpy as jnp\n"
           "a = np.linalg.inv(S)\n"                                  # bad
           "b = jnp.linalg.inv(K)\n"                                 # bad
           "c = np.linalg.inv(r2)  # dragg: disable=DT009, 2x2 rotation\n"
           "d = np.linalg.solve(S, r)\n"
           "e = jnp.linalg.cholesky(S)\n")
    got = run_src(src, "dragg_tpu/x.py", "DT009")
    assert {f.line for f in got} == {3, 4}
    # ops/ owns its factorization-internal inverses.
    assert not run_src(src, "dragg_tpu/ops/reluqp.py", "DT009")


def test_dt010_home_type_registry_live_and_negative(tmp_path):
    # Live repo: fully co-registered (the old tools/lint.py teeth).
    assert run_rules(select={"DT010"}) == []
    # The checker reads the REAL type lists, not a stale copy.
    from dragg_tpu.homes import HOME_TYPES
    from dragg_tpu.ops.qp import TYPE_SPECS

    got = literal_names(
        os.path.join(ROOT, "dragg_tpu", "homes.py"), "HOME_TYPES")
    assert tuple(got) == HOME_TYPES
    got_specs = literal_names(
        os.path.join(ROOT, "dragg_tpu", "ops", "qp.py"), "TYPE_SPECS")
    assert set(got_specs) == set(TYPE_SPECS)
    assert {"ev", "heat_pump"} <= set(got)
    # Negative: a skeleton repo with a half-wired home type.
    (tmp_path / "dragg_tpu" / "ops").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    (tmp_path / "tests").mkdir()
    (tmp_path / "dragg_tpu" / "homes.py").write_text(
        'HOME_TYPES = ("base", "rogue")\n')
    (tmp_path / "dragg_tpu" / "ops" / "qp.py").write_text(
        'TYPE_SPECS = {"base": 1}\n')
    (tmp_path / "docs" / "config.md").write_text("`base` only\n")
    (tmp_path / "tests" / "test_parity.py").write_text(
        '# parity\nTYPES = ["base"]\n')
    got = run_rules(root=str(tmp_path), paths=[], select={"DT010"})
    msgs = " ".join(f.message for f in got)
    assert "rogue" in msgs and "TYPE_SPECS" in msgs
    assert "undocumented" in msgs and "parity" in msgs
    assert len(got) == 3


def test_dt011_config_doc_live_and_negative(tmp_path):
    # Live repo: every default_config leaf documented (the old
    # tests/test_homes_data.py check, now an analyzer rule).
    assert run_rules(select={"DT011"}) == []
    # Negative: an injected config with an undocumented knob.
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "config.md").write_text(
        "# config\n\n## [sim]\n`homes` documented\n")
    rule = ConfigDocRule(config={"sim": {"homes": 4, "rogue_knob": 1}})
    got = [f for f in rule.run_project(str(tmp_path))]
    assert len(got) == 1 and "rogue_knob" in got[0].message


JIT_SCAN_FIXTURE = """\
import jax
import jax.numpy as jnp
from jax import lax

def helper(x):
    return x.item()          # line 6: reachable via body -> helper

def body(carry, t):
    v = helper(carry)
    w = float(t)             # line 10: t is a param of a traced fn
    return carry, v + w

def outer(c0, ts):
    return lax.scan(body, c0, ts)

def host_only(arr):
    return arr.item()        # NOT reachable from any jit/scan root
"""


def test_dt012_traced_host_sync():
    got = run_src(JIT_SCAN_FIXTURE, "dragg_tpu/ops/x.py", "DT012")
    assert {f.line for f in got} == {6, 10}, got
    # Same file without the scan root: nothing reachable, no findings.
    clean = JIT_SCAN_FIXTURE.replace("lax.scan(body, c0, ts)", "0")
    assert not run_src(clean, "dragg_tpu/ops/x.py", "DT012")
    # static_argnames values are trace-time Python — not syncs.
    static = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('bank',))\n"
        "def solve(vals, bank):\n"
        "    r = int(bank)\n"
        "    return vals * r\n")
    assert not run_src(static, "dragg_tpu/ops/x.py", "DT012")
    # ... including via a module-level _STATIC tuple, the solvers' idiom.
    static2 = static.replace("static_argnames=('bank',)",
                             "static_argnames=_STATIC")
    static2 = "_STATIC = ('bank',)\n" + static2
    assert not run_src(static2, "dragg_tpu/ops/x.py", "DT012")
    # jax.device_get and np.asarray of runtime values ARE flagged.
    sync = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.asarray(x)\n")
    assert run_src(sync, "dragg_tpu/ops/x.py", "DT012")


def test_dt012_catches_seeded_item_in_engine_scan_body():
    """The acceptance-criteria self-test: a ``.item()`` seeded into the
    REAL engine's scan body is caught at exactly the seeded line."""
    path = os.path.join(ROOT, "dragg_tpu", "engine.py")
    with open(path) as f:
        lines = f.read().splitlines(keepends=True)
    anchor = next(i for i, l in enumerate(lines)
                  if "new_state, new_factor, out = self._step(" in l)
    indent = " " * (len(lines[anchor]) - len(lines[anchor].lstrip()))
    seeded = lines[:anchor + 1] + [f"{indent}_bad = rp.item()\n"] \
        + lines[anchor + 1:]
    got = run_src("".join(seeded), "dragg_tpu/engine.py", "DT012")
    assert any(f.line == anchor + 2 and ".item()" in f.message
               for f in got), got
    # And the UNMODIFIED engine is clean — the zero-extra-syncs
    # invariant holds at HEAD.
    assert not run_src("".join(lines), "dragg_tpu/engine.py", "DT012")


def test_dt013_donation():
    bad = ("import jax\n"
           "def step(state, t):\n"
           "    return state\n"
           "fn = jax.jit(step)\n")
    got = run_src(bad, "dragg_tpu/x.py", "DT013")
    assert len(got) == 1 and got[0].line == 4
    ok = bad.replace("jax.jit(step)", "jax.jit(step, donate_argnums=(0,))")
    assert not run_src(ok, "dragg_tpu/x.py", "DT013")
    # Decorated form, and non-state signatures stay silent.
    dec = ("import jax\n"
           "@jax.jit\n"
           "def chunk(consts, carry, ts):\n"
           "    return carry\n")
    assert run_src(dec, "dragg_tpu/x.py", "DT013")
    small = ("import jax\n"
             "fn = jax.jit(lambda c, o: c + o)\n")
    assert not run_src(small, "dragg_tpu/x.py", "DT013")


def test_dt014_determinism():
    src = ("import time, random\n"
           "import numpy as np\n"
           "t = time.time()\n"                       # bad
           "m = time.monotonic()\n"                  # fine (elapsed)
           "r = random.random()\n"                   # bad
           "rng = random.Random(7)\n"                # seeded: fine
           "g = np.random.uniform(0, 1)\n"           # bad
           "rs = np.random.RandomState(7)\n"         # seeded: fine
           "dr = np.random.default_rng(7)\n"         # seeded: fine
           )
    got = run_src(src, "dragg_tpu/x.py", "DT014")
    assert {f.line for f in got} == {3, 5, 7}, got
    # telemetry/ is out of scope (wall clock IS its domain); so is
    # everything outside the package.
    assert not run_src(src, "dragg_tpu/telemetry/x.py", "DT014")
    assert not run_src(src, "tools/x.py", "DT014")
    # jax.random is the sanctioned in-graph PRNG.
    assert not run_src("import jax\nk = jax.random.PRNGKey(0)\n",
                       "dragg_tpu/x.py", "DT014")


def test_dt015_journal_fsync():
    bad = ("import json, os\n"
           "def append(fh, rec):\n"
           "    fh.write(json.dumps(rec) + '\\n')\n"
           "    fh.flush()\n")
    assert run_src(bad, "dragg_tpu/serve/journal.py", "DT015")
    ok = bad + "    os.fsync(fh.fileno())\n"
    assert not run_src(ok, "dragg_tpu/serve/journal.py", "DT015")
    # Scope: only the journal/spool/checkpoint durability files.
    assert not run_src(bad, "dragg_tpu/serve/daemon.py", "DT015")
    # np.savez without fsync counts as a record write too.
    npz = ("import numpy as np, os\n"
           "def save(path, arrays):\n"
           "    np.savez_compressed(path, **arrays)\n")
    assert run_src(npz, "dragg_tpu/checkpoint.py", "DT015")


def test_dt016_bad_suppression():
    """A typo'd or unknown rule ID in a disable comment is a silent
    no-op suppression — DT016 surfaces it.  (Markers are built by
    concatenation so THIS file's lines don't carry them literally.)"""
    d = "# dragg: disable="
    bad_id = "x = 1  " + d + "DT08, missing a digit\n"
    got = run_src(bad_id, "dragg_tpu/x.py", "DT016")
    assert len(got) == 1 and "DT08" in got[0].message
    unknown = "x = 1  " + d + "DT099, not a registered rule\n"
    got2 = run_src(unknown, "dragg_tpu/x.py", "DT016")
    assert len(got2) == 1 and "DT099" in got2[0].message
    # A typo'd ID AFTER a valid one must not fold into the reason text.
    trailing = "x = 1  " + d + "DT004,DT05, both intended\n"
    got3 = run_src(trailing, "dragg_tpu/x.py", "DT016")
    assert len(got3) == 1 and "DT05" in got3[0].message
    # Free-form reasons with no id-like tokens stay reasons.
    ok = "x = 1  " + d + "DT014, fine\n"
    assert not run_src(ok, "dragg_tpu/x.py", "DT016")
    # The docs placeholder spelling (DT0xx) is documentation, not a
    # malformed suppression — core.py's own docstring depends on this.
    doc = "# ``" + d + "DT0xx[, reason]`` is the syntax\n"
    assert not run_src(doc, "dragg_tpu/x.py", "DT016")
    # A malformed baseline count degrades to a note, not a crash.
    notes: list[str] = []
    apply_baseline([], [{"rule": "DT014", "path": "x.py",
                         "count": "twenty", "reason": "r"}], notes)
    assert any("malformed" in n for n in notes)


# ------------------------------------------------- suppressions & baseline
def test_parse_disable_syntax():
    assert parse_disable("DT004") == ({"DT004"}, "")
    assert parse_disable("DT004, supervised child") == (
        {"DT004"}, "supervised child")
    assert parse_disable("DT004,DT005, two rules, one reason") == (
        {"DT004", "DT005"}, "two rules, one reason")
    assert parse_disable("not-an-id") == (set(), "not-an-id")


def test_inline_suppression_records_reason():
    src = ("import jax\n"
           "d = jax.devices()  # dragg: disable=DT004, runs supervised\n")
    got = run_src(src, "tools/x.py", "DT004", live_only=False)
    assert got and got[0].suppressed == "inline"
    assert got[0].reason == "runs supervised"


def test_file_level_suppression():
    src = ("# dragg: disable-file=DT004, whole-file exemption for a test\n"
           "import jax\n"
           "a = jax.devices()\n"
           "b = jax.devices()\n")
    got = run_src(src, "tools/x.py", "DT004", live_only=False)
    assert len(got) == 2 and all(f.suppressed == "file" for f in got)


def test_legacy_markers_still_honored():
    """Satellite: the five pre-ISSUE-14 markers keep suppressing their
    rules (grandfathered) — and the analyzer warns once per run."""
    cases = [
        ("import jax\nd = jax.devices()  # device-call-ok: child\n",
         "tools/x.py", "DT004"),
        ("conn = sock.accept()  # accept-timeout-ok: settimeout above\n",
         "dragg_tpu/serve/x.py", "DT006"),
        ("from dragg_tpu import telemetry\n"
         "telemetry.emit('x.' + k)  # telemetry-name-ok: registered\n",
         "dragg_tpu/x.py", "DT007"),
        ("import jax.numpy as jnp\n"
         "a = jnp.einsum('bkk->b', M)  # precision-ok: trace\n",
         "dragg_tpu/ops/admm.py", "DT008"),
        ("import numpy as np\n"
         "a = np.linalg.inv(r)  # kkt-inv-ok: 2x2\n",
         "dragg_tpu/x.py", "DT009"),
    ]
    for src, rel, rule in cases:
        got = run_src(src, rel, rule, live_only=False)
        assert got and got[0].suppressed == "legacy", (rel, rule, got)


def test_legacy_marker_migration_note(tmp_path):
    (tmp_path / "tools").mkdir()
    p = tmp_path / "tools" / "tool.py"
    p.write_text("import jax\nd = jax.devices()  # device-call-ok: c\n")
    res = analyze(root=str(tmp_path), paths=[str(p)],
                  rules=[r for r in make_rules() if r.id == "DT004"],
                  use_baseline=False)
    assert any("legacy suppression" in n for n in res.notes)
    assert res.exit_code == 0


def test_baseline_absorbs_counts_and_ratchets():
    findings = [Finding("DT014", "error", "dragg_tpu/h.py", i, "m")
                for i in range(3)]
    notes: list[str] = []
    apply_baseline(findings, [{"rule": "DT014", "path": "dragg_tpu/h.py",
                               "count": 2, "reason": "debt"}], notes)
    assert [f.suppressed for f in findings] == ["baseline", "baseline", None]
    assert notes == []          # fully consumed: not stale
    # Stale entry (count above reality) is reported for ratcheting;
    # a reasonless entry is called out.
    notes2: list[str] = []
    apply_baseline([], [{"rule": "DT014", "path": "x.py", "count": 1,
                         "reason": ""}], notes2)
    assert any("stale" in n for n in notes2)
    assert any("missing reason" in n for n in notes2)


# ------------------------------------------------------- repo-level pins
def test_analyzer_clean_at_head():
    """Acceptance criteria: the analyzer exits clean at HEAD across
    dragg_tpu/, tools/, and bench.py, and every baseline entry carries a
    reason (empty-or-fully-reasoned baseline)."""
    res = analyze()
    assert res.errors == [], [f.render() for f in res.errors]
    assert not any("missing reason" in n or "stale" in n for n in res.notes), \
        res.notes
    with open(os.path.join(ROOT, ".dragglint-baseline.json")) as f:
        base = json.load(f)
    for e in base["entries"]:
        assert e.get("reason"), e


def test_run_rules_wrapper_clean_at_head():
    assert run_rules() == []


def test_single_pass_perf_guard():
    """ISSUE 14 satellite: the full-repo single-pass walk stays under
    ~5 s on this container (the old lint re-walked the AST once per
    check; the dispatch design must not regress toward that)."""
    t0 = time.perf_counter()
    analyze()
    elapsed = time.perf_counter() - t0
    assert elapsed < 5.0, f"full-repo analysis took {elapsed:.2f}s"


def test_every_rule_has_a_fixture_test_and_doc():
    """Every registered rule ID appears in this file as a fixture test
    and in docs/analysis.md's catalog."""
    with open(os.path.abspath(__file__)) as f:
        self_src = f.read()
    with open(os.path.join(ROOT, "docs", "analysis.md")) as f:
        doc = f.read()
    for rid in RULE_IDS:
        assert f"dt{rid[2:]}".lower() in self_src.lower(), rid
        assert rid in doc, f"{rid} missing from docs/analysis.md"


# ----------------------------------------------------------------- the CLI
def test_cli_json_and_exit_code(tmp_path):
    out = tmp_path / "findings.json"
    proc = subprocess.run(
        [sys.executable, "-m", "dragg_tpu.analysis", "--json", str(out)],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["version"] == 1 and doc["files"] > 100
    assert doc["summary"]["errors"] == 0
    assert doc["summary"]["baselined"] >= 1      # the homes.py debt


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "dragg_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, timeout=60, cwd=ROOT)
    assert proc.returncode == 0
    for rid in RULE_IDS:
        assert rid in proc.stdout, rid


def test_cli_changed_mode():
    """--changed analyzes only the git-diff'd files (fast pre-commit
    path); on a clean-by-rules tree it exits 0 either way."""
    proc = subprocess.run(
        [sys.executable, "-m", "dragg_tpu.analysis", "--changed"],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "dragglint:" in proc.stderr


def test_cli_subtree_paths():
    proc = subprocess.run(
        [sys.executable, "-m", "dragg_tpu.analysis", "dragg_tpu/serve"],
        capture_output=True, text=True, timeout=120, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_analyzer_import_is_jax_free():
    """The analyzer must be importable/runnable when ``import jax``
    would hang (wedged axon tunnel — the whole point of DT004)."""
    code = ("import sys\n"
            "import dragg_tpu.analysis\n"
            "import dragg_tpu.analysis.rules\n"
            "import dragg_tpu.analysis.project\n"
            "assert 'jax' not in sys.modules, 'analysis pulled in jax'\n"
            "print('jax-free-ok')\n")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=60,
                          cwd=ROOT)
    assert proc.returncode == 0 and "jax-free-ok" in proc.stdout, \
        proc.stdout + proc.stderr
