"""Pallas band-kernel parity vs the XLA scan implementation.

On the CPU test platform the kernels run in interpret mode — same program,
emulated memory model — so these tests pin the numerics; the on-chip win is
measured by bench.py/tools/profile_solver.py (docs/perf_notes.md).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from dragg_tpu.ops import banded as bd
from dragg_tpu.ops import pallas_band as pb


def _random_band_spd(B, m, bw, seed=0):
    rng = np.random.default_rng(seed)
    Sb = np.zeros((B, m, bw + 1), np.float32)
    Sb[:, :, 0] = 10.0 + rng.random((B, m))
    for k in range(1, bw + 1):
        Sb[:, k:, k] = rng.standard_normal((B, m - k)).astype(np.float32) * 0.5
    return jnp.asarray(Sb)


@pytest.fixture(scope="module")
def band_problem():
    B, m, bw = 5, 29, 4
    Sb = _random_band_spd(B, m, bw)
    rng = np.random.default_rng(1)
    r = jnp.asarray(rng.standard_normal((B, m)).astype(np.float32))
    return B, m, bw, Sb, r


def test_cholesky_t_matches_scan_path(band_problem):
    B, m, bw, Sb, r = band_problem
    L_ref = bd.banded_cholesky(Sb, bw)
    L_pal = jnp.transpose(
        pb.banded_cholesky_t(jnp.transpose(Sb, (1, 2, 0)), bw), (2, 0, 1)
    )
    # Identical operation order — bit-equal, not just close.
    np.testing.assert_array_equal(np.asarray(L_ref), np.asarray(L_pal))


def test_refined_solve_t_matches_scan_path(band_problem):
    B, m, bw, Sb, r = band_problem
    L = bd.banded_cholesky(Sb, bw)
    Lt = jnp.transpose(L, (1, 2, 0))
    St = jnp.transpose(Sb, (1, 2, 0))

    x0_ref = bd.banded_solve(L, r, bw)
    x0_pal = pb.refined_banded_solve_t(Lt, St, r.T, bw, refine=0).T
    np.testing.assert_allclose(np.asarray(x0_ref), np.asarray(x0_pal),
                               rtol=0, atol=1e-6)

    resid = r - bd.band_matvec(Sb, x0_ref, bw)
    x1_ref = x0_ref + bd.banded_solve(L, resid, bw)
    x1_pal = pb.refined_banded_solve_t(Lt, St, r.T, bw, refine=1).T
    np.testing.assert_allclose(np.asarray(x1_ref), np.asarray(x1_pal),
                               rtol=0, atol=1e-6)


def test_fused_factor_solve_matches_split(band_problem):
    """factor_refined_solve_t (one fused kernel) must be BIT-EQUAL to
    banded_cholesky_t followed by refined_banded_solve_t — identical
    recurrences, identical operation order, one fewer launch."""
    B, m, bw, Sb, r = band_problem
    St = jnp.transpose(Sb, (1, 2, 0))
    Lt = pb.banded_cholesky_t(St, bw)
    for refine in (0, 1):
        x_split = pb.refined_banded_solve_t(Lt, St, r.T, bw, refine=refine)
        L_fused, x_fused = pb.factor_refined_solve_t(St, r.T, bw,
                                                     refine=refine)
        np.testing.assert_array_equal(np.asarray(L_fused), np.asarray(Lt))
        np.testing.assert_array_equal(np.asarray(x_fused), np.asarray(x_split))


def test_fused_factor_solve_lane_block_invariant(band_problem):
    """lane_block only tiles the home axis — results are identical for any
    block size (the on-chip DRAGG_LANE_BLOCK sweep must be free to pick).
    The factor is pinned bitwise; the refined solve gets a ~1-ulp
    allowance because pre-0.5 jax's pallas interpret mode reassociates
    the refinement matvec across the padded lane width (measured 3e-8
    max abs at lane 128 vs 512 on jax 0.4.37's CPU interpreter; bitwise
    on current jax and on TPU, where blocks are compute-local)."""
    B, m, bw, Sb, r = band_problem
    St = jnp.transpose(Sb, (1, 2, 0))
    L128, x128 = pb.factor_refined_solve_t(St, r.T, bw, refine=1,
                                           lane_block=128)
    L512, x512 = pb.factor_refined_solve_t(St, r.T, bw, refine=1,
                                           lane_block=512)
    np.testing.assert_array_equal(np.asarray(L128), np.asarray(L512))
    np.testing.assert_allclose(np.asarray(x128), np.asarray(x512),
                               rtol=1e-5, atol=1e-7)


def test_lane_padding_is_benign():
    """B not a multiple of LANE_BLOCK pads with identity rows; results for
    the real homes are unchanged vs a padded-by-hand batch."""
    B, m, bw = 3, 17, 2
    Sb = _random_band_spd(B, m, bw, seed=2)
    L_ref = bd.banded_cholesky(Sb, bw)
    L_pal = jnp.transpose(
        pb.banded_cholesky_t(jnp.transpose(Sb, (1, 2, 0)), bw), (2, 0, 1)
    )
    np.testing.assert_array_equal(np.asarray(L_ref), np.asarray(L_pal))
    assert L_pal.shape == (B, m, bw + 1)


def test_band_scatter_t_matches():
    """Transposed scatter builds the same band content as the (B, m, bw+1)
    layout on the real MPC Schur pattern."""
    import sys

    sys.path.insert(0, "tests")
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.ops.admm import _schur_structure_for
    from dragg_tpu.ops.qp import schur_contrib

    qp, pat = _assemble_real_step(horizon_hours=4, n_homes=3)
    ss = _schur_structure_for(pat)
    plan = bd.plan_for(ss, pat.m)
    assert plan is not None
    rng = np.random.default_rng(3)
    dinv = jnp.asarray(rng.random((3, pat.n)).astype(np.float32) + 0.5)
    contrib = schur_contrib(ss, qp.vals, dinv)
    Sb = bd.band_scatter(plan, contrib)
    Sb_t = pb.band_scatter_t(plan, contrib)
    np.testing.assert_array_equal(
        np.asarray(Sb), np.asarray(jnp.transpose(Sb_t, (2, 0, 1)))
    )


def test_ipm_pallas_end_to_end_matches_xla():
    """Full IPM solve with band_kernel='pallas' (interpret mode on CPU)
    returns the same solution as the XLA band path on a real QP batch."""
    import sys

    sys.path.insert(0, "tests")
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.ops.ipm import ipm_solve_qp

    qp, pat = _assemble_real_step(horizon_hours=4, n_homes=4)
    sol_x = ipm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                         iters=12, band_kernel="xla")
    sol_p = ipm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                         iters=12, band_kernel="pallas")
    np.testing.assert_allclose(np.asarray(sol_x.x), np.asarray(sol_p.x),
                               rtol=0, atol=5e-4)
    np.testing.assert_array_equal(np.asarray(sol_x.solved),
                                  np.asarray(sol_p.solved))


def test_admm_band_pallas_matches_xla():
    """ADMM with solve_backend='band' + Pallas kernels matches the XLA band
    path on a real QP batch (same iterations, same solution)."""
    import sys

    sys.path.insert(0, "tests")
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.ops.admm import admm_solve_qp

    qp, pat = _assemble_real_step(horizon_hours=4, n_homes=4)
    kw = dict(iters=300, solve_backend="band", banded_factor=True)
    sol_x = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          band_kernel="xla", **kw)
    sol_p = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          band_kernel="pallas", **kw)
    np.testing.assert_array_equal(np.asarray(sol_x.iters),
                                  np.asarray(sol_p.iters))
    np.testing.assert_allclose(np.asarray(sol_x.x), np.asarray(sol_p.x),
                               rtol=0, atol=2e-4)
    np.testing.assert_array_equal(np.asarray(sol_x.solved),
                                  np.asarray(sol_p.solved))


@pytest.mark.slow  # round-11 tier-1 budget trim: single-device pallas parity tests keep the kernels covered; this is the mesh cross product
def test_sharded_pallas_band_kernels(tiny_config):
    """band_kernel='pallas' on an 8-device mesh: the kernels run under
    shard_map over the homes axis and agree with the single-device XLA
    path (interpret mode on the CPU mesh)."""
    import copy

    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes
    from dragg_tpu.parallel.mesh import make_mesh, make_sharded_engine

    cfg = copy.deepcopy(tiny_config)
    cfg["tpu"]["band_kernel"] = "pallas"
    env = load_environment(cfg, data_dir=None)
    dt = int(cfg["agg"]["subhourly_steps"])
    wd = load_waterdraw_profiles(None, seed=int(cfg["simulation"]["random_seed"]))
    homes = create_homes(cfg, 24 * dt, dt, wd)
    hems = cfg["home"]["hems"]
    batch = build_home_batch(homes, int(hems["prediction_horizon"]) * dt, dt,
                             int(hems["sub_subhourly_steps"]))
    n = batch.n_homes

    cfg_x = copy.deepcopy(cfg)
    cfg_x["tpu"]["band_kernel"] = "xla"
    ref = make_engine(batch, env, cfg_x, 0)
    sh = make_sharded_engine(batch, env, cfg, 0, mesh=make_mesh(8))
    assert sh._band_kernel == "pallas" and sh._solver_mesh is not None

    rps = np.zeros((2, ref.params.horizon), dtype=np.float32)
    _, ref_out = ref.run_chunk(ref.init_state(), 0, rps)
    _, sh_out = sh.run_chunk(sh.init_state(), 0, rps)
    np.testing.assert_allclose(
        np.asarray(sh_out.p_grid)[:, :n], np.asarray(ref_out.p_grid),
        rtol=1e-3, atol=1e-3,
    )
    np.testing.assert_allclose(
        np.asarray(sh_out.agg_load), np.asarray(ref_out.agg_load),
        rtol=1e-3, atol=1e-2,
    )


def test_pallas_self_test_passes():
    """The availability self-test (tiny diagonal system) validates the
    kernels on the current backend (interpret mode here); available() on a
    non-TPU backend reports False without running it."""
    assert pb._run_self_test() is True
    assert pb.available() is False  # CPU test backend


def test_ipm_tail_with_pallas_matches_xla():
    """The on-chip default combination — tail compaction + pallas band
    kernels — agrees with the XLA path on solutions and solve flags."""
    import sys

    sys.path.insert(0, "tests")
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.ops.ipm import ipm_solve_qp

    qp, pat = _assemble_real_step(horizon_hours=4, n_homes=12)
    kw = dict(iters=20, tail_frac=0.25, tail_iters=20)
    sol_x = ipm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                         band_kernel="xla", **kw)
    sol_p = ipm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                         band_kernel="pallas", **kw)
    np.testing.assert_array_equal(np.asarray(sol_x.solved),
                                  np.asarray(sol_p.solved))
    both = np.asarray(sol_x.solved)
    q = np.asarray(qp.q)
    fx = (q * np.asarray(sol_x.x)).sum(axis=1)
    fp = (q * np.asarray(sol_p.x)).sum(axis=1)
    np.testing.assert_allclose(fp[both], fx[both], rtol=1e-3, atol=1e-2)


def test_bchunk_is_bitwise_identical(band_problem):
    """b_chunk / DRAGG_PALLAS_BCHUNK (one pallas_call per home-axis slice
    — the fallback for the m=149 scoped-VMEM OOM, docs/onchip_r4/) must
    be bitwise identical to the unchunked call: homes are independent and
    each slice runs the same kernel.  b_chunk is a STATIC jit argument
    precisely so this path retraces (a module-global toggle would hit the
    unchunked cached executable and silently test nothing)."""
    B, m, bw, Sb, r = band_problem
    St = jnp.transpose(Sb, (1, 2, 0))
    rt = jnp.swapaxes(r, 0, 1)
    L0 = pb.banded_cholesky_t(St, bw)
    x0 = pb.refined_banded_solve_t(L0, St, rt, bw, refine=1)
    Lf0, xf0 = pb.factor_refined_solve_t(St, rt, bw, refine=0)

    L1 = pb.banded_cholesky_t(St, bw, b_chunk=2)  # B=5 → slices 2, 2, 1
    x1 = pb.refined_banded_solve_t(L1, St, rt, bw, refine=1, b_chunk=2)
    Lf1, xf1 = pb.factor_refined_solve_t(St, rt, bw, refine=0, b_chunk=2)

    np.testing.assert_array_equal(np.asarray(L0), np.asarray(L1))
    np.testing.assert_array_equal(np.asarray(x0), np.asarray(x1))
    np.testing.assert_array_equal(np.asarray(Lf0), np.asarray(Lf1))
    np.testing.assert_array_equal(np.asarray(xf0), np.asarray(xf1))


def test_auto_block_policy_measured_anchors():
    """The scoped-VMEM auto policy (round 5, VERDICT r4 next-3) must
    reproduce the two on-chip anchors with the default 10 MiB budget:
    m=77 (H=24) ran at lane_block=512 (docs/onchip_r4/band_kernel_24h),
    m=149 (H=48) scoped-VMEM OOM'd at 512 and was staged at 256
    (CLAUDE.md gotcha) — no env overrides."""
    from dragg_tpu.ops.pallas_band import _auto_blocks

    # Refined-solve shape: 2 band + 4 vector buffers, f32.
    lb24, _ = _auto_blocks(77, 5, 2, 4, 4, 512)
    lb48, _ = _auto_blocks(149, 5, 2, 4, 4, 512)
    assert lb24 == 512
    assert lb48 == 256
    # The full (m, B) output participates in the scoped budget (observed
    # round 4): at 25k homes x m=149 the policy must chunk the home axis
    # to a lane-block multiple; at 512 homes it must not chunk.
    _, ck_small = _auto_blocks(149, 5, 2, 4, 4, 512)
    _, ck_big = _auto_blocks(149, 5, 2, 4, 4, 25088)
    assert ck_small == 0
    assert ck_big > 0 and ck_big % lb48 == 0 and ck_big < 25088
    assert ck_big * 149 * 4 <= 5 * (1 << 20)


def test_auto_chunked_refined_solve_matches_unchunked(band_problem):
    """When the auto policy decides to chunk (forced here via a tiny
    DRAGG_VMEM_BUDGET through explicit b_chunk), results stay bitwise
    identical to the unchunked call — same guarantee the env-var path
    pins in test_bchunk_is_bitwise_identical, now for policy-chosen
    chunks."""
    import numpy as np

    from dragg_tpu.ops import banded as bd
    from dragg_tpu.ops.pallas_band import refined_banded_solve_t

    B, m, bw, Sb, r = band_problem
    Lb = bd.banded_cholesky(Sb, bw)
    Lt, St = jnp.transpose(Lb, (1, 2, 0)), jnp.transpose(Sb, (1, 2, 0))
    rt = jnp.swapaxes(r, 0, 1)
    full = refined_banded_solve_t(Lt, St, rt, bw, refine=1)
    chunked = refined_banded_solve_t(Lt, St, rt, bw, refine=1,
                                     lane_block=128, b_chunk=2)
    # ~1-ulp allowance for pre-0.5 jax's pallas interpreter, which
    # reassociates the refinement matvec across the padded lane width
    # (see test_fused_factor_solve_lane_block_invariant); bitwise on
    # current jax and on TPU.
    np.testing.assert_allclose(np.asarray(full), np.asarray(chunked),
                               rtol=1e-5, atol=1e-7)
