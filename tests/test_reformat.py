"""Analysis-layer tests: run discovery, daily statistics, figure generation,
and the CLI entry point."""

import json
import os

import numpy as np
import pytest

from dragg_tpu.config import default_config
from dragg_tpu.reformat import Reformat, daily_stats, stats_table


def test_daily_stats_known_values():
    # Two days of hourly data: day1 = 0..23, day2 = 10s.
    loads = np.concatenate([np.arange(24.0), np.full(24, 10.0)])
    st = daily_stats(loads, 24)
    assert st["daily_max"].tolist() == [23.0, 10.0]
    assert st["daily_min"].tolist() == [0.0, 10.0]
    assert st["avg_daily_range"] == pytest.approx((23.0 + 0.0) / 2)
    assert st["overall_max"] == 23.0
    np.testing.assert_allclose(
        st["composite_day"], (np.arange(24.0) + 10.0) / 2
    )


def test_daily_stats_insufficient_data():
    assert daily_stats(np.arange(10.0), 24) == {}


def test_stats_table_formats():
    st = daily_stats(np.arange(24.0), 24)
    txt = stats_table([("run-a", st), ("run-b", {})])
    assert "run-a" in txt and "run-b" in txt
    assert "23.000" in txt  # overall max
    assert txt.count("\n") >= 5


@pytest.fixture(scope="module")
def finished_run(tmp_path_factory):
    """A tiny finished baseline run in a temp outputs dir."""
    from dragg_tpu.aggregator import Aggregator

    td = tmp_path_factory.mktemp("outputs_root")
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 3
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 0
    cfg["simulation"]["end_datetime"] = "2015-01-02 00"
    cfg["simulation"]["run_rl_simplified"] = True
    cfg["home"]["hems"]["prediction_horizon"] = 2
    cfg["tpu"]["admm_iters"] = 200
    out = str(td / "outputs")
    agg = Aggregator(cfg, data_dir=None, outputs_dir=out)
    agg.run()
    return cfg, out, agg


def test_discovery_finds_cases(finished_run):
    cfg, out, agg = finished_run
    r = Reformat(config=cfg, outputs_dir=out)
    cases = {f["case"] for f in r.files}
    assert cases == {"baseline", "simplified"}
    # The simplified case carries agent telemetry.
    simp = next(f for f in r.files if f["case"] == "simplified")
    assert "q_results" in simp


def test_get_type_list(finished_run):
    cfg, out, agg = finished_run
    r = Reformat(config=cfg, outputs_dir=out)
    base_homes = r.get_type_list("base")
    # Summary-only runs (the simplified case) must NOT empty the
    # intersection: the result equals the baseline's base homes exactly.
    data = json.load(open(next(f for f in r.files if f["case"] == "baseline")["results"]))
    expected = {n for n, h in data.items() if isinstance(h, dict) and h.get("type") == "base"}
    assert base_homes == expected
    assert len(base_homes) >= 1


def test_figures_and_save(finished_run):
    cfg, out, agg = finished_run
    r = Reformat(config=cfg, outputs_dir=out)
    figs = r.main(save=True)
    assert len(figs) >= 3
    pngs = os.listdir(r.save_path)
    assert any(p.endswith(".png") for p in pngs)
    assert hasattr(r, "table") and "baseline" in r.table


def test_plot_all_homes(finished_run):
    """Every home in the run gets its own figure (dragg/reformat.py:298-309)."""
    cfg, out, agg = finished_run
    r = Reformat(config=cfg, outputs_dir=out)
    figs = r.plot_all_homes()
    assert len(figs) == cfg["community"]["total_number_homes"]
    names = {n for n, _ in figs}
    assert len(names) == len(figs)
    for _, fig in figs:
        assert fig is not None and fig.axes


def test_plot_max_and_12hravg(finished_run):
    cfg, out, agg = finished_run
    r = Reformat(config=cfg, outputs_dir=out)
    fig = r.plot_max_and_12hravg()
    assert fig is not None
    ax = fig.axes[0]
    assert ax.get_title() == "12 Hour Avg and Daily Max"
    labels = [t.get_label() for t in ax.get_lines()]
    assert any("Daily Max" in l for l in labels)
    assert any("12 Hr Avg" in l for l in labels)


def test_single_home_env_overlay_and_price(finished_run):
    """Environmental overlay (OAT/GHI + secondary TOU axis) and the price
    trace appear on single-home figures (dragg/reformat.py:206-211,229-244)."""
    cfg, out, agg = finished_run
    r = Reformat(config=cfg, outputs_dir=out)
    fig = r.plot_single_home()
    assert fig is not None
    assert len(fig.axes) == 2  # primary + twinx price axis
    prim, pax = fig.axes
    prim_labels = [t.get_label() for t in prim.get_lines()]
    assert any("OAT" in l for l in prim_labels)
    assert any("GHI" in l for l in prim_labels)
    pax_labels = [t.get_label() for t in pax.get_lines()]
    assert any("TOU" in l for l in pax_labels)
    assert pax.get_ylabel() == "Price ($/kWh)"


def test_missing_outputs_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        Reformat(config=default_config(), outputs_dir=str(tmp_path / "nope"))


_CLI_TOML = """
[community]
total_number_homes = 2
homes_battery = 0
homes_pv = 0
homes_pv_battery = 0
house_p_avg = 1.2

[simulation]
start_datetime = "2015-01-01 00"
end_datetime = "2015-01-01 06"
random_seed = 12
check_type = "all"
run_rbo_mpc = true
checkpoint_interval = "daily"
named_version = "test"

[agg]
base_price = 0.07
subhourly_steps = 1
tou_enabled = true

[home.hvac]
r_dist = [6.8, 9.2]
c_dist = [4.25, 5.75]
p_cool_dist = [3.5, 3.5]
p_heat_dist = [3.5, 3.5]
temp_sp_dist = [18, 22]
temp_deadband_dist = [2, 3]

[home.wh]
r_dist = [18.7, 25.3]
p_dist = [2.5, 2.5]
sp_dist = [45.5, 48.5]
deadband_dist = [9, 12]
size_dist = [200, 300]
waterdraw_file = "waterdraw_profiles.csv"

[home.battery]
max_rate = [3, 5]
capacity = [9.0, 13.5]
lower_bound = [0.01, 0.15]
upper_bound = [0.85, 0.99]
charge_eff = [0.85, 0.95]
discharge_eff = [0.97, 0.99]

[home.pv]
area = [20, 32]
efficiency = [0.15, 0.2]

[home.hems]
prediction_horizon = 2
sub_subhourly_steps = 6
discount_factor = 0.92
solver = "admm"

[tpu]
admm_iters = 200
"""


def test_cli_run_and_reformat(tmp_path):
    """End-to-end CLI: run a tiny sim from a TOML file, then reformat it —
    the reference's main.py flow (dragg/main.py:4-17)."""
    from dragg_tpu.__main__ import main

    cfg_path = str(tmp_path / "config.toml")
    with open(cfg_path, "w") as f:
        f.write(_CLI_TOML)
    out = str(tmp_path / "outputs")
    assert main(["run", "--config", cfg_path, "--outputs-dir", out]) == 0
    assert main(["reformat", "--config", cfg_path, "--outputs-dir", out, "--no-save"]) == 0


def test_cli_parser():
    from dragg_tpu.__main__ import build_parser

    p = build_parser()
    args = p.parse_args(["run", "--outputs-dir", "x"])
    assert args.cmd == "run" and args.outputs_dir == "x"
    args = p.parse_args(["reformat", "--home", "Bob-ABCDE", "--no-save"])
    assert args.cmd == "reformat" and args.home == "Bob-ABCDE"


# ------------------------------------------------------------------ dashboard

def test_dashboard_index_and_figures(finished_run):
    """The plotter.py-equivalent webapp renders an index over the discovered
    runs and serves every comparison figure as SVG."""
    from dragg_tpu.dashboard import Dashboard

    cfg, out, agg = finished_run
    dash = Dashboard(config=cfg, outputs_dir=out)
    page = dash.index_html()
    assert "baseline" in page and "Daily statistics" in page
    # Every discovered run's results path is listed.
    for f in dash.ref.files:
        assert f["results"] in page
    svg = dash.render_figure("baseline")
    assert svg is not None and b"<svg" in svg[:500]
    assert dash.render_figure("nonexistent") is None
    # Per-home drill-down mirrors plot_single_home.
    homes = dash._home_names()
    assert homes
    svg = dash.render_figure("single_home", home=homes[0])
    assert svg is not None and b"<svg" in svg[:500]


def test_dashboard_http_roundtrip(finished_run):
    """Real HTTP round-trip on an ephemeral port."""
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    from dragg_tpu.dashboard import Dashboard, make_handler

    cfg, out, agg = finished_run
    dash = Dashboard(config=cfg, outputs_dir=out)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(dash))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as r:
            assert r.status == 200
            assert "dragg_tpu dashboard" in r.read().decode()
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/fig/baseline.svg") as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "image/svg+xml"
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/fig/nope.svg")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_cli_parser_dashboard():
    from dragg_tpu.__main__ import build_parser

    args = build_parser().parse_args(["dashboard", "--port", "9000"])
    assert args.cmd == "dashboard" and args.port == 9000


def test_cli_sweep(tmp_path):
    """Horizon sweep: one run per horizon, parametric comparison discovers
    all of them (the reference paper's horizon study workflow)."""
    from dragg_tpu.__main__ import main

    cfg_path = str(tmp_path / "config.toml")
    with open(cfg_path, "w") as f:
        f.write(_CLI_TOML)
    out = str(tmp_path / "outputs")
    assert main(["sweep", "--horizons", "2,3", "--config", cfg_path,
                 "--outputs-dir", out, "--no-figures"]) == 0
    # Both horizon runs exist on disk under their own run dirs.
    import glob

    runs = glob.glob(os.path.join(out, "*", "*horizon_*", "version-*",
                                  "baseline", "results.json"))
    horizons = set()
    for p in runs:
        with open(p) as f:
            horizons.add(json.load(f)["Summary"]["horizon"])
    assert horizons == {2, 3}
