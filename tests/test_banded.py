"""Banded Schur factorization (dragg_tpu/ops/banded.py): RCM ordering,
band-Cholesky scans, and equality with the dense factorization path."""

import numpy as np
import pytest

import jax.numpy as jnp

from dragg_tpu.ops.banded import (
    BandPlan,
    band_scatter,
    banded_cholesky,
    banded_explicit_inverse,
    banded_forward_solve,
    plan_for,
    rcm_order,
)


def _random_banded_spd(rng, m, bw, B=4):
    A = np.zeros((B, m, m))
    for k in range(bw + 1):
        v = rng.randn(B, m - k) * (0.5 ** k)
        idx = np.arange(m - k)
        A[:, idx + k, idx] += v
        if k:
            A[:, idx, idx + k] += v
    # Make SPD: A <- A Aᵀ + I (bandwidth doubles; rebuild band from product).
    S = np.einsum("bij,bkj->bik", A, A) + 3.0 * np.eye(m)
    return S.astype(np.float32)


def test_rcm_reduces_bandwidth():
    rng = np.random.RandomState(0)
    m = 40
    # A path graph scrambled by a random permutation.
    scramble = rng.permutation(m)
    rows = scramble[np.arange(m - 1)]
    cols = scramble[np.arange(1, m)]
    perm = rcm_order(rows, cols, m)
    inv = np.empty(m, dtype=int)
    inv[perm] = np.arange(m)
    assert int(np.max(np.abs(inv[rows] - inv[cols]))) == 1


def test_banded_cholesky_matches_dense():
    rng = np.random.RandomState(1)
    m, bw = 17, 3
    S = _random_banded_spd(rng, m, bw)
    bw2 = 2 * bw  # product bandwidth
    Sb = np.zeros((S.shape[0], m, bw2 + 1), np.float32)
    for k in range(bw2 + 1):
        idx = np.arange(m - k)
        Sb[:, idx + k, k] = S[:, idx + k, idx]
    Lb = np.asarray(banded_cholesky(jnp.asarray(Sb), bw2))
    L_ref = np.linalg.cholesky(S.astype(np.float64))
    for k in range(bw2 + 1):
        idx = np.arange(m - k)
        np.testing.assert_allclose(Lb[:, idx + k, k], L_ref[:, idx + k, idx],
                                   rtol=2e-4, atol=2e-4)


def test_banded_forward_solve():
    rng = np.random.RandomState(2)
    m, bw = 12, 2
    S = _random_banded_spd(rng, m, bw)
    bw2 = 2 * bw
    Sb = np.zeros((S.shape[0], m, bw2 + 1), np.float32)
    for k in range(bw2 + 1):
        idx = np.arange(m - k)
        Sb[:, idx + k, k] = S[:, idx + k, idx]
    Lb = banded_cholesky(jnp.asarray(Sb), bw2)
    R = rng.randn(S.shape[0], m, 3).astype(np.float32)
    Y = np.asarray(banded_forward_solve(Lb, jnp.asarray(R), bw2))
    L_ref = np.linalg.cholesky(S.astype(np.float64))
    Y_ref = np.linalg.solve(L_ref, R.astype(np.float64))
    np.testing.assert_allclose(Y, Y_ref, rtol=5e-4, atol=5e-4)


def test_banded_factor_solver_equivalence():
    """The full ADMM with banded_factor=True must walk the same trajectory
    as the dense path (same iterations, same solutions) on the real QP."""
    import sys
    sys.path.insert(0, "tests")
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.ops.admm import admm_solve_qp

    qp, pat = _assemble_real_step(horizon_hours=8, n_homes=6)
    dense = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          iters=2000, banded_factor=False)
    banded = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                           iters=2000, banded_factor=True)
    assert int(dense.iters) == int(banded.iters)
    np.testing.assert_array_equal(np.asarray(dense.solved), np.asarray(banded.solved))
    np.testing.assert_allclose(np.asarray(banded.x), np.asarray(dense.x),
                               rtol=1e-3, atol=1e-3)


def test_plan_bandwidth_on_real_pattern():
    import sys
    sys.path.insert(0, "tests")
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.ops.admm import _schur_structure_for

    for H in (4, 24):
        qp, pat = _assemble_real_step(horizon_hours=H, n_homes=6)
        plan = plan_for(_schur_structure_for(pat), pat.m)
        assert plan is not None
        assert plan.bw <= 6, f"H={H}: RCM bandwidth {plan.bw}"
        # Every original index appears exactly once in the permutation.
        assert sorted(plan.perm.tolist()) == list(range(pat.m))
