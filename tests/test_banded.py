"""Banded Schur factorization (dragg_tpu/ops/banded.py): RCM ordering,
band-Cholesky scans, and equality with the dense factorization path."""

import numpy as np
import pytest

import jax.numpy as jnp

from dragg_tpu.ops.banded import (
    banded_cholesky,
    banded_forward_solve,
    plan_for,
    rcm_order,
)


def _random_banded_spd(rng, m, bw, B=4):
    A = np.zeros((B, m, m))
    for k in range(bw + 1):
        v = rng.randn(B, m - k) * (0.5 ** k)
        idx = np.arange(m - k)
        A[:, idx + k, idx] += v
        if k:
            A[:, idx, idx + k] += v
    # Make SPD: A <- A Aᵀ + I (bandwidth doubles; rebuild band from product).
    S = np.einsum("bij,bkj->bik", A, A) + 3.0 * np.eye(m)
    return S.astype(np.float32)


def test_rcm_reduces_bandwidth():
    rng = np.random.RandomState(0)
    m = 40
    # A path graph scrambled by a random permutation.
    scramble = rng.permutation(m)
    rows = scramble[np.arange(m - 1)]
    cols = scramble[np.arange(1, m)]
    perm = rcm_order(rows, cols, m)
    inv = np.empty(m, dtype=int)
    inv[perm] = np.arange(m)
    assert int(np.max(np.abs(inv[rows] - inv[cols]))) == 1


def test_banded_cholesky_matches_dense():
    rng = np.random.RandomState(1)
    m, bw = 17, 3
    S = _random_banded_spd(rng, m, bw)
    bw2 = 2 * bw  # product bandwidth
    Sb = np.zeros((S.shape[0], m, bw2 + 1), np.float32)
    for k in range(bw2 + 1):
        idx = np.arange(m - k)
        Sb[:, idx + k, k] = S[:, idx + k, idx]
    Lb = np.asarray(banded_cholesky(jnp.asarray(Sb), bw2))
    L_ref = np.linalg.cholesky(S.astype(np.float64))
    for k in range(bw2 + 1):
        idx = np.arange(m - k)
        np.testing.assert_allclose(Lb[:, idx + k, k], L_ref[:, idx + k, idx],
                                   rtol=2e-4, atol=2e-4)


def test_banded_forward_solve():
    rng = np.random.RandomState(2)
    m, bw = 12, 2
    S = _random_banded_spd(rng, m, bw)
    bw2 = 2 * bw
    Sb = np.zeros((S.shape[0], m, bw2 + 1), np.float32)
    for k in range(bw2 + 1):
        idx = np.arange(m - k)
        Sb[:, idx + k, k] = S[:, idx + k, idx]
    Lb = banded_cholesky(jnp.asarray(Sb), bw2)
    R = rng.randn(S.shape[0], m, 3).astype(np.float32)
    Y = np.asarray(banded_forward_solve(Lb, jnp.asarray(R), bw2))
    L_ref = np.linalg.cholesky(S.astype(np.float64))
    Y_ref = np.linalg.solve(L_ref, R.astype(np.float64))
    np.testing.assert_allclose(Y, Y_ref, rtol=5e-4, atol=5e-4)


def test_banded_factor_solver_equivalence():
    """The full ADMM with banded_factor=True must walk the same trajectory
    as the dense path (same iterations, same solutions) on the real QP."""
    import sys
    sys.path.insert(0, "tests")
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.ops.admm import admm_solve_qp

    qp, pat = _assemble_real_step(horizon_hours=8, n_homes=6)
    dense = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          iters=2000, banded_factor=False)
    banded = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                           iters=2000, banded_factor=True)
    assert int(dense.iters) == int(banded.iters)
    np.testing.assert_array_equal(np.asarray(dense.solved), np.asarray(banded.solved))
    np.testing.assert_allclose(np.asarray(banded.x), np.asarray(dense.x),
                               rtol=1e-3, atol=1e-3)


def test_plan_bandwidth_on_real_pattern():
    import sys
    sys.path.insert(0, "tests")
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.ops.admm import _schur_structure_for

    for H in (4, 24):
        qp, pat = _assemble_real_step(horizon_hours=H, n_homes=6)
        plan = plan_for(_schur_structure_for(pat), pat.m)
        assert plan is not None
        assert plan.bw <= 6, f"H={H}: RCM bandwidth {plan.bw}"
        # Every original index appears exactly once in the permutation.
        assert sorted(plan.perm.tolist()) == list(range(pat.m))


def test_band_solve_backend_equivalence():
    """solve_backend='band' (no dense (B,m,m) inverse anywhere) must walk
    the same trajectory as 'dense_inv' on the real QP."""
    import sys
    sys.path.insert(0, "tests")
    from test_qp_parity import _assemble_real_step

    from dragg_tpu.ops.admm import admm_solve_qp

    qp, pat = _assemble_real_step(horizon_hours=8, n_homes=6)
    dense = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                          iters=2000, solve_backend="dense_inv")
    band = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                         iters=2000, solve_backend="band")
    assert int(dense.iters) == int(band.iters)
    np.testing.assert_array_equal(np.asarray(dense.solved), np.asarray(band.solved))
    np.testing.assert_allclose(np.asarray(band.x), np.asarray(dense.x),
                               rtol=1e-3, atol=1e-3)


def test_band_backend_engine_chunk(tiny_config):
    """The engine's cached-factor MPC path (stale band factor + refinement)
    runs and solves with solve_backend='band'."""
    import copy

    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    cfg = copy.deepcopy(tiny_config)
    cfg["home"]["hems"]["solver"] = "admm"  # the band solve BACKEND is an
    # ADMM knob; under the ipm default this test would never exercise it
    cfg["tpu"]["admm_solve_backend"] = "band"
    env = load_environment(cfg, data_dir=None)
    dt = int(cfg["agg"]["subhourly_steps"])
    wd = load_waterdraw_profiles(None, seed=int(cfg["simulation"]["random_seed"]))
    homes = create_homes(cfg, 24 * dt, dt, wd)
    hems = cfg["home"]["hems"]
    batch = build_home_batch(homes, int(hems["prediction_horizon"]) * dt, dt,
                             int(hems["sub_subhourly_steps"]))
    eng = make_engine(batch, env, cfg, 0)
    # The factor carry holds the small band factor, not a dense inverse.
    f0 = eng.init_factor()
    assert f0.Sinv.shape[-1] <= 13  # bw+1, not m
    state, outs = eng.run_chunk(eng.init_state(), 0,
                                np.zeros((6, eng.params.horizon), np.float32))
    assert float(np.asarray(outs.correct_solve).mean()) > 0.9
    assert np.isfinite(np.asarray(outs.agg_load)).all()


def test_resolve_backend_auto():
    from dragg_tpu.ops.admm import resolve_backend

    assert resolve_backend("auto", 100, 77, True) == "dense_inv"
    assert resolve_backend("auto", 200_000, 77, True) == "band"  # >1 GB Sinv
    assert resolve_backend("auto", 200_000, 77, False) == "dense_inv"
    assert resolve_backend("dense_inv", 10, 5, False) == "dense_inv"
    with pytest.raises(ValueError):
        resolve_backend("band", 10, 5, False)
    with pytest.raises(ValueError):
        resolve_backend("nope", 10, 5, True)


def test_resolve_backend_shard_and_dtype_aware():
    from dragg_tpu.ops.admm import resolve_backend

    # 50k homes over 8 shards, m=149: global Sinv ~4.4 GB but per-shard
    # ~555 MB — stays on the dense path.
    assert resolve_backend("auto", 50_000, 149, True, n_shards=8) == "dense_inv"
    assert resolve_backend("auto", 50_000, 149, True, n_shards=1) == "band"
    # bf16 halves the bytes: 2x the homes fit before the switch
    # (60k homes x m=77: f32 Sinv ~1.4 GB, bf16 ~0.7 GB).
    assert resolve_backend("auto", 60_000, 77, True, elem_bytes=2) == "dense_inv"
    assert resolve_backend("auto", 60_000, 77, True, elem_bytes=4) == "band"
