"""Type-bucketed shape specialization (tpu.bucketed) — parity + plumbing.

The bucketed engine solves each home-type bucket at a type-specialized
(n, m) shape instead of padding every home to the superset pv_battery
layout (docs/architecture.md §10).  Parity follows the
tests/test_qp_parity.py convention: compare OBJECTIVES and applied
actions, not solver iterates — per-home trajectories are identical math
modulo fp reassociation across the different batch shapes, but
degenerate variables (curtailment at GHI=0) may legitimately differ.
"""

import copy

import numpy as np
import pytest

from dragg_tpu.config import default_config
from dragg_tpu.data import load_environment, load_waterdraw_profiles
from dragg_tpu.engine import (
    BUCKETED_MIN_HOMES,
    make_engine,
    resolve_bucket_plan,
)
from dragg_tpu.homes import build_home_batch, create_homes, type_bucket_ranges
from dragg_tpu.ops.qp import QPLayout, TYPE_SPECS


# ------------------------------------------------------------ layout/plan
def test_layout_specs_shapes():
    """Each spec's (n, m_eq) drops exactly the absent blocks; the superset
    spec reproduces the historical fixed layout."""
    H = 24
    lay = QPLayout(H)
    assert (lay.n, lay.m_eq) == (9 * H + 5, 3 * H + 5)
    assert lay.i_curt == 5 * H and lay.i_eb == 8 * H + 2
    expect = {
        "pv_battery": (9 * H + 5, 3 * H + 5),
        "pv_only": (6 * H + 4, 2 * H + 4),
        "battery_only": (8 * H + 5, 3 * H + 5),
        "base": (5 * H + 4, 2 * H + 4),
        # Scenario types (ISSUE 10): ev = base + H charge columns +
        # (H+1) SOC columns + (H+1) pin/dynamics rows; heat_pump changes
        # coefficients (COP band), never shapes.
        "ev": (7 * H + 5, 3 * H + 5),
        "heat_pump": (5 * H + 4, 2 * H + 4),
    }
    for name, spec in TYPE_SPECS.items():
        lay_t = QPLayout(H, spec)
        assert (lay_t.n, lay_t.m_eq) == expect[name], name
        if not spec.has_batt:
            assert lay_t.i_pch is None and lay_t.i_eb is None \
                and lay_t.r_ebd is None
        if not spec.has_curt:
            assert lay_t.i_curt is None
        if not spec.has_ev:
            assert lay_t.i_evch is None and lay_t.i_eev is None \
                and lay_t.r_eevd is None
        assert lay_t.i_pgr is None  # grid block is an engine upgrade
        # The shared blocks keep their relative order: controls first,
        # then evolution states, then the one-step deterministic temps.
        assert lay_t.i_cool == 0 and lay_t.i_twh1 == lay_t.n - 1


def test_resolve_bucket_plan():
    """Tri-state resolution: auto thresholds, forced true/false, and the
    grouped-by-type requirement."""
    mixed = np.array([0] * 4 + [1] * 20 + [2] * 4 + [3] * 20)  # 48 homes
    tiny = np.array([0, 1, 3])
    all_superset = np.zeros(64, dtype=int)
    interleaved = np.array([0, 3, 0, 3] * 16)

    assert resolve_bucket_plan("false", mixed) is None
    plan = resolve_bucket_plan("auto", mixed)
    assert [p[0] for p in plan] == ["pv_battery", "pv_only",
                                    "battery_only", "base"]
    assert resolve_bucket_plan("auto", tiny) is None        # < min homes
    assert len(tiny) < BUCKETED_MIN_HOMES
    assert resolve_bucket_plan("auto", all_superset) is None  # no win
    assert resolve_bucket_plan("auto", interleaved) is None
    assert resolve_bucket_plan("true", all_superset) is not None
    with pytest.raises(ValueError, match="grouped"):
        resolve_bucket_plan("true", interleaved)
    # Absent types produce no range — never a zero-width bucket.
    assert type_bucket_ranges(np.array([1, 1, 3, 3])) == [
        ("pv_only", 0, 2), ("base", 2, 4)]


# ---------------------------------------------------------------- parity
def _mixed_setup(n=64, pv=26, bat=6, pvb=6, horizon=4):
    """The 64-home mixed community of the parity satellite (bench-mix
    ratios)."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = pv
    cfg["community"]["homes_battery"] = bat
    cfg["community"]["homes_pv_battery"] = pvb
    cfg["home"]["hems"]["prediction_horizon"] = horizon
    env = load_environment(cfg, data_dir=None)
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24, 1, wd)
    batch = build_home_batch(homes, horizon, 1,
                             int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    return cfg, env, batch


@pytest.fixture(scope="module")
def parity_runs():
    """Superset vs bucketed chunk outputs on the same 64-home community
    (module-scoped: three engine compiles, asserted by several tests)."""
    cfg, env, batch = _mixed_setup()
    cfg_sup = copy.deepcopy(cfg)
    cfg_sup["tpu"]["bucketed"] = "false"
    eng_sup = make_engine(batch, env, cfg_sup, 0)
    assert not eng_sup.bucketed
    eng_bkt = make_engine(batch, env, cfg, 0)  # auto → bucketed at 64 homes
    assert eng_bkt.bucketed
    rps = np.zeros((3, eng_sup.params.horizon), np.float32)
    _, out_sup = eng_sup.run_chunk(eng_sup.init_state(), 0, rps)
    _, out_bkt = eng_bkt.run_chunk(eng_bkt.init_state(), 0, rps)
    return cfg, env, batch, eng_sup, eng_bkt, out_sup, out_bkt


def _assert_outputs_match(out_ref, out_bkt, cols, s):
    """Shared parity assertions: objectives + applied k=0 actions +
    physical state, bucketed mapped back to community order."""
    from dragg_tpu.engine import OBS_FIELDS

    ref = {f: np.asarray(getattr(out_ref, f)) for f in out_ref._fields}
    bkt = {}
    for f in out_bkt._fields:
        if f in OBS_FIELDS:
            # Observatory folds are per-BUCKET (tests/test_observatory.py
            # owns their parity) — no home axis to re-order here.
            continue
        a = np.asarray(getattr(out_bkt, f))
        bkt[f] = a[:, cols] if a.ndim == 2 else a

    # Identical StepOutputs ordering: solvedness per home must line up
    # exactly (a permutation would scramble it across home types).
    np.testing.assert_array_equal(bkt["correct_solve"], ref["correct_solve"])

    # Objectives (the test_qp_parity convention): per-home step cost and
    # the aggregate, to solver tolerance.
    np.testing.assert_allclose(bkt["cost"], ref["cost"], rtol=1e-2, atol=2e-3)
    np.testing.assert_allclose(bkt["agg_cost"], ref["agg_cost"],
                               rtol=1e-2, atol=5e-3)
    np.testing.assert_allclose(bkt["agg_load"], ref["agg_load"],
                               rtol=1e-2, atol=5e-3)

    # Applied k=0 actions: duty counts are integers (integer_first_action
    # default); bucketing must not move any action by more than one count
    # (a rounding flip on a near-.5 relaxed value), and almost all must
    # match exactly.
    exact = total = 0
    for key in ("hvac_cool_on", "hvac_heat_on", "wh_heat_on"):
        counts_r = ref[key] * s
        counts_b = bkt[key] * s
        assert np.max(np.abs(counts_b - counts_r)) <= 1 + 1e-3, key
        exact += int(np.sum(np.abs(counts_b - counts_r) < 1e-3))
        total += counts_r.size
    assert exact / total >= 0.95, f"only {exact}/{total} actions match"
    np.testing.assert_allclose(bkt["p_batt_ch"], ref["p_batt_ch"],
                               atol=2e-3)
    np.testing.assert_allclose(bkt["p_batt_disch"], ref["p_batt_disch"],
                               atol=2e-3)
    # Physical state trajectories.
    np.testing.assert_allclose(bkt["temp_in"], ref["temp_in"], atol=1e-3)
    np.testing.assert_allclose(bkt["temp_wh"], ref["temp_wh"], atol=1e-3)
    np.testing.assert_allclose(bkt["e_batt"], ref["e_batt"], atol=2e-3)


def test_bucketed_matches_superset_single_device(parity_runs):
    cfg, _env, _batch, eng_sup, eng_bkt, out_sup, out_bkt = parity_runs
    s = eng_sup.params.s
    cols = eng_bkt.real_home_cols
    # Unsharded buckets carry no padding — slot order IS community order.
    np.testing.assert_array_equal(cols, np.arange(64))
    _assert_outputs_match(out_sup, out_bkt, cols, s)
    # Solver telemetry scalars merge as the binding bucket; they must stay
    # in the same ballpark as the superset solve's residuals.
    assert float(np.max(np.asarray(out_bkt.r_prim_max))) < 1.0


def test_bucketed_zero_blocks_are_exact(parity_runs):
    """Battery/PV outputs of homes without those blocks are EXACT zeros —
    identical to the superset path's clipped [0, 0] boxes."""
    _cfg, _env, batch, _eng_sup, eng_bkt, _out_sup, out_bkt = parity_runs
    cols = eng_bkt.real_home_cols
    no_batt = np.asarray(batch.has_batt) == 0
    no_pv = np.asarray(batch.has_pv) == 0
    for f in ("p_batt_ch", "p_batt_disch", "e_batt"):
        a = np.asarray(getattr(out_bkt, f))[:, cols]
        assert np.all(a[:, no_batt] == 0.0), f
    assert np.all(np.asarray(out_bkt.p_pv)[:, cols][:, no_pv] == 0.0)


@pytest.mark.slow  # round-11 tier-1 budget trim: single-device bucketed parity + test_parallel's sharded-engine parity keep both axes covered; this is their cross product
def test_bucketed_sharded_matches_superset_8dev_mesh(parity_runs):
    """The parity satellite's 8-device leg: bucketed + per-bucket shard
    padding on the conftest CPU mesh vs the single-device superset run.
    Residual-max scalars keep the established 1e-3 tolerance (max over
    non-contractive iterates amplifies per-compile fp wobble)."""
    from dragg_tpu.parallel import make_mesh, make_sharded_engine

    cfg, env, batch, eng_sup, _eng_bkt, out_sup, _out_bkt = parity_runs
    sh = make_sharded_engine(batch, env, cfg, 0, mesh=make_mesh(8))
    assert sh.bucketed
    # Per-bucket shard padding: every bucket's slot count divides the mesh.
    for b in sh.bucket_info():
        assert b["n_slots"] % 8 == 0 and b["n_slots"] > 0
    rps = np.zeros((3, sh.params.horizon), np.float32)
    state = sh.init_state()
    assert isinstance(state, tuple) and len(state) == 4
    assert "homes" in str(state[0].temp_in.sharding.spec)
    _, out_sh = sh.run_chunk(state, 0, rps)
    cols = sh.real_home_cols
    assert len(cols) == 64 and len(set(cols.tolist())) == 64
    _assert_outputs_match(out_sup, out_sh, cols, sh.params.s)
    for f in ("r_prim_max", "r_dual_max"):
        np.testing.assert_allclose(
            np.asarray(getattr(out_sh, f)),
            np.asarray(getattr(out_sup, f)), rtol=1e-3, atol=1e-3,
            err_msg=f)


def test_bucketed_checkpoint_roundtrip(parity_runs):
    """The per-bucket state tuple survives a save/load cycle through the
    structure-agnostic pytree checkpoint (resume carries bucketed runs)."""
    import os
    import tempfile

    _cfg, _env, _batch, _eng_sup, eng_bkt, _o, _o2 = parity_runs
    from dragg_tpu.checkpoint import load_pytree, save_pytree

    rps = np.zeros((2, eng_bkt.params.horizon), np.float32)
    state, _ = eng_bkt.run_chunk(eng_bkt.init_state(), 0, rps)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "state.npz")
        save_pytree(path, state)
        restored = load_pytree(path, eng_bkt.init_state())
    for st, rt in zip(state, restored):
        for name, a, b in zip(st._fields, st, rt):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)
