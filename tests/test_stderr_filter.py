"""The warm-cache AOT mismatch filter (utils/stderr_filter.py).

Round-5 root cause: XLA:CPU embeds LLVM tuning preferences
(``+prefer-no-gather``/``+prefer-no-scatter``) in serialized AOT results
and cpu_aot_loader.cc's load check compares them against detected host
ISA features, which never contain tuning prefs — so every warm
persistent-cache load errors on the very host that compiled the entry
(docs/perf_notes.md round 5).  The filter must drop exactly that
signature and nothing else.
"""

import os
import subprocess
import sys

from dragg_tpu.utils.stderr_filter import line_is_benign_aot_mismatch

_TUNING = (
    b"E0731 16:41:20.874301 11256 cpu_aot_loader.cc:210] Loading XLA:CPU "
    b"AOT result. Target machine feature +prefer-no-gather is not  "
    b"supported on the host machine. Machine type used for XLA:CPU "
    b"compilation doesn't match the machine type for execution. Compile "
    b"machine features: [+64bit,+avx512f,+prefer-no-gather] vs host "
    b"machine features: [64bit,avx512f]. This could lead to execution "
    b"errors such as SIGILL."
)
# A REAL cross-host ISA mismatch (the genuine SIGILL hazard the round-4
# fingerprint keying guards) must pass through untouched.
_REAL = _TUNING.replace(b"+prefer-no-gather is", b"+avx512vnni is")


def test_tuning_pref_line_is_benign():
    assert line_is_benign_aot_mismatch(_TUNING)
    assert line_is_benign_aot_mismatch(
        _TUNING.replace(b"prefer-no-gather", b"prefer-no-scatter"))


def test_real_isa_mismatch_stays_loud():
    assert not line_is_benign_aot_mismatch(_REAL)


def test_ordinary_stderr_untouched():
    for line in (b"", b"Traceback (most recent call last):",
                 b"E0731 something else about cpu_aot_loader.cc entirely",
                 b"prefer-no-gather mentioned outside the loader message"):
        assert not line_is_benign_aot_mismatch(line)


def test_warm_cache_smoke_zero_mismatch_lines(tmp_path):
    """End-to-end: two child runs sharing a persistent cache; the second
    (warm) run with the filter installed must emit ZERO cpu_aot_loader
    mismatch lines while ordinary stderr still arrives (VERDICT r4
    next-7 'done' criterion, scaled to a unit-size program)."""
    prog = (
        "import os, sys\n"
        "from dragg_tpu.utils.stderr_filter import install_aot_mismatch_filter\n"
        "assert install_aot_mismatch_filter()\n"
        "import jax, jax.numpy as jnp, numpy as np\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.config.update('jax_compilation_cache_dir', sys.argv[1])\n"
        "jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)\n"
        "jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)\n"
        "f = jax.jit(lambda x: jnp.sin(x) @ jnp.cos(x).T)\n"
        "f(np.ones((128, 128), np.float32)).block_until_ready()\n"
        "print('OK', flush=True)\n"
        "sys.stderr.write('ordinary stderr line\\n')\n"
        "import time; time.sleep(0.2)\n"  # let the pump drain
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    cache = str(tmp_path / "cache")
    for i in range(2):
        r = subprocess.run([sys.executable, "-c", prog, cache],
                           capture_output=True, timeout=300, env=env)
        assert r.returncode == 0, r.stderr.decode()
        assert b"OK" in r.stdout
    assert b"cpu_aot_loader" not in r.stderr, r.stderr.decode()
    assert b"ordinary stderr line" in r.stderr


def test_crash_traceback_survives_exit_drain():
    """The atexit drain must deliver stderr written just before an
    uncaught exception kills the process — bench.py's child stderr_tail
    diagnostics depend on those final bytes (round-5 review finding)."""
    prog = (
        "from dragg_tpu.utils.stderr_filter import install_aot_mismatch_filter\n"
        "assert install_aot_mismatch_filter()\n"
        "raise RuntimeError('engine build exploded')\n"
    )
    env = {**os.environ}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, "-c", prog],
                       capture_output=True, timeout=120, env=env)
    assert r.returncode != 0
    assert b"engine build exploded" in r.stderr, r.stderr.decode()
    assert b"Traceback" in r.stderr
