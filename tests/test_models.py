"""Physics-core unit tests vs closed-form RC responses (SURVEY.md §4(a))."""

import numpy as np
import jax.numpy as jnp

from dragg_tpu.models import battery_step, expand_draws, fallback_control, hvac_step, pv_power, wh_mix, wh_step


class TestThermal:
    def test_hvac_free_response_decays_to_oat(self):
        """With no HVAC, T relaxes exponentially toward OAT with time
        constant R*C; one step must match the explicit-Euler closed form."""
        R, C, dt = 8.0, 5000.0, 1
        T0, oat = 20.0, 0.0
        T1 = float(hvac_step(T0, oat, R, C, dt, 0.0, 0.0, 0.0, 0.0))
        expected = T0 + 3600.0 * (oat - T0) / (R * C * dt)
        assert abs(T1 - expected) < 1e-9
        assert T1 < T0  # cooling toward oat

    def test_hvac_heat_and_cool_signs(self):
        R, C, dt, P = 8.0, 5000.0, 1, 3.5
        base = float(hvac_step(20.0, 20.0, R, C, dt, 0.0, 0.0, P, P))
        heat = float(hvac_step(20.0, 20.0, R, C, dt, 0.0, 1.0, P, P))
        cool = float(hvac_step(20.0, 20.0, R, C, dt, 1.0, 0.0, P, P))
        assert heat > base > cool
        assert abs((heat - base) - 3600.0 * P / (C * dt)) < 1e-9

    def test_wh_mix_conserves_energy(self):
        """Mixing: (T*(size-draw) + tap*draw)/size — a full-tank draw gives
        tap temp, zero draw leaves T unchanged."""
        assert abs(float(wh_mix(50.0, 0.0, 200.0)) - 50.0) < 1e-12
        assert abs(float(wh_mix(50.0, 200.0, 200.0)) - 15.0) < 1e-12
        half = float(wh_mix(50.0, 100.0, 200.0))
        assert abs(half - 32.5) < 1e-12

    def test_wh_step_equilibrium(self):
        """At T == Tin with heater off, temperature is unchanged."""
        assert abs(float(wh_step(20.0, 20.0, 20000.0, 840.0, 1, 0.0, 0.0)) - 20.0) < 1e-12

    def test_batched_shapes(self):
        n = 7
        T = jnp.linspace(18, 22, n)
        out = hvac_step(T, 10.0, jnp.full(n, 8.0), jnp.full(n, 5000.0), 1, jnp.zeros(n), jnp.ones(n), 0.5, 0.5)
        assert out.shape == (n,)


class TestBattery:
    def test_charge_discharge_efficiency(self):
        e = float(battery_step(5.0, 1.0, 0.0, 0.9, 0.98, 1))
        assert abs(e - 5.9) < 1e-12
        e = float(battery_step(5.0, 0.0, -1.0, 0.9, 0.98, 1))
        assert abs(e - (5.0 - 1.0 / 0.98)) < 1e-9


class TestPV:
    def test_pv_power(self):
        p = float(pv_power(1000.0, 25.0, 0.18, 0.0))
        assert abs(p - 4.5) < 1e-12
        assert float(pv_power(1000.0, 25.0, 0.18, 1.0)) == 0.0


class TestExpandDraws:
    def test_matches_reference_listcode_dt1(self):
        """Cross-check against a direct transcription of the reference's
        water_draws list arithmetic (dragg/mpc_calc.py:193-201)."""
        H, dt = 6, 1
        window = np.array([3.0, 0.0, 10.0, 2.0, 5.0, 1.0, 4.0])  # H//dt + 1 = 7
        raw = (np.repeat(window, dt) / dt).tolist()
        expect = raw[:dt]
        for i in range(dt, H + 1):
            expect.append(np.average(raw[i - 1 : i + 2]))
        got = np.asarray(expand_draws(jnp.asarray(window), dt, H))
        np.testing.assert_allclose(got, np.array(expect), rtol=1e-6)

    def test_matches_reference_listcode_dt2(self):
        H, dt = 8, 2
        window = np.array([3.0, 0.0, 10.0, 2.0, 5.0])  # H//dt + 1 = 5
        raw = (np.repeat(window, dt) / dt).tolist()
        expect = raw[:dt]
        for i in range(dt, H + 1):
            expect.append(np.average(raw[i - 1 : i + 2]))
        got = np.asarray(expand_draws(jnp.asarray(window), dt, H))
        np.testing.assert_allclose(got, np.array(expect), rtol=1e-6)

    def test_batched(self):
        w = jnp.asarray(np.random.RandomState(0).rand(4, 7))
        out = expand_draws(w, 1, 6)
        assert out.shape == (4, 7)


class TestFallback:
    def _params(self, n):
        return dict(
            hvac_r=jnp.full(n, 8.0), hvac_c=jnp.full(n, 5000.0),
            hvac_p_c=jnp.full(n, 0.58), hvac_p_h=jnp.full(n, 0.58),
            wh_r=jnp.full(n, 20000.0), wh_c=jnp.full(n, 840.0), wh_p=jnp.full(n, 0.42),
            temp_in_min=jnp.full(n, 19.0), temp_in_max=jnp.full(n, 21.0),
            temp_wh_min=jnp.full(n, 43.0), temp_wh_max=jnp.full(n, 50.0),
            cool_max=jnp.full(n, 0.0), heat_max=jnp.full(n, 6.0), wh_max=jnp.full(n, 6.0),
            dt=1,
        )

    def test_bang_bang_heats_when_cold(self):
        n = 1
        res = fallback_control(
            jnp.array([10]), 5, 8,
            jnp.zeros(n), jnp.zeros(n), jnp.zeros(n),
            jnp.array([18.0]),           # below temp_in_min -> heat on
            jnp.array([40.0]),           # below temp_wh_min -> wh on
            0.0, **self._params(n),
        )
        assert float(res.heat_on[0]) == 6.0
        assert float(res.cool_on[0]) == 0.0
        assert float(res.wh_on[0]) == 6.0
        assert float(res.temp_in[0]) > 18.0
        assert int(res.counter[0]) >= 8

    def test_in_band_idles(self):
        n = 1
        res = fallback_control(
            jnp.array([10]), 5, 8,
            jnp.zeros(n), jnp.zeros(n), jnp.zeros(n),
            jnp.array([20.0]), jnp.array([45.0]), 15.0, **self._params(n),
        )
        assert float(res.heat_on[0]) == 0.0
        assert float(res.wh_on[0]) == 0.0

    def test_replay_path_uses_previous_plan(self):
        """counter < horizon and t > 0 -> replay the shifted plan value."""
        n = 1
        res = fallback_control(
            jnp.array([2]), 5, 8,
            jnp.array([0.0]), jnp.array([3.0]), jnp.array([2.0]),  # replayed duties
            jnp.array([20.0]), jnp.array([45.0]), 15.0, **self._params(n),
        )
        # In-band temps: the replayed duties survive unmodified.
        assert float(res.heat_on[0]) == 3.0
        assert float(res.wh_on[0]) == 2.0
        assert int(res.counter[0]) == 2
