"""Closed-loop integer parity vs the HiGHS MILP optimum (VERDICT r4 #4).

The reference applies the first action of a per-home MIXED-INTEGER
program every step (GLPK_MI; integer duty counts in [0, s] —
dragg/mpc_calc.py:171-173,344-349).  Round 4 measured the LP
relaxation's single-step gap at 2.7-3.6 % and shipped the
``integer_first_action`` pin-and-re-solve repair; round 5 makes the
repair the DEFAULT.  This test closes the remaining evidence gap: it
bounds the **closed-loop cost** of the shipped default against a true
MILP oracle rolled forward through the same receding-horizon loop.

Both arms share the engine's own assembly (``_prepare`` is a pure
function of (state, t), and the per-step forecast-noise streams depend
only on (seed, t, home) — not on the trajectory), so the comparison
isolates solver semantics.  The oracle arm solves every home's step
MILP exactly (scipy.optimize.milp → HiGHS, integrality on all 3H duty
columns) and advances state through the engine's own
``recover_solution`` post-processing; the shipped arm is the public
``Engine.step`` with its defaults.

Budget: ≤1 % community cost gap over the day (SURVEY §4b's parity
budget, applied to the integer optimum rather than the LP relaxation).
"""

import numpy as np
import pytest
from scipy.optimize import Bounds, LinearConstraint, milp

import jax.numpy as jnp

from dragg_tpu.config import default_config
from dragg_tpu.data import load_environment, load_waterdraw_profiles
from dragg_tpu.engine import make_engine
from dragg_tpu.homes import build_home_batch, create_homes
from dragg_tpu.ops.qp import densify_A

H_HOURS = 8
N_HOMES = 6
N_STEPS = 24  # one simulated day


def _make_engine(solver="ipm"):
    cfg = default_config()
    cfg["home"]["hems"]["solver"] = solver
    cfg["community"]["total_number_homes"] = N_HOMES
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["simulation"]["end_datetime"] = "2015-01-02 00"
    cfg["home"]["hems"]["prediction_horizon"] = H_HOURS
    assert cfg["tpu"]["integer_first_action"] is True  # the shipped default
    env = load_environment(cfg)
    dt = env.dt
    waterdraw = load_waterdraw_profiles(None, seed=int(cfg["simulation"]["random_seed"]))
    homes = create_homes(cfg, 24 * dt, dt, waterdraw)
    hems = cfg["home"]["hems"]
    batch = build_home_batch(homes, H_HOURS * dt, dt,
                             int(hems["sub_subhourly_steps"]))
    return make_engine(batch, env, cfg, env.start_index(env.data_start))


def _milp_home(A, beq, l, u, q, int_cols):
    integrality = np.zeros(q.shape[0])
    integrality[int_cols] = 1
    res = milp(c=q,
               constraints=LinearConstraint(A, beq, beq),
               bounds=Bounds(np.where(np.isfinite(l), l, -np.inf),
                             np.where(np.isfinite(u), u, np.inf)),
               integrality=integrality)
    return res


@pytest.mark.slow
@pytest.mark.parametrize("solver", ["ipm", "reluqp"])
def test_closed_loop_cost_within_1pct_of_milp_oracle(solver):
    # Both arms per family: the oracle arm is solver-independent (exact
    # MILP through the engine's own _prepare/_finish), so running it per
    # family keeps the comparison self-contained; the reluqp arm is the
    # round-10 acceptance check that integer_first_action semantics are
    # unchanged under the pre-factorized dense family.
    eng = _make_engine(solver)
    lay, p = eng.layout, eng.params
    H, s = p.horizon, p.s
    n = eng.n_homes
    # All 3H duty-count columns are integer in the reference's program.
    int_cols = np.concatenate([
        np.arange(lay.i_cool, lay.i_cool + H),
        np.arange(lay.i_heat, lay.i_heat + H),
        np.arange(lay.i_wh, lay.i_wh + H),
    ])

    # --- Shipped arm: the public engine step with default (integer) semantics.
    state = eng.init_state()
    cost_ours = 0.0
    solved_ours = []
    for t in range(N_STEPS):
        state, out = eng.step(state, t, np.zeros((H,), np.float32))
        cost_ours += float(np.sum(np.asarray(out.cost)))
        solved_ours.append(np.asarray(out.correct_solve) == 1.0)

    # --- Oracle arm: exact per-home MILP each step; infeasible homes ride
    # the engine's OWN fallback controller (the reference does the same
    # when GLPK fails, dragg/mpc_calc.py:527-596) — the oracle solution is
    # packed into the solver's solution type and handed to ``_finish`` so
    # merge/fallback/state-advance are byte-identical to the shipped path.
    from dragg_tpu.ops.admm import ADMMSolution

    ostate = eng.init_state()
    cost_oracle = 0.0
    solved_oracle = []
    for t in range(N_STEPS):
        qp, aux = eng._prepare(eng._ctx0, ostate, jnp.asarray(t),
                               jnp.zeros((H,), jnp.float32))
        A = np.asarray(densify_A(eng.static.pattern, qp.vals), np.float64)
        beq = np.asarray(qp.b_eq, np.float64)
        l = np.asarray(qp.l_box, np.float64)
        u = np.asarray(qp.u_box, np.float64)
        q = np.asarray(qp.q, np.float64)
        xs, ok = [], []
        for i in range(n):
            res = _milp_home(A[i], beq[i], l[i], u[i], q[i], int_cols)
            feasible = res.status == 0
            ok.append(feasible)
            xs.append(res.x if feasible
                      else np.clip(np.zeros(l[i].shape[0]),
                                   np.where(np.isfinite(l[i]), l[i], 0.0),
                                   np.where(np.isfinite(u[i]), u[i], 0.0)))
        x = jnp.asarray(np.stack(xs), jnp.float32)
        okv = jnp.asarray(np.array(ok))
        zeros = jnp.zeros((n,), jnp.float32)
        sol = ADMMSolution(
            x=x, y_eq=jnp.zeros_like(qp.b_eq), y_box=jnp.zeros_like(x),
            r_prim=zeros, r_dual=zeros, solved=okv, infeasible=~okv,
            iters=jnp.asarray(0), rho=jnp.ones((n,), jnp.float32))
        ostate, out = eng._finish(eng._ctx0, ostate, jnp.asarray(t), sol,
                                  aux, sol)
        cost_oracle += float(np.sum(np.asarray(out.cost)))
        solved_oracle.append(np.asarray(out.correct_solve) == 1.0)

    # Apples-to-apples check: the shipped solver's solvedness verdict must
    # track HiGHS feasibility step-by-step (the single-step guarantee of
    # tests/test_qp_parity.py, here verified along the closed loop).
    # EXACT agreement required (round 6, VERDICT r5 weak #5): the 10k
    # forensics claim exact solvedness (35,399/35,399 HiGHS-infeasible,
    # 0 false-solves — docs/forensics_10k_*_r5.json) and this loop
    # measures 0 mismatches (docs/perf_notes.md round 6), so any slack
    # here would only mask a regression.
    mismatches = sum(int(np.sum(a != b))
                     for a, b in zip(solved_ours, solved_oracle))
    assert mismatches == 0, (
        f"{mismatches} home-step solvedness mismatches vs HiGHS along the loop")

    gap = (cost_ours - cost_oracle) / max(abs(cost_oracle), 1e-6)
    # ≤1 % closed-loop budget vs the INTEGER optimum (not the LP bound).
    # Ours may land slightly below the oracle's total: the repair pins
    # rounded counts against a fractional future plan, so individual
    # steps can trade differently than the exact MILP policy — bound the
    # magnitude both ways.
    assert abs(gap) <= 0.01, (
        f"closed-loop cost gap vs MILP oracle {gap:+.4%} "
        f"(ours {cost_ours:.3f} vs oracle {cost_oracle:.3f})")
