"""Fleet-batch result de-interleaving (ISSUE 13 satellite).

The serving worker's fleet engine coalesces up to C request groups into
ONE warm compiled solve (C identical community slots — serve/patterns
``seed_stride = 0``).  These tests pin the de-interleaving contract:
per-request outputs from a coalesced C-slot solve BIT-MATCH the same
requests solved individually — on the superset engine, the type-bucketed
engine (communities interleave inside each type bucket, so
``real_home_cols`` does real work), and the 8-device-mesh sharded
engine (conftest's virtual CPU mesh) — plus slot invariance (a group's
answer does not depend on which community slot it coalesced into) and
the multi-step chunk stream.

Bit-match holds by construction: per-home MPC problems are independent
(coupling enters only through the reward price, which is an input), the
compiled program is the same executable in both calls, and idle slots
carry the identical template state — so a home's row sees bitwise-equal
inputs either way.

The non-slow suite already runs at the 870 s tier-1 budget's edge
(round-15 note), so the heavier engine-compile legs (bucketed, sharded
mesh, C=1 parity) are slow-marked with the light superset siblings in
tier-1 — the round-15 precedent for real-engine coverage.
"""

from __future__ import annotations

import json

import pytest

from dragg_tpu.config import default_config
from dragg_tpu.serve.patterns import lane_config, normalize_spec
from dragg_tpu.serve.worker import EngineRunner


def _cfg(tmp_cache: str, *, bucketed=False, sharded=False) -> dict:
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 4
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["home"]["hems"]["prediction_horizon"] = 2
    cfg["tpu"]["compile_cache_dir"] = tmp_cache
    cfg["tpu"]["bucketed"] = bucketed
    cfg["tpu"]["sharded"] = sharded
    return cfg


def _fleet_runner(tmp_cache: str, C: int, **kw) -> EngineRunner:
    cfg = _cfg(tmp_cache, **kw)
    spec = normalize_spec({"fleet_slots": C}, {"fleet_slots": C})
    return EngineRunner(lane_config(cfg, spec))


GROUPS = [
    {"cslot": 0, "rp": 0.0,
     "requests": [{"id": "a0", "home": 1, "state": {"temp_in": 19.0}},
                  {"id": "a1", "home": 3}]},
    {"cslot": 1, "rp": 0.05,
     "requests": [{"id": "b0", "home": 1},
                  {"id": "b1", "home": 2, "state": {"temp_wh": 44.0}}]},
]


def _strip(resp: dict, drop=("cslot",)) -> dict:
    return {rid: {k: v for k, v in r.items() if k not in drop}
            for rid, r in resp.items()}


def _assert_bitmatch(runner: EngineRunner, groups=GROUPS, t: int = 0):
    coalesced = runner.solve(t, groups)
    solo: dict = {}
    for g in groups:
        solo.update(runner.solve(t, [g]))
    assert _strip(coalesced) == _strip(solo), (
        "coalesced C-slot solve does not bit-match the individually "
        "solved requests")
    return coalesced


@pytest.fixture(scope="module")
def cache_dir(tmp_path_factory):
    return str(tmp_path_factory.mktemp("serve_fleet_cc"))


@pytest.fixture(scope="module")
def fleet_superset(cache_dir):
    r = _fleet_runner(cache_dir, 2)
    assert r.fleet_slots == 2 and r.n_homes == 4
    assert not r.engine.bucketed
    return r


def test_coalesced_bitmatch_superset(fleet_superset):
    co = _assert_bitmatch(fleet_superset)
    assert {r["cslot"] for r in co.values()} == {0, 1}
    assert all(r["correct_solve"] == 1.0 for r in co.values())


def test_slot_invariance_and_rp_routing(fleet_superset):
    """A group's answers do not depend on which community slot it
    coalesced into, and the per-slot rp actually reaches its slot (a
    nonzero rp changes the answer vs rp=0 in the same solve)."""
    r = fleet_superset
    g0 = GROUPS[0]
    at0 = r.solve(0, [dict(g0, cslot=0)])
    at1 = r.solve(0, [dict(g0, cslot=1)])
    assert _strip(at0) == _strip(at1)
    # rp routing: group b solved at rp=0.05 differs from rp=0.0 (cost
    # includes the reward price, so this cannot alias).
    rp0 = r.solve(0, [dict(GROUPS[1], rp=0.0)])
    rp5 = r.solve(0, [GROUPS[1]])
    assert rp0["b0"]["cost"] != rp5["b0"]["cost"]


@pytest.mark.slow
def test_coalesced_bitmatch_bucketed(cache_dir):
    """Type-bucketed fleet engine: communities interleave INSIDE each
    type bucket (type-major batch), so the de-interleave goes through
    real_home_cols and the state-position inverse — still bit-exact."""
    r = _fleet_runner(cache_dir, 2, bucketed=True)
    assert r.engine.bucketed
    co = _assert_bitmatch(r)
    assert set(co) == {"a0", "a1", "b0", "b1"}


@pytest.mark.slow
def test_coalesced_bitmatch_sharded_mesh(cache_dir):
    """8-device mesh leg (conftest's virtual CPU mesh): the sharded
    fleet engine pads the home axis to the mesh; overrides re-commit the
    mesh placement and outputs de-interleave identically."""
    r = _fleet_runner(cache_dir, 2, sharded=True)
    assert getattr(r.engine, "mesh", None) is not None
    assert r.engine.mesh.devices.size == 8
    _assert_bitmatch(r)


@pytest.mark.slow
def test_single_community_parity(cache_dir, fleet_superset):
    """A request answered from a fleet slot matches the same request
    answered by the round-11 single-community (C=1) runner — the
    fleet's slot communities are genuine copies of the serving
    community (seed_stride 0), not lookalikes."""
    single = EngineRunner(_cfg(cache_dir))
    assert single.fleet_slots == 1
    g = GROUPS[0]
    from_single = _strip(single.solve(0, [dict(g, cslot=0)]))
    from_fleet = _strip(fleet_superset.solve(0, [dict(g, cslot=1)]))
    for rid in from_single:
        for field, v in from_single[rid].items():
            assert from_fleet[rid][field] == pytest.approx(v, abs=1e-4), \
                (rid, field)


def test_multistep_chunk_stream(fleet_superset, tmp_path):
    """steps = N re-runs the warm one-step program N times, emits one
    serve.chunk event per request per step on the telemetry stream, and
    the final response equals the last chunk's fields."""
    from dragg_tpu import telemetry

    telemetry.init_run(str(tmp_path))
    try:
        resp = fleet_superset.solve(0, [GROUPS[0]], steps=3)
        path = telemetry.events_path()
    finally:
        telemetry.close_run()
    chunks = [json.loads(line) for line in open(path)
              if '"serve.chunk"' in line]
    by_id: dict = {}
    for c in chunks:
        by_id.setdefault(c["id"], []).append(c)
    assert set(by_id) == {"a0", "a1"}
    for rid, evs in by_id.items():
        assert [e["step"] for e in evs] == [0, 1, 2]
        assert all(e["steps"] == 3 for e in evs)
        last = evs[-1]
        assert resp[rid]["steps"] == 3
        for field in ("p_grid", "cost", "temp_in"):
            assert resp[rid][field] == last[field]
    # Multi-step runs genuinely advance state: step 0 != step 2 indoor
    # temperature for the overridden home.
    a0 = by_id["a0"]
    assert a0[0]["temp_in"] != a0[2]["temp_in"]


def test_state_positions_cover_every_home(cache_dir, fleet_superset):
    """The state-position inverse is a bijection over the fleet's true
    homes, and output columns are distinct (no two requests can read
    the same merged column)."""
    r = fleet_superset
    n = r.fleet_slots * r.n_homes
    assert sorted(r._state_pos) == list(range(n))
    assert len({tuple(p) for p in r._state_pos.values()}) == n
    assert len(set(r._out_cols.tolist())) == n
