"""Native host-runtime tests: the C++ state bus and series collector, their
Python fallbacks, and cross-backend equivalence."""

import json
import threading

import numpy as np
import pytest

from dragg_tpu.native import SeriesCollector, StateBus, load_library


def test_native_library_builds():
    """The image ships g++; the native path must actually be exercised here,
    not silently fall back."""
    assert load_library() is not None


@pytest.fixture()
def bus():
    b = StateBus()
    b.flushall()
    yield b
    b.flushall()


def test_statebus_strings_and_hashes(bus):
    bus.set("start_hour_index", 42)
    assert bus.get("start_hour_index") == "42"
    assert bus.get("missing") is None
    bus.hset("current_values", "timestep", 7)
    bus.hset("current_values", "iteration", 3)
    assert bus.hget("current_values", "timestep") == "7"
    assert bus.hgetall("current_values") == {"timestep": "7", "iteration": "3"}
    bus.delete("current_values")
    assert bus.hgetall("current_values") == {}


def test_statebus_lists_redis_semantics(bus):
    vals = [0.0, 0.01, -0.02, 3.5]
    bus.rpush("reward_price", *vals)
    assert bus.llen("reward_price") == 4
    # Redis lrange is inclusive and supports negative indices.
    assert bus.lrange("reward_price", 0, -1) == [str(v) for v in vals]
    assert bus.lrange("reward_price", 1, 2) == ["0.01", "-0.02"]
    assert bus.lrange("reward_price", -2, -1) == ["-0.02", "3.5"]
    assert bus.lrange("nope", 0, -1) == []


def test_statebus_values_with_newlines(bus):
    """Length-prefixed framing must survive payloads with separators."""
    bus.hset("h", "a", "line1\nline2")
    bus.hset("h", "b", "x y z")
    assert bus.hgetall("h") == {"a": "line1\nline2", "b": "x y z"}
    bus.rpush("l", "with\nnewline", "with space")
    assert bus.lrange("l", 0, -1) == ["with\nnewline", "with space"]


def test_statebus_process_global(bus):
    """Every instance sees the same store (Redis-server semantics)."""
    bus.set("k", "v")
    assert StateBus().get("k") == "v"


def test_statebus_concurrent_disjoint_writers(bus):
    """The reference's structural race pattern: workers write disjoint hash
    keys concurrently, reader joins afterwards (SURVEY.md §5.2)."""
    def worker(i):
        for t in range(50):
            bus.hset(f"home_{i}", f"field_{t}", i * 1000 + t)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    for i in range(8):
        h = bus.hgetall(f"home_{i}")
        assert len(h) == 50
        assert h["field_49"] == str(i * 1000 + 49)


def test_statebus_multivalue_rpush_atomic(bus):
    """Variadic RPUSH is atomic in Redis: a concurrent llen must only ever
    observe multiples of the batch size (ADVICE r1 — the native path used
    one sb_rpush per value, each taking the lock independently)."""
    BATCH, ROUNDS = 7, 200
    stop = threading.Event()
    violations = []

    def reader():
        while not stop.is_set():
            n = bus.llen("atomic_l")
            if n % BATCH != 0:
                violations.append(n)
                return

    th = threading.Thread(target=reader)
    th.start()
    for r in range(ROUNDS):
        bus.rpush("atomic_l", *[f"{r}_{j}" for j in range(BATCH)])
    stop.set()
    th.join()
    assert not violations
    assert bus.llen("atomic_l") == BATCH * ROUNDS


def test_redis_client_singleton():
    from dragg_tpu.redis_client import RedisClient

    a = RedisClient()
    b = RedisClient()
    assert a is b
    a.conn.flushall()
    a.conn.rpush("GHI", 1.0, 2.0)
    assert b.conn.lrange("GHI", 0, -1) == ["1.0", "2.0"]
    a.conn.flushall()


# --------------------------------------------------------------- collector

def test_collector_chunks_and_export():
    col = SeriesCollector(3)
    chunk1 = np.arange(12.0).reshape(4, 3)
    chunk2 = np.arange(12.0, 18.0).reshape(2, 3)
    col.add_chunk("p_grid_opt", chunk1)
    col.add_chunk("p_grid_opt", chunk2)
    assert col.length("p_grid_opt", 0) == 6
    np.testing.assert_allclose(col.get("p_grid_opt", 1), [1, 4, 7, 10, 13, 16])
    col.import_series("p_grid_opt", 1, [9.0, 8.0])
    assert col.get("p_grid_opt", 1) == [9.0, 8.0]
    col.close()


def test_collector_shape_check():
    col = SeriesCollector(3)
    with pytest.raises(ValueError):
        col.add_chunk("x", np.zeros((2, 4)))
    col.close()


def test_collector_write_json_matches_python_json(tmp_path):
    """The native streaming writer must produce JSON that parses to exactly
    the structure Python's json module would emit."""
    col = SeriesCollector(2)
    rng = np.random.default_rng(3)
    data = rng.normal(size=(5, 2)) * 1e3
    ints = np.arange(10.0).reshape(5, 2)
    col.add_chunk("a", data)
    col.add_chunk("b", ints)
    path = str(tmp_path / "out.json")
    plan = [
        ("raw", '{"home0": {"type": "base", "a": '),
        ("series", "a", 0),
        ("raw", ', "b": '),
        ("series", "b", 0),
        ("raw", '}, "home1": {"a": '),
        ("series", "a", 1),
        ("raw", "}}"),
    ]
    col.write_json(path, plan)
    with open(path) as f:
        got = json.load(f)
    np.testing.assert_allclose(got["home0"]["a"], data[:, 0], rtol=0, atol=0)
    np.testing.assert_allclose(got["home1"]["a"], data[:, 1], rtol=0, atol=0)
    assert got["home0"]["b"] == ints[:, 0].tolist()
    assert got["home0"]["type"] == "base"
    col.close()


def test_collector_native_and_fallback_agree(tmp_path, monkeypatch):
    """Force the fallback path and compare against the native output."""
    import dragg_tpu.native as nat

    data = np.linspace(-1, 1, 12).reshape(6, 2) * 1234.5678
    plan = [("raw", '{"h": '), ("series", "s", 0), ("raw", "}")]

    native_col = SeriesCollector(2)
    assert native_col.native
    native_col.add_chunk("s", data)
    p1 = str(tmp_path / "native.json")
    native_col.write_json(p1, plan)
    native_col.close()

    monkeypatch.setattr(nat, "_LIB", None)
    monkeypatch.setattr(nat, "_LIB_TRIED", True)
    fb_col = SeriesCollector(2)
    assert not fb_col.native
    fb_col.add_chunk("s", data)
    p2 = str(tmp_path / "fallback.json")
    fb_col.write_json(p2, plan)

    a = json.load(open(p1))
    b = json.load(open(p2))
    assert a == b  # bit-identical doubles through both formatters


def test_statebus_fallback_agrees(monkeypatch):
    import dragg_tpu.native as nat

    monkeypatch.setattr(nat, "_LIB", None)
    monkeypatch.setattr(nat, "_LIB_TRIED", True)
    bus = StateBus()
    assert not bus.native
    bus.flushall()
    bus.rpush("l", "a", "b", "c")
    assert bus.lrange("l", -2, -1) == ["b", "c"]
    bus.hset("h", "f", 1)
    assert bus.hgetall("h") == {"f": "1"}
    bus.flushall()


def test_collector_nonfinite_roundtrip(tmp_path):
    """Non-finite doubles must emit Python-json literals (NaN/Infinity) so
    results and checkpoints stay loadable."""
    col = SeriesCollector(1)
    col.add_chunk("s", np.array([[np.nan], [np.inf], [-np.inf], [1.5]]))
    path = str(tmp_path / "nf.json")
    col.write_json(path, [("raw", '{"s": '), ("series", "s", 0), ("raw", "}")])
    got = json.load(open(path))["s"]
    assert np.isnan(got[0]) and got[1] == np.inf and got[2] == -np.inf and got[3] == 1.5
    col.close()


def test_statebus_rejects_nul_bytes(bus):
    """Embedded NULs would truncate across the C-string ABI; both backends
    reject them identically instead of silently diverging."""
    with pytest.raises(ValueError, match="NUL"):
        bus.set("k", "a\x00b")
    with pytest.raises(ValueError, match="NUL"):
        bus.hset("h", "f", "x\x00")
    with pytest.raises(ValueError, match="NUL"):
        bus.rpush("l", "ok", "bad\x00")
