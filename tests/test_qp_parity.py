"""Solver parity: the batched ADMM vs scipy's HiGHS on identical matrices.

The parity target is ≤1 % objective-cost gap against a trusted CPU solver on
the *same* (A_eq, b_eq, l, u, q) data (SURVEY.md §4b, BASELINE.md).  The
reference validated against GLPK_MI through CVXPY; CVXPY is not in this
image, so scipy.optimize.linprog(method="highs") plays the reference-solver
role — the per-home MPC objective is linear (dragg/mpc_calc.py:441-446), so
with the duty-cycle relaxation the problem is exactly an LP.
"""

import numpy as np
import pytest
from scipy.optimize import linprog

from dragg_tpu.fixtures import assemble_community_qp
from dragg_tpu.ops.admm import admm_solve_qp
from dragg_tpu.ops.qp import densify_A

import jax.numpy as jnp


def _assemble_real_step(horizon_hours=4, n_homes=6):
    """Assemble the t=0 QP for a real mixed community (shared recipe —
    dragg_tpu/fixtures.py — so the parity-tested matrices and the
    MILP-gap-measured matrices cannot drift apart)."""
    qp, pattern, _lay, _s = assemble_community_qp(
        horizon_hours=horizon_hours, n_homes=n_homes, season="heat")
    return qp, pattern


def _linprog_reference(A_eq, b_eq, l, u, q):
    """Solve one home's LP with HiGHS."""
    bounds = [(lo if np.isfinite(lo) else None, hi if np.isfinite(hi) else None)
              for lo, hi in zip(l, u)]
    res = linprog(q, A_eq=A_eq, b_eq=b_eq, bounds=bounds, method="highs")
    return res


@pytest.mark.slow
def test_admm_matches_highs_on_real_mpc():
    """≤1 % objective gap and matching primal cost on the real t=0 community
    QP, home by home.  Tolerance 1e-4 is the production setting — the fp32
    primal-residual floor sits near 1e-3 (unscaled temperature rows ~40), so
    tighter tolerances are unreachable on TPU-native float32; measured
    objective gaps at this tolerance are 0.002-0.04 % (40x under target)."""
    qp, pat = _assemble_real_step()
    sol = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                        iters=4000, eps_abs=1e-4, eps_rel=1e-4)
    A = np.asarray(densify_A(pat, qp.vals), dtype=np.float64)
    beq = np.asarray(qp.b_eq, dtype=np.float64)
    l = np.asarray(qp.l_box, dtype=np.float64)
    u = np.asarray(qp.u_box, dtype=np.float64)
    q = np.asarray(qp.q, dtype=np.float64)
    x = np.asarray(sol.x, dtype=np.float64)
    solved = np.asarray(sol.solved)
    n_checked = 0
    for i in range(A.shape[0]):
        ref = _linprog_reference(A[i], beq[i], l[i], u[i], q[i])
        if not ref.success:
            # HiGHS agrees the home is infeasible → our solver must not
            # claim success.
            assert not solved[i]
            continue
        assert solved[i], f"home {i}: HiGHS feasible but ADMM unsolved"
        obj_admm = float(q[i] @ x[i])
        obj_ref = float(ref.fun)
        scale = max(abs(obj_ref), 1e-3)
        gap = (obj_admm - obj_ref) / scale
        # ADMM cost can only be >= the true optimum (up to tolerance).
        assert gap < 0.01, f"home {i}: cost gap {gap:.4%}"
        assert gap > -0.005, f"home {i}: ADMM 'beat' the optimum — constraint violation"
        # Feasibility of the ADMM primal on the original data.
        # Feasibility floor: the returned primal is box-PROJECTED (hard
        # clip), so dynamics rows can be off by up to the box residual at
        # the stopping tolerance — ~1e-2 absolute on rows whose natural
        # scale is ~40 (temperatures), i.e. ~2e-4 relative.
        viol = np.max(np.abs(A[i] @ x[i] - beq[i]))
        assert viol < 1e-2, f"home {i}: equality violation {viol}"
        n_checked += 1
    assert n_checked >= 4  # most of the community must be feasible at t=0


@pytest.mark.slow
def test_admm_infeasibility_certificate():
    """A home whose pinned initial WH temp sits outside the comfort box is
    primal-infeasible (dragg/mpc_calc.py:329-334); ADMM must certify it and
    HiGHS must agree."""
    qp, pat = _assemble_real_step()
    # Corrupt home 0: force the WH box above the pinned initial temperature.
    l = np.asarray(qp.l_box).copy()
    u = np.asarray(qp.u_box).copy()
    # Find columns whose lower bound equals home0's temp_wh_min: simpler —
    # raise every finite lower bound of the WH band by setting l > pinned b.
    from dragg_tpu.ops.qp import QPLayout
    H = (pat.n - 5) // 9
    lay = QPLayout(H)
    b0 = float(np.asarray(qp.b_eq)[0, lay.r_twh0])
    l[0, lay.i_twh : lay.i_twh + H + 1] = b0 + 5.0  # bound above the pin
    sol = admm_solve_qp(pat, qp.vals, qp.b_eq, jnp.asarray(l), jnp.asarray(u), qp.q,
                        iters=4000, eps_abs=1e-4, eps_rel=1e-4)
    assert not np.asarray(sol.solved)[0]
    assert np.asarray(sol.infeasible)[0], "certificate missed an infeasible home"
    A0 = np.asarray(densify_A(pat, qp.vals)[0], np.float64)
    ref = _linprog_reference(
        A0, np.asarray(qp.b_eq[0], np.float64),
        l[0].astype(np.float64), u[0].astype(np.float64), np.asarray(qp.q[0], np.float64))
    assert not ref.success


@pytest.mark.slow
def test_parity_48h_horizon():
    """BASELINE.md row 5 regime: the 48 h horizon must solve and hold the
    ≤1 % objective budget (round-1 verdict, weak #3 — H=48 was a known
    unknown: long horizons degraded before the proximal fix)."""
    qp, pat = _assemble_real_step(horizon_hours=48, n_homes=6)
    sol = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                        iters=3000, eps_abs=1e-4, eps_rel=1e-4)
    A = np.asarray(densify_A(pat, qp.vals)); beq = np.asarray(qp.b_eq)
    l = np.asarray(qp.l_box); u = np.asarray(qp.u_box); q = np.asarray(qp.q)
    x = np.asarray(sol.x)
    solved = np.asarray(sol.solved)
    n_checked = 0
    for i in range(A.shape[0]):
        ref = _linprog_reference(A[i].astype(np.float64), beq[i].astype(np.float64),
                                 l[i].astype(np.float64), u[i].astype(np.float64),
                                 q[i].astype(np.float64))
        if ref is None or not ref.success:
            continue
        assert solved[i], f"home {i} unsolved at H=48 (r_prim={float(sol.r_prim[i]):.2e})"
        gap = (float(q[i] @ x[i]) - ref.fun) / max(abs(ref.fun), 1e-3)
        assert abs(gap) < 0.01, f"home {i}: 48h-horizon cost gap {gap:.4%}"
        n_checked += 1
    assert n_checked >= 4


def test_parity_24h_horizon():
    """Regression for the long-horizon regime: with the proximal default
    (admm_reg=1e-3) every home must SOLVE at H=24 within ~600 iterations and
    stay inside the <=1% objective budget.  With the old reg=1e-8 LP setting,
    819/1000 homes missed tolerance after 1000 iterations and silently fell
    back to the bang-bang controller."""
    qp, pat = _assemble_real_step(horizon_hours=24, n_homes=6)
    sol = admm_solve_qp(pat, qp.vals, qp.b_eq, qp.l_box, qp.u_box, qp.q,
                        iters=1500, eps_abs=1e-4, eps_rel=1e-4)
    A = np.asarray(densify_A(pat, qp.vals)); beq = np.asarray(qp.b_eq)
    l = np.asarray(qp.l_box); u = np.asarray(qp.u_box); q = np.asarray(qp.q)
    x = np.asarray(sol.x)
    solved = np.asarray(sol.solved)
    n_checked = 0
    for i in range(A.shape[0]):
        ref = _linprog_reference(A[i].astype(np.float64), beq[i].astype(np.float64),
                                 l[i].astype(np.float64), u[i].astype(np.float64),
                                 q[i].astype(np.float64))
        if ref is None or not ref.success:
            continue
        assert solved[i], f"home {i} unsolved at H=24 (r_prim={float(sol.r_prim[i]):.2e})"
        gap = (float(q[i] @ x[i]) - ref.fun) / max(abs(ref.fun), 1e-3)
        assert abs(gap) < 0.01, f"home {i}: 24h-horizon cost gap {gap:.4%}"
        n_checked += 1
    assert n_checked >= 4
