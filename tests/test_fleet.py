"""Multi-community fleet axis (round 12 — ISSUE 8, architecture.md §14).

Parity contract: C communities folded into ONE fleet batch must
reproduce C independent single-community runs — objectives, applied k=0
actions, physical state — with the established cross-batch-shape
tolerances (tests/test_bucketed.py convention: the fleet batch buckets /
shards at different shapes than a standalone community, so per-home
trajectories are identical math modulo fp reassociation).  Same-shape
compositions (unbucketed fleet vs unbucketed standalone) are BIT-exact:
the forecast-noise stream is keyed on (community seed, within-community
index), invariant to fleet composition by construction
(engine._prepare).

Heavy parametrizations are slow-marked with lighter siblings in tier-1
(round-11 budget convention).
"""

import copy
import os
import tempfile

import numpy as np
import pytest

import jax

from dragg_tpu.config import default_config
from dragg_tpu.data import load_environment, load_waterdraw_profiles
from dragg_tpu.engine import OBS_FIELDS, make_engine
from dragg_tpu.homes import (
    build_fleet_batch,
    create_fleet_homes,
    fleet_spec_for,
)


def _fleet_cfg(n=16, pv=6, bat=2, pvb=2, horizon=2, communities=2,
               seed_stride=5, weather_off=0):
    cfg = default_config()
    cfg["community"]["total_number_homes"] = n
    cfg["community"]["homes_pv"] = pv
    cfg["community"]["homes_battery"] = bat
    cfg["community"]["homes_pv_battery"] = pvb
    cfg["home"]["hems"]["prediction_horizon"] = horizon
    cfg["fleet"]["communities"] = communities
    cfg["fleet"]["seed_stride"] = seed_stride
    cfg["fleet"]["weather_offset_hours"] = weather_off
    # The IPM's tail compaction gathers the worst ipm_tail_frac of the
    # BATCH — its membership (hence the tail homes' final iterates within
    # solver tolerance) legitimately depends on batch composition.  Pin
    # it off so these tests isolate the fleet fold itself: with a
    # composition-invariant solver path, same-shape fleet-vs-standalone
    # comparisons are BIT-exact and cross-shape ones pure fp wobble.
    cfg["tpu"]["ipm_tail_frac"] = 0.0
    return cfg


def _build(cfg, sharded=False, mesh_devices=8, start_index=0, env=None):
    # Synthetic weather is seeded by simulation.random_seed — standalone
    # comparison runs must REUSE the fleet run's environment (pass env),
    # or a different community seed would also mean different weather.
    if env is None:
        env = load_environment(cfg, data_dir="")
    wd = load_waterdraw_profiles(None, seed=12)
    dt = int(cfg["agg"]["subhourly_steps"])
    homes = create_fleet_homes(cfg, 24 * dt, dt, wd)
    H = int(cfg["home"]["hems"]["prediction_horizon"]) * dt
    batch, fleet = build_fleet_batch(
        homes, cfg, H, dt, int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    if sharded:
        from dragg_tpu.parallel import make_mesh, make_sharded_engine

        eng = make_sharded_engine(batch, env, cfg, start_index,
                                  mesh=make_mesh(mesh_devices), fleet=fleet)
    else:
        eng = make_engine(batch, env, cfg, start_index, fleet=fleet)
    return homes, batch, fleet, eng, env


# ------------------------------------------------------------------- spec
def test_fleet_spec_structure():
    """C communities, own seeds, community-major list with prefixed
    names, type-major batch order, per-community env offsets."""
    cfg = _fleet_cfg(communities=3, seed_stride=7, weather_off=2)
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_fleet_homes(cfg, 24, 1, wd)
    assert len(homes) == 48
    assert homes[0]["name"].startswith("c0-")
    assert homes[16]["name"].startswith("c1-")
    # Distinct populations, not copies: different seeds draw different
    # parameters for the "same" home slot.
    assert homes[0]["hvac"]["r"] != homes[16]["hvac"]["r"]
    spec = fleet_spec_for(homes, cfg)
    assert spec.n_communities == 3 and spec.homes_per_community == 16
    assert spec.seeds == (12, 19, 26)
    # global_idx is a permutation of the community-major order; local =
    # global % B; env offsets are per community in sim steps (dt=1).
    assert sorted(spec.global_idx.tolist()) == list(range(48))
    np.testing.assert_array_equal(spec.local_idx, spec.global_idx % 16)
    np.testing.assert_array_equal(spec.env_offset, spec.community * 2)
    # Type-major: each type's rows are contiguous and cover all
    # communities before the next type starts.
    types = [homes[i]["type"] for i in spec.global_idx]
    seen = []
    for t in types:
        if t not in seen:
            seen.append(t)
    assert seen == ["pv_battery", "pv_only", "battery_only", "base"]

    # A C=1 config is NOT a fleet (the pre-round-12 engine unchanged).
    cfg1 = _fleet_cfg(communities=1)
    homes1 = create_fleet_homes(cfg1, 24, 1, wd)
    assert fleet_spec_for(homes1, cfg1) is None
    assert not homes1[0]["name"].startswith("c0-")

    # Malformed configs/lists are refused loudly (a negative offset
    # would undershoot the coverage check while the traced gather clamps
    # — silently wrong weather).
    cfg_neg = _fleet_cfg(weather_off=-2)
    with pytest.raises(ValueError, match="weather_offset_hours"):
        fleet_spec_for(homes, cfg_neg)
    with pytest.raises(ValueError, match="divisible"):
        fleet_spec_for(homes[:-1], cfg)
    shuffled = homes[:16][::-1] + homes[16:]
    with pytest.raises(ValueError, match="grouped|partition"):
        fleet_spec_for(shuffled, cfg)


# ----------------------------------------------------------------- parity
@pytest.fixture(scope="module")
def fleet_runs():
    """One C=2 fleet chunk + the two standalone community chunks it must
    reproduce (module-scoped: three engine compiles shared by the parity
    assertions).  32 fleet homes with a non-superset-heavy mix →
    ``tpu.bucketed=auto`` buckets the FLEET while each 16-home standalone
    stays unbucketed, so this exercises the cross-shape tolerance class
    too."""
    cfg = _fleet_cfg()
    homes, batch, fleet, eng, env = _build(cfg)
    assert eng.bucketed  # 32 homes, 62% non-superset → auto buckets
    assert eng.n_communities == 2
    rps = np.zeros((3, eng.params.horizon), np.float32)
    _, out_fleet = eng.run_chunk(eng.init_state(), 0, rps)

    solo_outs, solo_cols = [], []
    for c in range(2):
        cfg_c = copy.deepcopy(cfg)
        cfg_c["fleet"]["communities"] = 1
        cfg_c["simulation"]["random_seed"] = 12 + 5 * c
        _h, _b, f_c, eng_c, _e = _build(cfg_c, env=env)
        assert f_c is None and not eng_c.bucketed
        _, o = eng_c.run_chunk(eng_c.init_state(), 0, rps)
        solo_outs.append(o)
        solo_cols.append(eng_c.real_home_cols)
    return cfg, eng, out_fleet, solo_outs, solo_cols


def _per_home(outs, cols):
    host = {}
    for f in outs._fields:
        if f in OBS_FIELDS:
            continue
        a = np.asarray(getattr(outs, f))
        host[f] = a[:, cols] if a.ndim == 2 else a
    return host


def _assert_community_match(fl, so, s):
    """tests/test_bucketed.py tolerance class: solvedness exact,
    objectives/state to solver tolerance, applied integer actions within
    one rounding flip."""
    np.testing.assert_array_equal(fl["correct_solve"], so["correct_solve"])
    np.testing.assert_allclose(fl["cost"], so["cost"], rtol=1e-2, atol=2e-3)
    for key in ("hvac_cool_on", "hvac_heat_on", "wh_heat_on"):
        assert np.max(np.abs(fl[key] * s - so[key] * s)) <= 1 + 1e-3, key
    np.testing.assert_allclose(fl["temp_in"], so["temp_in"], atol=1e-3)
    np.testing.assert_allclose(fl["temp_wh"], so["temp_wh"], atol=1e-3)
    # Battery coordinates are near-degenerate in the objective at mW
    # magnitudes (test_bucketed docstring: degenerate variables may
    # legitimately differ across batch shapes): a ~0.01 kW charge wiggle
    # costs ~1e-3 — inside the solver's eps — so these carry a loose
    # 0.02 kW / kWh bound (0.2 % of capacity); the tight invariants are
    # cost/temps/solvedness/duty counts above.
    np.testing.assert_allclose(fl["e_batt"], so["e_batt"], atol=2e-2)
    np.testing.assert_allclose(fl["p_batt_ch"], so["p_batt_ch"], atol=2e-2)
    np.testing.assert_allclose(fl["p_batt_disch"], so["p_batt_disch"],
                               atol=2e-2)


def test_fleet_matches_standalone_communities(fleet_runs):
    """Each community's slice of the fleet output equals its standalone
    run; the fleet aggregate is the sum of the standalone aggregates."""
    cfg, eng, out_fleet, solo_outs, solo_cols = fleet_runs
    s = eng.params.s
    cols = eng.real_home_cols
    B = eng.fleet.homes_per_community
    agg_sum = np.zeros_like(np.asarray(out_fleet.agg_load))
    for c in range(2):
        fl = _per_home(out_fleet, cols[c * B:(c + 1) * B])
        so = _per_home(solo_outs[c], solo_cols[c])
        _assert_community_match(fl, so, s)
        agg_sum = agg_sum + np.asarray(solo_outs[c].agg_load)
    np.testing.assert_allclose(np.asarray(out_fleet.agg_load), agg_sum,
                               rtol=1e-3, atol=1e-2)


def test_fleet_real_home_pairs(fleet_runs):
    """(community, col) mapping: row j names community j//B and the
    output column carrying home j — consistent with real_home_cols."""
    _cfg, eng, _o, _so, _sc = fleet_runs
    pairs = eng.real_home_pairs
    B = eng.fleet.homes_per_community
    assert pairs.shape == (2 * B, 2)
    np.testing.assert_array_equal(pairs[:, 0], np.arange(2 * B) // B)
    np.testing.assert_array_equal(pairs[:, 1], eng.real_home_cols)
    # Every true home appears exactly once.
    assert len(set(pairs[:, 1].tolist())) == 2 * B


def test_fleet_checkpoint_roundtrip(fleet_runs):
    """The fleet state (per-bucket tuple sized C·B_type per bucket)
    survives save/load through the structure-agnostic pytree checkpoint
    — the community axis resumes (light sibling of the slow aggregator
    resume test)."""
    from dragg_tpu.checkpoint import load_pytree, save_pytree

    _cfg, eng, _o, _so, _sc = fleet_runs
    # 3-step chunks reuse the fixture's compiled scan (the scan length is
    # baked into the program — a different length would recompile).
    rps = np.zeros((3, eng.params.horizon), np.float32)
    state, _ = eng.run_chunk(eng.init_state(), 0, rps)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "state.npz")
        save_pytree(path, state)
        restored = load_pytree(path, eng.init_state())
    for st, rt in zip(state, restored):
        for name, a, b in zip(st._fields, st, rt):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)
    # Resuming from the restored carry continues identically.
    _, o1 = eng.run_chunk(state, 3, rps)
    _, o2 = eng.run_chunk(restored, 3, rps)
    np.testing.assert_array_equal(np.asarray(o1.p_grid),
                                  np.asarray(o2.p_grid))


def test_fleet_unbucketed_is_bit_exact():
    """Same-shape composition control: an UNBUCKETED fleet (tiny
    communities, bucketing off) reproduces each standalone run
    bit-for-bit — the noise/key/draw streams are provably composition-
    invariant, not merely tolerance-close."""
    cfg = _fleet_cfg(n=6, pv=1, bat=1, pvb=1, communities=2)
    cfg["tpu"]["bucketed"] = "false"
    _h, _b, fleet, eng, env = _build(cfg)
    assert not eng.bucketed
    rps = np.zeros((2, eng.params.horizon), np.float32)
    _, out = eng.run_chunk(eng.init_state(), 0, rps)
    cols = eng.real_home_cols
    for c in range(2):
        cfg_c = copy.deepcopy(cfg)
        cfg_c["fleet"]["communities"] = 1
        cfg_c["simulation"]["random_seed"] = 12 + 5 * c
        _h2, _b2, _f2, eng_c, _e = _build(cfg_c, env=env)
        _, o = eng_c.run_chunk(eng_c.init_state(), 0, rps)
        for f in out._fields:
            if f in OBS_FIELDS:
                continue
            a = np.asarray(getattr(out, f))
            b = np.asarray(getattr(o, f))
            if a.ndim == 2:
                np.testing.assert_array_equal(
                    a[:, cols[c * 6:(c + 1) * 6]],
                    b[:, eng_c.real_home_cols], err_msg=f)


def test_fleet_weather_offsets():
    """fleet.weather_offset_hours shifts community c's environment
    windows by c·offset steps: community 1's trajectory equals a
    standalone run whose start_index is advanced by the offset, and
    offset 0 keeps the scalar shared-window program path."""
    cfg = _fleet_cfg(n=6, pv=1, bat=1, pvb=1, communities=2, weather_off=3)
    cfg["tpu"]["bucketed"] = "false"
    _h, fleet_batch, fleet, eng, env = _build(cfg)
    assert eng._per_home_env
    rps = np.zeros((2, eng.params.horizon), np.float32)
    _, out = eng.run_chunk(eng.init_state(), 0, rps)
    cols = eng.real_home_cols

    cfg1 = copy.deepcopy(cfg)
    cfg1["fleet"]["communities"] = 1
    cfg1["simulation"]["random_seed"] = 17
    _h1, _b1, _f1, eng1, _e1 = _build(cfg1, start_index=3, env=env)
    assert not eng1._per_home_env  # C=1 stays on the scalar path
    _, o1 = eng1.run_chunk(eng1.init_state(), 0, rps)
    np.testing.assert_allclose(
        np.asarray(out.temp_in)[:, cols[6:]],
        np.asarray(o1.temp_in)[:, eng1.real_home_cols], atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(out.p_grid)[:, cols[6:]],
        np.asarray(o1.p_grid)[:, eng1.real_home_cols], atol=1e-3)


def test_fleet_sharded_8dev_mesh_tiny():
    """Light 8-device leg: a C=2 fleet on the conftest CPU mesh (shard-
    padded type buckets holding both communities) matches the
    single-device fleet run.  The bench-mix heavy leg is slow-marked
    below."""
    assert len(jax.devices()) == 8, "conftest pins the 8-device CPU mesh"
    cfg = _fleet_cfg(n=8, pv=3, bat=1, pvb=1, communities=2)
    _h, _b, fleet, eng, env = _build(cfg)     # single-device fleet
    _h2, _b2, fleet2, sh, _e = _build(cfg, sharded=True, env=env)
    rps = np.zeros((2, eng.params.horizon), np.float32)
    _, o1 = eng.run_chunk(eng.init_state(), 0, rps)
    _, o2 = sh.run_chunk(sh.init_state(), 0, rps)
    c1, c2 = eng.real_home_cols, sh.real_home_cols
    assert len(c2) == 16 and len(set(c2.tolist())) == 16
    np.testing.assert_array_equal(
        np.asarray(o1.correct_solve)[:, c1],
        np.asarray(o2.correct_solve)[:, c2])
    np.testing.assert_allclose(np.asarray(o1.temp_in)[:, c1],
                               np.asarray(o2.temp_in)[:, c2], atol=1e-3)
    np.testing.assert_allclose(np.asarray(o1.agg_load),
                               np.asarray(o2.agg_load),
                               rtol=1e-3, atol=1e-2)


@pytest.mark.slow  # heavy 8-dev leg; light sibling: test_fleet_sharded_8dev_mesh_tiny
def test_fleet_sharded_8dev_mesh_bench_mix(fleet_runs):
    """The parity fixture's fleet on the 8-device mesh: per-bucket shard
    padding over C·B_type homes, outputs mapped back through the fleet's
    community-major order, vs the standalone community runs."""
    cfg, eng, _of, solo_outs, solo_cols = fleet_runs
    _h, _b, fleet, sh, _e = _build(cfg, sharded=True)
    assert sh.bucketed
    for b in sh.bucket_info():
        assert b["n_slots"] % 8 == 0 and b["n_slots"] > 0
    rps = np.zeros((3, sh.params.horizon), np.float32)
    _, out = sh.run_chunk(sh.init_state(), 0, rps)
    cols = sh.real_home_cols
    B = sh.fleet.homes_per_community
    for c in range(2):
        fl = _per_home(out, cols[c * B:(c + 1) * B])
        so = _per_home(solo_outs[c], solo_cols[c])
        _assert_community_match(fl, so, sh.params.s)


# ----------------------------------------------------- aggregator pipeline
def _agg_cfg(end="2015-01-03 00", pipeline=True, communities=2):
    cfg = _fleet_cfg(n=6, pv=1, bat=1, pvb=1, communities=communities)
    cfg["simulation"]["start_datetime"] = "2015-01-01 00"
    cfg["simulation"]["end_datetime"] = end
    cfg["telemetry"]["enabled"] = False
    cfg["fleet"]["pipeline"] = pipeline
    return cfg


def _run_agg(cfg, outdir, stop_after=None):
    import json

    from dragg_tpu.aggregator import Aggregator

    a = Aggregator(copy.deepcopy(cfg), data_dir="", outputs_dir=outdir)
    if stop_after is not None:
        a.stop_after_chunks = stop_after
    a.run()
    with open(os.path.join(a.run_dir, "baseline", "results.json")) as f:
        return a, json.load(f)


def test_fleet_pipeline_identity(tmp_path):
    """The double-buffered pipeline is a pure scheduling change: a fleet
    run with fleet.pipeline=true produces byte-identical per-home series
    and Summary aggregates to the synchronous loop, and reports the new
    phase keys."""
    _a1, r1 = _run_agg(_agg_cfg(pipeline=True), str(tmp_path / "on"))
    _a2, r2 = _run_agg(_agg_cfg(pipeline=False), str(tmp_path / "off"))
    s1, s2 = r1["Summary"], r2["Summary"]
    assert s1["p_grid_aggregate"] == s2["p_grid_aggregate"]
    assert s1["fleet"]["communities"] == 2
    assert s1["num_homes"] == 12
    for k in ("overlap_hidden_s", "state_snapshot"):
        assert k in s1["phase_times"]
    homes = [k for k in r1 if k != "Summary"]
    assert len(homes) == 12
    for h in homes:
        for series, vals in r1[h].items():
            if isinstance(vals, list):
                assert vals == r2[h][series], (h, series)


@pytest.mark.slow  # aggregator-level resume (3 runs); light sibling: test_fleet_checkpoint_roundtrip
def test_fleet_aggregator_resume(tmp_path):
    """Kill-at-checkpoint + resume across the community axis: a fleet
    run stopped after its first chunk and resumed reproduces the
    straight-through run's results.json exactly."""
    cfg = _agg_cfg()
    _a, ref = _run_agg(cfg, str(tmp_path / "full"))
    cfg_r = copy.deepcopy(cfg)
    cfg_r["simulation"]["resume"] = True
    a1, _r1 = _run_agg(cfg_r, str(tmp_path / "resumed"), stop_after=1)
    assert a1.timestep < a1.num_timesteps
    a2, r2 = _run_agg(cfg_r, str(tmp_path / "resumed"))
    assert a2.resumed_from is not None
    for h in (k for k in ref if k != "Summary"):
        for series, vals in ref[h].items():
            if isinstance(vals, list):
                assert vals == r2[h][series], (h, series)


def test_fleet_run_shape_invalidates_on_communities(tmp_path):
    """A checkpoint written at one fleet size must not resume at another
    — ``communities`` is part of run_shape."""
    from dragg_tpu.aggregator import Aggregator

    a2 = Aggregator(_agg_cfg(), data_dir="", outputs_dir=str(tmp_path))
    a1 = Aggregator(_agg_cfg(communities=1), data_dir="",
                    outputs_dir=str(tmp_path))
    assert a2._run_shape()["communities"] == 2
    assert a1._run_shape()["communities"] == 1
    assert a2._run_shape() != a1._run_shape()

    # RL cases no longer refuse a fleet: fleet.communities > 1 routes to
    # the vectorized fleet trainer (ROADMAP item 1, shipped —
    # tests/test_rl_fleet.py owns that surface).  Baseline-only configs
    # keep the rl_fleet shape key inert so RL config edits cannot
    # invalidate MPC checkpoints.
    cfg = _agg_cfg()
    cfg["simulation"]["run_rl_agg"] = True
    a = Aggregator(cfg, data_dir="", outputs_dir=str(tmp_path))
    assert a._run_shape()["rl_fleet"] is not None
    assert a2._run_shape()["rl_fleet"] is None
