"""Multi-chip sharding tests on the virtual 8-device CPU mesh
(SURVEY.md §4(f): CPU-mesh emulation stands in for real ICI)."""

import numpy as np

import jax

from dragg_tpu.data import load_environment, load_waterdraw_profiles
from dragg_tpu.engine import OBS_FIELDS, make_engine
from dragg_tpu.homes import build_home_batch, create_homes
from dragg_tpu.parallel import make_mesh, make_sharded_engine, pad_batch


def _assert_obs_leaf_parity(name: str, ref_a, sh_a) -> None:
    """Observatory leaves (engine.OBS_FIELDS) are per-BUCKET folds, not
    per-home series, and they are DISCONTINUOUS in the residuals (fixed
    bin edges, near-tied top-k), so the same per-compile fp wobble the
    residual maxima tolerate can legitimately move a single count across
    a bin edge or swap tied worst-k slots between layouts.  The all-leaves
    tests therefore hold them to exact STRUCTURAL parity only (shape,
    histogram totals, divergence counts); distribution-level parity with
    wobble tolerance is
    tests/test_observatory.py::test_obs_sharded_matches_single.  The
    worst-k leaves are not even shape-comparable here: k clamps to the
    bucket SLOT count (min(obs_worst_k, ctx.n)), which shard padding
    legitimately inflates (6 real homes → 8 slots on the 8-device mesh),
    so they are covered only by the dedicated test above."""
    if name in ("conv_hist", "iters_hist"):
        np.testing.assert_array_equal(
            sh_a.sum(axis=2), ref_a.sum(axis=2),
            err_msg=f"StepOutputs.{name} total observations diverged "
                    f"between sharded and single")
    elif name == "diverged_count":
        np.testing.assert_array_equal(
            sh_a, ref_a,
            err_msg="StepOutputs.diverged_count diverged between sharded "
                    "and single")


def _setup(tiny_config):
    cfg = tiny_config
    env = load_environment(cfg, data_dir=None)
    dt = int(cfg["agg"]["subhourly_steps"])
    waterdraw = load_waterdraw_profiles(None, seed=int(cfg["simulation"]["random_seed"]))
    homes = create_homes(cfg, 24 * dt, dt, waterdraw)
    hems = cfg["home"]["hems"]
    batch = build_home_batch(
        homes, int(hems["prediction_horizon"]) * dt, dt, int(hems["sub_subhourly_steps"])
    )
    return cfg, env, batch


def test_pad_batch_masks_replicas(tiny_config):
    _, _, batch = _setup(tiny_config)
    padded, mask = pad_batch(batch, 8)
    assert padded.n_homes == 8
    assert mask.tolist() == [1.0] * 6 + [0.0] * 2
    # Edge padding keeps the dummy problems well-posed.
    assert padded.tank_size[-1] == batch.tank_size[-1]
    # No padding needed → same object.
    same, mask2 = pad_batch(batch, 3)
    assert same is batch and mask2.all()


def test_sharded_engine_matches_single_device(tiny_config):
    cfg, env, batch = _setup(tiny_config)
    n = batch.n_homes

    ref_engine = make_engine(batch, env, cfg, 0)
    mesh = make_mesh(8)
    sh_engine = make_sharded_engine(batch, env, cfg, 0, mesh=mesh)
    assert sh_engine.n_homes == 8 and sh_engine.true_n_homes == n

    rps = np.zeros((3, ref_engine.params.horizon), dtype=np.float32)
    _, ref_out = ref_engine.run_chunk(ref_engine.init_state(), 0, rps)
    state = sh_engine.init_state()
    # State leaves are committed with the homes sharding.
    assert "homes" in str(state.temp_in.sharding.spec)
    _, sh_out = sh_engine.run_chunk(state, 0, rps)

    # Tolerances reflect ADMM termination noise: the solver's stopping
    # criterion is batch-global, so the padded replica homes shift the
    # iteration count slightly; solutions agree to solver eps, not ulps.
    np.testing.assert_allclose(
        np.asarray(sh_out.p_grid)[:, :n], np.asarray(ref_out.p_grid),
        rtol=1e-2, atol=1e-2,
    )
    # The one cross-shard reduction: padded replicas are masked out.
    np.testing.assert_allclose(
        np.asarray(sh_out.agg_load), np.asarray(ref_out.agg_load),
        rtol=1e-2, atol=2e-2,
    )


def test_sharded_engine_all_leaves_fixed_iters(tiny_config):
    """Sharded vs single-device agreement over EVERY StepOutputs leaf and the
    final CommunityState, with the solver pinned to a fixed iteration count
    (eps=0 + patience=0) so batch-global stopping noise cannot mask a real
    sharding bug (round-1 verdict, weak #6 / next #10)."""
    import copy

    cfg = copy.deepcopy(tiny_config)
    cfg["home"]["hems"]["solver"] = "admm"  # this test pins the ADMM's
    cfg["tpu"]["admm_eps"] = 0.0       # fixed-iteration mode: convergence
    cfg["tpu"]["admm_patience"] = 0    # test never fires, stagnation exit
    cfg["tpu"]["admm_iters"] = 150     # disabled → exactly 150 iterations
    cfg["tpu"]["integer_first_action"] = False  # this test pins the exact
                                       # iteration count; the default
                                       # repair's 2nd solve would double it
    cfg, env, batch = _setup(cfg)
    n = batch.n_homes

    ref_engine = make_engine(batch, env, cfg, 0)
    sh_engine = make_sharded_engine(batch, env, cfg, 0, mesh=make_mesh(8))

    rps = np.zeros((3, ref_engine.params.horizon), dtype=np.float32)
    ref_state, ref_out = ref_engine.run_chunk(ref_engine.init_state(), 0, rps)
    sh_state, sh_out = sh_engine.run_chunk(sh_engine.init_state(), 0, rps)

    assert np.asarray(ref_out.admm_iters).tolist() == [150, 150, 150]
    np.testing.assert_array_equal(np.asarray(sh_out.admm_iters),
                                  np.asarray(ref_out.admm_iters))

    per_home = {"agg_load", "forecast_load", "agg_cost", "admm_iters",
                "repair_failed", "r_prim_max", "r_dual_max",
                "bank_fallback_count"}
    for name, ref_leaf, sh_leaf in zip(
        ref_out._fields, ref_out, sh_out
    ):
        ref_a, sh_a = np.asarray(ref_leaf), np.asarray(sh_leaf)
        if name in OBS_FIELDS:
            _assert_obs_leaf_parity(name, ref_a, sh_a)
            continue
        if name not in per_home:       # (T, n_padded) → real homes only
            sh_a = sh_a[:, :n]
        # The telemetry residual maxima amplify per-compile fp wobble
        # (a max over per-home residuals of non-contractive iterates) —
        # measured ~1.4e-4 relative between layouts; the physical
        # outputs keep the tight bound.
        tol = 1e-3 if name in ("r_prim_max", "r_dual_max") else 1e-5
        np.testing.assert_allclose(
            sh_a, ref_a, rtol=tol, atol=tol,
            err_msg=f"StepOutputs.{name} diverged between sharded and single",
        )

    for name, ref_leaf, sh_leaf in zip(
        ref_state._fields, ref_state, sh_state
    ):
        if name == "key":
            continue
        ref_a = np.asarray(ref_leaf)
        sh_a = np.asarray(sh_leaf)[:n]
        # Raw ADMM warm-start iterates are not contractive — per-compile fp
        # differences amplify over 450 fixed iterations — so they get a
        # loose bound; the physical state must agree tightly.
        tol = 0.05 if name.startswith("warm_") else 1e-5
        np.testing.assert_allclose(
            sh_a, ref_a, rtol=tol, atol=tol,
            err_msg=f"CommunityState.{name} diverged between sharded and single",
        )


def test_sharded_engine_all_leaves_ipm(tiny_config):
    """Sharded-vs-single agreement for the DEFAULT (IPM) solver: Mehrotra
    runs a fixed iteration count by construction, so every StepOutputs leaf
    must agree to fp tolerance with no stopping-criterion caveats."""
    import copy

    cfg = copy.deepcopy(tiny_config)
    cfg, env, batch = _setup(cfg)
    n = batch.n_homes

    ref_engine = make_engine(batch, env, cfg, 0)
    assert ref_engine.params.solver == "ipm"  # premise: fixed-iteration solver
    sh_engine = make_sharded_engine(batch, env, cfg, 0, mesh=make_mesh(8))

    rps = np.zeros((3, ref_engine.params.horizon), dtype=np.float32)
    _, ref_out = ref_engine.run_chunk(ref_engine.init_state(), 0, rps)
    _, sh_out = sh_engine.run_chunk(sh_engine.init_state(), 0, rps)

    per_home = {"agg_load", "forecast_load", "agg_cost", "admm_iters",
                "repair_failed", "r_prim_max", "r_dual_max",
                "bank_fallback_count"}
    for name, ref_leaf, sh_leaf in zip(ref_out._fields, ref_out, sh_out):
        ref_a, sh_a = np.asarray(ref_leaf), np.asarray(sh_leaf)
        if name in OBS_FIELDS:
            _assert_obs_leaf_parity(name, ref_a, sh_a)
            continue
        if name not in per_home:
            sh_a = sh_a[:, :n]
        tol = 1e-3 if name in ("r_prim_max", "r_dual_max") else 1e-4
        np.testing.assert_allclose(
            sh_a, ref_a, rtol=tol, atol=tol,
            err_msg=f"StepOutputs.{name} diverged between sharded and single",
        )


def test_sharded_engine_band_backend(tiny_config):
    """The BASELINE row-5 configuration is sharded AND banded: the band
    substitution scans must compile and solve under the SPMD partitioner."""
    import copy

    cfg = copy.deepcopy(tiny_config)
    cfg["home"]["hems"]["solver"] = "admm"  # the band solve BACKEND is an
    # ADMM knob — under the ipm default this test would be vacuous (the IPM
    # carry ignores admm_solve_backend entirely)
    cfg["tpu"]["admm_solve_backend"] = "band"
    cfg, env, batch = _setup(cfg)
    sh = make_sharded_engine(batch, env, cfg, 0, mesh=make_mesh(8))
    assert sh.init_factor().Sinv.shape[-1] <= 13  # band factor, not (m, m)
    rps = np.zeros((2, sh.params.horizon), dtype=np.float32)
    state, outs = sh.run_chunk(sh.init_state(), 0, rps)
    solved = np.asarray(outs.correct_solve)[:, :batch.n_homes]
    assert solved.mean() > 0.9
    assert np.isfinite(np.asarray(outs.agg_load)).all()


def test_dryrun_multichip_entry():
    import __graft_entry__ as ge

    ge.dryrun_multichip(min(8, len(jax.devices())))


def test_aggregator_auto_shards(tiny_config):
    """With >1 visible device (the 8-device CPU test mesh), the Aggregator
    builds a sharded engine automatically and produces the same results.json
    schema with true-population per-home series (tpu.sharded='auto')."""
    import copy
    import glob
    import json
    import os
    import tempfile

    from dragg_tpu.aggregator import Aggregator
    from dragg_tpu.parallel.mesh import ShardedEngine

    cfg = copy.deepcopy(tiny_config)
    cfg["simulation"]["end_datetime"] = "2015-01-02 00"
    with tempfile.TemporaryDirectory() as td:
        agg = Aggregator(cfg, data_dir=None, outputs_dir=td)
        agg.run()
        assert isinstance(agg.engine, ShardedEngine)
        assert agg.engine.n_homes % 8 == 0
        n = cfg["community"]["total_number_homes"]
        res = glob.glob(os.path.join(td, "**", "results.json"), recursive=True)
        assert res
        data = json.load(open(res[0]))
        homes = [k for k, v in data.items()
                 if k != "Summary" and isinstance(v, dict) and "type" in v]
        assert len(homes) == n  # no padded replicas leak into the output
        for h in homes:
            assert len(data[h]["p_grid_opt"]) == agg.num_timesteps
        assert np.isfinite(np.asarray(
            data["Summary"]["p_grid_aggregate"], dtype=float)).all()


def test_aggregator_sharded_false_forces_single(tiny_config):
    import copy
    import tempfile

    from dragg_tpu.aggregator import Aggregator
    from dragg_tpu.parallel.mesh import ShardedEngine

    cfg = copy.deepcopy(tiny_config)
    cfg["simulation"]["end_datetime"] = "2015-01-01 02"
    cfg["tpu"]["sharded"] = False
    with tempfile.TemporaryDirectory() as td:
        agg = Aggregator(cfg, data_dir=None, outputs_dir=td)
        agg.run()
        assert not isinstance(agg.engine, ShardedEngine)


def test_rl_agg_sharded_matches_single(tiny_config):
    """The fused RL-aggregator scan produces the same aggregate trajectory
    and reward prices sharded as single-device (fp tolerance — the IPM runs
    fixed-style iterations so there is no stopping noise)."""
    import copy
    import glob
    import json
    import os
    import tempfile

    from dragg_tpu.aggregator import Aggregator

    def run(sharded):
        cfg = copy.deepcopy(tiny_config)
        cfg["simulation"]["end_datetime"] = "2015-01-02 00"
        cfg["simulation"]["run_rbo_mpc"] = False
        cfg["simulation"]["run_rl_agg"] = True
        cfg["tpu"]["sharded"] = sharded
        with tempfile.TemporaryDirectory() as td:
            agg = Aggregator(cfg, data_dir=None, outputs_dir=td)
            agg.run()
            res = glob.glob(os.path.join(td, "**", "rl_agg", "results.json"),
                            recursive=True)[0]
            with open(res) as f:
                s = json.load(f)["Summary"]
            return (np.asarray(s["p_grid_aggregate"], dtype=float),
                    np.asarray(s["RP"], dtype=float))

    load_1, rp_1 = run(False)
    load_8, rp_8 = run(True)
    np.testing.assert_allclose(load_8, load_1, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(rp_8, rp_1, rtol=1e-3, atol=1e-4)
