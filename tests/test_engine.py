"""End-to-end engine + aggregator tests.

The reference has no test suite (SURVEY.md §4); these implement the test
pyramid it prescribes: solver-vs-reference parity on identical matrices
(§4b), output-schema/shape checks mirroring the reference's runtime
self-checks (dragg/aggregator.py:698-709), determinism keyed on the seeded
home-synthesis path (§4c), and physics validation (comfort bands respected
on solved steps — the checks the reference's paper does scientifically).
"""

import json
import os

import numpy as np
import pytest

from dragg_tpu.aggregator import Aggregator
from dragg_tpu.config import default_config


@pytest.fixture(scope="module")
def day_run(tmp_path_factory):
    """One 24h simulated day over a 6-home mixed community (module-scoped:
    compile once, assert many)."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 6
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["simulation"]["end_datetime"] = "2015-01-02 00"
    cfg["home"]["hems"]["prediction_horizon"] = 4
    out = tmp_path_factory.mktemp("outputs")
    agg = Aggregator(config=cfg, outputs_dir=str(out))
    agg.run()
    with open(os.path.join(agg.run_dir, "baseline", "results.json")) as f:
        return agg, json.load(f)


def test_results_schema(day_run):
    """results.json carries the reference schema (dragg/aggregator.py:589-615,
    783-816) at the right lengths (check_baseline_vals semantics)."""
    agg, data = day_run
    T = agg.num_timesteps
    assert T == 24
    summary = data["Summary"]
    for key in ("case", "start_datetime", "end_datetime", "solve_time", "horizon",
                "num_homes", "p_max_aggregate", "p_grid_aggregate", "OAT", "GHI",
                "RP", "p_grid_setpoint", "TOU"):
        assert key in summary, key
    assert len(summary["p_grid_aggregate"]) == T
    assert len(summary["OAT"]) == T
    assert summary["num_homes"] == 6
    homes = {k: v for k, v in data.items() if k != "Summary"}
    assert len(homes) == 6
    for name, d in homes.items():
        assert len(d["temp_in_opt"]) == T + 1
        assert len(d["temp_wh_opt"]) == T + 1
        for k in ("p_grid_opt", "p_load_opt", "cost_opt", "waterdraws",
                  "correct_solve", "hvac_cool_on_opt", "hvac_heat_on_opt",
                  "wh_heat_on_opt", "forecast_p_grid_opt"):
            assert len(d[k]) == T, (name, k)
        if "pv" in d["type"]:
            assert len(d["p_pv_opt"]) == T
            assert len(d["u_pv_curt_opt"]) == T
        if "battery" in d["type"]:
            assert len(d["e_batt_opt"]) == T + 1
            assert len(d["p_batt_ch"]) == T


def test_solve_rate_and_comfort(day_run):
    """Most solves succeed; on solved steps the planned temperatures honor
    the hard comfort bands (dragg/mpc_calc.py:318-340)."""
    agg, data = day_run
    homes = {k: v for k, v in data.items() if k != "Summary"}
    solved = np.array([d["correct_solve"] for d in homes.values()])
    assert solved.mean() > 0.7, f"solve rate {solved.mean()}"
    for i, (name, d) in enumerate(homes.items()):
        home = next(h for h in agg.all_homes if h["name"] == name)
        tin = np.array(d["temp_in_opt"][1:])
        ok = solved[i].astype(bool)
        lo = home["hvac"]["temp_in_min"] - 0.05
        hi = home["hvac"]["temp_in_max"] + 0.05
        assert np.all(tin[ok] >= lo) and np.all(tin[ok] <= hi), name


def test_winter_no_cooling(day_run):
    """January run: season gate must disable cooling (dragg/mpc_calc.py:302-309)."""
    _, data = day_run
    homes = {k: v for k, v in data.items() if k != "Summary"}
    for name, d in homes.items():
        assert np.max(d["hvac_cool_on_opt"]) == 0.0, name


def test_energy_accounting(day_run):
    """p_grid = p_load + batt - pv per home per step (dragg/mpc_calc.py:387-432),
    and agg series equals the per-home sum (dragg/aggregator.py:748-754)."""
    agg, data = day_run
    homes = {k: v for k, v in data.items() if k != "Summary"}
    total = np.zeros(agg.num_timesteps)
    for name, d in homes.items():
        p_load = np.array(d["p_load_opt"])
        p_grid = np.array(d["p_grid_opt"])
        batt = np.array(d.get("p_batt_ch", np.zeros(agg.num_timesteps))) + np.array(
            d.get("p_batt_disch", np.zeros(agg.num_timesteps))
        )
        pv = np.array(d.get("p_pv_opt", np.zeros(agg.num_timesteps)))
        np.testing.assert_allclose(p_grid, p_load + batt - pv, atol=1e-4)
        total += p_grid
    np.testing.assert_allclose(total, np.array(data["Summary"]["p_grid_aggregate"]), rtol=1e-5)


def test_battery_soc_within_bounds(day_run):
    """SoC trajectory respects capacity bounds (dragg/mpc_calc.py:371-372) —
    the validation the reference paper performs scientifically."""
    agg, data = day_run
    for name, d in data.items():
        if name == "Summary" or "battery" not in d["type"]:
            continue
        home = next(h for h in agg.all_homes if h["name"] == name)
        cap = home["battery"]["capacity"]
        lo = home["battery"]["capacity_lower"] * cap - 0.02
        hi = home["battery"]["capacity_upper"] * cap + 0.02
        soc = np.array(d["e_batt_opt"][1:])  # entry 0 is the init fraction (reference quirk)
        solved = np.array(d["correct_solve"]).astype(bool)
        assert np.all(soc[solved] >= lo) and np.all(soc[solved] <= hi), name


def test_determinism(tmp_path):
    """Same seed → identical trajectories (SURVEY.md §4c)."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 3
    cfg["community"]["homes_pv"] = 1
    cfg["simulation"]["end_datetime"] = "2015-01-01 06"
    cfg["home"]["hems"]["prediction_horizon"] = 3
    runs = []
    for sub in ("a", "b"):
        agg = Aggregator(config=cfg, outputs_dir=str(tmp_path / sub))
        agg.run()
        with open(os.path.join(agg.run_dir, "baseline", "results.json")) as f:
            runs.append(json.load(f))
    a, b = runs
    assert set(a) == set(b)
    for name in a:
        if name == "Summary":
            assert a[name]["p_grid_aggregate"] == b[name]["p_grid_aggregate"]
            continue
        assert a[name]["p_grid_opt"] == b[name]["p_grid_opt"]
        assert a[name]["temp_in_opt"] == b[name]["temp_in_opt"]


def test_homes_config_cache(tmp_path):
    """overwrite_existing=False reuses the cached population file
    (dragg/aggregator.py:263-271)."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 3
    cfg["community"]["homes_pv"] = 1
    cfg["simulation"]["end_datetime"] = "2015-01-01 02"
    cfg["home"]["hems"]["prediction_horizon"] = 2
    agg1 = Aggregator(config=cfg, outputs_dir=str(tmp_path))
    agg1.get_homes()
    names1 = [h["name"] for h in agg1.all_homes]
    cfg2 = json.loads(json.dumps(cfg))
    cfg2["community"]["overwrite_existing"] = False
    cfg2["simulation"]["random_seed"] = 999  # ignored: cache hit
    agg2 = Aggregator(config=cfg2, outputs_dir=str(tmp_path))
    agg2.get_homes()
    assert [h["name"] for h in agg2.all_homes] == names1


@pytest.mark.slow
def test_long_horizon_season_gate(tmp_path):
    """H=48 regression: the reference's unbounded 1.1^k forecast-noise
    growth flipped the 30 degC season gate to cooling-only in January at
    long horizons, certifying every home primal-infeasible (verified vs
    HiGHS).  With the capped noise std (tpu.forecast_noise_cap) the fleet
    must solve at H=48."""
    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    cfg = default_config()
    cfg["community"]["total_number_homes"] = 8
    cfg["community"]["homes_pv"] = 2
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["home"]["hems"]["prediction_horizon"] = 48
    env = load_environment(cfg, data_dir=None)
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24, 1, wd)
    batch = build_home_batch(homes, 48, 1, 6)
    eng = make_engine(batch, env, cfg, 0)
    state, out = eng.step(eng.init_state(), 0, np.zeros(48, dtype=np.float32))
    solved = np.asarray(out.correct_solve)
    assert solved.mean() >= 0.8, f"H=48 solve rate {solved.mean():.2f}"
    # January: heating, never cooling.
    assert float(np.asarray(out.hvac_cool_on).max()) == 0.0


def test_reference_solver_names_map(tiny_config):
    """An unmodified reference config (solver='GLPK_MI', config.toml:64)
    builds an engine on the batched IPM; unknown names raise."""
    import copy

    from dragg_tpu.engine import engine_params

    cfg = copy.deepcopy(tiny_config)
    for name in ("GLPK_MI", "ECOS", "GUROBI"):
        cfg["home"]["hems"]["solver"] = name
        assert engine_params(cfg, 0).solver == "ipm", name
    cfg["home"]["hems"]["solver"] = "ADMM"
    assert engine_params(cfg, 0).solver == "admm"
    cfg["home"]["hems"]["solver"] = "simplex"
    import pytest

    with pytest.raises(ValueError, match="solver"):
        engine_params(cfg, 0)


@pytest.mark.parametrize("solver", ["ipm", "admm"])
def test_integer_first_action_repair(tmp_path, solver):
    """MILP repair (tpu.integer_first_action, both solver families): on
    solved steps the APPLIED duty fractions must be integer counts / s
    (the reference's implementable discretization,
    dragg/mpc_calc.py:171-173,497-499), solve rate must not collapse vs
    the relaxation, and comfort bands must still hold.  The ADMM variant
    is the regression guard for the warm-start split: shifting warm
    starts from the REPAIRED solution measured a downstream solve-rate
    collapse 0.76 → 0.44 at this config (perf notes round 4); warm
    starts now always shift the relaxed solution."""
    cfg = default_config()
    cfg["community"]["total_number_homes"] = 8
    cfg["community"]["homes_pv"] = 1
    cfg["community"]["homes_battery"] = 1
    cfg["community"]["homes_pv_battery"] = 1
    cfg["simulation"]["end_datetime"] = "2015-01-02 00"
    cfg["home"]["hems"]["prediction_horizon"] = 6
    cfg["home"]["hems"]["solver"] = solver
    s = int(cfg["home"]["hems"]["sub_subhourly_steps"])

    def run(flag, sub):
        import copy

        c = copy.deepcopy(cfg)
        c["tpu"]["integer_first_action"] = flag
        agg = Aggregator(config=c, outputs_dir=str(tmp_path / sub))
        agg.run()
        with open(os.path.join(agg.run_dir, "baseline", "results.json")) as f:
            return json.load(f)

    base = run(False, "relaxed")
    rep = run(True, "repaired")

    def stats(data):
        solved_frac = []
        n_integral = n_counts = n_solved = 0
        for name, d in data.items():
            if name == "Summary":
                continue
            cs = np.asarray(d["correct_solve"], dtype=bool)
            n_solved += int(cs.sum())
            for key in ("hvac_cool_on_opt", "hvac_heat_on_opt", "wh_heat_on_opt"):
                counts = np.asarray(d[key])[cs] * s
                n_integral += int(np.sum(np.abs(counts - np.round(counts)) < 1e-3))
                n_counts += counts.size
            solved_frac.append(cs.mean())
        return (float(np.mean(solved_frac)),
                n_integral / max(n_counts, 1), n_solved)

    rate_base, int_base, _ = stats(base)
    rate_rep, int_rep, n_solved = stats(rep)
    assert n_solved > 0
    # Repaired applied actions are integer counts for the overwhelming
    # majority of solved steps — NOT all: the documented graceful
    # degradation keeps the relaxed (fractional) solution for homes whose
    # pinned re-solve fails, so a strict max-residual bound would fail by
    # design the first time one home's repair does (advisor finding, r4).
    # Measured coverage is 99.9 % (docs/perf_notes.md round 4).
    assert int_rep >= 0.9, f"repair coverage too low: {int_rep:.3f}"
    # The relaxation genuinely uses fractional cycles (else the repair
    # would be vacuous and the MILP gap unexplained).
    assert int_base < 0.9, f"relaxation unexpectedly integral: {int_base:.3f}"
    # No solve-rate collapse (repair failures keep the relaxed solution,
    # so the rate cannot drop below solved∩solved homes by much).
    assert rate_rep >= rate_base - 0.05, (rate_rep, rate_base)


def test_project_repair_checks_applied_wh_row():
    """The projection's comfort gate must bound BOTH k=1 WH entries: the
    EV row (i_twh+1, draw-mixed) and the APPLIED row (i_twh1, unmixed —
    what _finish propagates).  Round-5 regression: checking only the EV
    entry let a pinned action push the applied WH temp 0.124 degC out of
    band at 1000 homes (validate_scale).  Tampers a real relaxed
    solution so the two rows straddle the band edge and asserts the
    merged outcome is in-band-or-relaxed on the APPLIED row."""
    import jax.numpy as jnp

    from dragg_tpu.data import load_environment, load_waterdraw_profiles
    from dragg_tpu.engine import make_engine
    from dragg_tpu.homes import build_home_batch, create_homes

    cfg = default_config()
    cfg["community"]["total_number_homes"] = 4
    cfg["community"]["homes_pv"] = 0
    cfg["community"]["homes_battery"] = 0
    cfg["community"]["homes_pv_battery"] = 0
    cfg["simulation"]["end_datetime"] = "2015-01-02 00"
    cfg["home"]["hems"]["prediction_horizon"] = 4
    assert cfg["tpu"]["integer_repair"] == "project"
    env = load_environment(cfg)
    dt = env.dt
    wd = load_waterdraw_profiles(None, seed=12)
    homes = create_homes(cfg, 24 * dt, dt, wd)
    batch = build_home_batch(homes, 4 * dt, dt,
                             int(cfg["home"]["hems"]["sub_subhourly_steps"]))
    eng = make_engine(batch, env, cfg, 0)
    lay = eng.layout
    state = eng.init_state()
    qp, _aux = eng._prepare(eng._ctx0, state, jnp.asarray(0),
                            jnp.zeros((eng.params.horizon,), jnp.float32))
    from dragg_tpu.ops.ipm import ipm_solve_qp

    relaxed = ipm_solve_qp(eng.static.pattern, qp.vals, qp.b_eq,
                           qp.l_box, qp.u_box, qp.q, iters=30)
    assert bool(np.all(np.asarray(relaxed.solved)))
    # Tamper: push every home's APPLIED k=1 WH entry to the upper band
    # edge while the EV entry sits comfortably inside — a pin whose
    # positive delta is fine for the EV row now violates the applied row.
    hi_ap = np.asarray(qp.u_box)[:, lay.i_twh1]
    x = np.asarray(relaxed.x).copy()
    x[:, lay.i_twh1] = hi_ap - 1e-4
    x[:, lay.i_twh + 1] = hi_ap - 2.0
    # Force a +1 WH bump: make the rounded wh count exceed the relaxed.
    x[:, lay.i_wh] = np.clip(np.floor(x[:, lay.i_wh]) + 0.6, 0,
                             np.asarray(qp.u_box)[:, lay.i_wh])
    tampered = relaxed._replace(x=jnp.asarray(x, jnp.float32))

    def no_solver(l2, u2):  # project mode must never call it
        raise AssertionError("project mode called the solver")

    merged, _rf = eng._integerize_first_action(eng._ctx0, qp, tampered,
                                               no_solver)
    out_ap = np.asarray(merged.x)[:, lay.i_twh1]
    # Every home must end in-band on the APPLIED row (within the fp32
    # gate tolerance) — either via a comfort-safe pin or by keeping the
    # tampered relaxed value (which was in-band by construction).
    assert np.all(out_ap <= hi_ap + 2e-3), (out_ap, hi_ap)
