"""Benchmark-harness contract: the driver depends on bench.py always
printing exactly one parseable JSON line with the headline fields, rc 0 —
whatever happens to the backend (round-1 regression: a backend-init error
produced a bare traceback and no number)."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_contract():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel here
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected ONE json line, got: {proc.stdout!r}"
    result = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "solver",
                "solve_rate", "phase_s_per_step", "admm_iters_per_step",
                "band_kernel", "pallas_selftest", "semantics", "data",
                "precision", "mfu", "mfu_basis", "iter_kernel"):
        assert key in result, key
    # The shipped default is integer semantics (round 5) and the artifact
    # must say so; likewise the data environment (round 6 — bundled
    # assets are the shipped default and rates are not comparable
    # without the label).
    assert result["semantics"] == "integer"
    assert result["data"] == "bundled"
    # IPM runs must NOT report a refresh/cached split: the IPM has no
    # cross-step factor cache, so those keys would time the same program
    # (VERDICT r5 weak #4 — the "dead factor cache" was measurement
    # noise on an ipm run).
    assert "solve" in result["phase_s_per_step"]
    assert "solve_cached" not in result["phase_s_per_step"]
    assert result["unit"] == "timesteps/s"
    assert result["value"] > 0
    assert 0.5 <= result["solve_rate"] <= 1.0
    # On the CPU smoke run the resolved kernel must be the XLA path and the
    # Pallas self-test must not have been attempted.
    assert result["band_kernel"] == "xla"
    assert result["pallas_selftest"] is None
    # flops_per_step is ALWAYS populated (round 7 — analytic model,
    # platform-free); since ISSUE 11 the MFU key is never silently
    # dropped either: off-TPU it is computed against the clearly-
    # labelled CPU estimate, and mfu_basis names what the denominator
    # was (the schema satellite — ``peak`` used to be silently None
    # off-TPU, leaving every committed CPU artifact without MFU).
    assert result["flops_per_step_est"] is not None
    assert result["flops_per_step_est"] > 0
    assert result["mfu"] is not None and result["mfu"] >= 0
    assert result["mfu_basis"] == "cpu_estimate"
    # Precision is a HARD bench_trend series key; the smoke default is
    # the bit-identical f32 policy.  iter_kernel reports only for the
    # reluqp family (null for the ipm smoke).
    assert result["precision"] == "f32"
    assert result["iter_kernel"] is None


@pytest.mark.slow  # round-11 tier-1 budget trim: tier-1 keeps test_bench_smoke_contract (the child contract) and the resilience ladder tests; the dual-report ladder compiles two bench children
def test_bench_probe_gated_ladder_dual_report(tmp_path):
    """The DRIVER path (no --smoke): every TPU attempt is gated on a
    hard-timeout classified tunnel probe (resilience.liveness), the
    fallback is a FULL-size CPU run labelled ``fallback: true`` with the
    attempt ladder recorded, and the probe verdict lands in
    $DRAGG_PROBE_LOG (round-4 hardening — a wedged tunnel burned 22 min
    of the round-3 driver run).  ``--dual-report`` emits one line per
    data environment (bundled + synthetic, VERDICT r5 weak #3)."""
    probe_log = str(tmp_path / "probe_log.txt")
    env = dict(os.environ, JAX_PLATFORMS="cpu", DRAGG_PROBE_LOG=probe_log)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel here
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--homes", "40",
         "--horizon-hours", "2", "--steps", "2", "--chunks", "1",
         "--dual-report"],
        capture_output=True, text=True, timeout=420, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 2, f"expected TWO json lines, got: {proc.stdout!r}"
    results = [json.loads(ln) for ln in lines]
    assert [r["data"] for r in results] == ["bundled", "synthetic"]
    for result in results:
        # Probe failed (CPU-only env) → no TPU attempt, full-size CPU
        # fallback at the requested size.
        assert result["fallback"] is True
        assert result["n_homes"] == 40
        assert result["value"] > 0
        attempts = result["attempts"]
        # No tpu attempt may have EXECUTED; the probe-down verdict itself
        # is recorded as a skipped entry WITH its classified failure kind
        # so the artifact explains why nothing ran (ADVICE round 4 +
        # round-6 taxonomy).
        tpu = [a for a in attempts if a.get("platform") == "tpu"]
        assert tpu and all(a.get("skipped") == "probe_down" for a in tpu)
        assert tpu[0]["failure"] == "TUNNEL_DOWN"
    # The probe verdict is a committed-able artifact, not just a log line.
    with open(probe_log) as f:
        content = f.read()
    assert "DOWN" in content


def test_validate_scale_smoke():
    """The scale-validation tool runs end-to-end at a tiny config and emits
    its one-line JSON verdict with ok=true."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "validate_scale.py"),
         "--homes", "16", "--horizon-hours", "4", "--days", "1",
         "--chunk", "12", "--min-solve-rate", "0.8"],
        capture_output=True, text=True, timeout=400, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"] is True
    assert result["homes"] == 16
    assert 0.8 <= result["solve_rate"] <= 1.0
    assert result["comfort_violation_max"] <= 0.05


@pytest.mark.slow  # round-11 tier-1 budget trim: tier-1 keeps the unsharded validate_scale smoke; the 8-device sharded variant doubles the compile
def test_validate_scale_sharded_smoke():
    """--sharded mode (the row-5 topology the 100k instantiation and the
    on-chip runbook use) runs a capped-step chunk over the mesh and emits
    the extended JSON (home_slots / n_devices / peak_rss_gb)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "validate_scale.py"),
         "--homes", "32", "--horizon-hours", "4", "--days", "1",
         "--chunk", "4", "--steps", "4", "--sharded",
         "--min-solve-rate", "0.8"],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"] is True
    assert result["sharded"] is True and result["n_devices"] == 8
    assert result["steps"] == 4 and result["home_slots"] >= 32
    assert result["peak_rss_gb"] > 0


def test_doctor_reports_usable_environment(tmp_path):
    """doctor exits 0 with every check ok on the CPU test environment and
    never hangs on backend init (hard subprocess timeout inside)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "dragg_tpu", "doctor",
         "--outputs-dir", str(tmp_path / "out"), "--backend-timeout", "120"],
        capture_output=True, text=True, timeout=400, env=env, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr[-1000:]
    assert "DOCTOR: environment usable" in proc.stdout
    assert "[FAIL]" not in proc.stdout
