"""Load-harness + shared-schema tests (ISSUE 13 satellites).

* the soak and the load generator share ONE request builder
  (dragg_tpu/serve/loadgen.py) and ONE JSON-line envelope schema — both
  pinned here, end-to-end via the real CLIs (stub workers, seconds);
* the bench_trend ``serve`` series is hard-keyed: serve_load rows pair
  only with serve_load rows and never gate against engine-throughput
  history.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from dragg_tpu.serve import loadgen  # noqa: E402


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_tool(args: list[str], timeout: int = 240) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(  # noqa: S603
        [sys.executable] + args, cwd=ROOT, env=env, timeout=timeout,
        capture_output=True, text=True)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    lines = [ln for ln in out.stdout.splitlines()
             if ln.startswith("{")]
    assert lines, out.stdout[-2000:]
    return json.loads(lines[-1])


# ------------------------------------------------------- shared builder
def test_build_requests_default_reproduces_soak_trace():
    """The historical soak trace shape is the builder's default output —
    soak runs keep replaying the exact same stream they always did."""
    reqs = loadgen.build_requests(8, 6)
    assert [r["id"] for r in reqs] == [f"r{i:03d}" for i in range(8)]
    for i, r in enumerate(reqs):
        assert r["t"] == i % 3 and r["home"] == i % 6
        assert ("state" in r) == (i % 4 == 0)
        assert "rp" not in r and "steps" not in r and "pattern" not in r
    # Seeded draws are deterministic and distribution knobs stick.
    a = loadgen.build_requests(6, 4, rp_values=(0.0, 0.02), steps=3,
                               pattern="short", seed=7)
    b = loadgen.build_requests(6, 4, rp_values=(0.0, 0.02), steps=3,
                               pattern="short", seed=7)
    assert a == b
    assert {r.get("rp") for r in a} == {None, 0.02}
    assert all(r["steps"] == 3 and r["pattern"] == "short" for r in a)


def test_envelope_schema_keys():
    env = loadgen.result_envelope("x", ok=True, homes=4, requests=2,
                                  metrics={}, violations=[], extra_key=1)
    for key in loadgen.REQUIRED_KEYS:
        assert key in env
    assert env["schema"] == loadgen.SCHEMA and env["extra_key"] == 1


# ------------------------------------------------ end-to-end CLI schema
def test_serve_load_cli_emits_shared_schema(tmp_path):
    r = _run_tool(["tools/serve_load.py", "--stub", "--rates", "16",
                   "--duration-s", "1", "--root", str(tmp_path / "load")])
    for key in loadgen.REQUIRED_KEYS:
        assert key in r, key
    assert r["schema"] == loadgen.SCHEMA
    assert r["tool"] == "serve_load" and r["ok"] is True
    assert r["metric"] == "serve_sat_rps" and r["value"] > 0
    assert r["serve"].startswith("pool-C")
    assert r["levels"] and r["levels"][0]["p99_s"] is not None
    assert r["violations"] == []


def test_serve_soak_cli_emits_shared_schema(tmp_path):
    r = _run_tool(["tools/serve_soak.py", "--stub", "--scenario",
                   "baseline", "--homes", "4", "--trace-len", "6",
                   "--root", str(tmp_path / "soak")])
    for key in loadgen.REQUIRED_KEYS:
        assert key in r, key
    assert r["schema"] == loadgen.SCHEMA
    assert r["tool"] == "serve_soak" and r["ok"] is True


# -------------------------------------------------- bench_trend series
def test_bench_trend_serve_series_is_hard_keyed(tmp_path):
    """serve rows pair with serve rows of the SAME pool geometry and
    never against engine-throughput rows — the serve key is a hard key
    with its own gate."""
    bench_trend = _load_tool("bench_trend")

    def row(ordinal, **kw):
        base = dict(metric="serve_sat_rps", platform="cpu", solver="ipm",
                    value=10.0, serve="pool-C8x1w")
        base.update(kw)
        p = tmp_path / f"BENCH_r{ordinal:02d}.json"
        p.write_text(json.dumps(base))
        return str(p)

    arts = [
        row(1, metric="engine", value=100.0, serve="none"),
        row(2, metric="engine", value=100.0, serve="none"),
        row(3, value=10.0),
        row(4, value=10.5),
        row(5, value=12.0, serve="pool-C1x1w"),  # different geometry
    ]
    entries = []
    for i, p in enumerate(arts):
        entries.extend(bench_trend.load_artifact(p, i + 1))
    trend = bench_trend.build_trend(entries, 0.10)
    keys = [(r["key"]["metric"], r["key"]["serve"]) for r in trend["rows"]]
    assert ("engine", "none") in keys
    assert ("serve_sat_rps", "pool-C8x1w") in keys
    # The C1 row has no partner: no pair mixes pool geometries, and no
    # pair mixes serve rows with engine rows.
    assert len(keys) == 2
    assert trend["n_regressions"] == 0
    # A regressing serve pair gates like any other series.
    arts.append(row(6, value=5.0))
    entries = []
    for i, p in enumerate(arts):
        entries.extend(bench_trend.load_artifact(p, i + 1))
    trend = bench_trend.build_trend(entries, 0.10)
    assert trend["n_regressions"] == 1


# ------------------------------------------------- events-tail follower
def test_event_follower_contains_prefilter(tmp_path):
    """``poll(contains=...)`` skips the JSON parse of non-matching lines
    (each /result?stream=1 consumer follows the FULL events stream, so
    the chunk filter must not pay for every other event kind) — and
    filtered-out lines never resurface on later polls."""
    path = str(tmp_path / "events.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"event": "serve.request", "id": "a"}) + "\n")
        f.write(json.dumps({"event": "serve.chunk", "id": "a",
                            "step": 0}) + "\n")
    fo = loadgen.EventFollower(path)
    recs = fo.poll(contains=b'"serve.chunk"')
    assert [r["event"] for r in recs] == ["serve.chunk"]
    # Incremental: only appended matches show up on the next poll.
    with open(path, "a") as f:
        f.write(json.dumps({"event": "serve.done", "id": "a"}) + "\n")
        f.write(json.dumps({"event": "serve.chunk", "id": "a",
                            "step": 1}) + "\n")
    recs = fo.poll(contains=b'"serve.chunk"')
    assert [(r["event"], r["step"]) for r in recs] == [("serve.chunk", 1)]
    # Unfiltered polling still sees everything appended after that.
    with open(path, "a") as f:
        f.write(json.dumps({"event": "serve.failed", "id": "b"}) + "\n")
    assert [r["event"] for r in fo.poll()] == ["serve.failed"]
