"""Home-synthesis determinism + data-ingestion tests (SURVEY.md §4(c))."""

import numpy as np
import pytest

from dragg_tpu.config import ConfigError, default_config, validate_config
from dragg_tpu.data import build_tou, load_environment, parse_dt, synth_waterdraw_profiles, synth_weather
from dragg_tpu.homes import HOME_TYPES, build_home_batch, check_home_configs, create_homes


def _make_homes(cfg, num_timesteps=24, dt=1, seed=None):
    if seed is not None:
        cfg["simulation"]["random_seed"] = seed
    wd = synth_waterdraw_profiles(seed=7)
    return create_homes(cfg, num_timesteps, dt, wd)


class TestConfig:
    def test_default_validates(self):
        validate_config(default_config())

    def test_missing_key_raises(self):
        cfg = default_config()
        del cfg["home"]["hvac"]["r_dist"]
        with pytest.raises(ConfigError):
            validate_config(cfg)


class TestHomes:
    def test_counts_and_order(self, tiny_config):
        homes = _make_homes(tiny_config)
        assert len(homes) == 6
        check_home_configs(homes, tiny_config)
        # Creation order parity: pv_battery, pv_only, battery_only, base
        # (dragg/aggregator.py:393-578).
        assert [h["type"] for h in homes[:3]] == ["pv_battery", "pv_only", "battery_only"]
        assert all(h["type"] == "base" for h in homes[3:])

    def test_seed_determinism(self, tiny_config):
        a = _make_homes(dict(tiny_config), seed=42)
        b = _make_homes(dict(tiny_config), seed=42)
        c = _make_homes(dict(tiny_config), seed=43)
        assert a[0]["name"] == b[0]["name"]
        for ha, hb in zip(a, b):
            assert ha["hvac"]["r"] == hb["hvac"]["r"]
            assert ha["wh"]["draw_sizes"] == hb["wh"]["draw_sizes"]
        assert any(x["hvac"]["r"] != y["hvac"]["r"] for x, y in zip(a, c))

    def test_parameter_ranges(self, tiny_config):
        homes = _make_homes(tiny_config)
        hv = tiny_config["home"]["hvac"]
        for h in homes:
            assert hv["r_dist"][0] <= h["hvac"]["r"] <= hv["r_dist"][1]
            db = h["hvac"]["temp_in_max"] - h["hvac"]["temp_in_min"]
            assert hv["temp_deadband_dist"][0] - 1e-9 <= db <= hv["temp_deadband_dist"][1] + 1e-9
            assert h["hvac"]["temp_in_min"] <= h["hvac"]["temp_in_init"] <= h["hvac"]["temp_in_max"]
            assert h["wh"]["temp_wh_min"] <= h["wh"]["temp_wh_init"] <= h["wh"]["temp_wh_max"]
            # draws clipped to tank size (dragg/aggregator.py:376)
            assert max(h["wh"]["draw_sizes"]) <= h["wh"]["tank_size"] + 1e-9

    def test_batch_padding(self, tiny_config):
        homes = _make_homes(tiny_config)
        batch = build_home_batch(homes, horizon=4, dt=1, sub_steps=6)
        assert batch.n_homes == 6
        # base homes have zero-width battery/pv blocks
        base = np.asarray(batch.type_code) == HOME_TYPES.index("base")
        assert np.all(np.asarray(batch.batt_max_rate)[base] == 0)
        assert np.all(np.asarray(batch.pv_area)[base] == 0)
        # powers divided by sub_steps (dragg/mpc_calc.py:159-162)
        assert np.allclose(np.asarray(batch.hvac_p_c), np.array([h["hvac"]["p_c"] for h in homes]) / 6)
        # leading zero pad on draws: horizon//dt + 1 hours (dragg/mpc_calc.py:194)
        assert np.all(np.asarray(batch.draws_hourly)[:, :5] == 0)


class TestData:
    def test_tou_reference_parity(self):
        """Reference bug parity: peak price is overwritten by shoulder
        (dragg/aggregator.py:214-215) — peak never appears unless fixed."""
        start = parse_dt("2015-01-01 00")
        tou = build_tou(48, start, 1, 0.07, True, (9, 21), 0.09, (14, 18), 0.13)
        assert set(np.unique(tou)) == {0.07, 0.09}
        assert tou[10] == 0.09 and tou[2] == 0.07 and tou[15] == 0.09
        fixed = build_tou(48, start, 1, 0.07, True, (9, 21), 0.09, (14, 18), 0.13, fix_tou_peak=True)
        assert fixed[15] == 0.13 and fixed[10] == 0.09

    def test_synth_weather_shapes_and_determinism(self):
        oat1, ghi1, _ = synth_weather(parse_dt("2015-01-01 00"), days=3, dt=1, seed=5)
        oat2, ghi2, _ = synth_weather(parse_dt("2015-01-01 00"), days=3, dt=1, seed=5)
        assert oat1.shape == (72,)
        np.testing.assert_array_equal(oat1, oat2)
        assert ghi1.min() >= 0
        assert np.all(ghi1[:5] == 0)  # midnight: no sun

    def test_load_environment_coverage(self, tiny_config):
        env = load_environment(tiny_config)
        start = parse_dt(tiny_config["simulation"]["start_datetime"])
        end = parse_dt(tiny_config["simulation"]["end_datetime"])
        env.check_coverage(start, end, tiny_config["home"]["hems"]["prediction_horizon"])
        assert env.start_index(start) == 0
        with pytest.raises(ValueError):
            env.check_coverage(start, parse_dt("2099-01-01 00"), 4)


def test_config_reference_doc_covers_all_keys():
    """docs/config.md documents every leaf key in default_config — a new
    knob without documentation fails here.  The check itself is now
    dragglint rule DT011 (dragg_tpu/analysis/project.py, ISSUE 14); this
    test asserts it through the run_rules wrapper so the suite and the
    analyzer CLI can never disagree."""
    from dragg_tpu.analysis import run_rules

    findings = run_rules(select={"DT011"})
    assert findings == [], [f.render() for f in findings]
