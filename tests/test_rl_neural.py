"""Neural (Flax DDPG) agent tests and the learning-efficacy test the round-1
verdict called for (weak #5): a trained policy must beat the zero-action
baseline on tracking error in the cheap simplified environment.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from dragg_tpu.config import default_config
from dragg_tpu.rl import neural
from dragg_tpu.rl.core import (
    RLObservation,
    _phi_s,
    init_carry as linear_init,
    train_step as linear_step,
    params_from_config as linear_params,
)


def _ddpg_config():
    cfg = default_config()
    cfg["rl"]["parameters"]["agent"] = "ddpg"
    return cfg


def test_ddpg_step_shapes_and_determinism():
    cfg = _ddpg_config()
    p = neural.params_from_config(cfg)
    c0 = neural.init_carry(p, seed=7)
    obs = RLObservation(
        fcst_error=jnp.float32(0.2), forecast_trend=jnp.float32(-0.1),
        time_of_day=jnp.float32(0.5), delta_action=jnp.float32(0.0),
        reward=jnp.float32(-0.04),
    )
    step = jax.jit(lambda c, o: neural.train_step(c, o, p))
    c1, rec = step(c0, obs)
    c1b, recb = step(c0, obs)
    # Deterministic given the carry.
    assert float(c1.next_action) == float(c1b.next_action)
    assert float(rec.mu) == float(recb.mu)
    assert int(c1.t) == 1
    assert p.action_low <= float(c1.next_action) <= p.action_high
    # Telemetry slots are scalars (parameter norms) — schema-compatible.
    assert np.asarray(rec.theta_q).shape == ()
    assert np.asarray(rec.theta_mu).shape == ()
    # A second step advances the buffer.
    c2, _ = step(c1, obs)
    assert int(c2.t) == 2


def test_ddpg_actor_update_gated_until_batch():
    """No parameter motion before the replay buffer holds a batch."""
    cfg = _ddpg_config()
    p = neural.params_from_config(cfg)
    c = neural.init_carry(p, seed=3)
    obs = RLObservation(
        fcst_error=jnp.float32(0.1), forecast_trend=jnp.float32(0.0),
        time_of_day=jnp.float32(0.1), delta_action=jnp.float32(0.0),
        reward=jnp.float32(-0.01),
    )
    step = jax.jit(lambda c, o: neural.train_step(c, o, p))
    c1, _ = step(c, obs)
    for a, b in zip(jax.tree.leaves(c.actor), jax.tree.leaves(c1.actor)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(c.critic1), jax.tree.leaves(c1.critic1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ddpg_policy_delay_freezes_actor():
    """Off-cadence steps must not move the actor AT ALL — gradient-zeroing
    alone lets Adam momentum keep drifting the parameters."""
    cfg = _ddpg_config()
    p = neural.params_from_config(cfg)._replace(batch_size=2, policy_delay=4)
    c = neural.init_carry(p, seed=5)
    step = jax.jit(lambda c, o: neural.train_step(c, o, p))
    key = jax.random.PRNGKey(0)
    moved = []
    for t in range(12):
        key, sub = jax.random.split(key)
        v = jax.random.uniform(sub, (5,), jnp.float32, -0.3, 0.3)
        obs = RLObservation(*[v[i] for i in range(5)])
        c1, _ = step(c, obs)
        diff = max(float(jnp.max(jnp.abs(a - b)))
                   for a, b in zip(jax.tree.leaves(c.actor), jax.tree.leaves(c1.actor)))
        moved.append(diff > 0)
        c = c1
    # After warmup (t>=batch_size), the actor moves ONLY on the delay cadence.
    for t, m in enumerate(moved):
        expected = (t >= p.batch_size) and (t % 4 == 0)
        assert m == expected, f"step t={t}: actor moved={m}, expected {expected}"


def test_utility_agent_ddpg_selection():
    from dragg_tpu.rl.agent import UtilityAgent

    agent = UtilityAgent(_ddpg_config())
    assert agent.kind == "ddpg"
    assert agent.rl_data["parameters"]["agent"] == "ddpg"
    c, rec = jax.jit(agent.scan_step)(agent.carry, RLObservation(
        fcst_error=jnp.float32(0.0), forecast_trend=jnp.float32(0.0),
        time_of_day=jnp.float32(0.0), delta_action=jnp.float32(0.0),
        reward=jnp.float32(0.0),
    ))
    assert int(c.t) == 1
    with pytest.raises(ValueError):
        agent.load_from_previous("nope.json")


# --------------------------------------------------------------------------
# Learning efficacy (round-1 verdict item 6)
# --------------------------------------------------------------------------
#
# Environment: the simplified linear community response
# (dragg/aggregator.py:903-909) with a daily sinusoidal disturbance and a
# strong response rate, so the price signal materially moves the load:
#
#     load_{t+1} = load_t + kick(t) - c * rp_t * (sp_t - load_t)
#     sp = trailing mean of load (gen_setpoint, dragg/aggregator.py:687-696)
#     reward = -((load - sp)/norm)^2
#
# A competent policy damps the disturbance (rp of the right SIGN per state);
# the zero-action baseline only has the passive trailing-average decay.

NORM = 100.0
C_RATE = 4.0
PREV_N = 12
KICK = 8.0


def _env_scan(mu_fn, carry0, steps, sigma, key, train_fn=None):
    """Roll the forced env.  ``mu_fn(acarry, s) -> rp``; when ``train_fn`` is
    given the agent learns online (exploration noise sigma), otherwise the
    policy is evaluated greedily."""

    def step(c, t):
        acarry, load, prev_load, tracked, prev_a, a, key = c
        sp = jnp.mean(tracked)
        s = jnp.stack([
            (load - sp) / NORM, (load - prev_load) / NORM,
            jnp.mod(t, 24).astype(jnp.float32) / 24.0, a - prev_a,
        ])
        err = (load - sp) / NORM
        r = -(err * err)
        if train_fn is not None:
            obs = RLObservation(
                fcst_error=s[0], forecast_trend=s[1], time_of_day=s[2],
                delta_action=s[3], reward=r,
            )
            acarry, _ = train_fn(acarry, obs)
            rp = acarry.next_action
        else:
            rp = mu_fn(acarry, s)
        key, sub = jax.random.split(key)
        rp = jnp.clip(rp + sigma * jax.random.normal(sub, (), jnp.float32),
                      -0.05, 0.05)
        kick = KICK * jnp.sin(2 * jnp.pi * t / 24.0)
        new_load = load + kick - C_RATE * rp * (sp - load) * 1.0
        tracked = jnp.concatenate([tracked[1:], jnp.reshape(new_load, (1,))])
        return (acarry, new_load, load, tracked, a, rp, key), err * err

    c0 = (carry0, jnp.float32(55.0), jnp.float32(50.0),
          jnp.full((PREV_N,), 50.0, jnp.float32),
          jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32), key)
    cN, errs = lax.scan(step, c0, jnp.arange(steps))
    return cN[0], errs


@pytest.mark.parametrize("kind", ["linear", "ddpg"])
def test_trained_policy_beats_zero_action(kind):
    cfg = default_config()
    cfg["rl"]["utility"]["action_space"] = [-0.05, 0.05]
    if kind == "ddpg":
        p = neural.params_from_config(cfg)
        p = p._replace(sigma=0.02, action_low=-0.05, action_high=0.05)
        carry0 = neural.init_carry(p, seed=11)
        train_fn = jax.jit(lambda c, o: neural.train_step(c, o, p))
        mu_fn = lambda c, s: neural._mu(c.actor, s, p)
    else:
        p = linear_params(cfg)
        p = p._replace(sigma=0.02, action_low=-0.05, action_high=0.05)
        carry0 = linear_init(p, seed=11)
        train_fn = jax.jit(lambda c, o: linear_step(c, o, p))
        mu_fn = lambda c, s: jnp.clip(c.theta_mu @ _phi_s(s), -0.05, 0.05)

    key = jax.random.PRNGKey(0)
    trained, _ = _env_scan(mu_fn, carry0, 3000, sigma=0.0, key=key,
                           train_fn=train_fn)

    # Greedy evaluation of the trained policy vs the zero policy on the same
    # disturbance sequence (no exploration noise, no learning).
    _, err_trained = _env_scan(jax.jit(mu_fn), trained, 400, sigma=0.0,
                               key=jax.random.PRNGKey(1))
    zero_mu = lambda c, s: jnp.zeros((), jnp.float32)
    _, err_zero = _env_scan(zero_mu, trained, 400, sigma=0.0,
                            key=jax.random.PRNGKey(1))
    mse_trained = float(jnp.mean(err_trained[100:]))
    mse_zero = float(jnp.mean(err_zero[100:]))
    # The trained policy must reduce steady-state tracking error by >=10%.
    assert mse_trained < 0.9 * mse_zero, (
        f"{kind}: trained {mse_trained:.6f} vs zero {mse_zero:.6f}"
    )


def test_rl_simplified_runs_with_ddpg(tmp_path):
    """End-to-end: the simplified case scans the DDPG core on device."""
    from dragg_tpu.aggregator import Aggregator

    cfg = _ddpg_config()
    cfg["community"]["total_number_homes"] = 3
    cfg["simulation"]["run_rbo_mpc"] = False
    cfg["simulation"]["run_rl_simplified"] = True
    cfg["simulation"]["end_datetime"] = "2015-01-02 00"
    agg = Aggregator(cfg, data_dir=None, outputs_dir=str(tmp_path / "out"))
    agg.run()
    assert agg.agent is not None and agg.agent.kind == "ddpg"
    rl = agg.agent.rl_data
    assert len(rl["action"]) == agg.num_timesteps
    assert all(np.isfinite(rl["mu"]))


# --------------------------------------------------------------------------
# Fleet batch axis (ROADMAP item 1 — dragg_tpu/rl/fleet shared DDPG core)
# --------------------------------------------------------------------------

def test_fleet_ddpg_core_step():
    """The shared twin-Q DDPG core under the fleet batch axis: ONE set
    of networks over the (4 + F)-scalar fleet state, C rollout streams,
    shared replay (C transitions per step), delayed actor gating on the
    FLEET step counter, per-community exploration divergence."""
    from dragg_tpu.rl.fleet import (
        FLEET_STATE_SCALARS,
        FleetObservation,
        N_EVENT_FEATURES,
        fleet_ddpg_step,
        fleet_params_from_config,
        init_fleet_ddpg,
    )

    C = 3
    cfg = _ddpg_config()
    cfg["fleet"] = {"communities": C}
    cfg["rl"]["fleet"] = {"learner_batch": 8}
    params = neural.params_from_config(cfg)
    fparams = fleet_params_from_config(cfg, C)
    assert fparams.learner_batch == 8
    c1 = init_fleet_ddpg(params, fparams, cfg)
    c2 = init_fleet_ddpg(params, fparams, cfg)
    f32 = jnp.float32
    rep = lambda v: jnp.full((C,), v, f32)

    def fobs(fe, r):
        return FleetObservation(
            obs=RLObservation(rep(fe), rep(0.0), rep(0.5), rep(0.0),
                              rep(r)),
            events=jnp.zeros((C, N_EVENT_FEATURES), f32),
            drda=jnp.zeros((C,), f32))

    step = jax.jit(lambda c, o: fleet_ddpg_step(c, o, params, fparams))
    crit0 = np.asarray(jax.tree.leaves(c1.critic1)[0]).copy()
    for k in range(6):
        c1, rec = step(c1, fobs(0.1 * k, -0.2))
        c2, _ = step(c2, fobs(0.1 * k, -0.2))
    # Determinism across identical carries.
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(c1.actor)[0]),
        np.asarray(jax.tree.leaves(c2.actor)[0]))
    assert np.asarray(c1.state).shape == (C, FLEET_STATE_SCALARS)
    assert np.asarray(c1.mem_s).shape[1] == FLEET_STATE_SCALARS
    assert np.asarray(rec.q_pred).shape == (C,)
    assert int(c1.t) == 6
    # Shared replay holds C transitions per step (degenerate t=0
    # dropped): 5·C valid slots written.
    assert np.any(np.asarray(c1.mem_r[:5 * C]) != 0.0)
    # Per-community exploration streams are distinct (the sampled
    # actions may still COLLIDE at the clip bounds — σ=0.05 vs a ±0.02
    # action space — so the stream keys carry the claim, with at least
    # two distinct actions as the observable consequence).
    keys = np.asarray(c1.comm_keys)
    assert len({tuple(k) for k in keys}) == C
    acts = np.asarray(c1.next_action)
    assert len(set(np.round(acts, 8).tolist())) >= 2
    # The learner engaged once the shared buffer beat learner_batch
    # (valid = t·C ≥ 8 from step 3): critics moved off init.
    assert not np.array_equal(crit0,
                              np.asarray(jax.tree.leaves(c1.critic1)[0]))
    for f in rec:
        assert np.all(np.isfinite(np.asarray(f)))


@pytest.mark.slow  # end-to-end leg; light sibling: test_fleet_ddpg_core_step
def test_fleet_ddpg_simplified_end_to_end(tmp_path):
    """C=2 simplified fleet with the shared DDPG core — the Flax carry
    (nested param dicts + Adam moments) threads the fused fleet scan."""
    from dragg_tpu.aggregator import Aggregator

    cfg = _ddpg_config()
    cfg["community"]["total_number_homes"] = 3
    cfg["simulation"]["run_rbo_mpc"] = False
    cfg["simulation"]["run_rl_simplified"] = True
    cfg["simulation"]["end_datetime"] = "2015-01-02 00"
    cfg["fleet"] = {"communities": 2}
    cfg["telemetry"] = {"enabled": False}
    agg = Aggregator(cfg, data_dir=None, outputs_dir=str(tmp_path / "out"))
    agg.run()
    assert agg.agent.kind == "ddpg"
    assert agg.agent.fparams.policy == "shared"
    rl = agg.agent.rl_data
    assert len(rl["action"]) == agg.num_timesteps
    assert len(rl["action_by_community"][0]) == 2
    assert all(np.isfinite(rl["mu"]))
