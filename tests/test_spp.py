"""SPP (ERCOT day-ahead price) ingestion tests — the working equivalent of
the reference's dead spp path (dragg/aggregator.py:167-204, SURVEY.md §5.6)."""

from datetime import datetime

import numpy as np
import pandas as pd
import pytest

from dragg_tpu.config import default_config
from dragg_tpu.data import _align_price_series, load_environment, load_spp, synth_spp


def _ercot_csv(tmp_path, rows):
    df = pd.DataFrame(rows, columns=[
        "Delivery Date", "Hour Ending", "Repeated Hour Flag",
        "Settlement Point", "Settlement Point Price",
    ])
    path = str(tmp_path / "spp_data.csv")
    df.to_csv(path, index=False)
    return path


def test_load_spp_conversion_and_zone_filter(tmp_path):
    rows = [
        ["01/01/2015", "01:00", "N", "LZ_HOUSTON", 25.0],   # hour-beginning 0
        ["01/01/2015", "02:00", "N", "LZ_HOUSTON", 30.0],
        ["01/01/2015", "01:00", "N", "LZ_WEST", 99.0],      # other zone dropped
        ["01/01/2015", "03:00", "N", "LZ_HOUSTON", 45.0],
    ]
    prices, start = load_spp(_ercot_csv(tmp_path, rows), "LZ_HOUSTON", dt=1)
    assert start == datetime(2015, 1, 1, 0)
    np.testing.assert_allclose(prices, [0.025, 0.030, 0.045])  # $/MWh → $/kWh


def test_load_spp_subhourly_repeat_and_gap_fill(tmp_path):
    rows = [
        ["01/01/2015", "1", "N", "LZ_HOUSTON", 10.0],
        # hour 2 missing → forward-filled
        ["01/01/2015", "3", "N", "LZ_HOUSTON", 30.0],
    ]
    prices, start = load_spp(_ercot_csv(tmp_path, rows), "LZ_HOUSTON", dt=2)
    np.testing.assert_allclose(prices, [0.01, 0.01, 0.01, 0.01, 0.03, 0.03])


def test_load_spp_repeated_hour_dedup(tmp_path):
    rows = [
        ["11/01/2015", "1", "N", "LZ_HOUSTON", 10.0],
        ["11/01/2015", "1", "Y", "LZ_HOUSTON", 20.0],  # DST repeated hour
    ]
    prices, _ = load_spp(_ercot_csv(tmp_path, rows), "LZ_HOUSTON", dt=1)
    np.testing.assert_allclose(prices, [0.01])


def test_load_spp_missing_zone_raises(tmp_path):
    rows = [["01/01/2015", "1", "N", "LZ_WEST", 10.0]]
    with pytest.raises(ValueError, match="LZ_HOUSTON"):
        load_spp(_ercot_csv(tmp_path, rows), "LZ_HOUSTON", dt=1)


def test_align_price_series_offsets():
    prices = np.array([1.0, 2.0, 3.0, 4.0])
    # Price series starts 2 hours after the weather grid: leading steps take
    # the first price, trailing steps hold the last.
    out = _align_price_series(
        prices, datetime(2015, 1, 1, 2), datetime(2015, 1, 1, 0),
        n_steps=8, dt=1, base_price=0.07,
    )
    np.testing.assert_allclose(out, [1, 1, 1, 2, 3, 4, 4, 4])
    assert _align_price_series(np.array([]), datetime(2015, 1, 1),
                               datetime(2015, 1, 1), 3, 1, 0.07).tolist() == [0.07] * 3


def test_environment_spp_synth_path():
    cfg = default_config()
    cfg["agg"]["spp_enabled"] = True
    env = load_environment(cfg, data_dir=None)
    assert env.tou.shape == env.oat.shape
    # Synthetic DAM prices: positive, sub-$0.2/kWh, with diurnal structure.
    assert np.all(env.tou > 0) and np.all(env.tou < 0.2)
    day = env.tou[: 24 * env.dt]
    assert day.argmax() != 0


def test_environment_spp_csv_path(tmp_path):
    cfg = default_config()
    cfg["agg"]["spp_enabled"] = True
    cfg["simulation"]["load_zone"] = "LZ_HOUSTON"
    rows = []
    for d in range(3):
        for h in range(1, 25):
            rows.append([f"01/{d+1:02d}/2015", str(h), "N", "LZ_HOUSTON", 20.0 + h])
    _ercot_csv(tmp_path, rows)
    # weather is synthetic (no nsrdb.csv in tmp_path) but SPP comes from file
    env = load_environment(cfg, data_dir=str(tmp_path))
    assert env.tou[0] == pytest.approx(0.021)  # hour-beginning 0 ← HE 1
    assert env.tou.shape == env.oat.shape


def test_synth_spp_deterministic():
    a = synth_spp(datetime(2015, 1, 1), days=2, dt=1, seed=5)
    b = synth_spp(datetime(2015, 1, 1), days=2, dt=1, seed=5)
    np.testing.assert_array_equal(a, b)
