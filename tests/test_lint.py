"""tools/lint.py is a thin shim over dragglint (ISSUE 14) — these tests
pin the COMPATIBILITY story: the historical entry point still gates the
repo, and the five legacy suppression markers are grandfathered.  The
rule-by-rule fixtures live in tests/test_analysis.py."""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(ROOT, "tools", "lint.py")


def test_repo_passes_lint():
    """The CI entry point (`python tools/lint.py`) exits clean at HEAD —
    whole-package scope, empty-or-fully-reasoned baseline."""
    proc = subprocess.run([sys.executable, LINT],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "dragglint:" in proc.stderr      # it really is the analyzer


def test_shim_forwards_arguments():
    """Shim arguments pass through to the analyzer CLI."""
    proc = subprocess.run([sys.executable, LINT, "--list-rules"],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    assert "DT004" in proc.stdout and "DT015" in proc.stdout


def test_legacy_markers_grandfathered_through_shim(tmp_path):
    """A file using only pre-ISSUE-14 markers still passes (downstream
    docs/snippets must not break), and the run carries the one-time
    migration warning."""
    tool = tmp_path / "legacy_tool.py"
    tool.write_text(
        "import jax\n"
        "import subprocess\n"
        "d = jax.devices()  # device-call-ok: supervised child\n"
        "subprocess.run(['true'], timeout=5)\n"
    )
    proc = subprocess.run(
        [sys.executable, LINT, "--root", ROOT, "--no-baseline", str(tool)],
        capture_output=True, text=True, timeout=60)
    # The file lands outside the repo root, so give it an in-scope rel
    # path via the API instead for the scope-dependent half:
    sys.path.insert(0, ROOT)
    from dragg_tpu.analysis import check_source, make_rules

    got = check_source(tool.read_text(), "tools/legacy_tool.py",
                       make_rules())
    dt004 = [f for f in got if f.rule == "DT004"]
    assert dt004 and dt004[0].suppressed == "legacy"
    assert not [f for f in got if f.live and f.severity == "error"]
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_new_syntax_everywhere_in_tree():
    """Satellite: the tree itself uses the unified syntax — no legacy
    markers remain in committed .py files (they are only honored for
    DOWNSTREAM compatibility).  `# noqa` is exempt: it keeps its
    permanent flake8 meaning."""
    legacy = ("# device-call-ok:", "# accept-timeout-ok:",
              "# telemetry-name-ok:", "# precision-ok:", "# kkt-inv-ok:")
    offenders = []
    for base, dirs, files in os.walk(ROOT):
        dirs[:] = [d for d in dirs
                   if not d.startswith(".") and d != "__pycache__"]
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(base, fn)
            rel = os.path.relpath(path, ROOT)
            if rel.replace(os.sep, "/") in (
                    "tests/test_lint.py", "tests/test_analysis.py",
                    "dragg_tpu/analysis/core.py", "tools/lint.py"):
                continue        # the marker TABLE, these fixtures, and
                                # the shim docstring DESCRIBING them
            with open(path, encoding="utf-8") as f:
                src = f.read()
            for m in legacy:
                if m in src:
                    offenders.append(f"{rel}: {m}")
    assert not offenders, offenders
