"""The offline lint floor runs from the suite (round 6), so the
device-call discipline in entry points — no bare jax.devices(), no
un-deadlined subprocess calls in tools/ or bench.py — is CI-enforced,
not advisory."""

import importlib.util
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "dragg_lint", os.path.join(ROOT, "tools", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_passes_lint():
    proc = subprocess.run([sys.executable, os.path.join(ROOT, "tools", "lint.py")],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_device_discipline_flags_bare_calls(tmp_path):
    lint = _load_lint()
    bad = tmp_path / "bad_tool.py"
    bad.write_text(
        "import subprocess\n"
        "import jax\n"
        "d = jax.devices()\n"
        "ok = jax.devices()  # device-call-ok: supervised child\n"
        "subprocess.run(['true'])\n"
        "subprocess.run(['true'], timeout=5)\n"
    )
    # The rule is scoped to entry points (tools/ + bench.py); call the
    # checker directly so the fixture file need not live in the repo.
    import ast

    src = bad.read_text()
    problems = lint.check_device_discipline(
        ast.parse(src), src.splitlines(), "tools/bad_tool.py")
    assert len(problems) == 2
    assert any("jax.devices" in p and ":3:" in p for p in problems)
    assert any("subprocess.run" in p and ":5:" in p for p in problems)


def test_device_discipline_scoping():
    lint = _load_lint()
    assert lint._is_entry_point(os.path.join(ROOT, "bench.py"))
    assert lint._is_entry_point(os.path.join(ROOT, "tools", "x.py"))
    assert not lint._is_entry_point(os.path.join(ROOT, "dragg_tpu", "engine.py"))
    # ISSUE 7: the serving subsystem is an entry-point scope too — its
    # parent is the one process that must never touch a device bare.
    assert lint._is_entry_point(
        os.path.join(ROOT, "dragg_tpu", "serve", "daemon.py"))
    assert lint._is_serve_scope(
        os.path.join(ROOT, "dragg_tpu", "serve", "worker.py"))
    assert not lint._is_serve_scope(
        os.path.join(ROOT, "dragg_tpu", "engine.py"))
    # ISSUE 8: the aggregator's entry paths joined the scope — its one
    # sanctioned device enumeration routes through
    # resilience.devices.device_count, so any bare jax.devices() that
    # reappears there is flagged.
    assert lint._is_entry_point(
        os.path.join(ROOT, "dragg_tpu", "aggregator.py"))
    # The sanctioned helper's module itself stays out of entry scope
    # (documented single escape hatch).
    assert not lint._is_entry_point(
        os.path.join(ROOT, "dragg_tpu", "resilience", "devices.py"))


def test_aggregator_has_no_bare_device_calls():
    """The satellite's teeth: aggregator.py must contain no bare
    jax.devices()/local_devices()/default_backend() (ISSUE 8 routed the
    round-8 sharding probe through resilience.devices.device_count)."""
    lint = _load_lint()
    import ast

    path = os.path.join(ROOT, "dragg_tpu", "aggregator.py")
    with open(path) as f:
        src = f.read()
    problems = lint.check_device_discipline(
        ast.parse(src), src.splitlines(), "dragg_tpu/aggregator.py")
    assert problems == [], problems
    assert "device_count" in src  # the sanctioned route is actually used


def test_accept_loop_discipline():
    """ISSUE 7 rule: serving-daemon accept loops must stay interruptible
    — serve_forever() needs poll_interval=, raw socket accept() needs the
    accept-timeout-ok marker."""
    import ast

    lint = _load_lint()
    src = (
        "httpd.serve_forever()\n"                                   # bad
        "httpd.serve_forever(poll_interval=0.2)\n"                  # ok
        "conn, addr = sock.accept()\n"                              # bad
        "conn, addr = sock.accept()  "
        "# accept-timeout-ok: settimeout(1.0) above\n"              # marked
    )
    problems = lint.check_accept_loop_discipline(
        ast.parse(src), src.splitlines(), "dragg_tpu/serve/x.py")
    assert len(problems) == 2, problems
    assert any("serve_forever" in p and ":1:" in p for p in problems)
    assert any("accept()" in p and ":3:" in p for p in problems)


def test_telemetry_name_discipline(tmp_path):
    """Round-7 rule: telemetry emits in dragg_tpu/, tools/, and bench.py
    must name central-registry entries as literals; computed names need
    the telemetry-name-ok marker."""
    import ast

    lint = _load_lint()
    src = (
        "from dragg_tpu import telemetry\n"
        "telemetry.emit('chunk.done', t0=0)\n"                  # ok: registered
        "telemetry.emit('made.up.event')\n"                     # bad
        "telemetry.observe('engine.chunk_device_s', 1.0)\n"     # ok
        "telemetry.span('free.string.metric')\n"                # bad
        "kind = 'WEDGED'\n"
        "telemetry.emit('failure.' + kind)\n"                   # bad: no marker
        "telemetry.emit('failure.' + kind)  "
        "# telemetry-name-ok: taxonomy kinds are registered\n"  # ok: marked
    )
    problems = lint.check_telemetry_names(
        ast.parse(src), src.splitlines(), "dragg_tpu/x.py")
    assert len(problems) == 3, problems
    assert any("made.up.event" in p and ":3:" in p for p in problems)
    assert any("free.string.metric" in p and ":5:" in p for p in problems)
    assert any("computed name" in p and ":7:" in p for p in problems)


def test_telemetry_scope():
    lint = _load_lint()
    assert lint._is_telemetry_scope(os.path.join(ROOT, "dragg_tpu", "engine.py"))
    assert lint._is_telemetry_scope(os.path.join(ROOT, "bench.py"))
    assert lint._is_telemetry_scope(os.path.join(ROOT, "tools", "x.py"))
    assert not lint._is_telemetry_scope(os.path.join(ROOT, "tests", "x.py"))


def test_kkt_inverse_discipline(tmp_path):
    """Round-10 rule: direct np/jnp.linalg.inv outside dragg_tpu/ops/ is
    rejected — KKT-sized inverses must go through the equilibrated,
    condition-checked helper (ops.reluqp.equilibrated_spd_inverse); the
    kkt-inv-ok marker opts out sites with provably non-KKT operands."""
    import ast

    lint = _load_lint()
    src = (
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "a = np.linalg.inv(S)\n"                               # bad
        "b = jnp.linalg.inv(K)\n"                              # bad
        "c = np.linalg.inv(rot2x2)  # kkt-inv-ok: 2x2 rotation\n"  # marked
        "d = np.linalg.solve(S, r)\n"                          # fine
        "e = jnp.linalg.cholesky(S)\n"                         # fine
    )
    problems = lint.check_kkt_inverse_discipline(
        ast.parse(src), src.splitlines(), "dragg_tpu/x.py")
    assert len(problems) == 2, problems
    assert any(":3:" in p for p in problems)
    assert any(":4:" in p for p in problems)


def test_kkt_inverse_scope():
    """The rule covers framework + entry-point code but NOT dragg_tpu/ops/
    — the solver kernels own their factorization-internal inverses."""
    lint = _load_lint()
    assert lint._is_kkt_inv_scope(os.path.join(ROOT, "dragg_tpu", "engine.py"))
    assert lint._is_kkt_inv_scope(os.path.join(ROOT, "bench.py"))
    assert lint._is_kkt_inv_scope(os.path.join(ROOT, "tools", "x.py"))
    assert not lint._is_kkt_inv_scope(
        os.path.join(ROOT, "dragg_tpu", "ops", "reluqp.py"))
    assert not lint._is_kkt_inv_scope(os.path.join(ROOT, "tests", "x.py"))


def test_home_type_registry_rule():
    """ISSUE 10: every HOME_TYPES entry must carry a TYPE_SPECS spec, a
    parity-bearing test mention, and a docs/config.md mention — the live
    repo passes, and the checker actually reads the live tables."""
    lint = _load_lint()
    assert lint.check_home_type_registry() == []
    # The checker reads the REAL type lists (not a stale copy).
    from dragg_tpu.homes import HOME_TYPES
    from dragg_tpu.ops.qp import TYPE_SPECS

    got = lint._literal_names(
        os.path.join(ROOT, "dragg_tpu", "homes.py"), "HOME_TYPES")
    assert tuple(got) == HOME_TYPES
    got_specs = lint._literal_names(
        os.path.join(ROOT, "dragg_tpu", "ops", "qp.py"), "TYPE_SPECS")
    assert set(got_specs) == set(TYPE_SPECS)
    assert {"ev", "heat_pump"} <= set(got)


def test_precision_discipline(tmp_path):
    """ISSUE 11: dense contractions in the precision-disciplined solver
    files must route through ops/precision.mxu_einsum — bare
    jnp.einsum/dot/matmul/lax.dot_general are rejected unless the line
    carries the precision-ok marker (non-matmul einsums like a trace)."""
    import ast

    lint = _load_lint()
    src = (
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "from dragg_tpu.ops.precision import mxu_einsum\n"
        "a = jnp.einsum('bmn,bn->bm', A, x)\n"                    # bad
        "b = jnp.matmul(A, x)\n"                                  # bad
        "c = lax.dot_general(A, x, d)\n"                          # bad
        "d = jnp.einsum('bkk->b', M)  # precision-ok: trace\n"    # marked
        "e = mxu_einsum('bmn,bn->bm', A, x, precision='f32')\n"   # routed
        "f = jnp.linalg.cholesky(S)\n"                            # fine
    )
    problems = lint.check_precision_discipline(
        ast.parse(src), src.splitlines(), "dragg_tpu/ops/reluqp.py")
    assert len(problems) == 3, problems
    assert any(":4:" in p for p in problems)
    assert any(":5:" in p for p in problems)
    assert any(":6:" in p for p in problems)


def test_precision_discipline_scope():
    """The rule covers exactly the two dense solver files — the helper
    module itself (which owns the bare einsum) and everything else stay
    out of scope."""
    lint = _load_lint()
    assert lint._is_precision_scope(
        os.path.join(ROOT, "dragg_tpu", "ops", "reluqp.py"))
    assert lint._is_precision_scope(
        os.path.join(ROOT, "dragg_tpu", "ops", "admm.py"))
    assert not lint._is_precision_scope(
        os.path.join(ROOT, "dragg_tpu", "ops", "precision.py"))
    assert not lint._is_precision_scope(
        os.path.join(ROOT, "dragg_tpu", "ops", "ipm.py"))
    assert not lint._is_precision_scope(
        os.path.join(ROOT, "dragg_tpu", "engine.py"))
