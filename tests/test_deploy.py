"""Deployment-story execution (VERDICT r4 L8/next-9: the one SURVEY layer
with zero execution evidence).

No docker daemon or GCP project exists in CI, so the pod-launch script is
exercised end-to-end against a MOCKED ``gcloud`` that records every
invocation: the test asserts the real control flow — create slice →
scp repo to all workers → ssh install → ssh multi-host run with
``DRAGG_DISTRIBUTED=1`` — and the argument plumbing (accelerator/zone
defaults, ``--``-separated run args).  The multi-host run entry itself
is executed for real as N local processes by tests/test_distributed.py;
this closes the gap between that entry and the script that invokes it.

A committed transcript of one dry run lives at docs/deploy_dryrun_r5.md.
"""

import os
import stat
import subprocess

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One log line per invocation: embedded newlines inside arguments (the
# multi-line ssh --command payloads) are flattened to spaces.
_MOCK = """#!/bin/bash
printf '%s' "gcloud $*" | tr '\\n' ' ' >> "$GCLOUD_LOG"
printf '\\n' >> "$GCLOUD_LOG"
exit 0
"""


def _run_launch(tmp_path, args):
    mock_dir = tmp_path / "bin"
    mock_dir.mkdir(exist_ok=True)
    gcloud = mock_dir / "gcloud"
    gcloud.write_text(_MOCK)
    gcloud.chmod(gcloud.stat().st_mode | stat.S_IEXEC)
    log = tmp_path / "gcloud.log"
    log.write_text("")  # fresh transcript per launch
    env = dict(os.environ,
               PATH=f"{mock_dir}:{os.environ['PATH']}",
               GCLOUD_LOG=str(log))
    proc = subprocess.run(
        ["bash", os.path.join(ROOT, "deploy", "launch_tpu_pod.sh"), *args],
        capture_output=True, text=True, timeout=120, env=env)
    calls = log.read_text().splitlines() if log.exists() else []
    return proc, calls


def test_launch_tpu_pod_dry_run(tmp_path):
    proc, calls = _run_launch(
        tmp_path, ["dragg-v4-8", "v4-16", "us-central2-b", "--",
                   "--config", "config.toml"])
    assert proc.returncode == 0, proc.stderr
    assert len(calls) == 4, calls
    create, scp, install, run = calls
    assert "tpus tpu-vm create dragg-v4-8" in create
    assert "--accelerator-type=v4-16" in create
    assert "--zone=us-central2-b" in create
    assert "scp" in scp and "--worker=all" in scp
    assert "ssh" in install and "pip install" in install
    # The run command must join every worker into ONE multi-host JAX
    # program (DRAGG_DISTRIBUTED=1 → jax.distributed.initialize in
    # dragg_tpu/__main__.py) and forward the post-`--` args verbatim.
    assert "--worker=all" in run
    assert "DRAGG_DISTRIBUTED=1" in run
    assert "python -m dragg_tpu run --config config.toml" in run


def test_launch_tpu_pod_defaults_and_arg_errors(tmp_path):
    proc, calls = _run_launch(tmp_path, ["my-pod"])
    assert proc.returncode == 0, proc.stderr
    assert "--accelerator-type=v4-8" in calls[0]  # documented defaults
    assert "--zone=us-central2-b" in calls[0]

    # Misplaced run args (no `--`) must be rejected, not silently eaten.
    proc, _ = _run_launch(tmp_path, ["my-pod", "v4-8", "zone", "extra"])
    assert proc.returncode == 2
    assert "put run args after '--'" in proc.stderr

    # Missing pod name: usage error.
    proc, _ = _run_launch(tmp_path, [])
    assert proc.returncode != 0


def test_batch_script_is_sbatch_shaped():
    """deploy/batch.sh parity guard vs the reference's dragg/batch.sh:
    SLURM directives present, no redis-server boot (state is in-process —
    SURVEY §2.2 C3), runs the module entry."""
    with open(os.path.join(ROOT, "deploy", "batch.sh")) as f:
        content = f.read()
    assert "#SBATCH" in content
    assert "redis" not in content.lower().replace("redis-server boot", "")
    assert "python -u -m dragg_tpu run" in content
