"""Chaos tests for the supervised device-execution layer
(dragg_tpu/resilience) — every taxonomy outcome exercised via
deterministic fault injection on the CPU mesh, no chip required.

Covered here:
  TUNNEL_DOWN   real probe on the CPU-only env + injection
  WEDGED        injected round-4 signature (proxy http-403 + compile
                helper gone + hung probe)
  COMPILE_HANG  injected hang caught by the heartbeat-stall detector
  DEADLINE      child still beating when the hard deadline lands
  VMEM_OOM      injected scoped-VMEM OOM signature on stderr
  CHILD_CRASH   injected SIGKILL / nonzero exit

plus the two end-to-end guarantees the round-6 issue names: the
supervising parent provably performs NO jax backend init, and a
supervised run survives an injected mid-run device loss by resuming on
CPU from the latest atomic checkpoint with the platform transition
recorded in the output JSON.
"""

import json
import os
import re
import subprocess
import sys

import pytest

from dragg_tpu.resilience import faults, heartbeat, liveness, taxonomy
from dragg_tpu.resilience.runner import (latest_checkpoint_timestep,
                                         run_device_job)
from dragg_tpu.resilience.supervisor import run_supervised

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# A tiny supervised workload: beats once, then hits the "work" fault site.
CHILD = ("import dragg_tpu.resilience.faults as f, "
         "dragg_tpu.resilience.heartbeat as h\n"
         "h.beat({'stage': 'start'})\n"
         "f.fault_hook('work')\n"
         "import json; print(json.dumps({'done': True}))\n")


def _child_env(spec: str) -> dict:
    env = dict(os.environ, DRAGG_FAULT_INJECT=spec)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def _run(spec: str, deadline: float = 30.0, stall: float | None = None):
    return run_supervised([sys.executable, "-c", CHILD], deadline,
                          env=_child_env(spec), stall_s=stall)


@pytest.fixture
def inject(monkeypatch):
    """Arm a fault spec for THIS process (liveness checks read it)."""
    def arm(spec: str):
        monkeypatch.setenv(faults.ENV, spec)
        faults.reset_plan()
    yield arm
    faults.reset_plan()


# ------------------------------------------------------------- taxonomy
def test_classify_child_covers_every_outcome():
    c = taxonomy.classify_child
    assert c(0, False, False, "") is None
    assert c(-9, False, False, "") == taxonomy.CHILD_CRASH
    assert c(17, False, False, "") == taxonomy.CHILD_CRASH
    assert c(1, False, False, faults.VMEM_OOM_MESSAGE) == taxonomy.VMEM_OOM
    assert c(1, False, False,
             "RESOURCE_EXHAUSTED: scoped vmem limit exceeded"
             ) == taxonomy.VMEM_OOM
    assert c(-15, True, False, "") == taxonomy.DEADLINE
    assert c(-15, False, True, "") == taxonomy.COMPILE_HANG


def test_classify_liveness_wedge_signature():
    c = taxonomy.classify_liveness
    assert c(True, "tpu", False, None, None) is None
    assert c(True, "cpu", False, None, None) == taxonomy.TUNNEL_DOWN
    # The round-4 wedge: hung probe + proxy answering HTTP + helper gone.
    assert c(False, None, True, "http-403", "no-listen") == taxonomy.WEDGED
    # A hung probe WITHOUT the signature is an ordinary outage.
    assert c(False, None, True, "no-listen", "no-listen") == taxonomy.TUNNEL_DOWN
    assert c(False, None, True, "hang", "no-listen") == taxonomy.TUNNEL_DOWN
    assert c(False, None, False, None, None) == taxonomy.TUNNEL_DOWN


def test_fault_plan_grammar():
    p = faults.FaultPlan("sigkill@sim_chunk:3,probe_down:2,probe_live,"
                         "vmem_oom@kernel,hang@build:2:once")
    assert ("sigkill", "sim_chunk", 3, False) in p.site_faults
    assert ("vmem_oom", "kernel", 1, False) in p.site_faults
    assert ("hang", "build", 2, True) in p.site_faults
    assert p.probe_seq == ["down", "down"] and p.probe_live
    with pytest.raises(ValueError):
        faults.FaultPlan("explode@x")


def test_fault_site_catalog_in_sync():
    """``faults.SITES`` is THE catalog (satellite 2, round 19): every
    registered site appears in the architecture.md §8 table, every row
    of the table is registered, and every ``fault_hook("...")`` literal
    compiled into the framework is a registry entry.  The staged-compile
    family is one parameterized f-string site (``compile_{stage}``) —
    its concrete stages must each be registered."""
    import ast

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    doc = open(os.path.join(root, "docs", "architecture.md"),
               encoding="utf-8").read()
    # The §8 table rows: | `site` | where it lives |
    table_sites = set(re.findall(r"^\| `([a-z0-9_]+)` \|", doc,
                                 flags=re.MULTILINE))
    # Other tables in the doc use the same shape; the catalog rows are
    # exactly the registered sites plus nothing fault-shaped extra.
    assert set(faults.SITES) <= table_sites, \
        f"SITES entries missing from architecture.md §8 table: " \
        f"{set(faults.SITES) - table_sites}"
    for site, where in faults.SITES.items():
        assert f"| `{site}` |" in doc, site

    # Every fault_hook() call in the framework names a registered site.
    paths = [os.path.join(root, "bench.py")]
    for sub in ("dragg_tpu", "tools"):
        for dirpath, _dirs, names in os.walk(os.path.join(root, sub)):
            paths.extend(os.path.join(dirpath, n) for n in names
                         if n.endswith(".py"))
    dynamic = []
    for path in paths:
        try:
            tree = ast.parse(open(path, encoding="utf-8").read())
        except SyntaxError:  # pragma: no cover - DT001's job
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and ((isinstance(node.func, ast.Name)
                          and node.func.id == "fault_hook")
                         or (isinstance(node.func, ast.Attribute)
                             and node.func.attr == "fault_hook"))
                    and node.args):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                assert arg.value in faults.SITES, \
                    f"{path}:{node.lineno} fault_hook({arg.value!r}) " \
                    f"is not in faults.SITES"
            elif isinstance(arg, ast.JoinedStr):
                head = arg.values[0]
                assert (isinstance(head, ast.Constant)
                        and str(head.value).startswith("compile_")), \
                    f"{path}:{node.lineno} dynamic fault_hook site " \
                    f"outside the compile_ family"
                dynamic.append(path)
    # The parameterized family's concrete stages are registered.
    assert {"compile_lower", "compile_compile",
            "compile_first_execute"} <= set(faults.SITES)
    assert dynamic, "the staged-compile fault_hook site disappeared"


# ----------------------------------------------------------- supervisor
def test_supervisor_success_and_json_capture():
    res = _run("")
    assert res.ok and res.failure is None and res.rc == 0
    assert res.json == {"done": True}
    assert res.progress == {"stage": "start"}


def test_supervisor_child_crash_sigkill():
    res = _run("sigkill@work")
    assert not res.ok and res.rc == -9
    assert res.failure == taxonomy.CHILD_CRASH


def test_supervisor_vmem_oom_signature():
    res = _run("vmem_oom@work")
    assert not res.ok and res.failure == taxonomy.VMEM_OOM
    assert taxonomy.looks_like_vmem_oom(res.stderr_tail)


def test_supervisor_compile_hang_stall_detector():
    # The child beats once then hangs: the stall detector must kill it
    # well before the deadline and classify COMPILE_HANG — the round-4
    # wedge-prevention property (a hung compile dies in the child).
    res = _run("hang@work", deadline=60.0, stall=2.0)
    assert not res.ok and res.stalled and not res.timed_out
    assert res.failure == taxonomy.COMPILE_HANG
    assert res.elapsed_s < 30.0  # killed by stall, not deadline


def test_supervisor_deadline_still_beating():
    # No stall detection: a hung child only dies at the hard deadline,
    # which classifies DEADLINE (slow/stuck but nobody watched progress).
    res = _run("hang@work", deadline=3.0, stall=None)
    assert not res.ok and res.timed_out and not res.stalled
    assert res.failure == taxonomy.DEADLINE


# ------------------------------------------------------------- liveness
def test_liveness_real_probe_is_tunnel_down_on_cpu_env():
    # No injection: the real subprocess probe resolves the cpu backend,
    # which is TUNNEL_DOWN in the taxonomy (no TPU reachable).
    report = liveness.check_liveness(timeout_s=120.0)
    assert not report.alive
    assert report.kind == taxonomy.TUNNEL_DOWN


def test_liveness_injected_wedge_then_down_then_live(inject, tmp_path):
    log = str(tmp_path / "probe.txt")
    inject("probe_wedge:1,probe_down:1,probe_live")
    r1 = liveness.check_liveness(5.0, log_path=log)
    assert (not r1.alive and r1.kind == taxonomy.WEDGED
            and r1.proxy == "http-403" and r1.compile_helper == "no-listen")
    r2 = liveness.check_liveness(5.0, log_path=log)
    assert not r2.alive and r2.kind == taxonomy.TUNNEL_DOWN
    r3 = liveness.check_liveness(5.0, log_path=log)
    assert r3.alive and r3.kind is None
    content = open(log).read()
    assert content.count("DOWN") == 2 and content.count("LIVE") == 1


def test_backoff_schedule_is_exponential_and_capped():
    assert liveness.backoff_delays(4, 30.0) == [30.0, 60.0, 120.0, 240.0]
    assert liveness.backoff_delays(3, 300.0, cap_s=600.0) == [300.0, 600.0, 600.0]


# --------------------------------------------------------------- runner
def test_run_device_job_probe_gated_retry_then_cpu_fallback(inject):
    # Gate opens (injected live, ONE check), the TPU attempt crashes, the
    # retry is probe-gated and the tunnel is now down → skip straight to
    # the CPU fallback, which succeeds.  No wall-clock: sleep is injected.
    inject("probe_live:1")
    ok_child = [sys.executable, "-c",
                "import json; print(json.dumps({'v': 1}))"]
    bad_child = [sys.executable, "-c", "raise SystemExit(17)"]
    calls = []

    def build_argv(platform, attempt):
        calls.append((platform, attempt))
        return bad_child if platform == "tpu" else ok_child

    # After the first failed attempt the injected plan is exhausted; the
    # REAL probe then reports TUNNEL_DOWN (cpu env), vetoing the retry.
    slept = []
    result, attempts = run_device_job(
        build_argv, platform="auto", tpu_deadline_s=30, cpu_deadline_s=30,
        retries=1, backoff_s=7.0, probe_timeout_s=60.0,
        sleep=slept.append)
    assert result == {"v": 1}
    assert calls == [("tpu", 0), ("cpu", 0)]
    assert slept == [7.0]
    kinds = [(a["platform"], a.get("failure")) for a in attempts]
    assert kinds[0] == ("tpu", taxonomy.CHILD_CRASH)
    assert ("tpu", taxonomy.TUNNEL_DOWN) in kinds  # the vetoed retry
    assert kinds[-1] == ("cpu", None) and attempts[-1]["ok"]


# -------------------------------------- the end-to-end degradation story
SIM_WRAPPER = """
import json, os, sys
from dragg_tpu.config import default_config
from dragg_tpu.resilience.runner import supervised_sim_run
from dragg_tpu.resilience.supervisor import assert_parent_has_no_jax

assert_parent_has_no_jax()
cfg = default_config()
cfg["community"].update(total_number_homes=4, homes_pv=1, homes_battery=0,
                        homes_pv_battery=0)
cfg["simulation"].update(end_datetime="2015-01-01 12",
                         checkpoint_interval="hourly")
cfg["home"]["hems"].update(prediction_horizon=2)
cfg["resilience"].update(deadline_s=300.0, stall_s=120.0, retries=0,
                         backoff_s=0.0)
prov = supervised_sim_run(cfg, sys.argv[1], platform="auto",
                          log=lambda m: print(m, file=sys.stderr, flush=True))
assert_parent_has_no_jax()
print(json.dumps({"prov": prov, "parent_jax": "jax" in sys.modules}))
"""


def test_supervised_run_survives_device_loss_resumes_on_cpu(tmp_path):
    """THE acceptance scenario: a supervised run whose child is SIGKILLed
    mid-run (injected device loss at its 3rd chunk, after two atomic
    checkpoints) must resume on CPU from the latest checkpoint, complete,
    and emit a JSON line recording the platform transition — while the
    supervising parent provably never initializes a jax backend."""
    outputs = str(tmp_path / "outputs")
    env = _child_env("probe_live,sigkill@sim_chunk:3:once")
    env["DRAGG_FAULT_STATE"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"  # injected-live "tpu" child runs CPU here
    proc = subprocess.run(
        [sys.executable, "-c", SIM_WRAPPER, outputs],
        capture_output=True, text=True, timeout=600, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stderr[-3000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    # The parent performed no jax backend init (the whole point).
    assert payload["parent_jax"] is False
    prov = payload["prov"]
    assert prov["completed"] is True
    assert prov["final_platform"] == "cpu"
    # First attempt: the injected-live gate opened, the child died of the
    # injected SIGKILL (device loss) — classified CHILD_CRASH.
    tpu_attempts = [a for a in prov["attempts"] if a["platform"] == "tpu"]
    assert tpu_attempts and tpu_attempts[0]["failure"] == "CHILD_CRASH"
    # The transition record: resumed on CPU from the latest ATOMIC
    # checkpoint.  Under the round-12 double-buffered pipeline
    # (fleet.pipeline, aggregator.run_baseline) chunk N's checkpoint is
    # written WHILE chunk N+1 executes, so a kill at the 3rd chunk
    # dispatch finds chunk 1's checkpoint durable (t=1) and chunk 2's
    # host work never ran — the crash-recovery re-work bound is ≤2
    # chunks instead of the synchronous loop's ≤1 (the price of taking
    # collect/checkpoint off the device critical path; perf_notes round
    # 12).  Pre-round-12 this asserted t=2.
    [tr] = prov["platform_transitions"]
    assert tr["from"] == "tpu" and tr["to"] == "cpu"
    assert tr["failure"] == "CHILD_CRASH"
    assert tr["resumed_from_timestep"] == 1
    # The run actually finished: results.json exists with the full series.
    results = []
    for base, _dirs, files in os.walk(outputs):
        results += [os.path.join(base, f) for f in files if f == "results.json"]
    assert results, "no results.json written"
    with open(results[0]) as f:
        data = json.load(f)
    assert len(data["Summary"]["p_grid_aggregate"]) == 12
    # The checkpoint was consumed and cleared by the completed run.
    assert latest_checkpoint_timestep(outputs) is None


def test_sim_run_platform_tpu_never_degrades_without_a_device(inject,
                                                              tmp_path):
    """An explicit --platform tpu run whose probe never acquires a device
    must NOT silently complete on CPU (that would be a CPU artifact
    masquerading as the requested TPU measurement); degrade_to_cpu
    covers device loss MID-RUN only."""
    from dragg_tpu.config import default_config
    from dragg_tpu.resilience.runner import supervised_sim_run

    inject("probe_down:5")
    cfg = default_config()
    cfg["resilience"].update(retries=0, backoff_s=0.0)
    prov = supervised_sim_run(cfg, str(tmp_path / "out"), platform="tpu",
                              sleep=lambda s: None)
    assert prov["completed"] is False
    assert "final_platform" not in prov
    # Only the probe-skip record — no CPU attempt ever ran.
    assert [a.get("skipped") for a in prov["attempts"]] == ["probe_down"]


# --------------------------------------------- classify CLIs + runbook
def test_doctor_classify_names_the_failure(tmp_path):
    """``doctor --classify`` prints one JSON line NAMING the failure
    (taxonomy kind) instead of raw probe output — rc 1 when no TPU."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "dragg_tpu", "doctor", "--classify",
         "--backend-timeout", "120"],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert proc.returncode == 1
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["alive"] is False
    assert verdict["kind"] == taxonomy.TUNNEL_DOWN
    assert verdict["backend"] == "cpu"


def test_runbook_aborts_on_wedged_start_gate(tmp_path):
    """The Python runbook (the supervised successor to the bash stages)
    aborts at its start gate when the tunnel is wedged — naming WEDGED in
    the transcript instead of burning stage timeouts — and commits the
    probe verdict to the pass's probe log."""
    out = str(tmp_path / "pass")
    env = _child_env("probe_wedge")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "runbook.py"),
         "--out", out],
        capture_output=True, text=True, timeout=120, env=env, cwd=ROOT)
    assert proc.returncode == 1
    transcript = open(os.path.join(out, "runbook.log")).read()
    assert "WEDGED" in transcript and "aborting" in transcript
    assert "DOWN" in open(os.path.join(out, "probe_log.txt")).read()


# -------------------------------------------------------------- heartbeat
def test_heartbeat_write_and_read(tmp_path, monkeypatch):
    path = str(tmp_path / "hb.json")
    monkeypatch.setenv(heartbeat.ENV, path)
    heartbeat.beat({"timestep": 7})
    age, progress = heartbeat.read(path)
    assert age is not None and age < 5.0
    assert progress == {"timestep": 7}
    # Unreadable/missing files are (None, None), never an exception.
    assert heartbeat.read(str(tmp_path / "nope.json")) == (None, None)
